#!/usr/bin/env python
"""Autoscaling/QoS smoke stage for scripts/smoke.sh (ISSUE 6): a tiny CPU
run that closes the loop end to end —

1. a 2-class burst (interactive + batch, ``X-Kftpu-Qos`` headers) through
   a real router + model server must shed in priority order: batch takes
   every 429/shed, interactive is never shed and all-200s;
2. the SLO autoscaler, scraping the REAL replica's /metrics through
   ``default_probe``, must make exactly one scale-up decision off the
   burst's latency signals (and hold, not flap, while the fleet is
   partial);
3. scale-down must retire through the graceful drain path: a busy
   trimmed replica survives (Draining event) until idle, then tears down;
4. the new QoS/router metric names must pass ``kftpu lint``'s M2xx
   definition-site rules and render on /metrics under the exposition
   grammar with the ``kftpu_`` prefix.

Prints one JSON object; ``"autoscale_smoke": "ok"`` is the pass marker
smoke.sh greps for.

    JAX_PLATFORMS=cpu python scripts/autoscale_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: Files whose metric definition sites this PR added/changed — the M2xx
#: lint surface for the new series names.
METRIC_FILES = [
    "kubeflow_tpu/serve/server.py",
    "kubeflow_tpu/serve/router.py",
    "kubeflow_tpu/serve/isvc_controller.py",
]

#: Series the QoS/autoscaling loop introduces; all must render.
NEW_SERIES = [
    "kftpu_serving_qos_requests_total",
    "kftpu_serving_qos_requests_shed_total",
    "kftpu_serving_qos_preemptions_total",
    "kftpu_serving_qos_ttft_p95_ms",
    "kftpu_serving_qos_queue_delay_seconds_bucket",
    "kftpu_serving_ttft_p95_ms",
    "kftpu_serving_preemptions_total",
    "kftpu_router_panic_total",
    "kftpu_router_probe_total",
]


def completion(url: str, qos: str, timeout_s: float = 10.0) -> int:
    from kubeflow_tpu.serve.router import DEADLINE_HEADER, QOS_HEADER

    body = json.dumps({"prompt": "smoke", "max_tokens": 6,
                       "timeout": timeout_s}).encode()
    req = urllib.request.Request(
        url + "/v1/completions", data=body,
        headers={"Content-Type": "application/json", QOS_HEADER: qos,
                 DEADLINE_HEADER: str(int(timeout_s * 1e3))})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s + 5) as r:
            return r.status
    except urllib.error.HTTPError as exc:
        exc.read()
        return exc.code
    except OSError:
        return 502


def fire(url: str, qos: str, n: int, concurrency: int,
         out: list[int]) -> None:
    lock = threading.Lock()
    it = iter(range(n))

    def client():
        while True:
            with lock:
                nxt = next(it, None)
            if nxt is None:
                return
            status = completion(url, qos)
            with lock:
                out.append(status)

    threads = [threading.Thread(target=client) for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120.0)
        assert not t.is_alive(), "client thread hung"


def main() -> int:
    problems: list[str] = []
    report: dict = {}

    # -- stage 4 first (pure static): M2xx lint over the metric files ------
    from kubeflow_tpu.analysis.core import lint_source

    m2xx = []
    for rel in METRIC_FILES:
        with open(os.path.join(REPO, rel)) as f:
            src = f.read()
        m2xx += [f.render() for f in lint_source(src, rel)
                 if f.rule.startswith("M2")]
    report["m2xx_findings"] = m2xx
    if m2xx:
        problems.append(f"M2xx lint findings in metric files: {m2xx}")

    import jax  # noqa: F401  (force backend selection before engines)

    from kubeflow_tpu.core.serving import (
        BatchingSpec, QoSClassPolicy, QoSSpec,
    )
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.obs.registry import parse_exposition
    from kubeflow_tpu.serve.engine import LLMEngine
    from kubeflow_tpu.serve.router import Router
    from kubeflow_tpu.serve.server import ModelServer

    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    eng = LLMEngine(
        cfg,
        BatchingSpec(max_batch_size=2, max_seq_len=96, prefill_buckets=[32],
                     paged=True, page_size=16, chunked_prefill_tokens=16,
                     decode_steps=4, max_queue=4,
                     qos=QoSSpec(classes={
                         "batch": QoSClassPolicy(max_queue=1),
                         "interactive": QoSClassPolicy(
                             queue_delay_budget=5.0)})),
        params=params)
    server = ModelServer("smoke-svc", eng, port=0)
    server.start()
    router = Router(queue_timeout=5.0)
    router.set_backends({"latest": [server.url]})
    router.start()

    try:
        # -- stage 1: 2-class burst, shed ordering ------------------------
        got: dict[str, list[int]] = {"interactive": [], "batch": []}
        pools = [threading.Thread(
            target=fire, args=(router.url, cls, 8, 3, got[cls]))
            for cls in got]
        for t in pools:
            t.start()
        for t in pools:
            t.join(timeout=120.0)
        snap = eng.metrics.snapshot()
        shed = {c: snap.get("qos", {}).get(c, {}).get("shed", 0)
                for c in ("interactive", "batch")}
        report["statuses"] = {c: sorted(set(v)) for c, v in got.items()}
        report["shed"] = shed
        if shed["interactive"] != 0 or any(
                s != 200 for s in got["interactive"]):
            problems.append(f"interactive degraded: shed={shed}, "
                            f"statuses={report['statuses']}")
        if 429 in got["batch"] and shed["batch"] == 0:
            problems.append("batch 429s with no batch shed counter")

        # -- stage 4b: the live exposition renders + lints ----------------
        text = server.metrics_text()
        names = {name for name, _, _ in parse_exposition(text)}
        router_text = urllib.request.urlopen(
            router.url + "/-/router/metrics", timeout=5).read().decode()
        names |= {name for name, _, _ in parse_exposition(router_text)}
        missing = [s for s in NEW_SERIES if s not in names]
        report["missing_series"] = missing
        if missing:
            problems.append(f"series missing from /metrics: {missing}")
        reg = server.metrics_registry()
        lint = reg.lint()
        if lint:
            problems.append(f"registry lint: {lint}")

        # -- stage 2: SLO autoscaler scrapes the REAL replica -------------
        from kubeflow_tpu.core.jobs import Worker, WorkerPhase
        from kubeflow_tpu.core.object import ObjectMeta
        from kubeflow_tpu.core.serving import (
            InferenceService, InferenceServiceSpec, ModelSpec,
            PredictorSpec, SLOPolicy,
        )
        from kubeflow_tpu.operator.control_plane import (
            ControlPlane, ControlPlaneConfig,
        )
        from kubeflow_tpu.serve.isvc_controller import default_probe

        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            cp = ControlPlane(ControlPlaneConfig(
                base_dir=tmp, launch_processes=False,
                metrics_sync_interval=None))
            # Every replica probe scrapes the REAL loaded server: the
            # signal path under test is engine → /metrics → parse →
            # decision. The burst above left ttft/queue-delay p95s far
            # over the (deliberately microscopic) targets.
            cp.isvc_reconciler.probe = lambda url: default_probe(server.url)
            cp.submit(InferenceService(
                metadata=ObjectMeta(name="svc"),
                spec=InferenceServiceSpec(predictor=PredictorSpec(
                    model=ModelSpec(config={"preset": "tiny"}),
                    min_replicas=1, max_replicas=2,
                    slo=SLOPolicy(target_ttft_ms=0.01,
                                  target_queue_delay_ms=0.01,
                                  cooldown_s=0.2)))))
            key = "default/svc"
            recon = lambda: cp.isvc_reconciler.reconcile(key)  # noqa: E731

            def mark_running():
                for w in cp.store.list(Worker):
                    if w.status.phase != WorkerPhase.RUNNING:
                        w.status.phase = WorkerPhase.RUNNING
                        cp.store.update_status(w)

            recon()                   # create replica 1
            mark_running()
            recon()                   # ready; first sight starts the clock
            time.sleep(0.25)          # cooldown elapses
            recon()                   # hot signals → ONE scale-up decision
            isvc = cp.store.get(InferenceService, "svc")
            report["desired_after_burst"] = isvc.status.desired_replicas
            if isvc.status.desired_replicas != 2:
                problems.append(
                    f"no scale-up decision off the burst signals "
                    f"(desired={isvc.status.desired_replicas})")
            # Partial fleet (replica 2 created but not ready): hold.
            time.sleep(0.25)
            recon()
            isvc = cp.store.get(InferenceService, "svc")
            if isvc.status.desired_replicas != 2:
                problems.append("autoscaler flapped while fleet partial")

            # -- stage 3: scale-down completes drain before teardown ------
            mark_running()            # replica 2 comes up
            probe_state = {"in_flight": 1}

            def idle_probe(url):
                return {"ready": True, "in_flight": probe_state["in_flight"],
                        "requests_total": 0, "ttft_p95_ms": 0.001,
                        "queue_delay_p95_ms": 0.001,
                        "qos_ttft_p95_ms": {}, "qos_queue_delay_p95_ms": {}}

            cp.isvc_reconciler.probe = idle_probe
            time.sleep(0.25)
            recon()
            isvc = cp.store.get(InferenceService, "svc")
            if isvc.status.desired_replicas != 1:
                problems.append(
                    f"no scale-down on idle signals "
                    f"(desired={isvc.status.desired_replicas})")
            recon()       # trim pass: replica 1 enters draining (busy)
            n_workers = len(cp.store.list(Worker))
            if n_workers != 2:
                problems.append(
                    f"busy replica deleted before drain ({n_workers})")
            events = [e.reason for e in cp.recorder.for_object(isvc)]
            if "Draining" not in events:
                problems.append(f"no Draining event (events={events})")
            probe_state["in_flight"] = 0       # in-flight work finished
            recon()
            n_workers = len(cp.store.list(Worker))
            if n_workers != 1:
                problems.append(
                    f"drained replica not torn down ({n_workers})")
            report["events"] = events
            cp.isvc_reconciler.shutdown()
    finally:
        router.stop()
        server.stop()

    report["autoscale_smoke"] = "ok" if not problems else "FAIL"
    report["problems"] = problems
    print(json.dumps(report, indent=2))
    return 0 if not problems else 1


if __name__ == "__main__":
    sys.exit(main())
