#!/usr/bin/env python
"""Disaggregated-serving gate (scripts/smoke.sh): 1-prefill + 1-decode
CPU fleet behind the token-aware router, A/B'd against 2 unified
replicas at the SAME offered load (ISSUE 12).

Asserts, over the full HTTP protocol path:

- **token identity**: greedy output through the disaggregated fleet
  (prefill → paged-KV handoff → decode) is byte-identical to a unified
  replica's, streaming and non-streaming;
- **the disaggregation win**: on the ``mixed_interference`` loadgen
  scenario (bursty long-prefill batch arrivals interleaved with short
  interactive requests) the disaggregated split achieves HIGHER
  goodput-under-SLO than two unified replicas at the same offered
  load, with interactive TTFT p95 no worse — and ``bursty_qos`` is
  replayed on both fleets for the record;
- **handoff plumbing**: handoff counters nonzero on both sides
  (exported == adopted), router ``disagg_picks`` nonzero, zero failed
  handoffs in the measured segments;
- **seeded regression**: a wedged handoff (sleep injected into the
  handoff POST hop) replayed on the same scenario MUST breach the
  spread-derived noise band AND the attribution diff must name the
  ``handoff`` phase (its per-request span duration blowing up is what
  distinguishes "the handoff hop broke" from "the engine got slow");
- **hygiene**: zero page leaks and empty handoff holds on every engine
  after every segment (``assert_quiescent``), clean under
  ``KFTPU_SANITIZE=refcount``.

Writes ``BENCH_SERVE_r02.json`` — the disaggregation round of the
serving bench trajectory: one row per (scenario, fleet) with the full
attribution report. ``{"disagg_smoke": "ok"}`` is the gate line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: Handoff series this stage consumes off the fleet's rendered
#: exposition — the consumer half of the handoff metric contract (X7xx).
HANDOFF_SERIES = (
    "kftpu_engine_handoffs_exported_total",
    "kftpu_engine_handoffs_adopted_total",
    "kftpu_engine_handoffs_failed_total",
)

#: Operating point (tuned on the 1-core CI shape): interactive TTFT SLO
#: traffic at a rate near the unified knee, 25% of arrivals being 4×
#: long batch prefills. The unified engines pay the classic continuous-
#: batching tension — decode rounds sized for dispatch amortization
#: (decode_steps=32, the engine default) block prefill admissions and
#: slow chunk cadence — while the split prefill engine never rounds.
PROMPT_LEN = 48
MAX_NEW = 48
RATE = 10.0
REQUESTS = 64
#: The gate SLO is TPOT-led: the decode-side stall (interactive tokens
#: waiting behind co-resident prefill chunks) is the interference axis
#: the split removes STRUCTURALLY, so it separates far outside host
#: noise (measured ~3x: unified tpot p95 ≈ 9-11 ms vs disagg ≈ 3 ms at
#: this operating point). The TTFT bound stays generous — queue-order
#: luck on a single shared core makes tight TTFT gates flaky.
SLO_TTFT_MS = 2000.0
SLO_TPOT_MS = 6.0
SEGMENTS = 3
#: Gate margins: single-host CPU A/Bs jitter, so the goodput win must
#: clear an absolute margin and the interactive-TTFT "no worse" check
#: carries a noise tolerance (both over per-fleet segment MEANS). The
#: tolerance also absorbs the handoff floor every disaggregated TTFT
#: pays on a single shared core (~15 ms of export+POST+adopt riding on
#: the same CPU the engines compute on); the absolute backstop pins the
#: disagg p95 to comfortable TTFT-SLO headroom regardless.
GOODPUT_MARGIN = 0.05
TTFT_TOLERANCE = 1.4


def make_fleet(kind: str):
    """``unified`` → 2 unified replicas; ``disagg`` → 1 prefill + 1
    decode replica with token-aware pool routing. Same engine spec
    apart from the role split; the prefill engine carries the WHOLE
    fleet's admission concurrency (max_concurrent_prefills=4 = two
    unified engines' worth — it has no decode work to protect)."""
    import jax

    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.serve.engine import LLMEngine
    from kubeflow_tpu.serve.router import Router
    from kubeflow_tpu.serve.server import ModelServer

    cfg = preset("tiny", n_layers=4, hidden=128, mlp_dim=256,
                 max_seq_len=256)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)

    def mk(name, role):
        b = dict(max_batch_size=8, max_seq_len=cfg.max_seq_len,
                 paged=True, page_size=16, chunked_prefill_tokens=32,
                 decode_steps=32, role=role)
        if role == "prefill":
            b.update(max_concurrent_prefills=4)
        eng = LLMEngine(cfg, BatchingSpec(**b), params=params)
        srv = ModelServer(name, eng, port=0)
        srv.start()
        return srv

    router = Router(queue_timeout=10.0, eject_threshold=3,
                    eject_period=1.0, max_retries=2, upstream_timeout=60.0)
    router.scrape_interval = 0.1
    if kind == "unified":
        servers = [mk("uni-0", "unified"), mk("uni-1", "unified")]
        router.set_backends({"latest": [s.url for s in servers]})
    else:
        servers = [mk("prefill-0", "prefill"), mk("decode-0", "decode")]
        router.set_pools({"prefill": [servers[0].url],
                          "decode": [servers[1].url]})
    router.start()
    return router, servers, cfg


def stop_fleet(router, servers):
    router.stop()
    for s in servers:
        try:
            s.stop()
        except OSError:
            pass


def fleet_metrics_text(servers) -> str:
    """ONE rendered exposition for the whole fleet through the
    production registry path (the attribution join's engine half)."""
    from kubeflow_tpu.serve.server import serving_metrics_registry

    return serving_metrics_registry(
        [(s.name, s.engine) for s in servers]).render()


def completion(url: str, prompt: str, *, stream: bool,
               timeout: float = 60.0) -> str:
    body = {"prompt": prompt, "max_tokens": MAX_NEW, "temperature": 0.0,
            "timeout": timeout}
    if stream:
        body["stream"] = True
    req = urllib.request.Request(
        url + "/v1/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout + 10) as r:
        data = r.read()
    if not stream:
        return json.loads(data)["choices"][0]["text"]
    pieces = []
    for line in data.split(b"\n"):
        line = line.strip()
        if line.startswith(b"data:"):
            v = line[5:].strip()
            if v == b"[DONE]":
                break
            pieces.append(json.loads(v)["choices"][0].get("text", ""))
    return "".join(pieces)


def audit_fleet(servers) -> None:
    """Post-segment hygiene: every engine quiesces to zero pages and
    zero outstanding handoff holds (driving step() like a supervisor)."""
    deadline = time.monotonic() + 30.0
    for s in servers:
        eng = s.engine
        while (eng.kv_pages_in_use() > 0 or eng._handoff_holds
               or eng._rounds):
            time.sleep(0.05)
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"{s.name}: engine did not quiesce "
                    f"(pages={eng.kv_pages_in_use()}, "
                    f"holds={len(eng._handoff_holds)})")
        eng._allocator.assert_quiescent()


def run_segment(router, servers, cfg, scenario):
    from kubeflow_tpu.loadgen import ServerTarget, build_report, run_scenario
    from kubeflow_tpu.obs.trace import get_tracer
    from kubeflow_tpu.serve.engine import EngineMetrics

    tracer = get_tracer()
    tracer.reset()
    for s in servers:
        s.engine.metrics = EngineMetrics()
    run = run_scenario(ServerTarget(router.url), scenario,
                       vocab_size=cfg.vocab_size,
                       max_prompt_len=cfg.max_seq_len - MAX_NEW - 2,
                       tracer=tracer)
    rep = build_report(run, metrics_text=fleet_metrics_text(servers),
                       tracer=tracer)
    audit_fleet(servers)
    return rep


def measure(router, servers, cfg, scenario, *, segments: int = SEGMENTS):
    run_segment(router, servers, cfg, scenario)       # settle/warm
    return [run_segment(router, servers, cfg, scenario)
            for _ in range(segments)]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=REQUESTS)
    ap.add_argument("--rate", type=float, default=RATE)
    ap.add_argument("--out",
                    default=os.path.join(REPO, "BENCH_SERVE_r02.json"))
    args = ap.parse_args()

    from kubeflow_tpu.loadgen import (
        compare_matrix, noise_band_pct, spread_pct, standard_matrix,
    )
    from kubeflow_tpu.obs.registry import parse_exposition

    result: dict = {}

    def fail(msg: str) -> int:
        result["disagg_smoke"] = msg
        print(json.dumps(result, indent=2))
        return 1

    matrix = {s.name: s for s in standard_matrix(
        num_requests=args.requests, rate_rps=args.rate,
        prompt_len=PROMPT_LEN, max_new=MAX_NEW, slo_ttft_ms=SLO_TTFT_MS,
        mixed_slo_tpot_ms=SLO_TPOT_MS)}
    scenarios = [matrix["mixed_interference"], matrix["bursty_qos"]]

    fleets = {}
    rows = []
    reports: dict[str, dict] = {}
    for kind in ("unified", "disagg"):
        router, servers, cfg = make_fleet(kind)
        fleets[kind] = (router, servers, cfg)
        try:
            # Token identity first (doubles as the warmup): greedy
            # output through this fleet must match the other's.
            for stream in (False, True):
                for prompt in ("disagg token identity pin",
                               "a longer prompt, exercising the chunked "
                               "prefill path across several pages of kv"):
                    key = (prompt, stream)
                    text = completion(router.url, prompt, stream=stream)
                    if key in reports.setdefault("_identity", {}):
                        if reports["_identity"][key] != text:
                            return fail(
                                f"greedy output diverges across fleets "
                                f"(stream={stream}): "
                                f"{reports['_identity'][key]!r} vs {text!r}")
                    else:
                        reports["_identity"][key] = text
            for sc in scenarios:
                segs = measure(router, servers, cfg, sc)
                reports[f"{kind}:{sc.name}"] = segs
                rows.append({
                    "metric": f"disagg_goodput[{kind},{sc.name},"
                              f"r{args.rate:g},n{args.requests}]",
                    "value": round(sum(
                        s["goodput"]["ratio"] for s in segs) / len(segs), 4),
                    "unit": "goodput_ratio",
                    "vs_baseline": 1.0,
                    "detail": {"segments": segs},
                })
            if kind == "disagg":
                # Handoff plumbing proof: counters flowed in the LAST
                # measured segment's registry scrape.
                text = fleet_metrics_text(servers)
                counts = {}
                for name, labels, value in parse_exposition(text):
                    if name in HANDOFF_SERIES:
                        counts[name] = counts.get(name, 0) + int(value)
                if counts.get(HANDOFF_SERIES[0], 0) < 1 or \
                        counts.get(HANDOFF_SERIES[1], 0) < 1:
                    return fail(f"no handoffs flowed: {counts}")
                if counts.get(HANDOFF_SERIES[2], 0) != 0:
                    return fail(f"handoffs failed mid-measurement: {counts}")
                result["handoff_counters"] = counts
                snap = router.snapshot()
                if snap.get("disagg_picks", 0) < 1:
                    return fail(f"router made no token-aware picks: {snap}")
        except Exception as exc:  # noqa: BLE001 - gate surfaces, never hides
            stop_fleet(router, servers)
            fleets.pop(kind, None)
            raise
    # -- the disaggregation win (acceptance criterion) ---------------------
    mi = "mixed_interference"

    def mean(xs):
        xs = list(xs)
        return sum(xs) / max(len(xs), 1)

    uni_good = mean(r["goodput"]["ratio"] for r in reports[f"unified:{mi}"])
    dis_good = mean(r["goodput"]["ratio"] for r in reports[f"disagg:{mi}"])
    uni_ttft = mean((r.get("qos", {}).get("interactive", {})
                     .get("ttft_ms", {}).get("p95") or 0.0)
                    for r in reports[f"unified:{mi}"])
    dis_ttft = mean((r.get("qos", {}).get("interactive", {})
                     .get("ttft_ms", {}).get("p95") or 0.0)
                    for r in reports[f"disagg:{mi}"])
    result["win"] = {
        "goodput_unified": round(uni_good, 4),
        "goodput_disagg": round(dis_good, 4),
        "interactive_ttft_p95_unified_ms": round(uni_ttft, 1),
        "interactive_ttft_p95_disagg_ms": round(dis_ttft, 1),
    }
    if not dis_good > uni_good + GOODPUT_MARGIN:
        stop_all(fleets)
        return fail(
            f"disaggregation did not win goodput: disagg {dis_good:.3f} "
            f"<= unified {uni_good:.3f} + {GOODPUT_MARGIN} margin")
    if dis_ttft > uni_ttft * TTFT_TOLERANCE and dis_ttft - uni_ttft > 50.0:
        stop_all(fleets)
        return fail(
            f"disaggregation degraded interactive TTFT p95: "
            f"{dis_ttft:.0f}ms vs {uni_ttft:.0f}ms unified "
            f"(tolerance {TTFT_TOLERANCE}x)")
    if dis_ttft > SLO_TTFT_MS / 2:
        stop_all(fleets)
        return fail(
            f"disagg interactive TTFT p95 {dis_ttft:.0f}ms has no "
            f"headroom against the {SLO_TTFT_MS:.0f}ms SLO")

    # -- seeded regression: wedge the handoff hop --------------------------
    router, servers, cfg = fleets["disagg"]
    base_a, base_b = reports[f"disagg:{mi}"][-2:]
    band = noise_band_pct([
        spread_pct(base_a["req_s"], base_b["req_s"]),
        spread_pct(base_a["ttft_ms"].get("p95", 0.0),
                   base_b["ttft_ms"].get("p95", 0.0))])
    from kubeflow_tpu.serve import server as server_mod

    orig_open = server_mod.open_handoff

    def wedged_open(*a, **kw):
        time.sleep(0.5)
        return orig_open(*a, **kw)

    server_mod.open_handoff = wedged_open
    try:
        slow_rep = run_segment(router, servers, cfg, matrix[mi])
    finally:
        server_mod.open_handoff = orig_open
    verdict = compare_matrix([base_b], [slow_rep], bands={mi: band})
    if verdict["ok"]:
        stop_all(fleets)
        return fail(
            f"seeded wedged-handoff regression NOT flagged "
            f"(baseline ttft p95 {base_b['ttft_ms'].get('p95')}, wedged "
            f"{slow_rep['ttft_ms'].get('p95')}, band {band:.0f}%)")
    reg = verdict["regressions"][0]
    cand_phases = (reg.get("diff", {}).get("phases", {})
                   .get("candidate") or {})
    base_phases = (reg.get("diff", {}).get("phases", {})
                   .get("baseline") or {})
    wedged_handoff = cand_phases.get("handoff_ms", {}).get("p50", 0.0)
    base_handoff = base_phases.get("handoff_ms", {}).get("p50", 0.0)
    if wedged_handoff < 400.0 or wedged_handoff < 4 * max(base_handoff, 1.0):
        stop_all(fleets)
        return fail(
            f"regression attribution does not name the handoff phase: "
            f"baseline handoff_ms p50 {base_handoff}, wedged "
            f"{wedged_handoff}")
    result["seeded_regression"] = {
        "problems": reg["problems"],
        "band_pct": round(band, 1),
        "handoff_ms_p50_baseline": base_handoff,
        "handoff_ms_p50_wedged": wedged_handoff,
    }
    stop_all(fleets)

    # -- trajectory artifact ----------------------------------------------
    with open(args.out, "w") as f:
        json.dump({"schema": 1,
                   "generated_by": "scripts/disagg_smoke.py",
                   "config": {"requests_per_segment": args.requests,
                              "rate_rps": args.rate,
                              "prompt_len": PROMPT_LEN,
                              "max_new": MAX_NEW,
                              "slo_ttft_ms": SLO_TTFT_MS},
                   "win": result["win"],
                   "rows": rows}, f, indent=2)
        f.write("\n")
    result["artifact"] = os.path.relpath(args.out, REPO)

    result["disagg_smoke"] = "ok"
    print(json.dumps(result, indent=2))
    return 0


def stop_all(fleets) -> None:
    for router, servers, _ in fleets.values():
        stop_fleet(router, servers)


if __name__ == "__main__":
    sys.exit(main())
