"""Measured numbers for BASELINE.json configs 3-5 (the round-1 verdict's
missing benchmark rows). One JSON line per row; `--all` writes
BENCH_CONFIGS.json at the repo root.

- ``mixtral``: Mixtral-architecture MoE (8 experts, top-2, GQA) scaled to
  one chip's HBM, trained with the default capacity-factor DISPATCH MoE
  (only selected experts compute — measured 1.81× the dense oracle's
  tok/s at identical loss; EP sharding splits the expert dim on
  multi-chip meshes — dryrun_multichip covers that compilation).
  Reports tok/s/chip and ACTIVE-params MFU.
- ``vit``: ViT-L/16 supervised training driven AS A PIPELINES DAG
  (make-config → train-on-chip → summarize), the BASELINE "ViT-L/CLIP via
  pipelines" shape; components run in-process so the train step owns the
  chip. Reports images/sec/chip and DAG wall-clock overhead.
- ``gemma-chip``: gemma-2b architecture scaled to one chip, measured
  directly (tok/s/chip on TPU).
- ``gemma-sweep``: the Katib-analog HPO sweep — 4 random-search trials of
  tiny-gemma through the LIVE control plane with real worker processes
  (orchestration wall-clock; CPU workers — the sim tunnel serializes chip
  access across processes).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _train_rate(cfg, per_chip_batch, *, k_dispatch=8, disp=3, warm=2,
                mu="bfloat16", lr=None, attn_impl=None):
    """Thin wrapper over bench.measure_train_rate — ONE measurement
    methodology for every training-throughput row (same dispatch loop,
    fencing, MFU accounting AND knob defaults as the headline bench,
    via bench.TrainKnobs)."""
    from bench import HEADLINE_KNOBS, measure_train_rate

    import jax

    if attn_impl is None:
        attn_impl = HEADLINE_KNOBS.attn_impl(jax.default_backend() == "tpu")
    elif jax.default_backend() != "tpu":
        attn_impl = "xla"          # interpret-mode kernels are CI-only
    return measure_train_rate(cfg, per_chip_batch, k_dispatch=k_dispatch,
                              warm_disp=warm, disp=disp, mu_dtype=mu,
                              learning_rate=lr, attn_impl=attn_impl)


def bench_mixtral():
    """BASELINE config 3: Mixtral 8x7B architecture (8 experts, top-2),
    scaled to one chip's HBM at the same expert/hidden ratios."""
    import jax

    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.runtime.topology import detect_local_cluster

    cfg = preset(
        "mixtral-8x7b",
        n_layers=8, hidden=1024, n_heads=16, n_kv_heads=4, head_dim=64,
        mlp_dim=3584, vocab_size=32000, max_seq_len=2048,
        remat_policy="block_outs", loss_chunk_size=512,
    )
    out = _train_rate(cfg, per_chip_batch=4)
    gen = detect_local_cluster().slices[0].gen
    active_mfu = (cfg.flops_per_token() * out["tok_s_chip"]) / (
        gen.bf16_tflops * 1e12)
    return {
        "metric": "mixtral_moe_train_tokens_per_sec_per_chip"
                  "[mixtral-0.8b-8e-top2,seq2048]",
        "value": out["tok_s_chip"], "unit": "tokens/sec/chip",
        "detail": {**out, "active_param_mfu": round(active_mfu, 4),
                   "num_experts": 8, "experts_per_token": 2,
                   "moe_impl": "dispatch",
                   "capacity_factor": 1.25,
                   "note": "capacity-factor dispatch MoE (default): only "
                           "selected experts compute; the dense oracle "
                           "measured 14.1k tok/s on the same config "
                           "(BASELINE.md round-3 table)"},
    }


def bench_vit():
    """BASELINE config 4: ViT-L/16 supervised training as a pipelines DAG."""
    import jax

    from kubeflow_tpu.pipelines import dsl
    from kubeflow_tpu.pipelines.compiler import compile_pipeline
    from kubeflow_tpu.pipelines.artifacts import ArtifactStore
    from kubeflow_tpu.pipelines.executor import PipelineExecutor
    from kubeflow_tpu.pipelines.metadata import MetadataStore
    import tempfile

    @dsl.component
    def make_config(steps: int, batch: int) -> dict:
        return {"steps": steps, "batch": batch}

    @dsl.component
    def train_vit(plan: dict) -> dict:
        from kubeflow_tpu.models.vision import vit_preset
        from kubeflow_tpu.runtime.mesh import build_mesh
        from kubeflow_tpu.train.optim import OptimizerConfig
        from kubeflow_tpu.train.vision_task import setup_vit_train, vit_batch

        devices = jax.devices()
        mesh = build_mesh({"data": len(devices)}, devices)
        cfg = vit_preset("vit-l16")
        task = setup_vit_train(cfg, OptimizerConfig(total_steps=10_000), mesh)
        state = task.state
        warm, timed = 2, plan["steps"]
        # Image batches are ~38 MB each: through the tunneled chip the
        # host->device upload would dwarf the step. Stage a few batches on
        # device once (real input pipelines double-buffer the same way)
        # and cycle them in the timed loop.
        staged = [jax.device_put(vit_batch(cfg, plan["batch"], i),
                                 task.batch_shardings) for i in range(4)]
        for i in range(warm):
            state, m = task.step_fn(state, staged[i % len(staged)])
            float(m["loss"])
        t0 = time.perf_counter()
        for i in range(timed):
            state, m = task.step_fn(state, staged[(warm + i) % len(staged)])
            float(m["loss"])            # host fence per step (tunnel)
        dt = time.perf_counter() - t0
        return {"images_per_sec": plan["batch"] * timed / dt,
                "step_ms": dt / timed * 1e3, "loss": float(m["loss"])}

    @dsl.component
    def summarize(train: dict) -> float:
        return train["images_per_sec"]

    @dsl.pipeline(name="vit-l16-train")
    def vit_pipeline(steps: int = 8, batch: int = 64):
        plan = make_config(steps=steps, batch=batch)
        out = train_vit(plan=plan)
        summarize(train=out)

    td = tempfile.mkdtemp(prefix="vitbench-")
    store = MetadataStore(os.path.join(td, "mlmd.db"))
    ex = PipelineExecutor(ArtifactStore(os.path.join(td, "arts")), store)
    ir = compile_pipeline(vit_pipeline)
    t0 = time.perf_counter()
    run = ex.run(ir, parameters={"steps": 8, "batch": 64})
    wall = time.perf_counter() - t0
    store.close()
    from kubeflow_tpu.pipelines.executor import RunPhase

    assert run.phase is RunPhase.SUCCEEDED, run
    detail = run.tasks["train_vit"].outputs["output"]
    return {
        "metric": "vit_l16_train_images_per_sec_per_chip[pipelines-dag]",
        "value": round(detail["images_per_sec"] / len(jax.devices()), 1),
        "unit": "images/sec/chip",
        "detail": {"step_ms": round(detail["step_ms"], 2),
                   "dag_wall_s": round(wall, 1),
                   "loss": round(detail["loss"], 4),
                   "batch": 64, "timed_steps": 8},
    }


def bench_gemma_chip():
    """BASELINE config 5a: Gemma-2B architecture scaled to one chip
    (wide-head GQA, GeGLU, tied embeddings, 256k-vocab ratios kept via the
    chunked-CE head)."""
    from kubeflow_tpu.models.config import preset

    cfg = preset(
        "gemma-2b",
        n_layers=8, hidden=1024, n_heads=8, n_kv_heads=1, head_dim=128,
        mlp_dim=8192, vocab_size=64000, max_seq_len=2048,
        remat_policy="block_outs", loss_chunk_size=256,
    )
    out = _train_rate(cfg, per_chip_batch=4, lr=1e-4)
    return {
        "metric": "gemma_scaled_train_tokens_per_sec_per_chip"
                  "[gemma-0.4b,seq2048]",
        "value": out["tok_s_chip"], "unit": "tokens/sec/chip",
        "detail": {**out,
                   "note": "loss is init-dominated over a 40-step "
                           "throughput window (embed_scale x tied head "
                           "at this width inflates initial logits); "
                           "convergence is covered by the tiny-gemma "
                           "training tests"},
    }


def bench_gemma_sweep():
    """BASELINE config 5b: the HPO sweep itself — 4 random-search trials of
    tiny-gemma through the live control plane with real worker processes
    (orchestration wall-clock; the platform half of the Katib config)."""
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from kubeflow_tpu.operator.control_plane import (
        ControlPlane, ControlPlaneConfig,
    )
    from kubeflow_tpu.runtime.topology import Cluster, SliceTopology
    from kubeflow_tpu.tune.client import build_experiment, parameter

    plane = ControlPlane(ControlPlaneConfig(
        base_dir=tempfile.mkdtemp(prefix="sweep-"),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="cpu",
                                              dims=(2, 2))]),
        platform="cpu"))
    plane.start()
    try:
        exp = build_experiment(
            "gemma-sweep", entrypoint="llm_pretrain",
            parameters=[
                parameter("learning_rate", min=3e-4, max=3e-3,
                          log_scale=True),
                parameter("warmup_steps", min=0, max=4),
            ],
            objective_metric="loss", algorithm="random",
            algorithm_settings={"random_state": 0},
            max_trial_count=4, parallel_trial_count=2,
            metric_source="push",
            base_config={
                "model": "tiny-gemma", "steps": 12, "log_every": 4,
                "optimizer": {
                    "learning_rate": "${trialParameters.learning_rate}",
                    "warmup_steps": "${trialParameters.warmup_steps}"},
                "data": {"global_batch": 4, "seq_len": 64},
            })
        t0 = time.perf_counter()
        plane.submit(exp)
        done = plane.wait_for(exp, "Succeeded", timeout=600)
        wall = time.perf_counter() - t0
        opt = done.status.current_optimal_trial
        return {
            "metric": "katib_sweep_wall_clock_s"
                      "[tiny-gemma,4-trials,2-parallel]",
            "value": round(wall, 1), "unit": "seconds",
            "detail": {"trials_succeeded": done.status.trials_succeeded,
                       "best_objective": round(opt.objective_value, 4),
                       "best_params": opt.parameter_assignments},
        }
    finally:
        plane.stop()


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "--all"
    benches = {
        "mixtral": bench_mixtral,
        "vit": bench_vit,
        "gemma-chip": bench_gemma_chip,
        "gemma-sweep": bench_gemma_sweep,
    }
    if which != "--all":
        if which not in benches:
            sys.exit(f"unknown bench {which!r}; one of "
                     f"{sorted(benches)} or --all")
        print(json.dumps(benches[which]()))
        return
    rows = []
    for name, fn in benches.items():
        try:
            row = fn()
        except Exception as exc:   # record the failure, keep benching
            row = {"metric": name, "failed": True,
                   "err": f"{type(exc).__name__}: {exc}"}
        rows.append(row)
        print(json.dumps(row), flush=True)
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_CONFIGS.json")
    with open(out, "w") as f:
        json.dump({"rows": rows, "round": 2,
                   "script": "scripts/bench_configs.py"}, f, indent=1)


if __name__ == "__main__":
    main()
