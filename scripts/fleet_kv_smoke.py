#!/usr/bin/env python
"""Fleet-wide KV fabric gate (scripts/smoke.sh): cross-host handoff,
remote-tier conversation failover, and steady-state compile stability
(ISSUE 17).

What must hold, on small paged CPU engines:

- **handoff identity**: completions through a real prefill server →
  HTTP handoff → decode server are byte-identical to the unified
  single-engine reference, with the exported/adopted counters moving
  and ZERO fallbacks;
- **failover-resume beats cold recompute**: conversations generated on
  replica A and drained to the artifact store (the scale-down/SIGKILL
  survival path) resume on replica B — which shares only the store
  root, never a live connection — token-identical to a cold engine AND
  with better TTFT p95 than recomputing the whole history (the third
  tier's whole case: a promote must be cheaper than the prefill it
  replaces);
- **zero steady-state recompiles**: with KFTPU_SANITIZE=refcount,
  recompile on for the whole stage, a post-warm remote-tier resume and
  a post-warm handoff round trip compile NOTHING;
- **hygiene**: the new fabric series parse off the real exposition
  (the consumer half of the X7xx metric contract), per-owner refcount
  books balance to zero on every engine.

Writes ``BENCH_SERVE_r06.json`` (the fleet-KV bench round); prints one
JSON object; ``{"fleet_kv_smoke": "ok"}`` is the gate line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Refcount (per-owner page books) + recompile (steady-state watchdog)
# for the whole stage.
os.environ["KFTPU_SANITIZE"] = "refcount,recompile"

#: Fabric series this gate consumes off the engine exposition — the
#: consumer half of the kftpu_engine_kv_remote_*/handoff contract.
FLEET_SERIES = (
    "kftpu_engine_kv_pages_remote",
    "kftpu_engine_kv_remote_demoted_bytes_total",
    "kftpu_engine_kv_remote_promoted_bytes_total",
    "kftpu_engine_kv_remote_promote_timeouts_total",
    "kftpu_engine_kv_remote_blobs_corrupt_total",
    "kftpu_engine_kv_tier_pressure",
    "kftpu_engine_handoffs_retried_total",
    "kftpu_engine_handoffs_fallback_total",
)

TURN1_LEN = 160
MAX_NEW = 8
CONVS = 6          # conversation 0 is held back for the post-warm resume


def turn1_tokens(i: int) -> list:
    return [(i * 31 + j * 7) % 500 + 1 for j in range(TURN1_LEN)]


def wait(req, timeout=60.0):
    assert req.done.wait(timeout), "request never finished"
    return req


def p95(xs: list) -> float:
    ys = sorted(xs)
    return ys[min(len(ys) - 1, int(round(0.95 * (len(ys) - 1))))]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.parse_args()

    import jax

    from kubeflow_tpu.core.headers import DECODE_BACKEND_HEADER
    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.obs.registry import parse_exposition
    from kubeflow_tpu.runtime.sanitize import (
        mark_compile_warm, recompile_report, recompile_watchdog,
    )
    from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams
    from kubeflow_tpu.serve.server import (
        ModelServer, serving_metrics_registry,
    )

    result: dict = {}

    def fail(msg: str) -> int:
        result["fleet_kv_smoke"] = msg
        print(json.dumps(result, indent=2))
        return 1

    wd = recompile_watchdog()
    if wd is None:
        return fail("recompile watchdog not installed")

    # A notch above "tiny": resumed-vs-recomputed TTFT is an avoided-
    # prefill-compute claim, so prefill must cost real wall time.
    cfg = preset("tiny", vocab_size=512, max_seq_len=256, hidden=128,
                 n_layers=4, mlp_dim=256)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    tiny = preset("tiny", vocab_size=512)
    tiny_params = init_decoder_params(jax.random.PRNGKey(0), tiny)

    tmp = tempfile.mkdtemp(prefix="fleet-kv-")
    cold_root = tempfile.mkdtemp(prefix="fleet-kv-cold-")

    def fabric_spec(root):
        # Long idle timer: demotion happens only through the FORCED
        # drain (pre-warm), so no background demote batch can introduce
        # a fresh gather shape after mark_compile_warm().
        return BatchingSpec(
            max_batch_size=4, max_seq_len=256, paged=True, page_size=16,
            chunked_prefill_tokens=32, decode_steps=4,
            prefix_index="radix", host_kv_pages=256,
            kv_demote_after_s=60.0, remote_kv_root=root)

    sp = SamplingParams(max_new_tokens=MAX_NEW, temperature=0.0)
    sp1 = SamplingParams(max_new_tokens=1, temperature=0.0)
    engines: list = []
    servers: list = []

    def mk_engine(spec_, c=cfg, p=None):
        eng = LLMEngine(c, spec_, params=(p if p is not None else params))
        eng.start()
        engines.append(eng)
        return eng

    def completion(url, prompt, headers=()):
        body = json.dumps({"prompt": prompt, "max_tokens": 8,
                           "timeout": 30}).encode()
        req = urllib.request.Request(
            url + "/v1/completions", data=body,
            headers={"Content-Type": "application/json", **dict(headers)})
        with urllib.request.urlopen(req, timeout=40) as r:
            return json.loads(r.read())["choices"][0]["text"]

    try:
        # 1) Cross-host handoff identity over real HTTP: prefill server
        #    → v2 wire → decode server vs the unified reference.
        def srv_spec(role):
            return BatchingSpec(max_batch_size=2, max_seq_len=96,
                                paged=True, page_size=16,
                                prefill_buckets=[32],
                                chunked_prefill_tokens=16, decode_steps=4,
                                role=role)

        pre = ModelServer("pre", LLMEngine(tiny, srv_spec("prefill"),
                                           params=tiny_params), port=0)
        dec = ModelServer("dec", LLMEngine(tiny, srv_spec("decode"),
                                           params=tiny_params), port=0)
        uni = ModelServer("uni", LLMEngine(tiny, srv_spec("unified"),
                                           params=tiny_params), port=0)
        for s in (pre, dec, uni):
            s.start()
            servers.append(s)
        prompts = ["fleet kv fabric handoff %d" % i for i in range(4)]
        hdr = [(DECODE_BACKEND_HEADER, dec.url)]
        for p in prompts:
            got = completion(pre.url, p, headers=hdr)
            want = completion(uni.url, p)
            if got != want:
                return fail(f"handoff output diverged on {p!r}: "
                            f"{got!r} != {want!r}")
        pre_snap = pre.engine.metrics.snapshot()
        if pre_snap["handoffs_exported"] < len(prompts):
            return fail(f"handoffs not exported: {pre_snap}")
        if pre_snap["handoffs_fallback"] != 0:
            return fail(f"unexpected handoff fallbacks: {pre_snap}")
        if dec.engine.metrics.snapshot()["handoffs_adopted"] < len(prompts):
            return fail("decode side adopted fewer handoffs than sent")
        result["handoff_identity"] = "ok"

        # 2) Failover-resume: conversations born on A, drained to the
        #    store (the replica-leaves-the-fleet path), resumed on B.
        a = mk_engine(fabric_spec(tmp))
        turns1 = {}
        for i in range(CONVS):
            turns1[i] = wait(a.submit(turn1_tokens(i), sp))
        drained = a.drain_kv_to_remote()
        if drained <= 0:
            return fail("drain_kv_to_remote published no pages")
        result["pages_drained"] = drained
        a.stop()
        engines.remove(a)

        b = mk_engine(fabric_spec(tmp))               # the survivor
        cold = mk_engine(fabric_spec(cold_root))      # same code, no blobs

        def turn2_tokens(i: int) -> list:
            r = turns1[i]
            return (list(r.prompt_tokens) + list(r.output_tokens)
                    + [9, 17, 25, 33])

        # Warm both sides' full path shapes — including B's remote
        # promote (its OWN warmup conversation through the store) — so
        # the timing loop and the post-warm replay measure the fabric,
        # not XLA compiles.
        wreq = wait(b.submit(turn1_tokens(97), sp))
        b.drain_kv_to_remote()
        wait(b.submit(list(wreq.prompt_tokens) + list(wreq.output_tokens)
                      + [9, 17, 25, 33], sp))
        wait(cold.submit(turn1_tokens(98), sp))

        resume_ms, cold_ms = [], []
        for i in range(1, CONVS):                     # conv 0 held back
            toks = turn2_tokens(i)
            t0 = time.monotonic()
            wait(b.submit(list(toks), sp1))
            resume_ms.append((time.monotonic() - t0) * 1e3)
            t0 = time.monotonic()
            wait(cold.submit(list(toks), sp1))
            cold_ms.append((time.monotonic() - t0) * 1e3)
        tier = b.kv_tier_stats()
        if tier["remote_registry_hits"] <= 0:
            return fail(f"no remote registry hits on the survivor: {tier}")
        if tier["pages_promoted_remote"] < (CONVS - 1) * 2:
            return fail(f"too few remote promotes: {tier}")
        r_p95, c_p95 = p95(resume_ms), p95(cold_ms)
        result["ttft"] = {"resume_p95_ms": round(r_p95, 2),
                          "cold_p95_ms": round(c_p95, 2),
                          "speedup": round(c_p95 / max(r_p95, 1e-6), 3)}
        if r_p95 >= c_p95:
            return fail(f"failover resume did not beat cold recompute: "
                        f"{result['ttft']}")

        # Token identity of the resumed turns against the cold engine.
        for i in range(1, CONVS):
            toks = turn2_tokens(i)
            rb = wait(b.submit(list(toks), sp))
            rc = wait(cold.submit(list(toks), sp))
            if list(rb.output_tokens) != list(rc.output_tokens):
                return fail(f"resumed conversation {i} diverged")
        result["failover_identity"] = "ok"

        # 3) Zero steady-state recompiles: the held-back conversation
        #    rides the WHOLE fabric (registry probe, blob fetch, verify,
        #    promote upload) post-warm, plus one more handoff roundtrip.
        mark_compile_warm()
        rb = wait(b.submit(turn2_tokens(0), sp))
        rc = wait(cold.submit(turn2_tokens(0), sp))
        if list(rb.output_tokens) != list(rc.output_tokens):
            return fail("post-warm resumed conversation diverged")
        if b.kv_tier_stats()["pages_promoted_remote"] <= \
                tier["pages_promoted_remote"]:
            return fail("post-warm resume never touched the remote tier")
        got = completion(pre.url, prompts[0], headers=hdr)
        want = completion(uni.url, prompts[0])
        if got != want:
            return fail("post-warm handoff output diverged")
        rep = recompile_report()
        result["recompiles"] = {"warmup": len(rep["warmup"]),
                                "steady": len(rep["steady"])}
        if rep["steady"]:
            return fail(f"steady-state recompiles: {rep['steady']}")

        # 4) Hygiene: fabric series parse off the real exposition;
        #    per-owner books balance to zero everywhere.
        text = serving_metrics_registry(
            [("b", b), ("pre", pre.engine), ("dec", dec.engine)]).render()
        names = {n for n, _, _ in parse_exposition(text)}
        missing = [s for s in FLEET_SERIES if s not in names]
        if missing:
            return fail(f"fabric series missing from exposition: {missing}")
        vals = {(n, lab.get("model")): v
                for n, lab, v in parse_exposition(text)}
        if vals[("kftpu_engine_kv_remote_promoted_bytes_total", "b")] <= 0:
            return fail("remote promote bytes never counted")
        for eng in engines + [s.engine for s in servers]:
            deadline = time.monotonic() + 20.0
            while eng.kv_pages_in_use() > 0:
                time.sleep(0.02)
                if time.monotonic() > deadline:
                    return fail("KV pages failed to drain")
            report = eng._allocator.leak_report_by_owner()
            if report:
                return fail(f"per-owner page leaks: {report}")
            eng._allocator.assert_quiescent()
        result["hygiene"] = "ok"

        bench = {
            "bench": "serve_r06_fleet_kv_fabric",
            "model": "tiny-cpu-smoke",
            "handoff_identity": result["handoff_identity"],
            "failover_identity": result["failover_identity"],
            "ttft": result["ttft"],
            "pages_drained": result["pages_drained"],
            "remote_tier": {k: tier[k] for k in
                            ("remote_registry_hits",
                             "pages_promoted_remote",
                             "remote_promote_bytes")},
            "recompiles": result["recompiles"],
        }
        with open(os.path.join(REPO, "BENCH_SERVE_r06.json"), "w") as f:
            json.dump(bench, f, indent=2)
            f.write("\n")
        result["fleet_kv_smoke"] = "ok"
        print(json.dumps(result, indent=2))
        return 0
    finally:
        for s in servers:
            try:
                s.stop()
            except OSError:
                pass
        for eng in engines:
            eng.stop()


if __name__ == "__main__":
    sys.exit(main())
