"""Training chaos smoke stage for scripts/smoke.sh: survivable training
proven on a real control plane in one compact run.

Two scenarios against real worker processes (ISSUE 9 acceptance, the CI-fast
slice of tests/test_train_chaos.py):

1. **Preemption**: a 1-worker llm_pretrain job is SIGTERMed mid-run. The
   trainer must emergency-save at the next step boundary, exit retryable,
   gang-restart, and resume AT the emergency step — zero completed steps
   lost (``steps_lost_total == 0`` in the goodput ledger).
2. **Corruption**: the job is suspended, its newest checkpoint (either
   tier) is truncated to garbage, and on resume the verified restore must
   quarantine it and FALL BACK to an older valid step — the job still
   reaches Succeeded with ``restore_fallbacks >= 1`` and the goodput
   ledger lifted onto job status.

Prints one JSON object; ``"train_chaos_smoke": "ok"`` is the pass marker
smoke.sh greps for.

    JAX_PLATFORMS=cpu python scripts/train_chaos_smoke.py
"""

from __future__ import annotations

import json
import os
import re
import signal
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _train_job(name: str, *, steps: int, ckpt_every: int):
    from kubeflow_tpu.core.jobs import (
        JAXJob, JAXJobSpec, ReplicaSpec, RestartPolicy, TPUResourceSpec,
        WorkloadSpec,
    )
    from kubeflow_tpu.core.object import ObjectMeta

    j = JAXJob(
        metadata=ObjectMeta(name=name),
        spec=JAXJobSpec(replica_specs={"worker": ReplicaSpec(
            replicas=1,
            restart_policy=RestartPolicy.EXIT_CODE,
            template=WorkloadSpec(entrypoint="llm_pretrain", config={
                "model": "tiny",
                "model_overrides": {"n_layers": 2, "hidden": 128},
                "steps": steps,
                "log_every": 2,
                "data": {"global_batch": 16, "seq_len": 128,
                         "kind": "synthetic"},
            }),
            resources=TPUResourceSpec(tpu_chips=1),
        )}),
    )
    j.spec.run_policy.checkpoint.enabled = True
    j.spec.run_policy.checkpoint.interval_steps = ckpt_every
    return j


def _wait(cp, name, pred, timeout, what):
    deadline = time.time() + timeout
    while time.time() < deadline:
        cur = cp.get_job(name)
        if cur is not None and pred(cur):
            return cur
        time.sleep(0.2)
    raise AssertionError(f"{name}: timed out waiting for {what}")


def _ledger(cp, name):
    path = os.path.join(cp.config.base_dir, "default", name, "worker-0",
                        "goodput.json")
    with open(path) as f:
        return json.load(f)


def _log(cp, name):
    with open(os.path.join(cp.config.base_dir, "logs",
                           f"default.{name}-worker-0.log")) as f:
        return f.read()


def main() -> int:
    from kubeflow_tpu.core.store import ConflictError
    from kubeflow_tpu.operator.control_plane import (
        ControlPlane, ControlPlaneConfig,
    )
    from kubeflow_tpu.operator.faults import FaultInjector
    from kubeflow_tpu.runtime.topology import Cluster, SliceTopology
    from kubeflow_tpu.serve.retry import RetryPolicy, call_with_retry

    def set_suspend(cp, name: str, value: bool) -> None:
        """Flip run_policy.suspend through the optimistic-concurrency
        store, retrying lost ConflictError races via the blessed helper
        (T802: no ad-hoc sleep loops)."""
        def attempt(_attempt: int) -> None:
            fresh = cp.get_job(name)
            fresh.spec.run_policy.suspend = value
            cp.store.update(fresh)
        call_with_retry(
            attempt,
            policy=RetryPolicy(attempts=20, base_s=0.05, cap_s=0.05,
                               jitter_frac=0.0),
            retry_on=(ConflictError,))

    base = tempfile.mkdtemp(prefix="kftpu-train-chaos-")
    cp = ControlPlane(ControlPlaneConfig(
        base_dir=base,
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="cpu",
                                              dims=(2, 2))]),
        platform="cpu", heartbeat_timeout=20.0, rendezvous_timeout=60.0))
    cp.start()
    inj = FaultInjector(cp)
    checks: dict[str, object] = {}
    failures: list[str] = []

    def check(name: str, ok: bool, detail=None):
        checks[name] = bool(ok) if detail is None else detail
        if not ok:
            failures.append(name)

    try:
        # -- scenario 1: SIGTERM -> emergency tier, zero steps lost ----------
        job = cp.submit(_train_job("surv", steps=60, ckpt_every=20))
        cp.wait_for(job, "Running", timeout=240)
        _wait(cp, "surv", lambda j: j.status.metrics.step >= 4, 240,
              "step >= 4")
        inj.kill_worker("default/surv", index=0, sig=signal.SIGTERM)
        done = cp.wait_for(job, "Succeeded", timeout=420)
        led = _ledger(cp, "surv")
        log = _log(cp, "surv")
        m_save = re.search(
            r"preemption: emergency checkpoint at step (\d+) \(saved\)", log)
        m_res = re.search(
            r"resumed from checkpoint at step (\d+) \(tier=emergency", log)
        check("preempt_restarted", done.status.restart_count >= 1)
        check("preempt_all_steps", done.status.metrics.step == 60)
        check("preempt_emergency_saved", m_save is not None)
        check("preempt_resumed_at_emergency_step",
              bool(m_save and m_res
                   and m_save.group(1) == m_res.group(1)))
        check("preempt_zero_steps_lost", led["steps_lost_total"] == 0,
              detail=led["steps_lost_total"])
        check("preempt_goodput_on_status",
              done.status.metrics.goodput is not None
              and done.status.metrics.emergency_saves >= 1)

        # -- scenario 2: corrupt latest -> verified fallback, job succeeds ---
        job = cp.submit(_train_job("fallb", steps=80, ckpt_every=6))
        cp.wait_for(job, "Running", timeout=240)
        _wait(cp, "fallb",
              lambda j: (j.status.metrics.last_checkpoint_step or 0) >= 12,
              240, "two committed interval saves")
        set_suspend(cp, "fallb", True)
        cp.wait_for(job, "Suspended", timeout=120)
        deadline = time.time() + 60
        while cp.runtime.procman.alive() and time.time() < deadline:
            time.sleep(0.1)     # teardown emergency save must land first
        target = inj.corrupt_latest_checkpoint("default/fallb")
        check("corrupt_target_found", target is not None, detail=target)
        set_suspend(cp, "fallb", False)
        done = cp.wait_for(job, "Succeeded", timeout=420)
        led = _ledger(cp, "fallb")
        log = _log(cp, "fallb")
        m_res = re.search(
            r"resumed from checkpoint at step (\d+) \(tier=\w+, "
            r"fallbacks=(\d+)\)", log)
        check("corrupt_all_steps", done.status.metrics.step == 80)
        check("corrupt_fell_back",
              bool(m_res and int(m_res.group(2)) >= 1))
        check("corrupt_fallback_on_status",
              (done.status.metrics.restore_fallbacks or 0) >= 1,
              detail=done.status.metrics.restore_fallbacks)
        check("corrupt_ledger_fallbacks", led["restore_fallbacks"] >= 1,
              detail=led["restore_fallbacks"])
        check("corrupt_quarantined",
              target is not None and os.path.isdir(os.path.join(
                  os.path.dirname(target), "quarantine")))
    except Exception as exc:    # a hang/timeout is itself the failure
        failures.append(f"exception: {type(exc).__name__}: {exc}")
    finally:
        cp.stop()

    ok = not failures
    print(json.dumps({
        "train_chaos_smoke": "ok" if ok else "FAIL",
        "checks": checks,
        "failures": failures,
    }, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
