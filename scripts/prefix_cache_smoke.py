#!/usr/bin/env python
"""Tiered-KV-cache gate (scripts/smoke.sh): radix prefix index + host
tier vs the flat-cache baseline, proven through the loadgen scenarios
the subsystem exists for (ISSUE 13).

What must hold, on a small paged CPU engine:

- **token identity**: a full ``multi_turn`` scenario (conversation
  sessions re-arriving with their prior prefix + a new turn, think-time
  gaps forcing device→host demotion between turns) replayed on the
  radix+tier engine and on a prefix-caching-OFF engine produces
  IDENTICAL greedy outputs for every turn of every session;
- **the win**: on the 50%-overlap multi-turn workload the radix+tier
  engine must beat the flat-cache baseline on BOTH headline metrics —
  effective prefill tok/s (offered prompt tokens / total prefill-phase
  seconds, from the engine's own spans) and client TTFT p95 (best of
  two measured segments per side, the anti-noise discipline);
- **the sweep**: ``shared_prefix`` at overlap 0.5 / 0.75 / 0.95 (the
  scenario knob) — radix TTFT p95 stays within the noise band of flat
  (the flat hash already monetizes full-page overlap; radix must never
  regress it) and radix reuses at least as many prefix tokens;
- **tier lifecycle**: the multi-turn think gaps actually demote pages
  to the host tier and promote them back on re-arrival (both counters
  move), with the ``engine.kv_migrate`` phase visible in traces;
- **seeded migration wedge**: a sleep wedged into the migration
  thread's wire encode (it holds the tier lock, exactly how a wedged
  migration starves admission matching) MUST be flagged by the loadgen
  gate with the attribution diff naming where the latency went;
- **hygiene**: zero leaked KV pages per owner (KFTPU_SANITIZE=refcount
  is on for the whole stage) on device AND host tiers, quiescent pools
  after every run.

Writes ``BENCH_SERVE_r03.json`` (the tiered-KV serving bench round);
prints one JSON object; ``{"prefix_cache_smoke": "ok"}`` is the gate
line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Refcount sanitizer ON for the whole stage: every page reference is
# owner-stamped, so the final audit names leakers (must name none).
os.environ.setdefault("KFTPU_SANITIZE", "refcount")

#: Tier series this gate consumes off the engine exposition — the
#: consumer half of the kftpu_engine_kv_* metric contract (X7xx).
TIER_SERIES = (
    "kftpu_engine_kv_pages_resident",
    "kftpu_engine_kv_pages_cached",
    "kftpu_engine_kv_pages_host",
    "kftpu_engine_kv_prefix_hits_total",
    "kftpu_engine_kv_prefix_tokens_reused_total",
    "kftpu_engine_kv_cow_copies_total",
    "kftpu_engine_kv_pages_demoted_total",
    "kftpu_engine_kv_pages_promoted_total",
)

# Opening prompts big enough that saved prefill compute dominates TTFT
# (at tiny prompt sizes the fixed dispatch floor hides the cache win).
PROMPT_LEN = 64
MAX_NEW = 8
TURNS = 6


def mk_engine(kind: str):
    import jax

    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.serve.engine import LLMEngine

    # A notch above "tiny": chunk-prefill compute must cost real wall
    # time or the TTFT comparison drowns in scheduler jitter (the win
    # being measured IS avoided prefill compute).
    cfg = preset("tiny", vocab_size=512, max_seq_len=256, hidden=128,
                 n_layers=4, mlp_dim=256)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    # Deliberately tight device pool (the millions-of-users regime in
    # miniature): live traffic fits, but idle conversations cannot ALL
    # stay device-cached — the flat baseline's cached prefixes get LRU-
    # evicted under pressure, while the radix engine demotes them to
    # host RAM and promotes on re-arrival. That pressure is the tier's
    # whole case; without it both caches serve from HBM and tie.
    kw = dict(max_batch_size=8, max_seq_len=256, paged=True, page_size=16,
              max_pages=48, chunked_prefill_tokens=16, decode_steps=8)
    if kind == "radix":
        kw.update(prefix_index="radix", host_kv_pages=192,
                  kv_demote_after_s=0.4, kv_migrate_batch_pages=16)
    elif kind == "flat":
        kw.update(prefix_index="flat")
    elif kind == "off":
        kw.update(enable_prefix_caching=False)
    else:
        raise ValueError(kind)
    eng = LLMEngine(cfg, BatchingSpec(**kw), params=params)
    eng.start()
    return eng, cfg


def multi_turn_scenario(requests: int, *, think_s: float, seed: int = 7,
                        rate_rps: float = 2.0):
    from kubeflow_tpu.loadgen import Arrival, LengthDist, Scenario

    return Scenario(
        name="multi_turn", num_requests=requests, seed=seed,
        arrival=Arrival(process="poisson", rate_rps=rate_rps),
        prompt_len=LengthDist(kind="fixed", value=PROMPT_LEN),
        output_len=LengthDist(kind="fixed", value=MAX_NEW),
        turns=TURNS, think_time_s=think_s, prefix_overlap=0.5,
        slo_ttft_ms=5000.0, request_timeout_s=60.0)


def shared_prefix_scenario(requests: int, overlap: float):
    from kubeflow_tpu.loadgen import standard_matrix

    # Shape sized so the LIVE working set fits the 48-page pool with
    # headroom (shorter prompts, moderate rate): the sweep is a
    # no-regression check on the flat hash's bread-and-butter shape and
    # the overlap-knob plumbing, not the pressure probe — the
    # multi-turn A/B owns that (its sessions keep the live set small
    # while the IDLE set overflows, the tier's actual regime; a
    # saturated open-loop pool measures queueing order, not caching).
    sc = next(s for s in standard_matrix(
        num_requests=requests, rate_rps=3.0, prompt_len=PROMPT_LEN // 2,
        max_new=MAX_NEW, slo_ttft_ms=5000.0,
        shared_prefix_overlap=overlap) if s.name == "shared_prefix")
    return sc


def run_once(engine, cfg, sc):
    """One scenario segment: (report, prefill tok/s, run)."""
    from kubeflow_tpu.loadgen import (
        EngineTarget, build_report, run_scenario,
    )
    from kubeflow_tpu.obs.trace import get_tracer, phase_durations
    from kubeflow_tpu.serve.server import serving_metrics_registry

    tracer = get_tracer()
    tracer.reset()
    run = run_scenario(EngineTarget(engine), sc,
                       vocab_size=cfg.vocab_size, max_prompt_len=128)
    text = serving_metrics_registry([("smoke", engine)]).render()
    rep = build_report(run, metrics_text=text, tracer=tracer)
    # Effective prefill throughput: offered prompt tokens (composed
    # turns included — resolved client-side) / total prefill seconds
    # from the engine's own spans.
    prefill_ms = 0.0
    prompt_tokens = 0
    for o in run.outcomes:
        prompt_tokens += o.prompt_len       # composed conversations
        tr = tracer.trace(o.trace_id) if o.trace_id else None
        if tr is not None:
            ph = phase_durations(tr["spans"])
            prefill_ms += ph.get("prefill_ms", 0.0)
    tok_s = prompt_tokens / max(prefill_ms / 1e3, 1e-6)
    return rep, tok_s, run


def drain(engine, deadline_s: float = 20.0) -> None:
    deadline = time.monotonic() + deadline_s
    while engine.kv_pages_in_use() > 0:
        time.sleep(0.02)
        if time.monotonic() > deadline:
            raise AssertionError("KV pages failed to drain")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=18)
    args = ap.parse_args()

    from kubeflow_tpu.loadgen import compare_scenario
    from kubeflow_tpu.obs.registry import parse_exposition
    from kubeflow_tpu.serve.server import serving_metrics_registry

    result: dict = {}

    def fail(msg: str) -> int:
        result["prefix_cache_smoke"] = msg
        print(json.dumps(result, indent=2))
        return 1

    engines = {k: mk_engine(k) for k in ("radix", "flat", "off")}
    try:
        # 1) Token identity on the multi-turn conversation shape:
        #    radix+tier vs prefix caching OFF, every turn compared.
        sc_id = multi_turn_scenario(args.requests, think_s=0.25)
        outs = {}
        for kind in ("radix", "off"):
            eng, cfg = engines[kind]
            _, _, run = run_once(eng, cfg, sc_id)
            if not all(o.ok for o in run.outcomes):
                return fail(f"identity run had failures on {kind}: "
                            f"{[(o.idx, o.status) for o in run.outcomes if not o.ok]}")
            outs[kind] = {o.idx: tuple(o.gen) for o in run.outcomes}
        if outs["radix"] != outs["off"]:
            bad = [i for i in outs["radix"]
                   if outs["radix"][i] != outs["off"][i]]
            return fail(f"token identity broken on turns {bad[:8]}")
        result["token_identity"] = "ok"
        tier = engines["radix"][0].kv_tier_stats()
        if tier["prefix_hits"] < args.requests // 3:
            return fail(f"too few radix hits: {tier}")

        # 1b) Tier lifecycle, deterministically: one conversation goes
        #     idle past kv_demote_after_s (its pages demote to host),
        #     then its next turn arrives — the radix hit must promote
        #     BEFORE prefill admits, with output identical to the
        #     uncached engine.
        from kubeflow_tpu.serve.engine import SamplingParams

        eng_r, _cfg_r = engines["radix"]
        eng_o, _cfg_o = engines["off"]
        sp = SamplingParams(max_new_tokens=MAX_NEW, temperature=0.0)
        convo = [5, 1, 5, 2, 5, 3, 5, 4] * 8           # 64 tokens
        t1 = eng_r.submit(list(convo), sp)
        t1.done.wait(30.0)
        demoted0 = eng_r.kv_tier_stats()["pages_demoted"]
        # Wait until EVERY cached page (the conversation's included —
        # demotion walks the LRU, oldest content first) sits on host.
        deadline = time.monotonic() + 20.0
        while eng_r.kv_pages_cached() > 0 or eng_r.kv_pages_host() == 0:
            time.sleep(0.02)
            if time.monotonic() > deadline:
                return fail("idle conversation never demoted to host")
        turn2 = list(convo) + list(t1.output_tokens) + [9, 9, 2, 2]
        promoted0 = eng_r.kv_tier_stats()["pages_promoted"]
        t2 = eng_r.submit(list(turn2), sp)
        t2.done.wait(30.0)
        o1 = eng_o.submit(list(convo), sp)
        o1.done.wait(30.0)
        o2 = eng_o.submit(list(turn2), sp)
        o2.done.wait(30.0)
        if list(t2.output_tokens) != list(o2.output_tokens):
            return fail("promotion changed greedy output")
        tier = eng_r.kv_tier_stats()
        if tier["pages_demoted"] <= demoted0 - 1 \
                or tier["pages_promoted"] <= promoted0:
            return fail(f"tier lifecycle never cycled: {tier}")
        result["tier_lifecycle"] = {
            "pages_demoted": tier["pages_demoted"],
            "pages_promoted": tier["pages_promoted"],
            "cow_copies": tier["cow_copies"],
            "prefix_hits": tier["prefix_hits"],
        }

        # 2) The win: radix vs flat on multi-turn, best of two measured
        #    segments per side (flat gets the same warmup treatment).
        # The A/B runs the regime the tier exists for: MORE idle
        # conversations than the device pool can cache (6 overlapping
        # sessions x up to 12 pages vs a 48-page pool). The flat
        # baseline's cached conversations get LRU-evicted by competing
        # sessions during think gaps and re-arrivals RECOMPUTE; the
        # radix engine demotes them to host RAM and promotes on the
        # radix hit before prefill admits.
        sc_ab = multi_turn_scenario(2 * args.requests, think_s=0.25,
                                    seed=11, rate_rps=1.5)
        eng_f, cfg_f = engines["flat"]
        run_once(eng_f, cfg_f, sc_ab)          # flat warmup
        best = {}
        ab_reports = {}
        for kind in ("radix", "flat"):
            eng, cfg = engines[kind]
            run_once(eng, cfg, sc_ab)          # settle segment
            ttfts, toks = [], []
            for _ in range(3):                 # best-of-3: one straggler
                rep, tok_s, run = run_once(eng, cfg, sc_ab)   # (GC, a
                # promotion racing a burst) must not decide the gate
                if not all(o.ok for o in run.outcomes):
                    return fail(f"A/B run had failures on {kind}")
                ttfts.append(rep["ttft_ms"].get("p95", 0.0))
                toks.append(tok_s)
                ab_reports[kind] = rep
            best[kind] = {"ttft_p95_ms": min(ttfts),
                          "prefill_tok_s": max(toks)}
        result["multi_turn_ab"] = best
        if not best["radix"]["prefill_tok_s"] > best["flat"]["prefill_tok_s"]:
            return fail("radix prefill tok/s did not beat flat: "
                        f"{best}")
        if not best["radix"]["ttft_p95_ms"] < best["flat"]["ttft_p95_ms"]:
            return fail(f"radix ttft p95 did not beat flat: {best}")

        # 3) Overlap sweep 0.5–0.95 on shared_prefix: radix must never
        #    regress the flat hash's bread-and-butter shape, and must
        #    reuse at least as many tokens.
        sweep_rows = []
        for overlap in (0.5, 0.75, 0.95):
            sc = shared_prefix_scenario(args.requests, overlap)
            row = {"overlap": overlap}
            for kind in ("radix", "flat"):
                eng, cfg = engines[kind]
                reused0 = eng.kv_tier_stats().get("tokens_matched", 0) \
                    if kind == "radix" else \
                    eng._allocator.stats["prefix_hits"]
                ttfts, toks = [], []
                for _ in range(2):             # best-of-2 vs stragglers
                    rep, tok_s, run = run_once(eng, cfg, sc)
                    ttfts.append(rep["ttft_ms"].get("p95", 0.0))
                    toks.append(tok_s)
                row[kind] = {
                    "ttft_p95_ms": min(ttfts),
                    "prefill_tok_s": round(max(toks), 1),
                    "req_s": rep["req_s"],
                }
                if kind == "radix":
                    row["radix_tokens_reused"] = \
                        eng.kv_tier_stats()["tokens_matched"] - reused0
                ov = rep.get("prefix_overlap_declared")
                if ov != overlap:
                    return fail(f"overlap knob lost: {ov} != {overlap}")
            # Noise-banded no-regression: CPU TTFTs at this size jitter;
            # 60% band + 5 ms floor (the gate.py discipline).
            r, f = row["radix"], row["flat"]
            if r["ttft_p95_ms"] > f["ttft_p95_ms"] * 1.6 \
                    and r["ttft_p95_ms"] - f["ttft_p95_ms"] > 5.0:
                return fail(f"radix regressed shared_prefix: {row}")
            sweep_rows.append(row)
        result["overlap_sweep"] = sweep_rows

        # 4) Seeded migration wedge: a sleep in the migration thread's
        #    wire encode (holds the tier lock → admission matching
        #    starves) must be FLAGGED with the attribution diff.
        import kubeflow_tpu.serve.kvtier as kvtier

        eng_r, cfg_r = engines["radix"]
        baseline_rep = ab_reports["radix"]
        real_wire = kvtier.pages_to_wire

        def wedged_wire(k, v):
            time.sleep(0.12)
            return real_wire(k, v)

        kvtier.pages_to_wire = wedged_wire
        try:
            wedge_rep, _, _ = run_once(eng_r, cfg_r, sc_ab)
        finally:
            kvtier.pages_to_wire = real_wire
        problems = compare_scenario(baseline_rep, wedge_rep,
                                    band_pct=40.0, ttft_floor_ms=5.0)
        if not problems:
            return fail("seeded migration wedge was NOT flagged "
                        f"(baseline ttft p95 "
                        f"{baseline_rep['ttft_ms'].get('p95')} vs wedged "
                        f"{wedge_rep['ttft_ms'].get('p95')})")
        if "kv_tier" not in wedge_rep.get("engine", {}):
            return fail("wedge attribution lacks the kv_tier block")
        result["migration_wedge"] = {
            "flagged": problems,
            "kv_tier": wedge_rep["engine"]["kv_tier"],
        }

        # 5) Hygiene: tier series parse off the real exposition; pools
        #    drain to zero referenced pages; per-owner report is EMPTY.
        for kind, (eng, _cfg) in engines.items():
            text = serving_metrics_registry([(kind, eng)]).render()
            names = {n for n, _, _ in parse_exposition(text)}
            missing = [s for s in TIER_SERIES if s not in names]
            if missing:
                return fail(f"tier series missing from exposition: "
                            f"{missing}")
            drain(eng)
            report = eng._allocator.leak_report_by_owner()
            if report:
                return fail(f"per-owner page leaks on {kind}: {report}")
            eng._allocator.assert_quiescent()
        result["hygiene"] = "ok"

        bench = {
            "bench": "serve_r03_tiered_kv",
            "model": "tiny-cpu-smoke",
            "multi_turn_ab": best,
            "overlap_sweep": sweep_rows,
            "tier_lifecycle": result["tier_lifecycle"],
            "migration_wedge_flagged": bool(problems),
        }
        with open(os.path.join(REPO, "BENCH_SERVE_r03.json"), "w") as f:
            json.dump(bench, f, indent=2)
            f.write("\n")
        result["prefix_cache_smoke"] = "ok"
        print(json.dumps(result, indent=2))
        return 0
    finally:
        for eng, _cfg in engines.values():
            eng.stop()


if __name__ == "__main__":
    sys.exit(main())
