"""FLOP/bubble cost model for the two pipeline schedules (round-2 weak #6:
the 1F1B-vs-GPipe trade was implemented but never quantified — a
single-host box cannot measure a real multi-stage wall-clock, so this is
the analytical model grounded in the measured on-chip step decomposition).

Per microbatch per stage:
- checkpointed GPipe: 2 forwards + 1 backward (checkpoint recomputes the
  stage forward in its backward), bubble fraction (n-1)/(m+n-1) with m
  memory-capped at 2n (activation stash grows with m) → bubble → 1/3
  from below as n grows.
- 1F1B (this repo's m-independent ring): 3 forwards + 1 backward (the
  forward lane refills the 2n-1 ring AND the vjp's primal re-runs the
  stage), bubble fraction (n-1)/(m+n-1) with NO memory cap on m.

With f = forward cost and b = backward-proper cost (measured on-chip:
fwd 117 ms of a 391 ms fwd+bwd → b ≈ 2.3 f), per-microbatch work is
w_gpipe = 2f+b, w_1f1b = 3f+b, and total step time ∝ w · (m+n-1)/m.
1F1B wins exactly when its extra forward costs less than the bubble it
removes by raising m past GPipe's 2n cap.

Run: python scripts/pipeline_schedule_model.py   (prints the crossover
table; one JSON line at the end).
"""

import json


def step_cost(w: float, m: int, n: int) -> float:
    """Relative wall per step: per-microbatch work × occupied ticks / m."""
    return w * (m + n - 1) / m


def crossover(n: int, f: float = 1.0, b: float = 2.3,
              gpipe_m_cap_factor: int = 2):
    import math

    w_g = 2 * f + b
    w_1 = 3 * f + b
    m_g = gpipe_m_cap_factor * n           # GPipe's activation-stash cap
    g = step_cost(w_g, m_g, n)
    rows = [{"m": m, "oneFoneB_rel": round(step_cost(w_1, m, n) / g, 4)}
            for m in (m_g, 2 * m_g, 4 * m_g, 8 * m_g, 16 * m_g)]
    # Closed form: w_1·(m+n−1)/m < g  ⇔  m > (n−1)·w_1 / (g − w_1).
    wins_at = (math.floor((n - 1) * w_1 / (g - w_1)) + 1
               if g > w_1 else None)
    return {"stages": n, "gpipe_m": m_g, "gpipe_cost": round(g, 3),
            "rows": rows, "asymptote_rel": round(w_1 / g, 4),
            "wins_at_m": wins_at}


def main():
    out = []
    for n in (4, 8, 16):
        r = crossover(n)
        out.append(r)
        print(f"n={n:3d} stages: GPipe (m={r['gpipe_m']}) = {r['gpipe_cost']}"
              f" | 1F1B rel cost by m: "
              + ", ".join(f"m={row['m']}:{row['oneFoneB_rel']}"
                          for row in r["rows"])
              + f"  -> 1F1B wins from m={r['wins_at_m']} "
              f"(asymptote {r['asymptote_rel']})")
    print(json.dumps({"metric": "pipeline_schedule_crossover",
                      "fwd_bwd_ratio": 2.3, "configs": out}))


if __name__ == "__main__":
    main()
