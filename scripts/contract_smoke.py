#!/usr/bin/env python
"""Name-contract audit stage for scripts/smoke.sh (ISSUE 10).

Cross-checks the STATIC contract table (``kftpu lint --contracts-json``:
metric series produced/consumed, ``X-Kftpu-*`` headers set/read — the
X7xx rules' extraction) against what a real serve run ACTUALLY
exchanges, recorded by the ``KFTPU_SANITIZE=contract`` runtime auditor:

1. The manifest round-trips: the ``--contracts-json`` CLI output parses
   and equals the in-process extraction over the same scan set.
2. Traffic runs through a real router → model-server → engine stack with
   QoS + deadline headers, the autoscaler's ``default_probe`` scrapes a
   replica, and the router's own /metrics is scraped — covering every
   exchange class the serving path has.
3. ``contract_report()`` must show ZERO undeclared exchanges against the
   static table (``contract_diff``): every series actually rendered or
   matched, and every header actually read or stamped, was visible to
   the AST extractor. A dynamically-built name the static table missed
   fails here — the gap the runtime half exists to close.

Prints one JSON line; exit 0 iff ``"contract_smoke": "ok"``.

    JAX_PLATFORMS=cpu python scripts/contract_smoke.py [--requests 6]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# The auditor must be live before kubeflow_tpu (and its locks/engines)
# import — same contract as the other sanitizer modes.
os.environ["KFTPU_SANITIZE"] = "contract"

SCAN = ["kubeflow_tpu", "scripts", "bench.py", "bench_serve.py"]


def static_manifest() -> tuple[dict, list[str]]:
    """The contract table, via the CLI (proving the --contracts-json
    surface) AND in-process (proving the round-trip)."""
    problems: list[str] = []
    proc = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.analysis",
         "--contracts-json", *SCAN],
        capture_output=True, text=True, cwd=REPO, timeout=600)
    if proc.returncode != 0:
        return {}, [f"--contracts-json failed: {proc.stderr.strip()}"]
    try:
        cli_doc = json.loads(proc.stdout)
    except ValueError as exc:
        return {}, [f"--contracts-json output is not JSON: {exc}"]

    from kubeflow_tpu.analysis import build_program
    from kubeflow_tpu.analysis.rules_contracts import contract_manifest

    local_doc = json.loads(json.dumps(
        contract_manifest(build_program(
            [os.path.join(REPO, p) for p in SCAN], root=REPO))))
    if cli_doc != local_doc:
        problems.append("--contracts-json does not round-trip: CLI and "
                        "in-process manifests differ")
    return cli_doc, problems


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=6)
    args = ap.parse_args()
    os.chdir(REPO)

    verdict: dict = {"contract_smoke": "ok"}
    doc, problems = static_manifest()
    verdict["static_series_produced"] = len(
        doc.get("series", {}).get("produced", {}))
    if problems:
        verdict.update(contract_smoke="FAIL", problems=problems)
        print(json.dumps(verdict))
        return 1

    import jax

    from kubeflow_tpu.core.headers import (
        DEADLINE_HEADER, QOS_HEADER, TRACE_HEADER,
    )
    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.obs.registry import parse_exposition
    from kubeflow_tpu.runtime import sanitize
    from kubeflow_tpu.serve.engine import LLMEngine
    from kubeflow_tpu.serve.isvc_controller import default_probe
    from kubeflow_tpu.serve.router import Router
    from kubeflow_tpu.serve.server import ModelServer

    if sanitize.contract_auditor() is None:
        problems.append("contract auditor not installed at import")

    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    engine = LLMEngine(
        cfg,
        BatchingSpec(max_batch_size=2, max_seq_len=96, prefill_buckets=[32],
                     paged=True, page_size=16, decode_steps=4),
        params=params)
    server = ModelServer("contract-smoke", engine, port=0)
    server.start()
    router = Router(queue_timeout=5.0, upstream_timeout=60.0)
    router.set_backends({"latest": [server.url]})
    router.start()

    def one_request(i: int) -> None:
        body = json.dumps({"prompt": f"contract {i}", "max_tokens": 8,
                           "timeout": 30}).encode()
        req = urllib.request.Request(
            router.url + "/v1/completions", data=body,
            headers={"Content-Type": "application/json",
                     QOS_HEADER: "interactive" if i % 2 else "batch",
                     DEADLINE_HEADER: "30000"})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                r.read()
        except Exception as exc:  # noqa: BLE001 — counted, not fatal
            problems.append(f"request {i}: {exc}")

    try:
        threads = [threading.Thread(target=one_request, args=(i,))
                   for i in range(args.requests)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=90)

        # The autoscaler's scrape (records CONSUMED series) and the
        # router's own exposition surface (dynamic kftpu_router_* family).
        probe = default_probe(server.url, timeout=5.0)
        if probe is None or not probe.get("ready"):
            problems.append("default_probe found the replica not ready")
        with urllib.request.urlopen(
                router.url + "/-/router/metrics", timeout=10) as r:
            parse_exposition(r.read().decode())

        report = sanitize.contract_report()
        verdict["series_produced"] = len(report.get("series_produced", ()))
        verdict["series_consumed"] = len(report.get("series_consumed", ()))
        verdict["headers_set"] = report.get("headers_set", [])
        verdict["headers_read"] = report.get("headers_read", [])
        if not report.get("series_produced"):
            problems.append("auditor recorded no produced series")
        if not report.get("series_consumed"):
            problems.append("auditor recorded no consumed series "
                            "(default_probe matched nothing)")
        for h in (DEADLINE_HEADER, QOS_HEADER, TRACE_HEADER):
            if h not in report.get("headers_set", ()):
                problems.append(f"auditor never saw header {h} set")
        diff = sanitize.contract_diff(report, doc)
        verdict["undeclared_series"] = diff["undeclared_series"]
        verdict["undeclared_headers"] = diff["undeclared_headers"]
        if diff["undeclared_series"] or diff["undeclared_headers"]:
            problems.append(
                "runtime exchanged names the static contract table does "
                f"not declare: {diff}")
    finally:
        router.stop()
        server.stop()

    if problems:
        verdict["contract_smoke"] = "FAIL"
        verdict["problems"] = problems
    print(json.dumps(verdict))
    return 0 if not problems else 1


if __name__ == "__main__":
    raise SystemExit(main())
