"""AOT-validate the flagship recipes without hardware (VERDICT round-2
next #5 for Llama-3-8B; round-4 next #2 for Mixtral-8x7B; SURVEY.md §6
"Llama-3-8B-class pretrain, v5p-64" / BASELINE.json configs[2]
"Mixtral 8x7B MoE expert-parallel across multi-slice ICI/DCN").

Uses libtpu's topology-only AOT path (`jax.experimental.topologies`) to
lower + compile — never execute — the REAL train step (fwd+bwd+Adam,
Pallas flash attention, dots_no_batch remat) and the serving decode step
on virtual v5p/v5e meshes, then reads the compiled executable's
per-chip memory analysis against the chip HBM budget (v5p: 95 GB,
v5e: 16 GB). Multi-slice topologies come from the same path
(``num_slices=N``): devices carry distinct ``slice_index`` so GSPMD
plans DCN collectives for the ``dcn`` mesh axis, exactly as on real
multislice pods.

Run: python scripts/aot_validate_8b.py   (one JSON line per config)
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mesh_on(topology: str, axes: dict, *, num_slices: int = 1,
             topo_kwargs: dict = None):
    from jax.experimental import topologies

    from kubeflow_tpu.runtime.mesh import build_mesh

    kw = dict(topo_kwargs or {})
    if num_slices > 1:
        kw["num_slices"] = num_slices
    topo = topologies.get_topology_desc(topology, "tpu", **kw)
    return build_mesh(axes, topo.devices)


def train_step_analysis(topology: str, axes: dict, *, model="llama3-8b",
                        per_chip_batch=1, pp_layers=None, num_slices=1,
                        seq_len=None):
    """Compile `model`'s train step for `axes` on `topology`; return per-chip
    memory totals in GB from the compiled executable."""
    import jax

    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.train.data import DataConfig
    from kubeflow_tpu.train.optim import OptimizerConfig
    from kubeflow_tpu.train.step import make_state_init, setup_train

    mesh = _mesh_on(topology, axes, num_slices=num_slices)
    over = {"remat_policy": "dots_no_batch"}
    if pp_layers:
        over["pipeline_schedule"] = "1f1b"
    if seq_len:
        over["max_seq_len"] = seq_len
    cfg = preset(model, **over)
    task = setup_train(cfg, OptimizerConfig(total_steps=10), mesh,
                       attn_impl="pallas", init_state=False)
    state_sds = jax.eval_shape(make_state_init(cfg, task.optimizer))
    # Global batch: per_chip_batch per data shard; pipeline runs 2*pp
    # microbatches through the stages.
    batch_shards = 1
    for a in ("dcn", "data", "fsdp"):
        batch_shards *= axes.get(a, 1)
    pp = axes.get("pipeline", 1)
    global_batch = per_chip_batch * batch_shards * (2 * pp if pp > 1 else 1)
    batch_sds = jax.ShapeDtypeStruct((global_batch, cfg.max_seq_len + 1),
                                     jax.numpy.int32)
    compiled = task.step_fn.lower(state_sds, batch_sds).compile()
    m = compiled.memory_analysis()
    gb = 1 << 30
    return {
        "params_b": round(cfg.num_params() / 1e9, 2),
        "argument_gb": round(m.argument_size_in_bytes / gb, 2),
        "output_gb": round(m.output_size_in_bytes / gb, 2),
        "temp_gb": round(m.temp_size_in_bytes / gb, 2),
        "total_gb": round((m.argument_size_in_bytes + m.temp_size_in_bytes)
                          / gb, 2),
        "global_batch": global_batch,
    }


def serve_decode_analysis(topology: str, tp: int, *, model="llama3-8b",
                          slots=16, max_len=2048, quantize=None,
                          topo_kwargs=None):
    """Compile `model`'s serving decode step (K steps + sampling on device)
    TP-sharded over `tp` chips; per-chip memory vs the v5e 16 GB budget.
    ``quantize="int8"``: weight-only int8 (ops/quantization.py) — the AOT
    density proof that the halved params fit smaller topologies."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import (
        decoder_param_specs, init_decoder_params)
    from kubeflow_tpu.parallel.sharding import shard_params
    from kubeflow_tpu.serve.engine import _decode_multi
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = _mesh_on(topology, {"model": tp}, topo_kwargs=topo_kwargs)
    cfg = preset(model, dtype="bfloat16", param_dtype="bfloat16")
    if cfg.is_moe:
        # The engine's measured decode default: dense MoE (per-phase A/B in
        # serve/engine.py — zero-drop dispatch tied, dense is simpler).
        import dataclasses as _dc
        cfg = _dc.replace(cfg, moe_impl="dense")

    def _abstract_params():
        p = init_decoder_params(jax.random.PRNGKey(0), cfg)
        if quantize == "int8":
            from kubeflow_tpu.ops.quantization import quantize_params_int8

            p = quantize_params_int8(p, cfg)
        return p

    params_sds = jax.eval_shape(_abstract_params)
    psh = shard_params(params_sds, decoder_param_specs(cfg), mesh)
    params_sds = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        params_sds, psh)
    kv_sh = NamedSharding(mesh, PartitionSpec(None, None, None, "model",
                                              None))
    cache_sds = {
        n: jax.ShapeDtypeStruct(
            (cfg.n_layers, slots, max_len, cfg.n_kv_heads, cfg.head_dim),
            jnp.bfloat16, sharding=kv_sh) for n in ("k", "v")}
    i32 = lambda: jax.ShapeDtypeStruct((slots,), jnp.int32)
    f32 = lambda: jax.ShapeDtypeStruct((slots,), jnp.float32)
    b1 = jax.ShapeDtypeStruct((slots,), jnp.bool_)
    keys = jax.ShapeDtypeStruct((2,), jnp.uint32)
    fn = jax.jit(
        lambda p, c, t, l, lv, tp_, tk, tpp, st, bd, k:
        _decode_multi(p, c, t, l, lv, tp_, tk, tpp, st, bd, k, cfg, 16,
                      sample_mode="full"),
        donate_argnums=(1,))
    compiled = fn.lower(params_sds, cache_sds, i32(), i32(), b1, f32(),
                        i32(), f32(), i32(), i32(), keys).compile()
    m = compiled.memory_analysis()
    gb = 1 << 30
    return {
        "params_b": round(cfg.num_params() / 1e9, 2),
        "argument_gb": round(m.argument_size_in_bytes / gb, 2),
        "temp_gb": round(m.temp_size_in_bytes / gb, 2),
        "total_gb": round((m.argument_size_in_bytes + m.temp_size_in_bytes)
                          / gb, 2),
    }


CONFIGS = [
    ("train", "v5p:2x2x4", {"fsdp": 8, "model": 2}, {"per_chip_batch": 1}),
    ("train", "v5p:4x4x4", {"fsdp": 16, "model": 4}, {"per_chip_batch": 1}),
    ("train", "v5p:4x4x4", {"pipeline": 4, "fsdp": 8, "model": 2},
     {"per_chip_batch": 1, "pp_layers": True}),
    # Mixtral-8x7B north star (BASELINE.json configs[2]): expert-parallel
    # training at v5p-64, the same across a 2-slice DCN multislice, and
    # bf16 serving on v5e-8 (below, after the train table).
    ("train", "v5p:4x4x4", {"expert": 8, "fsdp": 8},
     {"model": "mixtral-8x7b", "per_chip_batch": 1}),
    ("train", "v5p:2x4x4", {"dcn": 2, "expert": 8, "fsdp": 4},
     {"model": "mixtral-8x7b", "per_chip_batch": 1, "num_slices": 2}),
]


def main():
    budget = {"v5p": 95.0, "v5e": 16.0}
    for kind, topo, axes, kw in CONFIGS:
        out = train_step_analysis(topo, axes, **kw)
        out.update(kind=kind, topology=topo, axes=axes,
                   model=kw.get("model", "llama3-8b"),
                   num_slices=kw.get("num_slices", 1),
                   budget_gb=budget["v5p"],
                   fits=out["total_gb"] < budget["v5p"])
        print(json.dumps(out), flush=True)
    for model, slots, max_len in (("llama3-8b", 16, 2048),
                                  ("mixtral-8x7b", 16, 2048)):
        out = serve_decode_analysis("v5e:2x4x1", 8, model=model, slots=slots,
                                    max_len=max_len)
        out.update(kind="serve_decode", topology="v5e-8", axes={"model": 8},
                   model=model, budget_gb=budget["v5e"],
                   fits=out["total_gb"] < budget["v5e"])
        print(json.dumps(out), flush=True)
    # int8 density points (VERDICT r4 #3): weight-only int8 on smaller
    # topologies than bf16 can reach.
    for topo, tp, kw in (
            ("v5e:1x1x1", 1,
             {"topo_kwargs": {"chips_per_host_bounds": [1, 1, 1]},
              "slots": 8}),
            ("v5e:2x2x1", 4, {})):
        out = serve_decode_analysis(topo, tp, model="llama3-8b",
                                    quantize="int8", **kw)
        out.update(kind="serve_decode_int8", topology=topo,
                   axes={"model": tp}, model="llama3-8b",
                   budget_gb=budget["v5e"],
                   fits=out["total_gb"] < budget["v5e"])
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
