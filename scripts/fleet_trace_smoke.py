#!/usr/bin/env python
"""Fleet observability gate (scripts/smoke.sh): cross-host trace
stitching, metrics history + SLO burn rate, flight recorder (ISSUE 18).

A 3-replica disaggregated fleet (1 prefill + 2 decode) behind the
hardened router takes a loadgen scenario while one decode replica is
SIGKILLed mid-session. The gate then asserts the fleet plane saw the
whole story:

- **one stitched trace per request**: the collector drains every
  replica's ``/debug/spans/export`` plus the router's, joins by trace
  id, and a single causal tree covers router → prefill → KV handoff →
  decode with per-hop wire-time attribution, every hop's skew-corrected
  ordering monotone;
- **the SIGKILL failover is a first-class hop**: a handoff placed on
  the dead decode replica lands on the retry alternate and stitches as
  kind ``failover`` — attributed, timed, in the same tree;
- **burn rate**: the metrics-history scrape loop ran against the real
  ``/metrics`` expositions during the run; a seeded SLO breach (targets
  under the observed TTFT) raises the per-class alert series while a
  clean evaluation over the SAME history does not;
- **flight recorder**: stopping a replica's engine leaves a dump
  (history window + stitched traces + SLO state) that ``kftpu trace``
  re-loads;
- **hygiene**: ``open_spans() == 0`` after settling, zero leaked KV
  pages on every engine (including the killed one), and every
  ``kftpu_fleet_*``/``kftpu_obs_*`` series parsing off the rendered
  fleet registry (the consumer half of the X7xx contract).

Prints one JSON object; ``{"fleet_trace_smoke": "ok"}`` is the gate
line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# ISSUE 20: the whole gate runs under the thread sanitizer — every
# threading.Thread created by app code is stamped with its creation
# site/owner, the component stop() paths assert per-owner quiescence,
# and step 10 proves the PROCESS ends quiescent even after the SIGKILL
# chaos. Set BEFORE any kubeflow_tpu import so maybe_install sees it.
_san = os.environ.get("KFTPU_SANITIZE", "")
if "threads" not in _san.split(","):
    os.environ["KFTPU_SANITIZE"] = ",".join(
        x for x in (_san, "threads") if x)

#: Fleet-plane series this gate consumes off the rendered fleet
#: registry — the consumer half of the kftpu_fleet_*/kftpu_obs_*
#: metric contract (X7xx).
FLEET_OBS_SERIES = (
    "kftpu_fleet_spans_total",
    "kftpu_fleet_spans_duplicate_total",
    "kftpu_fleet_drain_errors_total",
    "kftpu_fleet_traces_stitched",
    "kftpu_fleet_clock_skew_ms",
    "kftpu_fleet_hops_total",
    "kftpu_fleet_hop_wire_ms",
    "kftpu_obs_history_points",
    "kftpu_obs_history_scrapes_total",
    "kftpu_obs_history_scrape_errors_total",
    "kftpu_obs_slo_burn_rate",
    "kftpu_obs_slo_alert",
    "kftpu_obs_flight_dumps_total",
)

MAX_NEW = 8


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--rate", type=float, default=4.0)
    args = ap.parse_args()

    import jax

    from kubeflow_tpu.core.headers import (
        DECODE_ALTS_HEADER, DECODE_BACKEND_HEADER,
    )
    from kubeflow_tpu.core.serving import QOS_DEFAULT, BatchingSpec
    from kubeflow_tpu.loadgen import ServerTarget, build_report, run_scenario
    from kubeflow_tpu.loadgen.scenario import (
        Arrival, LengthDist, Scenario,
    )
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.obs import fleet
    from kubeflow_tpu.obs.registry import parse_exposition
    from kubeflow_tpu.obs.trace import format_dump, get_tracer, load_dump
    from kubeflow_tpu.serve.engine import LLMEngine
    from kubeflow_tpu.serve.faults import kill_model_server
    from kubeflow_tpu.serve.router import Router
    from kubeflow_tpu.serve.server import ModelServer

    result: dict = {}

    def fail(msg: str) -> int:
        result["fleet_trace_smoke"] = msg
        print(json.dumps(result, indent=2))
        return 1

    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    tracer = get_tracer()
    tracer.reset()

    def mk(name: str, role: str) -> ModelServer:
        eng = LLMEngine(
            cfg,
            BatchingSpec(max_batch_size=2, max_seq_len=96,
                         prefill_buckets=[32], paged=True, page_size=16,
                         chunked_prefill_tokens=16, decode_steps=4,
                         role=role),
            params=params)
        srv = ModelServer(name, eng, port=0)
        srv.start()
        return srv

    pre = mk("pre", "prefill")
    dec1 = mk("dec1", "decode")
    dec2 = mk("dec2", "decode")
    servers = [pre, dec1, dec2]
    router = Router(queue_timeout=5.0, eject_threshold=2, eject_period=0.5,
                    max_retries=2, upstream_timeout=30.0)
    router.set_pools({"prefill": [pre.url], "decode": [dec1.url, dec2.url]})
    router.start()

    # The fleet plane: collector sources (router FIRST so shared-ring
    # root spans attribute to it), history scrape loop over every
    # replica's real /metrics, flight recorder installed module-wide so
    # engine stops snapshot on their own.
    collector = fleet.FleetTraceCollector()
    collector.add_source("router",
                         router.url + fleet.ROUTER_SPANS_EXPORT_PATH)
    for srv in servers:
        collector.add_source(f"server:{srv.name}",
                             srv.url + fleet.SPANS_EXPORT_PATH)
    history = fleet.MetricsHistory(retention_s=120.0, interval_s=0.25)
    for srv in servers:
        history.add_target(srv.name, srv.url + "/metrics")
    history.start()
    flight_dir = tempfile.mkdtemp(prefix="fleet-flight-")
    recorder = fleet.FlightRecorder(flight_dir, window_s=120.0,
                                    history=history, collector=collector)
    prev_recorder = fleet.install_flight_recorder(recorder)

    def completion(url, prompt, headers=()):
        body = json.dumps({"prompt": prompt, "max_tokens": MAX_NEW,
                           "timeout": 20}).encode()
        req = urllib.request.Request(
            url + "/v1/completions", data=body,
            headers={"Content-Type": "application/json", **dict(headers)})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())["choices"][0]["text"]

    try:
        # Warm the disaggregated path (compiles stay out of the run).
        completion(router.url, "fleet observability warmup")

        # 1) Loadgen scenario through the router, SIGKILL dec1 when a
        #    third of the schedule has elapsed.
        sc = Scenario(
            name="fleet_uniform", num_requests=args.requests,
            arrival=Arrival(process="poisson", rate_rps=args.rate),
            prompt_len=LengthDist(kind="fixed", value=24),
            output_len=LengthDist(kind="fixed", value=MAX_NEW),
            slo_ttft_ms=5000.0, request_timeout_s=30.0)
        kill_delay = (args.requests / args.rate) / 3.0
        killer = threading.Timer(kill_delay,
                                 lambda: kill_model_server(dec1))
        killer.start()
        run = run_scenario(ServerTarget(router.url), sc,
                           vocab_size=cfg.vocab_size, max_prompt_len=30,
                           tracer=tracer)
        killer.join(timeout=10.0)
        ok_outs = [o for o in run.outcomes if o.ok]
        result["requests"] = {"offered": len(run.outcomes),
                              "completed": len(ok_outs)}
        if not ok_outs:
            return fail("no request survived the fleet run")

        # 2) Deterministic failover seeding: a handoff PLACED on the
        #    dead decode replica with the survivor as alternate — the
        #    exact pick-then-die race, minus the race.
        completion(pre.url, "failover seed", headers=[
            (DECODE_BACKEND_HEADER, dec1.url),
            (DECODE_ALTS_HEADER, dec2.url)])

        # 3) Drain + stitch. dec1 is dead: its drain must fail and be
        #    counted, never fatal (the missing-source tolerance).
        collector.drain()
        if collector.stats["drain_errors"] < 1:
            return fail("dead replica's drain did not error")
        for name, st in collector.sources().items():
            if name != "server:dec1" and st["errors"]:
                return fail(f"live source {name} failed to drain: {st}")
            if abs(st["offset_s"]) > 1.0:
                return fail(f"implausible clock offset for {name}: {st}")

        # 4) ONE stitched trace covers router → prefill → handoff →
        #    decode; every hop attributed and monotone.
        full = None
        for out in ok_outs:
            tr = collector.trace(out.trace_id) if out.trace_id else None
            if not tr:
                continue
            kinds = {h["kind"] for h in tr["hops"]}
            if ("route" in kinds or "failover" in kinds) and \
                    ("handoff" in kinds or "failover" in kinds) and \
                    len(tr["hops"]) >= 2:
                full = tr
                break
        if full is None:
            return fail("no stitched trace covers route + handoff")
        procs = {h["from"] for h in full["hops"]} \
            | {h["to"] for h in full["hops"]}
        if "router" not in procs or "server:pre" not in procs:
            return fail(f"hop attribution incomplete: {sorted(procs)}")
        if not procs & {"server:dec1", "server:dec2"}:
            return fail(f"no decode replica in the tree: {sorted(procs)}")
        if any("?" in (h["from"], h["to"]) for h in full["hops"]):
            return fail(f"unattributed hop endpoints: {full['hops']}")
        bad = [h for h in collector.hops() if not h["monotone"]]
        if bad:
            return fail(f"non-monotone hops after skew correction: {bad}")
        if any(h["wire_ms"] is None for h in collector.hops()):
            return fail("hop without wire-time attribution")
        result["stitched"] = {
            "trace_id": full["trace_id"],
            "hops": [{k: h[k] for k in ("kind", "from", "to", "wire_ms")}
                     for h in full["hops"]]}

        # 5) The SIGKILL failover hop: placed on the dead replica,
        #    landed on the survivor, stitched as kind "failover" in ONE
        #    tree together with its route + handoff context.
        failover_traces = [t for t in collector.traces(limit=256)
                           if any(h["kind"] == "failover"
                                  for h in t["hops"])]
        if not failover_traces:
            return fail("SIGKILL failover never stitched as a hop")
        ft = failover_traces[0]
        fh = [h for h in ft["hops"] if h["kind"] == "failover"]
        if not any(h["to"] == "server:dec2" for h in fh):
            return fail(f"failover hop missed the survivor: {fh}")
        if not all(h["monotone"] and h["wire_ms"] is not None for h in fh):
            return fail(f"failover hop unattributed: {fh}")
        result["failover"] = {"trace_id": ft["trace_id"],
                              "hops": len(fh),
                              "wire_ms": fh[0]["wire_ms"]}
        # The stitched tree renders (the kftpu trace view).
        if "engine.handoff" not in collector.format_tree(ft["trace_id"]):
            return fail("stitched tree render lost the handoff span")

        # 6) Burn rate over the run's REAL scraped history: a seeded
        #    breach (target far under the observed TTFT) alerts; a clean
        #    evaluation over the same rings does not.
        history.stop()
        if history.points_total() <= 0:
            return fail("metrics history scraped no points")
        result["history_points"] = history.points_total()
        breach = fleet.SloBurnRateMonitor(
            history, {QOS_DEFAULT: {"ttft_p95_ms": 1e-3}},
            fast_window_s=30.0, slow_window_s=120.0)
        clean = fleet.SloBurnRateMonitor(
            history, {QOS_DEFAULT: {"ttft_p95_ms": 1e9}},
            fast_window_s=30.0, slow_window_s=120.0)
        if breach.evaluate() != breach.state():
            return fail("monitor state diverged from evaluation")
        if breach.alerting() != [QOS_DEFAULT]:
            return fail(f"seeded SLO breach did not alert: "
                        f"{breach.state()}")
        if clean.evaluate()[QOS_DEFAULT]["alert"]:
            return fail(f"clean run raised a burn-rate alert: "
                        f"{clean.state()}")
        reg = fleet.fleet_obs_registry(collector=collector,
                                       history=history, monitor=breach,
                                       recorder=recorder)
        samples = parse_exposition(reg.render())
        by_name = {n for n, _, _ in samples}
        missing = [s for s in FLEET_OBS_SERIES if s not in by_name]
        if missing:
            return fail(f"fleet series missing from exposition: {missing}")
        alerts = {lab.get("class"): v for n, lab, v in samples
                  if n == "kftpu_obs_slo_alert"}
        if alerts.get(QOS_DEFAULT) != 1.0:
            return fail(f"alert series not raised: {alerts}")
        result["burn_rate"] = {
            cls: round(st["fast"], 2)
            for cls, st in breach.state().items() if st["fast"]}

        # 7) Loadgen attribution report with the fleet-hop block.
        rep = build_report(run, tracer=tracer, collector=collector)
        hops_rep = rep.get("fleet_hops") or {}
        if hops_rep.get("trace_coverage", 0) < 1:
            return fail(f"report joined no fleet hops: {hops_rep}")
        if hops_rep.get("non_monotone_hops"):
            return fail(f"report saw non-monotone hops: {hops_rep}")
        result["fleet_hops"] = hops_rep

        # 8) Flight recorder: stopping a replica's engine snapshots on
        #    its own (the installed-recorder hook); the dump re-loads
        #    through the kftpu trace loader.
        pre.stop()
        if not recorder.dumps():
            return fail("engine stop left no flight-recorder dump")
        doc = load_dump(recorder.dumps()[-1])
        rendered = format_dump(doc)
        if not rendered.startswith("flight recorder:"):
            return fail("dump lost its flight-recorder header")
        if "router.request" not in rendered:
            return fail("dump lost the stitched traces")
        if not doc.get("flight_recorder", {}).get("history"):
            return fail("dump lost the metrics-history window")
        result["flight_dump"] = os.path.basename(recorder.dumps()[-1])

        # 9) Hygiene: zero open spans, zero leaked KV pages everywhere
        #    (the kill strands in-flight work; cancel and reap it).
        for srv in servers:
            eng = srv.engine
            for s in eng.slots:
                if s is not None:
                    s.request.cancel()
            for req in list(eng._backlog) + list(eng._preempted):
                req.cancel()
            for ch in list(eng._chunkings):
                ch.request.cancel()
            deadline = time.monotonic() + 20.0
            while eng.kv_pages_in_use() > 0 and \
                    time.monotonic() < deadline:
                eng.step()
            if eng.kv_pages_in_use() != 0:
                return fail(f"{srv.name}: leaked KV pages")
        deadline = time.monotonic() + 5.0
        while tracer.open_spans() and time.monotonic() < deadline:
            time.sleep(0.02)
        if tracer.open_spans():
            return fail(f"{tracer.open_spans()} leaked open spans")
        result["hygiene"] = "ok"

        # 10) Liveness (ISSUE 20): orderly stop of every component —
        #     the stop() paths each assert their own threads quiescent
        #     under KFTPU_SANITIZE=threads — then the fleet-wide
        #     backstop: no stamped thread anywhere survives the stops,
        #     including anything the SIGKILL chaos stranded. The finally
        #     block's stops become no-ops (every path is idempotent).
        from kubeflow_tpu.runtime import sanitize

        if sanitize.thread_sanitizer() is None:
            return fail("thread sanitizer not installed — "
                        "KFTPU_SANITIZE=threads did not take")
        router.stop()
        for srv in servers:
            srv.stop()
        sanitize.assert_threads_quiescent(grace_s=10.0)
        leaked = sanitize.thread_leak_report_by_owner()
        if leaked:
            return fail(f"threads survived orderly stop: {leaked}")
        result["thread_sanitizer"] = {"mode": "threads", "leaked": 0}

        result["fleet_trace_smoke"] = "ok"
        print(json.dumps(result, indent=2))
        return 0
    finally:
        fleet.install_flight_recorder(prev_recorder)
        history.stop()
        router.stop()
        for srv in servers:
            try:
                srv.stop()
            except OSError:
                pass


if __name__ == "__main__":
    sys.exit(main())
