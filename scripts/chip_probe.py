"""Probe the chip's practical envelope: big-matmul TFLOPs (the real MXU
peak through this stack), HBM stream bandwidth, and the train step's
fwd vs fwd+bwd split for the bench model."""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, *args, reps=5):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)
    # fence via host fetch (axon tunnel: block_until_ready is not a fence)
    _ = jax.device_get(jax.tree.leaves(out)[0]).ravel()[0]
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
        _ = jax.device_get(jax.tree.leaves(out)[0]).ravel()[0]
    return (time.perf_counter() - t0) / reps


def main():
    import jax
    import jax.numpy as jnp

    results = {}

    # 1. Pure matmul peak, bf16 (8k^3 = 1.1 TFLOP per op)
    f = jax.jit(lambda a, b: a @ b)   # one wrapper; each shape traces once
    for n in (4096, 8192):
        a = jnp.ones((n, n), jnp.bfloat16)
        bmat = jnp.ones((n, n), jnp.bfloat16)
        dt = timeit(f, a, bmat)
        results[f"matmul{n}_tflops"] = round(2 * n**3 / dt / 1e12, 1)

    # 2. HBM stream: elementwise over 1 GB
    x = jnp.ones((512, 1024, 1024), jnp.bfloat16)   # 1 GiB
    f = jax.jit(lambda x: x * 1.5 + 2.0)
    dt = timeit(f, x)
    results["stream_gbps"] = round(2 * x.nbytes / dt / 1e9, 1)  # r+w

    # 3. Train model: fwd-only vs full step
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import decoder_loss
    from kubeflow_tpu.runtime.mesh import build_mesh
    from kubeflow_tpu.train.data import DataConfig, make_data_source
    from kubeflow_tpu.train.optim import OptimizerConfig
    from kubeflow_tpu.train.step import setup_train

    cfg = preset(
        "llama3-8b",
        n_layers=8, hidden=2048, n_heads=32, n_kv_heads=8, head_dim=64,
        mlp_dim=8192, vocab_size=32000, max_seq_len=2048)
    devices = jax.devices()
    mesh = build_mesh({"fsdp": len(devices)}, devices)
    data_cfg = DataConfig(vocab_size=cfg.vocab_size, seq_len=cfg.max_seq_len,
                          global_batch=4 * len(devices))
    source = make_data_source(data_cfg)
    task = setup_train(cfg, OptimizerConfig(total_steps=100), mesh)
    batch = jax.device_put(source.batch_at(0), task.batch_sharding)

    fwd = jax.jit(lambda p, b: decoder_loss(p, b, cfg, mesh=mesh)[0])
    dt_f = timeit(fwd, task.state["params"], batch, reps=4)
    results["fwd_only_ms"] = round(dt_f * 1e3, 1)

    grad = jax.jit(lambda p, b: jax.grad(
        lambda pp: decoder_loss(pp, b, cfg, mesh=mesh)[0])(p))
    dt_g = timeit(grad, task.state["params"], batch, reps=4)
    results["fwd_bwd_ms"] = round(dt_g * 1e3, 1)

    tokens = data_cfg.global_batch * data_cfg.seq_len
    fwd_tflop = 2 * cfg.num_params() * tokens / 1e12
    results["fwd_mxu_tflops"] = round(fwd_tflop / dt_f, 1)
    results["fwdbwd_mxu_tflops"] = round(
        (3 * fwd_tflop + fwd_tflop) / dt_g, 1)   # 6N + remat 2N = 8N

    print(json.dumps(results))


if __name__ == "__main__":
    main()
