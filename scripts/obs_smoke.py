"""Observability smoke stage for scripts/smoke.sh: fire traffic through a
real router → model-server → engine stack, then assert the observability
contract end to end:

- every /metrics endpoint (model server AND router) parses under the strict
  exposition grammar (obs/registry.parse_exposition);
- every exposed series name carries the platform ``kftpu_`` prefix (the
  metric-name lint);
- /debug/traces returns a well-formed trace: one trace id spanning
  router.request → server.request → engine.{queued,prefill,decode}, and the
  Chrome export is valid JSON with complete events;
- the tracer is quiescent after traffic (zero open spans — no leaked spans
  from any request path).

Prints one JSON line with the verdict; exit code 0 iff "obs_smoke": "ok".

    JAX_PLATFORMS=cpu python scripts/obs_smoke.py [--requests 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--concurrency", type=int, default=4)
    args = ap.parse_args()

    import jax

    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.obs.registry import NAME_PREFIX, parse_exposition
    from kubeflow_tpu.obs.trace import get_tracer
    from kubeflow_tpu.serve.engine import LLMEngine
    from kubeflow_tpu.serve.router import Router
    from kubeflow_tpu.serve.server import ModelServer

    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    engine = LLMEngine(
        cfg,
        BatchingSpec(max_batch_size=2, max_seq_len=96, prefill_buckets=[32],
                     paged=True, page_size=16, chunked_prefill_tokens=16,
                     decode_steps=4),
        params=params)
    server = ModelServer("obs-smoke", engine, port=0)
    server.start()
    router = Router(queue_timeout=5.0, upstream_timeout=60.0)
    router.set_backends({"latest": [server.url]})
    router.start()

    verdict: dict = {"obs_smoke": "ok"}
    problems: list[str] = []

    def one_request(i: int) -> None:
        body = json.dumps({"prompt": f"smoke {i}", "max_tokens": 8,
                           "timeout": 30}).encode()
        req = urllib.request.Request(
            router.url + "/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                r.read()
        except Exception as exc:  # noqa: BLE001 — counted, not fatal
            problems.append(f"request {i}: {exc}")

    try:
        threads = [threading.Thread(target=one_request, args=(i,))
                   for i in range(args.requests)]
        for batch in range(0, len(threads), args.concurrency):
            chunk = threads[batch:batch + args.concurrency]
            for t in chunk:
                t.start()
            for t in chunk:
                t.join(timeout=90)

        # -- /metrics grammar + name lint, both endpoints ---------------------
        scrapes = {
            "server": server.url + "/metrics",
            "router": router.url + "/-/router/metrics",
        }
        series = 0
        for which, url in scrapes.items():
            with urllib.request.urlopen(url, timeout=10) as r:
                text = r.read().decode()
            try:
                samples = parse_exposition(text)
            except ValueError as exc:
                problems.append(f"{which} /metrics: {exc}")
                continue
            series += len(samples)
            for name, _, _ in samples:
                base = name
                for suffix in ("_bucket", "_sum", "_count"):
                    if base.endswith(suffix):
                        base = base[:-len(suffix)]
                        break
                if not base.startswith(NAME_PREFIX):
                    problems.append(
                        f"{which}: series {name} missing {NAME_PREFIX}")
        verdict["series"] = series

        # -- /debug/traces shape ----------------------------------------------
        # The client can observe response bytes a beat before the router
        # handler's span closes — give the handler threads a moment to
        # quiesce before asserting on trace shape and open-span count.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and (
                get_tracer().open_spans() != 0):
            time.sleep(0.02)
        with urllib.request.urlopen(server.url + "/debug/traces",
                                    timeout=10) as r:
            doc = json.loads(r.read())
        traces = doc.get("traces", [])
        verdict["traces"] = len(traces)
        full = None
        for t in traces:
            names = {s["name"] for s in t["spans"]}
            if {"router.request", "server.request", "engine.queued",
                    "engine.prefill", "engine.decode"} <= names:
                full = t
                break
        if full is None:
            problems.append("no trace spans router→server→engine")
        else:
            ids = {s["trace_id"] for s in full["spans"]}
            if len(ids) != 1:
                problems.append(f"trace id not unified: {ids}")
            if any(s["end"] is None for s in full["spans"]):
                problems.append("trace contains unclosed spans")

        with urllib.request.urlopen(
                server.url + "/debug/traces?chrome=1", timeout=10) as r:
            chrome = json.loads(r.read())
        evs = chrome.get("traceEvents", [])
        if not evs:
            problems.append("chrome export is empty")
        # Complete ("X") events carry a duration; instant ("i") events —
        # span events such as per-round decode_round markers — carry a
        # scope instead (Chrome trace-event format).
        for e in evs:
            need = {"name", "ph", "ts", "pid", "tid"}
            need |= {"dur"} if e.get("ph") == "X" else {"s"}
            if not need <= set(e):
                problems.append("chrome export has malformed events")
                break

        open_spans = get_tracer().open_spans()
        verdict["open_spans"] = open_spans
        if open_spans != 0:
            problems.append(f"{open_spans} spans still open after traffic")
    finally:
        router.stop()
        server.stop()

    if problems:
        verdict["obs_smoke"] = "FAIL"
        verdict["problems"] = problems
    print(json.dumps(verdict))
    return 0 if not problems else 1


if __name__ == "__main__":
    raise SystemExit(main())
