#!/usr/bin/env bash
# One-command local gate for perf PRs: the tier-1 test suite (the exact
# command ROADMAP.md pins) followed by a short bench_serve sanity run, so a
# serving change is exercised end-to-end (engine + scheduler + metrics +
# bench JSON) before it ships. ~15 min total on an idle CPU host.
#
#   scripts/smoke.sh            # tier-1 + 30s-class bench sanity
#   SMOKE_SKIP_TESTS=1 scripts/smoke.sh   # bench sanity only (iterating)
set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== kftpu lint (static analysis vs committed baseline) =="
# Cheapest gate first: device-hygiene + lock-discipline + sharding/SPMD +
# resource-pairing + metric-name + compilation-stability rules over the
# whole tree (whole-program call graph, ASTs parsed once per run); any
# finding not in .kftpu-lint-baseline.json fails, and each rule family
# must still catch its seeded regression (D103 re-upload, C301 dropped
# lock, S401 de-donated carry, R501 exception-path page leak, R503 lock
# inversion, R504 fire-and-forget trainer checkpoint save, F602 weak-type
# scalar into the decode dispatch, F604 fresh tuple in its static
# position, X701 renamed autoscaler-scraped series, X703 typoed header
# literal).
timeout -k 10 120 python scripts/lint_smoke.py | tee /tmp/_smoke_lint.json
lint_rc=${PIPESTATUS[0]}
grep -q '"lint_smoke": "ok"' /tmp/_smoke_lint.json || lint_rc=1

rc=0
if [ -z "${SMOKE_SKIP_TESTS:-}" ]; then
  echo "== tier-1 tests (ROADMAP.md) =="
  rm -f /tmp/_t1.log
  timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
  rc=${PIPESTATUS[0]}
  echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
fi

echo "== bench_serve sanity (spec A/B, small shape) =="
# Small shapes: this is a does-it-run-and-report gate, not a measurement —
# the JSON must contain the spec-on/spec-off rows and the speedup line.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python bench_serve.py --workload spec --requests 4 --concurrency 4 \
  --max-new 64 | tee /tmp/_smoke_bench.json
bench_rc=${PIPESTATUS[0]}
grep -q "serve_spec_speedup" /tmp/_smoke_bench.json || bench_rc=1

echo "== chaos smoke (replica SIGKILL mid-run through the router) =="
# Serving-path robustness gate: one replica is killed mid-bench; the run
# must finish with zero hung requests, every request resolved explicitly,
# a recovered router, and zero paged-KV page leaks on both engines.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/chaos_smoke.py --requests 24 --concurrency 4 \
  | tee /tmp/_smoke_chaos.json
chaos_rc=${PIPESTATUS[0]}
grep -q '"chaos_smoke": "ok"' /tmp/_smoke_chaos.json || chaos_rc=1

echo "== obs smoke (trace propagation + /metrics exposition grammar) =="
# Observability gate: traffic through router→server→engine must yield one
# unified trace id with closed spans, every /metrics endpoint must parse
# under the exposition grammar, and every series name must be kftpu_-
# prefixed (the metric-name lint).
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/obs_smoke.py --requests 8 --concurrency 4 \
  | tee /tmp/_smoke_obs.json
obs_rc=${PIPESTATUS[0]}
grep -q '"obs_smoke": "ok"' /tmp/_smoke_obs.json || obs_rc=1

echo "== hotloop smoke (pipelined dispatch on/off A/B, CPU) =="
# Hot-loop gate: greedy token identity with pipelining on/off (dense +
# paged), zero full scheduler-state uploads past engine construction, a
# well-formed host_gap_ms attribute on decode spans, and the hot-loop
# /metrics series. Correctness + plumbing only — no perf assertion on CPU.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/hotloop_smoke.py | tee /tmp/_smoke_hotloop.json
hotloop_rc=${PIPESTATUS[0]}
grep -q '"hotloop_smoke": "ok"' /tmp/_smoke_hotloop.json || hotloop_rc=1

echo "== recompile smoke (zero steady-state retraces, warmed paged engine) =="
# Compilation-stability gate (KFTPU_SANITIZE=recompile): warm a paged
# engine, mark the compile cache warm, replay the same traffic shape —
# the steady state must compile NOTHING, every warmup trace must carry a
# named call-site attribution, and greedy outputs must be identical
# across the phases (the watchdog observes, never perturbs).
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/recompile_smoke.py | tee /tmp/_smoke_recompile.json
recompile_rc=${PIPESTATUS[0]}
grep -q '"recompile_smoke": "ok"' /tmp/_smoke_recompile.json || recompile_rc=1

echo "== train chaos smoke (preemption emergency save + verified fallback) =="
# Survivable-training gate (ISSUE 9): a SIGTERMed trainer must emergency-
# save and resume at that exact step (zero completed steps lost in the
# goodput ledger), and a corrupted newest checkpoint must be quarantined
# with resume falling back to an older valid step — job still Succeeded,
# ledger (goodput/fallbacks/emergency saves) lifted onto job status.
timeout -k 10 600 env JAX_PLATFORMS=cpu \
  python scripts/train_chaos_smoke.py | tee /tmp/_smoke_train_chaos.json
train_chaos_rc=${PIPESTATUS[0]}
grep -q '"train_chaos_smoke": "ok"' /tmp/_smoke_train_chaos.json || train_chaos_rc=1

echo "== autoscale smoke (QoS shed ordering + SLO autoscaler loop, CPU) =="
# Closed-loop gate for the SLO-aware serving loop: a 2-class burst must
# shed batch-first (interactive all-200), the signal-driven autoscaler
# must make exactly one scale-up decision off the replica's real
# /metrics, scale-down must complete drain before teardown, and the new
# QoS/router series must pass the M2xx metric-name lint + exposition
# grammar.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/autoscale_smoke.py | tee /tmp/_smoke_autoscale.json
autoscale_rc=${PIPESTATUS[0]}
grep -q '"autoscale_smoke": "ok"' /tmp/_smoke_autoscale.json || autoscale_rc=1

echo "== serve perf smoke (trace-driven scenario matrix + threshold gate) =="
# Serving-perf gate (ISSUE 11): the canonical loadgen scenario matrix
# (uniform Poisson, bursty multi-QoS, shared-prefix on the paged prefix-
# cache engine, mixed-interference class-correlated shapes) replayed
# open-loop over HTTP; two measured segments must agree within their own
# spread-derived noise band, a seeded throttled-dispatch regression must
# breach the threshold WITH an attribution diff, per-phase span
# breakdowns and per-class engine counters must join, and
# BENCH_SERVE_r01.json is (re)written as the serving bench trajectory.
timeout -k 10 600 env JAX_PLATFORMS=cpu \
  python scripts/serve_perf_smoke.py | tee /tmp/_smoke_serve_perf.json
serve_perf_rc=${PIPESTATUS[0]}
grep -q '"serve_perf_smoke": "ok"' /tmp/_smoke_serve_perf.json || serve_perf_rc=1

echo "== disagg smoke (prefill/decode split A/B + paged-KV handoff gate) =="
# Disaggregated-serving gate (ISSUE 12): a 1-prefill + 1-decode fleet
# behind the token-aware router vs 2 unified replicas at the same
# offered load. Greedy output must be token-identical across the
# prefill→handoff→decode boundary, the split must win goodput-under-SLO
# on the mixed_interference scenario (TPOT-led SLO — the decode-stall
# axis the split removes) with interactive TTFT no worse, handoff
# counters must flow with ZERO failures/leaks, and a seeded wedged
# handoff must be flagged with the attribution naming the handoff phase.
# Writes BENCH_SERVE_r02.json (the disaggregation bench round).
timeout -k 10 600 env JAX_PLATFORMS=cpu \
  python scripts/disagg_smoke.py | tee /tmp/_smoke_disagg.json
disagg_rc=${PIPESTATUS[0]}
grep -q '"disagg_smoke": "ok"' /tmp/_smoke_disagg.json || disagg_rc=1

echo "== prefix cache smoke (tiered KV: radix+host tier vs flat A/B) =="
# Tiered-KV-cache gate (ISSUE 13): multi-turn conversations + the
# shared-prefix overlap sweep on a small paged engine under pool
# pressure. Greedy output must be token-identical with sharing+tiering
# on vs off; the radix+host-tier engine must beat the flat-cache
# baseline on prefill tok/s AND TTFT p95 on the multi-turn shape; the
# tier must actually cycle (demote on idle, promote on the radix hit);
# a seeded migration wedge must be flagged with the kv_tier
# attribution; per-owner refcounts must balance on device and host
# tiers. Writes BENCH_SERVE_r03.json (the tiered-KV bench round).
timeout -k 10 600 env JAX_PLATFORMS=cpu \
  python scripts/prefix_cache_smoke.py | tee /tmp/_smoke_prefix_cache.json
prefix_cache_rc=${PIPESTATUS[0]}
grep -q '"prefix_cache_smoke": "ok"' /tmp/_smoke_prefix_cache.json || prefix_cache_rc=1

echo "== lora smoke (multi-tenant adapters: identity + churn + chaos) =="
# Multi-tenant LoRA gate (ISSUE 14): greedy decode under every adapter
# must be token-identical to the merged-weights single-model reference
# (dense + paged); the multi_adapter scenario at 8/32/64 concurrent
# adapters must stay inside the declared tok/s + TTFT p95 degradation
# band vs single-model with ZERO steady-state recompiles across the
# hot-load/evict churn (KFTPU_SANITIZE=refcount,recompile is on for the
# whole stage); a seeded slow-hot-load wedge must be flagged with the
# adapter_load attribution; SIGKILL mid-hot-load behind the model-id
# router must strand nothing (per-owner zero leaks: pages AND adapter
# slots). Writes BENCH_SERVE_r04.json (the multi-adapter bench round).
timeout -k 10 600 env JAX_PLATFORMS=cpu \
  python scripts/lora_smoke.py | tee /tmp/_smoke_lora.json
lora_rc=${PIPESTATUS[0]}
grep -q '"lora_smoke": "ok"' /tmp/_smoke_lora.json || lora_rc=1

echo "== quant smoke (int8 KV fabric: band + density + wire + kernel A/B) =="
# Quantized-serving gate (ISSUE 16): int8-pool greedy decode must track
# the full-dtype engine inside the declared tolerance band; the int8
# prefill→v2-wire→decode path must be token-identical to the int8
# unified engine; at head_dim=128 the int8 pool must hold >=1.9x the
# resident KV tokens per MiB and ship <0.6x the handoff/demote wire
# bytes; in-kernel dequant (pallas, interpret off-TPU) must match
# gather+dequant token for token on the same int8 pool; a warmed int8
# engine must replay decode + a handoff round trip with ZERO
# steady-state recompiles (KFTPU_SANITIZE=refcount,recompile); quant
# series must parse off the real exposition with per-owner refcounts
# balanced. Writes BENCH_SERVE_r05.json (the quantized-serving round).
timeout -k 10 600 env JAX_PLATFORMS=cpu \
  python scripts/quant_smoke.py | tee /tmp/_smoke_quant.json
quant_rc=${PIPESTATUS[0]}
grep -q '"quant_smoke": "ok"' /tmp/_smoke_quant.json || quant_rc=1

echo "== fleet kv smoke (cross-host handoff + remote-tier failover) =="
# Fleet-wide KV fabric gate (ISSUE 17): completions through a real
# prefill→HTTP-handoff→decode pair must be byte-identical to the
# unified reference with zero fallbacks; conversations drained to the
# artifact store must resume on a DIFFERENT replica token-identically
# AND with better TTFT p95 than cold recompute; a post-warm remote-tier
# resume and handoff round trip must compile NOTHING
# (KFTPU_SANITIZE=refcount,recompile); fabric series must parse off the
# real exposition with per-owner refcounts balanced. Writes
# BENCH_SERVE_r06.json (the fleet-KV bench round).
timeout -k 10 600 env JAX_PLATFORMS=cpu \
  python scripts/fleet_kv_smoke.py | tee /tmp/_smoke_fleet_kv.json
fleet_kv_rc=${PIPESTATUS[0]}
grep -q '"fleet_kv_smoke": "ok"' /tmp/_smoke_fleet_kv.json || fleet_kv_rc=1

echo "== fleet trace smoke (cross-host stitching + burn-rate + recorder) =="
# Fleet-observability gate (ISSUE 18): a 1-prefill + 2-decode fleet behind
# the router with one decode replica SIGKILLed mid-scenario must stitch
# into ONE causal trace spanning router→prefill→handoff→decode INCLUDING
# the failover hop, with per-hop wire attribution and skew-corrected
# monotone orderings; the metrics history must accumulate real /metrics
# points; a seeded SLO breach must raise the burn-rate alert while a
# clean run must not; all fleet/obs series must parse off the registry
# render; engine stop must leave a re-loadable flight-recorder dump; and
# the fleet hops must join into the loadgen report (zero leaked pages,
# zero open spans).
timeout -k 10 600 env JAX_PLATFORMS=cpu \
  python scripts/fleet_trace_smoke.py | tee /tmp/_smoke_fleet_trace.json
fleet_trace_rc=${PIPESTATUS[0]}
grep -q '"fleet_trace_smoke": "ok"' /tmp/_smoke_fleet_trace.json || fleet_trace_rc=1

echo "== contract smoke (static name-contract table vs a real serve run) =="
# Cross-component contract gate (ISSUE 10): the kftpu lint --contracts-json
# manifest must round-trip, and a serve run under KFTPU_SANITIZE=contract
# must exchange ZERO series/header names the static X7xx extraction does
# not declare — a dynamically-built name the AST missed fails here.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/contract_smoke.py | tee /tmp/_smoke_contract.json
contract_rc=${PIPESTATUS[0]}
grep -q '"contract_smoke": "ok"' /tmp/_smoke_contract.json || contract_rc=1

echo "== smoke: lint rc=$lint_rc tests rc=$rc bench rc=$bench_rc chaos rc=$chaos_rc obs rc=$obs_rc hotloop rc=$hotloop_rc recompile rc=$recompile_rc train_chaos rc=$train_chaos_rc autoscale rc=$autoscale_rc serve_perf rc=$serve_perf_rc disagg rc=$disagg_rc prefix_cache rc=$prefix_cache_rc lora rc=$lora_rc quant rc=$quant_rc fleet_kv rc=$fleet_kv_rc contract rc=$contract_rc =="
[ "$lint_rc" -eq 0 ] && [ "$rc" -eq 0 ] && [ "$bench_rc" -eq 0 ] && [ "$chaos_rc" -eq 0 ] && [ "$obs_rc" -eq 0 ] && [ "$hotloop_rc" -eq 0 ] && [ "$recompile_rc" -eq 0 ] && [ "$train_chaos_rc" -eq 0 ] && [ "$autoscale_rc" -eq 0 ] && [ "$serve_perf_rc" -eq 0 ] && [ "$disagg_rc" -eq 0 ] && [ "$prefix_cache_rc" -eq 0 ] && [ "$lora_rc" -eq 0 ] && [ "$quant_rc" -eq 0 ] && [ "$fleet_kv_rc" -eq 0 ] && [ "$contract_rc" -eq 0 ]
