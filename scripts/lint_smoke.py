#!/usr/bin/env python
"""Static-analysis gate for smoke.sh: ``kftpu lint`` over the whole tree.

Fails on ANY finding not matched by the checked-in baseline
(.kftpu-lint-baseline.json) — pre-existing debt is baselined with a
justification, new findings block. Also self-checks the analyzer the way
the acceptance criteria demand: each rule family must still catch its
seeded regression — the PR-4 per-round ``jnp.asarray(self._table)``
upload (D103), a dropped router lock acquisition (C301), a de-donated
decode carry (S401), an exception-path page leak (R501), an inverted
router lock pair (R503), a fire-and-forget trainer checkpoint save
(R504), a weak-type scalar riding into the dense decode dispatch (F602),
a fresh tuple in its static num_steps position (F604), a renamed
autoscaler-scraped series (X701, linted under the full package Program
so the cross-component table sees the real producers), a typoed
header literal (X703), and the ISSUE-20 liveness family: a router
metrics probe stripped of its timeout (T801), an inline sleep-retry
loop (T802), the kv-migrate join dropped from KVTier.close (T803), a
queue get under the router lock (T804), and the relay's derived
``timeout=remaining`` hardened to a literal (T805) — so a rule that
silently stops firing fails the gate too, not just the test suite.

Prints one JSON object; ``"lint_smoke": "ok"`` is the pass marker
smoke.sh greps for. Findings render as ``file:line:col`` so they are
clickable in CI logs; ``wall_time_s`` tracks the whole-program scan's
cost (ISSUE 8: parse-once + shared per-module structures made the
self-scan faster despite the added F-family and cross-module
resolution).
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from kubeflow_tpu.analysis import Baseline, find_baseline, lint_source, run_lint  # noqa: E402
from kubeflow_tpu.analysis import core as _core  # noqa: E402

SCAN = ["kubeflow_tpu", "scripts", "bench.py", "bench_serve.py"]


def _lint_with_program(relpath: str, src: str):
    """Lint ONE (possibly mutated) source under the full package-wide
    Program — the X-family cross-component rules need the real producers
    and consumers on the other side of each contract visible, which
    ``lint_source``'s standalone module cannot provide."""
    mods = []
    for path in _core.iter_py_files(SCAN):
        rel = os.path.relpath(os.path.abspath(path), REPO).replace(
            os.sep, "/")
        if rel == relpath:
            mods.append(_core.Module(relpath, src))
        else:
            try:
                mods.append(_core.load_module(path, rel))
            except (OSError, SyntaxError, ValueError):
                continue
    _core.Program(mods)
    target = next(m for m in mods if m.relpath == relpath)
    return _core.lint_module(target)


def _seeded_regressions() -> list[str]:
    """Mutate engine/router source in memory and check each rule family
    still fires exactly once. Returns a list of failure descriptions."""
    fails: list[str] = []

    def new_findings(path: str, edits, rule: str, needle: str) -> None:
        """``edits``: one (old, new) pair or a list of them — some seeds
        (the R503 lock-order inversion) need both an __init__ line and
        the inverted methods."""
        if isinstance(edits, tuple):
            edits = [edits]
        with open(os.path.join(REPO, path)) as f:
            src = f.read()
        mut = src
        for old, new in edits:
            nxt = mut.replace(old, new, 1)
            if nxt == mut:
                fails.append(
                    f"{rule}: mutation anchor not found in {path}")
                return
            mut = nxt
        before = {f.fingerprint for f in lint_source(src, path)}
        fresh = [f for f in lint_source(mut, path)
                 if f.fingerprint not in before]
        if len(fresh) != 1 or fresh[0].rule != rule \
                or needle not in fresh[0].message:
            fails.append(
                f"{rule}: seeded regression in {path} produced "
                f"{[f.render() for f in fresh]!r}, expected exactly one "
                f"{rule} mentioning {needle!r}")

    # Family A: the PR-4 bug — full page-table re-upload per decode round.
    new_findings(
        "kubeflow_tpu/serve/engine.py",
        ("        self._sync_decode_state()\n",
         "        self._sync_decode_state()\n"
         "        table = jnp.asarray(self._table)\n"),
        "D103", "self._table")
    # Family B: drop one router lock acquisition.
    new_findings(
        "kubeflow_tpu/serve/router.py",
        ("    def note_activity(self) -> None:\n        with self._lock:\n",
         "    def note_activity(self) -> None:\n        if True:\n"),
        "C301", "_last_activity")
    # Family S: drop the dense decode dispatch's carry donation (2x HBM).
    new_findings(
        "kubeflow_tpu/serve/engine.py",
        ("self._decode_n = jax.jit(_decode_fn, static_argnums=(4, 5),\n"
         "                                 donate_argnums=(1, 2))",
         "self._decode_n = jax.jit(_decode_fn, static_argnums=(4, 5))"),
        "S401", "self._decode_n")
    # Family R: a raise-capable call between page alloc and the ownership
    # recording — the exception path leaks the pages.
    new_findings(
        "kubeflow_tpu/serve/engine.py",
        ("owner=self._slot_owner(slot_idx))\n",
         "owner=self._slot_owner(slot_idx))\n"
         "            self._refresh_pool_gauge()\n"),
        "R501", "_ensure_pages")
    # Family R: a second router lock acquired in both orders (the cycle
    # KFTPU_SANITIZE=lockorder would catch at runtime).
    new_findings(
        "kubeflow_tpu/serve/router.py",
        [("        self._lock = threading.Lock()\n",
          "        self._lock = threading.Lock()\n"
          "        self._aux_lock = threading.Lock()\n"),
         ("    def note_activity(self) -> None:\n",
          "    def _seed_ab(self):\n"
          "        with self._lock:\n"
          "            with self._aux_lock:\n"
          "                pass\n\n"
          "    def _seed_ba(self):\n"
          "        with self._aux_lock:\n"
          "            with self._lock:\n"
          "                pass\n\n"
          "    def note_activity(self) -> None:\n")],
        "R503", "lock-order inversion")
    # Family R: a fire-and-forget checkpoint save on the training loop —
    # the acceptance bool dropped, no exception handling (the exact
    # Trainer.save bug ISSUE 9 fixed; a broken checkpoint store would
    # vanish silently instead of raising the save-failure alarm).
    new_findings(
        "kubeflow_tpu/train/trainer.py",
        ("        start = self.try_resume()\n",
         "        start = self.try_resume()\n"
         "        self.ckpt.save(0, self.task.state)\n"),
        "R504", "self.ckpt.save")
    # Family F: a weak-typed Python scalar in the dense decode dispatch
    # (a fresh compile-cache entry per scalar source) — the cycle
    # KFTPU_SANITIZE=recompile would catch at runtime.
    _DECODE_CALL = (
        "                out, self.cache, st = self._decode_n(\n"
        "                    self.params, self.cache, self._dstate.arrays,"
        " key, k_steps,\n"
        "                    mode)")
    new_findings(
        "kubeflow_tpu/serve/engine.py",
        (_DECODE_CALL,
         _DECODE_CALL.replace(" key, k_steps,", " 0.5, k_steps,")),
        "F602", "self._decode_n")
    # Family F: a per-call tuple in the dispatch's STATIC num_steps
    # position — hashed by value each call, a retrace per dispatch.
    new_findings(
        "kubeflow_tpu/serve/engine.py",
        (_DECODE_CALL,
         _DECODE_CALL.replace(" key, k_steps,", " key, (k_steps,),")),
        "F604", "self._decode_n")
    # Family F on the FUSED-KERNEL dispatch surface (ISSUE 15): the paged
    # decode dispatch now runs the fused RMSNorm Pallas kernel inside it
    # (layers.rmsnorm) — a weak Python scalar replacing its key would be
    # one fresh compile-cache entry per scalar source, exactly the
    # steady-state recompile the warmed-fused-step sanitizer test pins to
    # zero. Prove the analyzer guards the new path too.
    _PAGED_CALL = (
        "                out, self.cache, st, tbl = self._paged_decode_n(\n"
        "                    self.params, self.cache, self._dstate.arrays,\n"
        "                    self._dstate.table, key, k_steps, mode)")
    new_findings(
        "kubeflow_tpu/serve/engine.py",
        (_PAGED_CALL,
         _PAGED_CALL.replace(" key, k_steps, mode)", " 0.5, k_steps, mode)")),
        "F602", "self._paged_decode_n")

    # Family T: strip the scrape probe's timeout — the exact unbounded
    # urlopen class that wedged a router behind a SIGKILLed replica.
    new_findings(
        "kubeflow_tpu/serve/router.py",
        ('with urllib.request.urlopen(url + "/metrics",\n'
         '                                            timeout=1.0) as r:',
         'with urllib.request.urlopen(url + "/metrics") as r:'),
        "T801", "urllib.request.urlopen")
    # Family T: an inline sleep-and-swallow retry loop instead of the
    # blessed serve/retry.py::call_with_retry helper.
    new_findings(
        "kubeflow_tpu/serve/handoff.py",
        [("import json\n", "import json\nimport time\n"),
         ("    def validate(self) -> None:\n"
          "        if self.kv_k.shape != self.kv_v.shape:\n",
          "    def validate(self) -> None:\n"
          "        attempt = 0\n"
          "        while attempt < 5:\n"
          "            try:\n"
          "                json.loads(\"{}\")\n"
          "                break\n"
          "            except ValueError:\n"
          "                attempt += 1\n"
          "                time.sleep(0.05)\n"
          "        if self.kv_k.shape != self.kv_v.shape:\n")],
        "T802", "call_with_retry")
    # Family T: drop the kv-migrate join from KVTier.close — the thread
    # outlives the tier (the leak KFTPU_SANITIZE=threads catches live).
    new_findings(
        "kubeflow_tpu/serve/kvtier.py",
        ("            self._queue.put(None)\n"
         "            self._thread.join(timeout=5.0)\n"
         "            self._thread = None\n",
         "            self._queue.put(None)\n"
         "            self._thread = None\n"),
        "T803", "_thread")
    # Family T: an unbounded queue get while holding the router lock —
    # the attr-based wait C302's fixed call set misses.
    new_findings(
        "kubeflow_tpu/serve/router.py",
        ("    def note_activity(self) -> None:\n",
         "    def _drain_locked(self):\n"
         "        with self._lock:\n"
         "            return self._retire_q.get()\n\n"
         "    def note_activity(self) -> None:\n"),
        "T804", "while holding")

    def new_findings_prog(path: str, old: str, new: str, rule: str,
                          needle: str) -> None:
        """The X-family variant: lint the mutated module under the FULL
        package Program (cross-component contracts need both sides)."""
        with open(os.path.join(REPO, path)) as f:
            src = f.read()
        mut = src.replace(old, new, 1)
        if mut == src:
            fails.append(f"{rule}: mutation anchor not found in {path}")
            return
        before = {f.fingerprint for f in _lint_with_program(path, src)}
        fresh = [f for f in _lint_with_program(path, mut)
                 if f.fingerprint not in before]
        if len(fresh) != 1 or fresh[0].rule != rule \
                or needle not in fresh[0].message:
            fails.append(
                f"{rule}: seeded regression in {path} produced "
                f"{[f.render() for f in fresh]!r}, expected exactly one "
                f"{rule} mentioning {needle!r}")

    # Family X: rename one scraped series in the autoscaler probe — the
    # engine still produces the old name, the probe now matches nothing
    # (the silent-HOLD drift class ISSUE 10 exists to kill).
    new_findings_prog(
        "kubeflow_tpu/serve/isvc_controller.py",
        '"kftpu_serving_requests_total"',
        '"kftpu_serving_requests_totals"',
        "X701", "kftpu_serving_requests_totals")
    # Family X: typo one header literal on the model server's read side —
    # nothing sets the misspelled header, so the QoS class silently
    # defaults for every request.
    new_findings_prog(
        "kubeflow_tpu/serve/server.py",
        "raw = self.headers.get(QOS_HEADER) or body.get(\"qos\")",
        "raw = self.headers.get(\"X-Kftpu-Qoss\") or body.get(\"qos\")",
        "X703", "X-Kftpu-Qoss")
    # Family T: the relay forwards the caller's remaining budget today —
    # harden it to a literal and the handler scope that READS the
    # deadline header (resolved through the Program-wide header table)
    # now ignores it.
    new_findings_prog(
        "kubeflow_tpu/serve/router.py",
        "resp = urllib.request.urlopen(req, timeout=remaining)",
        "resp = urllib.request.urlopen(req, timeout=30.0)",
        "T805", "timeout=30.0")
    return fails


def main() -> int:
    os.chdir(REPO)
    baseline_path = find_baseline(SCAN)
    baseline = Baseline.load(baseline_path) if baseline_path else None
    result = run_lint(SCAN, baseline=baseline, root=REPO)
    seeded = _seeded_regressions()
    ok = result.ok and not seeded
    print(json.dumps({
        "lint_smoke": "ok" if ok else "FAIL",
        "files_scanned": result.files_scanned,
        "wall_time_s": round(result.wall_time_s, 3),
        "findings": [f.render() for f in result.errors + result.new],
        "baselined": len(result.baselined),
        "baseline": (os.path.relpath(baseline_path, REPO)
                     if baseline_path else None),
        "seeded_regression_failures": seeded,
    }, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
