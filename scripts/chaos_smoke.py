"""Chaos smoke stage for scripts/smoke.sh: a bench_serve-style closed-loop
run through the hardened router with one replica SIGKILLed mid-run.

Asserts the serving-path robustness contract end to end on a real stack
(2 model-server replicas, paged engines, router with retries + ejection):

- the bench completes — zero hung requests (every client thread joins);
- every request resolves explicitly (200 or an HTTP error status);
- the router recovers: post-kill requests succeed on the survivor;
- paged-KV page refcounts balance to zero leaks on both engines.

Prints one JSON line with the verdict; exit code 0 iff "chaos_smoke": "ok".

    JAX_PLATFORMS=cpu python scripts/chaos_smoke.py [--requests 24]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def completion(url: str, timeout_s: float) -> int:
    body = json.dumps({"prompt": "smoke", "max_tokens": 8,
                       "timeout": timeout_s}).encode()
    from kubeflow_tpu.serve.router import DEADLINE_HEADER

    req = urllib.request.Request(
        url + "/v1/completions", data=body,
        headers={"Content-Type": "application/json",
                 DEADLINE_HEADER: str(int(timeout_s * 1e3))})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s + 5) as r:
            return r.status
    except urllib.error.HTTPError as exc:
        exc.read()
        return exc.code
    except OSError:
        return 502


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--kill-after", type=int, default=4,
                    help="completed requests before the SIGKILL fires")
    ap.add_argument("--timeout", type=float, default=8.0,
                    help="per-request deadline (seconds)")
    args = ap.parse_args()

    import jax

    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.serve.engine import LLMEngine
    from kubeflow_tpu.serve.faults import kill_model_server
    from kubeflow_tpu.serve.router import Router
    from kubeflow_tpu.serve.server import ModelServer

    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)

    def mk(name: str) -> ModelServer:
        eng = LLMEngine(
            cfg,
            BatchingSpec(max_batch_size=2, max_seq_len=96,
                         prefill_buckets=[32], paged=True, page_size=16,
                         chunked_prefill_tokens=16, decode_steps=4),
            params=params)
        srv = ModelServer(name, eng, port=0)
        srv.start()
        return srv

    a, b = mk("replica-a"), mk("replica-b")
    router = Router(queue_timeout=5.0, eject_threshold=2, eject_period=0.5,
                    max_retries=2, upstream_timeout=30.0)
    router.set_backends({"latest": [a.url, b.url]})
    router.start()

    results: list[int] = []
    lock = threading.Lock()
    it = iter(range(args.requests))
    killed = threading.Event()

    def client() -> None:
        while True:
            with lock:
                nxt = next(it, None)
            if nxt is None:
                return
            status = completion(router.url, args.timeout)
            with lock:
                results.append(status)
                if not killed.is_set() and len(results) >= args.kill_after:
                    killed.set()
                    kill_model_server(b)

    t0 = time.monotonic()
    threads = [threading.Thread(target=client)
               for _ in range(max(1, args.concurrency))]
    for t in threads:
        t.start()
    hung = 0
    for t in threads:
        t.join(timeout=120.0)
        hung += t.is_alive()
    wall = time.monotonic() - t0

    # Router recovered? The survivor must serve fresh traffic.
    recovered = all(completion(router.url, args.timeout) == 200
                    for _ in range(3))

    # Refcount audit: cancel anything the kill stranded, drive the reaper.
    leaks = {}
    for srv in (a, b):
        eng = srv.engine
        for s in eng.slots:
            if s is not None:
                s.request.cancel()
        for req in list(eng._backlog) + list(eng._preempted):
            req.cancel()
        for ch in list(eng._chunkings):
            ch.request.cancel()
        deadline = time.monotonic() + 20.0
        while eng.kv_pages_in_use() > 0 and time.monotonic() < deadline:
            eng.step()
        leaks[srv.name] = eng.kv_pages_in_use()

    statuses = sorted(set(results))
    ok = (hung == 0 and len(results) == args.requests and killed.is_set()
          and recovered and all(v == 0 for v in leaks.values())
          and all(s in (200, 429, 500, 502, 503, 504) for s in results))
    print(json.dumps({
        "chaos_smoke": "ok" if ok else "FAIL",
        "requests": len(results), "hung": hung,
        "completed_200": results.count(200), "statuses": statuses,
        "router_recovered": recovered, "kv_page_leaks": leaks,
        "router_stats": router.snapshot(), "wall_s": round(wall, 2),
    }))
    router.stop()
    try:
        a.stop()
    except OSError:
        pass
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
