"""On-chip MFU sweep: time the full train step across remat / attention /
batch / steps-per-dispatch / Adam-mu-dtype grids.

Each config runs in a subprocess (the axon compile helper can 500 on big
programs; isolation keeps one failure from killing the sweep). Prints one
JSON line per config.

Usage:
    python scripts/mfu_sweep.py                                  # grid
    python scripts/mfu_sweep.py --one <remat> <attn> <batch> [k] [mu]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GRID = [
    # (remat_policy, attn_impl, per_chip_batch, k_dispatch, mu_dtype)
    ("nothing_saveable", "xla", 4, 1, "none"),      # round-1 baseline
    ("nothing_saveable", "xla", 4, 16, "none"),     # dispatch amortization
    ("block_outs", "xla", 4, 16, "none"),           # round-2 headline
    ("block_outs", "xla", 4, 16, "bfloat16"),
    ("block_outs", "pallas", 4, 16, "bfloat16"),
    ("dots_no_batch", "xla", 4, 16, "bfloat16"),
    ("none", "pallas", 4, 16, "bfloat16"),
]


def run_one(remat: str, attn: str, batch: int, kd: int = 1,
            mu: str = "none", steps: int = 16, warmup_disp: int = 2):
    import jax
    import numpy as np

    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.runtime.mesh import build_mesh
    from kubeflow_tpu.runtime.topology import detect_local_cluster
    from kubeflow_tpu.train.data import DataConfig, make_data_source
    from kubeflow_tpu.train.optim import OptimizerConfig
    from kubeflow_tpu.train.step import setup_train

    devices = jax.devices()
    n = len(devices)
    cfg = preset(
        "llama3-8b",
        n_layers=8, hidden=2048, n_heads=32, n_kv_heads=8, head_dim=64,
        mlp_dim=8192, vocab_size=32000, max_seq_len=2048,
        remat_policy=remat,
    )
    mesh = build_mesh({"fsdp": n}, devices)
    data_cfg = DataConfig(vocab_size=cfg.vocab_size, seq_len=cfg.max_seq_len,
                          global_batch=batch * n)
    source = make_data_source(data_cfg)
    opt_cfg = OptimizerConfig(total_steps=10_000,
                              mu_dtype=None if mu == "none" else mu)
    task = setup_train(cfg, opt_cfg, mesh, attn_impl=attn)

    def dispatch(i0, state):
        b = np.stack([source.batch_at(i0 + j) for j in range(kd)])
        b = jax.device_put(b, task.multi_batch_sharding)
        state, metrics = task.multi_step_fn(state, b)
        # Host fetch of the loss = the only reliable fence on the tunnel.
        return state, float(metrics["loss"])

    state = task.state
    t_c0 = time.perf_counter()
    for w in range(warmup_disp):
        state, loss = dispatch(w * kd, state)
    compile_s = time.perf_counter() - t_c0

    n_disp = max(steps // kd, 1)
    t0 = time.perf_counter()
    for di in range(n_disp):
        state, loss = dispatch((warmup_disp + di) * kd, state)
    dt = time.perf_counter() - t0

    tokens = data_cfg.global_batch * data_cfg.seq_len * kd * n_disp
    tps_chip = tokens / dt / n
    gen = detect_local_cluster().slices[0].gen
    mfu = (cfg.flops_per_token() * tps_chip) / (gen.bf16_tflops * 1e12)
    return {
        "remat": remat, "attn": attn, "batch": batch, "k": kd, "mu": mu,
        "tok_s_chip": round(tps_chip, 1),
        "step_ms": round(dt / (kd * n_disp) * 1e3, 2),
        "mfu": round(mfu, 4),
        "loss": round(loss, 4),
        "compile_s": round(compile_s, 1),
    }


def main():
    if len(sys.argv) >= 5 and sys.argv[1] == "--one":
        remat, attn, batch = sys.argv[2], sys.argv[3], int(sys.argv[4])
        kd = int(sys.argv[5]) if len(sys.argv) > 5 else 1
        mu = sys.argv[6] if len(sys.argv) > 6 else "none"
        print(json.dumps(run_one(remat, attn, batch, kd, mu)))
        return

    for remat, attn, batch, kd, mu in GRID:
        cmd = [sys.executable, __file__, "--one", remat, attn, str(batch),
               str(kd), mu]
        t0 = time.perf_counter()
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=900)
        except subprocess.TimeoutExpired:
            print(json.dumps({"remat": remat, "attn": attn, "batch": batch,
                              "k": kd, "failed": True, "err": "timeout 900s"}),
                  flush=True)
            continue
        wall = round(time.perf_counter() - t0, 1)
        if proc.returncode == 0 and proc.stdout.strip():
            print(proc.stdout.strip().splitlines()[-1], flush=True)
        else:
            err = (proc.stderr or "")[-300:].replace("\n", " | ")
            print(json.dumps({"remat": remat, "attn": attn, "batch": batch,
                              "k": kd, "mu": mu, "failed": True,
                              "wall_s": wall, "err": err}), flush=True)


if __name__ == "__main__":
    main()
