"""On-chip MFU sweep: time the full train step across remat / attention /
batch / steps-per-dispatch / Adam-mu-dtype / fused-kernel grids.

Each config runs in a subprocess (the axon compile helper can 500 on big
programs; isolation keeps one failure from killing the sweep). Prints one
JSON line per config. Rows run through ``bench.measure_train_rate`` — the
SAME dispatch loop, fencing, two-segment spread and MFU accounting as the
headline bench (and the same ``TrainKnobs`` defaults), so a sweep row and
the headline number can never measure different things.

Usage:
    python scripts/mfu_sweep.py                                   # grid
    python scripts/mfu_sweep.py --one <remat> <attn> <batch> [k] [mu] [fused]
    python scripts/mfu_sweep.py --fused on                        # A/B half
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GRID = [
    # (remat_policy, attn_impl, per_chip_batch, k_dispatch, mu_dtype, fused)
    ("nothing_saveable", "xla", 4, 1, "none", "off"),   # round-1 baseline
    ("nothing_saveable", "xla", 4, 16, "none", "off"),  # dispatch amortization
    ("block_outs", "xla", 4, 16, "none", "off"),        # round-2 headline
    ("block_outs", "xla", 4, 16, "bfloat16", "off"),
    ("block_outs", "pallas", 4, 16, "bfloat16", "off"),
    ("dots_no_batch", "xla", 4, 16, "bfloat16", "off"),
    ("none", "pallas", 4, 16, "bfloat16", "off"),
    # The round-6 A/B: headline knobs with the fused Pallas kernels
    # (blockwise CE + RMSNorm/SwiGLU) off vs on.
    ("dots_flash", "pallas", 5, 32, "bfloat16", "off"),
    ("dots_flash", "pallas", 5, 32, "bfloat16", "on"),
]


def run_one(remat: str, attn: str, batch: int, kd: int = 1,
            mu: str = "none", fused: str = "auto", disp: int = 2,
            warm_disp: int = 2):
    from bench import apply_perf_flags_if_tpu, measure_train_rate

    apply_perf_flags_if_tpu()

    import jax

    from kubeflow_tpu.models.config import preset

    if jax.default_backend() != "tpu":
        attn = "xla"               # interpret-mode kernels are CI-only
    cfg = preset(
        "llama3-8b",
        n_layers=8, hidden=2048, n_heads=32, n_kv_heads=8, head_dim=64,
        mlp_dim=8192, vocab_size=32000, max_seq_len=2048,
        remat_policy=remat, fused_kernels=fused,
    )
    t_c0 = time.perf_counter()
    out = measure_train_rate(
        cfg, batch, k_dispatch=kd, warm_disp=warm_disp, disp=disp,
        mu_dtype=None if mu == "none" else mu, attn_impl=attn)
    wall = time.perf_counter() - t_c0
    return {
        "remat": remat, "attn": attn, "batch": batch, "k": kd, "mu": mu,
        "fused": fused,
        **{k: out[k] for k in ("tok_s_chip", "step_ms", "mfu", "loss",
                               "segments", "spread_pct")},
        "wall_s": round(wall, 1),
    }


def main():
    if len(sys.argv) >= 5 and sys.argv[1] == "--one":
        remat, attn, batch = sys.argv[2], sys.argv[3], int(sys.argv[4])
        kd = int(sys.argv[5]) if len(sys.argv) > 5 else 1
        mu = sys.argv[6] if len(sys.argv) > 6 else "none"
        fused = sys.argv[7] if len(sys.argv) > 7 else "auto"
        print(json.dumps(run_one(remat, attn, batch, kd, mu, fused)))
        return

    grid = GRID
    if len(sys.argv) >= 3 and sys.argv[1] == "--fused":
        # Just the fused A/B half of the grid, one side: the quick re-check
        # after touching the kernels.
        grid = [row for row in GRID if row[5] == sys.argv[2]
                and row[0] == "dots_flash"]

    for remat, attn, batch, kd, mu, fused in grid:
        cmd = [sys.executable, __file__, "--one", remat, attn, str(batch),
               str(kd), mu, fused]
        t0 = time.perf_counter()
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=900)
        except subprocess.TimeoutExpired:
            print(json.dumps({"remat": remat, "attn": attn, "batch": batch,
                              "k": kd, "fused": fused, "failed": True,
                              "err": "timeout 900s"}),
                  flush=True)
            continue
        wall = round(time.perf_counter() - t0, 1)
        if proc.returncode == 0 and proc.stdout.strip():
            print(proc.stdout.strip().splitlines()[-1], flush=True)
        else:
            err = (proc.stderr or "")[-300:].replace("\n", " | ")
            print(json.dumps({"remat": remat, "attn": attn, "batch": batch,
                              "k": kd, "mu": mu, "fused": fused,
                              "failed": True, "wall_s": wall, "err": err}),
                  flush=True)


if __name__ == "__main__":
    main()
