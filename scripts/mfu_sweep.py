"""On-chip MFU sweep: time the full train step across remat/attn/batch grids.

Each config runs in a subprocess (the axon compile helper can 500 on big
programs; isolation keeps one failure from killing the sweep). Prints one
JSON line per config.

Usage:
    python scripts/mfu_sweep.py               # run the default grid
    python scripts/mfu_sweep.py --one nothing_saveable xla 4   # single config
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GRID = [
    # (remat_policy, attn_impl, per_chip_batch)
    ("nothing_saveable", "xla", 4),      # round-1 baseline
    ("dots_no_batch", "xla", 4),
    ("dots_no_batch", "pallas", 4),
    ("nothing_saveable", "pallas", 4),
    ("none", "pallas", 4),
    ("none", "xla", 4),
    ("dots_no_batch", "xla", 8),
    ("none", "pallas", 8),
    ("dots_no_batch", "pallas", 8),
]


def run_one(remat: str, attn: str, batch: int, steps: int = 8, warmup: int = 2):
    import jax

    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.runtime.mesh import build_mesh
    from kubeflow_tpu.runtime.topology import detect_local_cluster
    from kubeflow_tpu.train.data import DataConfig, make_data_source
    from kubeflow_tpu.train.optim import OptimizerConfig
    from kubeflow_tpu.train.step import setup_train

    devices = jax.devices()
    n = len(devices)
    cfg = preset(
        "llama3-8b",
        n_layers=8, hidden=2048, n_heads=32, n_kv_heads=8, head_dim=64,
        mlp_dim=8192, vocab_size=32000, max_seq_len=2048,
        remat_policy=remat,
    )
    mesh = build_mesh({"fsdp": n}, devices)
    data_cfg = DataConfig(vocab_size=cfg.vocab_size, seq_len=cfg.max_seq_len,
                          global_batch=batch * n)
    source = make_data_source(data_cfg)
    task = setup_train(cfg, OptimizerConfig(total_steps=warmup + steps), mesh,
                       attn_impl=attn)

    def step(i, state):
        b = jax.device_put(source.batch_at(i), task.batch_sharding)
        state, metrics = task.step_fn(state, b)
        return state, float(metrics["loss"])  # host fetch = the only fence

    state = task.state
    t_c0 = time.perf_counter()
    for i in range(warmup):
        state, loss = step(i, state)
    compile_s = time.perf_counter() - t_c0

    t0 = time.perf_counter()
    for i in range(warmup, warmup + steps):
        state, loss = step(i, state)
    dt = time.perf_counter() - t0

    tokens_per_step = data_cfg.global_batch * data_cfg.seq_len
    tps_chip = tokens_per_step * steps / dt / n
    gen = detect_local_cluster().slices[0].gen
    mfu = (cfg.flops_per_token() * tps_chip) / (gen.bf16_tflops * 1e12)
    return {
        "remat": remat, "attn": attn, "batch": batch,
        "tok_s_chip": round(tps_chip, 1),
        "step_ms": round(dt / steps * 1e3, 2),
        "mfu": round(mfu, 4),
        "loss": round(loss, 4),
        "compile_s": round(compile_s, 1),
    }


def main():
    if len(sys.argv) >= 5 and sys.argv[1] == "--one":
        remat, attn, batch = sys.argv[2], sys.argv[3], int(sys.argv[4])
        steps = int(sys.argv[5]) if len(sys.argv) > 5 else 8
        print(json.dumps(run_one(remat, attn, batch, steps=steps)))
        return

    for remat, attn, batch in GRID:
        cmd = [sys.executable, __file__, "--one", remat, attn, str(batch)]
        t0 = time.perf_counter()
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=900)
        except subprocess.TimeoutExpired:
            print(json.dumps({"remat": remat, "attn": attn, "batch": batch,
                              "failed": True, "err": "timeout 900s"}),
                  flush=True)
            continue
        wall = round(time.perf_counter() - t0, 1)
        if proc.returncode == 0 and proc.stdout.strip():
            line = proc.stdout.strip().splitlines()[-1]
            print(line, flush=True)
        else:
            err = (proc.stderr or "")[-400:].replace("\n", " | ")
            print(json.dumps({"remat": remat, "attn": attn, "batch": batch,
                              "failed": True, "wall_s": wall, "err": err}),
                  flush=True)


if __name__ == "__main__":
    main()
