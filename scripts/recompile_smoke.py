#!/usr/bin/env python
"""Recompile-sanitizer gate for smoke.sh (ISSUE 8).

Boots a paged engine under the `KFTPU_SANITIZE=recompile` watchdog,
warms it with representative traffic, marks the compile cache warm, and
replays the SAME traffic shape: the steady state must compile NOTHING.
One silent jit retrace costs minutes per step at supercluster scale
(ROADMAP open item 4) and a recompile storm in the decode hot loop
erases the pipelined-dispatch win — this stage is the runtime proof the
F6xx static rules stay honest against, end to end through the real
scheduler (admission, chunked paged prefill, multi-step decode, reap).

Asserts:
- zero steady-state recompiles on the warmed paged engine
  (`assert_no_steady_recompiles`);
- every warmup compile is ATTRIBUTED to a named call site (the
  `recompile_report()` audit payload — who traced, from where);
- the engine stays token-correct across the warm/steady phases (the
  sanitizer must observe, never perturb).

Prints one JSON object; `"recompile_smoke": "ok"` is the pass marker
smoke.sh greps for.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["KFTPU_SANITIZE"] = "recompile"

import kubeflow_tpu  # noqa: F401,E402  (maybe_install hooks the watchdog)
from kubeflow_tpu.runtime.sanitize import (  # noqa: E402
    RecompileError, mark_compile_warm, recompile_report,
    recompile_watchdog,
)

PROMPTS = [[3, 5, 7, 9, 3, 5, 7, 9], [2, 4, 6, 8, 2, 4, 6, 8],
           [11, 13, 17, 11, 13, 17, 11, 13]]


def main() -> int:
    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams

    wd = recompile_watchdog()
    checks: dict[str, bool] = {"watchdog_installed": wd is not None}
    if wd is None:
        print(json.dumps({"recompile_smoke": "FAIL", "checks": checks}))
        return 1

    eng = LLMEngine(preset("tiny"), BatchingSpec(
        max_batch_size=4, max_seq_len=128, paged=True, page_size=16))
    params = SamplingParams(max_new_tokens=16)
    warm_out = [eng.generate(p, params) for p in PROMPTS]
    mark_compile_warm()
    steady_out = [eng.generate(p, params) for p in PROMPTS]

    rep = recompile_report()
    checks["warmup_compiles_recorded"] = bool(rep["warmup"])
    checks["warmup_fully_attributed"] = all(
        e["site"] != "<unknown>" for e in rep["warmup"])
    checks["zero_steady_recompiles"] = rep["steady_count"] == 0
    try:
        wd.assert_no_steady_recompiles()
        checks["assert_passes"] = True
    except RecompileError:
        checks["assert_passes"] = False
    # greedy decode is deterministic: the warm and steady phases must
    # emit identical tokens — the sanitizer observes, never perturbs
    checks["token_identity"] = warm_out == steady_out
    eng.stop()

    ok = all(checks.values())
    print(json.dumps({
        "recompile_smoke": "ok" if ok else "FAIL",
        "checks": checks,
        "warmup_compiles": len(rep["warmup"]),
        "steady_recompiles": rep["steady"],
        "sample_attributions": rep["warmup"][:5],
    }, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
