"""Hot-loop smoke stage (scripts/smoke.sh): a short pipelined-dispatch
on/off A/B on CPU asserting CORRECTNESS + PLUMBING, never perf —

- greedy outputs token-identical with pipelining on and off, dense and
  paged (the tentpole's output contract);
- steady-state decode rounds perform zero full-array host→device uploads
  of scheduler state (the device_state counters stay at their
  construction values while rounds accumulate);
- traced decode spans carry well-formed ``host_gap_ms`` decode_round
  event attributes (the PR 3 tracer plumbing end-to-end);
- the model server's /metrics exposes the ``kftpu_engine_host_gap_seconds``
  histogram and ``kftpu_engine_dispatch_depth`` gauge, parsing under the
  exposition grammar.

Prints one JSON object; {"hotloop_smoke": "ok"} is the gate line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def mk_engine(cfg, params, *, pipelined, paged=False):
    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.serve.engine import LLMEngine

    return LLMEngine(cfg, BatchingSpec(
        max_batch_size=4, max_seq_len=128, prefill_buckets=[16, 64],
        chunked_prefill_tokens=32, paged=paged, page_size=16,
        decode_steps=4, pipelined_decode=pipelined), params=params)


def gen_all(eng, prompts, max_new, trace_parent=None):
    from kubeflow_tpu.serve.engine import SamplingParams

    sp = SamplingParams(max_new_tokens=max_new, temperature=0.0)
    reqs = [eng.submit(list(p), sp, trace_parent=trace_parent)
            for p in prompts]
    for _ in range(1200):
        eng.step()
        if all(r.done.is_set() for r in reqs):
            break
    if not all(r.done.is_set() for r in reqs):
        raise AssertionError("engine did not finish the smoke prompts")
    return [list(r.output_tokens) for r in reqs]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-new", type=int, default=48)
    args = ap.parse_args()

    import jax

    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.obs.trace import get_tracer

    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    prompts = [[5, 17, 3, 99, 42], list(range(1, 40)), [7] * 20]

    result: dict = {}

    # 1) Token identity: pipelining on/off, dense and paged.
    outputs = {}
    engines = {}
    for tag, kw in (("dense_off", {"pipelined": False}),
                    ("dense_on", {"pipelined": True}),
                    ("paged_off", {"pipelined": False, "paged": True}),
                    ("paged_on", {"pipelined": True, "paged": True})):
        eng = mk_engine(cfg, params, **kw)
        outputs[tag] = gen_all(eng, prompts, args.max_new)
        engines[tag] = eng
    for tag in ("dense_on", "paged_off", "paged_on"):
        if outputs[tag] != outputs["dense_off"]:
            result["hotloop_smoke"] = f"token mismatch: {tag}"
            print(json.dumps(result))
            return 1
    result["token_identity"] = "ok"

    # 2) Zero full uploads of scheduler state past construction.
    for tag, eng in engines.items():
        stats = eng._dstate.stats
        if eng.decode_rounds < 2:
            result["hotloop_smoke"] = f"{tag}: too few rounds to judge"
            print(json.dumps(result))
            return 1
        if stats["full_state_uploads"] != 1 or \
                stats["full_table_uploads"] != (1 if eng.paged else 0):
            result["hotloop_smoke"] = f"{tag}: full upload leak {stats}"
            print(json.dumps(result))
            return 1
        if eng.paged and eng.kv_pages_in_use() != 0:
            result["hotloop_smoke"] = f"{tag}: page leak"
            print(json.dumps(result))
            return 1
    result["state_uploads"] = {t: dict(e._dstate.stats)
                               for t, e in engines.items()}

    # 3) Traced decode rounds carry a well-formed host_gap_ms attribute.
    tracer = get_tracer()
    tracer.reset()
    eng = mk_engine(cfg, params, pipelined=True)
    with tracer.span("hotloop.smoke") as root:
        gen_all(eng, [list(range(1, 30))], args.max_new, trace_parent=root)
    gaps = []
    for t in tracer.traces():
        for s in t["spans"]:
            if s["name"] != "engine.decode":
                continue
            for ev in s.get("events", []):
                if ev["name"] == "decode_round" and "host_gap_ms" in ev:
                    gaps.append(ev["host_gap_ms"])
    bad = [g for g in gaps if not isinstance(g, (int, float)) or g < 0]
    if not gaps or bad:
        result["hotloop_smoke"] = \
            f"host_gap_ms malformed/missing (gaps={gaps[:8]}, bad={bad[:8]})"
        print(json.dumps(result))
        return 1
    if tracer.open_spans() != 0:
        result["hotloop_smoke"] = "open spans leaked"
        print(json.dumps(result))
        return 1
    result["decode_span_host_gap_ms_samples"] = len(gaps)

    # 4) /metrics exposes the hot-loop series and parses.
    from kubeflow_tpu.obs.registry import parse_exposition
    from kubeflow_tpu.serve.server import ModelServer

    srv = ModelServer("smoke", engines["dense_on"], port=0)
    try:
        text = srv.metrics_text()
        names = {n for n, _, _ in parse_exposition(text)}
        for need in ("kftpu_engine_host_gap_seconds_bucket",
                     "kftpu_engine_dispatch_depth"):
            if need not in names:
                result["hotloop_smoke"] = f"missing /metrics series {need}"
                print(json.dumps(result))
                return 1
    finally:
        srv.httpd.server_close()
    result["metrics_series"] = "ok"

    result["hotloop_smoke"] = "ok"
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
