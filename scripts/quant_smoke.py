#!/usr/bin/env python
"""Quantized-serving gate (scripts/smoke.sh): int8 KV through the whole
fabric — paged pool, in-kernel dequant, handoff wire, host tier (ISSUE
16 tentpole).

What must hold, on small paged CPU engines:

- **token band**: int8-pool greedy decode tracks the full-dtype engine
  inside the DECLARED tolerance band (quantization legitimately flips
  argmax near-ties, so identity is banded, not exact: mean per-prompt
  agreement >= 0.65, min >= 0.3 over the prompt set — one early flip
  cascades for the rest of a greedy trajectory);
- **fabric identity**: int8 prefill → v2 wire → int8 decode adoption is
  token-IDENTICAL to the int8 unified engine (same quantized KV on both
  paths — the wire/adopt rebuild may not introduce any divergence);
- **density**: at a real head dim (128), the int8 pool holds >= 1.9x
  the resident KV tokens of the full-dtype pool at equal HBM
  (tokens-per-MiB ratio off ``engine.kv_pool_density``);
- **wire bytes**: the v2 handoff payload and the tier's demote batches
  ship < 0.6x the full-dtype bytes at head dim 128 (~halved);
- **gather vs kernel A/B**: the in-kernel dequant path (pallas,
  interpret off-TPU) produces tokens IDENTICAL to gather+dequant on the
  same int8 pool (f32 config: the two dequant sites are the same math);
- **zero steady-state recompiles**: a warmed int8 engine replaying the
  same traffic shape (decode + a handoff round trip) compiles NOTHING
  (KFTPU_SANITIZE=recompile);
- **hygiene**: the quant metric series parse off the real exposition
  (the consumer half of the X7xx contract) and per-owner refcounts
  balance to zero.

Writes ``BENCH_SERVE_r05.json`` (the quantized-serving bench round);
prints one JSON object; ``{"quant_smoke": "ok"}`` is the gate line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Refcount (per-owner page books) + recompile (steady-state watchdog)
# for the whole stage.
os.environ["KFTPU_SANITIZE"] = "refcount,recompile"

#: Quant/wire series this gate consumes off the engine exposition — the
#: consumer half of the kftpu_engine_kv_quant_*/wire-bytes contract.
QUANT_SERIES = (
    "kftpu_engine_kv_quant_enabled",
    "kftpu_engine_kv_quant_tokens_per_mib",
    "kftpu_engine_kv_handoff_bytes_exported_total",
    "kftpu_engine_kv_handoff_bytes_adopted_total",
    "kftpu_engine_kv_wire_bytes_demoted_total",
    "kftpu_engine_kv_wire_bytes_promoted_total",
)

# The declared tolerance band: int8 KV legitimately flips greedy
# near-ties, so the A/B is banded agreement, never exact identity.
TOKEN_BAND_MEAN = 0.65
TOKEN_BAND_MIN = 0.30
MAX_NEW = 16

PROMPTS = [
    [5, 17, 3, 99, 42, 8, 8, 1] * 3,
    list(range(2, 34)),
    [7, 9, 11] * 9,
    [2] * 28,
    [13, 5, 13, 7, 13, 9, 13, 11] * 3,
    [101, 3, 55, 3, 101, 3, 55, 3] * 2,
    [41, 42, 43, 44] * 6,
    [9, 8, 7, 6, 5, 4, 3, 2, 1] * 3,
]


def wait(req, timeout=60.0):
    assert req.done.wait(timeout), "request never finished"
    return req


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.parse_args()

    import jax

    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.obs.registry import parse_exposition
    from kubeflow_tpu.runtime.sanitize import (
        mark_compile_warm, recompile_report, recompile_watchdog,
    )
    from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams
    from kubeflow_tpu.serve.handoff import HandoffPayload
    from kubeflow_tpu.serve.server import serving_metrics_registry

    result: dict = {}

    def fail(msg: str) -> int:
        result["quant_smoke"] = msg
        print(json.dumps(result, indent=2))
        return 1

    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    # f32 twin for the gather-vs-kernel identity A/B: both impls read
    # the SAME int8 pages, so at f32 accumulation the greedy paths match
    # exactly (bf16 would re-round the two dequant sites differently).
    fcfg = preset("tiny", vocab_size=512, dtype="float32")
    fparams = init_decoder_params(jax.random.PRNGKey(0), fcfg)

    def spec(kv=None, role="unified", impl="auto", host=0):
        return BatchingSpec(
            max_batch_size=4, max_seq_len=128, paged=True, page_size=16,
            prefill_buckets=[32], chunked_prefill_tokens=16,
            decode_steps=4, kv_cache_dtype=kv, role=role,
            paged_attn_impl=impl, host_kv_pages=host,
            prefix_index="radix",
            kv_demote_after_s=(0.05 if host else 2.0))

    def mk(c=cfg, p=None, **kw):
        eng = LLMEngine(c, spec(**kw), params=(params if p is None else p))
        eng.start()
        return eng

    sp = SamplingParams(max_new_tokens=MAX_NEW, temperature=0.0)
    engines = []
    try:
        wd = recompile_watchdog()
        if wd is None:
            return fail("recompile watchdog not installed")

        # 1) Token band: int8 pool vs full-dtype pool, banded agreement.
        eng8 = mk(kv="int8")
        eng16 = mk()
        engines += [eng8, eng16]
        agrees = []
        for prompt in PROMPTS:
            r8 = wait(eng8.submit(list(prompt), sp))
            r16 = wait(eng16.submit(list(prompt), sp))
            got, want = list(r8.output_tokens), list(r16.output_tokens)
            agrees.append(sum(a == b for a, b in zip(got, want))
                          / max(len(want), 1))
        band = {"mean_agreement": round(sum(agrees) / len(agrees), 3),
                "min_agreement": round(min(agrees), 3),
                "declared_mean": TOKEN_BAND_MEAN,
                "declared_min": TOKEN_BAND_MIN,
                "prompts": len(PROMPTS), "max_new": MAX_NEW}
        result["token_band"] = band
        if band["mean_agreement"] < TOKEN_BAND_MEAN \
                or band["min_agreement"] < TOKEN_BAND_MIN:
            return fail(f"int8 drifted outside the declared band: {band}")

        # 2) Fabric identity: int8 prefill → v2 wire → int8 decode must
        #    equal the int8 unified engine token for token.
        # Fresh unified engine: eng8's warm prefix cache would replay
        # its prompts down the prefix-hit path, whose bf16 padding
        # differs from the cold chunked prefill the disagg pair runs —
        # an LSB there legitimately flips a later greedy near-tie.
        uni8 = mk(kv="int8")
        pre8 = mk(kv="int8", role="prefill")
        dec8 = mk(kv="int8", role="decode")
        engines += [uni8, pre8, dec8]
        wire8 = 0
        for prompt in PROMPTS[:4]:
            want = list(wait(uni8.submit(list(prompt), sp)).output_tokens)
            p_req = wait(pre8.submit(list(prompt), sp))
            if p_req.finish_reason != "handoff":
                return fail(f"prefill engine did not hand off: "
                            f"{p_req.finish_reason}")
            blob = p_req.handoff.to_wire()
            wire8 += len(blob)
            payload = HandoffPayload.from_wire(blob)
            if payload.cache_dtype != "int8":
                return fail("v2 wire lost the cache-dtype tag")
            d_req = wait(dec8.submit_handoff(payload))
            got = [payload.first_token] + list(d_req.output_tokens)
            pre8.complete_handoff(p_req.id)
            if got != want:
                return fail(f"fabric identity broken: {got} != {want}")
        result["fabric_identity"] = "ok"

        # 3) Density + wire bytes at a real head dim (128).
        dcfg = preset("tiny", vocab_size=512, head_dim=128)
        dparams = init_decoder_params(jax.random.PRNGKey(1), dcfg)
        d8 = mk(dcfg, dparams, kv="int8", role="prefill", host=32)
        d16 = mk(dcfg, dparams, role="prefill", host=32)
        engines += [d8, d16]
        den8 = d8.kv_pool_density()
        den16 = d16.kv_pool_density()
        ratio = den8["tokens_per_mib"] / den16["tokens_per_mib"]
        result["density"] = {
            "head_dim": 128,
            "int8_tokens_per_mib": round(den8["tokens_per_mib"], 1),
            "full_tokens_per_mib": round(den16["tokens_per_mib"], 1),
            "resident_tokens_at_equal_hbm_x": round(ratio, 3),
        }
        if ratio < 1.9:
            return fail(f"density win below 1.9x: {result['density']}")
        # Handoff wire bytes: same prompt, both pools, payload sizes.
        prompt = list(range(3, 43))
        h8 = wait(d8.submit(list(prompt), sp))
        h16 = wait(d16.submit(list(prompt), sp))
        hb8, hb16 = h8.handoff.wire_bytes, h16.handoff.wire_bytes
        d8.complete_handoff(h8.id)
        d16.complete_handoff(h16.id)
        # Tier wire bytes: let both engines demote the released pages,
        # then compare bytes-per-demoted-page.
        deadline = time.monotonic() + 20.0
        while (d8.kv_tier_stats()["pages_demoted"] == 0
               or d16.kv_tier_stats()["pages_demoted"] == 0):
            time.sleep(0.02)
            if time.monotonic() > deadline:
                return fail("host tier never demoted on the Dh=128 pair")
        t8, t16 = d8.kv_tier_stats(), d16.kv_tier_stats()
        m8 = t8["demote_wire_bytes"] / t8["pages_demoted"]
        m16 = t16["demote_wire_bytes"] / t16["pages_demoted"]
        result["wire_bytes"] = {
            "handoff_int8": hb8, "handoff_full": hb16,
            "handoff_ratio": round(hb8 / hb16, 3),
            "demote_per_page_int8": round(m8, 1),
            "demote_per_page_full": round(m16, 1),
            "demote_ratio": round(m8 / m16, 3),
        }
        if hb8 / hb16 > 0.6 or m8 / m16 > 0.6:
            return fail(f"wire bytes not ~halved: {result['wire_bytes']}")

        # 4) Gather vs in-kernel dequant A/B on the SAME int8 pool
        #    (f32 config → exact identity; wall time reported only —
        #    interpret mode is not a perf statement).
        g8 = mk(fcfg, fparams, kv="int8", impl="gather")
        k8 = mk(fcfg, fparams, kv="int8", impl="pallas")
        engines += [g8, k8]
        ab = {}
        outs = {}
        for name, eng in (("gather", g8), ("kernel", k8)):
            t0 = time.perf_counter()
            outs[name] = [list(wait(eng.submit(list(p), sp)).output_tokens)
                          for p in PROMPTS[:3]]
            ab[name + "_s"] = round(time.perf_counter() - t0, 3)
        result["gather_vs_kernel"] = ab
        if outs["gather"] != outs["kernel"]:
            return fail("in-kernel dequant diverged from gather+dequant")

        # 5) Zero steady-state recompiles: replay the SAME traffic shape
        #    (decode + a handoff round trip) on the warmed engines.
        #    Nothing is constructed after the warm mark.
        warm_prompt = PROMPTS[0]
        p_req = wait(pre8.submit(list(warm_prompt), sp))
        d_req = wait(dec8.submit_handoff(p_req.handoff))
        pre8.complete_handoff(p_req.id)
        mark_compile_warm()
        r8 = wait(eng8.submit(list(warm_prompt), sp))
        p_req2 = wait(pre8.submit(list(warm_prompt), sp))
        d_req2 = wait(dec8.submit_handoff(p_req2.handoff))
        pre8.complete_handoff(p_req2.id)
        if list(d_req2.output_tokens) != list(d_req.output_tokens):
            return fail("steady-state handoff replay changed output")
        rep = recompile_report()
        result["recompiles"] = {"warmup": len(rep["warmup"]),
                               "steady": rep["steady_count"]}
        if rep["steady_count"] != 0:
            return fail(f"steady-state recompiles: {rep['steady']}")
        _ = r8

        # 6) Hygiene: quant series parse off the real exposition;
        #    per-owner books balance to zero everywhere.
        text = serving_metrics_registry(
            [("q", eng8), ("pre", pre8), ("dec", dec8),
             ("d128", d8)]).render()
        names = {n for n, _, _ in parse_exposition(text)}
        missing = [s for s in QUANT_SERIES if s not in names]
        if missing:
            return fail(f"quant series missing from exposition: {missing}")
        vals = {(n, lab.get("model")): v
                for n, lab, v in parse_exposition(text)}
        if vals[("kftpu_engine_kv_quant_enabled", "q")] != 1:
            return fail("quant_enabled gauge not set on the int8 engine")
        if vals[("kftpu_engine_kv_handoff_bytes_exported_total",
                 "pre")] <= 0:
            return fail("handoff wire bytes never counted")
        for eng in engines:
            deadline = time.monotonic() + 20.0
            while eng.kv_pages_in_use() > 0:
                time.sleep(0.02)
                if time.monotonic() > deadline:
                    return fail("KV pages failed to drain")
            report = eng._allocator.leak_report_by_owner()
            if report:
                return fail(f"per-owner page leaks: {report}")
            eng._allocator.assert_quiescent()
        result["hygiene"] = "ok"

        bench = {
            "bench": "serve_r05_int8_kv_fabric",
            "model": "tiny-cpu-smoke",
            "token_band": band,
            "density": result["density"],
            "wire_bytes": result["wire_bytes"],
            "gather_vs_kernel": ab,
            "recompiles": result["recompiles"],
            "handoff_wire_bytes_total_int8": wire8,
        }
        with open(os.path.join(REPO, "BENCH_SERVE_r05.json"), "w") as f:
            json.dump(bench, f, indent=2)
            f.write("\n")
        result["quant_smoke"] = "ok"
        print(json.dumps(result, indent=2))
        return 0
    finally:
        for eng in engines:
            eng.stop()


if __name__ == "__main__":
    sys.exit(main())
