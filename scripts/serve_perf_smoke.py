#!/usr/bin/env python
"""Serving-perf gate (scripts/smoke.sh): trace-driven scenario matrix +
thresholded regression check — the serving analogue of the train bench
gate (ISSUE 11).

Replays the canonical 3-scenario loadgen matrix (uniform Poisson /
bursty multi-QoS / shared-prefix on the paged prefix-cache engine)
open-loop over the FULL protocol path — HTTP SSE against a real
``ModelServer``, QoS on the ``X-Kftpu-Qos`` header, trace context on
``X-Kftpu-Trace`` — and gates on:

- **two-segment agreement**: each scenario runs two back-to-back
  measured segments after a warm segment; the segments must agree on
  req/s and TTFT p95 within the noise band derived from their own
  spread (``loadgen.gate.noise_band_pct`` — the bench.py methodology);
- **seeded regression detection**: an artificially throttled dispatch
  (a sleep wedged into ``engine.step``) replayed on the uniform
  scenario MUST breach the threshold and the failure must carry the
  attribution diff naming where the latency went — a comparator that
  cannot see a planted regression gates nothing;
- **attribution completeness**: engine-internal signals (queue-delay
  p95, host gap, per-class shed/preempt counters) joined from the real
  ``/metrics`` exposition, per-phase (queued/prefill/decode) span
  breakdowns with nonzero trace coverage, per-class rows for BOTH QoS
  classes in the bursty scenario, and the measured shared-prefix
  overlap within tolerance of the declared fraction;
- **hygiene**: ``open_spans() == 0`` after every segment (the
  quiescence invariant), zero leaked KV pages on the paged engine, the
  ``kftpu_loadgen_*`` report registry passing the metric-name lint and
  the exposition grammar, and ``/debug/traces?slowest=N`` surfacing the
  per-phase rollup.

Writes the measured matrix to ``BENCH_SERVE_r01.json`` at the repo root
(the serving twin of ``BENCH_r0x.json`` — one row per scenario with the
full attribution report), prints one JSON object;
``{"serve_perf_smoke": "ok"}`` is the gate line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: Loadgen report series the stage consumes off the rendered registry —
#: the consumer half of the kftpu_loadgen_* metric contract (X7xx).
LOADGEN_SERIES = (
    "kftpu_loadgen_requests_total",
    "kftpu_loadgen_requests_failed_total",
    "kftpu_loadgen_req_per_sec",
    "kftpu_loadgen_offered_req_per_sec",
    "kftpu_loadgen_ttft_p50_ms",
    "kftpu_loadgen_ttft_p95_ms",
    "kftpu_loadgen_tpot_p50_ms",
    "kftpu_loadgen_goodput_ratio",
    "kftpu_loadgen_schedule_lag_p95_ms",
)

PROMPT_LEN = 32
MAX_NEW = 8


def make_server(*, paged: bool):
    import jax

    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.serve.engine import LLMEngine
    from kubeflow_tpu.serve.server import ModelServer

    cfg = preset("tiny")
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    engine = LLMEngine(cfg, BatchingSpec(
        max_batch_size=8, max_seq_len=cfg.max_seq_len,
        prefill_buckets=[32, 64], chunked_prefill_tokens=32,
        paged=paged, page_size=16, decode_steps=4), params=params)
    srv = ModelServer("perf-smoke", engine, port=0)
    srv.start()
    return srv, cfg


def scrape(url: str, path: str = "/metrics") -> str:
    with urllib.request.urlopen(url + path, timeout=10.0) as r:
        return r.read().decode()


def warm_server(srv, cfg) -> None:
    """Compile the whole dispatch set BEFORE measuring (the bench_serve
    methodology: compile time never lands in a measured window). The
    lazy set is width-shaped: prefill GROUPS and first-token sampler
    batches compile per power-of-two size, so a measured segment whose
    Poisson arrivals happen to co-batch 2 requests for the first time
    eats a fresh ~0.5s compile mid-measurement. Bunches of each p2 depth
    per bucket, submitted back-to-back and drained between bunches, in
    two passes (a racy admit split in pass 1 leaves widths pass 2
    covers)."""
    from kubeflow_tpu.serve.engine import SamplingParams

    eng = srv.engine
    params = SamplingParams(max_new_tokens=MAX_NEW, temperature=0.0)
    for _ in range(2):
        for bucket in (32, 64):
            for depth in (8, 4, 2, 1):
                reqs = [eng.submit(
                    [1 + (7 * i + j) % (cfg.vocab_size - 2)
                     for j in range(bucket - 2)], params)
                    for i in range(depth)]
                for r in reqs:
                    r.result(timeout=60.0)


def run_segment(srv, cfg, scenario):
    """One measured segment: fresh engine metrics + trace ring, replay,
    scrape, report. Returns (report, open_spans_after)."""
    from kubeflow_tpu.loadgen import ServerTarget, build_report, run_scenario
    from kubeflow_tpu.obs.trace import get_tracer
    from kubeflow_tpu.serve.engine import EngineMetrics

    tracer = get_tracer()
    tracer.reset()
    srv.engine.metrics = EngineMetrics()
    run = run_scenario(ServerTarget(srv.url), scenario,
                       vocab_size=cfg.vocab_size,
                       max_prompt_len=cfg.max_seq_len - MAX_NEW - 2,
                       tracer=tracer)
    text = scrape(srv.url)
    rep = build_report(run, metrics_text=text, tracer=tracer)
    # The scheduler may still be closing the final request's span when
    # the last stream chunk lands client-side; settle briefly.
    deadline = time.monotonic() + 5.0
    while tracer.open_spans() and time.monotonic() < deadline:
        time.sleep(0.02)
    return rep, run, tracer.open_spans()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=16,
                    help="per measured segment")
    # Offered rate sits clearly UNDER the tiny CPU engine's ~8 req/s
    # capacity: the gate measures latency at a sustainable rate (the
    # regime where two segments agree), not queueing collapse — the
    # seeded throttle below drives capacity under the offered rate,
    # which is exactly the regression shape the gate must catch.
    ap.add_argument("--rate", type=float, default=5.0)
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "BENCH_SERVE_r01.json"))
    args = ap.parse_args()

    from kubeflow_tpu.loadgen import (
        build_schedule, compare_matrix, measured_prefix_overlap,
        noise_band_pct, report_registry, spread_pct, standard_matrix,
    )
    from kubeflow_tpu.obs.registry import parse_exposition

    result: dict = {}

    def fail(msg: str) -> int:
        result["serve_perf_smoke"] = msg
        print(json.dumps(result, indent=2))
        return 1

    # multi_turn gates in scripts/prefix_cache_smoke.py (the tiered-KV
    # stage, on a radix+host-tier engine) and multi_adapter in
    # scripts/lora_smoke.py (on a LoRA-enabled engine with registered
    # adapters) — excluded here to keep this stage inside its wall-time
    # budget and its engines adapter-free.
    matrix = [s for s in standard_matrix(
        num_requests=args.requests, rate_rps=args.rate,
        prompt_len=PROMPT_LEN, max_new=MAX_NEW, slo_ttft_ms=5000.0)
        if s.name not in ("multi_turn", "multi_adapter")]

    # 1) Measure: per scenario, warm + two measured segments. The
    #    shared-prefix scenario runs on the paged prefix-cache engine
    #    (its traffic property is the cache's whole case); the others on
    #    the dense engine.
    rows = []
    baseline_rows = []
    candidate_rows = []
    bands: dict = {}
    for sc in matrix:
        paged = sc.name == "shared_prefix"
        srv, cfg = make_server(paged=paged)
        try:
            warm_server(srv, cfg)
            run_segment(srv, cfg, sc)        # settle: the scenario's own mix
            segs = []
            for attempt in range(3):
                rep, run, open_spans = run_segment(srv, cfg, sc)
                if open_spans:
                    return fail(f"{sc.name}: {open_spans} leaked open "
                                "spans after a full scenario run")
                segs.append((rep, run))
                if len(segs) < 2:
                    continue
                a, b = segs[-2][0], segs[-1][0]
                if max(spread_pct(a["req_s"], b["req_s"]),
                       spread_pct(a["ttft_ms"].get("p95", 0.0),
                                  b["ttft_ms"].get("p95", 0.0))) <= 25.0:
                    break
                # One straggler compile can still land in a measured
                # segment (a width the warm races missed); it compiles
                # exactly once, so the LAST two segments converge — keep
                # them and let the spread-derived band tell the truth.
            segs = segs[-2:]
            if paged and srv.engine.kv_pages_in_use() != 0:
                return fail(f"{sc.name}: leaked KV pages")
            # /debug/traces?slowest=N must carry the per-phase rollup
            # (the surface the loadgen's breakdown rides in production).
            doc = json.loads(scrape(srv.url, "/debug/traces?slowest=4"))
        finally:
            srv.stop()
        rep_a, rep_b = segs[0][0], segs[1][0]
        for rep in (rep_a, rep_b):
            n_ok = rep["by_status"].get("ok", 0)
            if n_ok < args.requests * 0.75:
                return fail(f"{sc.name}: only {n_ok}/{args.requests} "
                            f"requests completed: {rep['by_status']}")
            if rep["phases"].get("trace_coverage", 0) < n_ok * 0.5:
                return fail(f"{sc.name}: phase breakdown covers "
                            f"{rep['phases'].get('trace_coverage')} of "
                            f"{n_ok} requests")
            if "engine" not in rep or "queue_delay_p95_ms" not in \
                    rep["engine"]:
                return fail(f"{sc.name}: engine attribution missing")
        traced = [t for t in doc.get("traces", []) if t.get("phases")]
        if not traced or not any("decode_ms" in t["phases"]
                                 for t in traced):
            return fail(f"{sc.name}: /debug/traces?slowest=N has no "
                        "per-phase rollup")
        if sc.name == "bursty_qos":
            classes = set((rep_b.get("engine", {}).get("qos") or {}))
            if not {"interactive", "batch"} <= classes:
                return fail(f"bursty_qos: per-class engine attribution "
                            f"incomplete: {sorted(classes)}")
        if sc.name == "shared_prefix":
            sched = build_schedule(sc, vocab_size=cfg.vocab_size,
                                   max_prompt_len=cfg.max_seq_len
                                   - MAX_NEW - 2)
            got = measured_prefix_overlap(
                [r.prompt_tokens for r in sched])
            if abs(got - sc.prefix_overlap) > 0.15:
                return fail(f"shared_prefix: measured overlap {got:.2f} "
                            f"vs declared {sc.prefix_overlap}")
            result["measured_prefix_overlap"] = round(got, 3)
        # Noise band from the two-segment spread (bench.py methodology);
        # the segments themselves must agree within it.
        sp_req = spread_pct(rep_a["req_s"], rep_b["req_s"])
        ttfts = [r["ttft_ms"].get("p95", 0.0) for r in (rep_a, rep_b)]
        band = noise_band_pct([sp_req, spread_pct(*ttfts)])
        bands[sc.name] = band
        baseline_rows.append(rep_a)
        candidate_rows.append(rep_b)
        rows.append({
            "metric": f"serve_scenario_req_per_sec[tiny,{sc.name},"
                      f"r{args.rate:g},n{args.requests}"
                      f"{',paged' if paged else ''}]",
            "value": round((rep_a["req_s"] + rep_b["req_s"]) / 2, 3),
            "unit": "req/s",
            "vs_baseline": 1.0,
            "detail": {"segments": [rep_a, rep_b],
                       "spread_pct": round(sp_req, 1),
                       "noise_band_pct": round(band, 1)},
        })
    verdict = compare_matrix(baseline_rows, candidate_rows, bands=bands)
    if not verdict["ok"]:
        result["segment_disagreement"] = verdict
        return fail("two-segment spread breached its own noise band")
    result["scenarios"] = {r["metric"]: r["value"] for r in rows}
    result["noise_bands_pct"] = {k: round(v, 1) for k, v in bands.items()}

    # 2) Seeded regression: throttle the dispatch and the gate MUST see
    #    it — req/s down and/or TTFT p95 up beyond every band above.
    srv, cfg = make_server(paged=False)
    try:
        orig_step = srv.engine.step

        def throttled_step():
            time.sleep(0.08)
            return orig_step()

        warm_server(srv, cfg)                # warm at full speed first
        srv.engine.step = throttled_step
        slow_rep, _, _ = run_segment(srv, cfg, matrix[0])
    finally:
        srv.stop()
    slow_verdict = compare_matrix([baseline_rows[0]], [slow_rep],
                                  bands=bands)
    if slow_verdict["ok"]:
        return fail("seeded throttled-dispatch regression NOT flagged "
                    f"(baseline req/s {baseline_rows[0]['req_s']}, "
                    f"throttled {slow_rep['req_s']}, "
                    f"band {bands['uniform']:.0f}%)")
    reg = slow_verdict["regressions"][0]
    if "diff" not in reg or "engine" not in reg["diff"]:
        return fail("regression verdict lacks the attribution diff")
    result["seeded_regression"] = {
        "problems": reg["problems"],
        "baseline_req_s": baseline_rows[0]["req_s"],
        "throttled_req_s": slow_rep["req_s"],
        "throttled_queue_delay_p95_ms":
            slow_rep.get("engine", {}).get("queue_delay_p95_ms"),
    }

    # 3) The loadgen's own report registry: lints clean, parses, and
    #    carries every series this stage (its in-scan consumer) reads.
    reg2 = report_registry(candidate_rows)
    problems = reg2.lint()
    if problems:
        return fail(f"loadgen registry lint: {problems}")
    names = {n for n, _, _ in parse_exposition(reg2.render())}
    missing = [n for n in LOADGEN_SERIES if n not in names]
    if missing:
        return fail(f"loadgen series missing from exposition: {missing}")
    result["loadgen_series"] = "ok"

    # 4) Trajectory artifact — the serving BENCH_r0x twin.
    with open(args.out, "w") as f:
        json.dump({"schema": 1,
                   "generated_by": "scripts/serve_perf_smoke.py",
                   "config": {"requests_per_segment": args.requests,
                              "rate_rps": args.rate,
                              "prompt_len": PROMPT_LEN,
                              "max_new": MAX_NEW},
                   "rows": rows}, f, indent=2)
        f.write("\n")
    result["artifact"] = os.path.relpath(args.out, REPO)

    result["serve_perf_smoke"] = "ok"
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
