#!/usr/bin/env python
"""Multi-tenant LoRA serving gate (scripts/smoke.sh): one engine, N
adapters over shared base weights — token-exact, bounded-degradation,
recompile-free, leak-free (ISSUE 14).

What must hold, on small f32 CPU engines:

- **token identity**: greedy decode under every registered adapter —
  dense AND paged — is token-identical to a single-model engine running
  the MERGED weights, while base traffic through the same batched
  dispatch matches a LoRA-free engine exactly;
- **the degradation band**: the ``multi_adapter`` loadgen scenario at
  8 / 32 / 64 concurrent adapters (zipf-skewed mix over 16 packed
  slots — the 64 case churns hot-loads/evictions continuously) must
  keep decode tok/s within ``TOKS_DROP_MAX_PCT`` and TTFT p95 within
  ``TTFT_RISE_MAX_PCT`` of the single-model baseline at the same
  offered load (best-of-two segments per side, the anti-noise
  discipline);
- **zero steady-state recompiles**: the whole stage runs under
  ``KFTPU_SANITIZE=refcount,recompile``; after the warm segments the
  compile cache is marked warm and every measured segment — including
  the full 64-adapter churn — must compile NOTHING (the packed buffer
  is the fixed dispatch shape; churn swaps slot contents, never
  shapes);
- **seeded adapter-churn wedge**: a sleep wedged into the registry's
  hot-load (exactly how a slow artifact-store pull would starve
  admissions) MUST be flagged by the loadgen gate with the attribution
  diff naming the ``adapter_load`` phase / load counters;
- **hygiene**: per-owner zero leaks for BOTH resources — KV pages and
  adapter-slot references — after every run (evict-under-traffic
  included), and a SIGKILL mid-hot-load behind the model-id router
  resolves every request (survivor serves the adapter; the victim's
  audit balances to zero per owner).

Writes ``BENCH_SERVE_r04.json`` (the multi-adapter serving bench
round); prints one JSON object; ``{"lora_smoke": "ok"}`` is the gate
line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Refcount (owner-stamped page + adapter references) AND recompile
# watchdog on for the whole stage.
os.environ.setdefault("KFTPU_SANITIZE", "refcount,recompile")

#: Adapter series this gate consumes off the engine exposition — the
#: consumer half of the kftpu_engine_adapter* metric contract (X7xx).
ADAPTER_SERIES = (
    "kftpu_engine_adapters_resident",
    "kftpu_engine_adapter_loads_total",
    "kftpu_engine_adapter_evictions_total",
)

#: The declared degradation band vs single-model at the same offered
#: load (acceptance criterion: "degrade ≤ a declared threshold").
TOKS_DROP_MAX_PCT = 40.0
TTFT_RISE_MAX_PCT = 150.0

ADAPTER_COUNTS = (8, 32, 64)
LORA_SLOTS = 16
RANK = 4
PROMPT_LEN = 32
MAX_NEW = 12


def mk_cfg():
    from kubeflow_tpu.models.config import preset

    # f32: the factored delta and the merged matmul are mathematically
    # equal; bf16 would round the two paths differently (argmax flips
    # on near-ties), and CPU bf16 is emulated anyway.
    return preset("tiny", dtype="float32")


def mk_engine(cfg, params, *, n_register: int = 0, slots: int = LORA_SLOTS,
              seed0: int = 100):
    import jax

    from kubeflow_tpu.core.serving import BatchingSpec, LoRASpec
    from kubeflow_tpu.serve.engine import LLMEngine
    from kubeflow_tpu.serve.lora import AdapterSpec, init_adapter_weights

    lora = (LoRASpec(max_adapters=slots, rank=RANK) if n_register
            else LoRASpec())
    eng = LLMEngine(cfg, BatchingSpec(
        max_batch_size=8, max_seq_len=128, prefill_buckets=[64],
        paged=True, page_size=16, chunked_prefill_tokens=32,
        decode_steps=8, lora=lora), params=params)
    for i in range(n_register):
        eng._lora.register(AdapterSpec(
            f"adpt-{i}", rank=RANK,
            weights=init_adapter_weights(jax.random.PRNGKey(seed0 + i),
                                         cfg, RANK)))
    return eng


def scenario_for(n_adapters: int, requests: int, rate: float):
    from kubeflow_tpu.loadgen import standard_matrix

    return next(s for s in standard_matrix(
        num_requests=requests, rate_rps=rate, prompt_len=PROMPT_LEN,
        max_new=MAX_NEW, slo_ttft_ms=5000.0, adapter_skew=0.5,
        adapter_ids=tuple(f"adpt-{i}" for i in range(n_adapters)))
        if s.name == "multi_adapter")


def warm_widths(engine, cfg, adapters=()):
    """Compile the width-shaped dispatch set BEFORE measuring (the
    serve_perf_smoke discipline): first-token sampler batches compile
    per power-of-two size, so a measured segment whose arrivals happen
    to co-complete N chunked prefills for the first time would eat a
    fresh compile mid-measurement. Two passes per depth (a racy admit
    split in pass 1 leaves widths pass 2 covers); adapter traffic rides
    along so the LoRA dispatch variants warm too."""
    from kubeflow_tpu.serve.engine import SamplingParams

    params = SamplingParams(max_new_tokens=4, temperature=0.0)
    names = list(adapters) or [None]
    for _ in range(2):
        for depth in (8, 4, 2, 1):
            reqs = [engine.submit(
                [1 + (7 * i + j) % (cfg.vocab_size - 2)
                 for j in range(PROMPT_LEN)], params,
                adapter=names[i % len(names)])
                for i in range(depth)]
            for r in reqs:
                r.result(timeout=60.0)


def run_segment(engine, sc, cfg):
    from kubeflow_tpu.loadgen import EngineTarget, build_report, run_scenario
    from kubeflow_tpu.obs.trace import get_tracer
    from kubeflow_tpu.serve.server import serving_metrics_registry

    tracer = get_tracer()
    tracer.reset()
    run = run_scenario(EngineTarget(engine), sc, vocab_size=cfg.vocab_size,
                       max_prompt_len=100, tracer=tracer)
    text = serving_metrics_registry([("lora", engine)]).render()
    return build_report(run, metrics_text=text, tracer=tracer), text


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--rate", type=float, default=24.0)
    args = ap.parse_args()

    import jax

    from kubeflow_tpu.loadgen import compare_scenario, noise_band_pct, \
        spread_pct
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.obs.registry import parse_exposition
    from kubeflow_tpu.runtime.sanitize import (
        assert_no_steady_recompiles, mark_compile_warm,
    )
    from kubeflow_tpu.serve.engine import SamplingParams
    from kubeflow_tpu.serve.lora import AdapterSpec, init_adapter_weights, \
        merged_params

    result: dict = {}

    def fail(msg: str) -> int:
        result["lora_smoke"] = msg
        print(json.dumps(result, indent=2))
        return 1

    cfg = mk_cfg()
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    prompt = [(13 * i) % 250 + 1 for i in range(PROMPT_LEN)]

    # ---- 1) token identity: adapters vs merged references, dense+paged
    from kubeflow_tpu.core.serving import BatchingSpec, LoRASpec
    from kubeflow_tpu.serve.engine import LLMEngine

    ident_specs = [AdapterSpec(
        f"adpt-{i}", rank=RANK,
        weights=init_adapter_weights(jax.random.PRNGKey(100 + i), cfg,
                                     RANK)) for i in range(2)]
    for paged in (False, True):
        def mk(b_lora, p):
            return LLMEngine(cfg, BatchingSpec(
                max_batch_size=4, max_seq_len=128, prefill_buckets=[64],
                paged=paged, page_size=16, lora=b_lora), params=p)

        eng = mk(LoRASpec(max_adapters=2, rank=RANK), params)
        for s in ident_specs:
            eng._lora.register(s)
        base = eng.generate(prompt, SamplingParams(max_new_tokens=MAX_NEW))
        want_base = mk(LoRASpec(), params).generate(
            prompt, SamplingParams(max_new_tokens=MAX_NEW))
        if base != want_base:
            return fail(f"identity: base traffic diverged (paged={paged})")
        for s in ident_specs:
            req = eng.submit(prompt, SamplingParams(max_new_tokens=MAX_NEW),
                             adapter=s.name)
            while not req.done.is_set():
                eng.step()
            got = req.result(5)
            want = mk(LoRASpec(), merged_params(params, cfg, s)).generate(
                prompt, SamplingParams(max_new_tokens=MAX_NEW))
            if got != want or got == base:
                return fail(
                    f"identity: adapter {s.name} (paged={paged}) "
                    f"got={got} want={want}")
        eng._lora.assert_quiescent()
        if paged:
            eng._allocator.assert_quiescent()
    result["token_identity"] = "ok"

    # ---- 2) degradation band + recompile-free churn
    # Build + WARM every engine first (each engine owns fresh jitted
    # closures; their compiles are warmup), then mark the cache warm —
    # every measured segment after that must compile nothing.
    baseline_eng = mk_engine(cfg, params, n_register=0)
    base_sc = scenario_for(0, args.requests, args.rate)
    churn_engines = {n: mk_engine(cfg, params, n_register=n)
                     for n in ADAPTER_COUNTS}
    baseline_eng.start()
    for eng in churn_engines.values():
        eng.start()
    try:
        warm_widths(baseline_eng, cfg)
        run_segment(baseline_eng, base_sc, cfg)              # warm
        for n, eng in churn_engines.items():
            warm_widths(eng, cfg,
                        adapters=[f"adpt-{i}" for i in range(min(n, 8))])
            run_segment(eng, scenario_for(n, args.requests, args.rate),
                        cfg)                                 # warm
        mark_compile_warm()

        segs = [run_segment(baseline_eng, base_sc, cfg)[0]
                for _ in range(2)]
        base_best_toks = max(s["tokens_per_sec"] for s in segs)
        base_best_ttft = min(s["ttft_ms"].get("p95", 1e9) for s in segs)
        base_spread = spread_pct(segs[0]["tokens_per_sec"],
                                 segs[1]["tokens_per_sec"])
        result["baseline"] = {"tokens_per_sec": base_best_toks,
                              "ttft_p95_ms": base_best_ttft}

        bench_rows = []
        unwedged_64 = None
        for n, eng in churn_engines.items():
            sc = scenario_for(n, args.requests, args.rate)
            reps = [run_segment(eng, sc, cfg) for _ in range(2)]
            rep = max((r for r, _ in reps),
                      key=lambda r: r["tokens_per_sec"])
            text = reps[-1][1]
            if n == 64:
                unwedged_64 = rep
            if rep["by_status"].get("ok", 0) < args.requests * 0.9:
                return fail(f"{n} adapters: too many failures: "
                            f"{rep['by_status']}")
            toks_drop = 100.0 * (1.0 - rep["tokens_per_sec"]
                                 / max(base_best_toks, 1e-9))
            ttft = min(r["ttft_ms"].get("p95", 1e9) for r, _ in reps)
            ttft_rise = 100.0 * (ttft / max(base_best_ttft, 1e-9) - 1.0)
            row = {"adapters": n,
                   "tokens_per_sec": rep["tokens_per_sec"],
                   "ttft_p95_ms": ttft,
                   "toks_drop_pct": round(toks_drop, 1),
                   "ttft_rise_pct": round(ttft_rise, 1),
                   "adapter_report": rep.get("adapters", {}),
                   "engine_adapters": rep["engine"].get("adapters", {})}
            bench_rows.append(row)
            if toks_drop > TOKS_DROP_MAX_PCT:
                return fail(f"{n} adapters: tok/s degraded "
                            f"{toks_drop:.0f}% > {TOKS_DROP_MAX_PCT}%")
            if ttft_rise > TTFT_RISE_MAX_PCT:
                return fail(f"{n} adapters: ttft p95 rose "
                            f"{ttft_rise:.0f}% > {TTFT_RISE_MAX_PCT}%")
            from kubeflow_tpu.loadgen import build_schedule
            distinct = len({r.adapter for r in build_schedule(
                sc, vocab_size=cfg.vocab_size, max_prompt_len=100)})
            if distinct > LORA_SLOTS and not rep["engine"].get(
                    "adapters", {}).get("evictions"):
                return fail(
                    f"{n} adapters ({distinct} distinct drawn) over "
                    f"{LORA_SLOTS} slots must have evicted")
            # per-adapter client split must cover the mix
            if len(rep.get("adapters", {})) < min(n, 4):
                return fail(f"{n} adapters: per-adapter report split "
                            f"missing: {list(rep.get('adapters', {}))}")
            # X7xx consumer half: the adapter series parse off the real
            # exposition.
            names = {nm for nm, _, _ in parse_exposition(text)}
            missing = [s for s in ADAPTER_SERIES if s not in names]
            if missing:
                return fail(f"adapter series not rendered: {missing}")
            eng._lora.assert_quiescent()
            eng._allocator.assert_quiescent()
        result["degradation"] = bench_rows

        # Zero steady-state recompiles across ALL measured churn.
        try:
            assert_no_steady_recompiles()
        except Exception as exc:
            return fail(f"steady-state recompiles under churn: {exc}")
        result["recompiles_steady"] = 0

        # ---- 3) seeded adapter-churn wedge (on the warmed 64 engine —
        # no fresh compiles; the wedge is pure host latency in the
        # hot-load, exactly a slow artifact-store pull).
        eng64 = churn_engines[64]
        real_load = eng64._lora._load_slot

        def wedged_load(spec):
            time.sleep(0.25)
            return real_load(spec)

        eng64._lora._load_slot = wedged_load
        try:
            wedged_rep, _ = run_segment(
                eng64, scenario_for(64, args.requests, args.rate), cfg)
        finally:
            eng64._lora._load_slot = real_load
        band = noise_band_pct([base_spread])
        problems = compare_scenario(unwedged_64, wedged_rep,
                                    band_pct=band)
        if not problems:
            return fail("seeded adapter-load wedge NOT flagged by the "
                        f"gate (band {band:.0f}%)")
        wedge_attr = {
            "problems": problems,
            "baseline_phases": unwedged_64.get("phases", {}),
            "wedged_phases": wedged_rep.get("phases", {}),
            "wedged_loads": wedged_rep["engine"].get("adapters", {}),
        }
        if "adapter_load_ms" not in wedged_rep.get("phases", {}):
            return fail("wedge flagged but adapter_load phase missing "
                        "from the attribution")
        result["seeded_wedge"] = wedge_attr
    finally:
        baseline_eng.stop()
        for eng in churn_engines.values():
            eng.stop()

    # ---- 4) chaos: SIGKILL mid-hot-load behind the model-id router
    rc = chaos_kill_mid_hot_load(cfg, params, result, fail)
    if rc is not None:
        return rc

    # ---- 5) bench round
    bench = {
        "bench": "serve_r04_multi_adapter",
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "host": "cpu",
        "baseline": result["baseline"],
        "declared_band": {"toks_drop_max_pct": TOKS_DROP_MAX_PCT,
                          "ttft_rise_max_pct": TTFT_RISE_MAX_PCT},
        "rows": result["degradation"],
    }
    with open(os.path.join(REPO, "BENCH_SERVE_r04.json"), "w") as f:
        json.dump(bench, f, indent=2)
        f.write("\n")
    result["lora_smoke"] = "ok"
    print(json.dumps(result, indent=2, default=str))
    return 0


def chaos_kill_mid_hot_load(cfg, params, result, fail):
    """Two LoRA replicas behind the model-id router; the victim's
    hot-loads are wedged slow, and it is killed MID-LOAD. Every client
    request must still resolve (router retries/ejects onto the
    survivor), the survivor must serve the adapter, and the victim's
    audit must balance pages AND adapter references per owner."""
    import threading
    import urllib.error
    import urllib.request

    import jax

    from kubeflow_tpu.core.headers import MODEL_HEADER
    from kubeflow_tpu.serve.faults import kill_model_server
    from kubeflow_tpu.serve.lora import AdapterSpec, init_adapter_weights
    from kubeflow_tpu.serve.router import Router
    from kubeflow_tpu.serve.server import ModelServer

    def mk_server(name, load_delay=0.0):
        # register through sources so the victim's pulls can be slow
        from kubeflow_tpu.core.serving import BatchingSpec, LoRASpec
        from kubeflow_tpu.serve.engine import LLMEngine
        eng = LLMEngine(cfg, BatchingSpec(
            max_batch_size=4, max_seq_len=128, prefill_buckets=[64],
            paged=True, page_size=16, decode_steps=4,
            lora=LoRASpec(max_adapters=4, rank=RANK)), params=params)
        for i in range(4):
            w = init_adapter_weights(jax.random.PRNGKey(100 + i), cfg, RANK)

            def source(w=w):
                if load_delay:
                    time.sleep(load_delay)
                return w

            eng._lora.register(AdapterSpec(f"adpt-{i}", rank=RANK,
                                           source=source))
        srv = ModelServer(name, eng, port=0)
        srv.start()
        return srv

    survivor = mk_server("lora-a")
    victim = mk_server("lora-b", load_delay=0.6)
    router = Router(queue_timeout=5.0, eject_threshold=2, eject_period=0.5,
                    max_retries=2, upstream_timeout=30.0)
    router.set_backends({"latest": [survivor.url, victim.url]})
    router.start()

    def completion(model, timeout_s=10.0):
        body = json.dumps({"prompt": "chaos" * 4, "max_tokens": 6,
                           "timeout": timeout_s}).encode()
        req = urllib.request.Request(
            router.url + "/v1/completions", data=body,
            headers={"Content-Type": "application/json",
                     MODEL_HEADER: model})
        try:
            with urllib.request.urlopen(req, timeout=timeout_s + 5) as r:
                r.read()
                return r.status
        except urllib.error.HTTPError as exc:
            exc.read()
            return exc.code
        except OSError:
            return 502

    statuses: list[int] = []
    lock = threading.Lock()

    def client(i):
        st = completion(f"adpt-{i % 4}")
        with lock:
            statuses.append(st)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    # Kill the victim while its (wedged, 0.6 s) hot-load is in flight.
    time.sleep(0.3)
    kill_model_server(victim)
    hung = 0
    for t in threads:
        t.join(timeout=60.0)
        hung += t.is_alive()
    try:
        if hung:
            return fail(f"chaos: {hung} client(s) hung after SIGKILL")
        ok = sum(1 for s in statuses if s == 200)
        if ok < len(statuses) // 2:
            return fail(f"chaos: only {ok}/{len(statuses)} resolved 200: "
                        f"{statuses}")
        if completion("adpt-1") != 200:
            return fail("chaos: survivor does not serve the adapter "
                        "after the kill")
        # Victim audit: drive its (halted) scheduler so the reaper
        # releases stranded slots/pages/adapter refs, then balance.
        deadline = time.monotonic() + 30.0
        veng = victim.engine
        while time.monotonic() < deadline:
            veng.step()
            if veng.kv_pages_in_use() == 0 and not \
                    veng._lora.leak_report_by_owner():
                break
            time.sleep(0.05)
        veng._allocator.assert_quiescent()
        veng._lora.assert_quiescent()
        survivor.engine._allocator.assert_quiescent()
        survivor.engine._lora.assert_quiescent()
        result["chaos_kill_mid_hot_load"] = {
            "statuses": statuses, "survivor_ok": True,
            "victim_leaks_by_owner": {}}
    finally:
        router.stop()
        survivor.stop()
    return None


if __name__ == "__main__":
    sys.exit(main())
