"""Paged chunk-prefill microbench: per-chunk dispatch latency vs resident
context (VERDICT round-2 weak #4 / next #6).

Before round 3 each chunk gathered the slot's ENTIRE max_len page row, so a
long prompt paid O(max_len²/C) in gather+attention traffic. The static
context bucket (engine passes ceil((pos+C)/page), rounded to a power of
two) makes chunk cost track the tokens actually resident. This bench times
the same chunk dispatch at increasing positions, bucketed vs full-row, on
one chip.

Run: python scripts/bench_chunk_prefill.py   (prints one JSON line)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.serve.paged import paged_chunk_prefill

    # Sized down from the 0.6B bench model: the point is per-chunk cost
    # SCALING with resident context, and each distinct context bucket is a
    # fresh multi-minute compile at full size through the tunnel.
    cfg = preset("llama3-8b", n_layers=2, hidden=512, n_heads=8,
                 n_kv_heads=4, head_dim=64, mlp_dim=1024, vocab_size=1024,
                 max_seq_len=8192)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    pg, C, max_len = 128, 512, 8192
    mpp = max_len // pg
    num_pages = mpp + 8
    cache = {
        "k": jnp.zeros((cfg.n_layers, num_pages, pg, cfg.n_kv_heads,
                        cfg.head_dim), cfg.activation_dtype),
        "v": jnp.zeros((cfg.n_layers, num_pages, pg, cfg.n_kv_heads,
                        cfg.head_dim), cfg.activation_dtype),
    }
    table = jnp.asarray(np.arange(mpp, dtype=np.int32))
    tokens = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (1, C)).astype(np.int32))

    fn = jax.jit(
        lambda c, st, vl, ncp: paged_chunk_prefill(
            params, c, tokens, table, st, vl, cfg, context_pages=ncp),
        static_argnums=(3,), donate_argnums=(0,))

    def run(pos, ctx, reps=10):
        st = jnp.int32(pos)
        vl = jnp.int32(C)
        nonlocal cache
        logits, cache = fn(cache, st, vl, ctx)      # compile
        float(jnp.sum(logits))
        best = None
        for _ in range(2):   # two windows, keep the better (warmup noise)
            t0 = time.perf_counter()
            for _ in range(reps):
                logits, cache = fn(cache, st, vl, ctx)
            float(jnp.sum(logits))                   # tunnel fence
            dt = (time.perf_counter() - t0) / reps * 1e3
            best = dt if best is None else min(best, dt)
        return best

    rows = []
    from kubeflow_tpu.serve.paged import context_bucket

    for pos in (0, 3072, 7168):
        ctx = context_bucket(pos, C, pg, mpp)
        bucketed = run(pos, ctx)
        full = run(pos, mpp)
        rows.append({"pos": pos, "ctx_pages": ctx,
                     "bucketed_ms": round(bucketed, 2),
                     "full_row_ms": round(full, 2)})
        print(f"pos={pos:5d} ctx={ctx:3d}: bucketed {bucketed:7.2f} ms  "
              f"full-row {full:7.2f} ms", flush=True)
    print(json.dumps({"metric": "paged_chunk_prefill_ms_vs_context",
                      "rows": rows}))


if __name__ == "__main__":
    main()
