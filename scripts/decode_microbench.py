"""Microbench the decode dispatch path on-chip: time K-step dispatches and
the prefill program, separating model time from tunnel round-trip."""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.serve.engine import LLMEngine

    cfg = preset(
        "llama3-8b",
        n_layers=8, hidden=2048, n_heads=32, n_kv_heads=8, head_dim=64,
        mlp_dim=8192, vocab_size=32000, max_seq_len=2048)
    eng = LLMEngine(cfg, BatchingSpec(max_batch_size=16, max_seq_len=2048,
                                      prefill_buckets=[512]))
    nb = eng.num_slots

    key = jax.random.PRNGKey(0)

    def fresh_state():
        # The engine's device-resident state shape (serve/device_state.py):
        # the dispatch donates and returns it, so the loop below re-feeds
        # the advanced carry exactly like the hot loop does.
        return {
            "tokens": jnp.zeros((nb,), jnp.int32),
            "lengths": jnp.full((nb,), 600, jnp.int32),
            "live": jnp.ones((nb,), bool),
            "temps": jnp.zeros((nb,), jnp.float32),
            "top_k": jnp.zeros((nb,), jnp.int32),
            "top_p": jnp.ones((nb,), jnp.float32),
            "stops": jnp.full((nb,), -1, jnp.int32),
            "budgets": jnp.full((nb,), 10**6, jnp.int32),
        }

    for k_steps in (1, 8, 16, 32):
        state = fresh_state()
        # compile
        out, eng.cache, state = eng._decode_n(
            eng.params, eng.cache, state, key, k_steps, "greedy")
        _ = out.block_until_ready()
        _ = int(jax.device_get(out)[0, 0])  # fence
        reps = 6
        t0 = time.perf_counter()
        for _ in range(reps):
            out, eng.cache, state = eng._decode_n(
                eng.params, eng.cache, state, key, k_steps, "greedy")
            _ = int(jax.device_get(out)[0, 0])  # fence via host fetch
        dt = (time.perf_counter() - t0) / reps
        print(json.dumps({
            "k_steps": k_steps,
            "dispatch_ms": round(dt * 1e3, 2),
            "ms_per_token_step": round(dt * 1e3 / k_steps, 2),
            "agg_tok_s": round(nb * k_steps / dt, 1),
        }), flush=True)

    # prefill program timing (512 bucket)
    toks = jnp.zeros((1, 512), jnp.int32)
    last, eng.cache = eng._prefill(eng.params, eng.cache, toks,
                                   jnp.int32(0), jnp.int32(500))
    _ = float(jax.device_get(last)[0])
    t0 = time.perf_counter()
    for _ in range(4):
        last, eng.cache = eng._prefill(eng.params, eng.cache, toks,
                                       jnp.int32(0), jnp.int32(500))
        _ = float(jax.device_get(last)[0])
    print(json.dumps({"prefill512_ms": round((time.perf_counter() - t0) / 4 * 1e3, 2)}))


if __name__ == "__main__":
    main()
