"""FusedAdamW (one-pass update, VERDICT r3 #6) must be numerically
equivalent to the optax chain it replaces: same clip, same bias-corrected
moments, same weight decay, same schedule."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.train.optim import FusedAdamW, OptimizerConfig, make_optimizer


def _tree(key, scale=1.0):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "w": jax.random.normal(k1, (16, 8)) * scale,
        "b": jax.random.normal(k2, (8,)) * scale,
        "emb": jax.random.normal(k3, (32, 16)) * scale,
    }


@pytest.mark.parametrize("clip_active", [False, True])
@pytest.mark.parametrize("mu_dtype", [None, "bfloat16"])
def test_fused_matches_optax_chain(clip_active, mu_dtype):
    import optax

    cfg = OptimizerConfig(learning_rate=1e-2, warmup_steps=2, total_steps=50,
                          clip_norm=1.0, mu_dtype=mu_dtype,
                          weight_decay=0.1)
    ref_opt = make_optimizer(cfg)
    fused = make_optimizer(OptimizerConfig(**{
        **cfg.__dict__, "fused": True}))
    assert isinstance(fused, FusedAdamW)

    # clip_active=True drives gradients large enough that the global-norm
    # scale actually engages; False keeps the norm under clip_norm=1.0
    # (~0.026 expected for the 648-leaf unit tree at 0.001) so the
    # scale==1 path is genuinely exercised too.
    gscale = 10.0 if clip_active else 0.001
    params_ref = _tree(jax.random.PRNGKey(0))
    params_fused = jax.tree.map(jnp.copy, params_ref)
    opt_ref = ref_opt.init(params_ref)
    opt_fused = fused.init(params_fused)

    for step in range(5):
        grads = _tree(jax.random.PRNGKey(100 + step), scale=gscale)
        updates, opt_ref = ref_opt.update(grads, opt_ref, params_ref)
        params_ref = optax.apply_updates(params_ref, updates)
        params_fused, opt_fused, gnorm = fused.apply(grads, opt_fused,
                                                     params_fused)
        assert float(gnorm) == pytest.approx(
            float(optax.global_norm(grads)), rel=1e-6)

    for name in params_ref:
        np.testing.assert_allclose(
            params_ref[name], params_fused[name],
            rtol=2e-5 if mu_dtype is None else 2e-2,
            atol=1e-6 if mu_dtype is None else 1e-4)


@pytest.mark.slow  # tier-1 budget (ISSUE 20): ~8s; leaf math vs optax
# stays fast via test_fused_matches_optax_chain
def test_fused_trains_in_the_real_step(tmp_path):
    """setup_train with fused=True: state init/shardings/step all work and
    the loss goes down — the structural integration, not just leaf math."""
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.runtime.mesh import build_mesh
    from kubeflow_tpu.train.data import DataConfig, make_data_source
    from kubeflow_tpu.train.step import setup_train

    cfg = preset("tiny", vocab_size=256, max_seq_len=32)
    task = setup_train(cfg, OptimizerConfig(total_steps=20, fused=True,
                                            warmup_steps=0),
                       build_mesh({"data": 8}))
    src = make_data_source(DataConfig(vocab_size=256, seq_len=32,
                                      global_batch=8))
    state = task.state
    losses = []
    for i in range(8):
        batch = jax.device_put(src.batch_at(i), task.batch_sharding)
        state, m = task.step_fn(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert int(state["opt_state"]["count"]) == 8


def test_fused_state_dtypes_stable_for_bf16_params():
    """Opt-state dtypes must be identical before and after apply() — a
    scan-carried train state (multi_step_fn) trace-errors otherwise."""
    fused = make_optimizer(OptimizerConfig(fused=True, mu_dtype="bfloat16",
                                           warmup_steps=0, total_steps=10))
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16),
                          _tree(jax.random.PRNGKey(0)))
    opt = fused.init(params)
    grads = jax.tree.map(lambda x: x.astype(jnp.bfloat16),
                         _tree(jax.random.PRNGKey(1)))
    new_p, new_opt, _ = fused.apply(grads, opt, params)
    assert jax.tree.map(lambda x: x.dtype, opt) \
        == jax.tree.map(lambda x: x.dtype, new_opt)
    assert jax.tree.map(lambda x: x.dtype, params) \
        == jax.tree.map(lambda x: x.dtype, new_p)


def test_fused_requires_adamw():
    with pytest.raises(ValueError, match="adamw only"):
        make_optimizer(OptimizerConfig(name="sgd", fused=True))
