"""Direct unit tests of ``kubeflow_tpu/compat.py`` (ISSUE 8 satellite).

The shim was previously exercised only indirectly through
importorskip-guarded suites (test_parallel_attention / test_moe_dispatch /
test_serve_sharded), so a regression in the fallback's keyword
translation would surface as a confusing downstream failure — or not at
all on a jax new enough to never take the fallback. These tests pin the
adapter's contract with recording fakes, independent of which jax is
installed, plus the live resolution on THIS environment's jax."""

import jax
import pytest

from kubeflow_tpu import compat
from kubeflow_tpu.compat import (
    axis_size, require_shard_map, wrap_legacy_shard_map,
)


class _RecordingImpl:
    """Stands in for jax.experimental.shard_map.shard_map."""

    def __init__(self):
        self.calls = []

    def __call__(self, f, **kw):
        self.calls.append((f, kw))
        return ("wrapped", f)


class TestLegacyShardMapWrapper:
    def test_check_vma_maps_to_check_rep(self):
        impl = _RecordingImpl()
        sm = wrap_legacy_shard_map(impl)

        def body(x):
            return x

        out = sm(body, mesh="m", in_specs="i", out_specs="o",
                 check_vma=False)
        assert out == ("wrapped", body)
        (f, kw), = impl.calls
        assert f is body
        assert kw == {"mesh": "m", "in_specs": "i", "out_specs": "o",
                      "check_rep": False}
        assert "check_vma" not in kw

    def test_keyword_only_call_returns_partial(self):
        impl = _RecordingImpl()
        sm = wrap_legacy_shard_map(impl)
        deco = sm(mesh="m", in_specs="i", out_specs="o", check_vma=True)
        assert not impl.calls            # nothing ran yet

        def body(x):
            return x

        deco(body)
        (f, kw), = impl.calls
        assert f is body and kw["check_rep"] is True

    def test_other_keywords_pass_through_untouched(self):
        impl = _RecordingImpl()
        sm = wrap_legacy_shard_map(impl)
        sm(lambda x: x, mesh="m", in_specs="i", out_specs="o")
        (_, kw), = impl.calls
        assert "check_rep" not in kw and "check_vma" not in kw


class TestResolution:
    def test_flags_are_consistent(self):
        if compat.HAS_SHARD_MAP:
            assert compat.shard_map is not None
            assert require_shard_map() is compat.shard_map
        else:
            assert compat.shard_map is None

    def test_native_flag_matches_jax_surface(self):
        assert compat.SHARD_MAP_NATIVE == hasattr(jax, "shard_map")

    def test_require_shard_map_raises_when_missing(self, monkeypatch):
        monkeypatch.setattr(compat, "shard_map", None)
        with pytest.raises(ImportError, match="shard_map"):
            require_shard_map()


class _FakeLax:
    """jax.lax stand-in: optionally exposes axis_size, always psum."""

    def __init__(self, with_axis_size: bool):
        self.psum_calls = []
        if with_axis_size:
            self.axis_size = lambda name: ("native", name)

    def __getattr__(self, name):
        if name == "axis_size":
            raise AttributeError(name)
        raise AttributeError(name)

    def psum(self, x, axis_name):
        self.psum_calls.append((x, axis_name))
        return ("psum", x, axis_name)


class TestAxisSizeShim:
    def test_prefers_native_axis_size(self, monkeypatch):
        fake = _FakeLax(with_axis_size=True)
        monkeypatch.setattr(jax, "lax", fake)
        assert axis_size("data") == ("native", "data")
        assert fake.psum_calls == []

    def test_falls_back_to_static_psum(self, monkeypatch):
        fake = _FakeLax(with_axis_size=False)
        monkeypatch.setattr(jax, "lax", fake)
        assert axis_size("data") == ("psum", 1, "data")
        assert fake.psum_calls == [(1, "data")]

    @pytest.mark.skipif(not compat.HAS_SHARD_MAP,
                        reason="no shard_map in this jax")
    def test_live_axis_size_under_shard_map(self):
        """The shim resolves to the real mesh axis size under an actual
        shard_map binding on this environment's jax."""
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        sm = require_shard_map()

        def body(x):
            return x * axis_size("data")

        out = sm(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"))(
            jax.numpy.ones(4, jax.numpy.int32))
        assert list(jax.device_get(out)) == [2, 2, 2, 2]
