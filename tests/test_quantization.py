"""int8 weight-only serving quantization + int8 paged KV (VERDICT round-4
next #3; SURVEY.md §2.3#27 — (U) kserve huggingfaceserver/vLLM ships weight
quantization as a first-class serving capability).

Covers: the per-channel scheme's error bound, which decoder weights
quantize (and which must not), the engine knob end-to-end (greedy quality
gate vs the bf16 engine), the int8 paged pool, and the TP-sharded
quantized engine (per-field shardings from the weight's own logical spec).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.ops.quantization import (
    QuantizedTensor, dequantize_kv, packed_param_bytes, quantization_quality,
    quantize_kv, quantize_params_int8, quantize_weight,
)


def test_quantize_weight_error_bound():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32)) * 0.1
    qt = quantize_weight(w, (0,))
    assert qt.q.dtype == jnp.int8 and qt.q.shape == w.shape
    assert qt.scale.shape == (1, 32)
    deq = qt.astype(jnp.float32)
    # Round-to-nearest: |error| <= scale/2 per element, scale = amax/127.
    bound = np.asarray(qt.scale)[0] / 2 + 1e-9
    assert np.all(np.abs(np.asarray(deq - w)) <= bound[None, :])


def test_quantize_weight_per_channel_independence():
    # One huge-magnitude channel must not destroy the others' resolution
    # (the whole point of per-channel over per-tensor).
    w = np.ones((16, 4), np.float32) * 0.01
    w[:, 0] = 100.0
    qt = quantize_weight(jnp.asarray(w), (0,))
    deq = np.asarray(qt.astype(jnp.float32))
    assert np.allclose(deq[:, 1:], 0.01, rtol=0.01)


def test_quantize_params_layout():
    cfg = preset("tiny-moe", param_dtype="float32")
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    qp = quantize_params_int8(params, cfg)
    lay = qp["layers"]
    for name in ("wq", "wk", "wv", "wo"):
        assert isinstance(lay["attn"][name], QuantizedTensor), name
    for name in ("gate", "up", "down"):
        assert isinstance(lay["mlp"][name], QuantizedTensor), name
    # Accuracy-critical / non-matmul leaves stay full precision.
    assert not isinstance(lay["mlp"]["router"], QuantizedTensor)
    assert not isinstance(qp["embed"], QuantizedTensor)
    assert not isinstance(lay["ln1"], QuantizedTensor)
    assert isinstance(qp["lm_head"], QuantizedTensor)
    # Stacked scan layout: scale keeps the layer dim, collapses contraction.
    wq = lay["attn"]["wq"]
    assert wq.scale.shape == (cfg.n_layers, 1, cfg.n_heads, cfg.head_dim)
    # MoE experts quantize per-expert-per-channel.
    assert lay["mlp"]["gate"].scale.shape == (
        cfg.n_layers, cfg.num_experts, 1, cfg.mlp_dim)
    # Density: packed bytes land near 1 byte/param for the quantized leaves.
    assert packed_param_bytes(qp) < packed_param_bytes(params) * 0.55


@pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
def test_forward_parity_tiny():
    """Dequant-in-matmul forward stays close to the fp32 forward, and the
    quality gate reports a high greedy match on a fixed prompt set."""
    cfg = preset("tiny", param_dtype="float32", dtype="float32")
    params = init_decoder_params(jax.random.PRNGKey(1), cfg)
    qp = quantize_params_int8(params, cfg)
    prompts = [[1, 5, 9, 2], [3, 3, 7]]
    q = quantization_quality(cfg, params, qp, prompts, max_new=8)
    assert q["tokens_compared"] == 16
    assert q["greedy_match_rate"] >= 0.8, q
    assert q["mean_abs_logprob_delta"] < 0.15, q


def test_engine_int8_generates():
    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams

    cfg = preset("tiny", param_dtype="float32")
    params = init_decoder_params(jax.random.PRNGKey(2), cfg)
    b = BatchingSpec(max_batch_size=2, max_seq_len=128,
                     weights_dtype="bfloat16", quantize="int8",
                     decode_steps=4, prefill_buckets=[16])
    eng = LLMEngine(cfg, b, params=params)
    ref = LLMEngine(cfg, BatchingSpec(max_batch_size=2, max_seq_len=128,
                                      weights_dtype="bfloat16",
                                      decode_steps=4, prefill_buckets=[16]),
                    params=params)
    sp = SamplingParams(max_new_tokens=12, temperature=0.0)
    out_q = eng.generate([4, 8, 15, 16], sp)
    out_ref = ref.generate([4, 8, 15, 16], sp)
    assert len(out_q) == 12
    # Greedy int8 tracks bf16 closely on the same weights (identical is not
    # guaranteed — near-ties can flip — but wholesale divergence means the
    # dequant is wrong).
    agree = sum(a == b_ for a, b_ in zip(out_q, out_ref)) / len(out_ref)
    assert agree >= 0.5, (out_q, out_ref)


def test_engine_rejects_bad_knobs():
    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.serve.engine import LLMEngine

    cfg = preset("tiny")
    with pytest.raises(ValueError, match="quantize"):
        LLMEngine(cfg, BatchingSpec(quantize="fp4", max_seq_len=128))
    with pytest.raises(ValueError, match="paged"):
        LLMEngine(cfg, BatchingSpec(kv_cache_dtype="int8", paged=False,
                                    max_seq_len=128))
    # pallas + int8 is a SUPPORTED pair now (in-kernel dequant): the old
    # "requires paged_attn_impl=gather" ban is gone.
    eng = LLMEngine(cfg, BatchingSpec(kv_cache_dtype="int8", paged=True,
                                      page_size=16, max_seq_len=128,
                                      paged_attn_impl="pallas"))
    assert eng.kv_quant and eng.paged_attn_impl == "pallas"


def test_spec_allows_int8_kv_through_fabric():
    """The two validator bans this feature removed, pinned OPEN: int8 KV
    composes with disaggregated roles (the wire carries scale blobs) and
    with the host tier (demote/promote batches carry them too)."""
    from kubeflow_tpu.core.serving import BatchingSpec

    # pydantic model_validator: construction IS validation.
    BatchingSpec(kv_cache_dtype="int8", paged=True, page_size=16,
                 max_seq_len=128, role="prefill")
    BatchingSpec(kv_cache_dtype="int8", paged=True, page_size=16,
                 max_seq_len=128, role="decode")
    BatchingSpec(kv_cache_dtype="int8", paged=True, page_size=16,
                 max_seq_len=128, host_kv_pages=32, prefix_index="radix")


def test_kv_quantize_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 2, 16)) * 3.0
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (4, 2)
    deq = dequantize_kv(q, s, jnp.float32)
    err = np.abs(np.asarray(deq - x))
    bound = np.asarray(s)[..., None] / 2 + 1e-9
    assert np.all(err <= bound)


def test_kv_quantize_extreme_magnitudes():
    """Per-token-per-head scales keep relative error bounded across 12
    orders of magnitude in the same batch — a per-tensor scale would
    flush the small rows to zero."""
    mags = np.asarray([1e-6, 1e-3, 1.0, 1e3, 1e6], np.float32)
    x = (jax.random.normal(jax.random.PRNGKey(7), (5, 3, 8))
         * mags[:, None, None])
    q, s = quantize_kv(x)
    deq = np.asarray(dequantize_kv(q, s, jnp.float32))
    xn = np.asarray(x)
    for i in range(5):
        amax = np.abs(xn[i]).max()
        # Round-to-nearest on a 127-step grid: error <= amax/254 per row.
        assert np.abs(deq[i] - xn[i]).max() <= amax / 127, mags[i]


def test_kv_quantize_zero_rows():
    """All-zero K/V rows (padding, unwritten page tails) must survive
    exactly — the 1e-8 scale floor guards the 0/0, and dequant returns
    exact zeros, not NaN."""
    x = jnp.zeros((3, 2, 16))
    q, s = quantize_kv(x)
    assert np.all(np.asarray(q) == 0)
    assert np.all(np.asarray(s) > 0)          # floored, not 0 (no 0/0)
    deq = np.asarray(dequantize_kv(q, s, jnp.float32))
    assert np.all(deq == 0.0) and not np.any(np.isnan(deq))
    # Mixed: one zero row among live rows stays exact.
    x = x.at[1, 1, :].set(jnp.arange(16, dtype=jnp.float32))
    q, s = quantize_kv(x)
    deq = np.asarray(dequantize_kv(q, s, jnp.float32))
    assert np.all(deq[0] == 0.0)
    assert np.abs(deq[1, 1] - np.arange(16)).max() <= 15.0 / 254 + 1e-6


@pytest.mark.parametrize("dh", [1, 3, 7, 17])
def test_kv_quantize_odd_head_dims(dh):
    """The scheme is shape-agnostic over head_dim (no lane-alignment
    assumption leaks into the math)."""
    x = jax.random.normal(jax.random.PRNGKey(11), (4, 2, dh)) * 2.5
    q, s = quantize_kv(x)
    assert q.shape == x.shape and s.shape == x.shape[:-1]
    deq = np.asarray(dequantize_kv(q, s, jnp.float32))
    bound = np.asarray(s)[..., None] / 2 + 1e-9
    assert np.all(np.abs(deq - np.asarray(x)) <= bound)


def test_packed_param_bytes_estimate_exact():
    """The config-only estimate prices EXACTLY what quantize_params_int8
    packs (the repository books placement off the estimate before any
    params exist — drift here mis-sizes the LRU budget)."""
    from kubeflow_tpu.ops.quantization import packed_param_bytes_estimate

    for name in ("tiny", "tiny-moe"):
        cfg = preset(name, param_dtype="float32")
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        real = packed_param_bytes(quantize_params_int8(params, cfg))
        assert packed_param_bytes_estimate(cfg) == real, name


@pytest.mark.slow  # tier-1 budget: ~8s; quant_smoke gates the int8 paged
# e2e path (band + fabric identity) on every smoke run
def test_paged_int8_kv_engine_e2e():
    """int8 paged pool serves greedy decode; outputs track the bf16 paged
    engine; pool bytes halve (+scale overhead)."""
    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams

    cfg = preset("tiny", param_dtype="float32")
    params = init_decoder_params(jax.random.PRNGKey(4), cfg)

    def make(kv_dtype):
        return LLMEngine(cfg, BatchingSpec(
            max_batch_size=2, max_seq_len=64, paged=True, page_size=16,
            chunked_prefill_tokens=16, decode_steps=4,
            weights_dtype="bfloat16", kv_cache_dtype=kv_dtype,
            paged_attn_impl="gather"), params=params)

    eng8 = make("int8")
    eng16 = make(None)
    assert eng8.cache["k"].dtype == jnp.int8
    assert "ks" in eng8.cache and eng8.cache["ks"].dtype == jnp.float32
    kv8 = eng8.cache["k"].nbytes + eng8.cache["ks"].nbytes
    kv16 = eng16.cache["k"].nbytes
    # int8 + 4/Dh scale overhead vs bf16: 0.625 at tiny's Dh=16; 0.52 at a
    # real model's Dh=128.
    assert kv8 < kv16 * 0.66
    sp = SamplingParams(max_new_tokens=10, temperature=0.0)
    prompt = [2, 7, 1, 8, 2, 8]
    out8 = eng8.generate(prompt, sp)
    out16 = eng16.generate(prompt, sp)
    assert len(out8) == 10
    agree = sum(a == b for a, b in zip(out8, out16)) / len(out16)
    assert agree >= 0.5, (out8, out16)
    # Multi-request continuity: a second request re-reads quantized pages.
    out8b = eng8.generate(prompt, sp)
    assert len(out8b) == 10 and out8b == out8


@pytest.mark.slow
def test_tp_sharded_quantized_engine():
    """Quantized weights shard per-field (q by the weight's logical spec,
    scale with collapsed dims replicated as needed) and the TP engine
    serves greedy tokens matching the single-device quantized engine."""
    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.runtime.mesh import build_mesh
    from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams

    cfg = preset("tiny", param_dtype="float32")
    params = init_decoder_params(jax.random.PRNGKey(5), cfg)
    mesh = build_mesh({"model": 2}, jax.devices()[:2])
    b = BatchingSpec(max_batch_size=2, max_seq_len=64,
                     weights_dtype="bfloat16", quantize="int8",
                     decode_steps=4, prefill_buckets=[16])
    eng_tp = LLMEngine(cfg, b, params=params, mesh=mesh)
    eng_1 = LLMEngine(cfg, b, params=params)
    # Per-field shardings really applied: wq's int8 payload is sharded on
    # the head dim, its scale exists with the collapsed contraction dim.
    wq = eng_tp.params["layers"]["attn"]["wq"]
    assert isinstance(wq, QuantizedTensor)
    assert wq.q.dtype == jnp.int8
    sp = SamplingParams(max_new_tokens=8, temperature=0.0)
    out_tp = eng_tp.generate([3, 1, 4, 1, 5], sp)
    out_1 = eng_1.generate([3, 1, 4, 1, 5], sp)
    assert out_tp == out_1
