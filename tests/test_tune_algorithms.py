"""Suggestion-algorithm unit tests — the analog of katib's in-process
suggestion-servicer tests ((U) katib test/unit/v1beta1/suggestion; SURVEY.md
§4.4): fabricate experiment specs, call the algorithm directly, assert
assignments are in-bounds/typed, plus convergence + state-serialization
properties katib never checks."""

import json
import math

import numpy as np
import pytest

from kubeflow_tpu.core.tuning import (
    AlgorithmSpec, ExperimentSpec, FeasibleSpace, ObjectiveSpec,
    ObjectiveType, ParameterSpec, ParameterType, TrialTemplate,
)
from kubeflow_tpu.tune import search_space as ss
from kubeflow_tpu.tune.algorithms import (
    Observation, get_suggester, median_should_stop, param_key,
)


def make_spec(params, algorithm="random", settings=None, **kw) -> ExperimentSpec:
    return ExperimentSpec(
        parameters=params,
        objective=ObjectiveSpec(type=ObjectiveType.MINIMIZE,
                                metric_name="loss"),
        algorithm=AlgorithmSpec(name=algorithm, settings=settings or {}),
        trial_template=TrialTemplate(manifest={"kind": "JAXJob"}),
        **kw)


MIXED = [
    ParameterSpec(name="lr", type=ParameterType.DOUBLE,
                  feasible_space=FeasibleSpace(min=1e-5, max=1e-1,
                                               log_scale=True)),
    ParameterSpec(name="layers", type=ParameterType.INT,
                  feasible_space=FeasibleSpace(min=2, max=8)),
    ParameterSpec(name="opt", type=ParameterType.CATEGORICAL,
                  feasible_space=FeasibleSpace(list=["adam", "sgd", "lion"])),
]

QUAD = [
    ParameterSpec(name="x", type=ParameterType.DOUBLE,
                  feasible_space=FeasibleSpace(min=-1.0, max=1.0)),
    ParameterSpec(name="y", type=ParameterType.DOUBLE,
                  feasible_space=FeasibleSpace(min=-1.0, max=1.0)),
]


def quad_value(p):
    return (p["x"] - 0.3) ** 2 + (p["y"] + 0.2) ** 2


def optimize(algorithm, settings=None, rounds=30, batch=1):
    """Sequential minimization of the quadratic bowl; returns best value."""
    spec = make_spec(QUAD, algorithm=algorithm, settings=settings)
    sugg = get_suggester(spec)
    history, state = [], {}
    for _ in range(rounds):
        asked, state = sugg.suggest(batch, history, state)
        # state must stay JSON-serializable every round (Suggestion storage)
        state = json.loads(json.dumps(state))
        if not asked:
            break
        for p in asked:
            history.append(Observation(parameters=p, value=quad_value(p)))
    return min(o.value for o in history), history


class TestSearchSpace:
    def test_round_trip_mixed(self):
        rng = np.random.default_rng(0)
        for _ in range(50):
            p = ss.sample(MIXED, rng)
            assert 1e-5 <= p["lr"] <= 1e-1
            assert isinstance(p["layers"], int) and 2 <= p["layers"] <= 8
            assert p["opt"] in ("adam", "sgd", "lion")
            u = ss.encode(MIXED, p)
            back = ss.decode(MIXED, u)
            assert back["opt"] == p["opt"]
            assert back["layers"] == p["layers"]
            assert math.isclose(back["lr"], p["lr"], rel_tol=1e-6)

    def test_log_scale_is_log_uniform(self):
        rng = np.random.default_rng(1)
        lrs = [ss.sample(MIXED, rng)["lr"] for _ in range(400)]
        # Median of log-uniform(1e-5,1e-1) ≈ 1e-3; linear-uniform would be ~0.05
        assert 2e-4 < float(np.median(lrs)) < 5e-3

    def test_grid_values(self):
        assert ss.grid_values(MIXED[1]) == [2, 3, 4, 5, 6, 7, 8]
        assert ss.grid_values(MIXED[2]) == ["adam", "sgd", "lion"]
        stepped = ParameterSpec(
            name="d", type=ParameterType.DOUBLE,
            feasible_space=FeasibleSpace(min=0.0, max=1.0, step=0.25))
        assert ss.grid_values(stepped) == pytest.approx([0, .25, .5, .75, 1.0])


class TestBounds:
    @pytest.mark.parametrize("algo,settings", [
        ("random", None),
        ("grid", None),
        ("tpe", {"n_startup_trials": 2}),
        ("gp_ei", {"n_startup_trials": 2}),
        ("cmaes", None),
        ("bayesianoptimization", {"n_startup_trials": 2}),
    ])
    def test_in_bounds_and_typed(self, algo, settings):
        spec = make_spec(MIXED, algorithm=algo, settings=settings)
        sugg = get_suggester(spec)
        history, state = [], {}
        rng = np.random.default_rng(2)
        for _ in range(6):
            asked, state = sugg.suggest(2, history, state)
            json.dumps(state)  # serializable
            for p in asked:
                assert 1e-5 <= p["lr"] <= 1e-1
                assert isinstance(p["layers"], int) and 2 <= p["layers"] <= 8
                assert p["opt"] in ("adam", "sgd", "lion")
                history.append(Observation(parameters=p,
                                           value=float(rng.random())))


class TestGrid:
    def test_exact_enumeration(self):
        params = [
            ParameterSpec(name="a", type=ParameterType.INT,
                          feasible_space=FeasibleSpace(min=1, max=3)),
            ParameterSpec(name="b", type=ParameterType.CATEGORICAL,
                          feasible_space=FeasibleSpace(list=["u", "v"])),
        ]
        spec = make_spec(params, algorithm="grid")
        sugg = get_suggester(spec)
        asked, state = sugg.suggest(100, [], {})
        assert len(asked) == 6
        assert len({param_key(p) for p in asked}) == 6
        more, state = sugg.suggest(5, [], state)
        assert more == []


class TestModelBased:
    def test_tpe_beats_random(self):
        # Median over seeds: a single TPE run can camp a bad basin (true of
        # hyperopt's TPE too), but the median must beat random's median.
        tpe, rnd = [], []
        for seed in (0, 1, 2):
            bt, _ = optimize("tpe", {"n_startup_trials": 6,
                                     "random_state": seed}, rounds=40)
            br, _ = optimize("random", {"random_state": seed}, rounds=40)
            tpe.append(bt)
            rnd.append(br)
        assert np.median(tpe) < 0.01
        assert np.median(tpe) < np.median(rnd)

    def test_gp_ei_converges(self):
        best, _ = optimize("gp_ei", {"n_startup_trials": 5,
                                     "random_state": 3}, rounds=30)
        assert best < 0.02

    def test_cmaes_converges(self):
        best, _ = optimize("cmaes", {"random_state": 5}, rounds=60, batch=2)
        assert best < 0.05

    def test_resume_continues_not_repeats(self):
        spec = make_spec(QUAD, algorithm="random",
                         settings={"random_state": 11})
        sugg = get_suggester(spec)
        a1, state = sugg.suggest(3, [], {})
        # Fresh suggester + persisted state (the FromSuggestion resume path)
        sugg2 = get_suggester(spec)
        a2, _ = sugg2.suggest(3, [], json.loads(json.dumps(state)))
        keys1 = {param_key(p) for p in a1}
        keys2 = {param_key(p) for p in a2}
        assert not keys1 & keys2


class TestHyperband:
    def params(self):
        return QUAD + [ParameterSpec(
            name="steps", type=ParameterType.INT,
            feasible_space=FeasibleSpace(min=1, max=9))]

    def test_rungs_and_promotion(self):
        spec = make_spec(
            self.params(), algorithm="hyperband",
            settings={"resource_parameter": "steps", "eta": 3,
                      "min_resource": 1, "max_resource": 9})
        sugg = get_suggester(spec)
        history, state = [], {}
        seen_resources = []
        for _ in range(40):
            asked, state = sugg.suggest(4, history, state)
            state = json.loads(json.dumps(state))
            if not asked:
                break
            for p in asked:
                seen_resources.append(p["steps"])
                history.append(Observation(parameters=p, value=quad_value(p)))
        # Bracket 0 rung 0 runs many configs at min resource, later rungs at
        # eta× more; the full HB schedule must touch the max resource.
        assert min(seen_resources) == 1
        assert max(seen_resources) == 9
        assert len(history) > 10

    def test_requires_resource_parameter(self):
        with pytest.raises(ValueError):
            get_suggester(make_spec(QUAD, algorithm="hyperband"))


class TestPBT:
    def test_population_evolves_toward_optimum(self):
        spec = make_spec(QUAD, algorithm="pbt",
                         settings={"population_size": 8, "random_state": 0})
        sugg = get_suggester(spec)
        history, state = [], {}
        gen_best = []
        for _ in range(8):   # generations
            asked, state = sugg.suggest(8, history, state)
            state = json.loads(json.dumps(state))
            vals = []
            for p in asked:
                assert -1.0 <= p["x"] <= 1.0 and -1.0 <= p["y"] <= 1.0
                v = quad_value(p)
                vals.append(v)
                history.append(Observation(parameters=p, value=v))
            if vals:
                gen_best.append(min(vals))
        # Later generations should beat the first (exploit+explore works).
        assert min(gen_best[3:]) < gen_best[0]

    def test_waits_for_generation(self):
        spec = make_spec(QUAD, algorithm="pbt",
                         settings={"population_size": 4})
        sugg = get_suggester(spec)
        asked, state = sugg.suggest(10, [], {})
        assert len(asked) == 4   # never more than the population in flight
        more, state = sugg.suggest(4, [], state)
        assert more == []        # generation incomplete → wait


class TestMedianStop:
    def test_prunes_bad_trial(self):
        completed = [[(s, 1.0 - 0.1 * s) for s in range(5)] for _ in range(3)]
        bad = [(s, 5.0) for s in range(3)]
        good = [(s, 0.2) for s in range(3)]
        assert median_should_stop(bad, completed)
        assert not median_should_stop(good, completed)

    def test_needs_min_trials(self):
        completed = [[(0, 1.0)]]
        assert not median_should_stop([(0, 9.0)], completed, min_trials=3)


def test_unknown_algorithm():
    with pytest.raises(ValueError):
        get_suggester(make_spec(QUAD, algorithm="nope"))
