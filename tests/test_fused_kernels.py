"""Fused Pallas kernel suite (ops/fused_xent.py, ops/fused_norm.py) —
numerics pinned against the unfused XLA references, the loss-path memory
claim asserted on the jaxpr, serve decode identity, dispatch stability
under the recompile sanitizer, and the input-staging double buffer.

Numerics policy (the bit-compare contract the README documents):
- forward RMSNorm / residual-add / SwiGLU(silu) / CE-nll are the SAME op
  sequence as the references → asserted BIT-identical in interpret mode;
- GeGLU's tanh polynomial may reassociate under compilation → pinned to
  float32 ulp-level tolerance;
- backward passes reduce in blocked order → pinned to fp32 tolerances.
"""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from kubeflow_tpu.models.config import preset  # noqa: E402
from kubeflow_tpu.ops import fused_norm, fused_xent  # noqa: E402

F32_TOL = 1e-6          # forward-level fp32 tolerance (pinned)
GRAD_TOL = 5e-6         # backward fp32 tolerance (pinned)


def _maxdiff(a, b):
    return float(jnp.abs(jnp.asarray(a) - jnp.asarray(b)).max())


def _tree_maxdiff(a, b):
    return max(_maxdiff(x, y)
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


# -- fused cross-entropy kernel ------------------------------------------------

class TestFusedXent:
    @pytest.fixture()
    def data(self):
        k = jax.random.PRNGKey(0)
        b, s, d, v = 2, 16, 64, 256
        h = jax.random.normal(k, (b, s, d), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (d, v), jnp.float32) * 0.1
        t = jax.random.randint(jax.random.PRNGKey(2), (b, s), 0, v)
        return h, w, t

    @pytest.mark.parametrize("softcap", [None, 5.0])
    def test_forward_matches_reference(self, data, softcap):
        h, w, t = data
        nll, corr = fused_xent.fused_cross_entropy(h, w, t,
                                                   logits_softcap=softcap)
        rn, rc = fused_xent.reference_cross_entropy(h, w, t,
                                                    logits_softcap=softcap)
        assert _maxdiff(nll, rn) <= F32_TOL
        # argmax bookkeeping (first-occurrence ties included) is exact
        assert (corr == rc).all()

    @pytest.mark.parametrize("softcap", [None, 5.0])
    def test_gradients_match_reference(self, data, softcap):
        h, w, t = data

        def f(fn):
            return jax.grad(
                lambda h, w: fn(h, w, t, logits_softcap=softcap)[0].mean(),
                argnums=(0, 1))

        gh, gw = f(fused_xent.fused_cross_entropy)(h, w)
        rh, rw = f(fused_xent.reference_cross_entropy)(h, w)
        assert _maxdiff(gh, rh) <= GRAD_TOL
        assert _maxdiff(gw, rw) <= GRAD_TOL

    def test_under_jit_and_scan(self, data):
        h, w, t = data

        def loss(h, w):
            return fused_xent.fused_cross_entropy(h, w, t)[0].mean()

        ref = jax.grad(lambda h, w: fused_xent.reference_cross_entropy(
            h, w, t)[0].mean(), argnums=(0, 1))(h, w)
        jit_g = jax.jit(jax.grad(loss, argnums=(0, 1)))(h, w)
        assert _tree_maxdiff(jit_g, ref) <= GRAD_TOL

        def step(c, _):
            return c - 0.1 * jax.grad(loss)(c, w), loss(c, w)

        _, ls = jax.jit(lambda h: jax.lax.scan(step, h, None, length=2))(h)
        assert bool(jnp.isfinite(ls).all())

    def test_loss_mask_flows_through_cotangent(self, data):
        """Masked rows contribute exactly zero gradient (the decoder_loss
        masking composes with the kernel through the nll cotangent)."""
        h, w, t = data
        mask = (jnp.arange(t.shape[1]) < 8).astype(jnp.float32)[None, :]

        def masked(fn):
            def f(h):
                nll, _ = fn(h, w, t)
                return (nll * mask).sum() / mask.sum()
            return jax.grad(f)(h)

        gf = masked(fused_xent.fused_cross_entropy)
        gr = masked(fused_xent.reference_cross_entropy)
        assert _maxdiff(gf, gr) <= GRAD_TOL
        assert float(jnp.abs(gf[:, 8:]).max()) == 0.0

    def test_odd_shapes_fit_blocks(self):
        # Rows/vocab without 128-aligned divisors still run in interpret
        # (block fit falls back to any divisor).
        h = jax.random.normal(jax.random.PRNGKey(0), (3, 5, 24), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (24, 100), jnp.float32)
        t = jax.random.randint(jax.random.PRNGKey(2), (3, 5), 0, 100)
        nll, _ = fused_xent.fused_cross_entropy(h, w, t)
        rn, _ = fused_xent.reference_cross_entropy(h, w, t)
        assert _maxdiff(nll, rn) <= F32_TOL


# -- fused norm / swiglu kernels -----------------------------------------------

def _ref_rmsnorm(x, w, plus_one=False, eps=1e-5):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    xf = xf * jax.lax.rsqrt(var + eps)
    wf = (1.0 + w.astype(jnp.float32)) if plus_one else w.astype(jnp.float32)
    return (xf * wf).astype(x.dtype)


class TestFusedNorm:
    @pytest.fixture()
    def data(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 12, 64), jnp.float32)
        r = jax.random.normal(jax.random.PRNGKey(3), (2, 12, 64), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (64,),
                              jnp.float32) * 0.2 + 1.0
        return x, r, w

    @pytest.mark.parametrize("plus_one", [False, True])
    def test_forward_bit_identical(self, data, plus_one):
        x, _, w = data
        o = fused_norm.rmsnorm_fused(x, w, eps=1e-5, plus_one=plus_one)
        assert (o == _ref_rmsnorm(x, w, plus_one)).all()

    @pytest.mark.parametrize("plus_one", [False, True])
    def test_gradients(self, data, plus_one):
        x, _, w = data
        gf = jax.grad(lambda x, w: (fused_norm.rmsnorm_fused(
            x, w, eps=1e-5, plus_one=plus_one) ** 2).sum(),
            argnums=(0, 1))(x, w)
        gr = jax.grad(lambda x, w: (_ref_rmsnorm(x, w, plus_one) ** 2).sum(),
                      argnums=(0, 1))(x, w)
        assert _tree_maxdiff(gf, gr) <= 1e-4   # dw sums 24 fp32 rows

    def test_add_rmsnorm_bit_identical_and_grads(self, data):
        x, r, w = data
        y, h = fused_norm.add_rmsnorm_fused(x, r, w, eps=1e-5)
        assert (y == x + r).all()
        assert (h == _ref_rmsnorm(x + r, w)).all()

        def f(fn):
            def loss(x, r, w):
                y, h = fn(x, r, w)
                return (y ** 2).sum() + (h ** 3).sum()
            return jax.grad(loss, argnums=(0, 1, 2))(x, r, w)

        gf = f(lambda x, r, w: fused_norm.add_rmsnorm_fused(x, r, w, eps=1e-5))
        gr = f(lambda x, r, w: (x + r, _ref_rmsnorm(x + r, w)))
        assert _tree_maxdiff(gf, gr) <= 1e-4

    def test_swiglu_silu_bit_identical(self):
        g = jax.random.normal(jax.random.PRNGKey(4), (2, 12, 128), jnp.float32)
        u = jax.random.normal(jax.random.PRNGKey(5), (2, 12, 128), jnp.float32)
        assert (fused_norm.swiglu_fused(g, u, act="silu")
                == jax.nn.silu(g) * u).all()

    def test_geglu_within_ulp_tolerance(self):
        # The documented exception to bit-identity: the gelu tanh
        # polynomial reassociates under compilation.
        g = jax.random.normal(jax.random.PRNGKey(4), (2, 12, 128), jnp.float32)
        u = jax.random.normal(jax.random.PRNGKey(5), (2, 12, 128), jnp.float32)
        o = fused_norm.swiglu_fused(g, u, act="gelu")
        assert _maxdiff(o, jax.nn.gelu(g, approximate=True) * u) <= 1e-6

    @pytest.mark.parametrize("act", ["silu", "gelu"])
    def test_swiglu_gradients(self, act):
        g = jax.random.normal(jax.random.PRNGKey(4), (2, 12, 128), jnp.float32)
        u = jax.random.normal(jax.random.PRNGKey(5), (2, 12, 128), jnp.float32)
        ref = {"silu": jax.nn.silu,
               "gelu": lambda x: jax.nn.gelu(x, approximate=True)}[act]
        gf = jax.grad(lambda g, u: (fused_norm.swiglu_fused(
            g, u, act=act) ** 2).sum(), argnums=(0, 1))(g, u)
        gr = jax.grad(lambda g, u: ((ref(g) * u) ** 2).sum(),
                      argnums=(0, 1))(g, u)
        assert _tree_maxdiff(gf, gr) <= GRAD_TOL


# -- resolution ----------------------------------------------------------------

class TestResolution:
    def test_auto_is_off_off_tpu(self):
        from kubeflow_tpu.models.layers import fused_kernels_on

        cfg = preset("tiny")                      # fused_kernels="auto"
        assert fused_kernels_on(cfg) is (jax.default_backend() == "tpu")
        assert fused_kernels_on(
            dataclasses.replace(cfg, fused_kernels="on")) is True
        assert fused_kernels_on(
            dataclasses.replace(cfg, fused_kernels="off")) is False
        with pytest.raises(ValueError):
            fused_kernels_on(dataclasses.replace(cfg, fused_kernels="yes"))

    def test_multi_device_mesh_disables(self):
        from kubeflow_tpu.models.layers import fused_kernels_on
        from kubeflow_tpu.runtime.mesh import build_mesh

        cfg = dataclasses.replace(preset("tiny"), fused_kernels="on")
        mesh = build_mesh({"data": len(jax.devices())})
        if mesh.size > 1:
            assert fused_kernels_on(cfg, mesh) is False
        assert fused_kernels_on(
            cfg, build_mesh({"data": 1}, jax.devices()[:1])) is True


# -- model-level parity --------------------------------------------------------

def _f32(cfg, **over):
    return dataclasses.replace(cfg, dtype="float32", **over)


class TestDecoderLossParity:
    @pytest.mark.parametrize("name", [
        "tiny",
        pytest.param("tiny-gemma", marks=pytest.mark.slow),  # tier-1 budget:
        # the gemma variant re-runs the same parity at ~8s; tiny covers it
    ])
    def test_loss_grads_accuracy_match_dense(self, name):
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params,
        )

        cfg_off = _f32(preset(name), fused_kernels="off")
        cfg_on = _f32(preset(name), fused_kernels="on")
        params = init_decoder_params(jax.random.PRNGKey(0), cfg_off)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 49), 0,
                                  cfg_off.vocab_size)
        l0, m0 = decoder_loss(params, toks, cfg_off)
        l1, m1 = decoder_loss(params, toks, cfg_on)
        assert abs(float(l0 - l1)) <= F32_TOL
        assert float(m0["accuracy"]) == float(m1["accuracy"])
        g0 = jax.grad(lambda p: decoder_loss(p, toks, cfg_off)[0])(params)
        g1 = jax.grad(lambda p: decoder_loss(p, toks, cfg_on)[0])(params)
        assert _tree_maxdiff(g0, g1) <= GRAD_TOL

    @pytest.mark.slow  # tier-1 budget: full K-step mesh dispatch, ~15s
    def test_scanned_k_step_dispatch_parity(self):
        """The donated K-step train dispatch (train/step.py multi_step_fn)
        picks the fused kernels up with zero signature churn and stays
        within fp32 tolerance of the unfused path."""
        from kubeflow_tpu.runtime.mesh import build_mesh
        from kubeflow_tpu.train.data import (
            DataConfig, make_data_source, stacked_batches,
        )
        from kubeflow_tpu.train.optim import OptimizerConfig
        from kubeflow_tpu.train.step import setup_train

        mesh = build_mesh({"fsdp": 1}, jax.devices()[:1])
        dc = DataConfig(vocab_size=256, seq_len=32, global_batch=2)
        batch = stacked_batches(make_data_source(dc), 0, 2)
        out = {}
        for fk in ("off", "on"):
            cfg = _f32(preset("tiny"), fused_kernels=fk,
                       remat_policy="dots_flash")
            task = setup_train(cfg, OptimizerConfig(total_steps=100), mesh)
            b = jax.device_put(batch, task.multi_batch_sharding)
            state, m = task.multi_step_fn(task.state, b)
            out[fk] = (float(m["loss"]), state["params"])
        assert abs(out["off"][0] - out["on"][0]) <= 5e-5
        assert _tree_maxdiff(out["off"][1], out["on"][1]) <= 1e-4


class TestLossMemoryFootprint:
    """The acceptance probe: the fused loss path never books a
    [B, S, vocab]-sized buffer, the unfused dense path provably does —
    asserted on every aval in the compiled-out jaxpr (an explicit
    allocation probe that is backend-independent)."""

    @staticmethod
    def _avals(closed):
        core = jax.core
        seen = []

        def walk(jaxpr):
            for v in list(jaxpr.constvars) + list(jaxpr.invars):
                seen.append(v.aval)
            for eqn in jaxpr.eqns:
                for v in eqn.outvars:
                    seen.append(v.aval)
                for p in eqn.params.values():
                    stack = [p]
                    while stack:
                        item = stack.pop()
                        if isinstance(item, core.ClosedJaxpr):
                            walk(item.jaxpr)
                        elif isinstance(item, core.Jaxpr):
                            walk(item)
                        elif isinstance(item, (tuple, list)):
                            stack.extend(item)

        walk(closed.jaxpr)
        return seen

    def test_fused_never_materializes_logits(self):
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params,
        )

        # Dims chosen so the kernel blocks genuinely subdivide (T=512 >
        # block_rows=256, V=1024 > block_vocab=512): the biggest fused
        # tile is [256, 512] — 4x under the [B*S, V] logits.
        b, s, v = 2, 256, 1024
        base = _f32(preset("tiny"), vocab_size=v, max_seq_len=s,
                    loss_chunk_size=0)
        params = init_decoder_params(
            jax.random.PRNGKey(0), dataclasses.replace(base,
                                                       fused_kernels="off"))
        toks = jax.random.randint(jax.random.PRNGKey(1), (b, s + 1), 0, v)

        def big_logits(cfg):
            jx = jax.make_jaxpr(
                lambda p: decoder_loss(p, toks, cfg)[0])(params)
            return [a for a in self._avals(jx)
                    if getattr(a, "shape", ()) and a.shape[-1] == v
                    and a.size >= b * s * v]

        assert big_logits(dataclasses.replace(base, fused_kernels="off")), \
            "probe broken: the dense path must book [B,S,V] logits"
        assert not big_logits(dataclasses.replace(base, fused_kernels="on"))

    def test_fused_backward_never_materializes_logits(self):
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params,
        )

        b, s, v = 2, 256, 1024
        base = _f32(preset("tiny"), vocab_size=v, max_seq_len=s,
                    loss_chunk_size=0, fused_kernels="on")
        params = init_decoder_params(jax.random.PRNGKey(0), base)
        toks = jax.random.randint(jax.random.PRNGKey(1), (b, s + 1), 0, v)
        jx = jax.make_jaxpr(
            jax.grad(lambda p: decoder_loss(p, toks, base)[0]))(params)
        big = [a for a in self._avals(jx)
               if getattr(a, "shape", ()) and a.shape[-1] == v
               and a.size >= b * s * v]
        assert not big


# -- serve decode identity -----------------------------------------------------

class TestServeDecodeIdentity:
    """The serve engine reuses the RMSNorm kernel through layers.rmsnorm:
    greedy decode must be token-identical with fused norms on vs off,
    dense and paged."""

    PROMPTS = [[5, 9, 2, 7], [3, 3, 8], [1, 2, 3, 4, 5, 6]]

    def _run(self, fk, paged):
        from kubeflow_tpu.models.decoder import init_decoder_params
        from kubeflow_tpu.serve.engine import (
            BatchingSpec, LLMEngine, SamplingParams,
        )

        cfg = dataclasses.replace(preset("tiny"), fused_kernels=fk)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        kw = {"page_size": 8} if paged else {}
        eng = LLMEngine(cfg, BatchingSpec(max_batch_size=2, max_seq_len=48,
                                          paged=paged, **kw), params=params)
        try:
            return [eng.generate(list(p), SamplingParams(max_new_tokens=8))
                    for p in self.PROMPTS]
        finally:
            eng.stop()

    def test_dense_greedy_identical(self):
        assert self._run("off", False) == self._run("on", False)

    @pytest.mark.slow
    def test_paged_greedy_identical(self):
        assert self._run("off", True) == self._run("on", True)


# -- recompile stability -------------------------------------------------------

class TestRecompileStability:
    @pytest.mark.slow  # tier-1 budget (ISSUE 20): ~9s; steady-state
    # recompile discipline is also gated by the F6xx sanitizer tests
    def test_warmed_fused_train_step_zero_steady_recompiles(self):
        """KFTPU_SANITIZE=recompile over a warmed fused-kernel train step:
        every compile lands in warmup, none after (the F6xx runtime
        cross-check for the new dispatch surface)."""
        from kubeflow_tpu.runtime.mesh import build_mesh
        from kubeflow_tpu.runtime.sanitize import (
            install_recompile_watchdog, recompile_report,
            uninstall_recompile_watchdog,
        )
        from kubeflow_tpu.train.optim import OptimizerConfig
        from kubeflow_tpu.train.step import setup_train

        wd = install_recompile_watchdog()
        wd.reset()
        try:
            cfg = dataclasses.replace(
                preset("tiny", vocab_size=256, max_seq_len=32),
                fused_kernels="on", remat_policy="dots_flash")
            task = setup_train(cfg, OptimizerConfig(warmup_steps=0),
                               build_mesh({"data": 1}, jax.devices()[:1]))
            batch = np.random.default_rng(0).integers(
                0, cfg.vocab_size, (4, 17), dtype=np.int32)
            put = lambda: jax.device_put(batch, task.batch_sharding)  # noqa: E731
            state, _ = task.step_fn(task.state, put())
            wd.mark_warm()
            state, _ = task.step_fn(state, put())
            state, _ = task.step_fn(state, put())
            assert wd.steady_count() == 0, recompile_report()["steady"]
        finally:
            uninstall_recompile_watchdog()


# -- input staging double buffer -----------------------------------------------

class TestDeviceBatchStager:
    def test_sequential_prefetch_matches_direct(self):
        from kubeflow_tpu.train.staging import DeviceBatchStager

        calls = []

        def fetch(i):
            calls.append(i)
            return i * 10

        with DeviceBatchStager(fetch, start=3, depth=2) as st:
            got = [st.get(i, timeout=5.0) for i in range(3, 9)]
        assert got == [i * 10 for i in range(3, 9)]
        assert calls[:6] == list(range(3, 9))

    def test_out_of_order_consumption_raises(self):
        from kubeflow_tpu.train.staging import DeviceBatchStager

        with DeviceBatchStager(lambda i: i, start=0) as st:
            st.get(0, timeout=5.0)
            with pytest.raises(RuntimeError, match="sequential"):
                st.get(5, timeout=5.0)

    def test_fetch_error_propagates(self):
        from kubeflow_tpu.train.staging import DeviceBatchStager

        def fetch(i):
            if i == 1:
                raise ValueError("boom")
            return i

        with DeviceBatchStager(fetch, start=0) as st:
            assert st.get(0, timeout=5.0) == 0
            with pytest.raises(RuntimeError, match="index 1"):
                st.get(1, timeout=5.0)

    def test_close_unblocks_producer(self):
        from kubeflow_tpu.train.staging import DeviceBatchStager

        st = DeviceBatchStager(lambda i: bytes(16), start=0, depth=1)
        st.get(0, timeout=5.0)
        st.close()                       # producer blocked on put: must exit
        assert not st._thread.is_alive()


# -- XLA perf flag merging -----------------------------------------------------

class TestXlaPerfFlags:
    def test_merges_without_overriding(self):
        from kubeflow_tpu.runtime.xla_flags import PERF_FLAGS, xla_perf_flags

        pinned = "--xla_tpu_enable_latency_hiding_scheduler=false"
        merged = xla_perf_flags(pinned)
        assert merged.startswith(pinned)
        assert merged.count("xla_tpu_enable_latency_hiding_scheduler") == 1
        for name in PERF_FLAGS:
            assert name in merged

    def test_escape_hatch(self):
        from kubeflow_tpu.runtime.xla_flags import xla_perf_flags

        assert xla_perf_flags("--a=b", "off") == "--a=b"
        assert xla_perf_flags("--a=b", "0") == "--a=b"
        assert xla_perf_flags("--a=b", "--custom=1") == "--a=b --custom=1"

    def test_apply_idempotent(self, monkeypatch):
        from kubeflow_tpu.runtime import xla_flags

        monkeypatch.setenv("XLA_FLAGS", "")
        monkeypatch.delenv(xla_flags.ESCAPE_ENV, raising=False)
        assert xla_flags.apply_xla_perf_flags() is True
        first = __import__("os").environ["XLA_FLAGS"]
        assert xla_flags.apply_xla_perf_flags() is False
        assert __import__("os").environ["XLA_FLAGS"] == first
