"""The artifact:// storage scheme — the train→deploy seam ((U) KFP object
store → kserve storage-initializer; SURVEY.md §2.3#28 + §2.5#44, §3.4→§3.2):
tree artifacts, the name@version register, cross-subsystem resolution, and
the committed e2e — a pipeline trains a model, its artifact uri serves an
InferenceService, and train() consumes a published dataset."""

import json
import os
import time
import urllib.request

import jax
import numpy as np
import pytest

from kubeflow_tpu.pipelines.artifacts import (
    ARTIFACT_SCHEME, ROOT_ENV, SCHEME, ArtifactStore, publish_file,
    publish_model,
)

CORPUS = ("the quick brown fox jumps over the lazy dog. " * 40
          + "pack my box with five dozen liquor jugs. " * 40)


@pytest.fixture()
def store(tmp_path):
    return ArtifactStore(str(tmp_path / "artifacts"))


def _make_tree(root, files):
    for rel, content in files.items():
        p = os.path.join(root, rel)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(content)


class TestTreeArtifacts:
    def test_roundtrip_preserves_contents(self, store, tmp_path):
        src = str(tmp_path / "src")
        files = {"a.bin": b"alpha", "sub/b.bin": b"beta", "sub/deep/c": b"c"}
        _make_tree(src, files)
        uri = store.put_tree(src)
        assert uri.startswith(SCHEME)
        out = store.materialize_tree(uri)
        for rel, content in files.items():
            with open(os.path.join(out, rel), "rb") as f:
                assert f.read() == content

    def test_materialize_idempotent_and_shared(self, store, tmp_path):
        src = str(tmp_path / "src")
        _make_tree(src, {"x": b"1"})
        uri = store.put_tree(src)
        first = store.materialize_tree(uri)
        marker = os.path.join(first, ".complete")
        before = os.path.getmtime(marker)
        assert store.materialize_tree(uri) == first
        assert os.path.getmtime(marker) == before   # no re-write

    def test_trees_dedup_shared_files(self, store, tmp_path):
        big = b"shard-bytes" * 1000
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        _make_tree(a, {"shared.bin": big, "only_a": b"a"})
        _make_tree(b, {"shared.bin": big, "only_b": b"b"})
        ua, ub = store.put_tree(a), store.put_tree(b)
        assert ua != ub
        # 2 manifests + 3 distinct blobs (shared.bin stored once), plus
        # nothing else: count CAS files outside trees/named.
        blobs = sum(
            len(fs) for d, _, fs in os.walk(store.root)
            if not os.path.relpath(d, store.root).startswith(("trees",
                                                              "named")))
        assert blobs == 5

    def test_blob_is_not_a_tree(self, store):
        uri = store.put_bytes(b"raw dataset text")
        with pytest.raises(ValueError, match="not a tree artifact"):
            store.materialize_tree(uri)

    def test_blob_starting_with_T_is_not_a_tree(self, store):
        # Raw blobs are untagged; first-byte sniffing alone would call any
        # capitalized text corpus a tree and crash staging on json.loads.
        for payload in (b"THE SONNETS\nby William Shakespeare",
                        b'T{"not": "a manifest"}',
                        b'T{"kftpu_tree": "wrong shape"}'):
            assert not store.is_tree(store.put_bytes(payload))
        assert open(store.localize(
            "artifact://" + store.put_bytes(b"Titled corpus")[len(SCHEME):]
        ), "rb").read() == b"Titled corpus"

    def test_republish_of_materialized_tree_skips_marker(self, store,
                                                         tmp_path):
        src = str(tmp_path / "src")
        _make_tree(src, {"w": b"weights"})
        out = store.materialize_tree(store.put_tree(src))
        # Re-publishing the materialized dir must not capture .complete —
        # the manifests (and so the digests) of both publishes are equal.
        assert store.put_tree(out) == store.put_tree(src)


class TestRegister:
    def test_register_lookup_latest(self, store):
        u1 = store.put_bytes(b"v1")
        u2 = store.put_bytes(b"v2")
        art1 = store.register("corpus", "1", u1)
        assert art1 == f"{ARTIFACT_SCHEME}corpus@1"
        store.register("corpus", "2", u2)
        assert store.lookup("corpus", "1") == u1
        assert store.lookup("corpus") == u2
        assert store.versions("corpus") == ["1", "2"]

    def test_latest_orders_numerically(self, store):
        # "latest" must be version ORDER, not mtime (racy within a quantum)
        # or lexicographic ("10" < "9").
        u9, u10 = store.put_bytes(b"nine"), store.put_bytes(b"ten")
        store.register("m", "10", u10)      # registered FIRST on purpose
        store.register("m", "9", u9)
        assert store.versions("m") == ["9", "10"]
        assert store.lookup("m") == u10
        ua, ub = store.put_bytes(b"a"), store.put_bytes(b"b")
        store.register("d", "1.9", ua)
        store.register("d", "1.10", ub)
        assert store.lookup("d") == ub

    def test_traversal_names_rejected(self, store):
        # storage_uri / dataset_uri are user-facing: names must never reach
        # os.path.join un-validated.
        for ref in ("../..@x", "/etc@passwd", "..", "a/b@1"):
            with pytest.raises(ValueError):
                store.resolve(ARTIFACT_SCHEME + ref)

    def test_crashed_register_does_not_bind(self, store):
        # A crash mid-register must not leave name@version bound to "".
        # The write-then-link protocol means the entry either has the full
        # uri or does not exist; simulate the old failure by checking a
        # re-register after an interrupted attempt succeeds cleanly.
        u = store.put_bytes(b"x")
        store.register("m2", "1", u)
        assert store.lookup("m2", "1") == u
        store.register("m2", "1", u)        # idempotent re-register

    def test_versions_are_immutable(self, store):
        u1 = store.put_bytes(b"v1")
        u2 = store.put_bytes(b"v2")
        store.register("m", "1", u1)
        store.register("m", "1", u1)             # same content: no-op
        with pytest.raises(ValueError, match="immutable"):
            store.register("m", "1", u2)

    def test_register_requires_stored_content(self, store):
        with pytest.raises(FileNotFoundError):
            store.register("m", "1", SCHEME + "0" * 64)

    def test_bad_names_rejected(self, store):
        u = store.put_bytes(b"x")
        with pytest.raises(ValueError):
            store.register("has/slash", "1", u)
        with pytest.raises(ValueError):
            store.register("0" * 64, "1", u)     # digest-shaped name
        with pytest.raises(ValueError):
            store.register("m", "v@1", u)
        # 64 chars but not hex: a fine name.
        store.register("z" * 64, "1", u)


class TestResolveAndLocalize:
    def test_resolve_digest_form(self, store):
        cas = store.put_bytes(b"data")
        digest = cas[len(SCHEME):]
        assert store.resolve(ARTIFACT_SCHEME + digest) == cas

    def test_resolve_named_forms(self, store):
        cas = store.put_bytes(b"data")
        store.register("m", "7", cas)
        assert store.resolve(f"{ARTIFACT_SCHEME}m@7") == cas
        assert store.resolve(f"{ARTIFACT_SCHEME}m") == cas

    def test_resolve_unknown_name_raises(self, store):
        with pytest.raises(FileNotFoundError, match="no registered"):
            store.resolve(f"{ARTIFACT_SCHEME}ghost")

    def test_resolve_rejects_other_schemes(self, store):
        with pytest.raises(ValueError, match="not an artifact uri"):
            store.resolve("s3://bucket/key")

    def test_resolve_rejects_empty_version(self, store):
        cas = store.put_bytes(b"x")
        store.register("m", "1", cas)
        with pytest.raises(ValueError, match="bad version"):
            store.resolve(f"{ARTIFACT_SCHEME}m@")

    def test_localize_blob_and_tree(self, store, tmp_path):
        blob = store.put_bytes(b"corpus text")
        p = store.localize(blob)
        assert open(p, "rb").read() == b"corpus text"
        src = str(tmp_path / "t")
        _make_tree(src, {"f": b"1"})
        tree = store.put_tree(src)
        assert os.path.isdir(store.localize(tree))


class TestPublishHelpers:
    def test_publish_file_named(self, store, tmp_path):
        p = tmp_path / "data.txt"
        p.write_text(CORPUS)
        uri = publish_file(str(p), name="corpus", store=store)
        assert uri == f"{ARTIFACT_SCHEME}corpus@1"
        assert open(store.localize(uri)).read() == CORPUS

    def test_publish_model_digest_form(self, store, tmp_path):
        src = str(tmp_path / "ckpt")
        _make_tree(src, {"state/params": b"weights"})
        uri = publish_model(src, store=store)
        assert uri.startswith(ARTIFACT_SCHEME)
        out = store.localize(uri)
        assert open(os.path.join(out, "state/params"), "rb").read() == b"weights"

    def test_env_fallback(self, store, tmp_path, monkeypatch):
        monkeypatch.setenv(ROOT_ENV, store.root)
        p = tmp_path / "d.txt"
        p.write_text("x")
        uri = publish_file(str(p), name="envd")
        from kubeflow_tpu.pipelines.artifacts import artifact_store_from_env

        assert artifact_store_from_env().lookup("envd") == store.resolve(uri)

    def test_version_without_name_rejected(self, store, tmp_path):
        p = tmp_path / "d.txt"
        p.write_text("x")
        with pytest.raises(ValueError, match="version requires name"):
            publish_file(str(p), version="2", store=store)

    def test_no_root_is_a_clear_error(self, monkeypatch):
        monkeypatch.delenv(ROOT_ENV, raising=False)
        from kubeflow_tpu.pipelines.artifacts import artifact_store_from_env

        with pytest.raises(RuntimeError, match="KFTPU_ARTIFACT_ROOT"):
            artifact_store_from_env()


class TestStagingArtifactScheme:
    def test_stage_published_dataset(self, store, tmp_path, monkeypatch):
        from kubeflow_tpu.train.staging import stage_inputs

        monkeypatch.setenv(ROOT_ENV, store.root)
        src = tmp_path / "corpus.txt"
        src.write_text(CORPUS)
        uri = publish_file(str(src), name="corpus", store=store)
        out = stage_inputs(str(tmp_path / "job"), dataset_uri=uri,
                           train_tokenizer_vocab=280)
        assert open(out["dataset"]).read() == CORPUS
        assert os.path.exists(out["tokenizer"])

    def test_tree_dataset_rejected(self, store, tmp_path, monkeypatch):
        from kubeflow_tpu.train.staging import stage_inputs

        monkeypatch.setenv(ROOT_ENV, store.root)
        src = str(tmp_path / "t")
        _make_tree(src, {"f": b"1"})
        uri = ARTIFACT_SCHEME + store.put_tree(src)[len(SCHEME):]
        with pytest.raises(ValueError, match="tree artifact"):
            stage_inputs(str(tmp_path / "job"), dataset_uri=uri)


class TestLoadParamsArtifact:
    def test_serving_loads_published_checkpoint(self, store, tmp_path):
        """Train-side orbax save → publish_model → serve-side load_params
        restores the identical param tree through artifact://name@ver."""
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import init_decoder_params
        from kubeflow_tpu.serve.storage import load_params
        from kubeflow_tpu.train.checkpoint import CheckpointManager

        cfg = preset("tiny", vocab_size=512)
        params = init_decoder_params(jax.random.PRNGKey(7), cfg)
        ckpt = str(tmp_path / "ckpt")
        mgr = CheckpointManager(ckpt)
        mgr.save(3, {"params": params}, force=True)
        mgr.wait()
        mgr.close()
        uri = publish_model(ckpt, name="m0", version="1", store=store)
        got = load_params(uri, cfg, artifact_root=store.root)
        jax.tree.map(np.testing.assert_array_equal, params,
                     jax.tree.map(np.asarray, got))


# -- the committed e2e seams --------------------------------------------------


@pytest.fixture()
def live_cp(tmp_path):
    from kubeflow_tpu.operator.control_plane import (
        ControlPlane, ControlPlaneConfig,
    )
    from kubeflow_tpu.runtime.topology import Cluster, SliceTopology

    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="cpu",
                                              dims=(2, 2))]),
        platform="cpu"))
    plane.start()
    yield plane
    plane.stop()


def _post(url: str, body: dict, timeout=180) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.mark.slow
def test_pipeline_trains_publishes_and_serves(live_cp, tmp_path):
    """VERDICT r3 #1 done-criterion: a pipeline trains a model, publishes
    the orbax checkpoint as a typed Model artifact (with lineage), and the
    artifact uri — no file path — serves an InferenceService."""
    from kubeflow_tpu.core.object import ObjectMeta
    from kubeflow_tpu.core.pipeline_specs import (
        PipelineRun, PipelineRunSpec, RunPhase,
    )
    from kubeflow_tpu.core.serving import (
        BatchingSpec, InferenceService, InferenceServiceSpec, ModelSpec,
        PredictorSpec,
    )
    from kubeflow_tpu.pipelines import dsl
    from kubeflow_tpu.pipelines.compiler import compile_pipeline

    ckpt_dir = str(tmp_path / "pipeckpt")

    @dsl.component
    def train_tiny(steps: int) -> str:
        from kubeflow_tpu.runtime.mesh import build_mesh
        from kubeflow_tpu.train.trainer import Trainer, TrainerConfig

        cfg = TrainerConfig(
            model="tiny", model_overrides={"vocab_size": 512},
            steps=steps, data={"global_batch": 8},
            checkpoint_dir=ckpt_dir, checkpoint_every=steps)
        Trainer(cfg, build_mesh({"data": 8}),
                workdir=str(tmp_path / "pipework")).run()
        return publish_model(ckpt_dir, name="pipe-model", version="1")

    @dsl.pipeline(name="train-and-publish")
    def train_and_publish(steps: int = 2):
        train_tiny(steps=steps)

    run = live_cp.submit(PipelineRun(
        metadata=ObjectMeta(name="tp1"),
        spec=PipelineRunSpec(ir=compile_pipeline(train_and_publish))))
    done = live_cp.wait_for(run, "Succeeded", timeout=300)
    assert done.status.phase is RunPhase.SUCCEEDED
    uri = done.status.tasks["train_tiny"].outputs["output"]
    assert uri == f"{ARTIFACT_SCHEME}pipe-model@1"

    # Lineage: a typed Model artifact exists, carries the register name,
    # was OUTPUT by the training execution, and is attributed to the run.
    from kubeflow_tpu.pipelines import metadata as md

    md_store = live_cp.pipelinerun_reconciler.metadata
    model_aids = md_store.artifacts_of_type("Model")
    assert model_aids, "publish_model recorded no Model artifact"
    art = md_store.get_artifact(model_aids[-1])
    assert art["properties"]["name"] == "pipe-model"
    evs = md_store.events_by_artifact(model_aids[-1])
    assert any(etype == md.EVENT_OUTPUT for _eid, etype in evs)
    train_eid = done.status.tasks["train_tiny"].execution_id
    assert train_eid in [eid for eid, _ in evs]

    # The served seam: the artifact uri IS the storageUri.
    isvc = live_cp.submit(InferenceService(
        metadata=ObjectMeta(name="from-artifact"),
        spec=InferenceServiceSpec(predictor=PredictorSpec(
            model=ModelSpec(
                model_name="from-artifact",
                storage_uri=uri,
                config={"preset": "tiny", "overrides": {"vocab_size": 512}}),
            batching=BatchingSpec(max_batch_size=2, max_seq_len=64,
                                  prefill_buckets=[32])))))
    ready = live_cp.wait_for(isvc, "Ready", timeout=240)
    out = _post(ready.status.url + "/v1/completions",
                {"prompt": "hello", "max_tokens": 4})
    assert out["usage"]["completion_tokens"] >= 1


@pytest.mark.slow
def test_train_consumes_published_dataset(live_cp, tmp_path):
    """The other half of the seam: train() staging a dataset published into
    the platform store, resolved inside a separate worker process through
    the control-plane-injected KFTPU_ARTIFACT_ROOT."""
    from kubeflow_tpu.sdk import Client

    client = Client(live_cp)
    corpus = tmp_path / "corpus.txt"
    corpus.write_text(CORPUS)
    uri = client.publish_file(str(corpus), name="corpus")
    job = client.train(
        "from-published", model="tiny",
        model_overrides={"vocab_size": 512, "max_seq_len": 32},
        steps=4, dataset_uri=uri, train_tokenizer_vocab=280,
        data={"global_batch": 4}, checkpoint=False,
        wait=True, timeout=300)
    assert job.status.metrics.step >= 4
    assert job.status.metrics.loss is not None
