"""Platform surface: REST API server (CRUD, events, metrics, authz) and the
CLI — the L6/L7 gateway analogs of SURVEY.md's layer map."""

import json
import os
import urllib.request

import pytest
import yaml

from kubeflow_tpu.core.jobs import JAXJob
from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.core.workspace_specs import Profile, ProfileSpec
from kubeflow_tpu.operator.control_plane import ControlPlane, ControlPlaneConfig
from kubeflow_tpu.platform.api_server import ApiServer
from kubeflow_tpu.runtime.topology import Cluster, SliceTopology

JOB_MANIFEST = {
    "apiVersion": "training.tpu.kubeflow.dev/v1",
    "kind": "JAXJob",
    "metadata": {"name": "api-job", "namespace": "default"},
    "spec": {"replica_specs": {"worker": {
        "replicas": 1,
        "template": {"entrypoint": "noop"},
        "resources": {"tpu_chips": 1}}}},
}


@pytest.fixture()
def api(tmp_path):
    cp = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="v5e",
                                              dims=(2, 2))]),
        launch_processes=False,
        metrics_sync_interval=None,
    ))
    server = ApiServer(cp, port=0)   # ephemeral port
    server.start()
    yield cp, server
    server.stop()


def call(server, method, path, body=None, user=None):
    req = urllib.request.Request(server.url + path, data=body, method=method)
    if user:
        req.add_header("X-Kftpu-User", user)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            data = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            code = resp.status
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")
    return code, (json.loads(data) if "json" in ctype else data.decode())


class TestApiServer:
    def test_crud_round_trip(self, api):
        cp, server = api
        code, out = call(server, "POST", "/apis",
                         json.dumps(JOB_MANIFEST).encode())
        assert code == 200 and out["metadata"]["name"] == "api-job"
        cp.step()   # controller picks it up
        code, out = call(server, "GET", "/apis/jaxjobs?namespace=default")
        assert code == 200 and len(out["items"]) == 1
        code, out = call(server, "GET", "/apis/JAXJob/default/api-job")
        assert code == 200
        assert out["kind"] == "JAXJob"
        code, out = call(server, "DELETE", "/apis/jaxjobs/default/api-job")
        assert code == 200
        assert cp.store.try_get(JAXJob, "api-job") is None

    def test_yaml_manifest_accepted(self, api):
        _, server = api
        code, out = call(server, "POST", "/apis",
                         yaml.safe_dump(JOB_MANIFEST).encode())
        assert code == 200

    def test_unknown_kind_and_missing(self, api):
        _, server = api
        assert call(server, "GET", "/apis/nonsense")[0] == 404
        assert call(server, "GET", "/apis/jaxjobs/default/nope")[0] == 404
        code, out = call(server, "POST", "/apis", b"kind: Bogus\n")
        assert code == 400

    def test_healthz_kinds_events(self, api):
        cp, server = api
        assert call(server, "GET", "/healthz")[1] == {"ok": True}
        code, out = call(server, "GET", "/apis")
        assert "JAXJob" in out["kinds"] and "Experiment" in out["kinds"]
        call(server, "POST", "/apis", json.dumps(JOB_MANIFEST).encode())
        cp.step()
        code, out = call(server, "GET", "/events")
        assert code == 200 and out["items"]

    def test_metrics_endpoint(self, api):
        cp, server = api
        call(server, "POST", "/apis", json.dumps(JOB_MANIFEST).encode())
        cp.step()
        code, text = call(server, "GET", "/metrics")
        assert code == 200
        assert 'kftpu_objects{kind="JAXJob"' in text
        assert "kftpu_chips_total 4" in text

    def test_kfam_authz(self, api):
        cp, server = api
        cp.submit(Profile(metadata=ObjectMeta(name="team-a"),
                          spec=ProfileSpec(owner="alice",
                                           contributors=["bob"])))
        manifest = dict(JOB_MANIFEST,
                        metadata={"name": "j", "namespace": "team-a"})
        body = json.dumps(manifest).encode()
        assert call(server, "POST", "/apis", body, user="eve")[0] == 403
        assert call(server, "POST", "/apis", body, user="bob")[0] == 200
        assert call(server, "DELETE", "/apis/jaxjobs/team-a/j",
                    user="eve")[0] == 403
        assert call(server, "DELETE", "/apis/jaxjobs/team-a/j",
                    user="alice")[0] == 200


class TestCli:
    def test_get_describe_metrics(self, api, capsys, tmp_path):
        cp, server = api
        from kubeflow_tpu import cli

        mf = tmp_path / "job.yaml"
        mf.write_text(yaml.safe_dump(JOB_MANIFEST))
        assert cli.main(["apply", "-f", str(mf),
                         "--server", server.url]) == 0
        cp.step()
        assert cli.main(["get", "jaxjobs", "--server", server.url]) == 0
        out = capsys.readouterr().out
        assert "api-job" in out
        assert cli.main(["describe", "jaxjobs", "api-job",
                         "--server", server.url]) == 0
        out = capsys.readouterr().out
        assert "JAXJob" in out and "Events:" in out
        assert cli.main(["metrics", "--server", server.url]) == 0
        assert "kftpu_objects" in capsys.readouterr().out
        assert cli.main(["delete", "jaxjobs", "api-job",
                         "--server", server.url]) == 0

    def test_server_unreachable_is_friendly(self):
        from kubeflow_tpu import cli

        with pytest.raises(SystemExit, match="cannot reach"):
            cli.main(["get", "jaxjobs", "--server", "http://127.0.0.1:1"])


class TestCliRun:
    def test_one_shot_run(self, tmp_path, capsys):
        from kubeflow_tpu import cli

        mf = tmp_path / "job.yaml"
        mf.write_text(yaml.safe_dump({
            **JOB_MANIFEST,
            "metadata": {"name": "oneshot", "namespace": "default"},
        }))
        rc = cli.main(["run", "-f", str(mf), "--timeout", "60",
                       "--base-dir", str(tmp_path / "state")])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "Succeeded" in out


class TestVolumes:
    """Volume browser (pvcviewer + volumes-web-app analog, (U) kubeflow
    components/pvcviewer-controller + crud-web-apps/volumes): list, browse,
    download, delete over the platform's per-workload storage."""

    def _seed(self, cp, tmp_path):
        vol = tmp_path / "default" / "train-1"
        (vol / "ckpt").mkdir(parents=True)
        (vol / "metrics.jsonl").write_text('{"step":1,"loss":2.0}\n')
        (vol / "ckpt" / "state.bin").write_bytes(b"\x00" * 64)
        return vol

    def test_list_browse_download(self, api, tmp_path):
        cp, server = api
        self._seed(cp, tmp_path)
        code, got = call(server, "GET", "/volumes/default")
        assert code == 200
        (v,) = got["volumes"]
        assert v["name"] == "train-1" and v["used_bytes"] > 0
        code, got = call(server, "GET", "/volumes/default/train-1")
        assert code == 200
        paths = {f["path"] for f in got["files"]}
        assert paths == {"metrics.jsonl", os.path.join("ckpt", "state.bin")}
        code, raw = call(server, "GET",
                         "/volumes/default/train-1/files/metrics.jsonl")
        assert code == 200 and "loss" in raw

    def test_create_and_delete(self, api, tmp_path):
        cp, server = api
        code, got = call(server, "POST", "/volumes/default/scratch", body=b"")
        assert code == 200
        assert (tmp_path / "default" / "scratch").is_dir()
        vol = self._seed(cp, tmp_path)
        code, got = call(server, "DELETE",
                         "/volumes/default/train-1/files/metrics.jsonl")
        assert code == 200
        assert not (vol / "metrics.jsonl").exists()
        code, got = call(server, "DELETE", "/volumes/default/train-1")
        assert code == 200
        assert not vol.exists()

    def test_traversal_blocked(self, api, tmp_path):
        cp, server = api
        self._seed(cp, tmp_path)
        (tmp_path / "secret.txt").write_text("s3cret")
        for path in ("/volumes/default/train-1/files/../../secret.txt",
                     "/volumes/default/../secret.txt"):
            code, got = call(server, "GET", path)
            assert code == 404, path
        # and namespace ".." can't escape the base dir
        code, got = call(server, "GET", "/volumes/../default/train-1")
        assert code in (400, 404)

    def test_namespace_authz(self, api, tmp_path):
        from kubeflow_tpu.core.object import ObjectMeta
        from kubeflow_tpu.core.workspace_specs import Profile, ProfileSpec

        cp, server = api
        self._seed(cp, tmp_path)
        cp.submit(Profile(metadata=ObjectMeta(name="default"),
                          spec=ProfileSpec(owner="alice")))
        code, _ = call(server, "GET", "/volumes/default", user="mallory")
        assert code == 403
        code, _ = call(server, "GET", "/volumes/default", user="alice")
        assert code == 200


def test_cli_volumes(api, tmp_path, capsys):
    from kubeflow_tpu import cli

    cp, server = api
    vol = tmp_path / "default" / "train-1"
    vol.mkdir(parents=True)
    (vol / "metrics.jsonl").write_text('{"step":1}\n')
    for argv, want in (
        (["volumes"], "train-1"),
        (["volumes", "train-1"], "metrics.jsonl"),
        (["volumes", "train-1", "metrics.jsonl"], '"step"'),
    ):
        rc = cli.main(argv + ["--server", server.url])
        assert rc == 0
        assert want in capsys.readouterr().out


def test_volumes_dot_segments_and_encoded_names(api, tmp_path):
    """Review regressions: '.'/'..' segments must not remap the path after
    authz (DELETE /volumes/./default once rmtree'd a namespace the caller
    couldn't touch by name), and percent-encoded file names round-trip."""
    from kubeflow_tpu.core.object import ObjectMeta
    from kubeflow_tpu.core.workspace_specs import Profile, ProfileSpec

    cp, server = api
    vol = tmp_path / "default" / "train-1"
    vol.mkdir(parents=True)
    (vol / "eval results.json").write_text('{"acc": 1}')
    cp.submit(Profile(metadata=ObjectMeta(name="default"),
                      spec=ProfileSpec(owner="alice")))

    for path in ("/volumes/./default", "/volumes/.."):
        code, _ = call(server, "GET", path, user="mallory")
        assert code in (400, 404), path
    code, _ = call(server, "DELETE", "/volumes/./default", user="mallory")
    assert code in (400, 404)
    code, _ = call(server, "DELETE", "/volumes/default/.", user="alice")
    assert code in (400, 404)
    assert vol.exists()

    # Percent-encoded names download and delete.
    code, raw = call(server, "GET",
                     "/volumes/default/train-1/files/eval%20results.json",
                     user="alice")
    assert code == 200 and "acc" in raw
    code, _ = call(server, "DELETE",
                   "/volumes/default/train-1/files/eval%20results.json",
                   user="alice")
    assert code == 200
    assert not (vol / "eval results.json").exists()
    # And the CLI sends identity on volume routes (mallory refused).
    from kubeflow_tpu import cli

    with pytest.raises(SystemExit, match="403"):
        cli.main(["volumes", "--server", server.url, "--user", "mallory"])


class TestNotebookForm:
    """Spawner form backend ((U) jupyter-web-app post_notebook): flat form
    JSON -> Notebook CR through the gateway."""

    def test_form_config(self, api):
        cp, server = api
        code, got = call(server, "GET", "/notebooks/form/config")
        assert code == 200
        assert got["accelerator"]["resource"] == "google.com/tpu"
        # The image family is enumerated from the kernel-profile registry.
        assert got["images"] == ["base", "jax-full", "jax-notebook"]
        assert got["default_image"] == "jax-notebook"
        assert "flax" in got["image_profiles"]["jax-full"]["packages"]
        assert got["image_profiles"]["base"]["description"]

    def test_spawn_from_form(self, api):
        from kubeflow_tpu.core.workspace_specs import Notebook

        cp, server = api
        form = {"name": "nb1", "tpu_chips": 4,
                "env": {"SEED": 7}, "idle_cull_seconds": 600,
                "pod_default_labels": {"team": "ml"}}
        code, got = call(server, "POST", "/notebooks/form",
                         body=json.dumps(form).encode())
        assert code == 200
        nb = cp.store.get(Notebook, "nb1")
        assert nb.spec.resources.tpu_chips == 4
        assert nb.spec.env == {"SEED": "7"}
        assert nb.spec.idle_cull_seconds == 600
        assert nb.spec.pod_default_labels == {"team": "ml"}

    def test_bad_form_and_authz(self, api):
        from kubeflow_tpu.core.object import ObjectMeta
        from kubeflow_tpu.core.workspace_specs import Profile, ProfileSpec

        cp, server = api
        code, _ = call(server, "POST", "/notebooks/form", body=b"{}")
        assert code == 400                       # name required
        cp.submit(Profile(metadata=ObjectMeta(name="default"),
                          spec=ProfileSpec(owner="alice")))
        code, _ = call(server, "POST", "/notebooks/form",
                       body=json.dumps({"name": "nb2"}).encode(),
                       user="mallory")
        assert code == 403


def test_notebook_form_zero_cull_and_bad_body(api):
    from kubeflow_tpu.core.workspace_specs import Notebook

    cp, server = api
    code, _ = call(server, "POST", "/notebooks/form",
                   body=json.dumps({"name": "nb0",
                                    "idle_cull_seconds": 0}).encode())
    assert code == 200
    assert cp.store.get(Notebook, "nb0").spec.idle_cull_seconds is None
    for body in (b"[]", b'"x"', b"5"):
        code, _ = call(server, "POST", "/notebooks/form", body=body)
        assert code == 400, body


class TestDashboard:
    """centraldashboard-analog aggregation surface (SURVEY.md §2.1#7)."""

    def test_dashboard_counts_and_rollups(self, api, capsys):
        cp, server = api
        cp.submit(JAXJob.from_manifest(JOB_MANIFEST))
        m2 = dict(JOB_MANIFEST, metadata={"name": "other-job",
                                          "namespace": "team-a"})
        cp.submit(JAXJob.from_manifest(m2))
        cp.submit(Profile(metadata=ObjectMeta(name="team-a"),
                          spec=ProfileSpec(owner="alice")))
        # A status-less kind must not 500 the aggregation (Pipeline has
        # only metadata+spec).
        from kubeflow_tpu.core.pipeline_specs import (
            Pipeline, PipelineIR, PipelineSpecModel)
        cp.submit(Pipeline(
            metadata=ObjectMeta(name="p1"),
            spec=PipelineSpecModel(ir=PipelineIR(name="p1"))))
        code, data = call(server, "GET", "/dashboard")
        assert code == 200
        assert "Pipeline" in data["namespaces"]["default"]["kinds"]
        assert data["namespaces"]["default"]["kinds"]["JAXJob"]["total"] == 1
        assert data["namespaces"]["team-a"]["kinds"]["JAXJob"]["total"] == 1
        # Profiles are namespaced under "default" (the profile NAME is the
        # namespace it manages).
        assert "Profile" in data["namespaces"]["default"]["kinds"]
        # Condition rollup buckets exist per state.
        row = data["namespaces"]["default"]["kinds"]["JAXJob"]
        assert sum(row["by_state"].values()) == row["total"]
        assert "links" in data and data["links"]["metrics"] == "/metrics"

        # HTML form renders the same table.
        code, html = call(server, "GET", "/dashboard?format=html")
        assert code == 200 and "<table" in html and "team-a" in html

        # CLI renders it.
        from kubeflow_tpu.cli import main as cli_main
        rc = cli_main(["dashboard", "--server", server.url])
        assert rc == 0
        out = capsys.readouterr().out
        assert "JAXJob" in out and "team-a" in out


def test_dashboard_html_escapes_user_fields(api):
    """Stored-markup injection: object/event fields render escaped."""
    cp, server = api
    from kubeflow_tpu.core.object import ObjectMeta
    from kubeflow_tpu.core.workspace_specs import Notebook, NotebookSpec

    nb = Notebook(metadata=ObjectMeta(name="evil"),
                  spec=NotebookSpec(image="<script>alert(1)</script>"))
    cp.submit(nb)
    cp.recorder.warning(nb, "UnknownImage",
                        "kernel profile '<script>alert(1)</script>'")
    code, html = call(server, "GET", "/dashboard?format=html")
    assert code == 200
    assert "<script>alert(1)</script>" not in html
    assert "&lt;script&gt;" in html


class TestArtifactsSurface:
    """The register's read surface: /artifacts routes + kftpu artifacts —
    what an operator checks before pointing a storageUri at a version."""

    def _publish(self, cp, tmp_path):
        from kubeflow_tpu.pipelines.artifacts import publish_file, publish_model

        corpus = tmp_path / "c.txt"
        corpus.write_text("hello " * 100)
        publish_file(str(corpus), name="corpus", store=cp.artifact_store)
        ckpt = tmp_path / "ckpt"
        (ckpt / "sub").mkdir(parents=True)
        (ckpt / "sub" / "w").write_bytes(b"weights" * 50)
        publish_model(str(ckpt), name="m", version="1",
                      store=cp.artifact_store)
        publish_model(str(ckpt), name="m", version="2",
                      store=cp.artifact_store)

    def test_routes(self, api, tmp_path):
        cp, server = api
        self._publish(cp, tmp_path)
        code, out = call(server, "GET", "/artifacts")
        assert code == 200 and out["names"] == ["corpus", "m"]
        assert out["items"]["m"]["latest"] == "2"
        assert out["items"]["m"]["kind"] == "tree"
        # Dedup-aware size: v1 and v2 are IDENTICAL trees — the shared
        # blob counts once.
        assert out["items"]["m"]["bytes"] == 7 * 50
        code, out = call(server, "GET", "/artifacts/m")
        assert code == 200 and out["latest"] == "2"
        assert out["versions"]["1"]["kind"] == "tree"
        assert out["versions"]["1"]["files"] == 1
        code, out = call(server, "GET", "/artifacts/corpus/1")
        assert code == 200 and out["kind"] == "blob"
        assert out["artifact_uri"] == "artifact://corpus@1"
        code, _ = call(server, "GET", "/artifacts/ghost")
        assert code == 404
        code, _ = call(server, "GET", "/artifacts/..%2F..%2Fetc/passwd")
        assert code == 400          # traversal-shaped names rejected

    def test_cli(self, api, tmp_path, capsys):
        from kubeflow_tpu.cli import main as cli_main

        cp, server = api
        self._publish(cp, tmp_path)
        assert cli_main(["artifacts", "--server", server.url]) == 0
        out = capsys.readouterr().out
        assert "corpus" in out and "latest=@2" in out
        assert cli_main(["artifacts", "m", "--server", server.url]) == 0
        out = capsys.readouterr().out
        assert "artifact://m@2" in out and "tree" in out

    def test_cli_survives_broken_entry(self, api, tmp_path, capsys):
        """A register entry whose blob was pruned outside the platform is
        degraded to kind="broken" by the server; the CLI must print it,
        not die with KeyError('bytes') — exactly the catalog state the
        server-side degradation was built to survive."""
        import os

        from kubeflow_tpu.cli import main as cli_main

        cp, server = api
        self._publish(cp, tmp_path)
        # Dangle every blob: remove the CAS objects (root/<2-hex>/<digest>)
        # behind the register's back.
        root = cp.artifact_store.root
        for d in os.listdir(root):
            full = os.path.join(root, d)
            if len(d) == 2 and os.path.isdir(full):
                for f in os.listdir(full):
                    os.unlink(os.path.join(full, f))
        assert cli_main(["artifacts", "--server", server.url]) == 0
        out = capsys.readouterr().out
        assert "BROKEN" in out
        assert cli_main(["artifacts", "corpus", "--server", server.url]) == 0
        out = capsys.readouterr().out
        assert "BROKEN" in out
