"""Platform surface: REST API server (CRUD, events, metrics, authz) and the
CLI — the L6/L7 gateway analogs of SURVEY.md's layer map."""

import json
import urllib.request

import pytest
import yaml

from kubeflow_tpu.core.jobs import JAXJob
from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.core.workspace_specs import Profile, ProfileSpec
from kubeflow_tpu.operator.control_plane import ControlPlane, ControlPlaneConfig
from kubeflow_tpu.platform.api_server import ApiServer
from kubeflow_tpu.runtime.topology import Cluster, SliceTopology

JOB_MANIFEST = {
    "apiVersion": "training.tpu.kubeflow.dev/v1",
    "kind": "JAXJob",
    "metadata": {"name": "api-job", "namespace": "default"},
    "spec": {"replica_specs": {"worker": {
        "replicas": 1,
        "template": {"entrypoint": "noop"},
        "resources": {"tpu_chips": 1}}}},
}


@pytest.fixture()
def api(tmp_path):
    cp = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="v5e",
                                              dims=(2, 2))]),
        launch_processes=False,
        metrics_sync_interval=None,
    ))
    server = ApiServer(cp, port=0)   # ephemeral port
    server.start()
    yield cp, server
    server.stop()


def call(server, method, path, body=None, user=None):
    req = urllib.request.Request(server.url + path, data=body, method=method)
    if user:
        req.add_header("X-Kftpu-User", user)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            data = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            code = resp.status
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")
    return code, (json.loads(data) if "json" in ctype else data.decode())


class TestApiServer:
    def test_crud_round_trip(self, api):
        cp, server = api
        code, out = call(server, "POST", "/apis",
                         json.dumps(JOB_MANIFEST).encode())
        assert code == 200 and out["metadata"]["name"] == "api-job"
        cp.step()   # controller picks it up
        code, out = call(server, "GET", "/apis/jaxjobs?namespace=default")
        assert code == 200 and len(out["items"]) == 1
        code, out = call(server, "GET", "/apis/JAXJob/default/api-job")
        assert code == 200
        assert out["kind"] == "JAXJob"
        code, out = call(server, "DELETE", "/apis/jaxjobs/default/api-job")
        assert code == 200
        assert cp.store.try_get(JAXJob, "api-job") is None

    def test_yaml_manifest_accepted(self, api):
        _, server = api
        code, out = call(server, "POST", "/apis",
                         yaml.safe_dump(JOB_MANIFEST).encode())
        assert code == 200

    def test_unknown_kind_and_missing(self, api):
        _, server = api
        assert call(server, "GET", "/apis/nonsense")[0] == 404
        assert call(server, "GET", "/apis/jaxjobs/default/nope")[0] == 404
        code, out = call(server, "POST", "/apis", b"kind: Bogus\n")
        assert code == 400

    def test_healthz_kinds_events(self, api):
        cp, server = api
        assert call(server, "GET", "/healthz")[1] == {"ok": True}
        code, out = call(server, "GET", "/apis")
        assert "JAXJob" in out["kinds"] and "Experiment" in out["kinds"]
        call(server, "POST", "/apis", json.dumps(JOB_MANIFEST).encode())
        cp.step()
        code, out = call(server, "GET", "/events")
        assert code == 200 and out["items"]

    def test_metrics_endpoint(self, api):
        cp, server = api
        call(server, "POST", "/apis", json.dumps(JOB_MANIFEST).encode())
        cp.step()
        code, text = call(server, "GET", "/metrics")
        assert code == 200
        assert 'kftpu_objects{kind="JAXJob"' in text
        assert "kftpu_chips_total 4" in text

    def test_kfam_authz(self, api):
        cp, server = api
        cp.submit(Profile(metadata=ObjectMeta(name="team-a"),
                          spec=ProfileSpec(owner="alice",
                                           contributors=["bob"])))
        manifest = dict(JOB_MANIFEST,
                        metadata={"name": "j", "namespace": "team-a"})
        body = json.dumps(manifest).encode()
        assert call(server, "POST", "/apis", body, user="eve")[0] == 403
        assert call(server, "POST", "/apis", body, user="bob")[0] == 200
        assert call(server, "DELETE", "/apis/jaxjobs/team-a/j",
                    user="eve")[0] == 403
        assert call(server, "DELETE", "/apis/jaxjobs/team-a/j",
                    user="alice")[0] == 200


class TestCli:
    def test_get_describe_metrics(self, api, capsys, tmp_path):
        cp, server = api
        from kubeflow_tpu import cli

        mf = tmp_path / "job.yaml"
        mf.write_text(yaml.safe_dump(JOB_MANIFEST))
        assert cli.main(["apply", "-f", str(mf),
                         "--server", server.url]) == 0
        cp.step()
        assert cli.main(["get", "jaxjobs", "--server", server.url]) == 0
        out = capsys.readouterr().out
        assert "api-job" in out
        assert cli.main(["describe", "jaxjobs", "api-job",
                         "--server", server.url]) == 0
        out = capsys.readouterr().out
        assert "JAXJob" in out and "Events:" in out
        assert cli.main(["metrics", "--server", server.url]) == 0
        assert "kftpu_objects" in capsys.readouterr().out
        assert cli.main(["delete", "jaxjobs", "api-job",
                         "--server", server.url]) == 0

    def test_server_unreachable_is_friendly(self):
        from kubeflow_tpu import cli

        with pytest.raises(SystemExit, match="cannot reach"):
            cli.main(["get", "jaxjobs", "--server", "http://127.0.0.1:1"])


class TestCliRun:
    def test_one_shot_run(self, tmp_path, capsys):
        from kubeflow_tpu import cli

        mf = tmp_path / "job.yaml"
        mf.write_text(yaml.safe_dump({
            **JOB_MANIFEST,
            "metadata": {"name": "oneshot", "namespace": "default"},
        }))
        rc = cli.main(["run", "-f", str(mf), "--timeout", "60",
                       "--base-dir", str(tmp_path / "state")])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "Succeeded" in out
