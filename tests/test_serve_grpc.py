"""gRPC v2 open-inference-protocol endpoint: same engine, same answers as
REST ((U) kserve kserve/protocol/grpc; SURVEY.md §2.3#26 — the reference's
v2 is REST+gRPC, so is ours)."""

import json
import urllib.request

import grpc
import jax
import pytest

from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine
from kubeflow_tpu.serve.grpc_server import oip_stub
from kubeflow_tpu.serve.protos import oip_pb2 as pb
from kubeflow_tpu.serve.server import ModelServer


@pytest.fixture(scope="module")
def server():
    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    engine = LLMEngine(cfg, BatchingSpec(
        max_batch_size=2, max_seq_len=96, prefill_buckets=[16, 32]),
        params=params)
    srv = ModelServer("llm", engine, grpc_port=0)
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def stub(server):
    channel = grpc.insecure_channel(server.grpc_server.target)
    yield oip_stub(channel)
    channel.close()


def test_health_rpcs(stub):
    assert stub.ServerLive(pb.ServerLiveRequest()).live
    assert stub.ServerReady(pb.ServerReadyRequest()).ready
    assert stub.ModelReady(pb.ModelReadyRequest(name="llm")).ready
    assert not stub.ModelReady(pb.ModelReadyRequest(name="nope")).ready


def test_server_and_model_metadata(stub):
    meta = stub.ServerMetadata(pb.ServerMetadataRequest())
    assert meta.name == "llm"
    mm = stub.ModelMetadata(pb.ModelMetadataRequest(name="llm"))
    assert mm.platform == "kubeflow-tpu-llm"
    assert mm.inputs[0].datatype == "BYTES"
    with pytest.raises(grpc.RpcError) as exc:
        stub.ModelMetadata(pb.ModelMetadataRequest(name="nope"))
    assert exc.value.code() == grpc.StatusCode.NOT_FOUND


def test_infer_matches_rest(server, stub):
    """The gRPC and REST v2 surfaces share one engine: greedy answers must
    be identical."""
    req = pb.ModelInferRequest(model_name="llm")
    req.parameters["max_tokens"].int64_param = 6
    req.parameters["temperature"].double_param = 0.0
    tin = req.inputs.add(name="text", datatype="BYTES", shape=[1])
    tin.contents.bytes_contents.append(b"hello tpu")
    out = stub.ModelInfer(req)
    assert out.model_name == "llm"
    grpc_text = out.outputs[0].contents.bytes_contents[0].decode()

    body = json.dumps({"inputs": [{"name": "text", "datatype": "BYTES",
                                   "shape": [1], "data": ["hello tpu"]}],
                       "max_tokens": 6, "temperature": 0.0}).encode()
    http_req = urllib.request.Request(
        server.url + "/v2/models/llm/infer", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(http_req, timeout=120) as r:
        rest_text = json.loads(r.read())["outputs"][0]["data"][0]
    assert grpc_text == rest_text
    assert len(grpc_text) > 0


def test_infer_bad_datatype_rejected(stub):
    req = pb.ModelInferRequest(model_name="llm")
    tin = req.inputs.add(name="ids", datatype="INT32", shape=[2])
    tin.contents.int_contents.extend([1, 2])
    with pytest.raises(grpc.RpcError) as exc:
        stub.ModelInfer(req)
    assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_infer_unknown_model(stub):
    req = pb.ModelInferRequest(model_name="ghost")
    tin = req.inputs.add(name="text", datatype="BYTES", shape=[1])
    tin.contents.bytes_contents.append(b"x")
    with pytest.raises(grpc.RpcError) as exc:
        stub.ModelInfer(req)
    assert exc.value.code() == grpc.StatusCode.NOT_FOUND
