"""MoE serving paths (VERDICT r3 #3): prefill runs the training dispatch
path (per-request, batch-independent by construction), decode offers a
zero-drop dispatch variant — both pinned token-exact against the dense
oracle in fp32 (bf16 argmax flips one-ulp across formulations)."""

import dataclasses

import jax
import pytest

from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams


@pytest.fixture(scope="module")
def cfg():
    # fp32 for exactness; ample capacity so the per-request prefill
    # dispatch provably matches dense (zero drops possible).
    c = preset("tiny-moe", dtype="float32")
    return dataclasses.replace(c, capacity_factor=float(c.num_experts))


@pytest.fixture(scope="module")
def params(cfg):
    return init_decoder_params(jax.random.PRNGKey(0), cfg)


def _engine(cfg, params, **knobs):
    return LLMEngine(
        cfg,
        BatchingSpec(max_batch_size=4, max_seq_len=96,
                     prefill_buckets=[16, 32], **knobs),
        params=params)


PROMPTS = [[5, 17, 3, 99, 42], [7] * 20, [9, 8, 7, 6, 5, 4], [30, 31]]


def _generate_all(eng, n_new=10):
    reqs = [eng.submit(p, SamplingParams(max_new_tokens=n_new))
            for p in PROMPTS]
    while not all(r.done.is_set() for r in reqs):
        eng.step()
    return [r.output_tokens for r in reqs]


class TestMoEServingImpls:
    def test_default_resolution(self, cfg, params):
        eng = _engine(cfg, params)
        assert eng._cfg_prefill.moe_impl == "dispatch"
        assert eng._cfg_decode.moe_impl == "dense"

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_prefill_dispatch_token_exact_vs_dense(self, cfg, params):
        dense = _engine(cfg, params, moe_prefill_impl="dense")
        disp = _engine(cfg, params, moe_prefill_impl="dispatch")
        assert _generate_all(dense) == _generate_all(disp)

    def test_zero_drop_decode_token_exact_vs_dense(self, cfg, params):
        dense = _engine(cfg, params, moe_decode_impl="dense")
        zd = _engine(cfg, params, moe_decode_impl="zero_drop")
        assert zd._cfg_decode.moe_impl == "dispatch"
        assert _generate_all(dense) == _generate_all(zd)

    @pytest.mark.slow  # tier-1 budget (ISSUE 14): slowest fast tests re-marked
    def test_trained_capacity_prefill_is_batch_independent(self, params):
        """At the TRAINING capacity factor (drops possible within a
        request), co-batched traffic must still not change any request's
        tokens: solo runs == batched runs, request by request."""
        c = preset("tiny-moe", dtype="float32")   # cf = training default
        eng_batched = _engine(c, init_decoder_params(jax.random.PRNGKey(0), c),
                              moe_prefill_impl="dispatch")
        p2 = init_decoder_params(jax.random.PRNGKey(0), c)
        batched = _generate_all(eng_batched)
        for i, prompt in enumerate(PROMPTS):
            solo = _engine(c, p2, moe_prefill_impl="dispatch")
            got = solo.generate(prompt, SamplingParams(max_new_tokens=10))
            assert got == batched[i], f"request {i} perturbed by co-batching"

    def test_prefill_pads_cannot_displace_choices(self):
        """Bucket padding must not claim expert capacity. At the TRAINING
        capacity factor, a short prompt in a 32-wide bucket brings ~27
        identical pad tokens whose first choices would flood one expert's
        buffer ahead of real tokens' second choices (choice-major priority)
        — the valid_len mask removes them, so prompts with <= C/k real
        choices are exactly the dense oracle."""
        c = preset("tiny-moe", dtype="float32")      # cf = training default
        params = init_decoder_params(jax.random.PRNGKey(0), c)
        prompts = [[5, 17, 3], [7] * 8, [9, 8, 7, 6], [30, 31]]
        dense = _engine(c, params, moe_prefill_impl="dense")
        disp = _engine(c, params, moe_prefill_impl="dispatch")
        for p in prompts:
            want = dense.generate(p, SamplingParams(max_new_tokens=8))
            got = disp.generate(p, SamplingParams(max_new_tokens=8))
            assert got == want, f"prompt {p}: pads perturbed routing"

    def test_unknown_impls_rejected(self, cfg, params):
        with pytest.raises(ValueError, match="moe_prefill_impl"):
            _engine(cfg, params, moe_prefill_impl="ragged")
        with pytest.raises(ValueError, match="moe_decode_impl"):
            _engine(cfg, params, moe_decode_impl="dispatch")
