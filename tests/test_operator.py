"""JAXJob controller semantics, envtest-style: no processes — tests drive
Worker statuses by hand, exactly how the reference tests reconcilers against
envtest with no kubelet (SURVEY.md §4.2)."""

import pytest

from kubeflow_tpu.core.jobs import (
    JAXJob, JAXJobSpec, JobConditionType, ReplicaSpec, RestartPolicy,
    TPUResourceSpec, Worker, WorkerPhase, WorkloadSpec, ParallelismSpec,
    ElasticPolicy, RunPolicy, SchedulingPolicy, CheckpointPolicy,
    worker_name,
)
from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.operator.control_plane import ControlPlane, ControlPlaneConfig
from kubeflow_tpu.runtime.topology import Cluster, SliceTopology


@pytest.fixture()
def cp(tmp_path):
    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="v5e",
                                              dims=(2, 2))]),
        launch_processes=False,
        metrics_sync_interval=None,
    ))
    yield plane


def make_job(name="job", replicas=2, chips=1, **spec_kw) -> JAXJob:
    return JAXJob(
        metadata=ObjectMeta(name=name),
        spec=JAXJobSpec(
            replica_specs={"worker": ReplicaSpec(
                replicas=replicas,
                template=WorkloadSpec(entrypoint="noop"),
                resources=TPUResourceSpec(tpu_chips=chips),
                **({"restart_policy": spec_kw.pop("restart_policy")}
                   if "restart_policy" in spec_kw else {}),
            )},
            **spec_kw,
        ),
    )


def workers_of(cp, name="job") -> list[Worker]:
    ws = cp.store.list(Worker, label_selector={
        "training.tpu.kubeflow.dev/job-name": name})
    return sorted(ws, key=lambda w: w.spec.replica_index)


def set_phase(cp, w: Worker, phase: WorkerPhase, exit_code=None, message=""):
    w = cp.store.get(Worker, w.metadata.name, w.metadata.namespace)
    w.status.phase = phase
    w.status.exit_code = exit_code
    w.status.message = message
    cp.store.update_status(w)


def run_all(cp, job, *phases):
    """Drive all workers through the given phases, stepping between."""
    for phase in phases:
        for w in workers_of(cp, job.metadata.name):
            set_phase(cp, w, phase, exit_code=0 if phase == WorkerPhase.SUCCEEDED else None)
        cp.step()


class TestPlacementAndLaunch:
    def test_creates_workers_with_rendezvous(self, cp):
        job = cp.submit(make_job(replicas=2, parallelism=ParallelismSpec(data=2)))
        cp.step()
        ws = workers_of(cp)
        assert len(ws) == 2
        job = cp.get_job("job")
        assert job.status.phase == "Pending" or job.status.has_condition("Created")
        coord = job.status.coordinator_address
        assert coord and coord.startswith("127.0.0.1:")
        for i, w in enumerate(ws):
            assert w.spec.replica_index == i
            assert w.spec.num_workers == 2
            assert w.spec.coordinator_address == coord
            assert w.spec.parallelism == {"dcn": 1, "pipeline": 1, "data": 2,
                                          "fsdp": 1, "expert": 1, "seq": 1,
                                          "model": 1}
            assert w.spec.slice_name == "s0"
            assert len(w.spec.chip_ids) == 1
        # chips are disjoint
        chips = [c for w in ws for c in w.spec.chip_ids]
        assert len(set(chips)) == 2

    def test_all_running_sets_running_condition(self, cp):
        job = cp.submit(make_job())
        cp.step()
        for w in workers_of(cp):
            set_phase(cp, w, WorkerPhase.RUNNING)
        cp.step()
        job = cp.get_job("job")
        assert job.status.phase == "Running"
        assert job.status.replica_statuses["worker"].active == 2

    def test_success_releases_gang(self, cp):
        job = cp.submit(make_job())
        cp.step()
        run_all(cp, job, WorkerPhase.RUNNING, WorkerPhase.SUCCEEDED)
        job = cp.get_job("job")
        assert job.status.phase == "Succeeded"
        assert job.status.completion_time is not None
        assert cp.allocator.allocation("default/job") is None
        assert cp.allocator.free_chips("s0") == 4

    def test_checkpoint_config_injected(self, cp):
        job = make_job()
        job.spec.run_policy.checkpoint = CheckpointPolicy(
            enabled=True, interval_steps=7)
        cp.submit(job)
        cp.step()
        w = workers_of(cp)[0]
        assert w.spec.template.config["checkpoint_dir"].endswith("default/job/ckpt")
        assert w.spec.template.config["checkpoint_every"] == 7


class TestQueueing:
    def test_second_job_queues_until_capacity(self, cp):
        # Slice has 4 chips; each job wants 4.
        j1 = cp.submit(make_job("a", replicas=4))
        cp.step()
        j2 = cp.submit(make_job("b", replicas=4))
        cp.step()
        assert len(workers_of(cp, "a")) == 4
        assert workers_of(cp, "b") == []
        assert cp.allocator.pending()[0].name == "default/b"
        # finish job a → b gets placed
        run_all(cp, j1, WorkerPhase.RUNNING, WorkerPhase.SUCCEEDED)
        cp.step()
        assert len(workers_of(cp, "b")) == 4

    def test_impossible_job_fails_fast(self, cp):
        cp.submit(make_job(replicas=8))  # 8 chips > 4-chip cluster
        cp.step()
        job = cp.get_job("job")
        assert job.status.phase == "Failed"
        assert job.status.get_condition("Failed").reason == "InsufficientCapacity"

    def test_placement_timeout(self, cp):
        j1 = cp.submit(make_job("a", replicas=4))
        cp.step()
        j2 = make_job("b", replicas=4)
        j2.spec.run_policy.scheduling_policy = SchedulingPolicy(timeout_seconds=0.0)
        cp.submit(j2)
        cp.step()
        job = cp.get_job("b")
        assert job.status.phase == "Failed"
        assert job.status.get_condition("Failed").reason == "PlacementTimeout"


class TestFailureSemantics:
    def test_permanent_failure_fails_job(self, cp):
        job = cp.submit(make_job(restart_policy=RestartPolicy.EXIT_CODE))
        cp.step()
        run_all(cp, job, WorkerPhase.RUNNING)
        ws = workers_of(cp)
        set_phase(cp, ws[0], WorkerPhase.FAILED, exit_code=1, message="bug")
        cp.step()
        job = cp.get_job("job")
        assert job.status.phase == "Failed"
        assert "exit=1" in job.status.get_condition("Failed").message
        assert cp.allocator.allocation("default/job") is None

    def test_retryable_failure_restarts_whole_gang(self, cp):
        job = cp.submit(make_job(restart_policy=RestartPolicy.EXIT_CODE))
        cp.step()
        run_all(cp, job, WorkerPhase.RUNNING)
        old_coord = cp.get_job("job").status.coordinator_address
        old_uids = {w.metadata.uid for w in workers_of(cp)}
        ws = workers_of(cp)
        set_phase(cp, ws[1], WorkerPhase.FAILED, exit_code=137)  # preemption
        cp.step()
        job = cp.get_job("job")
        assert job.status.restart_count == 1
        ws = workers_of(cp)
        assert len(ws) == 2  # recreated
        assert {w.metadata.uid for w in ws}.isdisjoint(old_uids)
        assert all(w.spec.attempt == 1 for w in ws)
        # new rendezvous epoch: coordinator port rotated
        assert cp.get_job("job").status.coordinator_address != old_coord
        # gang kept its chips throughout
        assert cp.allocator.allocation("default/job") is not None

    def test_never_policy_fails_on_any_exit(self, cp):
        job = cp.submit(make_job(restart_policy=RestartPolicy.NEVER))
        cp.step()
        run_all(cp, job, WorkerPhase.RUNNING)
        set_phase(cp, workers_of(cp)[0], WorkerPhase.FAILED, exit_code=143)
        cp.step()
        assert cp.get_job("job").status.phase == "Failed"

    def test_prerunning_death_is_retryable_even_with_bad_code(self, cp):
        # Rendezvous aborts can exit <128; before Running they're retryable.
        job = cp.submit(make_job(restart_policy=RestartPolicy.EXIT_CODE))
        cp.step()
        set_phase(cp, workers_of(cp)[0], WorkerPhase.FAILED, exit_code=1)
        cp.step()
        job = cp.get_job("job")
        assert job.status.phase != "Failed"
        assert job.status.restart_count == 1

    def test_backoff_limit_exceeded(self, cp):
        j = make_job(restart_policy=RestartPolicy.ON_FAILURE)
        j.spec.run_policy.backoff_limit = 1
        cp.submit(j)
        cp.step()
        for _ in range(2):
            run_all(cp, j, WorkerPhase.RUNNING)
            set_phase(cp, workers_of(cp)[0], WorkerPhase.FAILED, exit_code=130)
            cp.step()
        job = cp.get_job("job")
        assert job.status.phase == "Failed"
        assert job.status.get_condition("Failed").reason == "BackoffLimitExceeded"
        assert job.status.restart_count == 1

    def test_heartbeat_stale_is_retryable(self, cp):
        job = cp.submit(make_job(restart_policy=RestartPolicy.EXIT_CODE))
        cp.step()
        run_all(cp, job, WorkerPhase.RUNNING)
        set_phase(cp, workers_of(cp)[0], WorkerPhase.FAILED,
                  exit_code=None, message="heartbeat stale; killed")
        cp.step()
        job = cp.get_job("job")
        assert job.status.phase != "Failed"
        assert job.status.restart_count == 1


class TestLifecyclePolicies:
    def test_suspend_and_resume(self, cp):
        job = cp.submit(make_job())
        cp.step()
        run_all(cp, job, WorkerPhase.RUNNING)
        j = cp.get_job("job")
        j.spec.run_policy.suspend = True
        cp.store.update(j)
        cp.step()
        j = cp.get_job("job")
        assert j.status.phase == "Suspended"
        assert workers_of(cp) == []
        assert cp.allocator.allocation("default/job") is None
        # resume
        j.spec.run_policy.suspend = False
        cp.store.update(j)
        cp.step()
        assert len(workers_of(cp)) == 2
        assert cp.get_job("job").status.phase not in ("Suspended",)

    def test_active_deadline(self, cp):
        j = make_job()
        j.spec.run_policy.active_deadline_seconds = 0.0
        cp.submit(j)
        cp.step()
        job = cp.get_job("job")
        assert job.status.phase == "Failed"
        assert job.status.get_condition("Failed").reason == "DeadlineExceeded"

    def test_ttl_deletes_job(self, cp):
        j = make_job()
        j.spec.run_policy.ttl_seconds_after_finished = 0.0
        job = cp.submit(j)
        cp.step()
        run_all(cp, job, WorkerPhase.RUNNING, WorkerPhase.SUCCEEDED)
        cp.step()
        assert cp.get_job("job") is None
        assert workers_of(cp) == []

    def test_job_deletion_cleans_up(self, cp):
        job = cp.submit(make_job())
        cp.step()
        assert len(workers_of(cp)) == 2
        cp.store.delete(JAXJob, "job")
        cp.step()
        assert workers_of(cp) == []
        assert cp.allocator.allocation("default/job") is None


class TestQueuedResize:
    def test_shrinking_a_queued_job_lets_it_place(self, cp):
        # Job a holds 2 of 4 chips; b wants 4 -> queued; shrink b to 2 -> fits.
        cp.submit(make_job("a", replicas=2))
        cp.step()
        cp.submit(make_job("b", replicas=4))
        cp.step()
        assert workers_of(cp, "b") == []
        j = cp.get_job("b")
        j.spec.replica_specs["worker"].replicas = 2
        cp.store.update(j)
        cp.step()
        assert len(workers_of(cp, "b")) == 2


class TestElastic:
    def test_resize_regangs_at_new_size(self, cp):
        j = make_job(replicas=2, elastic_policy=ElasticPolicy(
            min_replicas=1, max_replicas=4))
        job = cp.submit(j)
        cp.step()
        run_all(cp, job, WorkerPhase.RUNNING)
        j = cp.get_job("job")
        j.spec.replica_specs["worker"].replicas = 4
        cp.store.update(j)
        cp.step()
        ws = workers_of(cp)
        assert len(ws) == 4
        assert all(w.spec.num_workers == 4 for w in ws)
        alloc = cp.allocator.allocation("default/job")
        assert alloc.request.num_workers == 4
        # resize is not a failure: no backoff consumed
        assert cp.get_job("job").status.restart_count == 0

    def test_autoscale_preserves_non_data_axes(self, cp):
        """An fsdp×tp job must stay fsdp×tp across an auto-resize — the
        autoscaler scales the data/fsdp product and keeps the model axis
        ((U) hpa.go scales worker counts regardless of inner strategy)."""
        j = make_job(replicas=2, chips=1,
                     parallelism=ParallelismSpec(model=2),
                     elastic_policy=ElasticPolicy(
                         min_replicas=1, max_replicas=4,
                         scale_on_headroom=True,
                         scale_cooldown_seconds=0.0))
        j.spec.run_policy.checkpoint.enabled = False
        job = cp.submit(j)
        cp.step()
        run_all(cp, job, WorkerPhase.RUNNING)
        cp.step()   # autoscaler: 2 free chips -> grow to 4 workers
        j = cp.get_job("job")
        assert j.spec.worker.replicas == 4
        par = j.spec.parallelism
        assert par.model == 2, "tensor axis lost on auto-resize"
        assert par.data * par.fsdp == 2
        assert par.total == 4

    def test_autoscale_shrink_preserves_fsdp(self, cp):
        """Shrinking an fsdp job steps to a count that still hosts the
        preserved axes and keeps params sharded (fsdp absorbs the pool)."""
        j = make_job(replicas=4, chips=1,
                     parallelism=ParallelismSpec(fsdp=4),
                     elastic_policy=ElasticPolicy(
                         min_replicas=1, max_replicas=4,
                         yield_to_pending=True,
                         scale_cooldown_seconds=0.0))
        j.spec.run_policy.checkpoint.enabled = False
        job = cp.submit(j)
        cp.step()
        run_all(cp, job, WorkerPhase.RUNNING)
        # A 2-chip gang queues -> yield shrinks 4 -> 3 (placeable: frees 1,
        # 1 free after = 2 might... actually 4 held, 0 free; shrink to 3
        # frees 1 < 2 needed; to 2 frees 2 -> but autoscaler steps to the
        # largest valid count below, so the gate must look at that count.
        cp.submit(make_job("waiter", replicas=2, chips=1))
        cp.step()
        j = cp.get_job("job")
        assert j.spec.worker.replicas == 3
        par = j.spec.parallelism
        assert par.data == 1 and par.fsdp == 3, "params silently unsharded"

    def test_yield_shrink_gated_on_placeable_waiter(self, cp):
        """yield_to_pending must NOT burn the resize budget when the freed
        chips cannot help the waiter (it needs more than one shrink step
        frees)."""
        j = make_job(replicas=2, chips=1,
                     elastic_policy=ElasticPolicy(
                         min_replicas=1, max_replicas=2,
                         yield_to_pending=True,
                         scale_cooldown_seconds=0.0))
        j.spec.run_policy.checkpoint.enabled = False
        job = cp.submit(j)
        cp.step()
        run_all(cp, job, WorkerPhase.RUNNING)
        # Cluster has 4 chips; job holds 2, 2 free. A 4-chip gang queues:
        # shrinking one worker frees 1 (3 < 4) — useless, so no shrink.
        cp.submit(make_job("big", replicas=4, chips=1))
        cp.step()
        j = cp.get_job("job")
        assert j.spec.worker.replicas == 2, \
            "shrank although the waiter stayed unplaceable"
        assert j.status.elastic_resizes == 0

    def test_yield_shrink_keeps_job_placed(self, cp):
        """The yield path shrinks IN PLACE: after yielding, the job still
        holds an allocation at the smaller shape and the waiter places —
        the job never goes Pending for volunteering chips."""
        j = make_job(replicas=3, chips=1,
                     elastic_policy=ElasticPolicy(
                         min_replicas=1, max_replicas=3,
                         yield_to_pending=True,
                         scale_cooldown_seconds=0.0))
        j.spec.run_policy.checkpoint.enabled = False
        job = cp.submit(j)
        cp.step()
        run_all(cp, job, WorkerPhase.RUNNING)
        cp.submit(make_job("waiter", replicas=2, chips=1))   # 1 free, needs 2
        cp.step()
        cp.step()
        j = cp.get_job("job")
        assert j.spec.worker.replicas == 2
        alloc = cp.allocator.allocation("default/job")
        assert alloc is not None and alloc.request.num_workers == 2, \
            "yielding job lost its placement"
        assert cp.allocator.allocation("default/waiter") is not None
        assert len(workers_of(cp, "waiter")) == 2


class TestThroughputFloor:
    """min_tokens_per_sec_per_chip (VERDICT r4 weak #6): chips-yielding
    semantics, documented in ElasticPolicy — each shrink needs a FRESH
    below-floor reading at the new shape; stale readings never ratchet."""

    def _floor_job(self, cp, replicas=3):
        j = make_job(replicas=replicas, chips=1,
                     elastic_policy=ElasticPolicy(
                         min_replicas=1, max_replicas=replicas,
                         min_tokens_per_sec_per_chip=1000.0,
                         scale_cooldown_seconds=0.0))
        j.spec.run_policy.checkpoint.enabled = False
        job = cp.submit(j)
        cp.step()
        run_all(cp, job, WorkerPhase.RUNNING)
        return job

    def _set_tput(self, cp, value):
        j = cp.get_job("job")
        j.status.metrics.tokens_per_sec_per_chip = value
        cp.store.update_status(j)

    def test_below_floor_shrinks_once_then_waits_for_fresh_reading(self, cp):
        self._floor_job(cp)
        self._set_tput(cp, 400.0)           # below the 1000 floor
        cp.step()
        j = cp.get_job("job")
        assert j.spec.worker.replicas == 2
        # The resize cleared the stale reading: without a fresh line from
        # the re-ganged shape, further reconciles must NOT shrink again.
        assert j.status.metrics.tokens_per_sec_per_chip is None
        run_all(cp, j, WorkerPhase.RUNNING)
        cp.step()
        cp.step()
        assert cp.get_job("job").spec.worker.replicas == 2

    def test_fresh_below_floor_reading_steps_down_again(self, cp):
        """Pure-DP width-independent throughput: a persistently-degraded
        job steps toward min_replicas one FRESH reading at a time (the
        documented chips-yielding semantics), then holds at the floor."""
        self._floor_job(cp)
        self._set_tput(cp, 400.0)
        cp.step()
        j = cp.get_job("job")
        run_all(cp, j, WorkerPhase.RUNNING)
        self._set_tput(cp, 400.0)           # fresh reading, still degraded
        cp.step()
        j = cp.get_job("job")
        assert j.spec.worker.replicas == 1
        run_all(cp, j, WorkerPhase.RUNNING)
        self._set_tput(cp, 400.0)
        cp.step()
        assert cp.get_job("job").spec.worker.replicas == 1, \
            "shrank below min_replicas"

    def test_healthy_reading_never_shrinks(self, cp):
        self._floor_job(cp)
        self._set_tput(cp, 5000.0)
        cp.step()
        j = cp.get_job("job")
        assert j.spec.worker.replicas == 3
        assert j.status.elastic_resizes == 0


class TestRetryableExitContract:
    """The exit-code contract (_is_retryable_exit) pinned: >=128 is a
    signal/preemption/rendezvous death (retryable), None is a lost process
    or heartbeat-stale kill (retryable infrastructure failure), anything
    in [0,128) is the program's own verdict (permanent)."""

    def test_contract_cases(self):
        from kubeflow_tpu.operator.jaxjob_controller import _is_retryable_exit

        assert _is_retryable_exit(128) is True    # EXIT_RETRYABLE boundary
        assert _is_retryable_exit(137) is True    # SIGKILL
        assert _is_retryable_exit(143) is True    # SIGTERM / preemption
        assert _is_retryable_exit(255) is True
        assert _is_retryable_exit(None) is True   # no exit code: lost process
        assert _is_retryable_exit(0) is False     # success is not a retry
        assert _is_retryable_exit(1) is False     # program bug
        assert _is_retryable_exit(2) is False     # config error
        assert _is_retryable_exit(127) is False   # last permanent code


class TestSurvivabilityMetricsLift:
    def test_goodput_ledger_fields_scraped_onto_status(self, cp, tmp_path):
        """The survivability ledger rides metrics.jsonl onto JAXJob status
        like every other data-plane metric (ISSUE 9: goodput as the honest
        metric, visible where the SRE looks)."""
        import json
        import os

        job = cp.submit(make_job())
        cp.step()
        w = workers_of(cp)[0]
        workdir = w.spec.template.working_dir
        os.makedirs(workdir, exist_ok=True)
        line = {"step": 42, "loss": 2.5, "goodput": 0.83,
                "steps_lost_total": 4, "emergency_saves": 1,
                "restore_fallbacks": 2, "checkpoint_save_failures": 3,
                "last_checkpoint_step": 40}
        with open(os.path.join(workdir, "metrics.jsonl"), "w") as f:
            f.write(json.dumps(line) + "\n")
        for w in workers_of(cp):
            set_phase(cp, w, WorkerPhase.RUNNING)
        cp.step()
        m = cp.get_job("job").status.metrics
        assert m.step == 42
        assert m.goodput == 0.83
        assert m.steps_lost_total == 4
        assert m.emergency_saves == 1
        assert m.restore_fallbacks == 2
        assert m.checkpoint_save_failures == 3
        assert m.last_checkpoint_step == 40
