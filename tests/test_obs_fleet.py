"""Fleet observability plane (obs/fleet.py): cross-host trace stitching
edge cases (skewed clocks, missing middle hop, duplicate delivery,
orphan children), hop-kind classification, metrics history window
queries + retention, the HistoryProbe ≡ default_probe equivalence the
autoscaler seam guarantees, multi-window burn-rate alerting, and the
flight-recorder round trip through ``kftpu trace``'s loader."""

import http.server
import json
import math
import threading

import pytest

from kubeflow_tpu.obs import fleet
from kubeflow_tpu.obs.fleet import (
    FleetTraceCollector, FlightRecorder, HistoryProbe, MetricsHistory,
    SloBurnRateMonitor, spans_export_payload,
)
from kubeflow_tpu.obs.trace import Tracer, format_dump

T0 = 1_700_000_000.0


def mk_span(sid, parent, name, start, end, trace_id="T1", attrs=None,
            events=None):
    return {"trace_id": trace_id, "span_id": sid, "parent_id": parent,
            "name": name, "start": start, "end": end,
            "duration_ms": round((end - start) * 1e3, 3), "status": "ok",
            "attrs": attrs or {}, "events": events or []}


def router_payload(now=T0, events=None):
    return {"process": {"name": "router", "pid": 1}, "now": now,
            "spans": [mk_span("r1", None, "router.request", T0, T0 + 1.0,
                              attrs={"path": "/v1/completions",
                                     "backend": "b1", "code": 200},
                              events=events)]}


def server_payload(name, skew=0.0, now=None):
    """One replica's export: server.request + nested engine phases,
    every timestamp shifted by that replica's clock skew."""
    spans = [mk_span("s1", "r1", "server.request", T0 + 0.1, T0 + 0.9,
                     attrs={"path": "/v1/completions", "server": name}),
             mk_span("e1", "s1", "engine.prefill", T0 + 0.2, T0 + 0.4),
             mk_span("e2", "s1", "engine.decode", T0 + 0.4, T0 + 0.8)]
    for s in spans:
        s["start"] += skew
        s["end"] += skew
    return {"process": {"name": name, "pid": 2},
            "now": (T0 + skew) if now is None else now, "spans": spans}


# -- stitching edge cases -----------------------------------------------------

@pytest.mark.parametrize("skew", [5.0, -5.0])
def test_skewed_clock_corrected_to_monotone_hops(skew):
    c = FleetTraceCollector()
    c.ingest(router_payload(), source="router", offset_s=0.0)
    c.ingest(server_payload("srv-a", skew=skew), source="server:srv-a",
             offset_s=skew)
    tr = c.trace("T1")
    assert len(tr["spans"]) == 4
    assert tr["sources"] == ["router", "server:srv-a"]
    # Corrected timeline: the server span sits back inside its parent.
    by_id = {s["span_id"]: s for s in tr["spans"]}
    assert by_id["s1"]["start"] == pytest.approx(T0 + 0.1)
    assert by_id["s1"]["clock_offset_ms"] == pytest.approx(skew * 1e3)
    hops = tr["hops"]
    assert [h["kind"] for h in hops] == ["route"]
    assert all(h["monotone"] for h in hops)
    assert hops[0]["wire_out_ms"] == pytest.approx(100.0, abs=1.0)
    assert hops[0]["wire_back_ms"] == pytest.approx(100.0, abs=1.0)


def test_uncorrected_skew_flags_non_monotone():
    c = FleetTraceCollector()
    c.ingest(router_payload(), source="router")
    # Skewed clock, NO offset: the stitcher must not pretend causality.
    c.ingest(server_payload("srv-a", skew=-5.0), source="server:srv-a")
    hops = c.hops("T1")
    assert hops and not hops[0]["monotone"]
    # Clamped attribution: noise never reads as negative latency.
    assert hops[0]["wire_out_ms"] == 0.0


def test_missing_middle_hop_renders_orphans_top_level():
    """Prefill replica SIGKILLed before export: its server.request span
    never arrives, but the engine spans relayed elsewhere still stitch —
    as top-level orphans, not silently dropped."""
    c = FleetTraceCollector()
    c.ingest(router_payload(), source="router")
    payload = server_payload("srv-a")
    payload["spans"] = [s for s in payload["spans"]
                        if s["span_id"] != "s1"]     # the dead middle
    c.ingest(payload, source="server:srv-a")
    tr = c.trace("T1")
    assert {s["span_id"] for s in tr["spans"]} == {"r1", "e1", "e2"}
    tree = c.format_tree("T1")
    # Orphans print at top level (their parent id resolves to nothing).
    assert tree.splitlines()[0].startswith("router.request")
    assert any(line.startswith("engine.prefill") for line in
               tree.splitlines())
    # No cross-process edge can be attributed through the hole.
    assert c.hops("T1") == []


def test_duplicate_delivery_stitches_exactly_once():
    c = FleetTraceCollector()
    n1 = c.ingest(router_payload(), source="router")
    n2 = c.ingest(router_payload(), source="router")       # re-drain
    n3 = c.ingest(router_payload(), source="router-2")     # other source
    assert (n1, n2, n3) == (1, 0, 0)
    assert len(c.trace("T1")["spans"]) == 1
    assert c.stats["duplicates"] == 2
    assert c.sources()["router-2"]["duplicates"] == 1


def test_orphan_child_waits_without_breaking_the_tree():
    c = FleetTraceCollector()
    c.ingest({"process": {"name": "server:srv-a", "pid": 2}, "now": T0,
              "spans": [mk_span("e9", "nope", "engine.decode",
                                T0, T0 + 0.5)]}, source="server:srv-a")
    tr = c.trace("T1")
    assert tr["root"] is None
    assert tr["hops"] == []
    assert c.format_tree("T1").startswith("engine.decode")


def test_hop_kinds_handoff_and_failover():
    c = FleetTraceCollector()
    # Router saw a connect failure (the SIGKILL path) before rerouting.
    c.ingest(router_payload(events=[{"name": "connect_failure",
                                     "t": T0 + 0.05, "attrs": {}}]),
             source="router")
    c.ingest(server_payload("srv-b"), source="server:srv-b")
    # Prefill's handoff span parents the decode replica's adoption work.
    c.ingest({"process": {"name": "server:srv-b", "pid": 2}, "now": T0,
              "spans": [mk_span("h1", "s1", "engine.handoff",
                                T0 + 0.45, T0 + 0.6,
                                attrs={"backend": "http://d:1",
                                       "request": "req-1"})]},
             source="server:srv-b")
    c.ingest({"process": {"name": "server:srv-c", "pid": 3}, "now": T0,
              "spans": [mk_span("a1", "h1", "server.request",
                                T0 + 0.48, T0 + 0.58,
                                attrs={"path": "/v1/kv/adopt",
                                       "server": "srv-c"})]},
             source="server:srv-c")
    kinds = {h["kind"]: h for h in c.hops("T1")}
    assert set(kinds) == {"failover", "handoff"}
    assert kinds["failover"]["to"] == "server:srv-b"
    assert kinds["handoff"]["from"] == "server:srv-b"
    assert kinds["handoff"]["to"] == "server:srv-c"
    assert all(h["monotone"] for h in kinds.values())


def test_handoff_retry_classifies_as_failover():
    """A handoff whose placed decode replica died lands on the retry
    alternate — the stitcher must call that hop a failover."""
    c = FleetTraceCollector()
    c.ingest({"process": {"name": "server:pre", "pid": 2}, "now": T0,
              "spans": [
                  mk_span("s1", None, "server.request", T0, T0 + 1.0,
                          attrs={"server": "pre"}),
                  mk_span("h1", "s1", "engine.handoff", T0 + 0.4, T0 + 0.9,
                          attrs={"backend": "http://dec2:1"},
                          events=[{"name": "connect_failure",
                                   "t": T0 + 0.45,
                                   "backend": "http://dec1:1"}])]},
             source="server:pre")
    c.ingest({"process": {"name": "server:dec2", "pid": 3}, "now": T0,
              "spans": [mk_span("a1", "h1", "server.request",
                                T0 + 0.5, T0 + 0.85,
                                attrs={"server": "dec2"})]},
             source="server:dec2")
    hops = c.hops("T1")
    assert [h["kind"] for h in hops] == ["failover"]
    assert hops[0]["from"] == "server:pre"
    assert hops[0]["to"] == "server:dec2"


def test_drain_estimates_offset_and_survives_dead_source():
    payloads = {"http://a/export": server_payload("srv-a", skew=5.0,
                                                  now=None)}

    def fetch(url):
        if url not in payloads:
            raise OSError("connection refused")
        p = dict(payloads[url])
        p["now"] = __import__("time").time() + 5.0   # clock runs 5s fast
        return p

    c = FleetTraceCollector(fetch=fetch)
    c.add_source("server:srv-a", "http://a/export")
    c.add_source("server:dead", "http://dead/export")
    assert c.drain() == 3
    assert c.stats["drain_errors"] == 1
    assert c.sources()["server:dead"]["errors"] == 1
    assert c.sources()["server:srv-a"]["offset_s"] == pytest.approx(
        5.0, abs=0.5)


def test_spans_export_payload_completed_only():
    t = Tracer()
    with t.span("router.request", path="/x"):
        pass
    with t.span("open-span"):
        payload = spans_export_payload(t, process="router")
        names = [s["name"] for s in payload["spans"]]
        assert "open-span" not in names        # still being written
    assert payload["process"]["name"] == "router"
    assert isinstance(payload["now"], float)
    assert t.open_spans() == 0


def test_chrome_export_one_lane_per_process():
    c = FleetTraceCollector()
    c.ingest(router_payload(), source="router")
    c.ingest(server_payload("srv-a"), source="server:srv-a")
    doc = c.export_chrome("T1")
    meta = {e["args"]["name"]: e["pid"] for e in doc["traceEvents"]
            if e["ph"] == "M"}
    assert set(meta) == {"router", "server:srv-a"}
    lanes = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert lanes == set(meta.values())


# -- metrics history ----------------------------------------------------------

def test_history_window_queries_and_retention():
    h = MetricsHistory(retention_s=30.0, max_points=8)
    for i in range(12):
        h.record("r0", [("kftpu_serving_requests_total", {}, 10.0 * i),
                        ("kftpu_serving_ttft_p95_ms", {}, 5.0 + i)],
                 now=T0 + i)
    assert h.latest("r0", "kftpu_serving_ttft_p95_ms") == 16.0
    # Window is inclusive at the horizon: [T0+7, T0+11] -> values 12..16.
    assert h.window_mean("r0", "kftpu_serving_ttft_p95_ms", 4.0,
                         now=T0 + 11) == pytest.approx(14.0)
    # Counter delta/rate over the covered window.
    assert h.delta("r0", "kftpu_serving_requests_total", 5.0,
                   now=T0 + 11) == pytest.approx(50.0)
    assert h.rate("r0", "kftpu_serving_requests_total", 5.0,
                  now=T0 + 11) == pytest.approx(10.0)
    # max_points bounds the ring; replicas() sees record()-fed feeds.
    assert h.points_total("r0") == 16
    assert h.replicas() == ["r0"]
    # Beyond retention: answers from what the ring holds, never invents.
    assert h.window_mean("r0", "kftpu_serving_ttft_p95_ms", 1e6,
                         now=T0 + 11) is not None
    assert h.latest("r0", "kftpu_nope") is None


def test_history_percentile_over_window_from_buckets():
    h = MetricsHistory()
    name = "kftpu_serving_ttft_ms"
    h.record("r0", [(name + "_bucket", {"le": "10"}, 0.0),
                    (name + "_bucket", {"le": "50"}, 0.0),
                    (name + "_bucket", {"le": "+Inf"}, 0.0)], now=T0)
    h.record("r0", [(name + "_bucket", {"le": "10"}, 5.0),
                    (name + "_bucket", {"le": "50"}, 9.0),
                    (name + "_bucket", {"le": "+Inf"}, 10.0)], now=T0 + 10)
    p50 = h.percentile_over_window("r0", name, 50.0, 60.0, now=T0 + 10)
    assert p50 == pytest.approx(10.0, abs=0.01)
    p95 = h.percentile_over_window("r0", name, 95.0, 60.0, now=T0 + 10)
    assert 10.0 < p95 <= 50.0
    # The overflow bucket caps interpolation at the last finite edge.
    p100 = h.percentile_over_window("r0", name, 100.0, 60.0, now=T0 + 10)
    assert p100 == pytest.approx(50.0)
    assert h.percentile_over_window("r0", "kftpu_nope", 95.0, 60.0,
                                    now=T0 + 10) is None


def test_history_scrape_via_fetch_injection():
    text = ("kftpu_serving_requests_total 7\n"
            "kftpu_serving_ttft_p95_ms 12.5\n")
    h = MetricsHistory(fetch=lambda url: text)
    h.add_target("r0", "http://r0/metrics")
    assert h.scrape_once() == 1
    assert h.latest_text("r0") == text
    assert h.latest("r0", "kftpu_serving_requests_total") == 7.0
    assert h.stats["scrapes"] == 1

    def boom(url):
        raise OSError("down")

    h2 = MetricsHistory(fetch=boom)
    h2.add_target("r0", "http://r0/metrics")
    assert h2.scrape_once() == 0
    assert h2.stats["scrape_errors"] == 1


# -- the autoscaler seam: HistoryProbe ≡ default_probe ------------------------

class _Exposition(http.server.BaseHTTPRequestHandler):
    METRICS = ("kftpu_serving_requests_total 42\n"
               "kftpu_serving_requests_in_flight 3\n"
               "kftpu_serving_ttft_p95_ms 12.5\n"
               "kftpu_serving_queue_delay_p95_ms 4.0\n"
               'kftpu_serving_qos_ttft_p95_ms{qos="interactive"} 9.5\n')

    def do_GET(self):
        body = (self.METRICS if self.path == "/metrics" else "ok").encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


def test_history_probe_matches_default_probe():
    """The ISSUE's drop-in guarantee: on steady traffic the autoscaler
    sees byte-identical signals from the history substrate as from a
    live scrape — decisions (a pure fold of the signals) can't differ."""
    from kubeflow_tpu.serve.isvc_controller import default_probe

    httpd = http.server.HTTPServer(("127.0.0.1", 0), _Exposition)
    thr = threading.Thread(target=httpd.serve_forever, daemon=True)
    thr.start()
    try:
        url = "http://127.0.0.1:%d" % httpd.server_address[1]
        live = default_probe(url)
        hist = HistoryProbe(MetricsHistory())(url)
        assert live is not None
        assert hist == live
    finally:
        httpd.shutdown()
        httpd.server_close()
        thr.join(timeout=5.0)
    # And a dead replica is a dead replica, whatever the ring remembers.
    h = MetricsHistory()
    h.record(url, [("kftpu_serving_requests_total", {}, 42.0)])
    assert HistoryProbe(h, timeout=0.2)(url) is None


# -- burn-rate monitor --------------------------------------------------------

def _seeded_history(values, *, qos=None, series="kftpu_serving_ttft_p95_ms"):
    h = MetricsHistory()
    labels = {"qos": qos} if qos else {}
    name = ("kftpu_serving_qos_ttft_p95_ms" if qos else series)
    for i, v in enumerate(values):
        h.record("r0", [(name, labels, float(v))], now=T0 + i)
    return h


def test_burn_rate_requires_both_windows():
    targets = {"interactive": {"ttft_p95_ms": 10.0}}
    # Sustained breach: both windows burn -> alert.
    hot = SloBurnRateMonitor(_seeded_history([30.0] * 20), targets,
                             fast_window_s=5.0, slow_window_s=20.0)
    st = hot.evaluate(now=T0 + 19)
    assert st["interactive"]["alert"] and hot.alerting() == ["interactive"]
    assert st["interactive"]["fast"] == pytest.approx(3.0)
    # One bad minute in a healthy day: fast burns, slow doesn't -> page
    # suppressed (the multi-window discipline).
    spike = SloBurnRateMonitor(
        _seeded_history([1.0] * 15 + [30.0] * 5), targets,
        fast_window_s=5.0, slow_window_s=1000.0)
    st = spike.evaluate(now=T0 + 19)
    assert st["interactive"]["fast"] > 1.0 > st["interactive"]["slow"]
    assert not st["interactive"]["alert"]
    # Clean run stays silent.
    clean = SloBurnRateMonitor(_seeded_history([2.0] * 20), targets,
                               fast_window_s=5.0, slow_window_s=20.0)
    assert not clean.evaluate(now=T0 + 19)["interactive"]["alert"]


def test_burn_rate_prefers_per_class_series():
    targets = {"interactive": {"ttft_p95_ms": 10.0}}
    h = _seeded_history([30.0] * 10, qos="interactive")
    # Aggregate says healthy; the interactive class is burning.
    for i in range(10):
        h.record("r0", [("kftpu_serving_ttft_p95_ms", {}, 1.0)], now=T0 + i)
    mon = SloBurnRateMonitor(h, targets, fast_window_s=5.0,
                             slow_window_s=10.0)
    assert mon.evaluate(now=T0 + 9)["interactive"]["alert"]


# -- flight recorder ----------------------------------------------------------

def test_flight_recorder_roundtrip_and_prune(tmp_path):
    import time as _time

    now = _time.time()
    c = FleetTraceCollector()
    c.ingest(router_payload(), source="router")
    h = MetricsHistory()
    h.record("r0", [("kftpu_serving_ttft_p95_ms", {}, 12.0)], now=now - 1)
    h.record("r0", [("kftpu_serving_ttft_p95_ms", {}, 14.0)], now=now)
    mon = SloBurnRateMonitor(h, {"interactive": {"ttft_p95_ms": 1.0}},
                             fast_window_s=30.0, slow_window_s=60.0)
    mon.evaluate()
    rec = FlightRecorder(str(tmp_path), window_s=60.0, keep=2,
                         history=h, collector=c, monitor=mon)
    paths = [rec.snapshot("unit") for _ in range(3)]
    assert all(paths)
    assert len(rec.dumps()) == 2                  # pruned to keep=
    doc = json.loads((tmp_path / rec.dumps()[-1].rsplit("/", 1)[-1])
                     .read_text())
    fr = doc["flight_recorder"]
    assert fr["reason"] == "unit"
    assert fr["slo"]["interactive"]["alert"]
    hist = {(s["replica"], s["name"]) for s in fr["history"]}
    assert ("r0", "kftpu_serving_ttft_p95_ms") in hist
    assert len([s for s in fr["history"]
                if s["name"] == "kftpu_serving_ttft_p95_ms"][0]
               ["points"]) == 2
    # The dump is a {"traces": ...} doc: kftpu trace re-loads it.
    rendered = format_dump(doc)
    assert rendered.startswith("flight recorder: reason=unit")
    assert "router.request" in rendered


def test_install_flight_recorder_module_seam(tmp_path):
    rec = FlightRecorder(str(tmp_path))
    prev = fleet.install_flight_recorder(rec)
    try:
        assert fleet.flight_recorder() is rec
    finally:
        fleet.install_flight_recorder(prev)


# -- the metrics contract -----------------------------------------------------

#: Consumption side of every series ``fleet_obs_registry`` produces —
#: the same two-sided X7xx idiom as ``_PROBE_SERIES``.
FLEET_OBS_SERIES = (
    "kftpu_fleet_spans_total",
    "kftpu_fleet_spans_duplicate_total",
    "kftpu_fleet_drain_errors_total",
    "kftpu_fleet_traces_stitched",
    "kftpu_fleet_clock_skew_ms",
    "kftpu_fleet_hops_total",
    "kftpu_fleet_hop_wire_ms",
    "kftpu_obs_history_points",
    "kftpu_obs_history_scrapes_total",
    "kftpu_obs_history_scrape_errors_total",
    "kftpu_obs_slo_burn_rate",
    "kftpu_obs_slo_alert",
    "kftpu_obs_flight_dumps_total",
)


def test_fleet_obs_registry_covers_the_catalog():
    c = FleetTraceCollector()
    c.ingest(router_payload(), source="router")
    c.ingest(server_payload("srv-a", skew=2.0), source="server:srv-a",
             offset_s=2.0)
    h = MetricsHistory(fetch=lambda url: (
        "kftpu_serving_requests_total 1\n"
        "kftpu_serving_ttft_p95_ms 25.0\n"))
    h.add_target("r0", "http://r0/metrics")
    h.scrape_once()
    mon = SloBurnRateMonitor(h, {"interactive": {"ttft_p95_ms": 10.0}})
    mon.evaluate()
    reg = fleet.fleet_obs_registry(collector=c, history=h, monitor=mon)
    text = reg.render()
    from kubeflow_tpu.obs.registry import parse_exposition

    produced = {name for name, _, _ in parse_exposition(text)}
    for name in FLEET_OBS_SERIES:
        assert name in produced, f"{name} missing from the fleet registry"
    by_key = {(n, tuple(sorted(l.items()))): v
              for n, l, v in parse_exposition(text)}
    assert by_key[("kftpu_fleet_traces_stitched", ())] == 1.0
    assert by_key[("kftpu_fleet_clock_skew_ms",
                   (("source", "server:srv-a"),))] == pytest.approx(2000.0)
    assert by_key[("kftpu_fleet_hops_total", (("kind", "route"),))] == 1.0
