"""Shared retry/backoff helper (serve/retry.py, ISSUE 17): the one
policy object every fleet failure path states its budget with. Pins the
arithmetic — jitter band, exponential growth, hard cap, give-up — and
the call contract (0-based attempt index, re-raised last exception)."""

import random

import pytest

from kubeflow_tpu.serve.retry import (
    PROBE_POLICY, STORE_POLICY, RetryPolicy, call_with_retry, env_float,
    env_int, handoff_policy,
)


class TestRetryPolicy:
    def test_delays_length_is_attempts_minus_one(self):
        p = RetryPolicy(attempts=4, base_s=0.1, cap_s=10.0, jitter_frac=0.0)
        assert p.delays() == [0.1, 0.2, 0.4]

    def test_jitter_band_bounds(self):
        """Every delay lands in [d*(1-j), d*(1+j)] for the un-jittered
        exponential d — sampled wide enough to catch a bad band."""
        p = RetryPolicy(attempts=3, base_s=0.1, cap_s=10.0, jitter_frac=0.5)
        rng = random.Random(7)
        for _ in range(500):
            d1 = p.delay_s(1, rng)
            d2 = p.delay_s(2, rng)
            assert 0.05 <= d1 <= 0.15, d1
            assert 0.10 <= d2 <= 0.30, d2

    def test_jitter_actually_desynchronizes(self):
        p = RetryPolicy(attempts=2, base_s=0.1, cap_s=1.0, jitter_frac=0.5)
        rng = random.Random(3)
        assert len({p.delay_s(1, rng) for _ in range(50)}) > 10

    def test_cap_applies_even_with_jitter(self):
        """The cap is a HARD ceiling: jitter widens the band but can
        never push a delay past cap_s (a fleet-wide retry storm must
        stay bounded)."""
        p = RetryPolicy(attempts=10, base_s=1.0, cap_s=2.0, jitter_frac=0.9)
        rng = random.Random(11)
        for failures in range(1, 10):
            for _ in range(100):
                assert p.delay_s(failures, rng) <= 2.0

    def test_zero_failures_means_zero_delay(self):
        assert RetryPolicy().delay_s(0) == 0.0


class TestCallWithRetry:
    def test_passes_attempt_index_and_succeeds(self):
        """fn receives the 0-based attempt index — the cross-host
        handoff uses it to target a DIFFERENT replica per attempt."""
        seen = []

        def fn(attempt):
            seen.append(attempt)
            if attempt < 2:
                raise OSError("down")
            return "ok"

        p = RetryPolicy(attempts=3, base_s=0.0, jitter_frac=0.0)
        assert call_with_retry(fn, policy=p, sleep=lambda s: None) == "ok"
        assert seen == [0, 1, 2]

    def test_exhaustion_reraises_last_exception(self):
        """Give-up is a signal (the caller's terminal fallback fires),
        never a silent None."""
        def fn(attempt):
            raise OSError(f"fail {attempt}")

        p = RetryPolicy(attempts=3, base_s=0.0, jitter_frac=0.0)
        with pytest.raises(OSError, match="fail 2"):
            call_with_retry(fn, policy=p, sleep=lambda s: None)

    def test_non_retryable_exception_propagates_immediately(self):
        calls = []

        def fn(attempt):
            calls.append(attempt)
            raise ValueError("not transient")

        p = RetryPolicy(attempts=5, base_s=0.0, jitter_frac=0.0)
        with pytest.raises(ValueError):
            call_with_retry(fn, policy=p, sleep=lambda s: None)
        assert calls == [0]

    def test_on_retry_fires_between_attempts_with_last_exc(self):
        notes = []

        def fn(attempt):
            raise OSError("x")

        p = RetryPolicy(attempts=3, base_s=0.0, jitter_frac=0.0)
        with pytest.raises(OSError):
            call_with_retry(fn, policy=p, sleep=lambda s: None,
                            on_retry=lambda a, e: notes.append((a, str(e))))
        assert notes == [(1, "x"), (2, "x")]

    def test_sleeps_follow_the_policy(self):
        slept = []

        def fn(attempt):
            raise OSError("x")

        p = RetryPolicy(attempts=3, base_s=0.1, cap_s=10.0, jitter_frac=0.0)
        with pytest.raises(OSError):
            call_with_retry(fn, policy=p, sleep=slept.append)
        assert slept == [0.1, 0.2]


class TestEnvKnobs:
    def test_handoff_policy_reads_retry_knob(self, monkeypatch):
        monkeypatch.setenv("KFTPU_HANDOFF_RETRIES", "4")
        assert handoff_policy().attempts == 5
        monkeypatch.setenv("KFTPU_HANDOFF_RETRIES", "0")
        assert handoff_policy().attempts == 1   # never zero tries
        monkeypatch.delenv("KFTPU_HANDOFF_RETRIES")
        assert handoff_policy().attempts == 3   # default: 2 retries

    def test_env_parsers_fall_back_on_garbage(self, monkeypatch):
        monkeypatch.setenv("KFTPU_TEST_KNOB", "not-a-number")
        assert env_float("KFTPU_TEST_KNOB", 1.5) == 1.5
        assert env_int("KFTPU_TEST_KNOB", 7) == 7
        monkeypatch.setenv("KFTPU_TEST_KNOB", "2.5")
        assert env_float("KFTPU_TEST_KNOB", 1.5) == 2.5

    def test_shared_policies_are_bounded(self):
        """The store/probe budgets stay tiny: both sit on latency paths
        that their own deadlines must dominate."""
        for p in (STORE_POLICY, PROBE_POLICY):
            assert p.attempts <= 3
            assert max(p.delays(random.Random(0))) <= p.cap_s <= 0.5
