"""Training chaos suite (ISSUE 9): survivable training proven under faults.

Fast tier (no processes): checkpoint manifest integrity, the verified
multi-tier resume walk, the goodput ledger, and the step-progress watchdog
as units.

Slow tier (real worker processes on the emulated control plane): the
scenarios the serving plane's chaos harness already answers for serving —
SIGTERM mid-run resumes at the emergency step with zero completed steps
lost, SIGKILL resumes from the last interval save, a corrupted latest
checkpoint falls back to an older valid step and the job still succeeds,
and a wedged step is caught by the watchdog long before the heartbeat
lease (which a wedged-but-alive worker never misses) would."""

import json
import os
import re
import time

import pytest

from kubeflow_tpu.runtime.bootstrap import EXIT_RETRYABLE
from kubeflow_tpu.train.survival import GoodputLedger, StepWatchdog

# -- fast: manifests + verified restore ----------------------------------------


def _abstract():
    import jax
    import jax.numpy as jnp

    return {"step": jax.ShapeDtypeStruct((), jnp.int32),
            "w": jax.ShapeDtypeStruct((16,), jnp.float32)}


def _state(step: int):
    import jax.numpy as jnp

    return {"step": jnp.int32(step),
            "w": jnp.arange(16, dtype=jnp.float32) * step}


def _corrupt(directory: str, step: int) -> None:
    root = os.path.join(directory, str(step))
    for base, _, files in os.walk(root):
        for fn in files:
            with open(os.path.join(base, fn), "wb") as f:
                f.write(b"\0corrupt\0")


class TestCheckpointIntegrity:
    def test_manifest_written_and_verifies(self, tmp_path):
        from kubeflow_tpu.train.checkpoint import CheckpointManager

        m = CheckpointManager(str(tmp_path / "ckpt"), 3)
        assert m.save(10, _state(10), force=True)
        m.wait()
        assert m.latest_committed_step() == 10
        mpath = os.path.join(m.directory, "manifests", "10.json")
        assert os.path.exists(mpath)
        manifest = json.load(open(mpath))
        assert manifest["step"] == 10 and manifest["files"]
        assert all("sha256" in meta for meta in manifest["files"].values())
        assert m.verify_step(10) is True
        restored = m.restore(_abstract())
        assert int(restored["step"]) == 10
        m.close()

    @pytest.mark.slow
    def test_corrupt_step_raises_not_restores(self, tmp_path):
        from kubeflow_tpu.train.checkpoint import (
            CheckpointCorruptionError, CheckpointManager,
        )

        m = CheckpointManager(str(tmp_path / "ckpt"), 3)
        m.save(10, _state(10), force=True)
        m.wait()
        _corrupt(m.directory, 10)
        with pytest.raises(CheckpointCorruptionError):
            m.restore(_abstract())
        # a deleted file is caught as a file-set mismatch, not a checksum one
        m.save(20, _state(20), force=True)
        m.wait()
        victim = next(
            os.path.join(b, fs[0])
            for b, _, fs in os.walk(os.path.join(m.directory, "20")) if fs)
        os.remove(victim)
        with pytest.raises(CheckpointCorruptionError, match="file set"):
            m.verify_step(20)
        m.close()

    @pytest.mark.slow
    def test_unmanifested_step_is_unverified_not_fatal(self, tmp_path):
        from kubeflow_tpu.train.checkpoint import CheckpointManager

        m = CheckpointManager(str(tmp_path / "ckpt"), 3, write_manifests=False)
        m.save(10, _state(10), force=True)
        m.wait()
        assert not os.path.exists(
            os.path.join(m.directory, "manifests", "10.json"))
        # legacy/pre-manifest checkpoint: restorable, reported unverified
        assert m.verify_step(10) is False
        assert int(m.restore(_abstract())["step"]) == 10
        m.close()

    def test_latest_committed_vs_latest_divergence(self, tmp_path):
        """``latest_step`` is the manager's in-memory registration —
        async saves appear there the moment save() returns, before their
        bytes are durable. Model the in-flight window deterministically:
        commit 10, register 20, then make 20's dir vanish the way a
        teardown mid-commit leaves it. The two queries MUST diverge, and
        only latest_committed_step tells the truth the elastic autoscaler
        can act on."""
        from kubeflow_tpu.train.checkpoint import CheckpointManager

        m = CheckpointManager(str(tmp_path / "ckpt"), 3)
        m.save(10, _state(10), force=True)
        m.save(20, _state(20), force=True)
        m.wait()
        os.rename(os.path.join(m.directory, "20"),
                  os.path.join(m.directory, "20.orbax-checkpoint-tmp-0"))
        assert m.latest_step() == 20            # registered in memory
        assert m.latest_committed_step() == 10  # durable on disk
        m.close()

    @pytest.mark.slow
    def test_resume_walk_falls_back_and_quarantines(self, tmp_path):
        from kubeflow_tpu.train.checkpoint import (
            CheckpointManager, resume_from_tiers,
        )

        m = CheckpointManager(str(tmp_path / "ckpt"), 3)
        em = CheckpointManager(str(tmp_path / "em"), 1)
        for s in (10, 20):
            m.save(s, _state(s), force=True)
        m.wait()
        _corrupt(m.directory, 20)
        out = resume_from_tiers([("emergency", em), ("interval", m)],
                                _abstract())
        assert out is not None
        state, step, tier, fallbacks = out
        assert (step, tier, fallbacks) == (10, "interval", 1)
        assert int(state["step"]) == 10
        # the bad step is out of the candidate set but kept for post-mortem
        assert m.steps_on_disk() == [10]
        assert os.path.isdir(os.path.join(m.directory, "quarantine", "20"))
        m.close(); em.close()

    @pytest.mark.slow
    def test_resume_walk_prefers_newest_across_tiers(self, tmp_path):
        from kubeflow_tpu.train.checkpoint import (
            CheckpointManager, resume_from_tiers,
        )

        m = CheckpointManager(str(tmp_path / "ckpt"), 3)
        em = CheckpointManager(str(tmp_path / "em"), 1)
        m.save(10, _state(10), force=True)
        em.save(14, _state(14), force=True)   # the post-preemption shape
        m.wait(); em.wait()
        _, step, tier, fb = resume_from_tiers(
            [("emergency", em), ("interval", m)], _abstract())
        assert (step, tier, fb) == (14, "emergency", 0)
        # both tiers empty -> None (fresh start)
        e1 = CheckpointManager(str(tmp_path / "e1"), 1)
        e2 = CheckpointManager(str(tmp_path / "e2"), 1)
        assert resume_from_tiers(
            [("emergency", e1), ("interval", e2)], _abstract()) is None
        for c in (m, em, e1, e2):
            c.close()


# -- fast: goodput ledger ------------------------------------------------------


class TestGoodputLedger:
    def test_restart_accounting(self, tmp_path):
        led = GoodputLedger(str(tmp_path))
        assert led.record_resume(0) == 0
        led.record_progress(12)
        # reload (a new attempt after a SIGKILL): 12 recorded, resumed at 8
        led2 = GoodputLedger(str(tmp_path))
        assert led2.record_resume(8) == 4
        assert led2.data["attempts"] == 2
        assert led2.data["steps_lost_total"] == 4
        # graceful preemption path: emergency save means zero lost
        led2.record_emergency_save(20)
        led3 = GoodputLedger(str(tmp_path))
        assert led3.record_resume(20) == 0
        assert led3.data["steps_lost_total"] == 4
        assert led3.data["emergency_saves"] == 1

    def test_goodput_math(self, tmp_path):
        led = GoodputLedger(str(tmp_path))
        led.record_resume(0)
        start = led.data["wall_start"]
        # 100 steps at 0.1s each over 20s of wall time -> 0.5 goodput
        assert led.goodput(100, 0.1, now=start + 20.0) == pytest.approx(0.5)
        # capped at 1.0; None without a step time
        assert led.goodput(1000, 0.1, now=start + 20.0) == 1.0
        assert led.goodput(100, None) is None
        m = led.metrics(100, 0.1)
        assert {"attempts", "steps_lost_total", "emergency_saves",
                "restore_fallbacks", "checkpoint_save_failures",
                "goodput"} <= set(m)

    def test_fallback_and_save_failure_counters(self, tmp_path):
        led = GoodputLedger(str(tmp_path))
        led.record_fallback(2)
        led.record_save_failure()
        led2 = GoodputLedger(str(tmp_path))
        assert led2.data["restore_fallbacks"] == 2
        assert led2.data["checkpoint_save_failures"] == 1


# -- fast: step watchdog -------------------------------------------------------


class TestStepWatchdog:
    def test_fires_on_stall_with_stack_dump(self):
        exits: list[int] = []
        stalls: list[float] = []
        wd = StepWatchdog(multiplier=2.0, min_seconds=0.2,
                          startup_grace_seconds=0.2, poll_seconds=0.02,
                          exit_fn=exits.append, on_stall=stalls.append)
        wd.start()
        try:
            deadline = time.monotonic() + 5.0
            while not exits and time.monotonic() < deadline:
                time.sleep(0.02)
            assert exits == [EXIT_RETRYABLE]
            assert wd.fired and stalls and stalls[0] >= 0.2
        finally:
            wd.stop()

    def test_progress_keeps_it_quiet_then_stall_fires(self):
        exits: list[int] = []
        wd = StepWatchdog(multiplier=3.0, min_seconds=0.3,
                          startup_grace_seconds=10.0, poll_seconds=0.02,
                          exit_fn=exits.append)
        wd.start()
        try:
            for step in range(1, 6):
                time.sleep(0.05)
                wd.step_completed(step)
            assert not exits and not wd.fired
            # threshold adapted to observed ~50ms steps, floored at 0.3s
            assert wd.threshold() == pytest.approx(0.3)
            deadline = time.monotonic() + 5.0
            while not exits and time.monotonic() < deadline:
                time.sleep(0.02)
            assert exits == [EXIT_RETRYABLE]
        finally:
            wd.stop()

    def test_stop_prevents_firing(self):
        exits: list[int] = []
        wd = StepWatchdog(min_seconds=0.1, startup_grace_seconds=0.1,
                          poll_seconds=0.02, exit_fn=exits.append)
        wd.start()
        wd.stop()
        time.sleep(0.3)
        assert not exits and not wd.fired


# -- slow: process-level chaos on the emulated control plane -------------------


@pytest.fixture()
def cp(tmp_path):
    from kubeflow_tpu.operator.control_plane import (
        ControlPlane, ControlPlaneConfig,
    )
    from kubeflow_tpu.runtime.topology import Cluster, SliceTopology

    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="cpu",
                                              dims=(2, 2))]),
        platform="cpu",
        heartbeat_timeout=20.0,
        rendezvous_timeout=60.0,
    ))
    plane.start()
    yield plane
    plane.stop()


def _train_job(name: str, *, steps: int, ckpt_every: int,
               extra_config: dict = None, backoff: int = 3):
    from kubeflow_tpu.core.jobs import (
        JAXJob, JAXJobSpec, ReplicaSpec, RestartPolicy, TPUResourceSpec,
        WorkloadSpec,
    )
    from kubeflow_tpu.core.object import ObjectMeta

    config = {
        "model": "tiny",
        # big enough that a step costs real time (the chaos window), small
        # enough that the suite stays minutes not hours
        "model_overrides": {"n_layers": 2, "hidden": 128},
        "steps": steps,
        "log_every": 2,
        "data": {"global_batch": 16, "seq_len": 128, "kind": "synthetic"},
        **(extra_config or {}),
    }
    j = JAXJob(
        metadata=ObjectMeta(name=name),
        spec=JAXJobSpec(
            replica_specs={"worker": ReplicaSpec(
                replicas=1,
                restart_policy=RestartPolicy.EXIT_CODE,
                template=WorkloadSpec(entrypoint="llm_pretrain", config=config),
                resources=TPUResourceSpec(tpu_chips=1),
            )},
        ),
    )
    j.spec.run_policy.backoff_limit = backoff
    j.spec.run_policy.checkpoint.enabled = True
    j.spec.run_policy.checkpoint.interval_steps = ckpt_every
    return j


def _wait_step(cp, name: str, step: int, timeout: float = 300.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        cur = cp.get_job(name)
        if cur is not None and cur.status.metrics.step >= step:
            return cur
        time.sleep(0.2)
    raise AssertionError(f"{name}: never reached step {step}")


def _ledger(cp, name: str) -> dict:
    path = os.path.join(cp.config.base_dir, "default", name, "worker-0",
                        "goodput.json")
    return json.load(open(path))


def _worker_log(cp, name: str) -> str:
    path = os.path.join(cp.config.base_dir, "logs",
                        f"default.{name}-worker-0.log")
    return open(path).read()


@pytest.mark.slow
def test_chaos_sigterm_resumes_at_emergency_step(cp):
    """A graceful preemption (SIGTERM with unbounded grace) loses ZERO
    completed steps: the trainer force-saves to the emergency tier at the
    next step boundary, exits retryable, the controller gang-restarts, and
    resume picks the emergency step — not the interval save up to
    checkpoint_every older."""
    import signal

    from kubeflow_tpu.operator.faults import FaultInjector

    job = cp.submit(_train_job("surv", steps=60, ckpt_every=20))
    cp.wait_for(job, "Running", timeout=240)
    _wait_step(cp, "surv", 4)
    inj = FaultInjector(cp)
    assert inj.kill_worker("default/surv", index=0, sig=signal.SIGTERM)
    done = cp.wait_for(job, "Succeeded", timeout=420)
    assert done.status.restart_count >= 1, "SIGTERM did not gang-restart"
    assert done.status.metrics.step == 60

    log = _worker_log(cp, "surv")
    m = re.search(r"preemption: emergency checkpoint at step (\d+) \(saved\)",
                  log)
    assert m, "no emergency save in worker log"
    saved_at = int(m.group(1))
    m = re.search(r"resumed from checkpoint at step (\d+) \(tier=emergency",
                  log)
    assert m, "resume did not come from the emergency tier"
    assert int(m.group(1)) == saved_at

    led = _ledger(cp, "surv")
    assert led["emergency_saves"] >= 1
    assert led["steps_lost_total"] == 0, led
    # the whole ledger rode metrics.jsonl onto job status
    assert done.status.metrics.emergency_saves >= 1
    assert done.status.metrics.steps_lost_total == 0
    assert done.status.metrics.goodput is not None
    assert 0.0 < done.status.metrics.goodput <= 1.0


@pytest.mark.slow
def test_chaos_sigkill_resumes_from_interval_save(cp):
    """SIGKILL gives no grace: the emergency tier stays empty and resume
    comes from the last committed interval save — losing at most
    checkpoint_every steps, all of them accounted in the ledger."""
    from kubeflow_tpu.operator.faults import FaultInjector

    job = cp.submit(_train_job("hardk", steps=60, ckpt_every=8))
    cp.wait_for(job, "Running", timeout=240)
    _wait_step(cp, "hardk", 10)   # >= one committed interval save
    inj = FaultInjector(cp)
    assert inj.kill_worker("default/hardk", index=0)   # SIGKILL
    done = cp.wait_for(job, "Succeeded", timeout=420)
    assert done.status.restart_count >= 1
    assert done.status.metrics.step == 60

    log = _worker_log(cp, "hardk")
    m = re.search(r"resumed from checkpoint at step (\d+) \(tier=interval",
                  log)
    assert m, "resume did not come from the interval tier"
    assert int(m.group(1)) % 8 == 0 and int(m.group(1)) > 0
    led = _ledger(cp, "hardk")
    assert led["emergency_saves"] == 0
    assert led["attempts"] >= 2
    assert done.status.metrics.goodput is not None


@pytest.mark.slow
def test_chaos_corrupt_latest_falls_back_and_succeeds(cp):
    """FaultInjector.corrupt_latest_checkpoint's reason to exist: the
    newest checkpoint is torn to garbage while the job is stopped; resume
    must verify, quarantine, FALL BACK to an older valid step, surface the
    fallback as a metric — and the job must still reach Succeeded."""
    from kubeflow_tpu.core.store import ConflictError
    from kubeflow_tpu.operator.faults import FaultInjector

    job = cp.submit(_train_job("fallb", steps=80, ckpt_every=6))
    cp.wait_for(job, "Running", timeout=240)
    # Two committed interval saves before suspending: even if the teardown
    # emergency save loses the grace race, corrupting the newest still
    # leaves an older VALID step to fall back to.
    deadline = time.time() + 240
    while time.time() < deadline:
        cur = cp.get_job("fallb")
        if (cur.status.metrics.last_checkpoint_step or 0) >= 12:
            break
        time.sleep(0.2)
    assert (cur.status.metrics.last_checkpoint_step or 0) >= 12

    def _set_suspend(value: bool):
        for _ in range(20):
            fresh = cp.get_job("fallb")
            fresh.spec.run_policy.suspend = value
            try:
                cp.store.update(fresh)
                return
            except ConflictError:
                time.sleep(0.05)
        raise AssertionError("could not update suspend")

    # Deterministic corruption window: suspend stops the gang (the trainer
    # emergency-saves on the teardown SIGTERM), then the newest step —
    # whichever tier holds it — is corrupted before resume.
    _set_suspend(True)
    cp.wait_for(job, "Suspended", timeout=120)
    # The Suspended condition lands when the Worker OBJECT is deleted; the
    # process drains asynchronously (teardown SIGTERM -> emergency save ->
    # exit). Corrupting before that save commits would miss the newest
    # step, so wait for the process to be gone.
    deadline = time.time() + 60
    while cp.runtime.procman.alive() and time.time() < deadline:
        time.sleep(0.1)
    assert not cp.runtime.procman.alive(), "worker never drained"
    inj = FaultInjector(cp)
    target = inj.corrupt_latest_checkpoint("default/fallb")
    assert target is not None
    _set_suspend(False)

    done = cp.wait_for(job, "Succeeded", timeout=420)
    assert done.status.metrics.step == 80
    log = _worker_log(cp, "fallb")
    m = re.search(r"resumed from checkpoint at step (\d+) \(tier=\w+, "
                  r"fallbacks=(\d+)\)", log)
    assert m and int(m.group(2)) >= 1, "no fallback recorded in resume"
    led = _ledger(cp, "fallb")
    assert led["restore_fallbacks"] >= 1
    assert done.status.metrics.restore_fallbacks >= 1
    # the corrupted step was quarantined for post-mortem, not deleted
    qroot = os.path.dirname(target)
    assert os.path.isdir(os.path.join(qroot, "quarantine"))


@pytest.mark.slow
def test_chaos_wedged_step_caught_by_watchdog(cp):
    """A wedged step (hung collective) never misses a heartbeat — the
    beat thread is alive — so the lease detector would wait forever. The
    in-trainer watchdog must catch it within a multiple of the observed
    step time, dump stacks, and exit retryable; the gang restart then
    resumes and finishes."""
    once = os.path.join(cp.config.base_dir, "wedge-once")
    job = cp.submit(_train_job(
        "wedge", steps=24, ckpt_every=6,
        extra_config={
            "fault_injection": {"wedge_at_step": 8, "wedge_once_file": once},
            "watchdog_multiplier": 3.0,
            "watchdog_min_seconds": 2.0,
            "watchdog_startup_grace_seconds": 120.0,
        }))
    done = cp.wait_for(job, "Succeeded", timeout=420)
    assert done.status.restart_count >= 1, "watchdog never fired"
    assert done.status.metrics.step == 24
    log = _worker_log(cp, "wedge")
    assert "fault injection: wedging at step 8" in log
    assert "watchdog: no step progress" in log
    assert "step-watchdog" in log or "--- thread" in log  # stack dump present

    # Detection latency: wedge -> watchdog fire, from the worker log's own
    # timestamps. Must beat the 20s heartbeat lease by a wide margin (the
    # lease would in fact NEVER fire here — the heartbeat thread still
    # beats — which is exactly why the watchdog exists).
    def _ts(pattern):
        m = re.search(r"^(\S+ \S+) .*" + pattern, log, re.M)
        assert m, pattern
        from datetime import datetime

        return datetime.strptime(m.group(1), "%Y-%m-%d %H:%M:%S,%f")

    wedged = _ts(r"fault injection: wedging")
    fired = _ts(r"watchdog: no step progress")
    latency = (fired - wedged).total_seconds()
    assert 0 <= latency < cp.config.heartbeat_timeout, latency


@pytest.mark.slow
def test_chaos_save_failure_training_continues(cp):
    """A checkpoint-store failure mid-run must not kill training: the save
    is logged + counted (checkpoint_save_failures on job status — the
    alarm someone pages on), the loop keeps stepping, and the job
    finishes."""
    job = cp.submit(_train_job(
        "savef", steps=24, ckpt_every=6,
        extra_config={"fault_injection": {"save_fail_steps": [6, 12]}}))
    done = cp.wait_for(job, "Succeeded", timeout=420)
    assert done.status.restart_count == 0       # a failed save is NOT fatal
    assert done.status.metrics.step == 24
    assert done.status.metrics.checkpoint_save_failures == 2
    led = _ledger(cp, "savef")
    assert led["checkpoint_save_failures"] == 2
    log = _worker_log(cp, "savef")
    assert "checkpoint save at step 6 failed" in log
