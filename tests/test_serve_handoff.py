"""Disaggregated prefill/decode handoff (ISSUE 12 tentpole): greedy
token identity across the prefill→handoff→decode boundary on dense AND
paged backends, the page-ownership protocol (holds released only on
ack, failure/reap paths refcount-balanced), and the wire format."""

import time

import numpy as np
import pytest
import jax

from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams
from kubeflow_tpu.serve.handoff import HandoffPayload

CFG = preset("tiny", vocab_size=512)
PARAMS = init_decoder_params(jax.random.PRNGKey(0), CFG)


def spec(role="unified", paged=False, **kw):
    base = dict(max_batch_size=2, max_seq_len=96, prefill_buckets=[32],
                chunked_prefill_tokens=16, decode_steps=4, role=role)
    if paged:
        base.update(paged=True, page_size=16)
    base.update(kw)
    return BatchingSpec(**base)


def engine(role="unified", paged=False, **kw):
    return LLMEngine(CFG, spec(role=role, paged=paged, **kw), params=PARAMS)


def drive(eng, req, timeout=60.0):
    deadline = time.monotonic() + timeout
    while not req.done.is_set():
        eng.step()
        assert time.monotonic() < deadline, "request never finished"
    return req


def drain(eng, timeout=30.0):
    deadline = time.monotonic() + timeout
    while (eng.kv_pages_in_use() > 0 or eng._rounds
           or eng._handoff_holds):
        eng.step()
        assert time.monotonic() < deadline, "engine did not quiesce"


PROMPTS = [list(range(3, 23)), [7, 9, 11] * 9, list(range(40, 45))]


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_greedy_token_identity_across_handoff(paged):
    """The acceptance pin: unified output == prefill→handoff→decode
    output, token for token, on both KV backends."""
    uni = engine(paged=paged)
    pre = engine(role="prefill", paged=paged)
    dec = engine(role="decode", paged=paged)
    params = SamplingParams(max_new_tokens=12, temperature=0.0)
    for prompt in PROMPTS:
        want = uni.generate(prompt, params)
        p_req = drive(pre, pre.submit(prompt, params))
        assert p_req.finish_reason == "handoff"
        payload = p_req.handoff
        assert payload is not None
        assert payload.first_token == want[0]
        assert payload.kv_len == len(prompt)
        # Round-trip the wire format — the HTTP path ships exactly this.
        payload = HandoffPayload.from_wire(payload.to_wire())
        d_req = drive(dec, dec.submit_handoff(payload))
        assert d_req.finish_reason in ("stop", "length")
        got = [payload.first_token] + d_req.output_tokens
        assert got == want, (prompt, got, want)
        pre.complete_handoff(p_req.id)
    drain(pre)
    drain(dec)
    if paged:
        pre._allocator.assert_quiescent()
        dec._allocator.assert_quiescent()


def test_handoff_hold_released_only_on_ack():
    """Paged ownership: exported pages stay referenced (backing the
    payload) until complete_handoff, then free refcount-balanced."""
    pre = engine(role="prefill", paged=True)
    req = drive(pre, pre.submit(PROMPTS[0], SamplingParams(max_new_tokens=8)))
    assert req.finish_reason == "handoff"
    assert pre.kv_pages_in_use() > 0, "hold should still reference pages"
    assert req.id in pre._handoff_holds
    pre.complete_handoff(req.id)
    drain(pre)
    pre._allocator.assert_quiescent()


def test_handoff_failure_and_reap_paths_free_pages():
    pre = engine(role="prefill", paged=True)
    # fail_handoff (decode side never acked): freed + counted failed.
    r1 = drive(pre, pre.submit(PROMPTS[0], SamplingParams(max_new_tokens=8)))
    pre.fail_handoff(r1.id)
    drain(pre)
    assert pre.metrics.snapshot()["handoffs_failed"] == 1
    # Abandoned hold (server died before any ack): the reaper frees it.
    r2 = drive(pre, pre.submit(PROMPTS[1], SamplingParams(max_new_tokens=8)))
    assert pre.kv_pages_in_use() > 0
    r2.cancel()
    drain(pre)
    assert pre.metrics.snapshot()["handoffs_failed"] == 2
    pre._allocator.assert_quiescent()


def test_prefill_role_finishes_short_requests_locally():
    """A request finished AT the first token (budget 1) never hands off
    — there is nothing to decode."""
    pre = engine(role="prefill", paged=True)
    req = drive(pre, pre.submit(PROMPTS[0], SamplingParams(max_new_tokens=1)))
    assert req.finish_reason == "length"
    assert req.handoff is None
    assert len(req.output_tokens) == 1
    drain(pre)
    pre._allocator.assert_quiescent()


def test_unified_fallback_submit_on_prefill_engine():
    """handoff=False on a prefill-role engine = full local decode (the
    router's unified-fallback path when the decode pool is unhealthy)."""
    uni = engine()
    pre = engine(role="prefill")
    params = SamplingParams(max_new_tokens=10, temperature=0.0)
    want = uni.generate(PROMPTS[0], params)
    req = drive(pre, pre.submit(PROMPTS[0], params, handoff=False))
    assert req.finish_reason in ("stop", "length")
    assert req.output_tokens == want


def test_adopted_pages_register_prefix_for_reuse():
    """Handed-off KV becomes prefix-cache content on the decode engine:
    a same-prefix adoption hits the cached pages."""
    pre = engine(role="prefill", paged=True)
    dec = engine(role="decode", paged=True)
    prompt = list(range(1, 33))          # two full 16-token pages
    params = SamplingParams(max_new_tokens=6, temperature=0.0)
    p1 = drive(pre, pre.submit(prompt, params))
    drive(dec, dec.submit_handoff(HandoffPayload.from_wire(
        p1.handoff.to_wire())))
    pre.complete_handoff(p1.id)
    hits_before = dec._allocator.stats["prefix_hits"]
    p2 = drive(pre, pre.submit(prompt, params, request_id="again"))
    drive(dec, dec.submit_handoff(p2.handoff))
    pre.complete_handoff(p2.id)
    assert dec._allocator.stats["prefix_hits"] > hits_before
    drain(pre)
    drain(dec)
    dec._allocator.assert_quiescent()


def test_adoption_rejects_shape_and_budget_mismatch():
    dec = engine(role="decode", paged=True)
    good = HandoffPayload(
        request_id="x", prompt_tokens=[1, 2, 3], first_token=4,
        max_new_tokens=4, temperature=0.0, top_k=0, top_p=1.0,
        stop_token=None, qos="standard",
        kv_k=np.zeros((CFG.n_layers, 3, CFG.n_kv_heads, CFG.head_dim),
                      np.float32),
        kv_v=np.zeros((CFG.n_layers, 3, CFG.n_kv_heads, CFG.head_dim),
                      np.float32))
    import dataclasses

    bad_budget = dataclasses.replace(good, max_new_tokens=0)
    with pytest.raises(ValueError, match="budget"):
        dec.submit_handoff(bad_budget)
    bad_shape = dataclasses.replace(
        good, kv_k=good.kv_k[:, :, :1], kv_v=good.kv_v[:, :, :1])
    with pytest.raises(ValueError, match="shape"):
        dec.submit_handoff(bad_shape)


@pytest.mark.slow   # ~20s: four engines, three prompts each
def test_int8_greedy_token_identity_across_handoff():
    """Tentpole pin: the quantized fabric end to end. An int8-pool
    prefill engine exports v2 blobs (int8 pages + scale rows); the int8
    decode engine adopts them and must reproduce the int8 UNIFIED
    engine's greedy output token for token — same quantized KV, so the
    wire/adopt rebuild cannot introduce any divergence."""
    uni = engine(paged=True, kv_cache_dtype="int8")
    pre = engine(role="prefill", paged=True, kv_cache_dtype="int8")
    dec = engine(role="decode", paged=True, kv_cache_dtype="int8")
    params = SamplingParams(max_new_tokens=12, temperature=0.0)
    for prompt in PROMPTS:
        want = uni.generate(prompt, params)
        p_req = drive(pre, pre.submit(prompt, params))
        assert p_req.finish_reason == "handoff"
        payload = p_req.handoff
        assert payload.cache_dtype == "int8"
        assert payload.kv_k.dtype == np.int8
        assert payload.kv_scale_k is not None
        # The v2 wire round trip the HTTP path ships.
        wire = payload.to_wire()
        payload = HandoffPayload.from_wire(wire)
        assert payload.cache_dtype == "int8"
        d_req = drive(dec, dec.submit_handoff(payload))
        got = [payload.first_token] + d_req.output_tokens
        assert got == want, (prompt, got, want)
        pre.complete_handoff(p_req.id)
        # Wire savings: int8+scales vs the full-dtype payload for the
        # same prompt (~0.625x at tiny's Dh=16; ~0.52x at Dh=128).
        full = engine(role="prefill", paged=True)
        f_req = drive(full, full.submit(prompt, params))
        assert len(wire) < len(f_req.handoff.to_wire()) * 0.8
        full.complete_handoff(f_req.id)
    # Byte metrics flowed on both sides.
    assert pre.metrics.snapshot()["handoff_bytes_exported"] > 0
    assert dec.metrics.snapshot()["handoff_bytes_adopted"] > 0
    drain(pre)
    drain(dec)
    pre._allocator.assert_quiescent()
    dec._allocator.assert_quiescent()


@pytest.mark.slow  # tier-1 budget: two engines + adoption round trips
def test_int8_adopted_pages_register_prefix_for_reuse():
    """Adoption rebuilds pages AND scale rows into the radix index: a
    same-prefix re-adoption on the int8 decode engine hits cache."""
    pre = engine(role="prefill", paged=True, kv_cache_dtype="int8")
    dec = engine(role="decode", paged=True, kv_cache_dtype="int8")
    prompt = list(range(1, 33))
    params = SamplingParams(max_new_tokens=6, temperature=0.0)
    p1 = drive(pre, pre.submit(prompt, params))
    drive(dec, dec.submit_handoff(HandoffPayload.from_wire(
        p1.handoff.to_wire())))
    pre.complete_handoff(p1.id)
    hits_before = dec._allocator.stats["prefix_hits"]
    p2 = drive(pre, pre.submit(prompt, params, request_id="again"))
    drive(dec, dec.submit_handoff(p2.handoff))
    pre.complete_handoff(p2.id)
    assert dec._allocator.stats["prefix_hits"] > hits_before
    drain(pre)
    drain(dec)
    dec._allocator.assert_quiescent()


@pytest.mark.slow  # tier-1 budget: four engines; negative path also covered by wire-v2 tests
def test_adoption_rejects_cache_dtype_mismatch():
    """A mixed fleet mid-rollout must fail LOUDLY, both directions: a
    full-dtype payload on an int8 engine and vice versa."""
    pre8 = engine(role="prefill", paged=True, kv_cache_dtype="int8")
    pre16 = engine(role="prefill", paged=True)
    dec8 = engine(role="decode", paged=True, kv_cache_dtype="int8")
    dec16 = engine(role="decode", paged=True)
    params = SamplingParams(max_new_tokens=4, temperature=0.0)
    p8 = drive(pre8, pre8.submit(PROMPTS[0], params))
    p16 = drive(pre16, pre16.submit(PROMPTS[0], params))
    with pytest.raises(ValueError, match="cache-dtype mismatch"):
        dec16.submit_handoff(p8.handoff)
    with pytest.raises(ValueError, match="cache-dtype mismatch"):
        dec8.submit_handoff(p16.handoff)
    # The matched pairs still work.
    drive(dec8, dec8.submit_handoff(p8.handoff))
    drive(dec16, dec16.submit_handoff(p16.handoff))
    pre8.complete_handoff(p8.id)
    pre16.complete_handoff(p16.id)
    for e in (pre8, pre16, dec8, dec16):
        drain(e)
        e._allocator.assert_quiescent()


def test_wire_v2_rejects_malformed_scales():
    """v2 validation: scales without int8 payload, one-sided scales, and
    a scale shape that disagrees with the page shape all fail validate()
    before anything ships."""
    kv8 = np.ones((1, 2, 1, 4), np.int8)
    sc = np.ones((1, 2, 1), np.float32)
    base = dict(request_id="w", prompt_tokens=[1, 2], first_token=3,
                max_new_tokens=2, temperature=0.0, top_k=0, top_p=1.0,
                stop_token=None, qos="standard")
    with pytest.raises(ValueError, match="pair"):
        HandoffPayload(kv_k=kv8, kv_v=kv8, kv_scale_k=sc, **base).validate()
    with pytest.raises(ValueError, match="int8"):
        HandoffPayload(kv_k=kv8.astype(np.float32),
                       kv_v=kv8.astype(np.float32),
                       kv_scale_k=sc, kv_scale_v=sc, **base).validate()
    with pytest.raises(ValueError, match="scale"):
        HandoffPayload(kv_k=kv8, kv_v=kv8, kv_scale_k=sc[:, :1],
                       kv_scale_v=sc[:, :1], **base).validate()
    # Truncating the scale segment off a v2 blob is detected.
    good = HandoffPayload(kv_k=kv8, kv_v=kv8, kv_scale_k=sc,
                          kv_scale_v=sc, **base)
    wire = good.to_wire()
    with pytest.raises(ValueError, match="truncated"):
        HandoffPayload.from_wire(wire[:-2])


def test_wire_format_rejects_truncation():
    payload = HandoffPayload(
        request_id="w", prompt_tokens=[1, 2], first_token=3,
        max_new_tokens=2, temperature=0.0, top_k=0, top_p=1.0,
        stop_token=None, qos="standard",
        kv_k=np.ones((1, 2, 1, 4), np.float32),
        kv_v=np.ones((1, 2, 1, 4), np.float32))
    wire = payload.to_wire()
    back = HandoffPayload.from_wire(wire)
    assert back.prompt_tokens == [1, 2]
    assert np.array_equal(back.kv_k, payload.kv_k)
    with pytest.raises(ValueError, match="truncated"):
        HandoffPayload.from_wire(wire[:-3])
