"""Disaggregated prefill/decode handoff (ISSUE 12 tentpole): greedy
token identity across the prefill→handoff→decode boundary on dense AND
paged backends, the page-ownership protocol (holds released only on
ack, failure/reap paths refcount-balanced), and the wire format."""

import time

import numpy as np
import pytest
import jax

from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams
from kubeflow_tpu.serve.handoff import HandoffPayload

CFG = preset("tiny", vocab_size=512)
PARAMS = init_decoder_params(jax.random.PRNGKey(0), CFG)


def spec(role="unified", paged=False, **kw):
    base = dict(max_batch_size=2, max_seq_len=96, prefill_buckets=[32],
                chunked_prefill_tokens=16, decode_steps=4, role=role)
    if paged:
        base.update(paged=True, page_size=16)
    base.update(kw)
    return BatchingSpec(**base)


def engine(role="unified", paged=False, **kw):
    return LLMEngine(CFG, spec(role=role, paged=paged, **kw), params=PARAMS)


def drive(eng, req, timeout=60.0):
    deadline = time.monotonic() + timeout
    while not req.done.is_set():
        eng.step()
        assert time.monotonic() < deadline, "request never finished"
    return req


def drain(eng, timeout=30.0):
    deadline = time.monotonic() + timeout
    while (eng.kv_pages_in_use() > 0 or eng._rounds
           or eng._handoff_holds):
        eng.step()
        assert time.monotonic() < deadline, "engine did not quiesce"


PROMPTS = [list(range(3, 23)), [7, 9, 11] * 9, list(range(40, 45))]


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_greedy_token_identity_across_handoff(paged):
    """The acceptance pin: unified output == prefill→handoff→decode
    output, token for token, on both KV backends."""
    uni = engine(paged=paged)
    pre = engine(role="prefill", paged=paged)
    dec = engine(role="decode", paged=paged)
    params = SamplingParams(max_new_tokens=12, temperature=0.0)
    for prompt in PROMPTS:
        want = uni.generate(prompt, params)
        p_req = drive(pre, pre.submit(prompt, params))
        assert p_req.finish_reason == "handoff"
        payload = p_req.handoff
        assert payload is not None
        assert payload.first_token == want[0]
        assert payload.kv_len == len(prompt)
        # Round-trip the wire format — the HTTP path ships exactly this.
        payload = HandoffPayload.from_wire(payload.to_wire())
        d_req = drive(dec, dec.submit_handoff(payload))
        assert d_req.finish_reason in ("stop", "length")
        got = [payload.first_token] + d_req.output_tokens
        assert got == want, (prompt, got, want)
        pre.complete_handoff(p_req.id)
    drain(pre)
    drain(dec)
    if paged:
        pre._allocator.assert_quiescent()
        dec._allocator.assert_quiescent()


def test_handoff_hold_released_only_on_ack():
    """Paged ownership: exported pages stay referenced (backing the
    payload) until complete_handoff, then free refcount-balanced."""
    pre = engine(role="prefill", paged=True)
    req = drive(pre, pre.submit(PROMPTS[0], SamplingParams(max_new_tokens=8)))
    assert req.finish_reason == "handoff"
    assert pre.kv_pages_in_use() > 0, "hold should still reference pages"
    assert req.id in pre._handoff_holds
    pre.complete_handoff(req.id)
    drain(pre)
    pre._allocator.assert_quiescent()


def test_handoff_failure_and_reap_paths_free_pages():
    pre = engine(role="prefill", paged=True)
    # fail_handoff (decode side never acked): freed + counted failed.
    r1 = drive(pre, pre.submit(PROMPTS[0], SamplingParams(max_new_tokens=8)))
    pre.fail_handoff(r1.id)
    drain(pre)
    assert pre.metrics.snapshot()["handoffs_failed"] == 1
    # Abandoned hold (server died before any ack): the reaper frees it.
    r2 = drive(pre, pre.submit(PROMPTS[1], SamplingParams(max_new_tokens=8)))
    assert pre.kv_pages_in_use() > 0
    r2.cancel()
    drain(pre)
    assert pre.metrics.snapshot()["handoffs_failed"] == 2
    pre._allocator.assert_quiescent()


def test_prefill_role_finishes_short_requests_locally():
    """A request finished AT the first token (budget 1) never hands off
    — there is nothing to decode."""
    pre = engine(role="prefill", paged=True)
    req = drive(pre, pre.submit(PROMPTS[0], SamplingParams(max_new_tokens=1)))
    assert req.finish_reason == "length"
    assert req.handoff is None
    assert len(req.output_tokens) == 1
    drain(pre)
    pre._allocator.assert_quiescent()


def test_unified_fallback_submit_on_prefill_engine():
    """handoff=False on a prefill-role engine = full local decode (the
    router's unified-fallback path when the decode pool is unhealthy)."""
    uni = engine()
    pre = engine(role="prefill")
    params = SamplingParams(max_new_tokens=10, temperature=0.0)
    want = uni.generate(PROMPTS[0], params)
    req = drive(pre, pre.submit(PROMPTS[0], params, handoff=False))
    assert req.finish_reason in ("stop", "length")
    assert req.output_tokens == want


def test_adopted_pages_register_prefix_for_reuse():
    """Handed-off KV becomes prefix-cache content on the decode engine:
    a same-prefix adoption hits the cached pages."""
    pre = engine(role="prefill", paged=True)
    dec = engine(role="decode", paged=True)
    prompt = list(range(1, 33))          # two full 16-token pages
    params = SamplingParams(max_new_tokens=6, temperature=0.0)
    p1 = drive(pre, pre.submit(prompt, params))
    drive(dec, dec.submit_handoff(HandoffPayload.from_wire(
        p1.handoff.to_wire())))
    pre.complete_handoff(p1.id)
    hits_before = dec._allocator.stats["prefix_hits"]
    p2 = drive(pre, pre.submit(prompt, params, request_id="again"))
    drive(dec, dec.submit_handoff(p2.handoff))
    pre.complete_handoff(p2.id)
    assert dec._allocator.stats["prefix_hits"] > hits_before
    drain(pre)
    drain(dec)
    dec._allocator.assert_quiescent()


def test_adoption_rejects_shape_and_budget_mismatch():
    dec = engine(role="decode", paged=True)
    good = HandoffPayload(
        request_id="x", prompt_tokens=[1, 2, 3], first_token=4,
        max_new_tokens=4, temperature=0.0, top_k=0, top_p=1.0,
        stop_token=None, qos="standard",
        kv_k=np.zeros((CFG.n_layers, 3, CFG.n_kv_heads, CFG.head_dim),
                      np.float32),
        kv_v=np.zeros((CFG.n_layers, 3, CFG.n_kv_heads, CFG.head_dim),
                      np.float32))
    import dataclasses

    bad_budget = dataclasses.replace(good, max_new_tokens=0)
    with pytest.raises(ValueError, match="budget"):
        dec.submit_handoff(bad_budget)
    bad_shape = dataclasses.replace(
        good, kv_k=good.kv_k[:, :, :1], kv_v=good.kv_v[:, :, :1])
    with pytest.raises(ValueError, match="shape"):
        dec.submit_handoff(bad_shape)


def test_wire_format_rejects_truncation():
    payload = HandoffPayload(
        request_id="w", prompt_tokens=[1, 2], first_token=3,
        max_new_tokens=2, temperature=0.0, top_k=0, top_p=1.0,
        stop_token=None, qos="standard",
        kv_k=np.ones((1, 2, 1, 4), np.float32),
        kv_v=np.ones((1, 2, 1, 4), np.float32))
    wire = payload.to_wire()
    back = HandoffPayload.from_wire(wire)
    assert back.prompt_tokens == [1, 2]
    assert np.array_equal(back.kv_k, payload.kv_k)
    with pytest.raises(ValueError, match="truncated"):
        HandoffPayload.from_wire(wire[:-3])
