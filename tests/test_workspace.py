"""Workspace subsystem: notebook sessions (real kernel processes + culling),
PodDefault injection, profile quota enforcement — the notebook-controller /
admission-webhook / profile-controller behaviors of SURVEY.md §2.1, §3.5."""

import os
import time

import pytest

from kubeflow_tpu.core.jobs import (
    JAXJob, JAXJobSpec, ReplicaSpec, TPUResourceSpec, WorkloadSpec,
)
from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.core.workspace_specs import (
    Notebook, NotebookSpec, PodDefault, PodDefaultSpec, Profile, ProfileSpec,
    QuotaSpec, apply_pod_defaults,
)
from kubeflow_tpu.operator.control_plane import ControlPlane, ControlPlaneConfig
from kubeflow_tpu.runtime.topology import Cluster, SliceTopology
from kubeflow_tpu.workspace.notebook_controller import WAKE_ANNOTATION
from kubeflow_tpu.workspace.profile_controller import (
    add_contributor, can_access, remove_contributor,
)
from kubeflow_tpu.workspace.session_main import exec_code


def make_cp(tmp_path, launch=False) -> ControlPlane:
    return ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="v5e",
                                              dims=(2, 2))]),
        launch_processes=launch,
        metrics_sync_interval=None,
    ))


class TestNotebookSession:
    """Real kernel process: spawn, exec, cull, wake."""

    @pytest.fixture()
    def cp(self, tmp_path):
        plane = make_cp(tmp_path, launch=True)
        plane.start()
        yield plane
        plane.stop()

    def wait_phase(self, cp, name, phase, timeout=30):
        deadline = time.time() + timeout
        while time.time() < deadline:
            nb = cp.store.try_get(Notebook, name)
            if nb is not None and nb.status.phase == phase:
                return nb
            time.sleep(0.1)
        raise TimeoutError(f"{name} never reached {phase}: "
                           f"{nb.status.phase if nb else None}")

    @staticmethod
    def _wait_session(sock, timeout=20):
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                if exec_code(sock, "0")["ok"]:
                    return
            except OSError:
                time.sleep(0.1)
        raise TimeoutError(f"session at {sock} never answered")

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_spawn_exec_cull_wake(self, cp):
        cp.submit(PodDefault(
            metadata=ObjectMeta(name="inject"),
            spec=PodDefaultSpec(selector={"team": "ml"},
                                env={"INJECTED_FLAG": "yes"})))
        cp.submit(Notebook(
            metadata=ObjectMeta(name="nb", labels={"team": "ml"}),
            spec=NotebookSpec(env={"OWN_VAR": "1",
                                   "KFTPU_NB_PREIMPORT": "0"},
                              # generous: under full-suite load the spawn
                              # itself can take seconds; the cull-wait below
                              # tolerates up to 60s
                              idle_cull_seconds=10.0)))
        nb = self.wait_phase(cp, "nb", "Running")
        assert nb.status.url.startswith("unix://")
        sock = nb.status.url[len("unix://"):]
        self._wait_session(sock)

        # The session is a live REPL...
        res = exec_code(sock, "x = 20 + 22\nprint(x)")
        assert res["ok"] and res["output"].strip() == "42"
        res = exec_code(sock, "x * 2")
        assert res["ok"] and res["output"].strip() == "84"
        # ...with PodDefault env injected (admission-webhook analog)
        res = exec_code(sock, "import os; print(os.environ['INJECTED_FLAG'], os.environ['OWN_VAR'])")
        assert res["output"].strip() == "yes 1"
        # errors surface without killing the session
        res = exec_code(sock, "1/0")
        assert not res["ok"] and "ZeroDivisionError" in res["error"]
        assert exec_code(sock, "print('alive')")["ok"]

        # Idle culling: stop talking to it for > idle_cull_seconds.
        nb = self.wait_phase(cp, "nb", "Culled", timeout=60)
        assert nb.status.pid is None

        # Wake: the "open notebook" action.
        nb.metadata.annotations[WAKE_ANNOTATION] = "true"
        cp.store.update(nb, check_version=False)
        nb = self.wait_phase(cp, "nb", "Running")
        sock = nb.status.url[len("unix://"):]
        self._wait_session(sock)
        assert exec_code(sock, "print('back')")["ok"]


class TestPodDefaults:
    def test_merge_semantics(self):
        pds = [
            PodDefault(metadata=ObjectMeta(name="a"),
                       spec=PodDefaultSpec(selector={"t": "x"},
                                           env={"A": "1", "B": "pd"})),
            PodDefault(metadata=ObjectMeta(name="b"),
                       spec=PodDefaultSpec(selector={"t": "y"},
                                           env={"C": "never"})),
        ]
        merged = apply_pod_defaults({"t": "x"}, {"B": "own"}, pds)
        assert merged == {"A": "1", "B": "own"}  # explicit env wins


def job_of(name, chips=1):
    return JAXJob(
        metadata=ObjectMeta(name=name, namespace="team-a"),
        spec=JAXJobSpec(replica_specs={"worker": ReplicaSpec(
            replicas=1, template=WorkloadSpec(entrypoint="noop"),
            resources=TPUResourceSpec(tpu_chips=chips))}))


class TestProfileQuota:
    @pytest.fixture()
    def cp(self, tmp_path):
        return make_cp(tmp_path, launch=False)

    def test_quota_suspends_and_resumes(self, cp):
        cp.submit(Profile(
            metadata=ObjectMeta(name="team-a"),
            spec=ProfileSpec(owner="alice", quota=QuotaSpec(max_jobs=1))))
        cp.submit(job_of("j1"))
        cp.submit(job_of("j2"))
        cp.step()
        j1 = cp.store.get(JAXJob, "j1", "team-a")
        j2 = cp.store.get(JAXJob, "j2", "team-a")
        assert not j1.spec.run_policy.suspend
        assert j2.spec.run_policy.suspend  # newest over quota
        # j1 finishes → j2 resumes
        j1.status.set_condition("Succeeded", True, reason="Done")
        cp.store.update_status(j1)
        cp.step()
        j2 = cp.store.get(JAXJob, "j2", "team-a")
        assert not j2.spec.run_policy.suspend

    def test_chip_quota(self, cp):
        cp.submit(Profile(
            metadata=ObjectMeta(name="team-a"),
            spec=ProfileSpec(owner="alice",
                             quota=QuotaSpec(max_tpu_chips=3))))
        cp.submit(job_of("big", chips=2))
        cp.submit(job_of("small", chips=2))   # 4 > 3 → suspended
        cp.step()
        assert not cp.store.get(JAXJob, "big", "team-a").spec.run_policy.suspend
        assert cp.store.get(JAXJob, "small", "team-a").spec.run_policy.suspend
        prof = cp.store.get(Profile, "team-a")
        assert prof.status.chips_in_use == 2

    def test_user_suspend_not_overridden(self, cp):
        cp.submit(Profile(
            metadata=ObjectMeta(name="team-a"),
            spec=ProfileSpec(owner="alice", quota=QuotaSpec(max_jobs=5))))
        j = job_of("j1")
        j.spec.run_policy.suspend = True   # user's own suspend
        cp.submit(j)
        cp.step()
        assert cp.store.get(JAXJob, "j1", "team-a").spec.run_policy.suspend

    def test_contributors(self, cp):
        cp.submit(Profile(metadata=ObjectMeta(name="team-a"),
                          spec=ProfileSpec(owner="alice")))
        add_contributor(cp.store, "team-a", "bob")
        p = cp.store.get(Profile, "team-a")
        assert can_access(p, "alice") and can_access(p, "bob")
        assert not can_access(p, "eve")
        remove_contributor(cp.store, "team-a", "bob")
        assert not can_access(cp.store.get(Profile, "team-a"), "bob")


class TestKernelProfiles:
    """The example-notebook-servers image family (SURVEY.md §2.1#11): each
    kernel profile spawns with its own preimported stack."""

    @pytest.fixture()
    def cp(self, tmp_path):
        plane = make_cp(tmp_path, launch=True)
        plane.start()
        yield plane
        plane.stop()

    def _spawn(self, cp, name, image):
        cp.submit(Notebook(metadata=ObjectMeta(name=name),
                           spec=NotebookSpec(image=image,
                                             idle_cull_seconds=None)))
        deadline = time.time() + 120
        while time.time() < deadline:
            nb = cp.store.try_get(Notebook, name)
            if nb is not None and nb.status.phase in ("Running", "Failed"):
                break
            time.sleep(0.1)
        return cp.store.get(Notebook, name)

    def test_base_has_no_preloads_jax_notebook_has_jax(self, cp):
        nb = self._spawn(cp, "nb-base", "base")
        assert nb.status.phase == "Running"
        sock = nb.status.url.removeprefix("unix://")
        TestNotebookSession._wait_session(sock)
        from kubeflow_tpu.workspace.session_main import exec_code
        res = exec_code(sock, "print('jax' in dir())")
        assert res["ok"] and res["output"].strip() == "False"

        nb2 = self._spawn(cp, "nb-jax", "jax-notebook")
        sock2 = nb2.status.url.removeprefix("unix://")
        TestNotebookSession._wait_session(sock2, timeout=120)
        res = exec_code(sock2, "print(jax.__name__, numpy.__name__)",
                        timeout=90)
        assert res["ok"] and res["output"].strip() == "jax numpy"

    def test_full_profile_preloads_stack(self, cp):
        nb = self._spawn(cp, "nb-full", "jax-full")
        assert nb.status.phase == "Running"
        sock = nb.status.url.removeprefix("unix://")
        TestNotebookSession._wait_session(sock, timeout=120)
        from kubeflow_tpu.workspace.session_main import exec_code
        res = exec_code(sock, "print(flax.__name__, optax.__name__)",
                        timeout=90)
        assert res["ok"] and res["output"].strip() == "flax optax"

    def test_unknown_image_fails_with_event(self, cp):
        nb = self._spawn(cp, "nb-bogus", "pytorch-notebook")
        assert nb.status.phase == "Failed"
        assert nb.status.has_condition("Running", status=False)
        evs = cp.recorder.for_object(nb)
        assert any(e.reason == "UnknownImage" for e in evs)
