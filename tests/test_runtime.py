"""Runtime tests: topology, gang allocator, mesh building, worker processes.

Gang semantics mirror the reference's PodGroup minMember all-or-nothing
contract (SURVEY.md §2.2#20); mesh tests run on the 8-device virtual CPU
platform from conftest."""

import os
import time

import pytest

from kubeflow_tpu.core.jobs import ParallelismSpec
from kubeflow_tpu.runtime.allocator import (
    GangAllocator, GangRequest, InsufficientCapacityError,
)
from kubeflow_tpu.runtime.bootstrap import WorkerEnv, free_port
from kubeflow_tpu.runtime.mesh import MESH_AXES, build_mesh, mesh_from_parallelism
from kubeflow_tpu.runtime.procman import LocalProcessManager
from kubeflow_tpu.runtime.topology import Cluster, SliceTopology, detect_local_cluster


# -- topology ------------------------------------------------------------------

def test_topology_parse_and_counts():
    s = SliceTopology.parse("s0", "4x4x4", generation="v5p")
    assert s.num_chips == 64
    assert s.num_hosts == 16
    assert s.gen.torus_dims == 3
    with pytest.raises(ValueError):
        SliceTopology.parse("bad", "4x0")


def test_detect_local_cluster_virtual():
    c = detect_local_cluster()
    assert c.total_chips == 8  # conftest forces 8 virtual CPU devices
    assert c.slices[0].dims == (2, 4)


# -- gang allocator ------------------------------------------------------------

def two_slice_cluster():
    return Cluster(slices=[
        SliceTopology(name="v5p-a", generation="v5p", dims=(2, 2, 2)),   # 8 chips
        SliceTopology(name="v5e-b", generation="v5e", dims=(2, 2)),      # 4 chips
    ])


def test_gang_all_or_nothing():
    alloc = GangAllocator(two_slice_cluster())
    a = alloc.submit(GangRequest(name="j1", num_workers=4, chips_per_worker=2))
    assert a is not None and a.slice_name == "v5p-a"
    assert sorted(a.all_chips) == list(range(8))
    # 6 chips free total (4 on v5e-b) but j2 wants 6 on ONE slice → queued
    b = alloc.submit(GangRequest(name="j2", num_workers=6, chips_per_worker=1))
    assert b is None
    assert [p.name for p in alloc.pending()] == ["j2"]
    # j1 releases → j2 places
    alloc.release("j1")
    assert alloc.allocation("j2") is not None


def test_gang_never_fits_raises():
    alloc = GangAllocator(two_slice_cluster())
    with pytest.raises(InsufficientCapacityError):
        alloc.submit(GangRequest(name="huge", num_workers=9, chips_per_worker=1))
    # pinned to a too-small slice: also impossible
    with pytest.raises(InsufficientCapacityError):
        alloc.submit(GangRequest(name="pinned", num_workers=5, chips_per_worker=1,
                                 slice_name="v5e-b"))


def test_gang_priority_and_fifo():
    alloc = GangAllocator(two_slice_cluster())
    alloc.submit(GangRequest(name="hog", num_workers=8, chips_per_worker=1))
    alloc.submit(GangRequest(name="low1", num_workers=8, chips_per_worker=1, priority=0))
    alloc.submit(GangRequest(name="hi", num_workers=8, chips_per_worker=1, priority=5))
    alloc.release("hog")
    # high priority jumps the FIFO queue
    assert alloc.allocation("hi") is not None
    assert alloc.allocation("low1") is None


def test_gang_contiguous_chip_runs():
    alloc = GangAllocator(two_slice_cluster())
    alloc.submit(GangRequest(name="a", num_workers=2, chips_per_worker=2))
    alloc.submit(GangRequest(name="b", num_workers=1, chips_per_worker=4))
    alloc.release("a")
    c = alloc.submit(GangRequest(name="c", num_workers=1, chips_per_worker=4))
    # c should take the contiguous freed run [0..3]
    assert sorted(c.all_chips) == [0, 1, 2, 3]


def test_gang_quota_hook_skips_not_blocks():
    def quota(req: GangRequest):
        return "over quota" if req.name.startswith("q-") else None

    alloc = GangAllocator(two_slice_cluster(), quota_check=quota)
    assert alloc.submit(GangRequest(name="q-denied", num_workers=1)) is None
    # a quota-blocked gang must not head-of-line-block others
    assert alloc.submit(GangRequest(name="ok", num_workers=1)) is not None


def test_gang_idempotent_submit():
    alloc = GangAllocator(two_slice_cluster())
    r = GangRequest(name="j", num_workers=2)
    a1 = alloc.submit(r)
    a2 = alloc.submit(r)
    assert a1.chip_assignment == a2.chip_assignment
    assert alloc.free_chips("v5p-a") == 6


def test_gang_atomic_shrink_feeds_waiter_without_losing_placement():
    """shrink() frees the trailing workers' chips and schedules waiters in
    ONE critical section: the yielding gang keeps its (smaller) placement.
    The release→re-submit alternative opened a window where a pending gang
    larger than the freed amount could take everything and leave the
    yielder queued."""
    alloc = GangAllocator(two_slice_cluster())
    a = alloc.submit(GangRequest(name="a", num_workers=3, chips_per_worker=2))
    assert a is not None and a.slice_name == "v5p-a"       # 6 of 8 chips
    b = alloc.submit(GangRequest(name="b", num_workers=6, chips_per_worker=1))
    assert b is None                                        # needs 6 on one slice
    new = alloc.shrink("a", 1)
    assert new.request.num_workers == 1
    assert new.chip_assignment == {0: a.chip_assignment[0]}  # survivors keep chips
    placed = alloc.allocation("b")
    assert placed is not None and placed.slice_name == "v5p-a"
    assert not set(placed.all_chips) & set(new.all_chips)
    assert alloc.allocation("a") is not None, "yielder displaced by waiter"


def test_gang_shrink_noop_and_bounds():
    alloc = GangAllocator(two_slice_cluster())
    a = alloc.submit(GangRequest(name="a", num_workers=2))
    assert alloc.shrink("a", 2) is a        # not a decrease: unchanged
    assert alloc.shrink("missing", 1) is None
    with pytest.raises(ValueError):
        alloc.shrink("a", 0)


# -- mesh ----------------------------------------------------------------------

def test_mesh_axes_canonical_order():
    assert MESH_AXES == ("dcn", "pipeline", "data", "fsdp", "expert", "seq", "model")


def test_build_mesh_8_devices():
    mesh = build_mesh({"fsdp": 4, "model": 2})
    assert mesh.shape["fsdp"] == 4 and mesh.shape["model"] == 2
    assert mesh.shape["dcn"] == 1
    assert mesh.devices.size == 8


def test_mesh_from_parallelism_spec():
    mesh = mesh_from_parallelism(ParallelismSpec(data=2, seq=4))
    assert mesh.shape["data"] == 2 and mesh.shape["seq"] == 4


def test_mesh_size_mismatch_raises():
    with pytest.raises(ValueError):
        build_mesh({"fsdp": 3})


# -- worker env protocol -------------------------------------------------------

def test_worker_env_roundtrip():
    w = WorkerEnv(
        coordinator_address="127.0.0.1:1234", num_processes=4, process_id=2,
        job="ns/j", replica_index=2, entrypoint="noop",
        config={"steps": 3}, parallelism={"fsdp": 4},
        heartbeat_file="/tmp/hb", workdir="/tmp/wd",
    )
    again = WorkerEnv.from_env(w.to_env())
    assert again == w


# -- process manager -----------------------------------------------------------

def worker_env(tmp_path, name, entrypoint="noop", config=None, nproc=1, pid=0):
    return WorkerEnv(
        coordinator_address=f"127.0.0.1:{free_port()}",
        num_processes=nproc, process_id=pid, job="default/t", replica_index=pid,
        entrypoint=entrypoint, config=config or {}, parallelism={},
        platform="cpu", virtual_devices=1,
        heartbeat_file=str(tmp_path / f"{name}.hb"),
    )


@pytest.mark.slow
def test_procman_lifecycle(tmp_path):
    pm = LocalProcessManager(log_dir=str(tmp_path / "logs"))
    h = pm.launch("w0", worker_env(tmp_path, "w0", "sleep", {"seconds": 30}))
    assert h.pid > 0
    deadline = time.time() + 15
    while h.heartbeat_age() is None and time.time() < deadline:
        time.sleep(0.1)
    assert h.heartbeat_age() is not None and h.heartbeat_age() < 10
    # SIGTERM → retryable exit 143 per the contract
    rc = pm.kill("w0")
    assert rc == 143
    pm.reap("w0")
    assert pm.get("w0") is None


@pytest.mark.slow
def test_procman_exit_codes(tmp_path):
    pm = LocalProcessManager()
    pm.launch("ok", worker_env(tmp_path, "ok", "noop"))
    pm.launch("bad", worker_env(tmp_path, "bad", "fail", {"exit_code": 7}))
    pm.launch("cfg", worker_env(tmp_path, "cfg", "no_such_entrypoint"))
    deadline = time.time() + 60
    while any(pm.poll(n) is None for n in ("ok", "bad", "cfg")) and time.time() < deadline:
        time.sleep(0.2)
    assert pm.poll("ok") == 0
    assert pm.poll("bad") == 7
    assert pm.poll("cfg") == 2  # config error
