"""Model-server protocol surface tests (≈ kserve's FastAPI TestClient server
tests, SURVEY.md §4.4 — here against the real threaded server over a port)."""

import json
import urllib.request

import pytest
import jax

from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine
from kubeflow_tpu.serve.server import ModelServer
from kubeflow_tpu.serve.tokenizer import ByteTokenizer, get_tokenizer


@pytest.fixture(scope="module")
def server():
    cfg = preset("tiny", vocab_size=512)  # roomy enough for byte vocab (259)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    engine = LLMEngine(
        cfg, BatchingSpec(max_batch_size=4, max_seq_len=96,
                          prefill_buckets=[32, 64]),
        params=params)
    srv = ModelServer("demo", engine, port=0)
    srv.start()
    yield srv
    srv.stop()


def _post(url: str, body: dict) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def test_health_and_metadata(server):
    status, body = _get(server.url + "/healthz")
    assert status == 200 and json.loads(body)["status"] == "ok"
    status, body = _get(server.url + "/v2/models/demo")
    meta = json.loads(body)
    assert meta["name"] == "demo"
    assert meta["inputs"][0]["datatype"] == "BYTES"


def test_v1_predict(server):
    out = _post(server.url + "/v1/models/demo:predict",
                {"instances": ["ab", "xyz"], "max_tokens": 4})
    assert len(out["predictions"]) == 2
    assert all(isinstance(p, str) for p in out["predictions"])


def test_v2_infer(server):
    out = _post(server.url + "/v2/models/demo/infer",
                {"inputs": [{"name": "text", "shape": [1],
                             "datatype": "BYTES", "data": ["hello"]}],
                 "max_tokens": 3})
    assert out["model_name"] == "demo"
    assert out["outputs"][0]["shape"] == [1]


def test_openai_completions(server):
    out = _post(server.url + "/v1/completions",
                {"prompt": "hi", "max_tokens": 5, "model": "demo"})
    assert out["object"] == "text_completion"
    assert out["usage"]["completion_tokens"] <= 5
    assert out["choices"][0]["finish_reason"] in ("length", "stop")


def test_openai_chat_completions(server):
    out = _post(server.url + "/v1/chat/completions",
                {"messages": [{"role": "user", "content": "hey"}],
                 "max_tokens": 4})
    assert out["object"] == "chat.completion"
    assert out["choices"][0]["message"]["role"] == "assistant"


def test_streaming_sse(server):
    req = urllib.request.Request(
        server.url + "/v1/completions",
        data=json.dumps({"prompt": "s", "max_tokens": 4,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        assert "text/event-stream" in r.headers["Content-Type"]
        payload = r.read().decode()
    events = [ln[6:] for ln in payload.splitlines() if ln.startswith("data: ")]
    assert events[-1] == "[DONE]"
    assert 1 <= len(events) - 1 <= 4
    assert all("choices" in json.loads(e) for e in events[:-1])


def test_metrics_endpoint(server):
    _post(server.url + "/v1/models/demo:predict",
          {"instances": ["m"], "max_tokens": 2})
    status, text = _get(server.url + "/metrics")
    assert status == 200
    assert "kftpu_serving_requests_total" in text
    assert "kftpu_serving_ttft_p50_ms" in text


def test_bad_request_400(server):
    req = urllib.request.Request(
        server.url + "/v1/models/demo:predict",
        data=json.dumps({"wrong": 1}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        urllib.request.urlopen(req, timeout=30)
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_byte_tokenizer_roundtrip():
    tok = get_tokenizer("byte")
    assert isinstance(tok, ByteTokenizer)
    ids = tok.encode("héllo ✓")
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == "héllo ✓"
