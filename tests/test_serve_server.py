"""Model-server protocol surface tests (≈ kserve's FastAPI TestClient server
tests, SURVEY.md §4.4 — here against the real threaded server over a port)."""

import json
import urllib.request

import pytest
import jax

from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine
from kubeflow_tpu.serve.server import ModelServer
from kubeflow_tpu.serve.tokenizer import ByteTokenizer, get_tokenizer


@pytest.fixture(scope="module")
def server():
    cfg = preset("tiny", vocab_size=512)  # roomy enough for byte vocab (259)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    engine = LLMEngine(
        cfg, BatchingSpec(max_batch_size=4, max_seq_len=96,
                          prefill_buckets=[32, 64]),
        params=params)
    srv = ModelServer("demo", engine, port=0)
    srv.start()
    yield srv
    srv.stop()


def _post(url: str, body: dict) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def test_health_and_metadata(server):
    status, body = _get(server.url + "/healthz")
    assert status == 200 and json.loads(body)["status"] == "ok"
    status, body = _get(server.url + "/v2/models/demo")
    meta = json.loads(body)
    assert meta["name"] == "demo"
    assert meta["inputs"][0]["datatype"] == "BYTES"


def test_v1_predict(server):
    out = _post(server.url + "/v1/models/demo:predict",
                {"instances": ["ab", "xyz"], "max_tokens": 4})
    assert len(out["predictions"]) == 2
    assert all(isinstance(p, str) for p in out["predictions"])


def test_v2_infer(server):
    out = _post(server.url + "/v2/models/demo/infer",
                {"inputs": [{"name": "text", "shape": [1],
                             "datatype": "BYTES", "data": ["hello"]}],
                 "max_tokens": 3})
    assert out["model_name"] == "demo"
    assert out["outputs"][0]["shape"] == [1]


def test_openai_completions(server):
    out = _post(server.url + "/v1/completions",
                {"prompt": "hi", "max_tokens": 5, "model": "demo"})
    assert out["object"] == "text_completion"
    assert out["usage"]["completion_tokens"] <= 5
    assert out["choices"][0]["finish_reason"] in ("length", "stop")


def test_openai_chat_completions(server):
    out = _post(server.url + "/v1/chat/completions",
                {"messages": [{"role": "user", "content": "hey"}],
                 "max_tokens": 4})
    assert out["object"] == "chat.completion"
    assert out["choices"][0]["message"]["role"] == "assistant"


def test_streaming_sse(server):
    req = urllib.request.Request(
        server.url + "/v1/completions",
        data=json.dumps({"prompt": "s", "max_tokens": 4,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        assert "text/event-stream" in r.headers["Content-Type"]
        payload = r.read().decode()
    events = [ln[6:] for ln in payload.splitlines() if ln.startswith("data: ")]
    assert events[-1] == "[DONE]"
    assert 1 <= len(events) - 1 <= 4
    assert all("choices" in json.loads(e) for e in events[:-1])


def test_metrics_endpoint(server):
    _post(server.url + "/v1/models/demo:predict",
          {"instances": ["m"], "max_tokens": 2})
    status, text = _get(server.url + "/metrics")
    assert status == 200
    assert "kftpu_serving_requests_total" in text
    assert "kftpu_serving_ttft_p50_ms" in text
    # Lifecycle/shedding surface (ISSUE 2): depth gauge, shed/reap
    # counters, queue-delay histogram.
    assert "kftpu_serving_queue_depth" in text
    assert "kftpu_serving_requests_shed_total" in text
    assert "kftpu_serving_requests_cancelled_total" in text
    assert "kftpu_serving_queue_delay_seconds_bucket" in text
    assert 'le="+Inf"' in text


def test_expired_deadline_returns_504_and_reaps(server):
    """A request whose budget is already gone must fail explicitly (504,
    finish_reason='deadline' engine-side) — never hang, never 200-empty."""
    req = urllib.request.Request(
        server.url + "/v1/completions",
        data=json.dumps({"prompt": "ab", "max_tokens": 8,
                         "timeout": 0}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        urllib.request.urlopen(req, timeout=30)
        assert False, "expected 504"
    except urllib.error.HTTPError as e:
        assert e.code == 504
        assert "deadline" in json.loads(e.read())["error"]
    assert server.engine.metrics.snapshot()["requests_expired"] >= 1
    # The engine is unharmed: the next request completes normally.
    out = _post(server.url + "/v1/completions",
                {"prompt": "cd", "max_tokens": 3})
    assert out["choices"][0]["finish_reason"] in ("length", "stop")


def test_overload_returns_429_with_retry_after():
    """Bounded admission at the protocol surface: queue full -> immediate
    429 + Retry-After (the engine never sees the shed request)."""
    import threading
    import time as _t

    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(1), cfg)
    engine = LLMEngine(
        cfg, BatchingSpec(max_batch_size=1, max_seq_len=64,
                          prefill_buckets=[32], max_queue=1),
        params=params)
    srv = ModelServer("jam", engine, port=0)
    srv.start()
    try:
        engine.stop()          # freeze the scheduler: submissions pile up
        first = threading.Thread(target=lambda: http(
            srv, "POST", "/v1/completions",
            {"prompt": "xy", "max_tokens": 4, "timeout": 2}))
        first.start()
        deadline = _t.monotonic() + 5.0
        while engine.queue_depth() < 1:
            assert _t.monotonic() < deadline
            _t.sleep(0.01)
        req = urllib.request.Request(
            srv.url + "/v1/completions",
            data=json.dumps({"prompt": "zz", "max_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=30)
            assert False, "expected 429"
        except urllib.error.HTTPError as e:
            assert e.code == 429
            assert int(e.headers["Retry-After"]) >= 1
            assert "queue full" in json.loads(e.read())["error"]
        first.join(timeout=15.0)
        assert not first.is_alive(), "queued request hung"
        assert engine.metrics.snapshot()["requests_shed"] >= 1
    finally:
        srv.stop()


def test_bad_request_400(server):
    req = urllib.request.Request(
        server.url + "/v1/models/demo:predict",
        data=json.dumps({"wrong": 1}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        urllib.request.urlopen(req, timeout=30)
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_byte_tokenizer_roundtrip():
    tok = get_tokenizer("byte")
    assert isinstance(tok, ByteTokenizer)
    ids = tok.encode("héllo ✓")
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == "héllo ✓"


def http(server, method: str, path: str, body: dict | None = None):
    """(status, parsed-json-or-text) without raising on 4xx/5xx."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(server.url + path, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            raw, code, ctype = r.read(), r.status, r.headers.get("Content-Type", "")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")
    return code, (json.loads(raw) if "json" in ctype else raw.decode())


class TestMultiModel:
    """ModelMesh-lite: repository-backed server with LRU load-on-demand and
    the v2 repository API (SURVEY.md §2.3#29)."""

    @pytest.fixture()
    def repo_server(self):
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.serve.repository import ModelRepository

        repo = ModelRepository(max_loaded=1)   # force evictions
        repo.register("alpha", preset("tiny"), batching=BatchingSpec(
            max_batch_size=2, max_seq_len=64, prefill_buckets=[16]))
        repo.register("beta", preset("tiny-gemma"), batching=BatchingSpec(
            max_batch_size=2, max_seq_len=64, prefill_buckets=[16]))
        srv = ModelServer("alpha", repository=repo, port=0)
        srv.start()
        yield srv
        srv.stop()

    @pytest.mark.slow  # tier-1 budget (ISSUE 17): slowest fast tests re-marked
    def test_index_and_lazy_load(self, repo_server):
        code, out = http(repo_server, "GET", "/v2/repository/index")
        assert code == 200
        states = {m["name"]: m["state"] for m in out["models"]}
        assert states == {"alpha": "UNLOADED", "beta": "UNLOADED"}
        # Serving a request loads on demand.
        code, out = http(repo_server, "POST", "/v1/models/alpha:predict",
                         {"instances": ["hi"], "max_tokens": 4})
        assert code == 200 and len(out["predictions"]) == 1
        states = {m["name"]: m["state"]
                  for m in http(repo_server, "GET",
                                "/v2/repository/index")[1]["models"]}
        assert states["alpha"] == "READY"

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_lru_eviction_on_second_model(self, repo_server):
        http(repo_server, "POST", "/v1/models/alpha:predict",
             {"instances": ["hi"], "max_tokens": 4})
        # Serving beta evicts alpha (max_loaded=1)...
        code, out = http(repo_server, "POST", "/v1/models/beta:predict",
                         {"instances": ["yo"], "max_tokens": 4})
        assert code == 200
        states = {m["name"]: m["state"]
                  for m in http(repo_server, "GET",
                                "/v2/repository/index")[1]["models"]}
        assert states == {"alpha": "UNLOADED", "beta": "READY"}
        # ...and alpha reloads transparently on the next request.
        code, _ = http(repo_server, "POST", "/v1/models/alpha:predict",
                       {"instances": ["back"], "max_tokens": 4})
        assert code == 200

    def test_explicit_load_unload(self, repo_server):
        code, out = http(repo_server, "POST",
                         "/v2/repository/models/beta/load", {})
        assert code == 200 and out["state"] == "READY"
        code, out = http(repo_server, "POST",
                         "/v2/repository/models/beta/unload", {})
        assert code == 200 and out["state"] == "UNLOADED"
        assert http(repo_server, "POST",
                    "/v2/repository/models/nope/load", {})[0] == 404

    def test_openai_model_field_routes(self, repo_server):
        code, out = http(repo_server, "POST", "/v1/completions",
                         {"model": "beta", "prompt": "hello",
                          "max_tokens": 4})
        assert code == 200 and out["model"] == "beta"

    def test_unknown_model_404(self, repo_server):
        code, out = http(repo_server, "POST", "/v1/models/ghost:predict",
                         {"instances": ["x"]})
        assert code == 404

    def test_metrics_labeled_per_model(self, repo_server):
        http(repo_server, "POST", "/v1/models/alpha:predict",
             {"instances": ["hi"], "max_tokens": 4})
        code, text = http(repo_server, "GET", "/metrics")
        assert 'kftpu_serving_requests_total{model="alpha"}' in text


def upcase_transformer(text: str, phase: str) -> str:
    """Test transformer: tags the prompt (pre) and uppercases output (post)."""
    return f"[pre]{text}" if phase == "pre" else text.upper()


class TestTransformer:
    def test_pre_and_post_hooks(self):
        cfg = preset("tiny", vocab_size=512)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        engine = LLMEngine(cfg, BatchingSpec(max_batch_size=2, max_seq_len=64,
                                             prefill_buckets=[16]),
                           params=params)
        srv = ModelServer("t", engine, transformer=upcase_transformer, port=0)
        srv.start()
        try:
            code, out = http(srv, "POST", "/v1/models/t:predict",
                             {"instances": ["ab"], "max_tokens": 3})
            assert code == 200
            pred = out["predictions"][0]
            assert pred == pred.upper()     # post hook ran
        finally:
            srv.stop()
