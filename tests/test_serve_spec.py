"""Speculative decoding correctness: greedy spec output must be
TOKEN-IDENTICAL to the non-speculative engine on BOTH KV backends and BOTH
draft sources (acceptance rate only moves throughput, never tokens), KV
rollback after rejections must leave page refcounts balanced, and the
engine must fall back to plain decode whenever greedy verification would
not be exact (sampling traffic)."""

import jax
import pytest

from kubeflow_tpu.core.serving import BatchingSpec, SpeculativeSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams
from kubeflow_tpu.serve.spec_decode import ngram_propose


@pytest.fixture(scope="module")
def cfg():
    return preset("tiny", vocab_size=512)


@pytest.fixture(scope="module")
def params(cfg):
    return init_decoder_params(jax.random.PRNGKey(0), cfg)


PROMPTS = [[5, 17, 3, 99, 42], list(range(1, 50)), [7] * 20,
           [9, 8, 7, 6, 5, 4]]
# A repetitive prompt: the n-gram drafter finds matches immediately, so
# acceptance (and rejection, when the model diverges from the template)
# both exercise for real.
TEMPLATED = [[4, 8, 15, 16, 23, 42] * 6 + [4, 8, 15],
             list(range(10, 26)) * 3 + [10, 11]]


def make_engine(cfg, params, *, spec=None, paged=False, slots=4,
                draft_params=None, decode_steps=4):
    return LLMEngine(cfg, BatchingSpec(
        max_batch_size=slots, max_seq_len=128, prefill_buckets=[16, 64],
        chunked_prefill_tokens=32, paged=paged, page_size=16,
        decode_steps=decode_steps,
        speculative=spec or SpeculativeSpec()), params=params,
        draft_params=draft_params)


def run_all(eng, reqs, max_steps=800):
    for _ in range(max_steps):
        eng.step()
        if all(r.done.is_set() for r in reqs):
            return
    raise AssertionError("requests did not finish")


def gen_all(eng, prompts, max_new=12):
    sp = SamplingParams(max_new_tokens=max_new, temperature=0.0)
    reqs = [eng.submit(list(p), sp) for p in prompts]
    run_all(eng, reqs)
    return [list(r.output_tokens) for r in reqs]


DRAFT = SpeculativeSpec(mode="draft_model", k=4,
                        draft={"preset": "tiny",
                               "overrides": {"vocab_size": 512,
                                             "n_layers": 1}})


class TestNgramPropose:
    def test_matches_most_recent_occurrence(self):
        ctx = [1, 2, 3, 9, 9, 1, 2, 3, 7, 7, 1, 2, 3]
        # suffix [1,2,3] last occurred at index 5 -> propose [7, 7, 1, 2]
        assert ngram_propose(ctx, 4, 3, 1) == [7, 7, 1, 2]

    def test_prefers_longer_ngrams(self):
        ctx = [5, 1, 2, 8, 0, 1, 2, 8]       # 3-gram [1,2,8] -> [0, 1, 2, 8]
        assert ngram_propose(ctx, 4, 3, 1) == [0, 1, 2, 8]

    def test_no_match_returns_empty(self):
        assert ngram_propose([1, 2, 3, 4, 5], 4, 3, 1) == []

    def test_truncates_to_k(self):
        ctx = [1, 2, 3, 4, 5, 6, 1, 2]
        assert ngram_propose(ctx, 2, 2, 1) == [3, 4]


class TestSpecExactMatch:
    """The acceptance-criteria core: every (draft source × KV backend)
    combination reproduces the plain greedy engine token-for-token."""

    @pytest.fixture(scope="class")
    def want(self, cfg, params):
        return gen_all(make_engine(cfg, params), PROMPTS)

    @pytest.fixture(scope="class")
    def want_templated(self, cfg, params):
        return gen_all(make_engine(cfg, params), TEMPLATED, max_new=20)

    def test_ngram_dense(self, cfg, params, want):
        eng = make_engine(cfg, params, spec=SpeculativeSpec(mode="ngram", k=4))
        assert gen_all(eng, PROMPTS) == want
        snap = eng.metrics.snapshot()
        assert snap["spec_rounds"] > 0
        assert "spec_acceptance_rate" in snap
        assert snap["spec_tokens_per_step"] >= 1.0

    def test_ngram_paged(self, cfg, params, want):
        eng = make_engine(cfg, params, paged=True,
                          spec=SpeculativeSpec(mode="ngram", k=4))
        assert gen_all(eng, PROMPTS) == want

    @pytest.mark.slow  # tier-1 budget: draft_model_paged keeps the lane, ~9s
    def test_draft_model_dense(self, cfg, params, want):
        eng = make_engine(cfg, params, spec=DRAFT)
        assert gen_all(eng, PROMPTS) == want
        assert eng.metrics.snapshot()["spec_rounds"] > 0

    @pytest.mark.slow  # tier-1 budget (ISSUE 20): ~8s; draft-model exact
    # match stays fast via test_draft_model_dense
    def test_draft_model_paged(self, cfg, params, want):
        eng = make_engine(cfg, params, paged=True, spec=DRAFT)
        assert gen_all(eng, PROMPTS) == want

    def test_ngram_templated_prompts_accept_and_match(self, cfg, params,
                                                      want_templated):
        """Templated prompts make the drafter propose every round; outputs
        still match exactly whether drafts are accepted or rejected."""
        eng = make_engine(cfg, params,
                          spec=SpeculativeSpec(mode="ngram", k=4))
        assert gen_all(eng, TEMPLATED, max_new=20) == want_templated
        assert eng.metrics.spec_drafted > 0

    def test_self_draft_accepts_almost_everything(self, cfg, params, want):
        """Draft == target: the argmax chains coincide, so acceptance is
        near-total and rounds emit multiple tokens."""
        spec = SpeculativeSpec(mode="draft_model", k=4,
                               draft={"preset": "tiny",
                                      "overrides": {"vocab_size": 512}})
        eng = make_engine(cfg, params, spec=spec, draft_params=params)
        assert gen_all(eng, PROMPTS) == want
        snap = eng.metrics.snapshot()
        assert snap["spec_acceptance_rate"] > 0.5
        assert snap["spec_tokens_per_step"] > 1.5

    def test_longer_k_still_exact(self, cfg, params, want):
        eng = make_engine(cfg, params, paged=True,
                          spec=SpeculativeSpec(mode="ngram", k=8))
        assert gen_all(eng, PROMPTS) == want

    @pytest.mark.slow  # tier-1 budget: three engines for one stop probe, ~9s
    def test_stop_token_inside_accepted_run(self, cfg, params):
        """A stop token appearing mid-round (inside the accepted prefix or
        as the bonus token) must truncate the emission exactly where the
        plain engine stops."""
        plain = make_engine(cfg, params)
        probe = gen_all(plain, [PROMPTS[0]], max_new=12)[0]
        stop = probe[5]
        sp = SamplingParams(max_new_tokens=50, stop_token=stop)
        weng = make_engine(cfg, params)
        want_req = weng.submit(list(PROMPTS[0]), sp)
        run_all(weng, [want_req])
        eng = make_engine(cfg, params, spec=SpeculativeSpec(mode="ngram", k=4))
        req = eng.submit(list(PROMPTS[0]), sp)
        run_all(eng, [req])
        assert req.output_tokens == want_req.output_tokens
        assert req.finish_reason == want_req.finish_reason

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_budget_exact_mid_round(self, cfg, params):
        """max_new_tokens falling inside a round's emission truncates it
        exactly (never over-generates)."""
        for n in (1, 3, 7):
            eng = make_engine(cfg, params, paged=True,
                              spec=SpeculativeSpec(mode="ngram", k=4))
            out = gen_all(eng, [TEMPLATED[0]], max_new=n)
            assert len(out[0]) == n

    @pytest.mark.slow  # tier-1 budget (ISSUE 20): ~10s; the fallback
    # branch itself is cheap — the cost is the sampled decode
    def test_sampling_traffic_falls_back_to_plain(self, cfg, params):
        eng = make_engine(cfg, params, spec=SpeculativeSpec(mode="ngram", k=4))
        sp = SamplingParams(max_new_tokens=6, temperature=1.2, top_k=20)
        req = eng.submit(list(PROMPTS[0]), sp)
        run_all(eng, [req])
        assert len(req.output_tokens) == 6
        assert "spec_rounds" not in eng.metrics.snapshot()


class TestPagedRollback:
    """Rejection rollback: the page table truncates to the accepted length
    and the pool's refcount accounting balances — no leak, no double free."""

    def _assert_balanced(self, eng):
        alloc = eng._allocator
        held = sum(len(p) for p in eng._slot_pages)
        # After all requests finish, no slot holds pages and every ref is 0
        # (prefix-cached pages linger at ref 0 in the reclaimable map).
        if all(s is None for s in eng.slots):
            assert held == 0
            assert alloc.in_use() == 0
            assert int(alloc._ref.sum()) == 0
            assert alloc.available() == alloc.num_pages

    @pytest.mark.slow   # ~7s: refcount balance also pinned by the
    # sanitizer + chaos suites
    def test_rejection_heavy_refcounts_balance(self, cfg, params):
        """A deliberately-bad draft model rejects nearly every round —
        maximal rollback traffic — and the pool must come back whole."""
        want = gen_all(make_engine(cfg, params), PROMPTS)
        eng = make_engine(cfg, params, paged=True, spec=DRAFT)
        assert gen_all(eng, PROMPTS) == want
        self._assert_balanced(eng)

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_rollback_truncates_table(self, cfg, params):
        """Mid-flight: after any spec round, a slot's page list covers
        exactly ceil(length/page) pages — rejected-tail pages were freed."""
        eng = make_engine(cfg, params, paged=True,
                          spec=SpeculativeSpec(mode="ngram", k=8))
        sp = SamplingParams(max_new_tokens=40, temperature=0.0)
        req = eng.submit(list(TEMPLATED[0]), sp)
        checked = 0
        for _ in range(400):
            eng.step()
            for i, s in enumerate(eng.slots):
                if s is None:
                    continue
                have = len(eng._slot_pages[i])
                need = -(-s.length // eng.page_size)
                assert need <= have <= need + 2, (have, need)
                checked += 1
            if req.done.is_set():
                break
        assert req.done.is_set() and checked > 0
        self._assert_balanced(eng)

    @pytest.mark.slow  # tier-1 budget: 48-token double prefill, ~14s
    def test_prefix_cache_pages_survive_rollback(self, cfg, params):
        """Rollback never frees registered prompt pages out from under the
        prefix cache: a second identical prompt still hits."""
        eng = make_engine(cfg, params, paged=True,
                          spec=SpeculativeSpec(mode="ngram", k=4))
        sp = SamplingParams(max_new_tokens=10, temperature=0.0)
        prompt = list(range(1, 49))
        r1 = eng.submit(prompt, sp)
        run_all(eng, [r1])
        r2 = eng.submit(prompt, sp)
        run_all(eng, [r2])
        assert eng._allocator.stats["prefix_hits"] >= 1
        assert list(r1.output_tokens) == list(r2.output_tokens)
        self._assert_balanced(eng)

    @pytest.mark.parametrize("spec", [
        SpeculativeSpec(mode="ngram", k=4), DRAFT], ids=["ngram", "draft"])
    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_pool_pressure_with_spec_still_exact(self, cfg, params, spec):
        """A pool too small for all slots: recompute preemption + spec
        coexist (including the draft-cache reset on re-admission) and
        outputs stay exact."""
        sp = SamplingParams(max_new_tokens=24, temperature=0.0)
        prompts = [list(range(1, 30)), list(range(2, 60)),
                   list(range(3, 40))]
        want_eng = make_engine(cfg, params)
        wreqs = [want_eng.submit(list(p), sp) for p in prompts]
        run_all(want_eng, wreqs)
        eng = LLMEngine(cfg, BatchingSpec(
            max_batch_size=4, max_seq_len=128, paged=True, page_size=16,
            max_pages=8, enable_prefix_caching=False,
            chunked_prefill_tokens=16,
            speculative=spec), params=params)
        reqs = [eng.submit(list(p), sp) for p in prompts]
        run_all(eng, reqs, max_steps=2000)
        assert [list(r.output_tokens) for r in reqs] == \
            [list(r.output_tokens) for r in wreqs]
        self._assert_balanced(eng)


class TestDraftModelConfig:
    def test_vocab_mismatch_rejected(self, cfg, params):
        with pytest.raises(ValueError, match="vocab"):
            make_engine(cfg, params, spec=SpeculativeSpec(
                mode="draft_model", k=4,
                draft={"preset": "tiny"}))     # vocab 256 != 512

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="speculative mode"):
            SpeculativeSpec(mode="medusa")

    def test_spec_roundtrips_through_batching_config(self, cfg, params):
        """The ISVC controller ships BatchingSpec.model_dump() to replicas;
        the nested speculative spec must survive the round trip."""
        b = BatchingSpec(max_batch_size=2, max_seq_len=64,
                         prefill_buckets=[16],
                         speculative=SpeculativeSpec(mode="ngram", k=6))
        again = BatchingSpec(**b.model_dump())
        assert again.speculative.mode == "ngram"
        assert again.speculative.k == 6
        eng = LLMEngine(cfg, again, params=params)
        assert eng.spec_mode == "ngram" and eng.spec_k == 6


class TestFlushPrefillRequeue:
    """Regression (ADVICE r5, engine._flush_prefills): a mid-flush dispatch
    failure must not silently drop the requests already popped off the
    backlog — the failing group fails loudly, the rest requeue and run."""

    def test_failed_flush_requeues_rest(self, cfg, params):
        eng = LLMEngine(cfg, BatchingSpec(
            max_batch_size=4, max_seq_len=64, prefill_buckets=[16],
            prefill_batch_max=1), params=params)
        real_prefill = eng._prefill
        calls = {"n": 0}

        def boom(*a, **k):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected prefill OOM")
            return real_prefill(*a, **k)

        eng._prefill = boom
        sp = SamplingParams(max_new_tokens=4, temperature=0.0)
        reqs = [eng.submit([i + 1, i + 2, i + 3], sp) for i in range(3)]
        with pytest.raises(RuntimeError, match="injected"):
            eng.step()
        # First request failed loudly; the others went back to the backlog.
        assert reqs[0].done.is_set()
        assert reqs[0].finish_reason == "error"
        assert [r.id for r in eng._backlog] == [reqs[1].id, reqs[2].id]
        run_all(eng, reqs[1:])
        want = gen_all(LLMEngine(cfg, BatchingSpec(
            max_batch_size=4, max_seq_len=64, prefill_buckets=[16]),
            params=params), [[2, 3, 4], [3, 4, 5]], max_new=4)
        assert [list(r.output_tokens) for r in reqs[1:]] == want
