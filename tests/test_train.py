"""Trainer tests: loss decreases, determinism/fast-forward, checkpoint
resume, throughput metrics — on the 8-device virtual mesh."""

import json
import os

import jax
import numpy as np
import pytest

from kubeflow_tpu.runtime.mesh import build_mesh
from kubeflow_tpu.train.data import DataConfig, SyntheticLM
from kubeflow_tpu.train.trainer import Trainer, TrainerConfig


def make_trainer(tmp_path, steps=30, ckpt=False, **kw):
    cfg = TrainerConfig(
        model="tiny",
        model_overrides={"n_layers": 2, "hidden": 64},
        # total_steps pinned so the LR schedule is identical across trainers
        # with different run lengths (resume tests compare them bitwise).
        optimizer={"learning_rate": 3e-3, "warmup_steps": 5, "total_steps": 100},
        data={"global_batch": 8, "seq_len": 32, "vocab_size": 256},
        steps=steps,
        log_every=10,
        checkpoint_dir=str(tmp_path / "ckpt") if ckpt else None,
        checkpoint_every=10,
        **kw,
    )
    mesh = build_mesh({"fsdp": 8})
    return Trainer(cfg, mesh, metrics_path=str(tmp_path / "m.jsonl"))


def test_synthetic_data_deterministic_fast_forward():
    cfg = DataConfig(global_batch=4, seq_len=16, vocab_size=64, seed=3)
    a = SyntheticLM(cfg)
    b = SyntheticLM(cfg)
    np.testing.assert_array_equal(a.batch_at(7), b.batch_at(7))
    assert not np.array_equal(a.batch_at(7), a.batch_at(8))
    # sharding partitions the batch deterministically
    s0 = SyntheticLM(cfg, shard=0, num_shards=2)
    s1 = SyntheticLM(cfg, shard=1, num_shards=2)
    assert s0.local_batch == 2
    assert not np.array_equal(s0.batch_at(0), s1.batch_at(0))


@pytest.mark.slow
def test_loss_decreases(tmp_path):
    tr = make_trainer(tmp_path, steps=40)
    first = None

    def on_step(step, metrics):
        nonlocal first
        if step == 10 and metrics:
            first = metrics["loss"]

    last = tr.run(on_step=on_step)
    assert first is not None
    assert last["loss"] < first * 0.9, (first, last["loss"])
    assert last["tokens_per_sec_per_chip"] > 0
    assert last["step_time_ms"] > 0
    # metrics jsonl written
    lines = [json.loads(l) for l in open(tmp_path / "m.jsonl")]
    assert lines[-1]["step"] == 40


@pytest.mark.slow
def test_checkpoint_resume_exact(tmp_path):
    # Train 20 steps with checkpoints every 10.
    tr1 = make_trainer(tmp_path, steps=20, ckpt=True)
    m1 = tr1.run()
    # Fresh trainer resumes from step 20 checkpoint and continues to 25.
    tr2 = make_trainer(tmp_path, steps=25, ckpt=True)
    start = tr2.try_resume()
    assert start == 20
    # Run the remaining steps; state must continue (loss finite, step advances).
    m2 = tr2.run()
    assert int(jax.device_get(tr2.task.state["step"])) == 25

    # Bitwise check: a third trainer restoring step-20 must produce identical
    # step-21 state to a straight 21-step run (determinism of resume).
    tr3 = make_trainer(tmp_path, steps=21, ckpt=True)
    # force restore of step 20 (latest is now 25)
    restored = tr3.ckpt.restore(tr3._abstract_state(), step=20)
    tr3.task.state = restored
    batch = tr3.make_global_batch(tr3.data.batch_at(20))
    s21, _ = tr3.task.step_fn(tr3.task.state, batch)

    tr4 = make_trainer(tmp_path, steps=21)
    for step in range(21):
        b = tr4.make_global_batch(tr4.data.batch_at(step))
        tr4.task.state, _ = tr4.task.step_fn(tr4.task.state, b)
    p_a = jax.device_get(s21["params"]["final_norm"])
    p_b = jax.device_get(tr4.task.state["params"]["final_norm"])
    np.testing.assert_allclose(p_a, p_b, atol=1e-6)
