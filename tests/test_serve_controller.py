"""ISVC controller semantics, envtest-style: no processes — a fake probe
plays the replicas' health/metrics endpoints (SURVEY.md §4.2 pattern)."""

import pytest

from kubeflow_tpu.core.jobs import Worker, WorkerPhase
from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.core.serving import (
    InferenceService, InferenceServiceSpec, ModelSpec, PredictorSpec,
)
from kubeflow_tpu.operator.control_plane import ControlPlane, ControlPlaneConfig


class FakeProbe:
    """url -> {"ready", "in_flight"}; tests mutate `ready` and `load`.
    ``signals[url]`` merges extra scrape keys (the SLO autoscaler's
    latency p95s); ``fail`` makes individual urls unprobeable (the
    stale/missing-signal condition)."""

    def __init__(self):
        self.ready = True
        self.load = {}          # url -> in_flight
        self.signals = {}       # url -> extra signal dict
        self.fail = set()       # urls whose probe fails outright

    def __call__(self, url):
        if not self.ready or url in self.fail:
            return None
        return {"ready": True, "in_flight": self.load.get(url, 0),
                **self.signals.get(url, {})}


@pytest.fixture()
def cp(tmp_path):
    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path), launch_processes=False,
        metrics_sync_interval=None))
    plane.probe = FakeProbe()
    plane.isvc_reconciler.probe = plane.probe
    yield plane
    plane.isvc_reconciler.shutdown()


def mkisvc(name="svc", min_replicas=1, max_replicas=1, scale_target=4,
           drain_deadline_s=30.0):
    return InferenceService(
        metadata=ObjectMeta(name=name),
        spec=InferenceServiceSpec(predictor=PredictorSpec(
            model=ModelSpec(config={"preset": "tiny"}),
            min_replicas=min_replicas, max_replicas=max_replicas,
            scale_target=scale_target, drain_deadline_s=drain_deadline_s)))


def replicas(cp, name="svc"):
    ws = cp.store.list(Worker, label_selector={
        "serving.tpu.kubeflow.dev/service": name})
    return sorted(ws, key=lambda w: int(
        w.metadata.labels["serving.tpu.kubeflow.dev/replica"]))


def mark_running(cp, ws):
    for w in ws:
        w = cp.store.get(Worker, w.metadata.name, w.metadata.namespace)
        w.status.phase = WorkerPhase.RUNNING
        cp.store.update_status(w)


def get_isvc(cp, name="svc"):
    return cp.store.get(InferenceService, name)


def test_creates_replicas_and_reports_ready(cp):
    cp.submit(mkisvc())
    cp.step()
    ws = replicas(cp)
    assert len(ws) == 1
    w = ws[0]
    assert w.spec.template.entrypoint == "model_server"
    assert w.spec.template.config["port"] > 0
    assert w.spec.template.config["model"] == {"preset": "tiny"}
    isvc = get_isvc(cp)
    assert isvc.status.ready_replicas == 0     # not Running yet
    mark_running(cp, ws)
    cp.step()
    isvc = get_isvc(cp)
    assert isvc.status.ready_replicas == 1
    assert isvc.status.has_condition("Ready")
    assert isvc.status.url.startswith("http://127.0.0.1:")


def test_unready_probe_blocks_ready_condition(cp):
    cp.submit(mkisvc())
    cp.step()
    mark_running(cp, replicas(cp))
    cp.probe.ready = False
    cp.step()
    isvc = get_isvc(cp)
    assert isvc.status.ready_replicas == 0
    assert isvc.status.has_condition("Ready", status=False)


def test_crashed_replica_is_replaced(cp):
    cp.submit(mkisvc())
    cp.step()
    w = replicas(cp)[0]
    old_uid = w.metadata.uid
    w = cp.store.get(Worker, w.metadata.name)
    w.status.phase = WorkerPhase.FAILED
    w.status.exit_code = 1
    cp.store.update_status(w)
    cp.step()
    ws = replicas(cp)
    assert len(ws) == 1
    assert ws[0].metadata.uid != old_uid


def test_scale_up_on_concurrency(cp):
    # Single reconciles (not cp.step(), which pumps several rounds): the
    # autoscaler moves one replica per reconcile.
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc(min_replicas=1, max_replicas=3, scale_target=2))
    recon()
    mark_running(cp, replicas(cp))
    # Load the single replica beyond target → scale to 2.
    url = f"http://127.0.0.1:{replicas(cp)[0].spec.template.config['port']}"
    cp.probe.load[url] = 5
    recon()
    assert get_isvc(cp).status.desired_replicas == 2
    # Load drops; new replica joins; cooldown prevents an instant scale-down.
    cp.probe.load[url] = 0
    recon()
    ws = replicas(cp)
    assert len(ws) == 2
    mark_running(cp, ws)
    recon()
    assert get_isvc(cp).status.desired_replicas == 2
    assert get_isvc(cp).status.ready_replicas == 2


def test_deletion_cleans_replicas(cp):
    cp.submit(mkisvc())
    cp.step()
    assert replicas(cp)
    cp.store.delete(InferenceService, "svc")
    cp.step()
    assert replicas(cp) == []


# -- scale-to-zero + activation (Knative serverless analog) -------------------

def _backdate(cp, key="default/svc", by=999.0):
    import time as _t
    cp.isvc_reconciler._last_scale[key] = _t.monotonic() - by


def test_scales_to_zero_when_idle(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc(min_replicas=0, max_replicas=2))
    recon()
    mark_running(cp, replicas(cp))
    recon()
    assert get_isvc(cp).status.ready_replicas == 1
    _backdate(cp)          # idle past the cooldown
    recon()
    isvc = get_isvc(cp)
    assert isvc.status.desired_replicas == 0
    recon()
    assert replicas(cp) == []
    isvc = get_isvc(cp)
    assert isvc.status.has_condition("Ready", status=False)
    assert isvc.status.url    # the routed URL survives at zero


def test_busy_service_never_drops_last_replica(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc(min_replicas=0, max_replicas=2))
    recon()
    mark_running(cp, replicas(cp))
    url = f"http://127.0.0.1:{replicas(cp)[0].spec.template.config['port']}"
    cp.probe.load[url] = 1   # in flight < target/2 but nonzero
    _backdate(cp)
    recon()
    assert get_isvc(cp).status.desired_replicas == 1


def test_cold_start_on_queued_request(cp):
    import threading

    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc(min_replicas=0, max_replicas=1))
    recon()
    mark_running(cp, replicas(cp))
    recon()
    _backdate(cp)
    recon()
    recon()
    assert replicas(cp) == []

    # A request arrives at the router: it must park, not 503.
    router = cp.isvc_reconciler._routers["default/svc"]
    got = {}

    def ask():
        got["backend"] = router.pick_or_wait(timeout=30.0)

    t = threading.Thread(target=ask)
    t.start()
    deadline = __import__("time").monotonic() + 5.0
    while router.pending == 0:
        assert __import__("time").monotonic() < deadline
    recon()                   # sees pending>0 → ColdStart 0→1
    ws = replicas(cp)
    assert len(ws) == 1
    assert get_isvc(cp).status.desired_replicas == 1
    mark_running(cp, ws)
    recon()                   # replica ready → set_backends wakes the queue
    t.join(timeout=10.0)
    assert got["backend"] is not None
    events = [e.reason for e in cp.recorder.for_object(get_isvc(cp))]
    assert "ColdStart" in events


def test_cold_started_replica_survives_slow_cold_start(cp):
    """Regression (round-4 red test): a cold start slower than the idle
    cooldown must not get the replica culled the moment it answers the
    parked request. The 0→1 scale event is ancient by the time the replica
    is up (spawn + model init + compile outlasted the cooldown), so the
    idle clock must count from the request's *completion*, not the scale
    event."""
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc(min_replicas=0, max_replicas=1))
    recon()
    mark_running(cp, replicas(cp))
    recon()
    _backdate(cp)    # the 0→1 scale event happened long before readiness
    router = cp.isvc_reconciler._routers["default/svc"]
    router.note_activity()   # the parked request just completed
    recon()
    isvc = get_isvc(cp)
    assert isvc.status.desired_replicas == 1, \
        "replica culled right after answering its cold-start request"
    assert replicas(cp)


def test_idle_clock_counts_from_traffic_not_scale_events(cp):
    """Requests arriving at ~cooldown cadence must not re-cold-start each
    time; only a full cooldown of real *traffic* silence scales to zero."""
    import time as _t

    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc(min_replicas=0, max_replicas=1))
    recon()
    mark_running(cp, replicas(cp))
    recon()
    key = "default/svc"
    for _ in range(3):
        # A request completed more recently than the cooldown (scale
        # events are ancient) → the replica survives.
        _backdate(cp)
        cp.isvc_reconciler._last_active[key] = _t.monotonic() - 8.0
        recon()
        assert get_isvc(cp).status.desired_replicas == 1
    # Traffic silence past the cooldown → now it scales to zero.
    cp.isvc_reconciler._last_active[key] = _t.monotonic() - 999.0
    recon()
    assert get_isvc(cp).status.desired_replicas == 0


def test_trickle_traffic_does_not_block_consolidation(cp):
    """N→N-1 scale-down stays CONCURRENCY-driven: a 2-replica service with
    steady low traffic (activity every pass, concurrency < target/2) must
    still consolidate after the cooldown — only the 1→0 decision waits for
    traffic silence."""
    import time as _t

    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc(min_replicas=1, max_replicas=3, scale_target=4))
    recon()
    mark_running(cp, replicas(cp))
    recon()
    isvc = get_isvc(cp)
    isvc.status.desired_replicas = 2
    cp.store.update_status(isvc)
    recon()
    mark_running(cp, replicas(cp))
    recon()
    assert get_isvc(cp).status.ready_replicas == 2
    key = "default/svc"
    _backdate(cp)                                         # cooldown elapsed
    cp.isvc_reconciler._last_active[key] = _t.monotonic()  # trickle traffic
    recon()
    assert get_isvc(cp).status.desired_replicas == 1, \
        "trickle traffic pinned an over-provisioned replica"


# -- canary rollout (generation traffic split) --------------------------------

def test_canary_split_and_promotion(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc(min_replicas=2, max_replicas=2))
    recon()
    mark_running(cp, replicas(cp))
    recon()
    isvc = get_isvc(cp)
    assert isvc.status.ready_replicas == 2
    gen1 = isvc.metadata.generation

    # Rollout: new model config at 50% canary.
    isvc.spec.predictor.model.config = {"preset": "tiny-gemma"}
    isvc.spec.predictor.canary_traffic_percent = 50
    cp.store.update(isvc)
    recon()
    ws = replicas(cp)
    gens = sorted({int(w.metadata.labels[
        "serving.tpu.kubeflow.dev/generation"]) for w in ws})
    assert len(gens) == 2 and gens[0] == gen1
    # Previous generation keeps its 2; canary gets round(2*0.5)=1.
    assert len(ws) == 3
    mark_running(cp, ws)
    recon()
    isvc = get_isvc(cp)
    assert isvc.status.traffic == {"latest": 50, "previous": 50}
    router = cp.isvc_reconciler._routers["default/svc"]
    assert len(router._groups.get("latest", [])) == 1
    assert len(router._groups.get("previous", [])) == 2

    # Promote: clear the canary percent → new generation takes 100%, old
    # replicas torn down once the promoted generation is ready.
    isvc.spec.predictor.canary_traffic_percent = None
    cp.store.update(isvc)
    recon()
    mark_running(cp, replicas(cp))
    recon()
    recon()
    ws = replicas(cp)
    gens = {int(w.metadata.labels["serving.tpu.kubeflow.dev/generation"])
            for w in ws}
    assert len(gens) == 1 and gens != {gen1}
    assert len(ws) == 2
    assert get_isvc(cp).status.traffic == {"latest": 100}


def test_canary_converges_previous_generation(cp):
    """A crashed previous-generation replica is RECREATED while the canary
    is active — a long-lived canary must not bleed stable-gen capacity
    (its group still claims 100-p percent of traffic)."""
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc(min_replicas=2, max_replicas=2))
    recon()
    mark_running(cp, replicas(cp))
    recon()
    isvc = get_isvc(cp)
    gen1 = isvc.metadata.generation
    isvc.spec.predictor.model.config = {"preset": "tiny-gemma"}
    isvc.spec.predictor.canary_traffic_percent = 50
    cp.store.update(isvc)
    recon()
    ws = replicas(cp)
    assert len(ws) == 3   # 2 previous + 1 canary
    mark_running(cp, ws)
    recon()
    # Crash one previous-generation replica.
    from kubeflow_tpu.core.jobs import WorkerPhase
    prev = [w for w in replicas(cp)
            if int(w.metadata.labels[
                "serving.tpu.kubeflow.dev/generation"]) == gen1]
    assert len(prev) == 2
    crashed = prev[0]
    crashed.status.phase = WorkerPhase.FAILED
    crashed.status.exit_code = 1
    cp.store.update_status(crashed)
    recon()   # deletes the crashed replica, recreates its index
    recon()
    prev_after = [w for w in replicas(cp)
                  if int(w.metadata.labels[
                      "serving.tpu.kubeflow.dev/generation"]) == gen1]
    assert len(prev_after) == 2, "crashed prev-gen replica must be recreated"
    assert len(replicas(cp)) == 3
    # The replacement must run the STABLE generation's model (cloned from a
    # surviving sibling), not the canary spec the isvc now holds.
    for w in prev_after:
        assert w.spec.template.config["model"] == {"preset": "tiny"}


def test_canary_not_ready_keeps_previous_serving(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc())
    recon()
    mark_running(cp, replicas(cp))
    recon()
    isvc = get_isvc(cp)
    isvc.spec.predictor.canary_traffic_percent = 30
    isvc.spec.predictor.model.config = {"preset": "tiny-gemma"}
    cp.store.update(isvc)
    recon()   # canary replica created but not Running
    router = cp.isvc_reconciler._routers["default/svc"]
    # Traffic still flows: previous group holds the only ready replica.
    assert router._groups.get("previous")
    assert router.pick() is not None


def test_scale_to_zero_suspends_canary_generations(cp):
    """A scaled-to-zero service must not keep previous-generation canary
    replicas running (regression: old generations leaked at zero)."""
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc(min_replicas=0, max_replicas=2))
    recon()
    mark_running(cp, replicas(cp))
    recon()
    isvc = get_isvc(cp)
    isvc.spec.predictor.canary_traffic_percent = 50
    isvc.spec.predictor.model.config = {"preset": "tiny-gemma"}
    cp.store.update(isvc)
    recon()
    mark_running(cp, replicas(cp))
    recon()
    assert len(replicas(cp)) == 2            # previous + canary
    _backdate(cp)
    recon()                                  # autoscaler -> 0
    recon()                                  # converge: everything gone
    recon()
    assert replicas(cp) == []


# -- graceful drain (scale-down/rollout retire path) --------------------------

def _force_two_replicas(cp, recon, **mk_kw):
    cp.submit(mkisvc(min_replicas=1, max_replicas=2, **mk_kw))
    recon()
    mark_running(cp, replicas(cp))
    isvc = get_isvc(cp)
    isvc.status.desired_replicas = 2
    cp.store.update_status(isvc)
    recon()
    mark_running(cp, replicas(cp))
    recon()
    assert get_isvc(cp).status.ready_replicas == 2


def test_scale_down_drains_busy_replica_before_delete(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    _force_two_replicas(cp, recon)
    ws = replicas(cp)
    url1 = f"http://127.0.0.1:{ws[1].spec.template.config['port']}"
    cp.probe.load[url1] = 3        # replica 1 has requests in flight
    isvc = get_isvc(cp)
    isvc.status.desired_replicas = 1
    cp.store.update_status(isvc)
    recon()
    # Still two workers: the trimmed replica is draining, not deleted.
    assert len(replicas(cp)) == 2
    events = [e.reason for e in cp.recorder.for_object(get_isvc(cp))]
    assert "Draining" in events
    recon()                        # still busy -> still draining
    assert len(replicas(cp)) == 2
    cp.probe.load[url1] = 0        # in-flight work finished
    recon()
    assert len(replicas(cp)) == 1, "idle draining replica must be deleted"


def test_drain_hard_deadline_forces_delete(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    _force_two_replicas(cp, recon, drain_deadline_s=0.0)
    ws = replicas(cp)
    url1 = f"http://127.0.0.1:{ws[1].spec.template.config['port']}"
    cp.probe.load[url1] = 5        # busy forever
    isvc = get_isvc(cp)
    isvc.status.desired_replicas = 1
    cp.store.update_status(isvc)
    recon()
    # Deadline 0: the drain window is already over — delete despite load.
    assert len(replicas(cp)) == 1


def test_idle_replica_scale_down_deletes_immediately(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    _force_two_replicas(cp, recon)
    isvc = get_isvc(cp)
    isvc.status.desired_replicas = 1
    cp.store.update_status(isvc)
    recon()                        # probe reports idle -> no drain wait
    assert len(replicas(cp)) == 1


def test_router_stop_releases_parked_requests(cp):
    import threading
    import time as _t
    from kubeflow_tpu.serve.router import Router

    r = Router()
    r.start()
    got = {}
    t = threading.Thread(
        target=lambda: got.update(x=r.pick_or_wait(timeout=60.0), done=True))
    t.start()
    while r.pending == 0:
        _t.sleep(0.01)
    start = _t.monotonic()
    r.stop()
    t.join(timeout=5.0)
    assert got.get("done") and got["x"] is None
    assert _t.monotonic() - start < 5.0      # fail fast, not queue_timeout


# -- SLO-driven autoscaler (ISSUE 6: the signal-driven closed loop) -----------

def mkisvc_slo(name="svc", min_replicas=1, max_replicas=3, *,
               target_ttft_ms=100.0, cooldown_s=10.0, **slo_kw):
    from kubeflow_tpu.core.serving import SLOPolicy

    return InferenceService(
        metadata=ObjectMeta(name=name),
        spec=InferenceServiceSpec(predictor=PredictorSpec(
            model=ModelSpec(config={"preset": "tiny"}),
            min_replicas=min_replicas, max_replicas=max_replicas,
            slo=SLOPolicy(target_ttft_ms=target_ttft_ms,
                          cooldown_s=cooldown_s, **slo_kw))))


def _urls(cp, name="svc"):
    return [f"http://127.0.0.1:{w.spec.template.config['port']}"
            for w in replicas(cp, name)]


def test_slo_scale_up_on_ttft_signal(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc_slo())
    recon()
    mark_running(cp, replicas(cp))
    url, = _urls(cp)
    cp.probe.signals[url] = {"ttft_p95_ms": 300.0}    # 3x over target
    _backdate(cp)
    recon()
    assert get_isvc(cp).status.desired_replicas == 2
    events = [e.reason for e in cp.recorder.for_object(get_isvc(cp))]
    assert "ScaledUp" in events


def test_slo_per_class_weights_drive_the_decision(cp):
    """A screaming batch p95 with near-zero weight must not buy replicas;
    the same p95 on interactive must."""
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc_slo(class_weights={"interactive": 1.0, "batch": 0.0}))
    recon()
    mark_running(cp, replicas(cp))
    url, = _urls(cp)
    cp.probe.signals[url] = {
        "qos_ttft_p95_ms": {"batch": 5000.0, "interactive": 50.0}}
    _backdate(cp)
    recon()
    assert get_isvc(cp).status.desired_replicas == 1, \
        "zero-weight batch latency bought a replica"
    cp.probe.signals[url] = {
        "qos_ttft_p95_ms": {"batch": 5.0, "interactive": 400.0}}
    _backdate(cp)
    recon()
    assert get_isvc(cp).status.desired_replicas == 2


def test_slo_hold_inside_hysteresis_band_no_oscillation(cp):
    """A signal inside (scale_down_ratio, scale_up_ratio) must never move
    the count — repeated reconciles with elapsed cooldowns stay put."""
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc_slo())
    recon()
    mark_running(cp, replicas(cp))
    url, = _urls(cp)
    cp.probe.signals[url] = {"ttft_p95_ms": 80.0}     # ratio 0.8: in band
    for _ in range(5):
        _backdate(cp)
        recon()
        assert get_isvc(cp).status.desired_replicas == 1, "autoscaler flapped"


def test_slo_missing_signal_holds_replica_count(cp):
    """ISSUE 6 satellite: stale/missing metrics from ONE replica hold the
    count — even while the other replica screams for a scale-up."""
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc_slo())
    recon()
    mark_running(cp, replicas(cp))
    isvc = get_isvc(cp)
    isvc.status.desired_replicas = 2
    cp.store.update_status(isvc)
    recon()
    mark_running(cp, replicas(cp))
    recon()
    u0, u1 = _urls(cp)
    cp.probe.signals[u0] = {"ttft_p95_ms": 900.0}
    cp.probe.fail.add(u1)                 # stale: probe fails outright
    _backdate(cp)
    recon()
    assert get_isvc(cp).status.desired_replicas == 2, \
        "resized on partial signals"
    cp.probe.fail.discard(u1)             # signal restored → decision resumes
    cp.probe.signals[u1] = {"ttft_p95_ms": 900.0}
    _backdate(cp)
    recon()
    assert get_isvc(cp).status.desired_replicas == 3


def test_slo_cooldown_suppresses_back_to_back_resizes(cp):
    """ISSUE 6 satellite: a hot signal right after a resize must wait out
    the cooldown before the next resize."""
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc_slo())
    recon()
    mark_running(cp, replicas(cp))
    url, = _urls(cp)
    cp.probe.signals[url] = {"ttft_p95_ms": 500.0}
    _backdate(cp)
    recon()
    assert get_isvc(cp).status.desired_replicas == 2
    recon()                                # converge: create replica 2
    mark_running(cp, replicas(cp))
    for u in _urls(cp):
        cp.probe.signals[u] = {"ttft_p95_ms": 500.0}
    recon()                                # cooldown fresh from the resize
    assert get_isvc(cp).status.desired_replicas == 2, \
        "back-to-back resize inside the cooldown"
    _backdate(cp)
    recon()
    assert get_isvc(cp).status.desired_replicas == 3


def test_slo_sigkill_between_scrape_and_resize_holds(cp):
    """ISSUE 6 satellite chaos: a replica SIGKILLed between scrape and
    resize leaves the fleet partial — the autoscaler holds until the
    replacement reports, then resumes deciding."""
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc_slo())
    recon()
    mark_running(cp, replicas(cp))
    isvc = get_isvc(cp)
    isvc.status.desired_replicas = 2
    cp.store.update_status(isvc)
    recon()
    mark_running(cp, replicas(cp))
    recon()
    for u in _urls(cp):
        cp.probe.signals[u] = {"ttft_p95_ms": 700.0}
    # SIGKILL one replica (envtest: phase flip, exit 137).
    w = replicas(cp)[1]
    w = cp.store.get(Worker, w.metadata.name)
    w.status.phase = WorkerPhase.FAILED
    w.status.exit_code = 137
    cp.store.update_status(w)
    _backdate(cp)
    recon()   # replacement spawns but is not RUNNING: fleet partial → hold
    assert get_isvc(cp).status.desired_replicas == 2, \
        "resized while a killed replica's replacement was still starting"
    mark_running(cp, replicas(cp))
    for u in _urls(cp):
        cp.probe.signals[u] = {"ttft_p95_ms": 700.0}
    _backdate(cp)
    recon()   # fleet whole again, still hot → scale-up resumes
    assert get_isvc(cp).status.desired_replicas == 3


def test_slo_scale_down_goes_through_drain(cp):
    """An SLO scale-down retires the trimmed replica through the graceful
    drain path — never an early kill of a busy replica."""
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc_slo())
    recon()
    mark_running(cp, replicas(cp))
    isvc = get_isvc(cp)
    isvc.status.desired_replicas = 2
    cp.store.update_status(isvc)
    recon()
    mark_running(cp, replicas(cp))
    recon()
    for u in _urls(cp):
        cp.probe.signals[u] = {"ttft_p95_ms": 10.0}   # far under target
    _backdate(cp)
    recon()
    assert get_isvc(cp).status.desired_replicas == 1
    # The trimmed replica is busy: it must drain, not die.
    ws = replicas(cp)
    url1 = f"http://127.0.0.1:{ws[1].spec.template.config['port']}"
    cp.probe.load[url1] = 2
    recon()
    assert len(replicas(cp)) == 2, "busy replica killed before drain"
    events = [e.reason for e in cp.recorder.for_object(get_isvc(cp))]
    assert "Draining" in events
    cp.probe.load[url1] = 0
    recon()
    assert len(replicas(cp)) == 1, "drained replica not torn down"


# -- disaggregated prefill/decode pools (ISSUE 12) ----------------------------

def mkisvc_pools(name="svc", prefill=1, decode=1, *, max_prefill=None,
                 max_decode=None, slo=None):
    from kubeflow_tpu.core.serving import PoolSplitSpec

    return InferenceService(
        metadata=ObjectMeta(name=name),
        spec=InferenceServiceSpec(predictor=PredictorSpec(
            model=ModelSpec(config={"preset": "tiny"}),
            pools=PoolSplitSpec(prefill=prefill, decode=decode,
                                max_prefill=max_prefill,
                                max_decode=max_decode),
            slo=slo)))


def _pool_slo(**kw):
    from kubeflow_tpu.core.serving import SLOPolicy

    base = dict(target_ttft_ms=100.0, target_queue_delay_ms=100.0,
                cooldown_s=10.0)
    base.update(kw)
    return SLOPolicy(**base)


def roles_of(cp, name="svc"):
    out = {}
    for w in replicas(cp, name):
        role = w.metadata.labels.get("serving.tpu.kubeflow.dev/role")
        out.setdefault(role, []).append(w)
    return out


def test_pool_split_creates_role_labeled_replicas(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc_pools(prefill=2, decode=1))
    recon()
    by_role = roles_of(cp)
    assert len(by_role.get("prefill", [])) == 2
    assert len(by_role.get("decode", [])) == 1
    # Pool membership rides into each replica's engine config.
    for role, ws in by_role.items():
        for w in ws:
            assert w.spec.template.config["batching"]["role"] == role
    mark_running(cp, replicas(cp))
    recon()
    isvc = get_isvc(cp)
    assert isvc.status.ready_replicas == 3
    assert isvc.status.desired_pool_replicas == {"prefill": 2, "decode": 1}
    assert isvc.status.has_condition("Ready")
    # The router carries both pools for token-aware placement.
    router = cp.isvc_reconciler._routers["default/svc"]
    assert router.has_pools


def test_pool_split_degraded_when_one_pool_empty(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc_pools(prefill=1, decode=1))
    recon()
    by_role = roles_of(cp)
    mark_running(cp, by_role["prefill"])     # decode pool never comes up
    recon()
    isvc = get_isvc(cp)
    assert not isvc.status.has_condition("Ready")


def test_pool_split_replaces_crashed_replica(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc_pools(prefill=1, decode=1))
    recon()
    mark_running(cp, replicas(cp))
    recon()
    w = roles_of(cp)["decode"][0]
    w = cp.store.get(Worker, w.metadata.name, w.metadata.namespace)
    w.status.phase = WorkerPhase.FAILED
    w.status.exit_code = 137
    cp.store.update_status(w)
    recon()
    recon()
    by_role = roles_of(cp)
    assert len(by_role["decode"]) == 1
    assert by_role["decode"][0].status.phase != WorkerPhase.FAILED


def test_pool_autoscaler_scales_each_pool_on_its_own_signal(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc_pools(prefill=1, decode=1, max_prefill=3,
                           max_decode=3, slo=_pool_slo()))
    recon()
    mark_running(cp, replicas(cp))
    by_role = roles_of(cp)
    pre_url = f"http://127.0.0.1:{by_role['prefill'][0].spec.template.config['port']}"
    dec_url = f"http://127.0.0.1:{by_role['decode'][0].spec.template.config['port']}"
    # Prefill backlog (queue delay over target), decode healthy: only the
    # prefill pool grows.
    cp.probe.signals[pre_url] = {"queue_delay_p95_ms": 500.0,
                                 "ttft_p95_ms": 50.0}
    cp.probe.signals[dec_url] = {"queue_delay_p95_ms": 10.0,
                                 "ttft_p95_ms": 60.0}
    _backdate(cp)
    recon()
    assert get_isvc(cp).status.desired_pool_replicas == \
        {"prefill": 2, "decode": 1}
    mark_running(cp, replicas(cp))
    # Decode TTFT over target: the decode pool grows too.
    for w in roles_of(cp)["prefill"]:
        u = f"http://127.0.0.1:{w.spec.template.config['port']}"
        cp.probe.signals[u] = {"queue_delay_p95_ms": 80.0,
                               "ttft_p95_ms": 50.0}
    cp.probe.signals[dec_url] = {"queue_delay_p95_ms": 10.0,
                                 "ttft_p95_ms": 400.0}
    _backdate(cp)
    recon()
    assert get_isvc(cp).status.desired_pool_replicas == \
        {"prefill": 2, "decode": 2}
    events = [e.reason for e in cp.recorder.for_object(get_isvc(cp))]
    assert events.count("ScaledUp") >= 2


def test_pool_autoscaler_holds_when_pool_blind(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc_pools(prefill=1, decode=1, max_prefill=3,
                           max_decode=3, slo=_pool_slo()))
    recon()
    mark_running(cp, replicas(cp))
    by_role = roles_of(cp)
    pre_url = f"http://127.0.0.1:{by_role['prefill'][0].spec.template.config['port']}"
    dec_url = f"http://127.0.0.1:{by_role['decode'][0].spec.template.config['port']}"
    cp.probe.signals[pre_url] = {"queue_delay_p95_ms": 500.0}
    cp.probe.fail.add(dec_url)          # one replica unprobeable: blind
    _backdate(cp)
    recon()
    assert get_isvc(cp).status.desired_pool_replicas == \
        {"prefill": 1, "decode": 1}, "resized while a probe was failing"


def test_pool_autoscaler_scales_down_to_spec_floor(cp):
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc_pools(prefill=2, decode=1, max_prefill=3,
                           max_decode=3, slo=_pool_slo()))
    recon()
    mark_running(cp, replicas(cp))
    for w in replicas(cp):
        u = f"http://127.0.0.1:{w.spec.template.config['port']}"
        cp.probe.signals[u] = {"queue_delay_p95_ms": 1.0,
                               "ttft_p95_ms": 1.0}
    _backdate(cp)
    recon()
    # Far under target on both signals, but prefill=2 is the SPEC floor.
    assert get_isvc(cp).status.desired_pool_replicas == \
        {"prefill": 2, "decode": 1}


def test_pools_reject_canary_and_roles():
    from kubeflow_tpu.core.serving import BatchingSpec, PoolSplitSpec

    with pytest.raises(ValueError, match="mutually exclusive"):
        PredictorSpec(model=ModelSpec(), canary_traffic_percent=50,
                      pools=PoolSplitSpec())
    with pytest.raises(ValueError, match="role"):
        PredictorSpec(model=ModelSpec(), pools=PoolSplitSpec(),
                      batching=BatchingSpec(role="prefill"))
    with pytest.raises(ValueError, match="max_prefill"):
        PoolSplitSpec(prefill=2, max_prefill=1)
