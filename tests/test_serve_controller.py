"""ISVC controller semantics, envtest-style: no processes — a fake probe
plays the replicas' health/metrics endpoints (SURVEY.md §4.2 pattern)."""

import pytest

from kubeflow_tpu.core.jobs import Worker, WorkerPhase
from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.core.serving import (
    InferenceService, InferenceServiceSpec, ModelSpec, PredictorSpec,
)
from kubeflow_tpu.operator.control_plane import ControlPlane, ControlPlaneConfig


class FakeProbe:
    """url -> {"ready", "in_flight"}; tests mutate `ready` and `load`."""

    def __init__(self):
        self.ready = True
        self.load = {}          # url -> in_flight

    def __call__(self, url):
        if not self.ready:
            return None
        return {"ready": True, "in_flight": self.load.get(url, 0)}


@pytest.fixture()
def cp(tmp_path):
    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path), launch_processes=False,
        metrics_sync_interval=None))
    plane.probe = FakeProbe()
    plane.isvc_reconciler.probe = plane.probe
    yield plane
    plane.isvc_reconciler.shutdown()


def mkisvc(name="svc", min_replicas=1, max_replicas=1, scale_target=4):
    return InferenceService(
        metadata=ObjectMeta(name=name),
        spec=InferenceServiceSpec(predictor=PredictorSpec(
            model=ModelSpec(config={"preset": "tiny"}),
            min_replicas=min_replicas, max_replicas=max_replicas,
            scale_target=scale_target)))


def replicas(cp, name="svc"):
    ws = cp.store.list(Worker, label_selector={
        "serving.tpu.kubeflow.dev/service": name})
    return sorted(ws, key=lambda w: int(
        w.metadata.labels["serving.tpu.kubeflow.dev/replica"]))


def mark_running(cp, ws):
    for w in ws:
        w = cp.store.get(Worker, w.metadata.name, w.metadata.namespace)
        w.status.phase = WorkerPhase.RUNNING
        cp.store.update_status(w)


def get_isvc(cp, name="svc"):
    return cp.store.get(InferenceService, name)


def test_creates_replicas_and_reports_ready(cp):
    cp.submit(mkisvc())
    cp.step()
    ws = replicas(cp)
    assert len(ws) == 1
    w = ws[0]
    assert w.spec.template.entrypoint == "model_server"
    assert w.spec.template.config["port"] > 0
    assert w.spec.template.config["model"] == {"preset": "tiny"}
    isvc = get_isvc(cp)
    assert isvc.status.ready_replicas == 0     # not Running yet
    mark_running(cp, ws)
    cp.step()
    isvc = get_isvc(cp)
    assert isvc.status.ready_replicas == 1
    assert isvc.status.has_condition("Ready")
    assert isvc.status.url.startswith("http://127.0.0.1:")


def test_unready_probe_blocks_ready_condition(cp):
    cp.submit(mkisvc())
    cp.step()
    mark_running(cp, replicas(cp))
    cp.probe.ready = False
    cp.step()
    isvc = get_isvc(cp)
    assert isvc.status.ready_replicas == 0
    assert isvc.status.has_condition("Ready", status=False)


def test_crashed_replica_is_replaced(cp):
    cp.submit(mkisvc())
    cp.step()
    w = replicas(cp)[0]
    old_uid = w.metadata.uid
    w = cp.store.get(Worker, w.metadata.name)
    w.status.phase = WorkerPhase.FAILED
    w.status.exit_code = 1
    cp.store.update_status(w)
    cp.step()
    ws = replicas(cp)
    assert len(ws) == 1
    assert ws[0].metadata.uid != old_uid


def test_scale_up_on_concurrency(cp):
    # Single reconciles (not cp.step(), which pumps several rounds): the
    # autoscaler moves one replica per reconcile.
    recon = lambda: cp.isvc_reconciler.reconcile("default/svc")
    cp.submit(mkisvc(min_replicas=1, max_replicas=3, scale_target=2))
    recon()
    mark_running(cp, replicas(cp))
    # Load the single replica beyond target → scale to 2.
    url = f"http://127.0.0.1:{replicas(cp)[0].spec.template.config['port']}"
    cp.probe.load[url] = 5
    recon()
    assert get_isvc(cp).status.desired_replicas == 2
    # Load drops; new replica joins; cooldown prevents an instant scale-down.
    cp.probe.load[url] = 0
    recon()
    ws = replicas(cp)
    assert len(ws) == 2
    mark_running(cp, ws)
    recon()
    assert get_isvc(cp).status.desired_replicas == 2
    assert get_isvc(cp).status.ready_replicas == 2


def test_deletion_cleans_replicas(cp):
    cp.submit(mkisvc())
    cp.step()
    assert replicas(cp)
    cp.store.delete(InferenceService, "svc")
    cp.step()
    assert replicas(cp) == []
