"""Router hardening: connect-failure retries, outlier ejection + half-open
re-probe, draining, deadline-aware upstream timeouts, and concurrent
set_backends() swaps (ISSUE 2). Pure-HTTP tests — no JAX, no engine: fake
backends answer with their own name so routing decisions are observable."""

import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubeflow_tpu.serve.faults import ChaosProxy
from kubeflow_tpu.serve.router import DEADLINE_HEADER, Router


class EchoBackend:
    """Answers every request with {"backend": <name>}."""

    def __init__(self, name: str):
        self.name = name
        backend = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _do(self):
                n = int(self.headers.get("Content-Length", 0))
                if n:
                    self.rfile.read(n)
                data = json.dumps({"backend": backend.name}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            do_GET = _do
            do_POST = _do

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def dead_url() -> str:
    """A url that refuses connections (bound once, then closed)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"http://127.0.0.1:{port}"


def ask(router_url: str, timeout: float = 10.0,
        deadline_ms: int = 0) -> tuple[int, dict]:
    headers = {"Content-Type": "application/json"}
    if deadline_ms:
        headers[DEADLINE_HEADER] = str(deadline_ms)
    req = urllib.request.Request(router_url + "/v1/echo", data=b"{}",
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as exc:
        body = exc.read()
        return exc.code, json.loads(body) if body else {}


@pytest.fixture()
def backends():
    a, b = EchoBackend("a"), EchoBackend("b")
    yield a, b
    a.stop()
    b.stop()


@pytest.fixture()
def router():
    r = Router(queue_timeout=5.0, eject_threshold=2, eject_period=0.4,
               max_retries=2, upstream_timeout=30.0)
    r.start()
    yield r
    r.stop()


def test_retry_on_connect_failure_uses_other_backend(router, backends):
    """Satellite: a refused connection must not become a client-visible 502
    while ready backends exist — retry the pick excluding the failure."""
    a, _ = backends
    router.set_backends({"latest": [dead_url(), a.url]})
    for _ in range(6):
        status, body = ask(router.url)
        assert status == 200 and body["backend"] == "a"
    snap = router.snapshot()
    assert snap["retries"] >= 1
    assert snap["connect_failures"] >= 1


def test_all_backends_dead_is_explicit_502(router):
    router.set_backends({"latest": [dead_url(), dead_url()]})
    status, body = ask(router.url)
    assert status == 502
    assert "unreachable" in body["error"]


def test_outlier_ejection_and_half_open_recovery(router, backends):
    a, b = backends
    proxy = ChaosProxy(a.url)
    proxy.start()
    try:
        router.set_backends({"latest": [proxy.url, b.url]})
        proxy.drop()                      # a's proxy now refuses everything
        for _ in range(6):
            status, body = ask(router.url)
            assert status == 200 and body["backend"] == "b"
        assert router.snapshot()["ejections"] >= 1
        # While ejected, the proxy is never even dialed.
        dropped_before = proxy.stats["dropped"]
        for _ in range(4):
            status, _ = ask(router.url)
            assert status == 200
        assert proxy.stats["dropped"] == dropped_before, \
            "ejected backend still being dialed"
        # Backend recovers; after the ejection window a half-open probe
        # reinstates it.
        proxy.undrop()
        time.sleep(0.5)
        for _ in range(8):
            status, _ = ask(router.url)
            assert status == 200
        assert proxy.stats["forwarded"] > 0, "recovered backend never probed"
        assert router.snapshot()["half_open_probes"] >= 1
    finally:
        proxy.stop()


def test_draining_backend_stops_receiving_picks(router, backends):
    a, b = backends
    router.set_backends({"latest": [a.url, b.url]})
    router.set_draining(a.url)
    for _ in range(6):
        status, body = ask(router.url)
        assert status == 200 and body["backend"] == "b"
    router.set_draining(a.url, False)
    seen = {ask(router.url)[1]["backend"] for _ in range(8)}
    assert seen == {"a", "b"}


def test_deadline_header_bounds_wedged_upstream(router, backends):
    """The hard-coded 600 s upstream timeout is gone: a wedged backend
    costs at most the client's remaining budget."""
    a, _ = backends
    proxy = ChaosProxy(a.url)
    proxy.start()
    try:
        router.set_backends({"latest": [proxy.url]})
        proxy.wedge()
        t0 = time.monotonic()
        status, body = ask(router.url, timeout=15.0, deadline_ms=400)
        elapsed = time.monotonic() - t0
        assert status in (502, 504), body
        assert elapsed < 5.0, f"wedged backend held the request {elapsed:.1f}s"
    finally:
        proxy.stop()


def test_router_upstream_timeout_replaces_hardcoded_600s(backends):
    a, _ = backends
    r = Router(queue_timeout=2.0, upstream_timeout=0.4, max_retries=1)
    r.start()
    proxy = ChaosProxy(a.url)
    proxy.start()
    try:
        r.set_backends({"latest": [proxy.url]})
        proxy.wedge()
        t0 = time.monotonic()
        status, _ = ask(r.url, timeout=15.0)    # no deadline header
        assert status in (502, 504)
        assert time.monotonic() - t0 < 5.0
    finally:
        proxy.stop()
        r.stop()


def test_5xx_is_forwarded_not_retried_but_counts_toward_ejection(
        router, backends):
    a, b = backends
    proxy = ChaosProxy(a.url)
    proxy.start()
    try:
        router.set_backends({"latest": [proxy.url]})
        proxy.fail_next(2, code=503)
        codes = [ask(router.url)[0] for _ in range(2)]
        assert codes == [503, 503], "5xx must reach the client verbatim"
        assert router.snapshot()["ejections"] >= 1
        # Post-burst: backend healthy again; half-open probe restores it.
        time.sleep(0.5)
        status, _ = ask(router.url)
        assert status == 200
    finally:
        proxy.stop()


def test_concurrent_set_backends_swaps_with_requests_in_flight(
        router, backends):
    """Satellite: requests racing set_backends() swaps must neither crash
    nor route to a backend after it has settled out of the rotation."""
    a, b = backends
    router.set_backends({"latest": [a.url, b.url]})
    errors: list = []
    results: list = []
    stop = threading.Event()

    def client():
        while not stop.is_set():
            try:
                status, body = ask(router.url, timeout=10.0)
                results.append((status, body.get("backend")))
                if status not in (200, 502, 503, 504):
                    errors.append(f"unexpected status {status}")
            except Exception as exc:   # noqa: BLE001 - any crash is a fail
                errors.append(repr(exc))

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    combos = [{"latest": [a.url, b.url]}, {"latest": [b.url]},
              {"latest": [a.url]}, {"latest": [a.url, b.url]}]
    for i in range(40):
        router.set_backends(combos[i % len(combos)])
        time.sleep(0.005)
    stop.set()
    for t in threads:
        t.join(timeout=15.0)
        assert not t.is_alive(), "client thread hung through backend swaps"
    assert not errors, errors
    assert results, "no requests completed during the swap storm"
    # Settle on b only: every subsequent request must land on b.
    router.set_backends({"latest": [b.url]})
    for _ in range(6):
        status, body = ask(router.url)
        assert status == 200 and body["backend"] == "b"


def test_pick_or_wait_never_returns_removed_backend(router, backends):
    a, b = backends
    router.set_backends({"latest": [a.url, b.url]})
    picks: list = []
    stop = threading.Event()

    def picker():
        while not stop.is_set():
            p = router.pick_or_wait(timeout=1.0)
            if p is not None:
                picks.append((time.monotonic(), p))

    threads = [threading.Thread(target=picker) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    router.set_backends({"latest": [b.url]})
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
        assert not t.is_alive()
    assert picks, "no picks happened under the swap"
    # After the swap has settled, the removed backend is never picked again.
    assert all(router.pick_or_wait(timeout=1.0) == b.url
               for _ in range(20))


def test_router_metrics_endpoint(router, backends):
    a, _ = backends
    router.set_backends({"latest": [a.url]})
    ask(router.url)
    with urllib.request.urlopen(router.url + "/-/router/metrics",
                                timeout=5.0) as r:
        text = r.read().decode()
    assert "kftpu_router_picks" in text
    assert "kftpu_router_ejected" in text


# -- disaggregated pools: token-aware placement (ISSUE 12) --------------------

def test_pool_placement_follows_token_signals(router, backends):
    """Prefills place on least-pending-prefill-tokens, decodes on
    least-resident-KV-pages (in-flight breaks ties) — from injected
    signals, no scrape needed."""
    a, b = backends
    c = EchoBackend("c")
    d = EchoBackend("d")
    try:
        router.set_pools({"prefill": [a.url, b.url],
                          "decode": [c.url, d.url]}, scrape=False)
        router.note_signals(a.url, {"pending_prefill_tokens": 500,
                                    "in_flight": 1})
        router.note_signals(b.url, {"pending_prefill_tokens": 20,
                                    "in_flight": 1})
        router.note_signals(c.url, {"kv_pages_resident": 90,
                                    "in_flight": 0})
        router.note_signals(d.url, {"kv_pages_resident": 3,
                                    "in_flight": 0})
        for _ in range(4):
            backend, decode = router.pick_disaggregated()
            assert backend == b.url, "prefill pick ignored pending tokens"
            assert decode == d.url, "decode pick ignored resident pages"
        assert router.snapshot()["disagg_picks"] >= 4
    finally:
        c.stop()
        d.stop()


def test_pool_placement_round_robins_equal_signals(router, backends):
    a, b = backends
    router.set_pools({"prefill": [a.url, b.url], "decode": [a.url]},
                     scrape=False)
    picks = {router.pick_disaggregated()[0] for _ in range(8)}
    assert picks == {a.url, b.url}, "equal signals pinned one backend"


def test_pool_fallback_when_decode_pool_unhealthy(router, backends):
    """No healthy decode member → unified fallback: a healthy backend
    carries the request WITHOUT a handoff target."""
    a, b = backends
    dead = dead_url()
    router.set_pools({"prefill": [a.url], "decode": [dead]}, scrape=False)
    router.note_backend_failure(dead, connect=True)
    router.note_backend_failure(dead, connect=True)   # threshold=2: eject
    backend, decode = router.pick_disaggregated()
    assert backend == a.url
    assert decode is None
    assert router.snapshot()["disagg_fallbacks"] >= 1


def test_pool_proxy_stamps_decode_backend_header(router, backends):
    """Through the HTTP proxy, a disaggregated pick forwards the decode
    target on X-Kftpu-Decode-Backend; fallback omits it."""
    import json as _json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from kubeflow_tpu.core.headers import DECODE_BACKEND_HEADER

    seen = {}

    class Capture(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        def do_POST(self):
            seen["decode"] = self.headers.get(DECODE_BACKEND_HEADER)
            n = int(self.headers.get("Content-Length", 0))
            if n:
                self.rfile.read(n)
            data = _json.dumps({"backend": "capture"}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Capture)
    httpd.daemon_threads = True
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    cap_url = f"http://127.0.0.1:{httpd.server_address[1]}"
    a, b = backends
    try:
        router.set_pools({"prefill": [cap_url], "decode": [b.url]},
                         scrape=False)
        status, _ = ask(router.url)
        assert status == 200
        assert seen["decode"] == b.url
        # Decode pool gone → fallback carries no handoff header.
        router.set_pools({"prefill": [cap_url], "decode": []},
                         scrape=False)
        seen.clear()
        status, _ = ask(router.url)
        assert status == 200
        assert seen["decode"] is None
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_scrape_failure_ejects_pool_member(router, backends):
    """The signal scrape doubles as a health probe: a pool member that
    stops answering /metrics is ejected from placement even though it
    takes no proxied traffic."""
    a, b = backends
    dead = dead_url()
    router.set_pools({"prefill": [a.url], "decode": [dead, b.url]},
                     scrape=False)
    for _ in range(router.eject_threshold):
        router.scrape_signals()
    backend, decode = router.pick_disaggregated()
    assert backend == a.url
    assert decode == b.url, "dead decode member still picked"
    assert router.snapshot()["ejections"] >= 1


# -- prefix affinity + decode alternates (ISSUE 17) ---------------------------

def test_prefix_affinity_pins_decode_replica(router, backends):
    """The same affinity key lands on the same decode replica every
    pick, overriding load signals — a session's turns chase the replica
    whose radix tree holds their prefix."""
    from kubeflow_tpu.serve.router import _rendezvous

    a, b = backends
    router.set_pools({"prefill": [a.url], "decode": [a.url, b.url]},
                     scrape=False)
    # Make the affinity home the LOAD-WORSE replica, so following it is
    # observably affinity, not the load tiebreak.
    home = max([a.url, b.url], key=lambda u: _rendezvous("sess", u))
    other = b.url if home == a.url else a.url
    router.note_signals(home, {"kv_pages_resident": 500, "in_flight": 5})
    router.note_signals(other, {"kv_pages_resident": 0, "in_flight": 0})
    for _ in range(6):
        _, decode = router.pick_disaggregated(affinity="sess")
        assert decode == home, "affinity did not pin the warm replica"
    assert router.snapshot()["affinity_hits"] >= 6
    # No key → pure load placement.
    _, decode = router.pick_disaggregated()
    assert decode == other


def test_prefix_affinity_falls_through_on_unhealth(router, backends):
    """Affinity is a cache hint, never a health exemption: an ejected
    home replica misses and the pick degrades to load placement."""
    from kubeflow_tpu.serve.router import _rendezvous

    a, b = backends
    # Dedicated prefill member: if the decode home doubled as the only
    # prefill replica, ejecting it would collapse the pick into the
    # unified fallback (decode=None by contract) whenever the
    # port-dependent rendezvous hash happened to land home there.
    pre = EchoBackend("pre")
    try:
        router.set_pools({"prefill": [pre.url],
                          "decode": [a.url, b.url]}, scrape=False)
        home = max([a.url, b.url], key=lambda u: _rendezvous("sess", u))
        other = b.url if home == a.url else a.url
        for _ in range(router.eject_threshold):
            router.note_backend_failure(home, connect=True)
        _, decode = router.pick_disaggregated(affinity="sess")
        assert decode == other, "ejected home replica still picked"
        assert router.snapshot()["affinity_misses"] >= 1
    finally:
        pre.stop()


def test_decode_alternates_are_healthy_non_primary(router, backends):
    a, b = backends
    dead = dead_url()
    router.set_pools({"prefill": [a.url], "decode": [a.url, b.url, dead]},
                     scrape=False)
    for _ in range(router.eject_threshold):
        router.note_backend_failure(dead, connect=True)
    alts = router.decode_alternates(a.url)
    assert alts == (b.url,), "alternates must exclude primary + ejected"
    assert router.decode_alternates(None) in ((a.url, b.url),
                                              (b.url, a.url))


def test_affinity_key_extraction():
    from kubeflow_tpu.serve.router import _affinity_key

    body = json.dumps({"prompt": "sys: " + "x" * 100}).encode()
    key = _affinity_key("/v1/completions", body)
    assert key is not None and len(key) == 64
    chat = json.dumps({"messages": [
        {"role": "system", "content": "you are helpful"},
        {"role": "user", "content": "hi"}]}).encode()
    assert _affinity_key("/v1/chat/completions", chat) == "you are helpful"
    assert _affinity_key("/v1/embeddings", body) is None
    assert _affinity_key("/v1/completions", b"not json") is None
    assert _affinity_key("/v1/completions", None) is None
    assert _affinity_key("/v1/completions", b'{"prompt": ""}') is None


def test_proxy_stamps_decode_alts_header(router, backends):
    """A disaggregated pick forwards the retry ladder on
    X-Kftpu-Decode-Alts: every healthy decode member except the primary
    target, so the prefill replica can retry a died-mid-handoff peer."""
    import json as _json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from kubeflow_tpu.core.headers import (
        DECODE_ALTS_HEADER, DECODE_BACKEND_HEADER,
    )

    seen = {}

    class Capture(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        def do_POST(self):
            seen["decode"] = self.headers.get(DECODE_BACKEND_HEADER)
            seen["alts"] = self.headers.get(DECODE_ALTS_HEADER)
            n = int(self.headers.get("Content-Length", 0))
            if n:
                self.rfile.read(n)
            data = _json.dumps({"backend": "capture"}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Capture)
    httpd.daemon_threads = True
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    cap_url = f"http://127.0.0.1:{httpd.server_address[1]}"
    a, b = backends
    try:
        router.set_pools({"prefill": [cap_url], "decode": [a.url, b.url]},
                         scrape=False)
        status, _ = ask(router.url)
        assert status == 200
        assert seen["decode"] in (a.url, b.url)
        expect_alt = b.url if seen["decode"] == a.url else a.url
        assert seen["alts"] == expect_alt
        # One-member decode pool → no alternates header at all.
        router.set_pools({"prefill": [cap_url], "decode": [b.url]},
                         scrape=False)
        seen.clear()
        status, _ = ask(router.url)
        assert status == 200
        assert seen["alts"] is None
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_remote_pages_signal_scraped(router, backends):
    """kftpu_engine_kv_pages_remote rides the scrape into the signal
    table (the consumer half of the two-sided gauge)."""
    text = ("# TYPE kftpu_engine_kv_pages_remote gauge\n"
            "kftpu_engine_kv_pages_remote 7\n"
            "# TYPE kftpu_serving_in_flight gauge\n"
            "kftpu_serving_in_flight 1\n")
    sig = Router._parse_signals(text)
    assert sig is not None
    assert sig["kv_pages_remote"] == 7.0
