"""Vision family tests: ViT + CLIP forward/shapes, learning, sharded
equivalence on the 8-device mesh, and the 'ViT/CLIP via pipelines' flow
(BASELINE config 4)."""

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.models.vision import (
    CLIPConfig, ViTConfig, clip_encode_image, clip_encode_text, clip_loss,
    clip_preset, init_clip_params, init_vit_params, patchify, vit_forward,
    vit_loss, vit_preset,
)
from kubeflow_tpu.runtime.mesh import build_mesh
from kubeflow_tpu.train.optim import OptimizerConfig
from kubeflow_tpu.train.vision_task import (
    clip_batch, setup_clip_train, setup_vit_train, vit_batch,
)

TINY = vit_preset("tiny-vit", dtype="float32")
TINY_CLIP = clip_preset("tiny-clip", dtype="float32")


class TestViT:
    def test_patchify_is_exact(self):
        imgs = jnp.arange(2 * 8 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 8, 3)
        p = patchify(imgs, 4)
        assert p.shape == (2, 4, 48)
        # First patch = top-left 4x4 block, row-major.
        assert jnp.array_equal(p[0, 0].reshape(4, 4, 3), imgs[0, :4, :4, :])

    def test_forward_shapes(self):
        params = init_vit_params(jax.random.PRNGKey(0), TINY)
        imgs = jnp.zeros((2, 32, 32, 3))
        logits = vit_forward(params, imgs, TINY)
        assert logits.shape == (2, TINY.num_classes)
        feat_cfg = vit_preset("tiny-vit", num_classes=0, dtype="float32")
        feats = vit_forward(init_vit_params(jax.random.PRNGKey(0), feat_cfg),
                            imgs, feat_cfg)
        assert feats.shape == (2, feat_cfg.hidden)

    def test_gap_pooling(self):
        cfg = vit_preset("tiny-vit", pool="gap", dtype="float32")
        params = init_vit_params(jax.random.PRNGKey(0), cfg)
        assert "cls_token" not in params
        assert vit_forward(params, jnp.zeros((2, 32, 32, 3)), cfg).shape == \
            (2, cfg.num_classes)

    def test_scan_matches_loop(self):
        loop_cfg = vit_preset("tiny-vit", scan_layers=False, dtype="float32")
        scan_cfg = vit_preset("tiny-vit", dtype="float32")
        scan_params = init_vit_params(jax.random.PRNGKey(0), scan_cfg)
        loop_params = dict(scan_params)
        loop_params["layers"] = [
            jax.tree.map(lambda p: p[i], scan_params["layers"])
            for i in range(scan_cfg.n_layers)]
        imgs = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        a = vit_forward(scan_params, imgs, scan_cfg)
        b = vit_forward(loop_params, imgs, loop_cfg)
        assert jnp.allclose(a, b, atol=1e-5)

    @pytest.mark.slow  # tier-1 budget (ISSUE 14): slowest fast tests re-marked
    def test_vit_learns(self):
        mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
        task = setup_vit_train(TINY, OptimizerConfig(
            learning_rate=1e-3, total_steps=10, warmup_steps=0), mesh)
        state, losses = task.state, []
        for step in range(8):
            b = jax.device_put(vit_batch(TINY, 16, step), task.batch_shardings)
            state, m = task.step_fn(state, b)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_sharded_matches_single(self):
        params = init_vit_params(jax.random.PRNGKey(0), TINY)
        batch = jax.tree.map(jnp.asarray, vit_batch(TINY, 8, 0))
        ref, _ = vit_loss(params, batch, TINY)
        mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
        sharded, _ = jax.jit(
            lambda p, b: vit_loss(p, b, TINY, mesh=mesh))(params, batch)
        assert abs(float(ref) - float(sharded)) < 1e-4 * max(1, abs(float(ref)))


class TestCLIP:
    def test_encoders_shapes(self):
        params = init_clip_params(jax.random.PRNGKey(0), TINY_CLIP)
        batch = jax.tree.map(jnp.asarray, clip_batch(TINY_CLIP, 4, 0))
        img = clip_encode_image(params, batch["images"], TINY_CLIP)
        txt = clip_encode_text(params, batch["tokens"], TINY_CLIP)
        assert img.shape == (4, TINY_CLIP.proj_dim)
        assert txt.shape == (4, TINY_CLIP.proj_dim)

    def test_loss_and_metrics(self):
        params = init_clip_params(jax.random.PRNGKey(0), TINY_CLIP)
        batch = jax.tree.map(jnp.asarray, clip_batch(TINY_CLIP, 4, 0))
        loss, metrics = clip_loss(params, batch, TINY_CLIP)
        assert jnp.isfinite(loss)
        # Untrained symmetric InfoNCE ≈ log(B).
        assert abs(float(loss) - jnp.log(4)) < 1.5
        assert 0.0 < float(metrics["temperature"]) < 1.0

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_clip_learns(self):
        mesh = build_mesh({"data": 4, "model": 2})
        task = setup_clip_train(TINY_CLIP, OptimizerConfig(
            learning_rate=3e-3, total_steps=12, warmup_steps=0), mesh)
        state, losses = task.state, []
        for step in range(10):
            b = jax.device_put(clip_batch(TINY_CLIP, 16, step),
                               task.batch_shardings)
            state, m = task.step_fn(state, b)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    @pytest.mark.slow  # tier-1 budget (ISSUE 17): slowest fast tests re-marked
    def test_sharded_matches_single(self):
        params = init_clip_params(jax.random.PRNGKey(0), TINY_CLIP)
        batch = jax.tree.map(jnp.asarray, clip_batch(TINY_CLIP, 8, 0))
        ref, _ = clip_loss(params, batch, TINY_CLIP)
        mesh = build_mesh({"data": 4, "model": 2})
        sharded, _ = jax.jit(
            lambda p, b: clip_loss(p, b, TINY_CLIP, mesh=mesh))(params, batch)
        assert abs(float(ref) - float(sharded)) < 5e-4 * max(1, abs(float(ref)))


class TestVisionViaPipelines:
    @pytest.mark.slow  # tier-1 budget (ISSUE 17): slowest fast tests re-marked
    def test_vit_training_pipeline(self, tmp_path):
        """BASELINE config 4: a KFP-analog pipeline whose component trains
        ViT and hands metrics downstream."""
        from kubeflow_tpu.pipelines import dsl
        from kubeflow_tpu.pipelines.artifacts import ArtifactStore
        from kubeflow_tpu.pipelines.compiler import compile_pipeline
        from kubeflow_tpu.pipelines.executor import PipelineExecutor
        from kubeflow_tpu.pipelines.metadata import MetadataStore

        @dsl.component
        def train_vit(steps: int) -> dict:
            mesh = build_mesh({"data": jax.device_count()})
            task = setup_vit_train(TINY, OptimizerConfig(
                learning_rate=1e-3, total_steps=steps, warmup_steps=0), mesh)
            state = task.state
            first = last = None
            for step in range(steps):
                b = jax.device_put(vit_batch(TINY, 16, step),
                                   task.batch_shardings)
                state, m = task.step_fn(state, b)
                if first is None:
                    first = float(m["loss"])
                last = float(m["loss"])
            return {"first_loss": first, "final_loss": last}

        @dsl.component
        def check(report: dict) -> bool:
            return report["final_loss"] < report["first_loss"]

        @dsl.pipeline(name="vit-train")
        def p(steps: int = 6):
            r = train_vit(steps=steps)
            check(report=r.output)

        ex = PipelineExecutor(ArtifactStore(str(tmp_path / "cas")),
                              MetadataStore(str(tmp_path / "md.db")))
        res = ex.run(compile_pipeline(p), run_name="r")
        assert res.phase.value == "Succeeded"
        assert res.tasks["check"].outputs["output"] is True
