"""Operator e2e with real worker processes (≈ the reference's kind-based e2e,
SURVEY.md §4.5): submit a JAXJob, watch the control plane gang-place, launch,
monitor, restart, and complete it — including the phase-4 flagship slice:
distributed training, worker killed mid-run, auto-resume from checkpoint."""

import time

import pytest

from kubeflow_tpu.core.jobs import (
    JAXJob, JAXJobSpec, ParallelismSpec, ReplicaSpec, RestartPolicy,
    TPUResourceSpec, Worker, WorkloadSpec,
)
from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.operator.control_plane import ControlPlane, ControlPlaneConfig
from kubeflow_tpu.operator.faults import FaultInjector
from kubeflow_tpu.runtime.topology import Cluster, SliceTopology


@pytest.fixture()
def cp(tmp_path):
    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="cpu",
                                              dims=(2, 2))]),
        platform="cpu",
        heartbeat_timeout=15.0,
        rendezvous_timeout=60.0,
    ))
    plane.start()
    yield plane
    plane.stop()


def job_of(entrypoint, config=None, *, name="e2e", replicas=2,
           parallelism=None, restart_policy=RestartPolicy.EXIT_CODE,
           backoff=3) -> JAXJob:
    j = JAXJob(
        metadata=ObjectMeta(name=name),
        spec=JAXJobSpec(
            replica_specs={"worker": ReplicaSpec(
                replicas=replicas,
                restart_policy=restart_policy,
                template=WorkloadSpec(entrypoint=entrypoint, config=config or {}),
                resources=TPUResourceSpec(tpu_chips=1),
            )},
            parallelism=parallelism or ParallelismSpec(),
        ),
    )
    j.spec.run_policy.backoff_limit = backoff
    j.spec.run_policy.checkpoint.enabled = False
    return j


@pytest.mark.slow  # tier-1 budget (ISSUE 14): slowest fast tests re-marked
def test_noop_job_succeeds(cp):
    job = cp.submit(job_of("noop"))
    done = cp.wait_for(job, "Succeeded", timeout=30)
    assert done.status.replica_statuses["worker"].succeeded == 2
    assert cp.allocator.allocation("default/e2e") is None


def test_flaky_worker_gang_restarts_then_succeeds(cp, tmp_path):
    job = cp.submit(job_of(
        "flaky", {"attempt_file": str(tmp_path / "attempts"), "fail_times": 1},
        replicas=1))
    done = cp.wait_for(job, "Succeeded", timeout=30)
    assert done.status.restart_count >= 1


def test_permanent_failure_fails_job(cp):
    job = cp.submit(job_of("fail", {"exit_code": 3}, replicas=1))
    done = cp.wait_for(job, "Failed", timeout=30)
    # first death is pre-Running => one retryable restart happens, then the
    # post-Running... no: 'fail' exits before Running settles. The controller
    # may grant pre-running retries until backoff; assert terminal state only.
    assert done.status.phase == "Failed"


def test_kill_worker_triggers_gang_restart(cp):
    job = cp.submit(job_of("sleep", {"seconds": 3.0}))
    cp.wait_for(job, "Running", timeout=30)
    inj = FaultInjector(cp)
    assert inj.kill_worker("default/e2e", index=1)
    done = cp.wait_for(job, "Succeeded", timeout=60)
    assert done.status.restart_count >= 1


@pytest.mark.slow
def test_train_gang_kill_resume_e2e(cp, tmp_path):
    """The minimum end-to-end slice (SURVEY.md §7 phase 4): a 2-process
    distributed tiny-LLM pretrain on the emulated cluster — gang rendezvous
    via jax.distributed, checkpointing every 2 steps, worker 0 killed
    mid-run, whole-gang restart, resume from checkpoint, completion with
    data-plane metrics on job status."""
    j = job_of(
        "llm_pretrain",
        {
            "model": "tiny",
            "steps": 40,
            "log_every": 2,
            "data": {"global_batch": 8, "seq_len": 64, "kind": "synthetic"},
        },
        name="train",
        replicas=2,
        parallelism=ParallelismSpec(data=2),
    )
    j.spec.run_policy.checkpoint.enabled = True
    j.spec.run_policy.checkpoint.interval_steps = 5
    job = cp.submit(j)
    cp.wait_for(job, "Running", timeout=240)
    inj = FaultInjector(cp)
    inj.kill_worker_at_step("default/train", index=0, step=6, timeout=300)
    done = cp.wait_for(job, "Succeeded", timeout=420)
    assert done.status.restart_count >= 1, "kill did not trigger a restart"
    assert done.status.metrics.step == 40
    assert done.status.metrics.tokens_per_sec_per_chip is not None
    assert done.status.metrics.loss is not None


@pytest.mark.slow
def test_elastic_resize_resharded_restore_e2e(cp, tmp_path):
    """Elastic resize with resharded restore (SURVEY.md §5, hard part #5):
    a 2-process distributed train is live-resized to 4 workers; the job
    re-gangs on the new mesh and orbax restores the 2-way-sharded
    checkpoint into the 4-way sharding, finishing all steps with no
    backoff consumed."""
    j = job_of(
        "llm_pretrain",
        {
            "model": "tiny",
            "steps": 60,
            "log_every": 2,
            "data": {"global_batch": 8, "seq_len": 64, "kind": "synthetic"},
        },
        name="elastic",
        replicas=2,
        parallelism=ParallelismSpec(data=2),
    )
    from kubeflow_tpu.core.jobs import ElasticPolicy

    j.spec.elastic_policy = ElasticPolicy(min_replicas=1, max_replicas=4)
    j.spec.run_policy.checkpoint.enabled = True
    j.spec.run_policy.checkpoint.interval_steps = 5
    job = cp.submit(j)
    cp.wait_for(job, "Running", timeout=240)

    # Let it make checkpointed progress before resizing.
    deadline = time.time() + 240
    while time.time() < deadline:
        cur = cp.get_job("elastic")
        if cur.status.metrics.step >= 6:
            break
        time.sleep(0.5)
    assert cur.status.metrics.step >= 6, "no training progress before resize"
    # Status metrics lag ~1s; 60 total steps leaves a wide window. If the
    # job is already near done the test setup regressed — fail loudly, not
    # flakily.
    assert cur.status.metrics.step < 40, "job too fast to resize reliably"

    # Spec-only update with optimistic retry: never write back stale status.
    from kubeflow_tpu.core.store import ConflictError

    for _ in range(10):
        fresh = cp.get_job("elastic")
        fresh.spec.replica_specs["worker"].replicas = 4
        fresh.spec.parallelism = ParallelismSpec(data=4)
        try:
            cp.store.update(fresh)
            break
        except ConflictError:
            time.sleep(0.05)
    else:
        pytest.fail("could not apply resize update")

    done = cp.wait_for(job, "Succeeded", timeout=420)
    assert done.status.metrics.step == 60
    assert done.status.restart_count == 0      # resize is not a failure
    ws = cp.store.list(Worker, label_selector={
        "training.tpu.kubeflow.dev/job-name": "elastic"})
    assert len(ws) == 4
    assert all(w.spec.num_workers == 4 for w in ws)
    # The resumed segment really started from the checkpoint, not step 0:
    # worker-0's log says so (trainer logs the resume step).
    log = tmp_path / "logs" / "default.elastic-worker-0.log"
    assert log.exists()
    assert "resumed from checkpoint at step" in log.read_text()


@pytest.mark.slow
def test_elastic_autoscale_e2e(cp):
    """The ElasticPolicy metric half ((U) training-operator hpa.go analog):
    a 1-worker elastic job auto-GROWS into free chips (scale_on_headroom),
    then auto-SHRINKS when another gang queues (yield_to_pending) — both
    through the real resize machinery (re-gang + resharded restore), with
    events and the auto-resize budget recorded on status."""
    from kubeflow_tpu.core.jobs import ElasticPolicy

    j = job_of(
        "llm_pretrain",
        {
            "model": "tiny",
            "steps": 80,
            "log_every": 2,
            "data": {"global_batch": 8, "seq_len": 64, "kind": "synthetic"},
        },
        name="auto",
        replicas=1,
        parallelism=None,                    # pure DP derives from workers
    )
    j.spec.elastic_policy = ElasticPolicy(
        min_replicas=1, max_replicas=2, max_restarts=4,
        scale_on_headroom=True, yield_to_pending=True,
        scale_cooldown_seconds=3.0)
    j.spec.run_policy.checkpoint.enabled = True
    j.spec.run_policy.checkpoint.interval_steps = 5
    job = cp.submit(j)
    cp.wait_for(job, "Running", timeout=240)

    # Phase 1: the cluster has 3 free chips -> the autoscaler should grow
    # the job to max_replicas=2 once it is Running past the cooldown.
    deadline = time.time() + 300
    while time.time() < deadline:
        cur = cp.get_job("auto")
        ws = cp.store.list(Worker, label_selector={
            "training.tpu.kubeflow.dev/job-name": "auto"})
        if (cur.spec.worker.replicas == 2 and len(ws) == 2
                and cur.status.has_condition("Running")):
            break
        time.sleep(0.5)
    else:
        raise AssertionError(
            f"never grew to 2 workers: replicas="
            f"{cp.get_job('auto').spec.worker.replicas}")
    assert cur.status.elastic_resizes == 1

    # Phase 2: a competing job that needs the remaining capacity queues ->
    # yield_to_pending shrinks the job back toward min.
    blocker = cp.submit(job_of("sleep", {"seconds": 25.0}, name="blocker",
                               replicas=3))
    deadline = time.time() + 300
    while time.time() < deadline:
        cur = cp.get_job("auto")
        if cur is None or cur.status.has_condition("Succeeded"):
            break                      # finished before the shrink landed
        if cur.spec.worker.replicas == 1 and cur.status.elastic_resizes >= 2:
            break
        time.sleep(0.5)
    cur = cp.get_job("auto")
    assert cur.spec.worker.replicas == 1 or cur.status.has_condition(
        "Succeeded"), "never yielded to the pending gang"

    done = cp.wait_for(job, "Succeeded", timeout=420)
    assert done.status.metrics.step == 80
    assert done.status.elastic_resizes >= 1
    # The resumed segments really restored (not restarted from step 0).
    log = cp.config.base_dir + "/logs/default.auto-worker-0.log"
    with open(log) as f:
        assert "resumed from checkpoint at step" in f.read()
    cp.wait_for(blocker, "Succeeded", timeout=240)


@pytest.mark.slow
def test_torch_adapter_distributed_e2e(cp):
    """Second-framework adapter (SURVEY.md §2.2#19, the XGBoost/Paddle
    controller analog): a 2-worker PyTorch job rendezvouses with gloo from
    the operator-injected cluster env, all-reduces gradients, reports
    through metrics.jsonl, and checkpoints — no framework-specific
    controller anywhere."""
    import json
    import os

    job = cp.submit(job_of(
        "torch_train",
        {"steps": 15, "batch": 16, "log_every": 1},
        name="torch",
        replicas=2,
        parallelism=ParallelismSpec(data=2),
    ))
    done = cp.wait_for(job, "Succeeded", timeout=240)
    workdir = os.path.join(cp.config.base_dir, "default", "torch",
                           "worker-0")
    mpath = os.path.join(workdir, "metrics.jsonl")
    rows = [json.loads(l) for l in open(mpath)]
    assert len(rows) == 15
    assert rows[-1]["loss"] < rows[0]["loss"], rows
    assert os.path.exists(os.path.join(workdir, "checkpoint.pt"))
    # The operator scraped the adapter's metrics like any JAX job's.
    assert done.status.metrics.loss is not None


@pytest.mark.slow
def test_elastic_autoscale_fsdp_e2e(cp):
    """Auto-resize of a NON-pure-DP job ((U) hpa.go scales worker counts
    regardless of inner strategy): an fsdp-sharded train auto-GROWS into
    free chips keeping its sharding strategy (data scales, fsdp preserved),
    then yields a step to a queued gang via the atomic in-place shrink —
    the yielding job keeps its placement (never goes Pending) and resumes
    resharded from checkpoint."""
    from kubeflow_tpu.core.jobs import ElasticPolicy

    j = job_of(
        "llm_pretrain",
        {"model": "tiny", "steps": 100, "log_every": 2,
         "data": {"global_batch": 8, "seq_len": 64, "kind": "synthetic"}},
        name="fauto", replicas=2,
        parallelism=ParallelismSpec(fsdp=2))
    j.spec.elastic_policy = ElasticPolicy(
        min_replicas=1, max_replicas=4, max_restarts=6,
        scale_on_headroom=True, yield_to_pending=True,
        scale_cooldown_seconds=3.0)
    j.spec.run_policy.checkpoint.enabled = True
    j.spec.run_policy.checkpoint.interval_steps = 5
    job = cp.submit(j)
    cp.wait_for(job, "Running", timeout=240)

    # Phase 1: grow 2 -> 4 preserving the fsdp axis (the data axis scales).
    deadline = time.time() + 300
    while time.time() < deadline:
        cur = cp.get_job("fauto")
        if (cur.spec.worker.replicas == 4
                and cur.status.has_condition("Running")):
            break
        time.sleep(0.5)
    else:
        pytest.fail("never grew to 4 workers")
    par = cur.spec.parallelism
    assert par.fsdp == 2 and par.data == 2 and par.total == 4, \
        f"fsdp axis lost on auto-grow: {par.axis_sizes()}"

    # Phase 2: a 1-chip gang queues -> one yield step (4 -> 3). The shrink
    # is atomic in place: the job must never lose its allocation.
    blocker = cp.submit(job_of("sleep", {"seconds": 20.0}, name="blk",
                               replicas=1))
    deadline = time.time() + 300
    while time.time() < deadline:
        cur = cp.get_job("fauto")
        if cur is None or cur.status.has_condition("Succeeded"):
            break
        if cur.spec.worker.replicas == 3:
            break
        time.sleep(0.5)
    cur = cp.get_job("fauto")
    if not cur.status.has_condition("Succeeded"):
        assert cur.spec.worker.replicas == 3, "never yielded to the waiter"
        assert cp.allocator.allocation("default/fauto") is not None, \
            "yielding job lost its placement"
        par = cur.spec.parallelism
        assert par.data * par.fsdp == 3 and par.model == 1
    cp.wait_for(blocker, "Succeeded", timeout=240)

    done = cp.wait_for(job, "Succeeded", timeout=600)
    assert done.status.metrics.step == 100
    log = cp.config.base_dir + "/logs/default.fauto-worker-0.log"
    with open(log) as f:
        assert "resumed from checkpoint at step" in f.read()
