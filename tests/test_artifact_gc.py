"""Artifact GC (VERDICT round-4 next #9): retention-prune the register,
retire matching lineage, mark-and-sweep the CAS — referenced blobs (incl.
shards deduped across versions) survive, dangling blobs go, lineage stays
readable, dry-run touches nothing. Plus the REST/CLI surface."""

import json
import os
import urllib.request

import pytest

from kubeflow_tpu.pipelines.artifacts import SCHEME, ArtifactStore
from kubeflow_tpu.pipelines.gc import collect_garbage


def _age(store, seconds=3600):
    """Backdate every blob so the grace window doesn't protect it."""
    import time

    past = time.time() - seconds
    for d2 in os.listdir(store.root):
        p2 = os.path.join(store.root, d2)
        if len(d2) == 2 and os.path.isdir(p2):
            for f in os.listdir(p2):
                os.utime(os.path.join(p2, f), (past, past))


def _publish_tree(store, tmp_path, name, version, files: dict):
    d = tmp_path / f"src-{name}-{version}"
    d.mkdir()
    for rel, content in files.items():
        (d / rel).write_bytes(content)
    cas = store.put_tree(str(d))
    store.register(name, version, cas)
    return cas


@pytest.fixture()
def store(tmp_path):
    return ArtifactStore(str(tmp_path / "artifacts"))


class TestRetentionAndSweep:
    def test_keep_last_prunes_old_versions_and_their_blobs(self, store,
                                                           tmp_path):
        # v1..v3 share "base" (dedup'd shard); each has a unique shard.
        shared = b"S" * 64
        for i in (1, 2, 3):
            _publish_tree(store, tmp_path, "m", str(i),
                          {"base": shared, "uniq": f"u{i}".encode() * 32})
        _age(store)
        rep = collect_garbage(store, keep_last=2, min_age_s=0)
        assert rep["pruned_versions"] == ["m@1"]
        assert store.versions("m") == ["2", "3"]
        # Shared shard survives (rooted by v2/v3); v1's unique shard gone.
        assert rep["swept_blobs"] == 2          # v1 manifest + u1 shard
        assert store.exists(store.lookup("m", "2"))
        assert store.exists(store.lookup("m", "3"))
        # Retained trees still fully materialize (every shard present).
        path = store.materialize_tree(store.lookup("m", "3"))
        assert (open(os.path.join(path, "base"), "rb").read() == shared)
        # The listing needs no "broken entry" degradation after platform GC.
        for v in store.versions("m"):
            assert store.describe(store.lookup("m", v))["kind"] == "tree"

    def test_dangling_unregistered_blobs_sweep(self, store):
        keep = store.put_bytes(b"registered" * 10)
        store.register("d", "1", keep)
        dangling = store.put_bytes(b"never registered" * 10)
        _age(store)
        rep = collect_garbage(store, min_age_s=0)
        assert not store.exists(dangling)
        assert store.exists(keep)
        assert rep["swept_blobs"] == 1

    def test_grace_window_protects_young_blobs(self, store):
        young = store.put_bytes(b"just written, register imminent")
        rep = collect_garbage(store, min_age_s=600)
        assert store.exists(young)
        assert rep["swept_blobs"] == 0

    def test_dry_run_deletes_nothing(self, store, tmp_path):
        for i in (1, 2, 3):
            _publish_tree(store, tmp_path, "m", str(i),
                          {"f": f"v{i}".encode() * 32})
        dangling = store.put_bytes(b"x" * 99)
        _age(store)
        rep = collect_garbage(store, keep_last=1, min_age_s=0, dry_run=True)
        assert rep["dry_run"] and rep["pruned_versions"] == ["m@1", "m@2"]
        assert rep["swept_blobs"] > 0
        # Nothing actually changed.
        assert store.versions("m") == ["1", "2", "3"]
        assert store.exists(dangling)

    def test_materialized_tree_of_swept_version_goes(self, store, tmp_path):
        cas = _publish_tree(store, tmp_path, "m", "1", {"f": b"z" * 64})
        _publish_tree(store, tmp_path, "m", "2", {"f": b"w" * 64})
        tree_dir = store.materialize_tree(cas)
        assert os.path.isdir(tree_dir)
        _age(store)
        os.utime(tree_dir, (os.path.getmtime(tree_dir) - 3600,) * 2)
        rep = collect_garbage(store, keep_last=1, min_age_s=0)
        assert rep["swept_trees"] == 1
        assert not os.path.isdir(tree_dir)


class TestLineageRoots:
    def test_id_gap_does_not_unroot_later_artifacts(self, store, tmp_path):
        """Regression (ADVICE r5, gc.py root discovery): enumeration must be
        a full scan, not an id probe that stops at the first gap — a gap
        used to silently unmark every later LIVE artifact and sweep its
        bytes."""
        from kubeflow_tpu.pipelines.metadata import ART_LIVE, MetadataStore

        md = MetadataStore(str(tmp_path / "md.db"), backend="python")
        try:
            first = store.put_bytes(b"early output" * 8)
            md.create_artifact("Dataset", uri=first, state=ART_LIVE)
            survivor = store.put_bytes(b"later output" * 8)
            aid2 = md.create_artifact("Dataset", uri=survivor, state=ART_LIVE)
            # Simulate a backend with an id gap (deletion support / id
            # reuse / alternate store): drop the first row outright.
            md._b._write("DELETE FROM artifacts WHERE id=?", (aid2 - 1,))
            assert md.get_artifact(aid2 - 1) is None       # the gap is real
            _age(store)
            collect_garbage(store, md, min_age_s=0)
            assert store.exists(survivor)                  # still rooted
        finally:
            md.close()

    def test_probe_fallback_refuses_on_count_mismatch(self, store):
        """Duck-typed stores without the scan API fall back to the id probe,
        but a store that can report a row count cross-checks it and refuses
        to sweep with an incomplete root set."""
        class GappyStore:
            def get_artifact(self, aid):
                return {"uri": "", "state": 0} if aid in (1, 3) else None

            def count_artifacts(self):
                return 2        # probe only reaches id 1

        with pytest.raises(RuntimeError, match="refusing to sweep"):
            collect_garbage(store, GappyStore(), min_age_s=0)

    def test_live_lineage_roots_blobs_and_retirement(self, store, tmp_path):
        from kubeflow_tpu.pipelines.metadata import (
            ART_DELETED, ART_LIVE, MetadataStore,
        )

        md = MetadataStore(str(tmp_path / "md.db"), backend="sqlite")
        try:
            # A pipeline-output blob, never registered: LIVE lineage keeps it.
            out_uri = store.put_bytes(b"pipeline output" * 8)
            aid = md.create_artifact("Dataset", uri=out_uri, state=ART_LIVE)
            # A registered model whose old version retention will prune.
            _publish_tree(store, tmp_path, "m", "1", {"f": b"a" * 64})
            old = store.lookup("m", "1")
            aid_old = md.create_artifact("Model", uri=old, state=ART_LIVE)
            _publish_tree(store, tmp_path, "m", "2", {"f": b"b" * 64})
            _age(store)
            rep = collect_garbage(store, md, keep_last=1, min_age_s=0)
            # The lineage-rooted output survived; the pruned version's
            # lineage row was retired (readable, state=DELETED), bytes gone.
            assert store.exists(out_uri)
            assert rep["retired_lineage"] == [aid_old]
            row = md.get_artifact(aid_old)
            assert row["state"] == ART_DELETED and row["uri"] == old
            assert md.get_artifact(aid)["state"] == ART_LIVE
            assert not store.exists(old)
        finally:
            md.close()


def _call(server, method, path, body=None, user=None):
    req = urllib.request.Request(server.url + path, data=body, method=method)
    if user:
        req.add_header("X-Kftpu-User", user)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


class TestGCSurface:
    @pytest.fixture()
    def api(self, tmp_path):
        from kubeflow_tpu.operator.control_plane import (
            ControlPlane, ControlPlaneConfig,
        )
        from kubeflow_tpu.platform.api_server import ApiServer
        from kubeflow_tpu.runtime.topology import Cluster, SliceTopology

        cp = ControlPlane(ControlPlaneConfig(
            base_dir=str(tmp_path),
            cluster=Cluster(slices=[SliceTopology(
                name="s0", generation="v5e", dims=(2, 2))]),
            launch_processes=False, metrics_sync_interval=None))
        server = ApiServer(cp, port=0)
        server.start()
        yield cp, server
        server.stop()

    def test_rest_gc_route(self, api, tmp_path):
        cp, server = api
        store = cp.artifact_store
        dangling = store.put_bytes(b"dangle" * 20)
        keep = store.put_bytes(b"keepme" * 20)
        store.register("k", "1", keep)
        _age(store)
        code, rep = _call(server, "POST", "/artifacts/gc",
                          json.dumps({"min_age_s": 0}).encode())
        assert code == 200, rep
        assert rep["swept_blobs"] >= 1
        assert not store.exists(dangling)
        assert store.exists(keep)

    def test_rest_gc_authz(self, api):
        from kubeflow_tpu.core.object import ObjectMeta
        from kubeflow_tpu.core.workspace_specs import Profile, ProfileSpec

        cp, server = api
        code, rep = _call(server, "POST", "/artifacts/gc", b"{}",
                          user="mallory@corp")
        assert code == 403
        cp.store.create(Profile(
            metadata=ObjectMeta(name="kubeflow", namespace="default"),
            spec=ProfileSpec(owner="admin@corp")))
        code, rep = _call(server, "POST", "/artifacts/gc",
                          json.dumps({"dry_run": True}).encode(),
                          user="admin@corp")
        assert code == 200, rep
        code, _ = _call(server, "POST", "/artifacts/gc", b"{}",
                        user="mallory@corp")
        assert code == 403

    def test_rest_gc_validates_body(self, api):
        _, server = api
        code, rep = _call(server, "POST", "/artifacts/gc",
                          json.dumps({"keep_last": 0}).encode())
        assert code == 400
        code, rep = _call(server, "POST", "/artifacts/gc", b"not json")
        assert code == 400

    def test_cli_gc(self, api, capsys, tmp_path):
        import kubeflow_tpu.cli as cli

        cp, server = api
        store = cp.artifact_store
        store.put_bytes(b"junk" * 50)
        _age(store)
        rc = cli.main(["artifacts", "gc", "--min-age", "0",
                       "--server", server.url])
        assert rc == 0
        out = capsys.readouterr().out
        assert "swept 1 blobs" in out
