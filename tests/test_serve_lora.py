"""Multi-tenant LoRA serving (serve/lora.py + engine integration).

The acceptance contract (ISSUE 14): greedy decode under every loaded
adapter is token-identical to a single-model engine running the MERGED
weights — dense and paged — while base traffic through the same batched
dispatch stays identical to a LoRA-free engine. Identity is pinned at
f32 compute (the factored delta and the merged matmul are mathematically
equal; bf16 rounds them differently, flipping argmax on near-ties —
documented, not pinned). Plus: registry hot-load/evict + per-owner
refcounts, per-adapter prefix-cache namespacing (tenants never share
KV), model-id routing signals, and the /metrics adapter series.
"""

import threading

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from kubeflow_tpu.core.serving import BatchingSpec, LoRASpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams
from kubeflow_tpu.serve.lora import (
    AdapterRegistry, AdapterSlotsExhausted, AdapterSpec, adapter_from_bytes,
    adapter_to_bytes, init_adapter_weights, merged_params, target_dims,
)

ALL_TARGETS = ("wq", "wk", "wv", "wo")


@pytest.fixture(scope="module")
def cfg():
    # f32 compute: factored-vs-merged identity is exact to ~1e-6 — bf16
    # would re-round the two (mathematically equal) paths differently.
    return preset("tiny", dtype="float32")


@pytest.fixture(scope="module")
def params(cfg):
    return init_decoder_params(jax.random.PRNGKey(0), cfg)


@pytest.fixture(scope="module")
def specs(cfg):
    # Adapter 0 targets the classic (wq, wv) pair; adapter 1 targets all
    # four projections; adapter 2 has a SMALLER rank than the packed cap
    # (the zero-pad path). All factors nonzero — a zero-delta adapter
    # would make every identity assertion vacuous.
    return [
        AdapterSpec("tenant-a", rank=4, alpha=8.0,
                    weights=init_adapter_weights(
                        jax.random.PRNGKey(11), cfg, 4, ("wq", "wv"))),
        AdapterSpec("tenant-b", rank=4, alpha=4.0,
                    weights=init_adapter_weights(
                        jax.random.PRNGKey(12), cfg, 4, ALL_TARGETS)),
        AdapterSpec("tenant-c", rank=2, alpha=8.0,
                    weights=init_adapter_weights(
                        jax.random.PRNGKey(13), cfg, 2, ("wq", "wv"))),
    ]


def mk_engine(cfg, params, *, paged: bool, lora_slots: int = 2,
              max_new_room: int = 128, kv_dtype=None):
    b = BatchingSpec(
        max_batch_size=4, max_seq_len=max_new_room,
        prefill_buckets=[16, 64], paged=paged, page_size=16,
        kv_cache_dtype=kv_dtype,
        lora=(LoRASpec(max_adapters=lora_slots, rank=4,
                       targets=ALL_TARGETS) if lora_slots else LoRASpec()))
    return LLMEngine(cfg, b, params=params)


def run_to_done(engine, req):
    while not req.done.is_set():
        engine.step()
    return req.result(5)


PROMPT = [5, 17, 3, 99, 42, 8, 8, 1]


@pytest.fixture(scope="module")
def merged_refs(cfg, params, specs):
    """name -> (dense tokens, paged tokens) from merged-weights engines
    — the single-model oracle the multi-adapter dispatch must match."""
    out = {}
    for spec in specs:
        mp = merged_params(params, cfg, spec)
        outs = []
        for paged in (False, True):
            eng = mk_engine(cfg, mp, paged=paged, lora_slots=0)
            outs.append(eng.generate(PROMPT,
                                     SamplingParams(max_new_tokens=10)))
        out[spec.name] = tuple(outs)
    return out


@pytest.fixture(scope="module")
def base_refs(cfg, params):
    out = []
    for paged in (False, True):
        eng = mk_engine(cfg, params, paged=paged, lora_slots=0)
        out.append(eng.generate(PROMPT, SamplingParams(max_new_tokens=10)))
    return tuple(out)


class TestTokenIdentity:
    @pytest.mark.parametrize("paged", [False, True])
    def test_every_adapter_matches_merged_reference(
            self, cfg, params, specs, merged_refs, base_refs, paged):
        """3 adapters through 2 packed slots (forces a hot-load + LRU
        evict mid-run): every output token-identical to its merged
        single-model reference, base traffic identical to a LoRA-free
        engine, zero adapter-slot leaks."""
        eng = mk_engine(cfg, params, paged=paged, lora_slots=2)
        for s in specs:
            eng._lora.register(s)
        base = eng.generate(PROMPT, SamplingParams(max_new_tokens=10))
        assert base == base_refs[int(paged)], \
            "base traffic must be bit-identical to a LoRA-free engine"
        for s in specs:
            got = run_to_done(eng, eng.submit(
                PROMPT, SamplingParams(max_new_tokens=10), adapter=s.name))
            want = merged_refs[s.name][int(paged)]
            assert got == want, (s.name, got, want)
            assert got != base, "adapter must actually change the output"
        assert eng._lora.stats["evictions"] >= 1, \
            "3 adapters over 2 slots must have evicted"
        eng._lora.assert_quiescent()
        if paged:
            eng._allocator.assert_quiescent()

    @pytest.mark.slow  # tier-1 budget: 3 merged-reference engines on an int8 pool
    def test_int8_kv_every_adapter_matches_merged_reference(
            self, cfg, params, specs):
        """Tentpole pin (quantized base + f32 LoRA deltas): the int8
        paged pool under multi-adapter decode. Adapter K/V deltas apply
        BEFORE the pool write, so both the factored and the merged
        engine quantize the same K/V values — greedy output must stay
        token-identical through the int8 rounding, and base traffic
        identical to a LoRA-free int8 engine."""
        eng = mk_engine(cfg, params, paged=True, lora_slots=2,
                        kv_dtype="int8")
        for s in specs:
            eng._lora.register(s)
        base_ref = mk_engine(cfg, params, paged=True, lora_slots=0,
                             kv_dtype="int8").generate(
            PROMPT, SamplingParams(max_new_tokens=10))
        base = eng.generate(PROMPT, SamplingParams(max_new_tokens=10))
        assert base == base_ref, \
            "base traffic must match a LoRA-free int8 engine"
        for s in specs:
            got = run_to_done(eng, eng.submit(
                PROMPT, SamplingParams(max_new_tokens=10), adapter=s.name))
            ref = mk_engine(cfg, merged_params(params, cfg, s), paged=True,
                            lora_slots=0, kv_dtype="int8")
            want = ref.generate(PROMPT, SamplingParams(max_new_tokens=10))
            assert got == want, (s.name, got, want)
            assert got != base, "adapter must actually change the output"
        eng._lora.assert_quiescent()
        eng._allocator.assert_quiescent()

    def test_mixed_batch_decodes_concurrently(self, cfg, params, specs,
                                              merged_refs, base_refs):
        """One BATCHED dispatch serves base + two different adapters in
        neighboring slots without cross-talk (the whole point of the
        packed gather: no per-tenant dispatch)."""
        eng = mk_engine(cfg, params, paged=True, lora_slots=2)
        for s in specs[:2]:
            eng._lora.register(s)
        reqs = [
            eng.submit(PROMPT, SamplingParams(max_new_tokens=10)),
            eng.submit(PROMPT, SamplingParams(max_new_tokens=10),
                       adapter="tenant-a"),
            eng.submit(PROMPT, SamplingParams(max_new_tokens=10),
                       adapter="tenant-b"),
        ]
        while not all(r.done.is_set() for r in reqs):
            eng.step()
        assert reqs[0].output_tokens == list(base_refs[1])
        assert reqs[1].output_tokens == list(merged_refs["tenant-a"][1])
        assert reqs[2].output_tokens == list(merged_refs["tenant-b"][1])
        eng._lora.assert_quiescent()
        eng._allocator.assert_quiescent()

    def test_chunked_prefill_applies_adapter(self, cfg, params, specs):
        """A prompt long enough to chunk (paged admission always chunks;
        48 tokens = 3 pages) prefills THROUGH the adapter — the delta
        applies to prompt KV, not just decode steps."""
        spec = specs[0]
        long_prompt = [(7 * i) % 250 + 1 for i in range(48)]
        eng = mk_engine(cfg, params, paged=True, lora_slots=2)
        eng._lora.register(spec)
        got = run_to_done(eng, eng.submit(
            long_prompt, SamplingParams(max_new_tokens=8),
            adapter=spec.name))
        ref = mk_engine(cfg, merged_params(params, cfg, spec), paged=True,
                        lora_slots=0)
        want = ref.generate(long_prompt, SamplingParams(max_new_tokens=8))
        assert got == want
        eng._lora.assert_quiescent()


class TestPrefixIsolation:
    @pytest.mark.slow  # tier-1 budget: ~9s; lora_smoke gates KV namespacing
    def test_adapters_never_share_kv(self, cfg, params, specs):
        """Same prompt under adapter A, adapter B, then A again and
        base, on a radix prefix-cache engine: only the same-adapter
        re-arrival may hit the index; every output still matches its
        merged reference (no cross-tenant KV reuse)."""
        prompt = list(range(2, 50))
        eng = mk_engine(cfg, params, paged=True, lora_slots=2)
        for s in specs[:2]:
            eng._lora.register(s)
        order = ["tenant-a", "tenant-b", "tenant-a", None]
        outs = []
        for name in order:
            outs.append(run_to_done(eng, eng.submit(
                prompt, SamplingParams(max_new_tokens=8), adapter=name)))
        tier = eng.kv_tier_stats()
        assert tier["prefix_queries"] == 4
        assert tier["prefix_hits"] == 1, \
            "only the tenant-a re-arrival may match the index"
        for name, got in zip(order, outs):
            if name is None:
                continue
            ref = mk_engine(cfg, merged_params(
                params, cfg, next(s for s in specs if s.name == name)),
                paged=True, lora_slots=0)
            assert got == ref.generate(prompt,
                                       SamplingParams(max_new_tokens=8))
        assert outs[0] == outs[2] and outs[0] != outs[1] != outs[3]
        eng._lora.assert_quiescent()
        eng._allocator.assert_quiescent()

    def test_flat_hash_namespacing(self):
        """PageAllocator.chain_keys: the namespace salts the chain root,
        so the flat cache can never cross-match adapters either."""
        from kubeflow_tpu.serve.paged import PageAllocator

        toks = list(range(32))
        base = PageAllocator.chain_keys(toks, 16)
        ns = PageAllocator.chain_keys(toks, 16, namespace="tenant-a")
        assert base != ns
        assert PageAllocator.chain_keys(toks, 16, namespace="tenant-a") == ns
        assert PageAllocator.chain_keys(toks, 16) == base


class TestRegistry:
    def test_acquire_release_lru_evict(self, cfg):
        reg = AdapterRegistry(cfg, max_adapters=2, rank=4)
        for i in range(3):
            reg.register(AdapterSpec(
                f"a{i}", rank=4,
                weights=init_adapter_weights(jax.random.PRNGKey(i), cfg, 4)))
        s0, hot0 = reg.acquire("a0", owner="r0")
        assert hot0 and reg.resident() == ["a0"]
        s1, _ = reg.acquire("a1", owner="r1")
        assert s0 != s1
        reg.release("a0")
        reg.release("a1")
        # a0 is LRU among ref-0 residents: a2 evicts it, not a1.
        s2, hot2 = reg.acquire("a2", owner="r2")
        assert hot2 and s2 == s0
        assert set(reg.resident()) == {"a1", "a2"}
        assert reg.stats["evictions"] == 1
        # re-acquire of a resident adapter is a hit, not a load
        _, hot1b = reg.acquire("a1", owner="r3")
        assert not hot1b
        reg.release("a1")
        reg.release("a2")
        reg.assert_quiescent()

    def test_referenced_adapters_never_evict(self, cfg):
        reg = AdapterRegistry(cfg, max_adapters=2, rank=4)
        for i in range(3):
            reg.register(AdapterSpec(
                f"a{i}", rank=4,
                weights=init_adapter_weights(jax.random.PRNGKey(i), cfg, 4)))
        reg.acquire("a0", owner="r0")
        reg.acquire("a1", owner="r1")
        with pytest.raises(AdapterSlotsExhausted):
            reg.acquire("a2", owner="r2")
        reg.release("a0")
        reg.acquire("a2", owner="r2")      # now a0's slot frees up
        assert set(reg.resident()) == {"a1", "a2"}

    def test_unknown_adapter_keyerror(self, cfg):
        reg = AdapterRegistry(cfg, max_adapters=2, rank=4)
        with pytest.raises(KeyError):
            reg.acquire("nope")

    def test_rank_cap(self, cfg):
        reg = AdapterRegistry(cfg, max_adapters=2, rank=4)
        with pytest.raises(ValueError):
            reg.register(AdapterSpec("big", rank=8))

    def test_quiescence_names_leaker(self, cfg, monkeypatch):
        import kubeflow_tpu.runtime.sanitize as sanitize

        monkeypatch.setattr(sanitize, "enabled",
                            lambda mode=None: True)
        reg = AdapterRegistry(cfg, max_adapters=2, rank=4)
        reg.register(AdapterSpec(
            "a0", rank=4,
            weights=init_adapter_weights(jax.random.PRNGKey(0), cfg, 4)))
        reg.acquire("a0", owner="req-leaky")
        assert reg.leak_report_by_owner() == {"req-leaky": 1}
        with pytest.raises(AssertionError, match="req-leaky"):
            reg.assert_quiescent()
        reg.release("a0")
        reg.assert_quiescent()

    def test_packed_bytes_and_dims(self, cfg):
        reg = AdapterRegistry(cfg, max_adapters=4, rank=8,
                              targets=ALL_TARGETS)
        assert reg.packed_bytes() > 0
        d = cfg.hidden
        assert target_dims(cfg, "wq") == (d, cfg.n_heads * cfg.head_dim)
        assert target_dims(cfg, "wk") == (d, cfg.n_kv_heads * cfg.head_dim)
        assert target_dims(cfg, "wo") == (cfg.n_heads * cfg.head_dim, d)
        with pytest.raises(ValueError):
            target_dims(cfg, "mlp_up")


class TestArtifactRoundTrip:
    def test_bytes_round_trip(self, cfg):
        w = init_adapter_weights(jax.random.PRNGKey(3), cfg, 4,
                                 ("wq", "wv"))
        blob = adapter_to_bytes(w, rank=4, alpha=12.0)
        spec = adapter_from_bytes("t", blob)
        assert spec.rank == 4 and spec.alpha == 12.0
        for t in ("wq", "wv"):
            np.testing.assert_array_equal(spec.weights[t][0], w[t][0])
            np.testing.assert_array_equal(spec.weights[t][1], w[t][1])

    def test_store_pull_is_lazy(self, cfg, tmp_path):
        from kubeflow_tpu.pipelines.artifacts import ArtifactStore
        from kubeflow_tpu.serve.lora import adapter_spec_from_store

        store = ArtifactStore(str(tmp_path))
        w = init_adapter_weights(jax.random.PRNGKey(4), cfg, 4)
        uri = store.put_bytes(adapter_to_bytes(w, rank=4, alpha=16.0))
        store.register("tenant-x", "1", uri)
        spec = adapter_spec_from_store(store, "tenant-x",
                                       "artifact://tenant-x", rank=4)
        assert spec.weights is None          # nothing pulled yet
        got = spec.resolve_weights()
        np.testing.assert_array_equal(got["wq"][0], w["wq"][0])


class TestEngineLifecycle:
    def test_submit_unknown_adapter_404s(self, cfg, params):
        eng = mk_engine(cfg, params, paged=False, lora_slots=2)
        with pytest.raises(KeyError):
            eng.submit(PROMPT, adapter="nobody")
        # LoRA-free engines reject every adapter id the same way.
        bare = mk_engine(cfg, params, paged=False, lora_slots=0)
        with pytest.raises(KeyError):
            bare.submit(PROMPT, adapter="tenant-a")

    def test_slot_backpressure_requeues(self, cfg, params, specs):
        """Every adapter slot referenced by a live request: the next
        adapter's request WAITS (requeued, not failed) and completes
        once a slot drains."""
        eng = mk_engine(cfg, params, paged=False, lora_slots=1)
        for s in specs[:2]:
            eng._lora.register(s)
        r1 = eng.submit(PROMPT, SamplingParams(max_new_tokens=6),
                        adapter="tenant-a")
        r2 = eng.submit(PROMPT, SamplingParams(max_new_tokens=6),
                        adapter="tenant-b")
        while not (r1.done.is_set() and r2.done.is_set()):
            eng.step()
        assert r1.finish_reason == "length"
        assert r2.finish_reason == "length"
        assert eng._lora.stats["evictions"] == 1
        eng._lora.assert_quiescent()

    @pytest.mark.slow  # tier-1 budget (ISSUE 20): ~9s; quiescence +
    # refcount discipline stays fast via the other lifecycle tests
    def test_cancel_releases_adapter_ref(self, cfg, params, specs):
        eng = mk_engine(cfg, params, paged=True, lora_slots=2)
        eng._lora.register(specs[0])
        req = eng.submit(PROMPT, SamplingParams(max_new_tokens=64),
                         adapter="tenant-a")
        eng.step()                      # admit + start decoding
        req.cancel()
        while not req.done.is_set():
            eng.step()
        assert req.finish_reason == "cancelled"
        eng._lora.assert_quiescent()
        eng._allocator.assert_quiescent()

    def test_adapter_load_phase_on_trace(self, cfg, params, specs):
        from kubeflow_tpu.obs.trace import get_tracer, phase_durations

        tracer = get_tracer()
        tracer.reset()
        eng = mk_engine(cfg, params, paged=True, lora_slots=2)
        eng._lora.register(specs[0])
        root = tracer.start_span("test.request")
        req = eng.submit(PROMPT, SamplingParams(max_new_tokens=4),
                         adapter="tenant-a", trace_parent=root)
        run_to_done(eng, req)
        root.end("ok")
        tr = tracer.trace(root.trace_id)
        ph = phase_durations(tr["spans"])
        assert "adapter_load_ms" in ph, ph
        # Resident now: a second request must NOT pay the load phase.
        root2 = tracer.start_span("test.request2")
        req2 = eng.submit(PROMPT, SamplingParams(max_new_tokens=4),
                          adapter="tenant-a", trace_parent=root2)
        run_to_done(eng, req2)
        root2.end("ok")
        ph2 = phase_durations(tracer.trace(root2.trace_id)["spans"])
        assert "adapter_load_ms" not in ph2, ph2


class TestRoutingSignals:
    def test_metrics_registry_renders_adapter_series(self, cfg, params,
                                                     specs):
        from kubeflow_tpu.obs.registry import parse_exposition
        from kubeflow_tpu.serve.server import serving_metrics_registry

        eng = mk_engine(cfg, params, paged=False, lora_slots=2)
        eng._lora.register(specs[0])
        run_to_done(eng, eng.submit(PROMPT,
                                    SamplingParams(max_new_tokens=4),
                                    adapter="tenant-a"))
        text = serving_metrics_registry([("m", eng)]).render()
        samples = {(n, labels.get("adapter")): v
                   for n, labels, v in parse_exposition(text)}
        assert samples[("kftpu_engine_adapters_resident", "tenant-a")] == 1
        assert samples[("kftpu_engine_adapter_loads_total", None)] == 1
        assert samples[("kftpu_engine_adapter_evictions_total", None)] == 0
        # LoRA-free engines still render the series (0 / no labels) so
        # the loadgen's ATTRIBUTION_SERIES pin holds fleet-wide.
        bare = mk_engine(cfg, params, paged=False, lora_slots=0)
        names = {n for n, _, _ in parse_exposition(
            serving_metrics_registry([("m", bare)]).render())}
        assert "kftpu_engine_adapters_resident" in names

    def test_router_parses_adapter_residency(self):
        from kubeflow_tpu.serve.router import Router

        text = (
            "kftpu_engine_adapters_resident{model=\"m\","
            "adapter=\"tenant-a\"} 1\n"
            "kftpu_engine_adapters_resident{model=\"m\","
            "adapter=\"tenant-b\"} 1\n"
            "kftpu_serving_in_flight 2\n")
        sig = Router._parse_signals(text)
        assert sig["adapters"] == {"tenant-a", "tenant-b"}
        assert sig["in_flight"] == 2.0

    def test_pick_prefers_warm_backend(self):
        from kubeflow_tpu.serve.router import Router

        router = Router(port=0)
        router.start()        # stop() joins serve_forever — it must run
        try:
            urls = ["http://127.0.0.1:9001", "http://127.0.0.1:9002",
                    "http://127.0.0.1:9003"]
            router.set_backends({"latest": urls})
            router.note_signals(urls[1], {"adapters": {"tenant-a"}})
            picks = {router.pick(model="tenant-a") for _ in range(6)}
            assert picks == {urls[1]}, \
                "the warm backend must win while it is the only one"
            # Nobody has tenant-z hot: the pick falls back to the whole
            # rotation (and thereby warms someone).
            cold = {router.pick(model="tenant-z") for _ in range(6)}
            assert cold == set(urls)
            # Two warm backends round-robin.
            router.note_signals(urls[2], {"adapters": {"tenant-a"}})
            two = {router.pick(model="tenant-a") for _ in range(6)}
            assert two == {urls[1], urls[2]}
        finally:
            router.stop()


class TestKvPressure:
    def test_pressure_fn_overrides_pool_rule(self):
        """ISSUE 14 en passant: demotion urgency is pluggable — the
        default reproduces the quarter-pool rule exactly, and an
        injected pressure (the engine folds queue-delay-vs-budget and
        adapter hot-load backpressure into it) flips tick into urgent
        mode regardless of the free-list level."""
        from kubeflow_tpu.serve.kvtier import RadixPrefixIndex
        from kubeflow_tpu.serve.paged import PageAllocator

        alloc = PageAllocator(16, 4)
        idx = RadixPrefixIndex(alloc, 4)
        try:
            assert idx.pressure() < 1.0           # empty pool: calm
            held = alloc.alloc(13)                # available 3 <= 16//4
            assert idx.pressure() >= 1.0          # the classic rule
            alloc.free(held)
        finally:
            idx.close()
        hot = {"x": 0.0}
        idx2 = RadixPrefixIndex(alloc, 4, pressure_fn=lambda: hot["x"])
        try:
            assert idx2.pressure() == 0.0
            hot["x"] = 2.0
            assert idx2.pressure() == 2.0         # external signal wins
        finally:
            idx2.close()

    def test_engine_pressure_folds_adapter_backpressure(self, cfg,
                                                        params, specs):
        eng = mk_engine(cfg, params, paged=True, lora_slots=1)
        eng._lora.register(specs[0])
        assert eng._kv_pressure() < 1.0
        # Every adapter slot referenced + a waiting backlog: urgent.
        eng._lora.acquire(specs[0].name, owner="r0")
        eng.submit([1, 2, 3])
        eng._drain_waiting()
        assert eng._kv_pressure() >= 1.0
        eng._lora.release(specs[0].name)
        assert eng._kv_pressure() < 1.0


class TestServerRouting:
    @pytest.fixture()
    def server(self, cfg, params, specs):
        from kubeflow_tpu.serve.server import ModelServer

        eng = mk_engine(cfg, params, paged=False, lora_slots=2)
        for s in specs[:2]:
            eng._lora.register(s)
        srv = ModelServer("base", eng, port=0)
        srv.start()
        yield srv
        srv.stop()

    def _post(self, srv, body, headers=None):
        import http.client
        import json as _json

        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        try:
            conn.request("POST", "/v1/completions", body=_json.dumps(body),
                         headers={"Content-Type": "application/json",
                                  **(headers or {})})
            resp = conn.getresponse()
            return resp.status, _json.loads(resp.read() or b"{}")
        finally:
            conn.close()

    @pytest.mark.slow  # tier-1 budget: full HTTP server + 3 generations
    def test_model_field_header_and_404(self, server, cfg, params, specs):
        from kubeflow_tpu.core.headers import MODEL_HEADER

        base_prompt = "hello tenants"
        status, obj = self._post(server, {"prompt": base_prompt,
                                          "max_tokens": 6})
        assert status == 200
        base_text = obj["choices"][0]["text"]
        # body "model" field routes to the adapter
        status, obj = self._post(server, {"prompt": base_prompt,
                                          "max_tokens": 6,
                                          "model": "tenant-a"})
        assert status == 200
        adapted = obj["choices"][0]["text"]
        assert adapted != base_text
        # the header overrides the body field
        status, obj = self._post(
            server, {"prompt": base_prompt, "max_tokens": 6,
                     "model": "tenant-b"},
            headers={MODEL_HEADER: "tenant-a"})
        assert status == 200
        assert obj["choices"][0]["text"] == adapted
        # unknown ids 404 — never a silent base fallthrough
        status, obj = self._post(server, {"prompt": base_prompt,
                                          "max_tokens": 6,
                                          "model": "tenant-zzz"})
        assert status == 404
        # /v1/models lists base + adapters
        import json as _json
        import urllib.request

        with urllib.request.urlopen(server.url + "/v1/models",
                                    timeout=10) as r:
            models = _json.loads(r.read())["models"]
        assert set(models) == {"base", "tenant-a", "tenant-b"}
