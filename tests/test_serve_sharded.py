"""Tensor-parallel (mesh-mode) serving: a TP-sharded engine must reproduce
the single-device engine EXACTLY — same tokens, same continuous-batching
behavior — with weights and KV cache actually distributed over the mesh.

The TPU-native analog of vLLM's ``tensor_parallel_size`` serving path ((U)
kserve python/huggingfaceserver; SURVEY.md §2.3#27): GSPMD partitions the
same jitted dispatches; no separate "distributed engine" codebase exists to
drift from the single-chip one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip(
    "jax.experimental.pallas",
    reason="Pallas unavailable: the sharded prefill path's kernels need it")
from kubeflow_tpu.compat import HAS_SHARD_MAP  # noqa: E402

if not HAS_SHARD_MAP:
    pytest.skip("this jax has no shard_map (native or experimental)",
                allow_module_level=True)

from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.runtime.mesh import build_mesh
from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams


@pytest.fixture(scope="module")
def cfg():
    # 4 q heads, 2 kv heads: tp=2 divides both. fp32 activations for the
    # token-exact tests: sharding changes GSPMD's collective decomposition,
    # which legitimately shifts bf16 rounding by one ulp (measured ~0.016 at
    # tp=4) — enough to flip argmax on a random-init 256-vocab model. In
    # fp32 the reduction-order noise is ~1e-6 against ~0.2 logit gaps.
    return preset("tiny", dtype="float32")


@pytest.fixture(scope="module")
def params(cfg):
    return init_decoder_params(jax.random.PRNGKey(0), cfg)


def mk_engine(cfg, params, *, tp=1, **kw):
    batching = BatchingSpec(max_batch_size=4, max_seq_len=96,
                            prefill_buckets=[16, 32, 64], **kw)
    mesh = None
    if tp > 1:
        mesh = build_mesh({"model": tp}, jax.devices()[:tp])
    return LLMEngine(cfg, batching, params=params, seed=0, mesh=mesh)


PROMPTS = [[5, 17, 3, 99, 42], [7] * 20, [9, 8, 7, 6, 5, 4], [30, 31]]


def run_all(engine, sampling=None):
    sampling = sampling or SamplingParams(max_new_tokens=10)
    reqs = [engine.submit(p, sampling) for p in PROMPTS]
    while not all(r.done.is_set() for r in reqs):
        engine.step()
    return [r.output_tokens for r in reqs]


@pytest.mark.slow  # tier-1 budget (ISSUE 14): slowest fast tests re-marked
def test_tp2_matches_single_device_greedy(cfg, params):
    want = run_all(mk_engine(cfg, params))
    got = run_all(mk_engine(cfg, params, tp=2))
    assert got == want


@pytest.mark.slow  # tier-1 budget (ISSUE 14): slowest fast tests re-marked
def test_tp4_matches_single_device_greedy(cfg, params):
    want = run_all(mk_engine(cfg, params))
    got = run_all(mk_engine(cfg, params, tp=4))
    assert got == want


def test_tp2_weights_and_cache_are_distributed(cfg, params):
    eng = mk_engine(cfg, params, tp=2)
    # A TP-split weight (wq: [L, D, H, Dh] sharded on heads) must place half
    # the array on each device — the whole point is escaping one chip's HBM.
    wq = eng.params["layers"]["attn"]["wq"]
    shard_shapes = {s.data.shape for s in wq.addressable_shards}
    assert shard_shapes == {wq.shape[:2] + (wq.shape[2] // 2, wq.shape[3])}
    ck = eng.cache["k"]
    assert {s.data.shape[3] for s in ck.addressable_shards} == \
        {ck.shape[3] // 2}
    # And serving still works end to end.
    out = eng.generate(PROMPTS[0], SamplingParams(max_new_tokens=6))
    assert len(out) == 6


@pytest.mark.slow  # tier-1 budget (ISSUE 14): slowest fast tests re-marked
def test_tp2_sampled_matches_single_device(cfg, params):
    """Same PRNG seed => identical sampled streams: sharding must not change
    sampling semantics (threefry values are placement-invariant)."""
    sp = SamplingParams(max_new_tokens=8, temperature=0.8, top_k=20,
                        top_p=0.9)
    want = run_all(mk_engine(cfg, params), sp)
    got = run_all(mk_engine(cfg, params, tp=2), sp)
    assert got == want


@pytest.mark.slow  # tier-1 budget: tp=2 paged engine compile, ~9s;
# tp2_chunked_prefill keeps the sharded-identity lane in tier-1
def test_tp2_paged_matches_single_device(cfg, params):
    dense = mk_engine(cfg, params, paged=True, page_size=16,
                      chunked_prefill_tokens=16)
    sharded = mk_engine(cfg, params, tp=2, paged=True, page_size=16,
                        chunked_prefill_tokens=16)
    want = run_all(dense)
    got = run_all(sharded)
    assert got == want
    for eng in (dense, sharded):
        assert eng.kv_pages_in_use() == 0
        eng._allocator.assert_quiescent()


def test_tp2_chunked_prefill_matches(cfg, params):
    """Long prompt through the chunked-prefill path, sharded vs not."""
    sp = SamplingParams(max_new_tokens=6)
    prompt = list(np.arange(70) % cfg.vocab_size)
    want = mk_engine(cfg, params,
                     chunked_prefill_tokens=32).generate(prompt, sp)
    got = mk_engine(cfg, params, tp=2,
                    chunked_prefill_tokens=32).generate(prompt, sp)
    assert got == want


def test_tp2_bf16_serves(params):
    """The production dtype (bf16 activations) through the sharded path —
    smoke only: one-ulp rounding differs by collective decomposition, so
    token-exactness is pinned in fp32 above."""
    cfgb = preset("tiny")
    pb = init_decoder_params(jax.random.PRNGKey(0), cfgb)
    out = mk_engine(cfgb, pb, tp=2).generate(
        PROMPTS[0], SamplingParams(max_new_tokens=6))
    assert len(out) == 6


def test_gqa_nondivisible_kv_replicates(params):
    """1 kv head under tp=2: the cache replicates (heads still split) and
    generation still matches the unsharded engine."""
    cfg1 = preset("tiny-gemma", dtype="float32")     # n_kv_heads=1
    p1 = init_decoder_params(jax.random.PRNGKey(1), cfg1)
    want = mk_engine(cfg1, p1).generate(PROMPTS[0],
                                        SamplingParams(max_new_tokens=6))
    eng = mk_engine(cfg1, p1, tp=2)
    assert eng._cache_sh.spec == jax.sharding.PartitionSpec()
    got = eng.generate(PROMPTS[0], SamplingParams(max_new_tokens=6))
    assert got == want


@pytest.mark.slow  # tier-1 budget (ISSUE 14): slowest fast tests re-marked
def test_tp2_flash_prefill_matches(cfg, params):
    """Forced pallas prefill under the TP mesh: the flash kernel runs
    per-shard via shard_map (Mosaic can't be GSPMD-partitioned) and must
    match the single-device pallas engine token-exactly."""
    want = run_all(mk_engine(cfg, params, prefill_attn_impl="pallas"))
    got = run_all(mk_engine(cfg, params, tp=2, prefill_attn_impl="pallas"))
    assert got == want
