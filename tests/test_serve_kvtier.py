"""Tiered KV cache (serve/kvtier.py): radix prefix index with live
copy-on-write sharing + host-RAM overflow tier.

Unit level drives the index against a real ``PageAllocator`` with fake
device closures; engine level pins the acceptance contracts — greedy
output token-identical with sharing+tiering on vs. off, conversation
reuse across slot release, COW on sub-page divergence, demote→promote
round trips, and per-owner refcount balance after everything."""

import time

import jax
import numpy as np
import pytest

from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams
from kubeflow_tpu.serve.handoff import pages_from_wire, pages_to_wire
from kubeflow_tpu.serve.kvtier import RadixPrefixIndex
from kubeflow_tpu.serve.paged import PageAllocator

PG = 4


class FakeDevice:
    """Records the device traffic the index would have enqueued."""

    def __init__(self, layers=2, kv=1, dh=2):
        self.shape = (layers, PG, kv, dh)
        self.copies: list = []
        self.uploads: list = []
        self.fetches: list = []

    def page_block(self, page: int) -> np.ndarray:
        return np.full(self.shape, float(page), np.float32)

    def copy_pages(self, src, dst):
        self.copies.append((list(src), list(dst)))

    def upload_pages(self, ids, k, v):
        self.uploads.append((list(ids), k, v))

    def fetch_pages(self, ids):
        self.fetches.append(list(ids))
        k = np.stack([self.page_block(p) for p in ids], axis=1)
        return k, k.copy()


def mk_index(num_pages=16, **kw):
    alloc = PageAllocator(num_pages, PG, enable_prefix_caching=True)
    dev = FakeDevice()
    idx = RadixPrefixIndex(
        alloc, PG, copy_pages_fn=dev.copy_pages,
        upload_pages_fn=dev.upload_pages, fetch_pages_fn=dev.fetch_pages,
        **kw)
    return idx, alloc, dev


class TestRadixIndex:
    def test_full_block_match_capped_one_short(self):
        idx, alloc, dev = mk_index()
        toks = list(range(1, 13))             # 3 full pages of content
        pages = alloc.alloc(3, owner="a")
        idx.insert(toks, pages, 12)
        # Identical prompt: cap keeps >= 1 token to prefill — with
        # 12 tokens that caps the FULL-block walk at 2 pages, then the
        # COW tail picks up 3 of the last block's tokens (11 total).
        hit, covered = idx.match_and_acquire(toks, owner="b")
        assert hit[:2] == pages[:2]
        assert covered == 11
        assert len(hit) == 3 and hit[2] not in pages     # COW tail page
        assert dev.copies == [([pages[2]], [hit[2]])]
        # Live sharing: owner a never released; refs are per sharer.
        assert alloc.ref(pages[0]) == 2
        alloc.free(hit)
        alloc.free(pages)
        assert alloc.in_use() == 0
        alloc.assert_quiescent()

    def test_cap_excludes_last_token_exactly(self):
        idx, alloc, _ = mk_index()
        toks = list(range(1, 9))              # 2 full pages
        pages = alloc.alloc(2, owner="a")
        idx.insert(toks, pages, 8)
        # Page-aligned query: one token short -> only 1 full block +
        # 3-token COW; a query one token LONGER shares both full pages.
        _, covered = idx.match_and_acquire(toks, owner="b")
        assert covered == 7
        _, covered2 = idx.match_and_acquire(toks + [99], owner="c")
        assert covered2 == 8

    def test_divergence_cow_copies_partial_tail(self):
        idx, alloc, dev = mk_index()
        toks = [1, 2, 3, 4, 5, 6, 7, 8]
        pages = alloc.alloc(2, owner="a")
        idx.insert(toks, pages, 8)
        alloc.free(pages)                      # a released: cached now
        # Diverges 2 tokens into the second block.
        q = [1, 2, 3, 4, 5, 6, 99, 98, 97]
        hit, covered = idx.match_and_acquire(q, owner="b")
        assert covered == PG + 2
        assert hit[0] == pages[0] and hit[1] != pages[1]
        assert dev.copies[-1] == ([pages[1]], [hit[1]])
        assert alloc.ref(pages[1]) == 0        # source stays cached

    def test_partial_leaf_upgrade_in_place(self):
        idx, alloc, _ = mk_index()
        toks = [1, 2, 3, 4, 5, 6]
        pages = alloc.alloc(2, owner="a")
        idx.insert(toks, pages, 6)             # partial leaf: (5, 6)
        idx.insert(toks + [7], pages, 7)       # same page, more content
        hit, covered = idx.match_and_acquire(toks + [7, 8, 9], owner="b")
        assert covered == PG + 3               # upgraded claim matched
        assert len(hit) == 2
        assert hit[1] != pages[1]              # tail rode a COW copy

    def test_eviction_cascades_subtree(self):
        idx, alloc, _ = mk_index(num_pages=4)
        toks = list(range(1, 17))              # 4 full pages
        pages = alloc.alloc(4, owner="a")
        idx.insert(toks, pages, 16)
        alloc.free(pages)                      # all cached, LRU order
        assert alloc.cached() == 4
        # Pool pressure: allocating everything must evict the cached
        # chain; the on_evict callback drops nodes + cascades children.
        fresh = alloc.alloc(4, owner="b")
        assert len(fresh) == 4
        assert idx.stats["evictions"] >= 1
        assert idx.stats["nodes"] == 0
        hit, covered = idx.match_and_acquire(toks + [99], owner="c")
        assert hit == [] and covered == 0
        alloc.free(fresh)
        alloc.assert_quiescent()

    def test_cow_alloc_evicting_its_own_source_misses_cleanly(self):
        """Pool-pressure regression: ``_cow_tail``'s alloc reclaims
        ref-0 indexed pages via the eviction callback — under a full
        pool the coldest cached page IS the COW source, which then
        arrives at the copy DEAD. The match must degrade to the
        full-block hit (no exception, no stranded fresh page), not
        throw and forfeit the whole prefix."""
        idx, alloc, dev = mk_index(num_pages=2)
        toks = [1, 2, 3, 4, 5, 6, 7, 8]
        pages = alloc.alloc(2, owner="a")
        idx.insert(toks, pages, 8)
        alloc.free(pages)                      # both cached, ref-0
        # Diverge inside block 2: the walk increfs pages[0], then the
        # COW alloc has only pages[1] — the source — to reclaim.
        q = [1, 2, 3, 4, 5, 6, 99, 98]
        hit, covered = idx.match_and_acquire(q, owner="b")
        assert covered == PG and hit == [pages[0]]
        assert dev.copies == []                # no copy of dead content
        assert idx.stats["evictions"] == 1
        alloc.free(hit)
        alloc.assert_quiescent()

    def test_leaf_first_release_evicts_leaves_first(self):
        idx, alloc, _ = mk_index(num_pages=5)
        toks = list(range(1, 17))
        pages = alloc.alloc(4, owner="a")
        idx.insert(toks, pages, 16)
        alloc.free(list(reversed(pages)))      # engine's release order
        alloc.alloc(1, owner="b")              # evicts ONE page: a leaf
        # The root chain must survive: prefix of 2 blocks still matches.
        hit, covered = idx.match_and_acquire(toks[:8] + [99], owner="c")
        assert covered == 8 and hit[0] == pages[0]


class TestHostTier:
    def test_demote_then_promote_roundtrip(self):
        idx, alloc, dev = mk_index(host_pages=8, demote_after_s=0.01,
                                   scan_interval_s=0.0)
        try:
            toks = list(range(1, 13))
            pages = alloc.alloc(3, owner="a")
            idx.insert(toks, pages, 12)
            alloc.free(list(reversed(pages)))
            time.sleep(0.05)
            n = idx.tick(now=time.monotonic())
            assert n == 3
            idx.drain_migrations()
            assert idx.host_pages_resident() == 3
            assert alloc.cached() == 0         # device pages freed
            assert sorted(dev.fetches[0]) == sorted(pages)
            # Promotion on a radix hit: fresh device pages, batched
            # upload carrying the EXACT demoted bytes (wire roundtrip).
            hit, covered = idx.match_and_acquire(toks + [99], owner="b")
            assert covered == 12 and len(hit) == 3
            assert idx.host_pages_resident() == 0
            assert idx.stats["pages_promoted"] == 3
            ids, k, v = dev.uploads[-1]
            assert ids == hit
            # Per-page blocks in path order; content survives the wire
            # roundtrip bit-exactly.
            np.testing.assert_array_equal(k[0], dev.page_block(pages[0]))
            alloc.free(hit)
            alloc.assert_quiescent()
        finally:
            idx.close()

    def test_host_capacity_evicts_lru(self):
        idx, alloc, _ = mk_index(host_pages=2, demote_after_s=0.0,
                                 scan_interval_s=0.0)
        try:
            a = alloc.alloc(2, owner="a")
            idx.insert([1, 2, 3, 4, 5, 6, 7, 8], a, 8)
            alloc.free(list(reversed(a)))
            assert idx.tick(now=time.monotonic() + 1) == 2
            idx.drain_migrations()
            assert idx.host_pages_resident() == 2
            b = alloc.alloc(2, owner="b")
            idx.insert([9, 10, 11, 12, 13, 14, 15, 16], b, 8)
            alloc.free(list(reversed(b)))
            assert idx.tick(now=time.monotonic() + 10) == 2
            idx.drain_migrations()
            # The older conversation was evicted to make room.
            assert idx.host_pages_resident() == 2
            assert idx.stats["host_evictions"] >= 1
        finally:
            idx.close()

    def test_wire_roundtrip(self):
        k = np.arange(24, dtype=np.float32).reshape(2, 3, 2, 2)
        v = k * 2
        k2, v2, sk, sv = pages_from_wire(pages_to_wire(k, v))
        np.testing.assert_array_equal(k, k2)
        np.testing.assert_array_equal(v, v2)
        assert sk is None and sv is None    # v1 blob: no scale segment

    def test_wire_roundtrip_int8_scales(self):
        """v2 blob: int8 page bytes + f32 per-token-per-head scale rows
        survive the wire bit-exactly, and the blob is about half the
        full-dtype one (the ~halved-migration-bytes claim, at the wire)."""
        rng = np.random.default_rng(0)
        kf = rng.standard_normal((2, 3, 4, 8), np.float32)
        k = np.clip(np.round(kf * 40), -127, 127).astype(np.int8)
        v = (k[::-1]).copy()
        sk = rng.random((2, 3, 4), np.float32) + 0.1
        sv = sk * 2
        blob = pages_to_wire(k, v, kv_sk=sk, kv_sv=sv)
        k2, v2, sk2, sv2 = pages_from_wire(blob)
        assert k2.dtype == np.int8 and sk2.dtype == np.float32
        np.testing.assert_array_equal(k, k2)
        np.testing.assert_array_equal(v, v2)
        np.testing.assert_array_equal(sk, sk2)
        np.testing.assert_array_equal(sv, sv2)
        full = pages_to_wire(kf, kf * 2)
        # int8 payload + 4/Dh scales vs f32 pages: 0.375 at Dh=8.
        assert len(blob) < len(full) * 0.55, (len(blob), len(full))


class TestRemoteTier:
    """Third tier (ISSUE 17): cold sharer-free radix subtrees spill past
    host RAM into the artifact store as manifest-checksummed blobs, and
    ANY index with the same fabric signature can adopt them through the
    registry — the conversation-failover substrate. Every store fault
    degrades to a clean miss (= recompute upstream), never a wedge."""

    SIG = "L2.H1.D2.P4.full"

    def _mk(self, store, **kw):
        kw.setdefault("host_pages", 8)
        kw.setdefault("demote_after_s", 0.01)
        kw.setdefault("scan_interval_s", 0.0)
        kw.setdefault("remote_after_s", 0.0)
        kw.setdefault("fabric_sig", self.SIG)
        return mk_index(remote_store=store, **kw)

    def _spill(self, idx, alloc, toks):
        """Insert → release → demote-to-host → spill-to-remote."""
        pages = alloc.alloc(len(toks) // PG, owner="a")
        idx.insert(toks, pages, len(toks))
        alloc.free(list(reversed(pages)))
        idx.tick(now=time.monotonic() + 1)       # device -> host
        idx.drain_migrations()
        idx.tick(now=time.monotonic() + 10)      # host -> remote
        idx.drain_migrations()
        return pages

    def test_spill_then_promote_roundtrip(self, tmp_path):
        from kubeflow_tpu.pipelines.artifacts import ArtifactStore

        store = ArtifactStore(str(tmp_path))
        idx, alloc, dev = self._mk(store)
        try:
            toks = list(range(1, 13))
            pages = self._spill(idx, alloc, toks)
            assert idx.remote_pages_resident() == 3
            assert idx.host_pages_resident() == 0
            snap = idx.snapshot()
            assert snap["pages_demoted_remote"] == 3
            assert snap["remote_demote_bytes"] > 0
            # Promotion on a radix hit: store fetch, checksum verify,
            # fresh device pages carrying the EXACT original bytes.
            hit, covered = idx.match_and_acquire(toks + [99], owner="b")
            assert covered == 12 and len(hit) == 3
            assert idx.remote_pages_resident() == 0
            snap = idx.snapshot()
            assert snap["pages_promoted_remote"] == 3
            assert snap["remote_promote_bytes"] > 0
            ids, k, v = dev.uploads[-1]
            assert ids == hit
            np.testing.assert_array_equal(k[0], dev.page_block(pages[0]))
            alloc.free(hit)
            alloc.assert_quiescent()
        finally:
            idx.close()

    def test_cross_index_failover_via_registry(self, tmp_path):
        """A fresh index on a DIFFERENT host (same store, same fabric
        signature) adopts the spilled subtree through the registry —
        next turn after a SIGKILL lands on a survivor and reuses the
        stored prefix instead of recomputing."""
        from kubeflow_tpu.pipelines.artifacts import ArtifactStore

        store = ArtifactStore(str(tmp_path))
        toks = list(range(1, 13))
        idx_a, alloc_a, dev_a = self._mk(store)
        try:
            pages_a = self._spill(idx_a, alloc_a, toks)
        finally:
            idx_a.close()                        # the "killed" engine
        idx_b, alloc_b, dev_b = self._mk(store)
        try:
            hit, covered = idx_b.match_and_acquire(toks + [99], owner="b")
            assert covered == 12 and len(hit) == 3
            snap = idx_b.snapshot()
            assert snap["remote_registry_hits"] >= 3
            assert snap["pages_promoted_remote"] == 3
            # B uploaded A's bytes: the conversation content crossed
            # hosts via the store, not via any live connection.
            ids, k, v = dev_b.uploads[-1]
            np.testing.assert_array_equal(
                k[0], dev_a.page_block(pages_a[0]))
            alloc_b.free(hit)
            alloc_b.assert_quiescent()
        finally:
            idx_b.close()

    def test_fabric_sig_mismatch_never_adopts(self, tmp_path):
        """Registry keys fold the fabric signature: a mixed-version
        fleet (different layout/dtype) gets a clean miss, never a blob
        interpreted under the wrong shape."""
        from kubeflow_tpu.pipelines.artifacts import ArtifactStore

        store = ArtifactStore(str(tmp_path))
        toks = list(range(1, 13))
        idx_a, alloc_a, _ = self._mk(store)
        try:
            self._spill(idx_a, alloc_a, toks)
        finally:
            idx_a.close()
        idx_b, alloc_b, _ = self._mk(store, fabric_sig="L2.H1.D2.P4.int8")
        try:
            hit, covered = idx_b.match_and_acquire(toks + [99], owner="b")
            assert covered == 0 and hit == []
            assert idx_b.snapshot()["remote_registry_hits"] == 0
            alloc_b.assert_quiescent()
        finally:
            idx_b.close()

    def test_truncated_blob_rejected_by_checksum(self, tmp_path):
        """Torn write / partial read: the content-address IS the
        checksum, so a truncated blob is rejected (counted corrupt) and
        the match degrades to a clean miss — never corrupted pages."""
        from kubeflow_tpu.pipelines.artifacts import ArtifactStore
        from kubeflow_tpu.serve.faults import ChaosStore

        chaos = ChaosStore(ArtifactStore(str(tmp_path)))
        toks = list(range(1, 13))
        idx_a, alloc_a, _ = self._mk(chaos)
        try:
            self._spill(idx_a, alloc_a, toks)
        finally:
            idx_a.close()
        idx_b, alloc_b, _ = self._mk(chaos)
        try:
            chaos.truncate_next(8)
            hit, covered = idx_b.match_and_acquire(toks + [99], owner="b")
            assert covered == 0 and hit == []
            assert idx_b.snapshot()["remote_blobs_corrupt"] >= 1
            assert chaos.stats["truncated_reads"] >= 1
            alloc_b.assert_quiescent()
        finally:
            chaos.truncate_next(0)
            idx_b.close()

    def test_wedged_store_degrades_within_deadline(self, tmp_path):
        """Hung store endpoint mid-promote: the per-match deadline
        bounds the stall and the match degrades to recompute — admission
        never wedges on the third tier."""
        from kubeflow_tpu.pipelines.artifacts import ArtifactStore
        from kubeflow_tpu.serve.faults import ChaosStore

        chaos = ChaosStore(ArtifactStore(str(tmp_path)))
        toks = list(range(1, 13))
        idx_a, alloc_a, _ = self._mk(chaos)
        try:
            self._spill(idx_a, alloc_a, toks)
        finally:
            idx_a.close()
        idx_b, alloc_b, _ = self._mk(chaos, remote_deadline_s=0.15)
        try:
            chaos.wedge_promote()
            t0 = time.monotonic()
            hit, covered = idx_b.match_and_acquire(toks + [99], owner="b")
            elapsed = time.monotonic() - t0
            assert covered == 0 and hit == []
            assert elapsed < 2.0, f"match wedged for {elapsed:.2f}s"
            assert idx_b.snapshot()["remote_promote_timeouts"] >= 1
            assert chaos.stats["wedged_reads"] >= 1
            alloc_b.assert_quiescent()
        finally:
            chaos.unwedge()
            idx_b.close()

    def test_spill_publish_failure_reverts_to_host(self, tmp_path):
        """Unreachable store at demote time: the page stays in the host
        tier (content never lost) and the NEXT match still promotes it
        from host RAM."""
        from kubeflow_tpu.pipelines.artifacts import ArtifactStore
        from kubeflow_tpu.serve.faults import ChaosStore

        chaos = ChaosStore(ArtifactStore(str(tmp_path)))
        # Long remote_after_s: the background scan must not race the
        # fault arming — only our explicit future-now tick spills.
        idx, alloc, _ = self._mk(chaos, remote_after_s=60.0)
        try:
            toks = list(range(1, 13))
            pages = alloc.alloc(3, owner="a")
            idx.insert(toks, pages, 12)
            alloc.free(list(reversed(pages)))
            idx.tick(now=time.monotonic() + 1)
            idx.drain_migrations()               # hosted
            chaos.fail_next(100)                 # store goes dark
            idx.tick(now=time.monotonic() + 120)
            idx.drain_migrations()
            chaos.fail_next(0)
            assert idx.remote_pages_resident() == 0
            assert idx.host_pages_resident() == 3
            assert idx.snapshot()["remote_spill_errors"] >= 1
            hit, covered = idx.match_and_acquire(toks + [99], owner="b")
            assert covered == 12
            assert idx.snapshot()["pages_promoted"] == 3
            alloc.free(hit)
            alloc.assert_quiescent()
        finally:
            idx.close()

    def test_spill_all_to_remote_forced(self, tmp_path):
        """The drain-for-failover entry point: force everything resident
        out to the store regardless of idle timers, so a terminating
        replica's conversations survive it."""
        from kubeflow_tpu.pipelines.artifacts import ArtifactStore

        store = ArtifactStore(str(tmp_path))
        idx, alloc, _ = self._mk(store, demote_after_s=60.0,
                                 remote_after_s=60.0)
        try:
            toks = list(range(1, 13))
            pages = alloc.alloc(3, owner="a")
            idx.insert(toks, pages, 12)
            alloc.free(list(reversed(pages)))
            assert idx.spill_all_to_remote() == 3
            assert idx.remote_pages_resident() == 3
            assert alloc.cached() == 0
            hit, covered = idx.match_and_acquire(toks + [99], owner="b")
            assert covered == 12
            alloc.free(hit)
            alloc.assert_quiescent()
        finally:
            idx.close()

    def test_gc_sweeps_orphans_keeps_registered(self, tmp_path):
        """SIGKILL mid-demote leaves a published-but-unregistered blob
        (the crash window is publish→register). The register-only GC
        sweep reclaims it; registered spill blobs stay promotable."""
        import os

        from kubeflow_tpu.pipelines.artifacts import ArtifactStore
        from kubeflow_tpu.pipelines.gc import collect_garbage

        store = ArtifactStore(str(tmp_path))
        toks = list(range(1, 13))
        idx_a, alloc_a, _ = self._mk(store)
        try:
            self._spill(idx_a, alloc_a, toks)
        finally:
            idx_a.close()
        # The crash window: bytes published, register never ran.
        orphan = store.put_bytes(b"kv blob from an engine killed mid-demote")
        past = time.time() - 3600
        os.utime(store.path_for(orphan), (past, past))
        report = collect_garbage(store, None, min_age_s=600.0)
        assert report["swept_blobs"] == 1
        assert not store.exists(orphan)
        # Registered blobs survived the sweep and still promote.
        idx_b, alloc_b, _ = self._mk(store)
        try:
            hit, covered = idx_b.match_and_acquire(toks + [99], owner="b")
            assert covered == 12
            assert idx_b.snapshot()["pages_promoted_remote"] == 3
            alloc_b.free(hit)
            alloc_b.assert_quiescent()
        finally:
            idx_b.close()


# -- engine level --------------------------------------------------------------

@pytest.fixture(scope="module")
def cfg():
    return preset("tiny", vocab_size=512)


@pytest.fixture(scope="module")
def params(cfg):
    return init_decoder_params(jax.random.PRNGKey(0), cfg)


def mk_engine(cfg, params, *, prefix_index="radix", prefix=True,
              host_pages=0, demote_after_s=2.0, slots=4, page=16,
              chunk=32, max_pages=None, kv_dtype=None):
    return LLMEngine(cfg, BatchingSpec(
        max_batch_size=slots, max_seq_len=128, paged=True, page_size=page,
        max_pages=max_pages, enable_prefix_caching=prefix,
        prefix_index=prefix_index, host_kv_pages=host_pages,
        kv_demote_after_s=demote_after_s, kv_cache_dtype=kv_dtype,
        chunked_prefill_tokens=chunk, max_concurrent_prefills=2),
        params=params)


def run_all(eng, reqs, max_steps=800):
    for _ in range(max_steps):
        eng.step()
        if all(r.done.is_set() for r in reqs):
            return
    raise AssertionError("requests did not finish")


def quiesce(eng, deadline_s=10.0):
    deadline = time.monotonic() + deadline_s
    while eng.kv_pages_in_use() > 0:
        eng.step()
        assert time.monotonic() < deadline, "KV pages leaked"
    eng._allocator.assert_quiescent()


class TestEngineRadix:
    PROMPTS = [
        [7, 1, 9, 2, 4, 4, 8, 3] * 3,                     # 24 tokens
        [7, 1, 9, 2, 4, 4, 8, 3] * 2 + [5, 6, 7, 8],      # diverges @16
        [2] * 40,
    ]

    def _outputs(self, eng):
        sp = SamplingParams(max_new_tokens=8, temperature=0.0)
        outs = []
        for p in self.PROMPTS:
            # Sequential: later submissions see earlier registrations —
            # maximal sharing on the radix engine.
            r = eng.submit(list(p), sp)
            run_all(eng, [r])
            outs.append(list(r.output_tokens))
        # Re-arrivals of the first prompt: the conversation-reuse path.
        r = eng.submit(list(self.PROMPTS[0]), sp)
        run_all(eng, [r])
        outs.append(list(r.output_tokens))
        return outs

    @pytest.mark.slow  # tier-1 budget: two full engines A/B, ~9s; identity
    # with sharing ON is also pinned by test_conversation_reuse_after_release
    def test_token_identity_sharing_on_vs_off(self, cfg, params):
        base = mk_engine(cfg, params, prefix=False)
        radix = mk_engine(cfg, params, prefix_index="radix")
        flat = mk_engine(cfg, params, prefix_index="flat")
        want = self._outputs(base)
        got_radix = self._outputs(radix)
        got_flat = self._outputs(flat)
        assert got_radix == want
        assert got_flat == want
        tier = radix.kv_tier_stats()
        assert tier["prefix_hits"] >= 2
        assert tier["cow_copies"] >= 1       # the @16+ divergence
        for eng in (base, radix, flat):
            quiesce(eng)

    def test_conversation_reuse_after_release(self, cfg, params):
        """Multi-turn: turn 2 = turn 1's prompt + ACTUAL output + new
        tokens must match through the released conversation's pages —
        including the decode-grown ones the flat cache always lost."""
        eng = mk_engine(cfg, params)
        sp = SamplingParams(max_new_tokens=8, temperature=0.0)
        r1 = eng.submit([3, 1, 4, 1, 5, 9, 2, 6] * 3, sp)
        run_all(eng, [r1])
        turn2 = list(r1.prompt_tokens) + list(r1.output_tokens) \
            + [8, 8, 4, 2]
        before = eng.kv_tier_stats()["tokens_matched"]
        r2 = eng.submit(turn2, sp)
        run_all(eng, [r2])
        matched = eng.kv_tier_stats()["tokens_matched"] - before
        # 24 prompt + 7 of 8 generated tokens have reusable KV; the
        # match must cover nearly the whole history (>= 24 proves the
        # decode-grown page rode along; flat caching would cap at 16).
        assert matched >= 24, matched
        base = mk_engine(cfg, params, prefix=False)
        rb1 = base.submit([3, 1, 4, 1, 5, 9, 2, 6] * 3, sp)
        run_all(base, [rb1])
        rb2 = base.submit(list(turn2), sp)
        run_all(base, [rb2])
        assert list(r2.output_tokens) == list(rb2.output_tokens)
        quiesce(eng)
        quiesce(base)

    def test_live_sharing_two_inflight(self, cfg, params):
        """Two requests with one prompt IN FLIGHT together: the second
        shares ref>0 pages while the first still decodes; both finish
        with identical greedy output and the pool balances per owner."""
        eng = mk_engine(cfg, params)
        sp = SamplingParams(max_new_tokens=16, temperature=0.0)
        p = [9, 8, 7, 6, 5, 4, 3, 2] * 4
        r1 = eng.submit(list(p), sp)
        # A few steps: r1 prefills + registers, keeps decoding.
        for _ in range(6):
            eng.step()
        r2 = eng.submit(list(p), sp)
        run_all(eng, [r1, r2])
        assert list(r1.output_tokens) == list(r2.output_tokens)
        assert eng.kv_tier_stats()["prefix_hits"] >= 1
        quiesce(eng)

    def test_spec_rollback_with_shared_pages(self, cfg, params):
        """Speculative rollback truncation must never free a shared
        prefix page out from under a co-sharer (satellite: spec-decode
        rollback interacting with shared pages)."""
        from kubeflow_tpu.core.serving import SpeculativeSpec

        eng = LLMEngine(cfg, BatchingSpec(
            max_batch_size=4, max_seq_len=128, paged=True, page_size=16,
            chunked_prefill_tokens=16,
            speculative=SpeculativeSpec(mode="ngram", k=3)),
            params=params)
        base = mk_engine(cfg, params, prefix=False)
        sp = SamplingParams(max_new_tokens=12, temperature=0.0)
        p = [5, 3, 5, 3, 5, 3, 1, 2] * 3
        r1 = eng.submit(list(p), sp)
        for _ in range(6):
            eng.step()
        r2 = eng.submit(list(p) + [4, 4], sp)
        run_all(eng, [r1, r2])
        b1 = base.submit(list(p), sp)
        run_all(base, [b1])
        b2 = base.submit(list(p) + [4, 4], sp)
        run_all(base, [b2])
        assert list(r1.output_tokens) == list(b1.output_tokens)
        assert list(r2.output_tokens) == list(b2.output_tokens)
        quiesce(eng)
        quiesce(base)

    @pytest.mark.slow
    def test_chunked_resume_mid_page_after_preemption(self, cfg, params):
        """Chunking-preemption resume through the radix index: the
        victim's written chunks (full pages + sub-page tail) must match
        back, and the mid-page COW resume must produce identical greedy
        output (satellite: chunked-prefill page-alignment resume)."""
        eng = mk_engine(cfg, params, chunk=16, max_pages=24, slots=2)
        base = mk_engine(cfg, params, prefix=False, slots=2)
        sp = SamplingParams(max_new_tokens=6, temperature=0.0)
        long_p = [11, 13, 17, 19] * 14 + [1, 2, 3]     # 59 tokens
        r1 = eng.submit(list(long_p), sp)
        for _ in range(2):
            eng.step()                  # a couple of chunks land
        # Pool-pressure the chunking into the preempted lane, then let
        # it resume: its re-admission matches the registered chunks
        # (incl. the partial tail — a mid-page resume).
        r2 = eng.submit([2, 4, 6, 8] * 8, sp)
        run_all(eng, [r1, r2])
        b1 = base.submit(list(long_p), sp)
        b2 = base.submit([2, 4, 6, 8] * 8, sp)
        run_all(base, [b1, b2])
        assert list(r1.output_tokens) == list(b1.output_tokens)
        assert list(r2.output_tokens) == list(b2.output_tokens)
        quiesce(eng)
        quiesce(base)


class TestEngineHostTier:
    @pytest.mark.slow
    def test_idle_conversation_demotes_then_promotes(self, cfg, params):
        eng = mk_engine(cfg, params, host_pages=32, demote_after_s=0.05)
        base = mk_engine(cfg, params, prefix=False)
        try:
            sp = SamplingParams(max_new_tokens=6, temperature=0.0)
            p = [6, 2, 8, 1, 8, 2, 8, 4] * 4
            r1 = eng.submit(list(p), sp)
            run_all(eng, [r1])
            # Idle: the background thread + scheduler tick demote the
            # released conversation to host RAM.
            deadline = time.monotonic() + 10.0
            while eng.kv_pages_host() == 0:
                eng.step()
                time.sleep(0.01)
                assert time.monotonic() < deadline, "no demotion happened"
            assert eng.kv_pages_cached() == 0 or eng.kv_pages_host() > 0
            # Re-arrival: radix hit promotes BEFORE prefill admits;
            # output identical to the uncached engine.
            r2 = eng.submit(list(p), sp)
            run_all(eng, [r2])
            b = base.submit(list(p), sp)
            run_all(base, [b])
            assert list(r2.output_tokens) == list(b.output_tokens)
            tier = eng.kv_tier_stats()
            assert tier["pages_demoted"] > 0
            assert tier["pages_promoted"] > 0
            quiesce(eng)
            quiesce(base)
        finally:
            eng.stop()
            base.stop()

    @pytest.mark.slow
    def test_int8_pool_demote_promote_identity(self, cfg, params):
        """Quantized-pool tiering: scale rows must ride the v2 wire to
        host RAM and back — a promote that loses them re-reads garbage
        pages. Greedy output through a demote→promote round trip must
        match the untier-ed int8 engine token for token."""
        eng = mk_engine(cfg, params, host_pages=32, demote_after_s=0.05,
                        kv_dtype="int8")
        base = mk_engine(cfg, params, prefix=False, kv_dtype="int8")
        try:
            sp = SamplingParams(max_new_tokens=6, temperature=0.0)
            p = [6, 2, 8, 1, 8, 2, 8, 4] * 4
            r1 = eng.submit(list(p), sp)
            run_all(eng, [r1])
            deadline = time.monotonic() + 10.0
            while eng.kv_pages_host() == 0:
                eng.step()
                time.sleep(0.01)
                assert time.monotonic() < deadline, "no demotion happened"
            r2 = eng.submit(list(p), sp)
            run_all(eng, [r2])
            b = base.submit(list(p), sp)
            run_all(base, [b])
            assert list(r2.output_tokens) == list(b.output_tokens)
            tier = eng.kv_tier_stats()
            assert tier["pages_demoted"] > 0
            assert tier["pages_promoted"] > 0
            # Wire-byte accounting flowed: demoted blobs were counted,
            # and int8+scales cost ~0.625x the bf16 pages at Dh=16
            # (0.52x at a real model's Dh=128).
            assert tier["demote_wire_bytes"] > 0
            assert tier["promote_wire_bytes"] > 0
            quiesce(eng)
            quiesce(base)
        finally:
            eng.stop()
            base.stop()

    def test_quant_metric_series_exposed(self, cfg, params):
        from kubeflow_tpu.obs.registry import parse_exposition
        from kubeflow_tpu.serve.server import serving_metrics_registry

        eng8 = mk_engine(cfg, params, kv_dtype="int8")
        eng16 = mk_engine(cfg, params)
        try:
            text = serving_metrics_registry(
                [("q", eng8), ("f", eng16)]).render()
            vals = {(n, labels.get("model")): v
                    for n, labels, v in parse_exposition(text)}
            assert vals[("kftpu_engine_kv_quant_enabled", "q")] == 1
            assert vals[("kftpu_engine_kv_quant_enabled", "f")] == 0
            d8 = vals[("kftpu_engine_kv_quant_tokens_per_mib", "q")]
            d16 = vals[("kftpu_engine_kv_quant_tokens_per_mib", "f")]
            # Density at tiny's Dh=16 (bf16 pool): 32 B/token/head full
            # vs 20 B int8+f32 scale = 1.6x. The >=1.9x gate claim needs
            # a real head dim (Dh=128: 256 vs 132 B ≈ 1.94x) and lives
            # in scripts/quant_smoke.py.
            assert d8 >= d16 * 1.55, (d8, d16)
            assert ("kftpu_engine_kv_handoff_bytes_exported_total",
                    "q") in vals
            assert ("kftpu_engine_kv_wire_bytes_demoted_total",
                    "q") in vals
        finally:
            eng8.stop()
            eng16.stop()

    def test_tier_gauges_split_resident_vs_cached_vs_host(self, cfg,
                                                         params):
        from kubeflow_tpu.obs.registry import parse_exposition
        from kubeflow_tpu.serve.server import serving_metrics_registry

        eng = mk_engine(cfg, params, host_pages=16, demote_after_s=0.05)
        try:
            sp = SamplingParams(max_new_tokens=4, temperature=0.0)
            r = eng.submit([4] * 20, sp)
            run_all(eng, [r])
            assert eng.kv_pages_in_use() == 0      # released: not load
            assert eng.kv_pages_cached() > 0       # but still cached
            text = serving_metrics_registry([("m", eng)]).render()
            vals = {n: v for n, labels, v in parse_exposition(text)}
            assert vals["kftpu_engine_kv_pages_resident"] == 0
            assert vals["kftpu_engine_kv_pages_cached"] > 0
            assert "kftpu_engine_kv_pages_host" in vals
            assert vals["kftpu_engine_kv_prefix_hits_total"] >= 0
        finally:
            eng.stop()
