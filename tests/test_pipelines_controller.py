"""PipelineRun/ScheduledRun reconciler tests through the control plane
(stepped, envtest-style) — the apiserver/scheduledworkflow behaviors of
SURVEY.md §2.5#38-39."""

import datetime

import pytest

from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.core.pipeline_specs import (
    Pipeline, PipelineRun, PipelineRunSpec, PipelineSpecModel, RunPhase,
    ScheduledRun, ScheduledRunSpec,
)
from kubeflow_tpu.operator.control_plane import ControlPlane, ControlPlaneConfig
from kubeflow_tpu.pipelines import dsl
from kubeflow_tpu.pipelines.compiler import compile_pipeline
from kubeflow_tpu.pipelines.controller import ScheduledRunController, cron_matches
from kubeflow_tpu.runtime.topology import Cluster, SliceTopology


@dsl.component
def add(a: int, b: int) -> int:
    return a + b


@dsl.pipeline(name="sum2")
def sum2(a: int = 1, b: int = 2):
    add(a=a, b=b)


@pytest.fixture()
def cp(tmp_path):
    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="v5e",
                                              dims=(2, 2))]),
        launch_processes=False,
        metrics_sync_interval=None,
    ))
    yield plane
    plane.pipelinerun_reconciler.shutdown()


class TestPipelineRun:
    def test_run_stored_pipeline(self, cp):
        ir = compile_pipeline(sum2)
        cp.submit(Pipeline(metadata=ObjectMeta(name="sum2"),
                           spec=PipelineSpecModel(ir=ir)))
        run = cp.submit(PipelineRun(
            metadata=ObjectMeta(name="r1"),
            spec=PipelineRunSpec(pipeline="sum2", parameters={"b": 41})))
        cp.step()
        got = cp.store.get(PipelineRun, "r1")
        assert got.status.phase is RunPhase.SUCCEEDED
        assert got.status.tasks["add"].outputs["output"] == 42
        assert got.status.outputs == {"add.output": 42}

    def test_run_inline_ir(self, cp):
        run = cp.submit(PipelineRun(
            metadata=ObjectMeta(name="r2"),
            spec=PipelineRunSpec(ir=compile_pipeline(sum2))))
        cp.step()
        assert cp.store.get(PipelineRun, "r2").status.phase is RunPhase.SUCCEEDED

    def test_unknown_pipeline_fails(self, cp):
        cp.submit(PipelineRun(
            metadata=ObjectMeta(name="r3"),
            spec=PipelineRunSpec(pipeline="missing")))
        cp.step()
        got = cp.store.get(PipelineRun, "r3")
        assert got.status.phase is RunPhase.FAILED
        assert got.status.has_condition("Failed")

    def test_cache_shared_across_runs(self, cp):
        cp.submit(Pipeline(metadata=ObjectMeta(name="sum2"),
                           spec=PipelineSpecModel(
                               ir=compile_pipeline(sum2))))
        cp.submit(PipelineRun(metadata=ObjectMeta(name="a"),
                              spec=PipelineRunSpec(pipeline="sum2")))
        cp.step()
        cp.submit(PipelineRun(metadata=ObjectMeta(name="b"),
                              spec=PipelineRunSpec(pipeline="sum2")))
        cp.step()
        assert cp.store.get(PipelineRun, "b").status.tasks["add"].cached


class TestScheduledRun:
    def test_interval_triggers_runs(self, cp):
        now = [datetime.datetime(2026, 1, 1, 0, 0, 0)]
        cp.schedule_reconciler.now_fn = lambda: now[0]
        cp.submit(Pipeline(metadata=ObjectMeta(name="sum2"),
                           spec=PipelineSpecModel(ir=compile_pipeline(sum2))))
        cp.submit(ScheduledRun(
            metadata=ObjectMeta(name="nightly"),
            spec=ScheduledRunSpec(pipeline="sum2", interval_seconds=60.0)))
        cp.step()
        sr = cp.store.get(ScheduledRun, "nightly")
        assert sr.status.runs_started == 1        # fires immediately
        runs = cp.store.list(PipelineRun)
        assert len(runs) == 1 and runs[0].metadata.name == "nightly-00000"
        # not due yet (drive the reconciler directly: stepped mode does not
        # sleep through the 60s interval requeue, by design)
        now[0] += datetime.timedelta(seconds=30)
        cp.schedule_reconciler.reconcile("default/nightly")
        assert cp.store.get(ScheduledRun, "nightly").status.runs_started == 1
        # due again
        now[0] += datetime.timedelta(seconds=31)
        cp.step()   # lets the first run finish executing
        cp.schedule_reconciler.reconcile("default/nightly")
        sr = cp.store.get(ScheduledRun, "nightly")
        assert sr.status.runs_started == 2
        # the triggered runs actually executed
        assert cp.store.get(PipelineRun, "nightly-00000").status.phase \
            is RunPhase.SUCCEEDED

    def test_disabled_never_triggers(self, cp):
        cp.submit(ScheduledRun(
            metadata=ObjectMeta(name="off"),
            spec=ScheduledRunSpec(pipeline="sum2", interval_seconds=1.0,
                                  enabled=False)))
        cp.step()
        assert cp.store.get(ScheduledRun, "off").status.runs_started == 0

    def test_max_concurrency(self, cp, tmp_path):
        # Standalone controller so runs stay Pending (no executor stepping).
        from kubeflow_tpu.core.store import ObjectStore

        store = ObjectStore()
        now = [datetime.datetime(2026, 1, 1)]
        ctl = ScheduledRunController(store, now_fn=lambda: now[0])
        store.create(ScheduledRun(
            metadata=ObjectMeta(name="s"),
            spec=ScheduledRunSpec(pipeline="p", interval_seconds=1.0,
                                  max_concurrency=1)))
        ctl.reconcile("default/s")
        now[0] += datetime.timedelta(seconds=2)
        ctl.reconcile("default/s")   # previous run still Pending → hold
        assert store.get(ScheduledRun, "s").status.runs_started == 1


class TestCron:
    def test_cron_matching(self):
        t = datetime.datetime(2026, 7, 30, 9, 30)
        assert cron_matches("30 9 * * *", t)
        assert cron_matches("*/15 * * * *", t)
        assert not cron_matches("0 9 * * *", t)
        assert cron_matches("30 9 30 7 *", t)
        assert not cron_matches("30 9 31 * *", t)
        with pytest.raises(ValueError):
            cron_matches("* *", t)

    def test_cron_schedule_fires_once_per_minute(self):
        from kubeflow_tpu.core.store import ObjectStore

        store = ObjectStore()
        now = [datetime.datetime(2026, 1, 1, 9, 30, 0)]
        ctl = ScheduledRunController(store, now_fn=lambda: now[0])
        store.create(ScheduledRun(
            metadata=ObjectMeta(name="c"),
            spec=ScheduledRunSpec(pipeline="p", cron="30 9 * * *",
                                  max_concurrency=10)))
        ctl.reconcile("default/c")
        assert store.get(ScheduledRun, "c").status.runs_started == 1
        now[0] += datetime.timedelta(seconds=20)   # same minute
        ctl.reconcile("default/c")
        assert store.get(ScheduledRun, "c").status.runs_started == 1
        now[0] += datetime.timedelta(days=1)       # next day, 9:30 again
        ctl.reconcile("default/c")
        assert store.get(ScheduledRun, "c").status.runs_started == 2
