"""Serve-side chaos suite (ISSUE 2 tentpole #5): two real model servers
behind the hardened router, faults injected mid-traffic via serve/faults.py.

Invariants asserted after EVERY scenario:
- no hangs: every client thread joins within its bound;
- every in-flight request completes (200) or fails with an explicit HTTP
  error — never a silent stall;
- the router recovers: a fresh request succeeds afterwards;
- paged-KV refcounts balance: once quiescent, both engines hold zero pages.

The kill scenario runs LAST — it destroys one replica for good."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest
import jax

from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams
from kubeflow_tpu.serve.faults import ChaosProxy, kill_model_server
from kubeflow_tpu.serve.router import DEADLINE_HEADER, Router
from kubeflow_tpu.serve.server import ModelServer

EXPLICIT_STATUSES = {200, 429, 500, 502, 503, 504}


@pytest.fixture(scope="module")
def stack():
    cfg = preset("tiny", vocab_size=512)      # byte tokenizer fits
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)

    def mk(name):
        eng = LLMEngine(
            cfg,
            BatchingSpec(max_batch_size=2, max_seq_len=96,
                         prefill_buckets=[32], paged=True, page_size=16,
                         chunked_prefill_tokens=16, decode_steps=4,
                         # Explicit: every scenario here runs with a decode
                         # round potentially in flight (ISSUE 4) — the
                         # quiescence audits below must hold regardless.
                         pipelined_decode=True),
            params=params)
        srv = ModelServer(name, eng, port=0)
        srv.start()
        return srv

    a, b = mk("replica-a"), mk("replica-b")
    router = Router(queue_timeout=5.0, eject_threshold=2, eject_period=0.4,
                    max_retries=2, upstream_timeout=30.0)
    router.set_backends({"latest": [a.url, b.url]})
    router.start()
    yield a, b, router
    router.stop()
    for s in (a, b):
        try:
            s.stop()
        except OSError:
            pass


def completion(url: str, *, timeout_s: float = 10.0, max_tokens: int = 8,
               prompt: str = "chaos", qos: str = "") -> int:
    body = json.dumps({"prompt": prompt, "max_tokens": max_tokens,
                       "timeout": timeout_s}).encode()
    headers = {"Content-Type": "application/json",
               DEADLINE_HEADER: str(int(timeout_s * 1e3))}
    if qos:
        headers["X-Kftpu-Qos"] = qos
    req = urllib.request.Request(
        url + "/v1/completions", data=body, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s + 5) as r:
            return r.status
    except urllib.error.HTTPError as exc:
        exc.read()
        return exc.code
    except OSError:
        return 502    # connection-level failure: explicit, not a hang


def fire(url: str, n: int, concurrency: int = 4, *,
         mid_fault=None, fault_after: int = 2, **kw) -> list[int]:
    """Closed-loop client pool; optionally triggers ``mid_fault()`` once
    ``fault_after`` requests have completed. Asserts the no-hang bound."""
    results: list[int] = []
    lock = threading.Lock()
    it = iter(range(n))
    fault_fired = threading.Event()

    def client():
        while True:
            with lock:
                nxt = next(it, None)
            if nxt is None:
                return
            status = completion(url, **kw)
            with lock:
                results.append(status)
                if (mid_fault is not None and not fault_fired.is_set()
                        and len(results) >= fault_after):
                    fault_fired.set()
                    mid_fault()

    threads = [threading.Thread(target=client)
               for _ in range(max(1, concurrency))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90.0)
        assert not t.is_alive(), "client thread hung (no-hang invariant)"
    assert len(results) == n
    return results


def audit_quiescent(*servers, deadline_s: float = 20.0) -> None:
    """Post-scenario refcount audit: cancel anything stranded (the operator
    analog of process teardown), drive the reaper, assert zero page leaks.
    Handoff holds (pages backing an exported-but-never-acked payload)
    count as stranded state too — their requests cancel and the reaper
    must free them."""
    for srv in servers:
        eng = srv.engine
        for s in eng.slots:
            if s is not None:
                s.request.cancel()
        for lane in (eng._backlog, eng._preempted):
            for req in lane:
                req.cancel()
        for ch in list(eng._chunkings):
            ch.request.cancel()
        for hreq, _pages in list(eng._handoff_holds.values()):
            hreq.cancel()
        deadline = time.monotonic() + deadline_s
        while eng.kv_pages_in_use() > 0 or eng._handoff_holds:
            eng.step()
            assert time.monotonic() < deadline, \
                f"{srv.name}: KV pages leaked after scenario"
        eng._allocator.assert_quiescent()
        # Pipelined dispatch: the reap path must also have drained any
        # decode round left in flight by the scenario.
        while eng._rounds:
            eng.step()
        assert not eng._rounds, f"{srv.name}: in-flight round stranded"


def test_chaos_5xx_burst_ejects_then_recovers(stack):
    a, b, router = stack
    proxy = ChaosProxy(a.url)
    proxy.start()
    try:
        router.set_backends({"latest": [proxy.url, b.url]})
        proxy.fail_next(4, code=503)
        results = fire(router.url, 12, timeout_s=10.0)
        assert set(results) <= EXPLICIT_STATUSES
        assert results.count(200) >= 6, results
        assert router.snapshot()["ejections"] >= 1
        assert proxy.stats["injected_5xx"] >= 2     # burst actually landed
        # Recovery: end the burst (ejection may have diverted traffic
        # before the backend consumed all 4 injected faults), let the
        # ejection window pass, then traffic must be clean — including the
        # half-open probe that reinstates the backend.
        proxy.fail_next(0)
        time.sleep(0.5)
        assert all(s == 200 for s in fire(router.url, 4, timeout_s=10.0))
    finally:
        proxy.stop()
        router.set_backends({"latest": [a.url, b.url]})
    audit_quiescent(a, b)


def test_chaos_wedged_replica_fails_within_deadline(stack):
    a, b, router = stack
    proxy = ChaosProxy(a.url)
    proxy.start()
    try:
        router.set_backends({"latest": [proxy.url, b.url]})
        proxy.wedge()
        t0 = time.monotonic()
        results = fire(router.url, 8, timeout_s=3.0)
        elapsed = time.monotonic() - t0
        assert set(results) <= EXPLICIT_STATUSES
        # The healthy replica keeps serving: wedged picks retry onto b
        # after the deadline-bounded upstream wait.
        assert results.count(200) >= 4, results
        assert elapsed < 60.0
        proxy.unwedge()
        time.sleep(0.5)
        assert all(s == 200 for s in fire(router.url, 4, timeout_s=10.0))
    finally:
        proxy.stop()
        router.set_backends({"latest": [a.url, b.url]})
    audit_quiescent(a, b)


def test_chaos_scale_down_under_load_drains_cleanly(stack):
    """Scale-down analog: replica a leaves the rotation while its request
    is still streaming — the in-flight request completes, new traffic goes
    to b, and a's engine drains to zero pages."""
    a, b, router = stack
    router.set_backends({"latest": [a.url]})
    got: dict = {}

    def long_request():
        got["status"] = completion(router.url, timeout_s=15.0,
                                   max_tokens=48)

    t = threading.Thread(target=long_request)
    t.start()
    # wait until a is actually serving it
    deadline = time.monotonic() + 10.0
    while a.in_flight == 0 and not got:
        assert time.monotonic() < deadline
        time.sleep(0.005)
    router.set_backends({"latest": [b.url]})     # a retired mid-request
    t.join(timeout=30.0)
    assert not t.is_alive(), "in-flight request hung through scale-down"
    assert got["status"] == 200, "draining replica dropped its request"
    assert all(s == 200 for s in fire(router.url, 4, timeout_s=10.0))
    router.set_backends({"latest": [a.url, b.url]})
    audit_quiescent(a, b)


def test_chaos_halt_with_round_in_flight_reaps_clean():
    """ISSUE 4: the scheduler halting between dispatch and consume — the
    worst spot a SIGKILL can land with pipelined dispatch — must leave a
    state the recovery audit can still balance: the stranded in-flight
    round drains, cancelled requests mask their late tokens, and every
    paged-KV refcount returns to zero."""
    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    eng = LLMEngine(
        cfg,
        BatchingSpec(max_batch_size=2, max_seq_len=96, prefill_buckets=[32],
                     paged=True, page_size=16, chunked_prefill_tokens=16,
                     decode_steps=4, pipelined_decode=True),
        params=params)
    reqs = [eng.submit([i + 1] * 20, SamplingParams(max_new_tokens=60))
            for i in range(2)]
    for _ in range(3):
        eng.step()
    assert eng._rounds, "pipelining should have a round in flight here"
    emitted_at_halt = [len(r.output_tokens) for r in reqs]
    # SIGKILL analog: the loop never consumes that round. Recovery cancels
    # the stranded requests and drives step() like a supervisor would.
    for r in reqs:
        r.cancel()
    deadline = time.monotonic() + 20.0
    while eng.kv_pages_in_use() > 0 or eng._rounds:
        eng.step()
        assert time.monotonic() < deadline, "recovery did not quiesce"
    eng._allocator.assert_quiescent()
    assert all(r.done.is_set() and r.finish_reason == "cancelled"
               for r in reqs)
    # The stranded round's results never leaked into cancelled streams.
    assert [len(r.output_tokens) for r in reqs] == emitted_at_halt


def test_chaos_qos_overload_sheds_batch_first(stack):
    """ISSUE 6 acceptance (``qos_overload``): ~2x sustained overload with
    mixed interactive+batch classes through the router. Invariants:

    - batch absorbs ALL shedding (429 at the door + queue sheds);
      interactive is never shed;
    - interactive queue-delay p95 stays within its declared budget —
      delivered by strict-priority dequeue + cross-class preemption, not
      by shedding (its shed count is zero);
    - after the storm (preemptions included), every engine drains to
      zero pages and ``assert_quiescent`` holds."""
    from kubeflow_tpu.core.serving import QoSClassPolicy

    a, b, router = stack
    I_BUDGET_S = 5.0
    engines = [a.engine, b.engine]
    for eng in engines:
        eng.max_queue = 4
        eng.qos_policies = {
            "batch": QoSClassPolicy(max_queue=1),
            "interactive": QoSClassPolicy(queue_delay_budget=I_BUDGET_S)}
    try:
        results: dict[str, list[int]] = {"interactive": [], "batch": []}
        threads = []
        for cls, nclients in (("interactive", 3), ("batch", 3)):
            def pool(cls=cls):
                got = fire(router.url, 9, concurrency=3, timeout_s=10.0,
                           max_tokens=6, qos=cls)
                results[cls].extend(got)
            t = threading.Thread(target=pool)
            threads.append(t)
            t.start()
        for t in threads:
            t.join(timeout=120.0)
            assert not t.is_alive(), "client pool hung under qos overload"
        for cls in results:
            assert set(results[cls]) <= EXPLICIT_STATUSES, results[cls]
        # Graceful, prioritized degradation: interactive all served.
        assert all(s == 200 for s in results["interactive"]), \
            results["interactive"]
        shed = {"interactive": 0, "batch": 0}
        qd_p95 = []
        for eng in engines:
            snap = eng.metrics.snapshot()
            for cls in shed:
                shed[cls] += snap.get("qos", {}).get(cls, {}).get("shed", 0)
            qcls = snap.get("qos", {}).get("interactive", {})
            if "queue_delay_p95_ms" in qcls:
                qd_p95.append(qcls["queue_delay_p95_ms"])
        assert shed["interactive"] == 0, "interactive was shed under overload"
        if 429 in results["batch"]:
            assert shed["batch"] > 0
        assert qd_p95, "no interactive queue-delay signal recorded"
        assert max(qd_p95) <= I_BUDGET_S * 1e3, \
            f"interactive queue-delay p95 {max(qd_p95):.0f}ms over budget"
    finally:
        for eng in engines:
            eng.max_queue = 0
            eng.qos_policies = {}
    audit_quiescent(a, b)


@pytest.mark.slow  # tier-1 budget: sanitizer fleet under kill loop, ~8s
def test_chaos_refcount_sanitizer_kill_mid_traffic(monkeypatch):
    """ISSUE 7: one chaos scenario end-to-end under
    ``KFTPU_SANITIZE=refcount`` — SIGKILL analog mid-traffic, then the
    recovery audit must produce a PER-OWNER zero-leak report: every page
    reference was stamped with the request that took it, and every stamp
    was popped by a balancing free. Self-contained stack (the sanitize
    mode must be on BEFORE the engines build their allocators)."""
    monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)

    def mk(name):
        eng = LLMEngine(
            cfg,
            BatchingSpec(max_batch_size=2, max_seq_len=96,
                         prefill_buckets=[32], paged=True, page_size=16,
                         chunked_prefill_tokens=16, decode_steps=4,
                         pipelined_decode=True),
            params=params)
        srv = ModelServer(name, eng, port=0)
        srv.start()
        return srv

    a, b = mk("rc-a"), mk("rc-b")
    assert a.engine._allocator.refcount_debug, \
        "refcount mode not active at allocator construction"
    router = Router(queue_timeout=5.0, eject_threshold=2, eject_period=0.4,
                    max_retries=2, upstream_timeout=30.0)
    router.set_backends({"latest": [a.url, b.url]})
    router.start()
    try:
        results = fire(router.url, 12, timeout_s=6.0,
                       mid_fault=lambda: kill_model_server(b),
                       fault_after=2)
        assert set(results) <= EXPLICIT_STATUSES
        assert results.count(200) >= 4, results
        audit_quiescent(a, b)
        for srv in (a, b):
            alloc = srv.engine._allocator
            # traffic really was stamped, and every stamp was popped:
            # the per-owner report must be EMPTY, not merely small
            assert alloc.stats["stamped_allocs"] > 0, \
                f"{srv.name}: no stamped page traffic recorded"
            report = alloc.leak_report_by_owner()
            assert report == {}, \
                f"{srv.name}: per-owner leaks after recovery: {report}"
            alloc.assert_quiescent()
    finally:
        router.stop()
        for s in (a, b):
            try:
                s.stop()
            except OSError:
                pass


@pytest.mark.slow  # tier-1 budget: ~8s; the handoff module's
# unified-fallback test keeps the recompute lane in tier-1
def test_chaos_prefill_kill_mid_handoff_unified_fallback(monkeypatch):
    """ISSUE 12: SIGKILL the PREFILL replica of a disaggregated fleet
    mid-handoff, under ``KFTPU_SANITIZE=refcount``. Invariants:

    - a handoff hold stranded by the kill (pages exported, decode side
      never acked) reaps refcount-balanced — ``assert_quiescent`` holds
      on BOTH pools and the per-owner report names ZERO leaks;
    - continuing traffic requeues onto the surviving pool: the router's
      token-aware placement falls back to the decode replica serving
      whole requests locally (unified fallback), explicitly — no hangs."""
    monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)

    def mk(name, role):
        eng = LLMEngine(
            cfg,
            BatchingSpec(max_batch_size=2, max_seq_len=96,
                         prefill_buckets=[32], paged=True, page_size=16,
                         chunked_prefill_tokens=16, decode_steps=4,
                         role=role),
            params=params)
        srv = ModelServer(name, eng, port=0)
        srv.start()
        return srv

    pre, dec = mk("pre-a", "prefill"), mk("dec-b", "decode")
    assert pre.engine._allocator.refcount_debug
    proxy = ChaosProxy(pre.url)   # the prefill replica's "process"
    proxy.start()
    router = Router(queue_timeout=5.0, eject_threshold=2, eject_period=5.0,
                    max_retries=2, upstream_timeout=30.0)
    router.scrape_interval = 0.1
    router.set_pools({"prefill": [proxy.url], "decode": [dec.url]})
    router.start()
    try:
        # Disaggregated traffic flows: prefill → handoff → decode.
        results = fire(router.url, 6, timeout_s=10.0)
        assert set(results) <= EXPLICIT_STATUSES
        assert results.count(200) >= 4, results
        assert pre.engine.metrics.snapshot()["handoffs_exported"] >= 1
        assert dec.engine.metrics.snapshot()["handoffs_adopted"] >= 1
        # Strand a MID-handoff state: exported (pages in the ack hold),
        # decode side never told — exactly where a SIGKILL lands between
        # export and ack.
        from kubeflow_tpu.serve.engine import SamplingParams as SP

        orphan = pre.engine.submit([7] * 24, SP(max_new_tokens=8),
                                   handoff=True)
        assert orphan.done.wait(20.0)
        assert orphan.finish_reason == "handoff"
        assert pre.engine._handoff_holds, "no hold backing the payload"
        held_pages = pre.engine.kv_pages_in_use()
        assert held_pages > 0
        # SIGKILL the prefill replica mid-handoff.
        proxy.drop()
        kill_model_server(pre)
        time.sleep(0.5)     # scrape loop ejects the corpse from the pool
        # Continuing traffic lands on the SURVIVING pool (the decode
        # replica serving whole requests locally — unified fallback).
        results = fire(router.url, 8, timeout_s=10.0)
        assert set(results) <= EXPLICIT_STATUSES
        assert results.count(200) >= 4, results
        assert router.snapshot()["disagg_fallbacks"] >= 1
        # Recovery audit: BOTH pools balance their books; in refcount
        # mode the per-owner report must be EMPTY, not merely small.
        audit_quiescent(pre, dec)
        for srv in (pre, dec):
            alloc = srv.engine._allocator
            assert alloc.stats["stamped_allocs"] > 0
            report = alloc.leak_report_by_owner()
            assert report == {}, \
                f"{srv.name}: per-owner leaks after mid-handoff kill: " \
                f"{report}"
            alloc.assert_quiescent()
    finally:
        proxy.stop()
        router.stop()
        for s in (pre, dec):
            try:
                s.stop()
            except OSError:
                pass


@pytest.mark.slow  # tier-1 budget: ~10s; COW-cancel also pinned by kvtier
def test_chaos_cancel_while_shared(monkeypatch):
    """Tiered KV cache (ISSUE 13): cancel a request whose prefix pages
    are SHARED ref>0 with another in-flight request. The co-sharer must
    finish with correct greedy output (its references pin the pages),
    and after it completes the per-owner report must name ZERO leaks on
    the device tier — the cancel freed exactly the victim's own
    references, never the shared content."""
    monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    mk = lambda prefix: LLMEngine(  # noqa: E731
        cfg, BatchingSpec(max_batch_size=2, max_seq_len=96, paged=True,
                          page_size=16, chunked_prefill_tokens=16,
                          decode_steps=4,
                          enable_prefix_caching=prefix),
        params=params)
    eng, base = mk(True), mk(False)
    assert eng._allocator.refcount_debug
    sp = SamplingParams(max_new_tokens=24)
    prompt = [9, 2, 9, 4, 9, 6, 9, 8] * 4
    victim = eng.submit(list(prompt), sp)
    for _ in range(4):
        eng.step()                      # victim prefills + registers
    sharer = eng.submit(list(prompt), sp)
    for _ in range(3):
        eng.step()                      # sharer matches ref>0 pages
    assert eng.kv_tier_stats()["prefix_hits"] >= 1
    victim.cancel()                     # mid-decode, pages shared
    deadline = time.monotonic() + 30.0
    while not sharer.done.is_set():
        eng.step()
        assert time.monotonic() < deadline, "sharer hung after cancel"
    assert victim.finish_reason == "cancelled"
    b = base.submit(list(prompt), sp)
    while not b.done.is_set():
        base.step()
    assert list(sharer.output_tokens) == list(b.output_tokens)
    while eng.kv_pages_in_use() > 0:
        eng.step()
        assert time.monotonic() < deadline
    assert eng._allocator.leak_report_by_owner() == {}
    eng._allocator.assert_quiescent()


@pytest.mark.slow
def test_chaos_kill_mid_migration(monkeypatch):
    """Tiered KV cache (ISSUE 13): SIGKILL a replica while a device→host
    demotion batch is IN FLIGHT on its migration thread. Invariants:
    traffic keeps resolving explicitly on the survivor; the per-owner
    refcount audit names ZERO leaks on BOTH replicas' device pools (the
    demoted pages were freed scheduler-side before the kill — a dead
    migration thread can strand host blobs, never device pages); and
    the host tier stays within budget with no phantom occupancy."""
    monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
    import kubeflow_tpu.serve.kvtier as kvtier

    real_wire = kvtier.pages_to_wire

    def slow_wire(k, v):
        time.sleep(0.25)                # widen the mid-migration window
        return real_wire(k, v)

    monkeypatch.setattr(kvtier, "pages_to_wire", slow_wire)
    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)

    def mk(name):
        eng = LLMEngine(
            cfg,
            BatchingSpec(max_batch_size=2, max_seq_len=96,
                         prefill_buckets=[32], paged=True, page_size=16,
                         chunked_prefill_tokens=16, decode_steps=4,
                         host_kv_pages=48, kv_demote_after_s=0.05),
            params=params)
        srv = ModelServer(name, eng, port=0)
        srv.start()
        return srv

    a, b = mk("mig-a"), mk("mig-b")
    router = Router(queue_timeout=5.0, eject_threshold=2, eject_period=0.4,
                    max_retries=2, upstream_timeout=30.0)
    router.set_backends({"latest": [b.url, a.url]})
    router.start()
    try:
        results = fire(router.url, 8, timeout_s=6.0)
        assert set(results) <= EXPLICIT_STATUSES
        # Wait for a migration batch to be in flight (or already
        # landed) on b, then kill it mid-flight.
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            with b.engine._kvtier._lock:
                migrating = b.engine._kvtier._migrating
            if migrating > 0 or b.engine.kv_pages_host() > 0:
                break
            time.sleep(0.01)
        assert migrating > 0 or b.engine.kv_pages_host() > 0, \
            "no demotion ever started on b"
        kill_model_server(b)
        # Survivor keeps serving explicitly.
        results = fire(router.url, 8, timeout_s=6.0)
        assert set(results) <= EXPLICIT_STATUSES
        assert results.count(200) >= 4, results
        audit_quiescent(a, b)
        for srv in (a, b):
            alloc = srv.engine._allocator
            assert alloc.stats["stamped_allocs"] > 0
            report = alloc.leak_report_by_owner()
            assert report == {}, \
                f"{srv.name}: per-owner leaks after mid-migration kill: " \
                f"{report}"
            alloc.assert_quiescent()
            # Host-tier books: in-flight batches drain (the daemon
            # thread survives the server kill) and occupancy stays
            # consistent with the budget — no phantom pages.
            tier = srv.engine._kvtier
            tier.drain_migrations(timeout_s=10.0)
            snap = tier.snapshot()
            assert 0 <= snap["host_pages_resident"] <= 48
            assert snap["migrating_pages"] == 0
    finally:
        router.stop()
        for s in (a, b):
            try:
                s.stop()
            except OSError:
                pass


@pytest.mark.slow
def test_chaos_int8_prefill_kill_mid_handoff(monkeypatch):
    """Quantized fabric under SIGKILL mid-handoff: an int8-pool prefill
    replica dies between export and ack. The hold backed int8 pages AND
    their scale rows — the per-owner audit must name ZERO leaks on both
    replicas (scales share page identity, so a page freed is its scale
    row freed), and the surviving int8 decode replica keeps serving
    token-consistently with a fresh int8 reference engine."""
    monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)

    def spec(role):
        return BatchingSpec(max_batch_size=2, max_seq_len=96,
                            prefill_buckets=[32], paged=True, page_size=16,
                            chunked_prefill_tokens=16, decode_steps=4,
                            kv_cache_dtype="int8", role=role)

    def mk(name, role):
        srv = ModelServer(name, LLMEngine(cfg, spec(role), params=params),
                          port=0)
        srv.start()
        return srv

    pre, dec = mk("q-pre", "prefill"), mk("q-dec", "decode")
    assert pre.engine.kv_quant and "ks" in pre.engine.cache
    router = Router(queue_timeout=5.0, eject_threshold=2, eject_period=5.0,
                    max_retries=2, upstream_timeout=30.0)
    router.scrape_interval = 0.1
    router.set_pools({"prefill": [pre.url], "decode": [dec.url]})
    router.start()
    try:
        results = fire(router.url, 6, timeout_s=10.0)
        assert set(results) <= EXPLICIT_STATUSES
        assert results.count(200) >= 4, results
        assert pre.engine.metrics.snapshot()["handoff_bytes_exported"] > 0
        # Strand a mid-handoff hold (quantized pages + scale rows), then
        # SIGKILL the prefill replica.
        orphan = pre.engine.submit([7] * 24, SamplingParams(max_new_tokens=8),
                                   handoff=True)
        assert orphan.done.wait(20.0)
        assert orphan.finish_reason == "handoff"
        assert orphan.handoff.cache_dtype == "int8"
        assert pre.engine.kv_pages_in_use() > 0
        kill_model_server(pre)
        time.sleep(0.5)
        # Survivor still serves; unified fallback on the decode pool.
        results = fire(router.url, 8, timeout_s=10.0)
        assert set(results) <= EXPLICIT_STATUSES
        assert results.count(200) >= 4, results
        # Token consistency: the survivor's local decode matches a fresh
        # int8 engine on the same prompt (its pool was never corrupted
        # by the dead peer's half-shipped blob).
        sp = SamplingParams(max_new_tokens=8, temperature=0.0)
        prompt = [3, 1, 4, 1, 5, 9] * 4
        got = dec.engine.generate(list(prompt), sp)
        want = LLMEngine(cfg, spec("unified"),
                         params=params).generate(list(prompt), sp)
        assert got == want, (got, want)
        audit_quiescent(pre, dec)
        for srv in (pre, dec):
            alloc = srv.engine._allocator
            assert alloc.stats["stamped_allocs"] > 0
            assert alloc.leak_report_by_owner() == {}
            alloc.assert_quiescent()
    finally:
        router.stop()
        for s in (pre, dec):
            try:
                s.stop()
            except OSError:
                pass


@pytest.mark.slow
def test_chaos_int8_kill_mid_migration(monkeypatch):
    """Quantized pool under SIGKILL mid-demotion: the migration batch in
    flight carries int8 pages + scale rows (5-tuple queue items → v2
    blobs). Device books must balance to zero per owner on both
    replicas, the host tier stays within budget, and the survivor keeps
    serving token-consistently."""
    monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
    import kubeflow_tpu.serve.kvtier as kvtier

    real_wire = kvtier.pages_to_wire

    def slow_wire(k, v, **kw):
        time.sleep(0.25)                # widen the mid-migration window
        return real_wire(k, v, **kw)

    monkeypatch.setattr(kvtier, "pages_to_wire", slow_wire)
    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)

    def spec():
        return BatchingSpec(max_batch_size=2, max_seq_len=96,
                            prefill_buckets=[32], paged=True, page_size=16,
                            chunked_prefill_tokens=16, decode_steps=4,
                            kv_cache_dtype="int8",
                            host_kv_pages=48, kv_demote_after_s=0.05)

    def mk(name):
        srv = ModelServer(name, LLMEngine(cfg, spec(), params=params),
                          port=0)
        srv.start()
        return srv

    a, b = mk("qmig-a"), mk("qmig-b")
    router = Router(queue_timeout=5.0, eject_threshold=2, eject_period=0.4,
                    max_retries=2, upstream_timeout=30.0)
    router.set_backends({"latest": [b.url, a.url]})
    router.start()
    try:
        results = fire(router.url, 8, timeout_s=6.0)
        assert set(results) <= EXPLICIT_STATUSES
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            with b.engine._kvtier._lock:
                migrating = b.engine._kvtier._migrating
            if migrating > 0 or b.engine.kv_pages_host() > 0:
                break
            time.sleep(0.01)
        assert migrating > 0 or b.engine.kv_pages_host() > 0, \
            "no demotion ever started on b"
        kill_model_server(b)
        results = fire(router.url, 8, timeout_s=6.0)
        assert set(results) <= EXPLICIT_STATUSES
        assert results.count(200) >= 4, results
        sp = SamplingParams(max_new_tokens=8, temperature=0.0)
        prompt = [2, 7, 1, 8, 2, 8] * 4
        got = a.engine.generate(list(prompt), sp)
        want = LLMEngine(cfg, spec(), params=params).generate(
            list(prompt), sp)
        assert got == want, (got, want)
        audit_quiescent(a, b)
        for srv in (a, b):
            alloc = srv.engine._allocator
            assert alloc.stats["stamped_allocs"] > 0
            assert alloc.leak_report_by_owner() == {}
            alloc.assert_quiescent()
            tier = srv.engine._kvtier
            tier.drain_migrations(timeout_s=10.0)
            snap = tier.snapshot()
            assert 0 <= snap["host_pages_resident"] <= 48
            assert snap["migrating_pages"] == 0
    finally:
        router.stop()
        for s in (a, b):
            try:
                s.stop()
            except OSError:
                pass


def test_chaos_zz_replica_kill_mid_traffic(stack):
    """SIGKILL analog mid-traffic (runs last: b never comes back). Requests
    racing the kill resolve explicitly; the router ejects the corpse and
    recovers on the survivor; the dead engine's stranded state reaps to
    zero page leaks."""
    a, b, router = stack

    results = fire(router.url, 12, timeout_s=6.0,
                   mid_fault=lambda: kill_model_server(b), fault_after=2)
    assert set(results) <= EXPLICIT_STATUSES
    assert results.count(200) >= 4, results
    # Router recovered: the survivor serves fresh traffic.
    assert all(s == 200 for s in fire(router.url, 4, timeout_s=10.0))
    snap = router.snapshot()
    assert snap["connect_failures"] >= 1 or snap["http_5xx"] >= 1
    # The killed replica's engine halted where it stood; the reaper must
    # still balance its books (the scheduler loop is dead, so we drive
    # step() by hand — exactly what a recovering supervisor would do).
    audit_quiescent(a, b)
