"""``kftpu lint`` — the static analyzer itself (ISSUE 5).

Contracts pinned here:
- every rule fires on its minimal positive fixture and stays silent on
  the matching negative (annotations close the false positives they are
  documented to close);
- ``# lint: disable=`` suppression and the baseline round-trip work, and
  baseline fingerprints survive unrelated line shifts;
- the two seeded regressions from the acceptance criteria: re-introducing
  the PR-4 per-round ``jnp.asarray(self._table)`` upload into the REAL
  engine and removing one REAL router lock acquisition each produce
  exactly the expected finding — the rules are tuned to this codebase,
  not just to fixtures;
- the repo itself scans clean against the committed baseline.
"""

import json
import os
import subprocess
import sys

from kubeflow_tpu.analysis import (
    Baseline, all_rules, find_baseline, lint_source, run_lint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(src: str, path: str = "kubeflow_tpu/serve/fixture.py"):
    return [f.rule for f in lint_source(src, path)]


# -- Family A: device hygiene --------------------------------------------------


class TestHostSyncInJit:
    def test_np_asarray_in_jitted_fn(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return np.asarray(x) + 1\n")
        assert rules_of(src) == ["D101"]

    def test_item_and_float_on_traced_param(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def step(x, y):\n"
            "    return x.item() + float(y)\n")
        assert rules_of(src) == ["D101", "D101"]

    def test_partial_jit_decorator_and_traced_annotation(self):
        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnums=(1,))\n"
            "def a(x, n):\n"
            "    x.block_until_ready()\n"
            "    return x\n"
            "def b(x):  # traced\n"
            "    return jax.device_get(x)\n")
        assert rules_of(src) == ["D101", "D101"]

    def test_jit_wrapped_local_fn(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "def build():\n"
            "    def inner(x):\n"
            "        return np.asarray(x)\n"
            "    return jax.jit(inner)\n")
        assert rules_of(src) == ["D101"]

    def test_same_calls_outside_jit_are_clean(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "def host_side(x):\n"
            "    return np.asarray(jax.device_get(x)).item()\n")
        assert rules_of(src) == []


class TestHostSyncInHotLoop:
    def test_device_get_in_hot_loop(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def consume(self):  # hot-loop\n"
            "        return jax.device_get(self.buf)\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["D102"]
        assert "consume" in fs[0].message

    def test_sync_point_annotation_is_the_designed_fetch(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def consume(self):  # hot-loop\n"
            "        return jax.device_get(self.buf)"
            "  # sync-point: the one designed fetch\n")
        assert rules_of(src) == []

    def test_sleep_in_hot_loop(self):
        src = (
            "import time\n"
            "def spin():  # hot-loop\n"
            "    time.sleep(0.01)\n")
        assert rules_of(src) == ["D102"]

    def test_unannotated_function_is_clean(self):
        src = (
            "import jax\n"
            "def consume(buf):\n"
            "    return jax.device_get(buf)\n")
        assert rules_of(src) == []


class TestFullBufferReupload:
    POSITIVE = (
        "import jax.numpy as jnp\n"
        "class E:\n"
        "    def dispatch(self):  # hot-loop\n"
        "        return jnp.asarray(self._table)\n")

    def test_persistent_self_buffer_uploaded_per_round(self):
        fs = lint_source(self.POSITIVE)
        assert [f.rule for f in fs] == ["D103"]
        assert "self._table" in fs[0].message

    def test_device_put_of_self_buffer_also_fires(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def dispatch(self):  # hot-loop\n"
            "        return jax.device_put(self._state.arrays)\n")
        assert rules_of(src) == ["D103"]

    def test_local_array_upload_is_clean(self):
        src = (
            "import jax.numpy as jnp\n"
            "class E:\n"
            "    def dispatch(self, row):  # hot-loop\n"
            "        return jnp.asarray(row)\n")
        assert rules_of(src) == []

    def test_lint_disable_suppresses(self):
        src = self.POSITIVE.replace(
            "return jnp.asarray(self._table)",
            "return jnp.asarray(self._table)  # lint: disable=D103")
        assert rules_of(src) == []


class TestDonatedBufferReuse:
    def test_read_after_donating_dispatch(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c, donate_argnums=(0,))\n"
            "    def go(self):\n"
            "        out = self._fn(self.cache)\n"
            "        return self.cache\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["D104"]
        assert "self.cache" in fs[0].message

    def test_rebind_then_read_is_clean(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c, donate_argnums=(0,))\n"
            "    def go(self):\n"
            "        self.cache = self._fn(self.cache)\n"
            "        return self.cache\n")
        assert rules_of(src) == []

    def test_donation_in_one_branch_not_read_in_sibling(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c, donate_argnums=(0,))\n"
            "    def go(self, paged):\n"
            "        if paged:\n"
            "            self.cache = self._fn(self.cache)\n"
            "        else:\n"
            "            out = self.cache\n"
            "        return out\n")
        assert rules_of(src) == []


class TestJitInLoop:
    def test_jit_constructed_per_iteration(self):
        src = (
            "import jax\n"
            "def run(xs):\n"
            "    for x in xs:\n"
            "        f = jax.jit(lambda v: v)\n"
            "        f(x)\n")
        assert rules_of(src) == ["D105"]

    def test_jit_in_hot_loop_function(self):
        src = (
            "import jax\n"
            "def dispatch(x):  # hot-loop\n"
            "    return jax.jit(lambda v: v)(x)\n")
        assert rules_of(src) == ["D105"]

    def test_jit_at_init_is_clean(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda v: v)\n")
        assert rules_of(src) == []


# -- Family B: lock discipline -------------------------------------------------


class TestUnlockedSharedMutation:
    def test_inferred_cross_thread_mutation(self):
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._run,\n"
            "                         daemon=True).start()\n"
            "    def _run(self):\n"
            "        self._items.append(1)\n"
            "    def results(self):\n"
            "        return list(self._items)\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["C301"]
        assert "Worker._items" in fs[0].message

    def test_lock_held_everywhere_is_clean(self):
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._run,\n"
            "                         daemon=True).start()\n"
            "    def _run(self):\n"
            "        with self._lock:\n"
            "            self._items.append(1)\n"
            "    def results(self):\n"
            "        with self._lock:\n"
            "            return list(self._items)\n")
        assert rules_of(src) == []

    def test_guarded_by_contract_checked_without_threads(self):
        # guarded_by turns the attribute into a contract even when the
        # class spawns no threads this module can see.
        src = (
            "import threading\n"
            "class G:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded_by: _lock\n"
            "    def bump(self):\n"
            "        self._n += 1\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["C301"]
        assert "guarded_by" in fs[0].message and "bump" in fs[0].message

    def test_guarded_by_satisfied_under_lock(self):
        src = (
            "import threading\n"
            "class G:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded_by: _lock\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n")
        assert rules_of(src) == []

    def test_locked_suffix_counts_as_holding(self):
        src = (
            "import threading\n"
            "class G:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded_by: _lock\n"
            "    def _bump_locked(self):\n"
            "        self._n += 1\n")
        assert rules_of(src) == []

    def test_requires_lock_annotation(self):
        src = (
            "import threading\n"
            "class G:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded_by: _lock\n"
            "    def _bump(self):  # requires_lock: _lock\n"
            "        self._n += 1\n")
        assert rules_of(src) == []

    def test_lockfree_annotation_closes_inference(self):
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []  # lockfree: scheduler-confined\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._run,\n"
            "                         daemon=True).start()\n"
            "    def _run(self):\n"
            "        self._items.append(1)\n"
            "    def results(self):\n"
            "        return list(self._items)\n")
        assert rules_of(src) == []

    def test_condition_guard_counts_as_its_lock(self):
        src = (
            "import threading\n"
            "class Q:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._cv = threading.Condition(self._lock)\n"
            "        self._pending = {}  # guarded_by: _cv\n"
            "    def add(self, k):\n"
            "        with self._cv:\n"
            "            self._pending[k] = None\n")
        assert rules_of(src) == []


class TestBlockingCallUnderLock:
    def test_sleep_under_lock(self):
        src = (
            "import threading, time\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def poll(self):\n"
            "        with self._lock:\n"
            "            time.sleep(0.1)\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["C302"]
        assert "time.sleep" in fs[0].message

    def test_thread_join_under_lock(self):
        src = (
            "import threading\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def stop(self):\n"
            "        with self._lock:\n"
            "            self._thread.join()\n")
        assert rules_of(src) == ["C302"]

    def test_sleep_outside_lock_is_clean(self):
        src = (
            "import threading, time\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def poll(self):\n"
            "        with self._lock:\n"
            "            n = 1\n"
            "        time.sleep(0.1)\n")
        assert rules_of(src) == []

    def test_condition_wait_is_exempt(self):
        # Condition.wait releases the lock — the whole point of a CV.
        src = (
            "import threading\n"
            "class Q:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._cv = threading.Condition(self._lock)\n"
            "    def pop(self):\n"
            "        with self._cv:\n"
            "            self._cv.wait(1.0)\n")
        assert rules_of(src) == []


class TestSwallowedException:
    def test_bare_except_pass(self):
        src = (
            "def reconcile(work):\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        pass\n")
        assert rules_of(src) == ["C303"]

    def test_logged_broad_except_is_clean(self):
        src = (
            "import logging\n"
            "def reconcile(work):\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        logging.exception('reconcile failed')\n")
        assert rules_of(src) == []

    def test_narrow_except_pass_is_clean(self):
        src = (
            "def probe(work):\n"
            "    try:\n"
            "        work()\n"
            "    except ValueError:\n"
            "        pass\n")
        assert rules_of(src) == []

    def test_reraise_is_clean(self):
        src = (
            "def run(work):\n"
            "    try:\n"
            "        work()\n"
            "    except BaseException:\n"
            "        raise\n")
        assert rules_of(src) == []


# -- Family S: sharding / SPMD -------------------------------------------------


class TestUndonatedCarry:
    def test_carry_without_donation(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c)\n"
            "    def go(self):\n"
            "        self.cache = self._fn(self.cache)\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["S401"]
        assert "self._fn" in fs[0].message and "self.cache" in fs[0].message

    def test_tuple_target_carry(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda p, c: (1, c))\n"
            "    def go(self):\n"
            "        out, self.cache = self._fn(self.params, self.cache)\n")
        assert rules_of(src) == ["S401"]

    def test_donated_carry_is_clean(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c, donate_argnums=(0,))\n"
            "    def go(self):\n"
            "        self.cache = self._fn(self.cache)\n")
        assert rules_of(src) == []

    def test_non_carry_call_is_clean(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda x: x)\n"
            "    def go(self):\n"
            "        out = self._fn(self.logits)\n"
            "        return out\n")
        assert rules_of(src) == []


class TestUnknownMeshAxis:
    def test_typo_in_partition_spec(self):
        src = (
            "from jax.sharding import PartitionSpec\n"
            "spec = PartitionSpec('modle', None)\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["S402"]
        assert "modle" in fs[0].message

    def test_axis_name_kwarg_and_tuple(self):
        src = (
            "import jax\n"
            "from jax.sharding import PartitionSpec as P\n"
            "spec = P(('dcn', 'dat'), None)\n"
            "def f(x):  # mesh-context: test fixture\n"
            "    return jax.lax.psum(x, axis_name='modell')\n")
        assert rules_of(src) == ["S402", "S402"]

    def test_canonical_axes_clean(self):
        src = (
            "from jax.sharding import PartitionSpec as P\n"
            "spec = P(('dcn', 'data', 'fsdp'), 'seq', 'model')\n")
        assert rules_of(src) == []

    def test_canonical_set_matches_runtime_mesh(self):
        from kubeflow_tpu.analysis.core import canonical_mesh_axes
        from kubeflow_tpu.runtime.mesh import MESH_AXES

        assert canonical_mesh_axes() == MESH_AXES


class TestHostRoundTrip:
    def test_fetch_then_dispatch(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c, donate_argnums=(0,))\n"
            "    def go(self, st):\n"
            "        lens = jax.device_get(st)\n"
            "        return self._fn(lens)\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["S403"]
        assert "lens" in fs[0].message

    def test_taint_propagates_through_assignment(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c, donate_argnums=(0,))\n"
            "    def go(self, st):\n"
            "        host = np.asarray(st)\n"
            "        padded = host + 1\n"
            "        return self._fn(padded)\n")
        assert rules_of(src) == ["S403"]

    def test_fetch_after_dispatch_is_clean(self):
        # the engine's draft-propose pattern: dispatch first, fetch after
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c, donate_argnums=(0,))\n"
            "    def go(self, st):\n"
            "        out = self._fn(st)\n"
            "        host = jax.device_get(out)\n"
            "        return host\n")
        assert rules_of(src) == []

    def test_rebinding_clears_taint(self):
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c, donate_argnums=(0,))\n"
            "    def go(self, st):\n"
            "        host = jax.device_get(st)\n"
            "        host = jnp.zeros((4,))\n"
            "        return self._fn(host)\n")
        assert rules_of(src) == []


class TestImplicitReplication:
    def test_unsharded_params_device_put(self):
        src = (
            "import jax\n"
            "from jax.sharding import NamedSharding\n"
            "def load(params):\n"
            "    return jax.device_put(params)\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["S404"]
        assert "shard_params" in fs[0].message

    def test_sharded_put_is_clean(self):
        src = (
            "import jax\n"
            "from jax.sharding import NamedSharding\n"
            "def load(params, sh):\n"
            "    return jax.device_put(params, sh)\n")
        assert rules_of(src) == []

    def test_non_mesh_module_is_clean(self):
        src = (
            "import jax\n"
            "def load(params):\n"
            "    return jax.device_put(params)\n")
        assert rules_of(src) == []


class TestUnboundCollective:
    def test_literal_axis_without_shard_map(self):
        src = (
            "import jax\n"
            "def allreduce(x):\n"
            "    return jax.lax.psum(x, 'model')\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["S405"]
        assert "model" in fs[0].message

    def test_shard_mapped_fn_is_bound(self):
        src = (
            "import jax\n"
            "from jax.experimental.shard_map import shard_map\n"
            "def worker(x):\n"
            "    return jax.lax.psum(x, 'model')\n"
            "def build(mesh, spec):\n"
            "    return shard_map(worker, mesh=mesh, in_specs=(spec,),\n"
            "                     out_specs=spec)\n")
        assert rules_of(src) == []

    def test_one_level_callee_of_shard_mapped_fn_is_bound(self):
        src = (
            "import jax\n"
            "from jax.experimental.shard_map import shard_map\n"
            "def reduce_part(x):\n"
            "    return jax.lax.psum(x, 'model')\n"
            "def worker(x):\n"
            "    return reduce_part(x) + 1\n"
            "def build(mesh, spec):\n"
            "    return shard_map(worker, mesh=mesh, in_specs=(spec,),\n"
            "                     out_specs=spec)\n")
        assert rules_of(src) == []

    def test_mesh_context_annotation_closes_it(self):
        src = (
            "import jax\n"
            "def allreduce(x):  # mesh-context: stage fn, bound in pipeline.py\n"
            "    return jax.lax.psum(x, 'model')\n")
        assert rules_of(src) == []

    def test_variable_axis_is_clean(self):
        src = (
            "import jax\n"
            "def allreduce(x, axis_name):\n"
            "    return jax.lax.psum(x, axis_name)\n")
        assert rules_of(src) == []


# -- Family R: resources & ordering --------------------------------------------


class TestLeakedAlloc:
    def test_risky_call_between_alloc_and_record(self):
        src = (
            "class E:\n"
            "    def grow(self, idx, n):\n"
            "        new = self._allocator.alloc(n)\n"
            "        self._refresh_gauge()\n"
            "        self._slot_pages[idx].extend(new)\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["R501"]
        assert "new" in fs[0].message and "grow" in fs[0].message

    def test_immediate_record_is_clean(self):
        src = (
            "class E:\n"
            "    def grow(self, idx, n):\n"
            "        new = self._allocator.alloc(n)\n"
            "        self._slot_pages[idx].extend(new)\n"
            "        self._refresh_gauge()\n")
        assert rules_of(src) == []

    def test_handler_free_is_clean(self):
        src = (
            "class E:\n"
            "    def grow(self, idx, n):\n"
            "        try:\n"
            "            new = self._allocator.alloc(n)\n"
            "            self._risky_dispatch()\n"
            "        except Exception:\n"
            "            self._allocator.free(new)\n"
            "            raise\n"
            "        self._slot_pages[idx].extend(new)\n")
        assert rules_of(src) == []

    def test_record_after_try_is_clean(self):
        # the engine's real _ensure_pages shape: alloc inside try (for
        # PagePoolExhausted), ownership recorded right after the try.
        src = (
            "class E:\n"
            "    def grow(self, idx, n):\n"
            "        try:\n"
            "            new = self._allocator.alloc(n)\n"
            "        except PagePoolExhausted:\n"
            "            return False\n"
            "        self._slot_pages[idx].extend(new)\n"
            "        return True\n")
        assert rules_of(src) == []

    def test_never_recorded_alloc_fires(self):
        src = (
            "class E:\n"
            "    def grow(self, n):\n"
            "        new = self._allocator.alloc(n)\n")
        assert rules_of(src) == ["R501"]


class TestUnauditedPagedTest:
    def test_paged_test_without_audit(self):
        src = (
            "def test_paged_decode(mk_engine):\n"
            "    eng = mk_engine(paged=True)\n"
            "    eng.generate([1, 2, 3])\n")
        fs = lint_source(src, "tests/test_fixture_x.py")
        assert [f.rule for f in fs] == ["R502"]

    def test_direct_audit_is_clean(self):
        src = (
            "def test_paged_decode(mk_engine):\n"
            "    eng = mk_engine(paged=True)\n"
            "    eng.generate([1, 2, 3])\n"
            "    eng._allocator.assert_quiescent()\n")
        assert [f.rule for f in lint_source(
            src, "tests/test_fixture_x.py")] == []

    def test_helper_audit_one_level_is_clean(self):
        src = (
            "def audit(eng):\n"
            "    assert eng.kv_pages_in_use() == 0\n"
            "def test_paged_decode(mk_engine):\n"
            "    eng = mk_engine(paged=True)\n"
            "    eng.generate([1, 2, 3])\n"
            "    audit(eng)\n")
        assert [f.rule for f in lint_source(
            src, "tests/test_fixture_x.py")] == []

    def test_non_test_path_ignored(self):
        src = (
            "def test_paged_decode(mk_engine):\n"
            "    eng = mk_engine(paged=True)\n")
        assert [f.rule for f in lint_source(
            src, "kubeflow_tpu/serve/fixture.py")] == []


class TestLockOrderInversion:
    INVERTED = (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n")

    def test_two_lock_cycle(self):
        fs = lint_source(self.INVERTED)
        assert [f.rule for f in fs] == ["R503"]
        assert "S._a" in fs[0].message and "S._b" in fs[0].message

    def test_consistent_order_is_clean(self):
        src = self.INVERTED.replace(
            "        with self._b:\n"
            "            with self._a:\n",
            "        with self._a:\n"
            "            with self._b:\n")
        assert rules_of(src) == []

    def test_one_level_helper_acquisition(self):
        src = (
            "import threading\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def one(self):\n"
            "        with self._a:\n"
            "            self._grab_b()\n"
            "    def _grab_b(self):\n"
            "        with self._b:\n"
            "            pass\n"
            "    def two(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n")
        assert rules_of(src) == ["R503"]

    def test_condition_canonicalizes_to_its_lock(self):
        # Condition(self._a) IS lock _a: with-ing the condition in one
        # method and the lock in another is NOT an inversion.
        src = (
            "import threading\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._cv = threading.Condition(self._a)\n"
            "        self._b = threading.Lock()\n"
            "    def one(self):\n"
            "        with self._cv:\n"
            "            with self._b:\n"
            "                pass\n"
            "    def two(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n")
        assert rules_of(src) == []


class TestUnhandledCheckpointIO:
    def test_bare_save_and_unguarded_restore_fire(self):
        src = (
            "def resume(ckpt, abstract):\n"
            "    state = ckpt.restore(abstract)\n"
            "    return state\n"
            "class T:\n"
            "    def save(self, step):\n"
            "        self.ckpt.save(step, self.state)\n")
        fs = lint_source(src, "kubeflow_tpu/train/fixture.py")
        assert [f.rule for f in fs] == ["R504", "R504"]
        assert "restore" in fs[0].message and "save" in fs[1].message

    def test_try_handler_is_clean(self):
        src = (
            "def resume(ckpt, abstract):\n"
            "    try:\n"
            "        return ckpt.restore(abstract)\n"
            "    except CheckpointCorruptionError:\n"
            "        return None\n"
            "class T:\n"
            "    def save(self, step):\n"
            "        try:\n"
            "            self.ckpt.save(step, self.state)\n"
            "        except OSError:\n"
            "            self.failures += 1\n")
        assert rules_of(src, "kubeflow_tpu/train/fixture.py") == []

    def test_consumed_save_return_is_clean(self):
        src = (
            "class T:\n"
            "    def save(self, step):\n"
            "        accepted = self.ckpt.save(step, self.state)\n"
            "        if not accepted:\n"
            "            self.failures += 1\n")
        assert rules_of(src, "kubeflow_tpu/train/fixture.py") == []

    def test_non_checkpoint_receiver_ignored(self):
        src = (
            "def load(mgr, path):\n"
            "    mgr.restore(path)\n"
            "    store.save(path)\n")
        assert rules_of(src, "kubeflow_tpu/serve/fixture.py") == []

    def test_test_paths_exempt(self):
        src = (
            "def test_resume(ckpt, abstract):\n"
            "    state = ckpt.restore(abstract)\n"
            "    ckpt.save(1, state)\n")
        assert [f.rule for f in lint_source(src, "tests/test_x.py")] == []

    def test_suppression_comment(self):
        src = (
            "def resume(ckpt, abstract):\n"
            "    return ckpt.restore(abstract)  # lint: disable=R504\n")
        assert rules_of(src, "kubeflow_tpu/train/fixture.py") == []

    def test_real_trainer_is_clean(self):
        """The shipped Trainer handles both: try_resume walks tiers under
        a fallback, save checks the acceptance bool inside try/except."""
        relpath = "kubeflow_tpu/train/trainer.py"
        with open(os.path.join(REPO, relpath)) as f:
            fs = [x for x in lint_source(f.read(), relpath)
                  if x.rule == "R504"]
        assert fs == []


# -- interprocedural core (one-level call-following) ---------------------------


class TestCallFollowing:
    def test_d101_sees_through_helper(self):
        # helper only ever called from jitted code: its host sync fires
        src = (
            "import jax\n"
            "import numpy as np\n"
            "def fetch(x):\n"
            "    return np.asarray(x)\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return fetch(x) + 1\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["D101"]
        assert fs[0].symbol == "fetch"

    def test_d101_skips_helper_shared_with_host_path(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "def fetch(x):\n"
            "    return np.asarray(x)\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return fetch(x) + 1\n"
            "def host_side(x):\n"
            "    return fetch(x)\n")
        assert rules_of(src) == []

    def test_d104_read_inside_helper(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c, donate_argnums=(0,))\n"
            "    def go(self):\n"
            "        out = self._fn(self.cache)\n"
            "        self._peek()\n"
            "        return out\n"
            "    def _peek(self):\n"
            "        return self.cache.shape\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["D104"]
        assert "self.cache" in fs[0].message

    def test_d104_helper_rebind_is_clean(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c, donate_argnums=(0,))\n"
            "    def go(self):\n"
            "        out = self._fn(self.cache)\n"
            "        self._rebuild()\n"
            "        return self.cache\n"
            "    def _rebuild(self):\n"
            "        self.cache = None\n")
        assert rules_of(src) == []

    def test_c301_caller_held_lock_inference(self):
        # private helper only called under the lock: its mutation counts
        # as guarded WITHOUT a # requires_lock annotation
        src = (
            "import threading\n"
            "class G:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded_by: _lock\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._bump_inner()\n"
            "    def _bump_inner(self):\n"
            "        self._n += 1\n")
        assert rules_of(src) == []

    def test_c301_mixed_call_sites_still_fire(self):
        # one call site does NOT hold the lock: inference must not silence
        src = (
            "import threading\n"
            "class G:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded_by: _lock\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._bump_inner()\n"
            "    def bump_unlocked(self):\n"
            "        self._bump_inner()\n"
            "    def _bump_inner(self):\n"
            "        self._n += 1\n")
        assert rules_of(src) == ["C301"]

    def test_c302_blocking_helper_under_lock(self):
        # the helper is only ever called under the lock, so caller-held
        # inference flags its sleep DIRECTLY (one finding, in the helper)
        src = (
            "import threading, time\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def poll(self):\n"
            "        with self._lock:\n"
            "            self._wait_a_bit()\n"
            "    def _wait_a_bit(self):\n"
            "        time.sleep(0.1)\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["C302"]
        assert fs[0].symbol.endswith("_wait_a_bit")

    def test_c302_helper_followed_from_mixed_call_sites(self):
        # one unlocked call site kills the inference; the lock-held call
        # site still reports via one-level following
        src = (
            "import threading, time\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def poll(self):\n"
            "        with self._lock:\n"
            "            self._wait_a_bit()\n"
            "    def idle(self):\n"
            "        self._wait_a_bit()\n"
            "    def _wait_a_bit(self):\n"
            "        time.sleep(0.1)\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["C302"]
        assert "_wait_a_bit" in fs[0].message


# -- metric-name rules ---------------------------------------------------------


class TestMetricRules:
    def test_missing_prefix(self):
        src = "def setup(reg):\n    reg.counter('queue_depth', 'help')\n"
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["M201"]
        assert "kftpu_" in fs[0].message

    def test_bad_grammar(self):
        src = "def setup(reg):\n    reg.gauge('kftpu_bad-name', 'help')\n"
        assert rules_of(src) == ["M201"]

    def test_fstring_head_checked(self):
        src = (
            "def setup(reg, kind):\n"
            "    reg.gauge(f'queue_{kind}_depth', 'help')\n"
            "    reg.gauge(f'kftpu_{kind}_depth', 'help')\n")
        assert rules_of(src) == ["M201"]

    def test_duplicate_family_in_one_function(self):
        src = (
            "def setup(reg):\n"
            "    reg.counter('kftpu_reqs_total', 'a')\n"
            "    reg.counter('kftpu_reqs_total', 'b')\n")
        assert rules_of(src) == ["M202"]

    def test_good_names_clean(self):
        src = (
            "def setup(reg):\n"
            "    reg.counter('kftpu_reqs_total', 'a')\n"
            "    reg.histogram('kftpu_latency_seconds', 'b')\n")
        assert rules_of(src) == []

    def test_fstring_expanded_via_literal_loop(self):
        # the PR-6 labeled-series idiom: the loop's literal values expand
        # the f-string, so FULL grammar (not just the prefix) is checked
        src = (
            "def setup(reg, snap):\n"
            "    for k in ('ttft_p95_ms', 'bad-grammar'):\n"
            "        reg.gauge(f'kftpu_serving_{k}').set(snap[k])\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["M201"]
        assert "bad-grammar" in fs[0].message

    def test_fstring_loop_expansion_all_good_is_clean(self):
        src = (
            "def setup(reg, snap):\n"
            "    for k in ('ttft_p95_ms', 'queue_delay_p95_ms'):\n"
            "        reg.gauge(f'kftpu_serving_{k}').set(snap[k])\n")
        assert rules_of(src) == []

    def test_fstring_loop_expansion_duplicate_detected(self):
        src = (
            "def setup(reg):\n"
            "    for k in ('depth', 'depth'):\n"
            "        reg.gauge(f'kftpu_q_{k}')\n")
        assert rules_of(src) == ["M202"]

    def test_reserved_label_at_sample_site(self):
        src = (
            "def setup(reg):\n"
            "    g = reg.gauge('kftpu_latency_p95_ms')\n"
            "    g.set(1.0, le='0.5')\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["M203"]
        assert "le" in fs[0].message

    def test_reserved_label_in_dict_splat(self):
        src = (
            "def setup(reg):\n"
            "    reg.counter('kftpu_reqs_total').inc(1, **{'quantile': 'x'})\n")
        assert rules_of(src) == ["M203"]

    def test_normal_labels_clean(self):
        src = (
            "def setup(reg, name, cls):\n"
            "    q = reg.counter('kftpu_serving_qos_requests_total')\n"
            "    q.inc(3, model=name, qos=cls)\n")
        assert rules_of(src) == []


# -- core machinery ------------------------------------------------------------


class TestBaseline:
    SRC = TestFullBufferReupload.POSITIVE

    def test_round_trip(self, tmp_path):
        findings = lint_source(self.SRC, "pkg/mod.py")
        assert findings
        path = str(tmp_path / "baseline.json")
        Baseline.from_findings(findings, reason="seed fixture").save(path)
        loaded = Baseline.load(path)
        new, matched = loaded.split(lint_source(self.SRC, "pkg/mod.py"))
        assert new == [] and len(matched) == len(findings)
        # the file is valid JSON with a reason per entry
        doc = json.loads(open(path).read())
        assert all(e["reason"] for e in doc["entries"])

    def test_fingerprints_survive_line_shifts(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        Baseline.from_findings(
            lint_source(self.SRC, "pkg/mod.py")).save(path)
        shifted = "# a new header comment\n\n" + self.SRC
        new, matched = Baseline.load(path).split(
            lint_source(shifted, "pkg/mod.py"))
        assert new == [] and matched

    def test_second_occurrence_is_new(self, tmp_path):
        # The baseline budget is a multiset: one entry forgives ONE
        # occurrence, a second identical defect is still a finding.
        path = str(tmp_path / "baseline.json")
        Baseline.from_findings(
            lint_source(self.SRC, "pkg/mod.py")).save(path)
        doubled = self.SRC + (
            "    def dispatch2(self):  # hot-loop\n"
            "        return jnp.asarray(self._table)\n")
        new, matched = Baseline.load(path).split(
            lint_source(doubled, "pkg/mod.py"))
        assert len(matched) == 1 and len(new) == 1

    def test_committed_baseline_exists(self):
        path = find_baseline([os.path.join(REPO, "kubeflow_tpu")])
        assert path is not None
        assert os.path.basename(path) == ".kftpu-lint-baseline.json"
        assert os.path.dirname(path) == REPO


class TestRegistry:
    def test_all_families_registered(self):
        ids = {r.id for r in all_rules()}
        assert {"D101", "D102", "D103", "D104", "D105",
                "C301", "C302", "C303", "M201", "M202", "M203",
                "S401", "S402", "S403", "S404", "S405",
                "R501", "R502", "R503", "R504",
                "F601", "F602", "F603", "F604", "F605",
                "T801", "T802", "T803", "T804", "T805"} <= ids

    def test_parse_error_is_reported_not_raised(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def broken(:\n")
        result = run_lint([str(bad)], root=str(tmp_path))
        assert not result.ok
        assert [e.rule for e in result.errors] == ["E000"]


# -- Family F: compilation stability (ISSUE 8) ---------------------------------


_F_PRELUDE = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "import numpy as np\n"
    "def _impl(x, y=None):\n"
    "    return x\n"
)


class TestUnstableTraceShape:
    def test_len_derived_shape_into_dispatch(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, reqs):\n"
            "        n = len(reqs)\n"
            "        toks = np.zeros((n, 8), np.int32)\n"
            "        return self._fn(jnp.asarray(toks))\n")
        assert rules_of(src) == ["F601"]

    def test_pow2_padded_width_is_clean(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, reqs):\n"
            "        n = len(reqs)\n"
            "        width = 1\n"
            "        while width < n:\n"
            "            width *= 2\n"
            "        toks = np.zeros((width, 8), np.int32)\n"
            "        return self._fn(jnp.asarray(toks))\n")
        assert rules_of(src) == []

    def test_bucket_helper_stabilizes(self):
        src = _F_PRELUDE + (
            "def _bucket_for(n):\n"
            "    return 64\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, reqs):\n"
            "        n = _bucket_for(len(reqs))\n"
            "        return self._fn(np.zeros((n, 8), np.int32))\n")
        assert rules_of(src) == []

    def test_tainted_slice_bound(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, buf, reqs):\n"
            "        n = len(reqs)\n"
            "        return self._fn(buf[:n])\n")
        assert rules_of(src) == ["F601"]

    def test_retrace_ok_annotation_closes(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, reqs):\n"
            "        n = len(reqs)\n"
            "        # retrace-ok: cold admin path, one call per restart\n"
            "        return self._fn(np.zeros((n,), np.int32))\n")
        assert rules_of(src) == []

    def test_lint_disable_suppresses(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, reqs):\n"
            "        n = len(reqs)\n"
            "        return self._fn(np.zeros((n,), np.int32))  "
            "# lint: disable=F601\n")
        assert rules_of(src) == []


class TestWeakTypeLeak:
    def test_scalar_literal_into_traced_arg(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, x):\n"
            "        return self._fn(x, 0.5)\n")
        assert rules_of(src) == ["F602"]

    def test_float_result_var_and_dtype_less_asarray(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, x, raw):\n"
            "        t = float(raw)\n"
            "        return self._fn(x, jnp.asarray(t))\n")
        assert rules_of(src) == ["F602"]

    def test_explicit_dtype_is_clean(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, x, raw):\n"
            "        t = float(raw)\n"
            "        a = self._fn(x, jnp.float32(0.5))\n"
            "        b = self._fn(x, jnp.asarray(t, jnp.float32))\n"
            "        return a, b\n")
        assert rules_of(src) == []

    def test_static_argnum_position_is_exempt(self):
        # the engine's `self._decode_n(..., k_steps, mode)` idiom: ints
        # in static positions are hashed, not traced — no weak type
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl, static_argnums=(1,))\n"
            "    def run(self, x):\n"
            "        return self._fn(x, 16)\n")
        assert rules_of(src) == []

    def test_static_argname_kwarg_is_exempt(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl, static_argnames=('y',))\n"
            "    def run(self, x):\n"
            "        return self._fn(x, y=16)\n")
        assert rules_of(src) == []

    def test_suppression(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, x):\n"
            "        return self._fn(x, 0.5)  # lint: disable=F602\n")
        assert rules_of(src) == []


class TestDtypePromotionDrift:
    def test_sites_disagree_on_dtype(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def a(self, x):\n"
            "        return self._fn(jnp.asarray(x, jnp.float32))\n"
            "    def b(self, x):\n"
            "        return self._fn(jnp.asarray(x, jnp.bfloat16))\n")
        found = rules_of(src)
        assert found == ["F603"]

    def test_consistent_dtype_is_clean(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def a(self, x):\n"
            "        return self._fn(jnp.asarray(x, jnp.float32))\n"
            "    def b(self, x):\n"
            "        return self._fn(x.astype(jnp.float32))\n")
        assert rules_of(src) == []

    def test_suppression(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def a(self, x):\n"
            "        return self._fn(jnp.asarray(x, jnp.float32))\n"
            "    def b(self, x):\n"
            "        return self._fn(jnp.asarray(x, jnp.bfloat16))  "
            "# lint: disable=F603\n")
        assert rules_of(src) == []


class TestStaticArgInstability:
    def test_fresh_tuple_of_runtime_values(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl, static_argnums=(1,))\n"
            "    def run(self, x, n):\n"
            "        return self._fn(x, (n, 1))\n")
        assert rules_of(src) == ["F604"]

    def test_constant_tuple_is_clean(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl, static_argnums=(1,))\n"
            "    def run(self, x):\n"
            "        return self._fn(x, (4, 5))\n")
        assert rules_of(src) == []

    def test_fresh_lambda_and_partial(self):
        src = _F_PRELUDE + (
            "import functools\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl, static_argnums=(1,))\n"
            "    def a(self, x):\n"
            "        return self._fn(x, lambda v: v)\n"
            "    def b(self, x, g):\n"
            "        return self._fn(x, functools.partial(g, 1))\n")
        assert rules_of(src) == ["F604", "F604"]

    def test_non_static_tuple_is_fine(self):
        # a tuple in a TRACED position is just a pytree of leaves
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, x, a, b):\n"
            "        return self._fn((a, b))\n")
        assert rules_of(src) == []

    def test_retrace_ok_escape(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl, static_argnums=(1,))\n"
            "    def run(self, x, n):\n"
            "        # retrace-ok: shapes enumerate a tiny fixed set\n"
            "        return self._fn(x, (n, 1))\n")
        assert rules_of(src) == []

    def test_lint_disable_suppresses(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl, static_argnums=(1,))\n"
            "    def run(self, x, n):\n"
            "        return self._fn(x, (n, 1))  # lint: disable=F604\n")
        assert rules_of(src) == []


class TestPytreeStructureInstability:
    def test_call_sites_disagree_on_keys(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def a(self, x):\n"
            "        return self._fn({'a': x, 'b': x})\n"
            "    def b(self, x):\n"
            "        return self._fn({'a': x})\n")
        assert rules_of(src) == ["F605"]

    def test_conditional_key_insert_before_dispatch(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, x, flag):\n"
            "        d = {'a': x}\n"
            "        if flag:\n"
            "            d['c'] = x\n"
            "        return self._fn(d)\n")
        assert rules_of(src) == ["F605"]

    def test_same_keys_and_unconditional_insert_are_clean(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def a(self, x):\n"
            "        return self._fn({'a': x, 'b': x})\n"
            "    def b(self, x):\n"
            "        d = {'a': x}\n"
            "        d['b'] = x\n"
            "        return self._fn(d)\n")
        assert rules_of(src) == []

    def test_value_update_is_clean(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, x, flag):\n"
            "        d = {'a': x}\n"
            "        if flag:\n"
            "            d['a'] = x + 1\n"
            "        return self._fn(d)\n")
        assert rules_of(src) == []

    def test_insert_in_same_branch_as_dispatch_is_clean(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, x, flag):\n"
            "        d = {'a': x}\n"
            "        if flag:\n"
            "            d['c'] = x\n"
            "            return self._fn(d)\n"
            "        return x\n")
        assert rules_of(src) == []

    def test_spread_rebuild_is_opaque(self):
        # the engine's `{**st, 'tokens': t}` rebuild preserves structure
        # by construction and must not be compared against literals
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def a(self, st, t):\n"
            "        return self._fn({**st, 'tokens': t})\n"
            "    def b(self, x):\n"
            "        return self._fn({'a': x})\n")
        assert rules_of(src) == []

    def test_suppression(self):
        src = _F_PRELUDE + (
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(_impl)\n"
            "    def run(self, x, flag):\n"
            "        d = {'a': x}\n"
            "        if flag:\n"
            "            d['c'] = x\n"
            "        return self._fn(d)  # lint: disable=F605\n")
        assert rules_of(src) == []


# -- whole-program core (ISSUE 8 tentpole) -------------------------------------


class TestProgram:
    A = (
        "import jax\n"
        "def g(x, k):\n"
        "    return x\n"
        "G = jax.jit(g, static_argnums=(1,))\n")

    def test_imported_jit_fact_carries_static_argnums(self):
        """A jitted callable defined in one module keeps its static-arg
        spec at call sites in another: the importing module's bare int in
        the static position is NOT a weak-type leak, and a fresh tuple
        there IS an F604."""
        b_ok = (
            "from kubeflow_tpu.a import G\n"
            "def run(x):\n"
            "    return G(x, 16)\n")
        b_bad = (
            "from kubeflow_tpu.a import G\n"
            "def run(x, n):\n"
            "    return G(x, (n, 1))\n")
        from kubeflow_tpu.analysis import lint_sources
        assert lint_sources({"kubeflow_tpu/a.py": self.A,
                             "kubeflow_tpu/b.py": b_ok},
                            lint=["kubeflow_tpu/b.py"]) == []
        found = lint_sources({"kubeflow_tpu/a.py": self.A,
                              "kubeflow_tpu/b.py": b_bad},
                             lint=["kubeflow_tpu/b.py"])
        assert [f.rule for f in found] == ["F604"]

    def test_resolve_and_transitive_callees(self):
        from kubeflow_tpu.analysis import Module, Program

        a = Module("kubeflow_tpu/a.py", "def leaf():\n    return 1\n")
        b = Module("kubeflow_tpu/b.py",
                   "from kubeflow_tpu.a import leaf\n"
                   "def mid():\n"
                   "    return leaf()\n")
        c = Module("kubeflow_tpu/c.py",
                   "from kubeflow_tpu.b import mid\n"
                   "def top():\n"
                   "    return mid()\n")
        prog = Program([a, b, c])
        got = prog.resolve("kubeflow_tpu.a.leaf")
        assert got is not None and got[0] is a
        top = c.callgraph.module_fns["top"]
        names = [fn.name for _, fn in prog.transitive_callees(c, top)]
        assert names == ["mid", "leaf"]
        # depth bound: 1 stops at mid
        names1 = [fn.name
                  for _, fn in prog.transitive_callees(c, top, depth=1)]
        assert names1 == ["mid"]

    def test_standalone_module_still_lints(self):
        # no Program attached: rules degrade to module-local facts
        src = _F_PRELUDE + (
            "F = jax.jit(_impl)\n"
            "def run(x):\n"
            "    return F(x, 0.5)\n")
        assert rules_of(src) == ["F602"]

    def test_jit_table_collects_decorated_and_assigned(self):
        from kubeflow_tpu.analysis import Module, jit_table

        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnums=(2,), donate_argnums=(0,))\n"
            "def dec(a, b, c):\n"
            "    return a\n"
            "def _imp(x):\n"
            "    return x\n"
            "J = jax.jit(_imp, donate_argnames=('x',))\n")
        table = jit_table(Module("kubeflow_tpu/t.py", src))
        assert table["dec"].static_argnums == (2,)
        assert table["dec"].donate_argnums == (0,)
        assert table["J"].donate_argnames == ("x",)
        assert table["J"].donates


# -- Family X: cross-component name contracts (ISSUE 10 tentpole) --------------


def xrules(sources: dict, lint=None):
    from kubeflow_tpu.analysis import lint_sources

    return [f for f in lint_sources(sources, lint=lint)
            if f.rule.startswith("X7")]


class TestConsumedSeriesNeverProduced:
    PRODUCER = (
        "def reg_metrics(reg, snap):\n"
        "    reg.counter('kftpu_fix_total')\n"
        "    reg.histogram('kftpu_fix_delay_seconds', [0.1])\n"
        "    for k in ('util',):\n"
        "        reg.gauge(f'kftpu_fixd_{k}')\n"
        "    for k, v in snap.items():\n"
        "        reg.gauge(f'kftpu_fixdyn_{k}').set(v)\n")

    def _consumer(self, *names):
        chain = "".join(
            f"        elif name == '{n}':\n            out.append(value)\n"
            for n in names)
        return ("def probe(samples):\n"
                "    out = []\n"
                "    for name, labels, value in samples:\n"
                "        if False:\n"
                "            pass\n" + chain + "    return out\n")

    def _lint_consumer(self, *names):
        return xrules(
            {"kubeflow_tpu/serve/prod.py": self.PRODUCER,
             "kubeflow_tpu/serve/cons.py": self._consumer(*names)},
            lint=["kubeflow_tpu/serve/cons.py"])

    def test_exact_loop_expanded_suffix_and_prefix_names_match(self):
        """Every producer spelling counts: literal, loop-expanded
        f-string, histogram ``_bucket`` fan-out, dynamic f-string
        prefix."""
        assert self._lint_consumer(
            "kftpu_fix_total", "kftpu_fixd_util",
            "kftpu_fix_delay_seconds_bucket",
            "kftpu_fixdyn_anything") == []

    def test_renamed_consumer_is_caught(self):
        found = self._lint_consumer("kftpu_fix_total", "kftpu_fix_missing")
        assert [f.rule for f in found] == ["X701"]
        assert "kftpu_fix_missing" in found[0].message

    def test_contract_annotation_closes_it(self):
        src = ("def probe(samples):\n"
               "    for name, labels, value in samples:\n"
               "        # contract: produced by an out-of-scan exporter\n"
               "        if name == 'kftpu_fix_external':\n"
               "            return value\n")
        assert xrules({"kubeflow_tpu/serve/cons.py": src}) == []

    def test_standalone_lint_source_is_silent(self):
        """Without a Program the cross-component family must not guess
        from one module's half of the contract."""
        found = lint_source(self._consumer("kftpu_fix_missing"),
                            "kubeflow_tpu/serve/cons.py")
        assert [f for f in found if f.rule.startswith("X7")] == []


class TestProducedSeriesUnconsumed:
    def test_unconsumed_undocumented_series_is_caught(self):
        src = ("def reg_metrics(reg):\n"
               "    reg.counter('kftpu_fix_orphan_total')\n")
        found = xrules({"kubeflow_tpu/serve/prod.py": src})
        assert [f.rule for f in found] == ["X702"]
        assert "kftpu_fix_orphan_total" in found[0].message

    def test_consumed_in_sibling_module_is_clean(self):
        found = xrules(
            {"kubeflow_tpu/serve/prod.py":
                TestConsumedSeriesNeverProduced.PRODUCER,
             "kubeflow_tpu/serve/cons.py":
                TestConsumedSeriesNeverProduced()._consumer(
                    "kftpu_fix_total", "kftpu_fixd_util",
                    "kftpu_fix_delay_seconds_bucket")},
            lint=["kubeflow_tpu/serve/prod.py"])
        assert found == []

    def test_readme_catalog_counts_as_consumed(self):
        """A series documented in the real README metric catalog needs no
        in-scan consumer (dashboards are consumers the AST cannot see)."""
        src = ("def reg_metrics(reg):\n"
               "    reg.gauge('kftpu_serving_queue_depth')\n")
        assert xrules({"kubeflow_tpu/serve/prod.py": src}) == []


class TestHeaderContractDrift:
    def test_read_never_set_is_caught(self):
        src = ("def qos(h):\n"
               "    return h.get('X-Kftpu-Qoss')\n")
        found = xrules({"kubeflow_tpu/serve/s.py": src})
        assert [f.rule for f in found] == ["X703"]
        assert "X-Kftpu-Qoss" in found[0].message

    def test_set_never_read_is_caught(self):
        src = ("def fwd(h, out):\n"
               "    out['X-Kftpu-Dead'] = h['User-Agent']\n")
        found = xrules({"kubeflow_tpu/serve/s.py": src})
        assert [f.rule for f in found] == ["X703"]

    def test_constants_resolve_across_modules(self):
        """The centralized-constants idiom (core/headers.py) is the X703
        fix: both sides import ONE spelling, so the pair always
        matches."""
        sources = {
            "kubeflow_tpu/hdrs.py": "BUDGET = 'X-Kftpu-Budget'\n",
            "kubeflow_tpu/serve/a.py": (
                "from kubeflow_tpu.hdrs import BUDGET\n"
                "def stamp(out, ms):\n"
                "    out[BUDGET] = str(ms)\n"),
            "kubeflow_tpu/serve/b.py": (
                "from kubeflow_tpu.hdrs import BUDGET\n"
                "def read(h):\n"
                "    return h.get(BUDGET.lower())\n"),
        }
        assert xrules(sources) == []

    def test_case_drift_is_caught(self):
        src = ("def f(h, out):\n"
               "    out['X-Kftpu-Qos'] = h.get('X-KFTPU-QOS')\n")
        found = xrules({"kubeflow_tpu/serve/s.py": src})
        assert found and all(f.rule == "X703" for f in found)
        assert any("drift" in f.message for f in found)

    def test_serving_path_header_missing_from_forward_list(self):
        sources = {
            "kubeflow_tpu/hdrs.py": (
                "DEADLINE = 'X-Kftpu-Deadline-Ms'\n"
                "BUDGET = 'X-Kftpu-Budget'\n"
                "FORWARD_HEADERS = (DEADLINE,)\n"),
            "kubeflow_tpu/serve/a.py": (
                "from kubeflow_tpu.hdrs import BUDGET, DEADLINE\n"
                "def fwd(h, out):\n"
                "    out[DEADLINE] = h.get(DEADLINE)\n"
                "    out[BUDGET] = h.get(BUDGET)\n"),
        }
        found = xrules(sources, lint=["kubeflow_tpu/hdrs.py"])
        assert [f.rule for f in found] == ["X703"]
        assert "X-Kftpu-Budget" in found[0].message
        assert "forward" in found[0].message


class TestOrphanEnvVar:
    def test_read_never_set_is_caught(self):
        src = ("import os\n"
               "def knob():\n"
               "    return os.environ.get('KFTPU_FIX_KNOB')\n")
        found = xrules({"kubeflow_tpu/rt.py": src})
        assert [f.rule for f in found] == ["X704"]
        assert "KFTPU_FIX_KNOB" in found[0].message

    def test_set_in_child_env_dict_pairs_with_read(self):
        sources = {
            "kubeflow_tpu/cp.py": (
                "def child_env(v):\n"
                "    return {'KFTPU_FIX_KNOB': v}\n"),
            "kubeflow_tpu/rt.py": (
                "import os\n"
                "def knob():\n"
                "    return os.environ.get('KFTPU_FIX_KNOB')\n"),
        }
        assert xrules(sources) == []

    def test_set_never_read_is_caught_via_constant(self):
        sources = {
            "kubeflow_tpu/names.py": "ROOT = 'KFTPU_FIX_ROOT'\n",
            "kubeflow_tpu/cp.py": (
                "from kubeflow_tpu.names import ROOT\n"
                "def launch(env):\n"
                "    env[ROOT] = '/tmp'\n"),
        }
        found = xrules(sources, lint=["kubeflow_tpu/cp.py"])
        assert [f.rule for f in found] == ["X704"]
        assert "KFTPU_FIX_ROOT" in found[0].message

    def test_contract_annotation_closes_user_knobs(self):
        src = ("import os\n"
               "def knob():\n"
               "    # contract: operator-facing knob\n"
               "    return os.environ.get('KFTPU_FIX_KNOB')\n")
        assert xrules({"kubeflow_tpu/rt.py": src}) == []


class TestStatusFieldDrift:
    WRITER = ("def emit(step):\n"
              "    rec = {'step': step}\n"
              "    rec['loss_x'] = 1.0\n"
              "    return rec\n")

    def test_loop_tuple_consumption_catches_renamed_writer(self):
        reader = ("import json\n"
                  "def scrape(line, status):\n"
                  "    m = json.loads(line)\n"
                  "    status.step = m.get('step')\n"
                  "    for field in ('loss_x', 'mfu_x'):\n"
                  "        value = m.get(field)\n"
                  "        if value is not None:\n"
                  "            setattr(status, field, value)\n")
        found = xrules({"kubeflow_tpu/train/w.py": self.WRITER,
                        "kubeflow_tpu/op/r.py": reader},
                       lint=["kubeflow_tpu/op/r.py"])
        assert [f.rule for f in found] == ["X705"]
        assert "mfu_x" in found[0].message

    def test_produced_keys_are_clean(self):
        reader = ("import json\n"
                  "def scrape(line):\n"
                  "    m = json.loads(line)\n"
                  "    return m.get('step'), m.get('loss_x')\n")
        assert xrules({"kubeflow_tpu/train/w.py": self.WRITER,
                       "kubeflow_tpu/op/r.py": reader}) == []

    def test_gets_on_non_json_vars_are_ignored(self):
        src = ("def conf(d):\n"
               "    return d.get('whatever_missing_key')\n")
        assert xrules({"kubeflow_tpu/op/r.py": src}) == []


# -- Family T: distributed liveness (ISSUE 20) ---------------------------------


class TestUnboundedBlockingCall:
    def test_urlopen_without_timeout(self):
        src = ("import urllib.request\n"
               "def probe(url):\n"
               "    with urllib.request.urlopen(url) as r:\n"
               "        return r.read()\n")
        assert rules_of(src) == ["T801"]

    def test_explicit_timeout_none_still_fires(self):
        src = ("import urllib.request\n"
               "def probe(url):\n"
               "    return urllib.request.urlopen(url, timeout=None)\n")
        assert rules_of(src) == ["T801"]

    def test_bounded_urlopen_is_clean(self):
        src = ("import urllib.request\n"
               "def probe(url):\n"
               "    return urllib.request.urlopen(url, timeout=1.0)\n")
        assert rules_of(src) == []

    def test_queueish_get_and_zero_arg_wait(self):
        src = ("def pump(self):\n"
               "    item = self._work_q.get()\n"
               "    self._done.wait()\n")
        assert rules_of(src) == ["T801", "T801"]

    def test_bounded_get_nonblocking_get_and_str_join_clean(self):
        src = ("def pump(self, parts):\n"
               "    a = self._work_q.get(timeout=1.0)\n"
               "    b = self._work_q.get(block=False)\n"
               "    return ','.join(parts)\n")
        assert rules_of(src) == []

    def test_subprocess_without_timeout(self):
        src = ("import subprocess\n"
               "def run(cmd):\n"
               "    return subprocess.check_output(cmd)\n")
        assert rules_of(src) == ["T801"]

    def test_blocking_ok_annotation_closes_it(self):
        src = ("def pump(self):\n"
               "    # blocking-ok: close() pushes a None sentinel\n"
               "    return self._work_q.get()\n")
        assert rules_of(src) == []

    def test_wrapper_default_none_without_arg(self):
        """Call into a local wrapper whose timeout defaults to None and
        flows into urlopen: the call site must pass the budget."""
        src = ("import urllib.request\n"
               "def fetch(url, timeout=None):\n"
               "    return urllib.request.urlopen(url, timeout=timeout)\n"
               "def probe(url):\n"
               "    return fetch(url)\n")
        assert rules_of(src) == ["T801"]

    def test_wrapper_called_with_budget_is_clean(self):
        src = ("import urllib.request\n"
               "def fetch(url, timeout=None):\n"
               "    return urllib.request.urlopen(url, timeout=timeout)\n"
               "def probe(url):\n"
               "    return fetch(url, timeout=2.0)\n")
        assert rules_of(src) == []

    def test_wrapper_branching_on_none_is_designed(self):
        """A wrapper that BRANCHES on ``timeout is None`` has designed
        None-semantics (non-blocking drain): the default is a choice."""
        src = ("import urllib.request\n"
               "def fetch(url, timeout=None):\n"
               "    if timeout is None:\n"
               "        return None\n"
               "    return urllib.request.urlopen(url, timeout=timeout)\n"
               "def probe(url):\n"
               "    return fetch(url)\n")
        assert rules_of(src) == []

    def test_wrapper_plumbing_not_blocking_is_clean(self):
        """Forwarding the budget into a dataclass/other wrapper is
        plumbing, not a wait this call site could wedge on."""
        src = ("def submit(self, prompt, deadline=None):\n"
               "    return self._mk_request(prompt, deadline=deadline)\n"
               "def caller(self, prompt):\n"
               "    return self.submit(prompt)\n")
        assert rules_of(src) == []

    def test_test_paths_exempt(self):
        src = ("import urllib.request\n"
               "def test_probe(url):\n"
               "    return urllib.request.urlopen(url)\n")
        assert rules_of(src, "tests/test_fixture_t.py") == []


class TestAdHocRetryLoop:
    RETRY = ("import time\n"
             "def nudge(cp):\n"
             "    for _ in range(20):\n"
             "        try:\n"
             "            cp.patch({'x': 1})\n"
             "            break\n"
             "        except OSError:\n"
             "            time.sleep(0.05)\n")

    def test_sleep_and_swallow_loop(self):
        assert rules_of(self.RETRY) == ["T802"]

    def test_blessed_helper_is_clean(self):
        src = ("from kubeflow_tpu.serve.retry import call_with_retry\n"
               "def nudge(cp):\n"
               "    call_with_retry(lambda a: cp.patch({'x': 1}),\n"
               "                    retry_on=(OSError,))\n")
        assert rules_of(src) == []

    def test_reraising_handler_is_clean(self):
        src = self.RETRY.replace("            time.sleep(0.05)\n",
                                 "            time.sleep(0.05)\n"
                                 "            raise\n")
        assert rules_of(src) == []

    def test_sleep_without_retry_is_clean(self):
        src = ("import time\n"
               "def poll(pred):\n"
               "    while not pred():\n"
               "        time.sleep(0.05)\n")
        assert rules_of(src) == []

    def test_blocking_ok_on_loop_closes_it(self):
        src = self.RETRY.replace(
            "    for _ in range(20):\n",
            "    # blocking-ok: startup-only conflict window\n"
            "    for _ in range(20):\n")
        assert rules_of(src) == []


_T_CLASS = ("import threading\n"
            "class Pump:\n"
            "    def start(self):\n"
            "        self._thread = threading.Thread(target=self._loop)\n"
            "        self._thread.start()\n"
            "    def _loop(self):\n"
            "        pass\n")


class TestLeakedThread:
    def test_stop_surface_never_joins(self):
        src = _T_CLASS + ("    def stop(self):\n"
                          "        self._stop.set()\n")
        fs = lint_source(src, "kubeflow_tpu/serve/fixture.py")
        assert [f.rule for f in fs] == ["T803"]
        assert "Pump._thread" in fs[0].message

    def test_joining_stop_is_clean(self):
        src = _T_CLASS + ("    def stop(self):\n"
                          "        self._thread.join(timeout=5.0)\n")
        assert rules_of(src) == []

    def test_local_thread_never_joined(self):
        src = ("import threading\n"
               "def run(work):\n"
               "    t = threading.Thread(target=work)\n"
               "    t.start()\n"
               "    return 1\n")
        assert rules_of(src) == ["T803"]

    def test_local_joined_daemon_or_escaping_clean(self):
        src = ("import threading\n"
               "def a(work):\n"
               "    t = threading.Thread(target=work)\n"
               "    t.start()\n"
               "    t.join(timeout=5.0)\n"
               "def b(work):\n"
               "    t = threading.Thread(target=work, daemon=True)\n"
               "    t.start()\n"
               "def c(work, sink):\n"
               "    t = threading.Thread(target=work)\n"
               "    t.start()\n"
               "    sink.append(t)\n"
               "def d(work):\n"
               "    t = threading.Thread(target=work)\n"
               "    t.start()\n"
               "    return t\n")
        assert rules_of(src) == []


class TestThreadLifecycle:
    def test_thread_in_class_without_stop_surface(self):
        src = ("import threading\n"
               "class Fire:\n"
               "    def launch(self):\n"
               "        t = threading.Thread(target=self._loop)\n"
               "        t.start()\n"
               "    def _loop(self):\n"
               "        pass\n")
        fs = lint_source(src, "kubeflow_tpu/serve/fixture.py")
        assert [f.rule for f in fs] == ["T804"]
        assert "'Fire'" in fs[0].message
        assert "stop/close/shutdown" in fs[0].message

    def test_daemon_thread_without_stop_surface_is_clean(self):
        src = ("import threading\n"
               "class Fire:\n"
               "    def launch(self):\n"
               "        t = threading.Thread(target=self._loop,\n"
               "                             daemon=True)\n"
               "        t.start()\n"
               "    def _loop(self):\n"
               "        pass\n")
        assert rules_of(src) == []

    def test_unbounded_queue_get_under_lock(self):
        """The attr-based wait C302's fixed call set misses: held-lock
        sites report as T804, never ALSO as T801."""
        src = ("import threading\n"
               "class R:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "    def drain(self):\n"
               "        with self._lock:\n"
               "            return self._work_q.get()\n")
        fs = lint_source(src, "kubeflow_tpu/serve/fixture.py")
        assert [f.rule for f in fs] == ["T804"]
        assert "while holding" in fs[0].message

    def test_bounded_get_under_lock_is_clean(self):
        src = ("import threading\n"
               "class R:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "    def drain(self):\n"
               "        with self._lock:\n"
               "            return self._work_q.get(timeout=1.0)\n")
        assert rules_of(src) == []

    def test_c302_site_not_double_reported(self):
        """urlopen under a lock is C302's finding — T804 must not also
        fire on it."""
        src = ("import threading\n"
               "import urllib.request\n"
               "class R:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "    def fetch(self, url):\n"
               "        with self._lock:\n"
               "            return urllib.request.urlopen(url, timeout=1)\n")
        assert rules_of(src) == ["C302"]


class TestDeadlinePropagationDrift:
    _H = ("import urllib.request\n"
          "class Handler:\n"
          "    def _budget_s(self):\n"
          "        return self.headers.get('X-Kftpu-Deadline-Ms')\n")

    def test_fixed_literal_timeout_in_reading_scope(self):
        src = self._H + (
            "    def relay(self, req):\n"
            "        return urllib.request.urlopen(req, timeout=30.0)\n")
        fs = lint_source(src, "kubeflow_tpu/serve/fixture.py")
        assert [f.rule for f in fs] == ["T805"]
        assert "timeout=30.0" in fs[0].message

    def test_derived_timeout_is_clean(self):
        src = self._H + (
            "    def relay(self, req, remaining):\n"
            "        return urllib.request.urlopen(req, timeout=remaining)\n")
        assert rules_of(src) == []

    def test_scope_without_deadline_read_is_clean(self):
        src = ("import urllib.request\n"
               "class Other:\n"
               "    def relay(self, req):\n"
               "        return urllib.request.urlopen(req, timeout=30.0)\n")
        assert rules_of(src) == []


# -- seeded regressions against the REAL codebase (acceptance criteria) --------


def _new_findings(relpath: str, old: str, new: str):
    with open(os.path.join(REPO, relpath)) as f:
        src = f.read()
    mutated = src.replace(old, new, 1)
    assert mutated != src, f"mutation anchor vanished from {relpath}"
    before = {f.fingerprint for f in lint_source(src, relpath)}
    return [f for f in lint_source(mutated, relpath)
            if f.fingerprint not in before]


class TestSeededRegressions:
    def test_pr4_full_table_reupload_is_caught(self):
        """Re-introducing the PR-4 bug — a per-round full page-table
        upload in the dispatch hot loop — produces exactly one D103."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/engine.py",
            "        self._sync_decode_state()\n",
            "        self._sync_decode_state()\n"
            "        table = jnp.asarray(self._table)\n")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "D103" and "self._table" in f.message
        assert "_dispatch_round" in f.message

    def test_removed_router_lock_is_caught(self):
        """Dropping one router lock acquisition produces exactly one C301
        naming the attribute and the offending method."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/router.py",
            "    def note_activity(self) -> None:\n"
            "        with self._lock:\n",
            "    def note_activity(self) -> None:\n"
            "        if True:\n")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "C301"
        assert "_last_activity" in f.message
        assert "note_activity" in f.message

    def test_bad_metric_name_is_caught(self):
        """A metric family registered without the kftpu_ prefix fails at
        lint time (obs/registry.lint() made static)."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/server.py",
            'reg.gauge("kftpu_serving_queue_depth")',
            'reg.gauge("serving_queue_depth")')
        assert [f.rule for f in fresh] == ["M201"]

    def test_dropped_decode_donation_is_caught(self):
        """Removing the dense decode dispatch's donate_argnums — the 2x-HBM
        carry — produces exactly one S401."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/engine.py",
            "self._decode_n = jax.jit(_decode_fn, static_argnums=(4, 5),\n"
            "                                 donate_argnums=(1, 2))",
            "self._decode_n = jax.jit(_decode_fn, static_argnums=(4, 5))")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "S401" and "self._decode_n" in f.message

    def test_exception_path_page_leak_is_caught(self):
        """A raise-capable call between the page alloc and its ownership
        recording produces exactly one R501."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/engine.py",
            "owner=self._slot_owner(slot_idx))\n",
            "owner=self._slot_owner(slot_idx))\n"
            "            self._refresh_pool_gauge()\n")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "R501" and "_ensure_pages" in f.message

    def test_fire_and_forget_trainer_save_is_caught(self):
        """A bare ``self.ckpt.save(...)`` dropped into the training loop
        (the pre-ISSUE-9 Trainer.save shape) produces exactly one R504."""
        fresh = _new_findings(
            "kubeflow_tpu/train/trainer.py",
            "        start = self.try_resume()\n",
            "        start = self.try_resume()\n"
            "        self.ckpt.save(0, self.task.state)\n")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "R504" and "self.ckpt.save" in f.message

    def test_injected_router_lock_inversion_is_caught(self):
        """A second router lock acquired in both orders produces exactly
        one R503 naming the cycle."""
        relpath = "kubeflow_tpu/serve/router.py"
        with open(os.path.join(REPO, relpath)) as f:
            src = f.read()
        mut = src.replace(
            "        self._lock = threading.Lock()\n",
            "        self._lock = threading.Lock()\n"
            "        self._aux_lock = threading.Lock()\n", 1)
        mut = mut.replace(
            "    def note_activity(self) -> None:\n",
            "    def _seed_ab(self):\n"
            "        with self._lock:\n"
            "            with self._aux_lock:\n"
            "                pass\n\n"
            "    def _seed_ba(self):\n"
            "        with self._aux_lock:\n"
            "            with self._lock:\n"
            "                pass\n\n"
            "    def note_activity(self) -> None:\n", 1)
        assert mut != src
        before = {f.fingerprint for f in lint_source(src, relpath)}
        fresh = [f for f in lint_source(mut, relpath)
                 if f.fingerprint not in before]
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "R503"
        assert "Router._aux_lock" in f.message and "Router._lock" in f.message

    def test_weak_type_scalar_into_decode_dispatch_is_caught(self):
        """Replacing the dense decode dispatch's PRNG key with a bare
        Python float — a weak-typed cache entry per dispatch — produces
        exactly one F602."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/engine.py",
            "                out, self.cache, st = self._decode_n(\n"
            "                    self.params, self.cache, self._dstate.arrays,"
            " key, k_steps,\n"
            "                    mode)",
            "                out, self.cache, st = self._decode_n(\n"
            "                    self.params, self.cache, self._dstate.arrays,"
            " 0.5, k_steps,\n"
            "                    mode)")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "F602" and "self._decode_n" in f.message

    def test_fresh_tuple_static_arg_is_caught(self):
        """Feeding the decode dispatch's static num_steps position a
        per-call tuple produces exactly one F604."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/engine.py",
            "                out, self.cache, st = self._decode_n(\n"
            "                    self.params, self.cache, self._dstate.arrays,"
            " key, k_steps,\n"
            "                    mode)",
            "                out, self.cache, st = self._decode_n(\n"
            "                    self.params, self.cache, self._dstate.arrays,"
            " key, (k_steps,),\n"
            "                    mode)")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "F604" and "self._decode_n" in f.message


def _new_findings_prog(relpath: str, old: str, new: str):
    """The X-family seeded-regression helper: lint the (mutated) module
    under the FULL package Program so the cross-component table sees the
    real other side of each contract."""
    from kubeflow_tpu.analysis import core

    with open(os.path.join(REPO, relpath)) as f:
        src = f.read()
    mutated = src.replace(old, new, 1)
    assert mutated != src, f"mutation anchor vanished from {relpath}"

    def lint(text: str):
        mods = []
        for path in core.iter_py_files(
                [os.path.join(REPO, p) for p in
                 ("kubeflow_tpu", "scripts", "bench.py", "bench_serve.py")]):
            rel = os.path.relpath(os.path.abspath(path), REPO).replace(
                os.sep, "/")
            if rel == relpath:
                mods.append(core.Module(relpath, text))
            else:
                mods.append(core.load_module(path, rel))
        core.Program(mods)
        target = next(m for m in mods if m.relpath == relpath)
        return core.lint_module(target)

    before = {f.fingerprint for f in lint(src)}
    return [f for f in lint(mutated) if f.fingerprint not in before]


class TestContractSeededRegressions:
    def test_renamed_probe_series_is_caught(self):
        """Renaming one series the SLO autoscaler's probe scrapes — while
        the engine keeps emitting the old name — produces exactly one
        X701: the silent-HOLD drift class ISSUE 10 exists to kill."""
        fresh = _new_findings_prog(
            "kubeflow_tpu/serve/isvc_controller.py",
            '"kftpu_serving_requests_total"',
            '"kftpu_serving_requests_totals"')
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "X701"
        assert "kftpu_serving_requests_totals" in f.message

    def test_typoed_header_literal_is_caught(self):
        """Replacing the model server's QOS_HEADER constant read with a
        typoed literal produces exactly one X703 — nothing sets the
        misspelled header, so every request silently defaults."""
        fresh = _new_findings_prog(
            "kubeflow_tpu/serve/server.py",
            'raw = self.headers.get(QOS_HEADER) or body.get("qos")',
            'raw = self.headers.get("X-Kftpu-Qoss") or body.get("qos")')
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "X703" and "X-Kftpu-Qoss" in f.message

    def test_dropped_forward_list_entry_is_caught(self):
        """Removing the trace header from core/headers.FORWARD_HEADERS
        produces exactly one X703 on the forward-list — the ChaosProxy
        would silently break trace continuity through it."""
        fresh = _new_findings_prog(
            "kubeflow_tpu/core/headers.py",
            "FORWARD_HEADERS = (DEADLINE_HEADER, QOS_HEADER, TRACE_HEADER,\n"
            "                   DECODE_BACKEND_HEADER, DECODE_ALTS_HEADER,\n"
            "                   MODEL_HEADER, HANDOFF_DTYPE_HEADER,\n"
            "                   HANDOFF_WIRE_HEADER)",
            "FORWARD_HEADERS = (DEADLINE_HEADER, QOS_HEADER,\n"
            "                   DECODE_BACKEND_HEADER, DECODE_ALTS_HEADER,\n"
            "                   MODEL_HEADER, HANDOFF_DTYPE_HEADER,\n"
            "                   HANDOFF_WIRE_HEADER)")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "X703" and "X-Kftpu-Trace" in f.message

    def test_orphaned_rendezvous_env_is_caught(self):
        """Renaming one rendezvous env var on the WRITE side (bootstrap's
        child-env dict) produces X704 on the now-orphaned pair."""
        fresh = _new_findings_prog(
            "kubeflow_tpu/runtime/bootstrap.py",
            '"KFTPU_REPLICA_INDEX": str(self.replica_index)',
            '"KFTPU_REPLICA_IDX": str(self.replica_index)')
        assert {f.rule for f in fresh} == {"X704"}
        assert any("KFTPU_REPLICA_IDX" in f.message for f in fresh)


class TestLivenessSeededRegressions:
    def test_stripped_probe_timeout_is_caught(self):
        """Removing the router metrics probe's urlopen timeout — the
        exact unbounded wait that wedged a router behind a SIGKILLed
        replica — produces exactly one T801."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/router.py",
            'with urllib.request.urlopen(url + "/metrics",\n'
            '                                            timeout=1.0) as r:',
            'with urllib.request.urlopen(url + "/metrics") as r:')
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "T801" and "urllib.request.urlopen" in f.message

    def test_inline_retry_loop_is_caught(self):
        """An inline sleep-and-swallow retry loop instead of the blessed
        serve/retry.py helper produces exactly one T802."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/handoff.py",
            "    def validate(self) -> None:\n",
            "    def validate(self) -> None:\n"
            "        import time\n"
            "        for _ in range(5):\n"
            "            try:\n"
            "                self.kv_len\n"
            "                len(self.prompt_tokens)\n"
            "                break\n"
            "            except ValueError:\n"
            "                time.sleep(0.05)\n")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "T802" and "call_with_retry" in f.message

    def test_dropped_kv_migrate_join_is_caught(self):
        """Dropping the kv-migrate join from the tiered cache's close()
        produces exactly one T803 — the leak KFTPU_SANITIZE=threads
        would catch live at stop."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/kvtier.py",
            "            self._queue.put(None)\n"
            "            self._thread.join(timeout=5.0)\n",
            "            self._queue.put(None)\n")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "T803" and "._thread" in f.message

    def test_queue_get_under_router_lock_is_caught(self):
        """An unbounded queue get while holding the router lock — the
        attr-based wait C302's fixed call set misses — produces exactly
        one T804 (and NOT also a T801: one finding per defect)."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/router.py",
            "    def note_activity(self) -> None:\n",
            "    def _drain_locked(self):\n"
            "        with self._lock:\n"
            "            return self._retire_q.get()\n\n"
            "    def note_activity(self) -> None:\n")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "T804" and "while holding" in f.message

    def test_fixed_relay_timeout_is_caught(self):
        """Hardening the relay's derived ``timeout=remaining`` to a
        literal — while the handler scope reads the deadline header,
        resolved through the Program-wide header table — produces
        exactly one T805."""
        fresh = _new_findings_prog(
            "kubeflow_tpu/serve/router.py",
            "resp = urllib.request.urlopen(req, timeout=remaining)",
            "resp = urllib.request.urlopen(req, timeout=30.0)")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "T805" and "timeout=30.0" in f.message


# -- self-scan + CLI -----------------------------------------------------------


class TestSelfScan:
    def test_repo_is_clean_against_committed_baseline(self):
        baseline_path = find_baseline([os.path.join(REPO, "kubeflow_tpu")])
        baseline = Baseline.load(baseline_path) if baseline_path else None
        result = run_lint(
            [os.path.join(REPO, p) for p in
             ("kubeflow_tpu", "scripts", "bench.py", "bench_serve.py")],
            baseline=baseline, root=REPO)
        assert result.files_scanned > 50
        assert result.errors == []
        assert result.new == [], "\n".join(
            f.render() for f in result.new)


class TestCli:
    def test_kftpu_lint_exit_codes(self, tmp_path):
        from kubeflow_tpu.cli import main as cli_main

        dirty = tmp_path / "dirty.py"
        dirty.write_text(TestFullBufferReupload.POSITIVE)
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        assert cli_main(["lint", "--no-baseline", str(clean)]) == 0
        assert cli_main(["lint", "--no-baseline", str(dirty)]) == 1

    def test_json_output_has_clickable_locations(self, tmp_path):
        dirty = tmp_path / "dirty.py"
        dirty.write_text(TestFullBufferReupload.POSITIVE)
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.analysis", "--json",
             "--no-baseline", str(dirty)],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        assert doc["ok"] is False and len(doc["findings"]) == 1
        f = doc["findings"][0]
        assert f["rule"] == "D103" and f["line"] == 4 and f["col"] >= 1

    def test_update_baseline_then_clean(self, tmp_path):
        from kubeflow_tpu.cli import main as cli_main

        dirty = tmp_path / "dirty.py"
        dirty.write_text(TestFullBufferReupload.POSITIVE)
        bl = tmp_path / "bl.json"
        assert cli_main(["lint", "--update-baseline",
                         "--baseline", str(bl), str(dirty)]) == 0
        assert cli_main(["lint", "--baseline", str(bl), str(dirty)]) == 0
        assert cli_main(["lint", "--no-baseline", str(dirty)]) == 1

    def test_list_rules(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.analysis", "--list-rules"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0
        for rid in ("D103", "C301", "M201", "S401", "R503", "X701",
                    "X703", "X704", "X705"):
            assert rid in proc.stdout

    def test_contracts_json_round_trips(self):
        """--contracts-json emits the whole-program contract table, and
        the CLI output equals the in-process extraction byte for byte
        (after JSON round-trip) — the manifest the runtime contract
        auditor diffs against."""
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.analysis",
             "--contracts-json", "kubeflow_tpu"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["version"] == 1
        produced = doc["series"]["produced"]
        assert "kftpu_serving_requests_total" in produced
        assert all(":" in s for s in
                   produced["kftpu_serving_requests_total"])  # clickable
        assert "kftpu_router_" in doc["series"]["produced_prefixes"]
        assert "kftpu_serving_queue_delay_seconds" in \
            doc["series"]["histograms"]
        assert "kftpu_serving_qos_ttft_p95_ms" in doc["series"]["consumed"]
        for h in ("X-Kftpu-Deadline-Ms", "X-Kftpu-Qos", "X-Kftpu-Trace"):
            assert h in doc["headers"]["set"] and h in doc["headers"]["read"]
            assert h in doc["headers"]["forward_list"]
        assert "KFTPU_PROCESS_ID" in doc["env"]["set"]
        assert "KFTPU_PROCESS_ID" in doc["env"]["read"]
        assert "goodput" in doc["fields"]["consumed"]
        assert "goodput" in doc["fields"]["produced"]

        from kubeflow_tpu.analysis import build_program
        from kubeflow_tpu.analysis.rules_contracts import contract_manifest

        local = json.loads(json.dumps(contract_manifest(
            build_program([os.path.join(REPO, "kubeflow_tpu")],
                          root=REPO))))
        assert local == doc

    def _git_repo(self, tmp_path):
        def git(*args):
            subprocess.run(
                ["git", "-c", "user.email=t@t", "-c", "user.name=t",
                 *args], cwd=tmp_path, check=True, capture_output=True)
        git("init", "-q")
        (tmp_path / "clean.py").write_text("x = 1\n")
        git("add", "clean.py")
        git("commit", "-qm", "seed")
        return git

    def test_changed_lints_only_touched_files(self, tmp_path):
        self._git_repo(tmp_path)
        # clean.py is committed and untouched; dirty.py is new + dirty
        (tmp_path / "dirty.py").write_text(
            TestFullBufferReupload.POSITIVE)
        env = dict(os.environ, PYTHONPATH=REPO)
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.analysis", "--changed",
             "--no-baseline", "--json"],
            capture_output=True, text=True, cwd=tmp_path, env=env)
        doc = json.loads(proc.stdout)
        assert proc.returncode == 1
        assert doc["files_scanned"] == 1       # dirty.py only
        assert [f["rule"] for f in doc["findings"]] == ["D103"]

    def test_changed_with_nothing_touched_is_ok(self, tmp_path):
        self._git_repo(tmp_path)
        env = dict(os.environ, PYTHONPATH=REPO)
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.analysis", "--changed",
             "--no-baseline"],
            capture_output=True, text=True, cwd=tmp_path, env=env)
        assert proc.returncode == 0
        assert "0 files changed" in proc.stdout

    def test_changed_rejects_update_baseline(self, tmp_path):
        self._git_repo(tmp_path)
        env = dict(os.environ, PYTHONPATH=REPO)
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.analysis", "--changed",
             "--update-baseline"],
            capture_output=True, text=True, cwd=tmp_path, env=env)
        assert proc.returncode == 2
        assert "full scan" in proc.stderr

    def test_changed_skips_deleted_files(self, tmp_path):
        """A tracked .py removed from the working tree must not reach the
        file walker (it used to error the pre-commit path): the deletion
        shows up in the diff but is excluded by its D status."""
        git = self._git_repo(tmp_path)
        git("rm", "-q", "clean.py")
        (tmp_path / "dirty.py").write_text(
            TestFullBufferReupload.POSITIVE)
        env = dict(os.environ, PYTHONPATH=REPO)
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.analysis", "--changed",
             "--no-baseline", "--json"],
            capture_output=True, text=True, cwd=tmp_path, env=env)
        doc = json.loads(proc.stdout)
        assert proc.returncode == 1, proc.stderr
        assert doc["errors"] == []
        assert doc["files_scanned"] == 1       # dirty.py; NOT clean.py
        assert [f["rule"] for f in doc["findings"]] == ["D103"]

    def test_changed_rename_lints_only_new_name(self, tmp_path):
        """A committed rename lints the NEW path only — the old name is
        gone from disk and must be skipped by its R status."""
        git = self._git_repo(tmp_path)
        git("mv", "clean.py", "renamed.py")
        git("commit", "-qm", "rename")
        from kubeflow_tpu.analysis import changed_files

        files = changed_files("HEAD~1", root=str(tmp_path))
        assert files == ["renamed.py"]

    def test_json_reports_wall_time(self, tmp_path):
        (tmp_path / "one.py").write_text("x = 1\n")
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.analysis", "--json",
             "--no-baseline", str(tmp_path / "one.py")],
            capture_output=True, text=True, cwd=REPO)
        doc = json.loads(proc.stdout)
        assert proc.returncode == 0
        assert doc["wall_time_s"] > 0

    def test_update_baseline_is_deterministic(self, tmp_path):
        """The baseline file is a pure function of the finding SET:
        shuffled finding order writes byte-identical output, so baseline
        diffs are reviewable."""
        import random

        from kubeflow_tpu.analysis import lint_source

        src = TestFullBufferReupload.POSITIVE + (
            "    def again(self):  # hot-loop\n"
            "        jnp.asarray(self._other)\n")
        findings = lint_source(src, "kubeflow_tpu/serve/fixture.py")
        assert len(findings) >= 2
        blobs = []
        for seed in (0, 1, 2):
            shuffled = list(findings)
            random.Random(seed).shuffle(shuffled)
            out = tmp_path / f"bl{seed}.json"
            Baseline.from_findings(shuffled).save(str(out))
            blobs.append(out.read_bytes())
        assert blobs[0] == blobs[1] == blobs[2]
