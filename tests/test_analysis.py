"""``kftpu lint`` — the static analyzer itself (ISSUE 5).

Contracts pinned here:
- every rule fires on its minimal positive fixture and stays silent on
  the matching negative (annotations close the false positives they are
  documented to close);
- ``# lint: disable=`` suppression and the baseline round-trip work, and
  baseline fingerprints survive unrelated line shifts;
- the two seeded regressions from the acceptance criteria: re-introducing
  the PR-4 per-round ``jnp.asarray(self._table)`` upload into the REAL
  engine and removing one REAL router lock acquisition each produce
  exactly the expected finding — the rules are tuned to this codebase,
  not just to fixtures;
- the repo itself scans clean against the committed baseline.
"""

import json
import os
import subprocess
import sys

from kubeflow_tpu.analysis import (
    Baseline, all_rules, find_baseline, lint_source, run_lint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(src: str, path: str = "kubeflow_tpu/serve/fixture.py"):
    return [f.rule for f in lint_source(src, path)]


# -- Family A: device hygiene --------------------------------------------------


class TestHostSyncInJit:
    def test_np_asarray_in_jitted_fn(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return np.asarray(x) + 1\n")
        assert rules_of(src) == ["D101"]

    def test_item_and_float_on_traced_param(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def step(x, y):\n"
            "    return x.item() + float(y)\n")
        assert rules_of(src) == ["D101", "D101"]

    def test_partial_jit_decorator_and_traced_annotation(self):
        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnums=(1,))\n"
            "def a(x, n):\n"
            "    x.block_until_ready()\n"
            "    return x\n"
            "def b(x):  # traced\n"
            "    return jax.device_get(x)\n")
        assert rules_of(src) == ["D101", "D101"]

    def test_jit_wrapped_local_fn(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "def build():\n"
            "    def inner(x):\n"
            "        return np.asarray(x)\n"
            "    return jax.jit(inner)\n")
        assert rules_of(src) == ["D101"]

    def test_same_calls_outside_jit_are_clean(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "def host_side(x):\n"
            "    return np.asarray(jax.device_get(x)).item()\n")
        assert rules_of(src) == []


class TestHostSyncInHotLoop:
    def test_device_get_in_hot_loop(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def consume(self):  # hot-loop\n"
            "        return jax.device_get(self.buf)\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["D102"]
        assert "consume" in fs[0].message

    def test_sync_point_annotation_is_the_designed_fetch(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def consume(self):  # hot-loop\n"
            "        return jax.device_get(self.buf)"
            "  # sync-point: the one designed fetch\n")
        assert rules_of(src) == []

    def test_sleep_in_hot_loop(self):
        src = (
            "import time\n"
            "def spin():  # hot-loop\n"
            "    time.sleep(0.01)\n")
        assert rules_of(src) == ["D102"]

    def test_unannotated_function_is_clean(self):
        src = (
            "import jax\n"
            "def consume(buf):\n"
            "    return jax.device_get(buf)\n")
        assert rules_of(src) == []


class TestFullBufferReupload:
    POSITIVE = (
        "import jax.numpy as jnp\n"
        "class E:\n"
        "    def dispatch(self):  # hot-loop\n"
        "        return jnp.asarray(self._table)\n")

    def test_persistent_self_buffer_uploaded_per_round(self):
        fs = lint_source(self.POSITIVE)
        assert [f.rule for f in fs] == ["D103"]
        assert "self._table" in fs[0].message

    def test_device_put_of_self_buffer_also_fires(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def dispatch(self):  # hot-loop\n"
            "        return jax.device_put(self._state.arrays)\n")
        assert rules_of(src) == ["D103"]

    def test_local_array_upload_is_clean(self):
        src = (
            "import jax.numpy as jnp\n"
            "class E:\n"
            "    def dispatch(self, row):  # hot-loop\n"
            "        return jnp.asarray(row)\n")
        assert rules_of(src) == []

    def test_lint_disable_suppresses(self):
        src = self.POSITIVE.replace(
            "return jnp.asarray(self._table)",
            "return jnp.asarray(self._table)  # lint: disable=D103")
        assert rules_of(src) == []


class TestDonatedBufferReuse:
    def test_read_after_donating_dispatch(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c, donate_argnums=(0,))\n"
            "    def go(self):\n"
            "        out = self._fn(self.cache)\n"
            "        return self.cache\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["D104"]
        assert "self.cache" in fs[0].message

    def test_rebind_then_read_is_clean(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c, donate_argnums=(0,))\n"
            "    def go(self):\n"
            "        self.cache = self._fn(self.cache)\n"
            "        return self.cache\n")
        assert rules_of(src) == []

    def test_donation_in_one_branch_not_read_in_sibling(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda c: c, donate_argnums=(0,))\n"
            "    def go(self, paged):\n"
            "        if paged:\n"
            "            self.cache = self._fn(self.cache)\n"
            "        else:\n"
            "            out = self.cache\n"
            "        return out\n")
        assert rules_of(src) == []


class TestJitInLoop:
    def test_jit_constructed_per_iteration(self):
        src = (
            "import jax\n"
            "def run(xs):\n"
            "    for x in xs:\n"
            "        f = jax.jit(lambda v: v)\n"
            "        f(x)\n")
        assert rules_of(src) == ["D105"]

    def test_jit_in_hot_loop_function(self):
        src = (
            "import jax\n"
            "def dispatch(x):  # hot-loop\n"
            "    return jax.jit(lambda v: v)(x)\n")
        assert rules_of(src) == ["D105"]

    def test_jit_at_init_is_clean(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = jax.jit(lambda v: v)\n")
        assert rules_of(src) == []


# -- Family B: lock discipline -------------------------------------------------


class TestUnlockedSharedMutation:
    def test_inferred_cross_thread_mutation(self):
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._run).start()\n"
            "    def _run(self):\n"
            "        self._items.append(1)\n"
            "    def results(self):\n"
            "        return list(self._items)\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["C301"]
        assert "Worker._items" in fs[0].message

    def test_lock_held_everywhere_is_clean(self):
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._run).start()\n"
            "    def _run(self):\n"
            "        with self._lock:\n"
            "            self._items.append(1)\n"
            "    def results(self):\n"
            "        with self._lock:\n"
            "            return list(self._items)\n")
        assert rules_of(src) == []

    def test_guarded_by_contract_checked_without_threads(self):
        # guarded_by turns the attribute into a contract even when the
        # class spawns no threads this module can see.
        src = (
            "import threading\n"
            "class G:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded_by: _lock\n"
            "    def bump(self):\n"
            "        self._n += 1\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["C301"]
        assert "guarded_by" in fs[0].message and "bump" in fs[0].message

    def test_guarded_by_satisfied_under_lock(self):
        src = (
            "import threading\n"
            "class G:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded_by: _lock\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n")
        assert rules_of(src) == []

    def test_locked_suffix_counts_as_holding(self):
        src = (
            "import threading\n"
            "class G:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded_by: _lock\n"
            "    def _bump_locked(self):\n"
            "        self._n += 1\n")
        assert rules_of(src) == []

    def test_requires_lock_annotation(self):
        src = (
            "import threading\n"
            "class G:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded_by: _lock\n"
            "    def _bump(self):  # requires_lock: _lock\n"
            "        self._n += 1\n")
        assert rules_of(src) == []

    def test_lockfree_annotation_closes_inference(self):
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []  # lockfree: scheduler-confined\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._run).start()\n"
            "    def _run(self):\n"
            "        self._items.append(1)\n"
            "    def results(self):\n"
            "        return list(self._items)\n")
        assert rules_of(src) == []

    def test_condition_guard_counts_as_its_lock(self):
        src = (
            "import threading\n"
            "class Q:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._cv = threading.Condition(self._lock)\n"
            "        self._pending = {}  # guarded_by: _cv\n"
            "    def add(self, k):\n"
            "        with self._cv:\n"
            "            self._pending[k] = None\n")
        assert rules_of(src) == []


class TestBlockingCallUnderLock:
    def test_sleep_under_lock(self):
        src = (
            "import threading, time\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def poll(self):\n"
            "        with self._lock:\n"
            "            time.sleep(0.1)\n")
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["C302"]
        assert "time.sleep" in fs[0].message

    def test_thread_join_under_lock(self):
        src = (
            "import threading\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def stop(self):\n"
            "        with self._lock:\n"
            "            self._thread.join()\n")
        assert rules_of(src) == ["C302"]

    def test_sleep_outside_lock_is_clean(self):
        src = (
            "import threading, time\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def poll(self):\n"
            "        with self._lock:\n"
            "            n = 1\n"
            "        time.sleep(0.1)\n")
        assert rules_of(src) == []

    def test_condition_wait_is_exempt(self):
        # Condition.wait releases the lock — the whole point of a CV.
        src = (
            "import threading\n"
            "class Q:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._cv = threading.Condition(self._lock)\n"
            "    def pop(self):\n"
            "        with self._cv:\n"
            "            self._cv.wait(1.0)\n")
        assert rules_of(src) == []


class TestSwallowedException:
    def test_bare_except_pass(self):
        src = (
            "def reconcile(work):\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        pass\n")
        assert rules_of(src) == ["C303"]

    def test_logged_broad_except_is_clean(self):
        src = (
            "import logging\n"
            "def reconcile(work):\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        logging.exception('reconcile failed')\n")
        assert rules_of(src) == []

    def test_narrow_except_pass_is_clean(self):
        src = (
            "def probe(work):\n"
            "    try:\n"
            "        work()\n"
            "    except ValueError:\n"
            "        pass\n")
        assert rules_of(src) == []

    def test_reraise_is_clean(self):
        src = (
            "def run(work):\n"
            "    try:\n"
            "        work()\n"
            "    except BaseException:\n"
            "        raise\n")
        assert rules_of(src) == []


# -- metric-name rules ---------------------------------------------------------


class TestMetricRules:
    def test_missing_prefix(self):
        src = "def setup(reg):\n    reg.counter('queue_depth', 'help')\n"
        fs = lint_source(src)
        assert [f.rule for f in fs] == ["M201"]
        assert "kftpu_" in fs[0].message

    def test_bad_grammar(self):
        src = "def setup(reg):\n    reg.gauge('kftpu_bad-name', 'help')\n"
        assert rules_of(src) == ["M201"]

    def test_fstring_head_checked(self):
        src = (
            "def setup(reg, kind):\n"
            "    reg.gauge(f'queue_{kind}_depth', 'help')\n"
            "    reg.gauge(f'kftpu_{kind}_depth', 'help')\n")
        assert rules_of(src) == ["M201"]

    def test_duplicate_family_in_one_function(self):
        src = (
            "def setup(reg):\n"
            "    reg.counter('kftpu_reqs_total', 'a')\n"
            "    reg.counter('kftpu_reqs_total', 'b')\n")
        assert rules_of(src) == ["M202"]

    def test_good_names_clean(self):
        src = (
            "def setup(reg):\n"
            "    reg.counter('kftpu_reqs_total', 'a')\n"
            "    reg.histogram('kftpu_latency_seconds', 'b')\n")
        assert rules_of(src) == []


# -- core machinery ------------------------------------------------------------


class TestBaseline:
    SRC = TestFullBufferReupload.POSITIVE

    def test_round_trip(self, tmp_path):
        findings = lint_source(self.SRC, "pkg/mod.py")
        assert findings
        path = str(tmp_path / "baseline.json")
        Baseline.from_findings(findings, reason="seed fixture").save(path)
        loaded = Baseline.load(path)
        new, matched = loaded.split(lint_source(self.SRC, "pkg/mod.py"))
        assert new == [] and len(matched) == len(findings)
        # the file is valid JSON with a reason per entry
        doc = json.loads(open(path).read())
        assert all(e["reason"] for e in doc["entries"])

    def test_fingerprints_survive_line_shifts(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        Baseline.from_findings(
            lint_source(self.SRC, "pkg/mod.py")).save(path)
        shifted = "# a new header comment\n\n" + self.SRC
        new, matched = Baseline.load(path).split(
            lint_source(shifted, "pkg/mod.py"))
        assert new == [] and matched

    def test_second_occurrence_is_new(self, tmp_path):
        # The baseline budget is a multiset: one entry forgives ONE
        # occurrence, a second identical defect is still a finding.
        path = str(tmp_path / "baseline.json")
        Baseline.from_findings(
            lint_source(self.SRC, "pkg/mod.py")).save(path)
        doubled = self.SRC + (
            "    def dispatch2(self):  # hot-loop\n"
            "        return jnp.asarray(self._table)\n")
        new, matched = Baseline.load(path).split(
            lint_source(doubled, "pkg/mod.py"))
        assert len(matched) == 1 and len(new) == 1

    def test_committed_baseline_exists(self):
        path = find_baseline([os.path.join(REPO, "kubeflow_tpu")])
        assert path is not None
        assert os.path.basename(path) == ".kftpu-lint-baseline.json"
        assert os.path.dirname(path) == REPO


class TestRegistry:
    def test_all_families_registered(self):
        ids = {r.id for r in all_rules()}
        assert {"D101", "D102", "D103", "D104", "D105",
                "C301", "C302", "C303", "M201", "M202"} <= ids

    def test_parse_error_is_reported_not_raised(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def broken(:\n")
        result = run_lint([str(bad)], root=str(tmp_path))
        assert not result.ok
        assert [e.rule for e in result.errors] == ["E000"]


# -- seeded regressions against the REAL codebase (acceptance criteria) --------


def _new_findings(relpath: str, old: str, new: str):
    with open(os.path.join(REPO, relpath)) as f:
        src = f.read()
    mutated = src.replace(old, new, 1)
    assert mutated != src, f"mutation anchor vanished from {relpath}"
    before = {f.fingerprint for f in lint_source(src, relpath)}
    return [f for f in lint_source(mutated, relpath)
            if f.fingerprint not in before]


class TestSeededRegressions:
    def test_pr4_full_table_reupload_is_caught(self):
        """Re-introducing the PR-4 bug — a per-round full page-table
        upload in the dispatch hot loop — produces exactly one D103."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/engine.py",
            "        self._sync_decode_state()\n",
            "        self._sync_decode_state()\n"
            "        table = jnp.asarray(self._table)\n")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "D103" and "self._table" in f.message
        assert "_dispatch_round" in f.message

    def test_removed_router_lock_is_caught(self):
        """Dropping one router lock acquisition produces exactly one C301
        naming the attribute and the offending method."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/router.py",
            "    def note_activity(self) -> None:\n"
            "        with self._lock:\n",
            "    def note_activity(self) -> None:\n"
            "        if True:\n")
        assert len(fresh) == 1
        f = fresh[0]
        assert f.rule == "C301"
        assert "_last_activity" in f.message
        assert "note_activity" in f.message

    def test_bad_metric_name_is_caught(self):
        """A metric family registered without the kftpu_ prefix fails at
        lint time (obs/registry.lint() made static)."""
        fresh = _new_findings(
            "kubeflow_tpu/serve/server.py",
            'reg.gauge("kftpu_serving_queue_depth")',
            'reg.gauge("serving_queue_depth")')
        assert [f.rule for f in fresh] == ["M201"]


# -- self-scan + CLI -----------------------------------------------------------


class TestSelfScan:
    def test_repo_is_clean_against_committed_baseline(self):
        baseline_path = find_baseline([os.path.join(REPO, "kubeflow_tpu")])
        baseline = Baseline.load(baseline_path) if baseline_path else None
        result = run_lint(
            [os.path.join(REPO, p) for p in
             ("kubeflow_tpu", "scripts", "bench.py", "bench_serve.py")],
            baseline=baseline, root=REPO)
        assert result.files_scanned > 50
        assert result.errors == []
        assert result.new == [], "\n".join(
            f.render() for f in result.new)


class TestCli:
    def test_kftpu_lint_exit_codes(self, tmp_path):
        from kubeflow_tpu.cli import main as cli_main

        dirty = tmp_path / "dirty.py"
        dirty.write_text(TestFullBufferReupload.POSITIVE)
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        assert cli_main(["lint", "--no-baseline", str(clean)]) == 0
        assert cli_main(["lint", "--no-baseline", str(dirty)]) == 1

    def test_json_output_has_clickable_locations(self, tmp_path):
        dirty = tmp_path / "dirty.py"
        dirty.write_text(TestFullBufferReupload.POSITIVE)
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.analysis", "--json",
             "--no-baseline", str(dirty)],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        assert doc["ok"] is False and len(doc["findings"]) == 1
        f = doc["findings"][0]
        assert f["rule"] == "D103" and f["line"] == 4 and f["col"] >= 1

    def test_update_baseline_then_clean(self, tmp_path):
        from kubeflow_tpu.cli import main as cli_main

        dirty = tmp_path / "dirty.py"
        dirty.write_text(TestFullBufferReupload.POSITIVE)
        bl = tmp_path / "bl.json"
        assert cli_main(["lint", "--update-baseline",
                         "--baseline", str(bl), str(dirty)]) == 0
        assert cli_main(["lint", "--baseline", str(bl), str(dirty)]) == 0
        assert cli_main(["lint", "--no-baseline", str(dirty)]) == 1

    def test_list_rules(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.analysis", "--list-rules"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0
        for rid in ("D103", "C301", "M201"):
            assert rid in proc.stdout
