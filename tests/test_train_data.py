"""Real data path: BPE tokenizer, text→grain pipeline, staging
(storage-initializer analog), and mid-epoch resume — the round-1 verdict's
"train from a text file and resume mid-epoch" e2e ((U) training-operator
sdk train(); SURVEY.md §2.2#22)."""

import os

import numpy as np
import jax
import pytest

from kubeflow_tpu.serve.tokenizer import BPETokenizer, ByteTokenizer
from kubeflow_tpu.train.data import DataConfig, make_data_source

CORPUS = ("the tpu runs the model and the model runs on the tpu " * 40
          + "pipelines schedule experiments while experiments tune models " * 30)


class TestBPE:
    def test_roundtrip_exact(self):
        tok = BPETokenizer.train(CORPUS, vocab_size=300)
        for text in ("the tpu runs", "experiments tune models",
                     "unseen words also roundtrip", "ünïcödé too"):
            assert tok.decode(tok.encode(text)) == text

    def test_compresses_vs_bytes(self):
        tok = BPETokenizer.train(CORPUS, vocab_size=340)
        byte = ByteTokenizer()
        text = "the tpu runs the model"
        assert len(tok.encode(text)) < len(byte.encode(text))
        assert tok.vocab_size > byte.vocab_size

    def test_save_load(self, tmp_path):
        tok = BPETokenizer.train(CORPUS, vocab_size=300)
        path = str(tmp_path / "tok.json")
        tok.save(path)
        tok2 = BPETokenizer.load(path)
        assert tok2.merges == tok.merges
        assert tok2.encode("the tpu") == tok.encode("the tpu")


class TestTextSource:
    def _cfg(self, tmp_path, **kw):
        p = tmp_path / "corpus.txt"
        if not p.exists():
            p.write_text(CORPUS)
        return DataConfig(kind="text", path=str(p), vocab_size=512,
                          seq_len=16, global_batch=4, **kw)

    def test_batches_are_deterministic_fast_forward(self, tmp_path):
        cfg = self._cfg(tmp_path)
        a = make_data_source(cfg)
        b = make_data_source(cfg)     # a "restarted worker"
        for step in (0, 3, 17, 100):
            np.testing.assert_array_equal(a.batch_at(step), b.batch_at(step))
        assert a.batch_at(0).shape == (4, 17)
        # Different steps see different data (epoch shuffle, not repetition).
        assert not np.array_equal(a.batch_at(0), a.batch_at(1))

    def test_shards_partition_the_batch(self, tmp_path):
        cfg = self._cfg(tmp_path)
        full = make_data_source(cfg).batch_at(5)
        s0 = make_data_source(cfg, shard=0, num_shards=2).batch_at(5)
        s1 = make_data_source(cfg, shard=1, num_shards=2).batch_at(5)
        np.testing.assert_array_equal(np.concatenate([s0, s1]), full)

    def test_tokenization_cached_once(self, tmp_path):
        cfg = self._cfg(tmp_path)
        make_data_source(cfg)
        caches = [f for f in os.listdir(tmp_path) if f.endswith(".tokens.npy")]
        assert len(caches) == 1
        mtime = os.path.getmtime(tmp_path / caches[0])
        make_data_source(cfg)         # second construction reuses the cache
        assert os.path.getmtime(tmp_path / caches[0]) == mtime

    def test_bpe_tokenizer_path(self, tmp_path):
        tok = BPETokenizer.train(CORPUS, vocab_size=300)
        tok_path = str(tmp_path / "tok.json")
        tok.save(tok_path)
        cfg = self._cfg(tmp_path, tokenizer_path=tok_path)
        src = make_data_source(cfg)
        batch = src.batch_at(0)
        assert batch.max() >= 259   # merged ids beyond the byte range occur


class TestStaging:
    def test_stage_dataset_and_train_tokenizer(self, tmp_path):
        from kubeflow_tpu.train.staging import stage_inputs

        src = tmp_path / "data.txt"
        src.write_text(CORPUS)
        work = tmp_path / "job"
        out = stage_inputs(str(work), dataset_uri=f"file://{src}",
                           train_tokenizer_vocab=300)
        assert os.path.exists(out["dataset"])
        assert os.path.exists(out["tokenizer"])
        tok = BPETokenizer.load(out["tokenizer"])
        assert tok.vocab_size == 300
        # Idempotent (restart path).
        again = stage_inputs(str(work), dataset_uri=f"file://{src}",
                             train_tokenizer_vocab=300)
        assert again == out

    def test_unsupported_scheme_rejected(self, tmp_path):
        from kubeflow_tpu.train.staging import stage_inputs

        with pytest.raises(ValueError, match="scheme"):
            stage_inputs(str(tmp_path), dataset_uri="s3://bucket/x")


@pytest.mark.slow
def test_text_training_resumes_mid_epoch(tmp_path):
    """The committed e2e: train from a raw text file (staged, BPE-tokenized)
    with checkpoints, kill, resume mid-epoch — the resumed run must consume
    EXACTLY the batches an uninterrupted run would and end bitwise-equal."""
    from kubeflow_tpu.train.trainer import Trainer, TrainerConfig

    src = tmp_path / "corpus.txt"
    src.write_text(CORPUS)

    def make(steps):
        cfg = TrainerConfig(
            model="tiny", model_overrides={"vocab_size": 512,
                                           "max_seq_len": 32},
            dataset_uri=f"file://{src}",
            train_tokenizer_vocab=300,
            data={"global_batch": 8},
            steps=steps, log_every=5,
            checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=5,
        )
        from kubeflow_tpu.runtime.mesh import build_mesh

        mesh = build_mesh({"data": 8})
        return Trainer(cfg, mesh, workdir=str(tmp_path / "job"))

    tr1 = make(steps=5)
    tr1.run()

    # Resume: picks up the step-5 checkpoint mid-epoch and continues.
    tr2 = make(steps=10)
    assert tr2.try_resume() == 5
    # Fast-forward proof: the resumed source serves the same step-5.. batches
    # a fresh source would.
    fresh = make(steps=10)
    np.testing.assert_array_equal(tr2.data.batch_at(5), fresh.data.batch_at(5))
    np.testing.assert_array_equal(tr2.data.batch_at(9), fresh.data.batch_at(9))
    m2 = tr2.run()
    assert int(jax.device_get(tr2.task.state["step"])) == 10
    assert np.isfinite(m2["loss"])

    # Uninterrupted oracle: same 10 steps in one run, bitwise-equal params.
    import shutil

    shutil.rmtree(tmp_path / "ckpt")
    tr3 = make(steps=10)
    tr3.run()
    a = jax.device_get(tr2.task.state["params"]["embed"])
    b = jax.device_get(tr3.task.state["params"]["embed"])
    np.testing.assert_array_equal(a, b)


class TestReviewRegressions:
    def test_too_short_corpus_clear_error(self, tmp_path):
        p = tmp_path / "tiny.txt"
        p.write_text("short")
        cfg = DataConfig(kind="text", path=str(p), vocab_size=512,
                         seq_len=128, global_batch=4)
        with pytest.raises(ValueError, match="seq_len"):
            make_data_source(cfg)

    def test_cached_tokens_validated_against_vocab(self, tmp_path):
        p = tmp_path / "corpus.txt"
        p.write_text(CORPUS)
        big = DataConfig(kind="text", path=str(p), vocab_size=512,
                         seq_len=16, global_batch=4)
        make_data_source(big)         # writes the cache
        small = DataConfig(kind="text", path=str(p), vocab_size=50,
                           seq_len=16, global_batch=4)
        with pytest.raises(ValueError, match="vocab"):
            make_data_source(small)   # cache hit must still validate

    def test_bpe_trailing_space_roundtrip(self):
        tok = BPETokenizer.train(CORPUS, vocab_size=300)
        for text in ("a ", "", "  ", "the tpu ", " leading"):
            assert tok.decode(tok.encode(text)) == text, repr(text)

    def test_staged_tokenizer_refreshes_on_change(self, tmp_path):
        import time as _t

        from kubeflow_tpu.train.staging import stage_inputs

        art = tmp_path / "tok.json"
        BPETokenizer.train(CORPUS, 280).save(str(art))
        work = str(tmp_path / "job")
        out = stage_inputs(work, tokenizer_uri=str(art))
        v1 = BPETokenizer.load(out["tokenizer"]).vocab_size
        _t.sleep(0.05)
        BPETokenizer.train(CORPUS, 320).save(str(art))
        out = stage_inputs(work, tokenizer_uri=str(art))
        assert BPETokenizer.load(out["tokenizer"]).vocab_size != v1
