"""Paged KV cache correctness: the paged engine must reproduce the
contiguous engine's greedy outputs exactly, decouple HBM from
slots × max_seq_len, reuse shared-prefix pages, chunk several long prompts
concurrently, and survive pool pressure via recompute preemption — the vLLM
feature set ((U) kserve huggingfaceserver vLLM backend, SURVEY.md §2.3#27),
exact-match tested like every other serving path."""

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams
from kubeflow_tpu.serve.paged import PageAllocator, PagePoolExhausted


@pytest.fixture(scope="module")
def cfg():
    return preset("tiny", vocab_size=512)


@pytest.fixture(scope="module")
def params(cfg):
    return init_decoder_params(jax.random.PRNGKey(0), cfg)


def make_paged(cfg, params, *, max_pages=None, page=16, chunk=32, slots=4,
               prefix=True, prefills=2, prefix_index="radix"):
    return LLMEngine(cfg, BatchingSpec(
        max_batch_size=slots, max_seq_len=128, paged=True, page_size=page,
        max_pages=max_pages, enable_prefix_caching=prefix,
        prefix_index=prefix_index,
        chunked_prefill_tokens=chunk, max_concurrent_prefills=prefills),
        params=params)


def make_contig(cfg, params, *, slots=4):
    return LLMEngine(cfg, BatchingSpec(
        max_batch_size=slots, max_seq_len=128, prefill_buckets=[16, 64],
        chunked_prefill_tokens=0),
        params=params)


def run_all(eng, reqs, max_steps=500):
    for _ in range(max_steps):
        eng.step()
        if all(r.done.is_set() for r in reqs):
            return
    raise AssertionError("requests did not finish")


class TestPagedAllocator:
    def test_alloc_free_refcount(self):
        a = PageAllocator(4, 8)
        p = a.alloc(3)
        assert len(set(p)) == 3 and a.available() == 1
        a.incref([p[0]])
        a.free(p)
        assert a.available() == 3            # p[0] still referenced
        a.free([p[0]])
        assert a.available() == 4

    def test_exhaustion_raises(self):
        a = PageAllocator(2, 8)
        a.alloc(2)
        with pytest.raises(PagePoolExhausted):
            a.alloc(1)

    def test_prefix_match_and_eviction(self):
        a = PageAllocator(4, 4)
        toks = list(range(1, 13))            # 3 full pages
        pages = a.alloc(3)
        a.register_prefix(toks, pages)
        a.free(pages)                        # ref 0 -> cached, reclaimable
        hit = a.match_prefix(toks + [99])
        assert hit == pages                  # full-page prefix reused
        a.free(hit)
        # Allocating everything evicts the cached pages LRU.
        a.alloc(4)
        assert a.match_prefix(toks + [99]) == []
        assert a.stats["evictions"] >= 1

    def test_match_capped_before_last_token(self):
        """A fully-cached prompt must still leave >=1 token to prefill (the
        first sampled token needs real logits)."""
        a = PageAllocator(4, 4)
        toks = list(range(8))                # exactly 2 pages
        pages = a.alloc(2)
        a.register_prefix(toks, pages)
        hit = a.match_prefix(toks)           # same 8-token prompt
        assert len(hit) <= 1                 # (8-1)//4 = 1 page max

    def test_match_cap_edges(self):
        """The one-token-short cap, walked across the page boundary —
        the contract the radix index must preserve (its cap is the same
        ``len(tokens) - 1``): a page-multiple prompt reuses all but the
        last page; one extra token unlocks it."""
        a = PageAllocator(8, 4)
        toks = list(range(1, 13))            # 3 full pages
        pages = a.alloc(3)
        a.register_prefix(toks, pages)
        a.free(pages)
        assert len(a.match_prefix(toks)) == 2          # (12-1)//4
        for h in (a.match_prefix(toks + [99]),):       # 13 tokens
            assert len(h) == 3
            a.free(h)
        assert len(a.match_prefix(toks[:5])) == 1      # (5-1)//4
        assert len(a.match_prefix(toks[:4])) == 0      # (4-1)//4 = 0

    def test_match_partial_chain_break(self):
        """A chain whose middle page was evicted must stop at the break
        (never skip-match disjoint pages)."""
        a = PageAllocator(4, 4)
        toks = list(range(1, 13))
        pages = a.alloc(3)
        a.register_prefix(toks, pages)
        a.free(pages)
        # Evict the middle page's content by dropping its hash entry
        # the way LRU eviction does.
        key = a._key_of.pop(pages[1])
        a._by_key.pop(key)
        hit = a.match_prefix(toks + [99])
        assert hit == [pages[0]]
        a.free(hit)


class TestPagedExactMatch:
    @pytest.mark.slow  # tier-1 budget: ~12s; handoff/kvtier identity tests
    # pin the same paged-vs-contiguous contract in tier-1
    def test_matches_contiguous_greedy(self, cfg, params):
        prompts = [[5, 17, 3, 99, 42], list(range(1, 50)), [7] * 20,
                   [9, 8, 7, 6, 5, 4]]
        sp = SamplingParams(max_new_tokens=10, temperature=0.0)
        want, got = [], []
        eng = make_contig(cfg, params)
        reqs = [eng.submit(p, sp) for p in prompts]
        run_all(eng, reqs)
        want = [list(r.output_tokens) for r in reqs]
        eng = make_paged(cfg, params)
        reqs = [eng.submit(p, sp) for p in prompts]
        run_all(eng, reqs)
        got = [list(r.output_tokens) for r in reqs]
        assert got == want

    @pytest.mark.slow   # ~7s: capacity/slot decoupling; pool accounting
    # stays fast-covered by the allocator units + TestPagedExactMatch
    def test_hbm_decoupled_from_slots(self, cfg, params):
        """A pool far below slots × max_len still serves mixed traffic: the
        whole point of paging on v5e."""
        # 4 slots x 128 = 512 positions contiguous; pool = 12 pages x 16
        # = 192 positions.
        eng = make_paged(cfg, params, max_pages=12, page=16)
        assert eng.cache["k"].shape[1] == 12
        sp = SamplingParams(max_new_tokens=8, temperature=0.0)
        reqs = [eng.submit(p, sp) for p in
                ([1, 2, 3], list(range(1, 40)), [4] * 10, [9, 9])]
        run_all(eng, reqs)
        want_eng = make_contig(cfg, params)
        wreqs = [want_eng.submit(p, sp) for p in
                 ([1, 2, 3], list(range(1, 40)), [4] * 10, [9, 9])]
        run_all(want_eng, wreqs)
        assert [list(r.output_tokens) for r in reqs] == \
            [list(r.output_tokens) for r in wreqs]

    def test_sampled_modes_run(self, cfg, params):
        eng = make_paged(cfg, params)
        reqs = [eng.submit([1, 2, 3, 4],
                           SamplingParams(max_new_tokens=5, temperature=0.8,
                                          top_k=7)),
                eng.submit([5, 6], SamplingParams(max_new_tokens=5))]
        run_all(eng, reqs)
        assert all(len(r.output_tokens) == 5 for r in reqs)


class TestPrefixCaching:
    @pytest.mark.slow  # tier-1 budget (ISSUE 20): ~10s;
    # test_identical_prompt_twice_exact keeps prefix reuse fast-covered
    def test_shared_prefix_reuses_pages(self, cfg, params):
        system = list(range(40, 90))         # 50-token shared "system prompt"
        sp = SamplingParams(max_new_tokens=6, temperature=0.0)
        eng = make_paged(cfg, params, page=16, chunk=32)
        r1 = eng.submit(system + [1, 2, 3], sp)
        run_all(eng, [r1])
        stats0 = dict(eng._allocator.stats)
        r2 = eng.submit(system + [7, 8, 9], sp)
        run_all(eng, [r2])
        assert eng._allocator.stats["prefix_hits"] == stats0["prefix_hits"] + 1
        # And the reuse must not perturb outputs: compare vs cold engines.
        cold = make_paged(cfg, params, prefix=False)
        c1 = cold.submit(system + [1, 2, 3], sp)
        c2 = cold.submit(system + [7, 8, 9], sp)
        run_all(cold, [c1, c2])
        assert list(r1.output_tokens) == list(c1.output_tokens)
        assert list(r2.output_tokens) == list(c2.output_tokens)

    def test_identical_prompt_twice_exact(self, cfg, params):
        sp = SamplingParams(max_new_tokens=8, temperature=0.0)
        prompt = list(range(1, 49))          # 48 tokens = 3 full pages
        eng = make_paged(cfg, params, page=16, chunk=16)
        r1 = eng.submit(prompt, sp)
        run_all(eng, [r1])
        r2 = eng.submit(prompt, sp)
        run_all(eng, [r2])
        assert list(r1.output_tokens) == list(r2.output_tokens)
        assert eng._allocator.stats["prefix_hits"] >= 1


class TestConcurrentChunkedPrefills:
    @pytest.mark.slow  # tier-1 budget (ISSUE 14): slowest fast tests re-marked
    def test_two_long_prompts_chunk_concurrently(self, cfg, params):
        """Two long prompts admitted together must BOTH be mid-chunking at
        once (no head-of-line blocking) and finish with exact outputs."""
        sp = SamplingParams(max_new_tokens=4, temperature=0.0)
        long_a = list(range(1, 100))
        long_b = list(range(3, 90))
        eng = make_paged(cfg, params, chunk=32, prefills=2)
        ra, rb = eng.submit(long_a, sp), eng.submit(long_b, sp)
        eng._admit()
        assert len(eng._chunkings) == 2      # both in flight
        run_all(eng, [ra, rb])
        solo = make_paged(cfg, params, chunk=32, prefills=1)
        sa, sb = solo.submit(long_a, sp), solo.submit(long_b, sp)
        run_all(solo, [sa, sb])
        assert list(ra.output_tokens) == list(sa.output_tokens)
        assert list(rb.output_tokens) == list(sb.output_tokens)

    def test_contiguous_mode_also_chunks_concurrently(self, cfg, params):
        sp = SamplingParams(max_new_tokens=4, temperature=0.0)
        eng = LLMEngine(cfg, BatchingSpec(
            max_batch_size=4, max_seq_len=128, prefill_buckets=[16, 64],
            chunked_prefill_tokens=16, max_concurrent_prefills=2),
            params=params)
        ra = eng.submit(list(range(1, 100)), sp)
        rb = eng.submit(list(range(3, 90)), sp)
        eng._admit()
        assert len(eng._chunkings) == 2
        run_all(eng, [ra, rb])
        assert len(ra.output_tokens) == 4 and len(rb.output_tokens) == 4


class TestPreemption:
    @pytest.mark.slow   # ~7s: preempt/resume also chaos-covered
    def test_pool_pressure_preempts_and_resumes(self, cfg, params):
        """A pool too small for all slots forces recompute preemption; every
        request still finishes with the exact greedy output."""
        sp = SamplingParams(max_new_tokens=24, temperature=0.0)
        prompts = [list(range(1, 30)), list(range(2, 60)),
                   list(range(3, 40))]
        # 8 pages x 16 = 128 positions: one max-len sequence fits, three
        # growing sequences cannot — someone must be preempted.
        eng = make_paged(cfg, params, max_pages=8, page=16, chunk=16,
                         prefix=False)
        reqs = [eng.submit(p, sp) for p in prompts]
        run_all(eng, reqs, max_steps=2000)
        want_eng = make_contig(cfg, params)
        wreqs = [want_eng.submit(p, sp) for p in prompts]
        run_all(want_eng, wreqs)
        assert [list(r.output_tokens) for r in reqs] == \
            [list(r.output_tokens) for r in wreqs]


class TestReviewRegressions:
    @pytest.mark.slow  # tier-1 budget: long-prompt chunked prefill, ~9s
    def test_chunk_window_crossing_max_len_via_prefix_hit(self, cfg, params):
        """Prefix hits start tail chunks at page — not chunk — alignment, so
        the final chunk's C-wide window can cross max_seq_len; the padded
        cache row must keep the output exact (regression: the window used to
        clamp and overwrite earlier KV)."""
        sp = SamplingParams(max_new_tokens=4, temperature=0.0)
        shared = list(range(1, 51))          # 50 tokens -> 3 full 16-pages
        long_tail = shared[:48] + list(range(60, 97))   # 85 tokens total
        eng = make_paged(cfg, params, page=16, chunk=32)
        warm = eng.submit(shared, sp)
        run_all(eng, [warm])
        r = eng.submit(long_tail, sp)        # hits 3 pages -> pos starts 48
        run_all(eng, [r])
        assert eng._allocator.stats["prefix_hits"] >= 1
        cold = make_paged(cfg, params, page=16, chunk=32, prefix=False)
        c = cold.submit(long_tail, sp)
        run_all(cold, [c])
        assert list(r.output_tokens) == list(c.output_tokens)

    def test_paged_with_chunking_disabled_falls_back_to_page_chunks(
            self, cfg, params):
        """chunked_prefill_tokens=0 ('off' on the contiguous path) must not
        hang the paged engine (regression: zero-token chunks looped
        forever)."""
        eng = make_paged(cfg, params, chunk=0)
        assert eng.chunk_size == eng.page_size
        r = eng.submit([1, 2, 3, 4, 5], SamplingParams(max_new_tokens=4,
                                                       temperature=0.0))
        run_all(eng, [r])
        assert len(r.output_tokens) == 4

    @pytest.mark.slow  # tier-1 budget (ISSUE 14): slowest fast tests re-marked
    def test_concurrent_prefills_starved_pool_does_not_deadlock(
            self, cfg, params):
        """Two long prompts whose combined prefills exceed the pool: the
        starved chunking must abort/requeue (its pages are invisible to
        decode preemption), not deadlock (regression)."""
        sp = SamplingParams(max_new_tokens=6, temperature=0.0)
        a, b = list(range(1, 81)), list(range(2, 82))
        # 8 pages x 16 = 128 = max_len: one sequence fits; two 5-page
        # prompts cannot prefill together.
        eng = make_paged(cfg, params, max_pages=8, page=16, chunk=16,
                         prefix=False, prefills=2)
        ra, rb = eng.submit(a, sp), eng.submit(b, sp)
        run_all(eng, [ra, rb], max_steps=2000)
        solo = make_paged(cfg, params, chunk=16, prefix=False, prefills=1)
        sa, sb = solo.submit(a, sp), solo.submit(b, sp)
        run_all(solo, [sa, sb])
        assert list(ra.output_tokens) == list(sa.output_tokens)
        assert list(rb.output_tokens) == list(sb.output_tokens)


class TestFlatIndexPreserved:
    """The legacy flat chained-hash path (prefix_index='flat') must keep
    its exact behavior after the radix swap — the match_prefix edges the
    new subsystem must preserve, exercised through the engine."""

    @pytest.mark.slow
    def test_chunking_preempt_resume_page_aligned_flat(self, cfg, params):
        """Cross-class chunking preemption registers written chunks and
        the resume's match_prefix lands page-aligned (the engine's
        chunking-preemption path), with output identical to a cold
        engine."""
        from kubeflow_tpu.core.serving import QoSSpec

        sp = SamplingParams(max_new_tokens=4, temperature=0.0)
        long_p = list(range(1, 70))          # 69 tokens: 4 full 16-pages
        eng = LLMEngine(cfg, BatchingSpec(
            max_batch_size=2, max_seq_len=128, paged=True, page_size=16,
            prefix_index="flat", chunked_prefill_tokens=16,
            max_concurrent_prefills=1, qos=QoSSpec(preemption=True)),
            params=params)
        r1 = eng.submit(long_p, sp, qos="batch")
        for _ in range(2):
            eng.step()                       # a couple of chunks land
        r2 = eng.submit([5, 6, 7, 8] * 3, sp, qos="interactive")
        run_all(eng, [r1, r2])
        assert eng.metrics.snapshot()["preemptions"] >= 1
        assert eng._allocator.stats["prefix_hits"] >= 1   # the resume
        cold = make_paged(cfg, params, prefix=False, chunk=16)
        c1 = cold.submit(long_p, sp)
        c2 = cold.submit([5, 6, 7, 8] * 3, sp)
        run_all(cold, [c1, c2])
        assert list(r1.output_tokens) == list(c1.output_tokens)
        assert list(r2.output_tokens) == list(c2.output_tokens)
        assert eng.kv_pages_in_use() == 0

    @pytest.mark.slow
    def test_spec_rollback_with_shared_pages_flat(self, cfg, params):
        """Speculative rollback truncation never frees a shared
        (registered, ref>0) prefix page on the flat index either."""
        from kubeflow_tpu.core.serving import SpeculativeSpec

        eng = LLMEngine(cfg, BatchingSpec(
            max_batch_size=4, max_seq_len=128, paged=True, page_size=16,
            prefix_index="flat", chunked_prefill_tokens=16,
            speculative=SpeculativeSpec(mode="ngram", k=3)),
            params=params)
        sp = SamplingParams(max_new_tokens=12, temperature=0.0)
        p = [5, 3, 5, 3, 5, 3, 1, 2] * 3
        r1 = eng.submit(list(p), sp)
        for _ in range(6):
            eng.step()
        r2 = eng.submit(list(p) + [4, 4], sp)
        run_all(eng, [r1, r2])
        base = make_paged(cfg, params, prefix=False)
        b1 = base.submit(list(p), sp)
        run_all(base, [b1])
        b2 = base.submit(list(p) + [4, 4], sp)
        run_all(base, [b2])
        assert list(r1.output_tokens) == list(b1.output_tokens)
        assert list(r2.output_tokens) == list(b2.output_tokens)
        assert eng.kv_pages_in_use() == 0
        eng._allocator.assert_quiescent()


class TestPagedAttentionKernel:
    """The Pallas paged-attention decode kernel (ops/paged_attention.py)
    must agree exactly with the gather+XLA oracle (interpret mode off-TPU)."""

    def _setup(self, B=3, H=8, K=2, D=16, pg=8, mpp=4, P=10):
        import numpy as np

        rng = np.random.default_rng(0)
        pool_k = jnp.asarray(rng.normal(size=(P, pg, K, D)), jnp.float32)
        pool_v = jnp.asarray(rng.normal(size=(P, pg, K, D)), jnp.float32)
        q = jnp.asarray(rng.normal(size=(B, 1, H, D)), jnp.float32)
        table = jnp.asarray([[3, 1, 7, -1], [0, 2, -1, -1], [5, 4, 9, 6]],
                            jnp.int32)
        lengths = jnp.asarray([19, 9, 30], jnp.int32)
        return q, pool_k, pool_v, table, lengths

    def test_matches_gather_oracle(self, cfg):
        import dataclasses

        from kubeflow_tpu.ops.paged_attention import paged_decode_attention
        from kubeflow_tpu.serve.engine import _decode_attention
        from kubeflow_tpu.serve.paged import paged_gather

        q, pk, pv, table, lengths = self._setup()
        out = paged_decode_attention(q, pk, pv, table, lengths)
        c = dataclasses.replace(cfg, n_heads=8, n_kv_heads=2, head_dim=16)
        ref = _decode_attention(q, paged_gather(pk, table),
                                paged_gather(pv, table), lengths, c)
        assert float(jnp.abs(out - ref).max()) < 2e-5

    def test_int8_in_kernel_dequant_matches_gather_oracle(self, cfg):
        """int8 pages + scale rows through the kernel's in-VMEM dequant
        must match the gather+dequantize_kv oracle — same math, so the
        only gap is fp32 accumulation order (~1e-6)."""
        import dataclasses

        from kubeflow_tpu.ops.paged_attention import paged_decode_attention
        from kubeflow_tpu.ops.quantization import dequantize_kv, quantize_kv
        from kubeflow_tpu.serve.engine import _decode_attention
        from kubeflow_tpu.serve.paged import paged_gather

        q, pk, pv, table, lengths = self._setup()
        qk, sk = quantize_kv(pk)           # [P,pg,K,D] int8, [P,pg,K] f32
        qv, sv = quantize_kv(pv)
        out = paged_decode_attention(q, qk, qv, table, lengths,
                                     pool_ks=sk, pool_vs=sv)
        c = dataclasses.replace(cfg, n_heads=8, n_kv_heads=2, head_dim=16)
        dk = dequantize_kv(qk, sk, jnp.float32)
        dv = dequantize_kv(qv, sv, jnp.float32)
        ref = _decode_attention(q, paged_gather(dk, table),
                                paged_gather(dv, table), lengths, c)
        assert float(jnp.abs(out - ref).max()) < 2e-5
        # And the quantization itself stays within its error band of the
        # full-precision attention (sanity that scales weren't dropped).
        full = paged_decode_attention(q, pk, pv, table, lengths)
        assert float(jnp.abs(out - full).max()) < 0.05

    def test_int8_kernel_requires_scale_pair(self):
        from kubeflow_tpu.ops.paged_attention import paged_decode_attention
        from kubeflow_tpu.ops.quantization import quantize_kv

        q, pk, pv, table, lengths = self._setup()
        qk, sk = quantize_kv(pk)
        qv, _ = quantize_kv(pv)
        with pytest.raises(ValueError, match="together"):
            paged_decode_attention(q, qk, qv, table, lengths, pool_ks=sk)

    def test_unmapped_and_partial_pages_masked(self):
        """Garbage in unmapped (-1) pages and beyond-length positions must
        not leak into the output: shrinking lengths changes results only
        through real positions."""
        from kubeflow_tpu.ops.paged_attention import paged_decode_attention

        q, pk, pv, table, lengths = self._setup()
        base = paged_decode_attention(q, pk, pv, table, lengths)
        # Poison every unmapped page's content: output must be identical.
        poisoned_k = pk.at[8].set(999.0)    # page 8 is unmapped everywhere
        poisoned_v = pv.at[8].set(999.0)
        out = paged_decode_attention(q, poisoned_k, poisoned_v, table,
                                     lengths)
        assert float(jnp.abs(out - base).max()) == 0.0

    @pytest.mark.slow   # ~12s e2e; the kernel-level pallas-vs-gather
    # equivalence tests above stay fast
    def test_engine_pallas_matches_gather_end_to_end(self):
        """The whole paged engine under attn_impl=pallas (interpret mode)
        must reproduce the gather path's greedy outputs. float32 config:
        the kernel accumulates fp32 where the gather path rounds probs to
        the cache dtype, so in bf16 the two are numerically equal but not
        bitwise — f32 keeps the ~1e-7 gap far below any argmax tie."""
        fcfg = preset("tiny", vocab_size=512, dtype="float32")
        fparams = init_decoder_params(jax.random.PRNGKey(0), fcfg)
        sp = SamplingParams(max_new_tokens=8, temperature=0.0)
        prompts = [[5, 17, 3, 99, 42], list(range(1, 40)), [7] * 20]

        def run(impl):
            eng = LLMEngine(fcfg, BatchingSpec(
                max_batch_size=4, max_seq_len=128, paged=True, page_size=16,
                chunked_prefill_tokens=32, paged_attn_impl=impl),
                params=fparams)
            reqs = [eng.submit(p, sp) for p in prompts]
            run_all(eng, reqs)
            return [list(r.output_tokens) for r in reqs]

        assert run("pallas") == run("gather")

    @pytest.mark.slow   # interpret-mode kernel e2e, ~15s
    def test_engine_int8_pallas_matches_gather_end_to_end(self):
        """int8 pool + in-kernel dequant vs int8 pool + gather+dequant:
        both read the SAME quantized pages, so greedy outputs must be
        token-identical (the dequant happens in different places but is
        the same math; f32 config keeps the fp-accumulation gap far
        below any argmax tie)."""
        fcfg = preset("tiny", vocab_size=512, dtype="float32")
        fparams = init_decoder_params(jax.random.PRNGKey(0), fcfg)
        sp = SamplingParams(max_new_tokens=8, temperature=0.0)
        prompts = [[5, 17, 3, 99, 42], list(range(1, 40)), [7] * 20]

        def run(impl):
            eng = LLMEngine(fcfg, BatchingSpec(
                max_batch_size=4, max_seq_len=128, paged=True, page_size=16,
                chunked_prefill_tokens=32, kv_cache_dtype="int8",
                paged_attn_impl=impl), params=fparams)
            reqs = [eng.submit(p, sp) for p in prompts]
            run_all(eng, reqs)
            return [list(r.output_tokens) for r in reqs]

        assert run("pallas") == run("gather")

    def test_unknown_impl_rejected(self, cfg, params):
        with pytest.raises(ValueError, match="paged_attn_impl"):
            LLMEngine(cfg, BatchingSpec(
                max_batch_size=2, max_seq_len=64, paged=True, page_size=16,
                paged_attn_impl="flash"), params=params)
