"""Multi-tenant QoS in the engine scheduler (ISSUE 6 tentpole layer 1+2):
per-class admission quotas and queue-delay budgets, strict-priority
dequeue, shed-lowest-first under overload, cross-class recompute
preemption, and the per-class observability surface (EngineMetrics qos
labels, X-Kftpu-Qos header end-to-end).

The engine fixture is module-scoped and manually stepped; QoS knobs
(qos_policies, max_queue) are plain attributes mutated per test, the
test_serve_lifecycle idiom."""

import json
import time
import urllib.error
import urllib.request

import pytest
import jax

from kubeflow_tpu.core.serving import (
    BatchingSpec, QOS_CLASSES, QoSClassPolicy,
)
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import (
    EngineOverloaded, LLMEngine, SamplingParams,
)


@pytest.fixture(scope="module")
def cfg():
    return preset("tiny", vocab_size=512)     # byte tokenizer fits


@pytest.fixture(scope="module")
def params(cfg):
    return init_decoder_params(jax.random.PRNGKey(0), cfg)


@pytest.fixture(scope="module")
def engine(cfg, params):
    # Paged so every scenario also audits page-refcount balance.
    return LLMEngine(
        cfg,
        BatchingSpec(max_batch_size=2, max_seq_len=64, prefill_buckets=[16],
                     paged=True, page_size=8, chunked_prefill_tokens=8,
                     decode_steps=4),
        params=params)


def _drain(engine, reqs=(), max_steps=800):
    for _ in range(max_steps):
        worked = engine.step()
        if worked == 0 and all(r.done.is_set() for r in reqs):
            return
    raise AssertionError("engine did not quiesce")


def _quiesce(engine):
    assert engine.kv_pages_in_use() == 0
    engine._allocator.assert_quiescent()


def test_unknown_qos_class_rejected(engine):
    with pytest.raises(ValueError, match="unknown QoS class"):
        engine.submit([1, 2, 3], SamplingParams(max_new_tokens=2),
                      qos="platinum")


def test_priority_dequeue_interactive_jumps_batch(engine):
    """A later-arriving interactive request is admitted before earlier
    batch requests once a slot frees (strict-priority, FIFO in class)."""
    blockers = [engine.submit([i + 1] * 8, SamplingParams(max_new_tokens=24),
                              qos="batch") for i in range(2)]
    engine.step()                          # both slots busy
    engine.qos_preemption = False          # isolate dequeue order
    try:
        b_first = engine.submit([7] * 4, SamplingParams(max_new_tokens=2),
                                qos="batch")
        i_later = engine.submit([8] * 4, SamplingParams(max_new_tokens=2),
                                qos="interactive")
        _drain(engine, blockers + [b_first, i_later])
        assert i_later.first_token_time < b_first.first_token_time, \
            "interactive arrival did not dequeue before queued batch"
    finally:
        engine.qos_preemption = True
    _quiesce(engine)


def test_cross_class_preemption_recompute(engine):
    """An interactive arrival recompute-preempts a running batch slot via
    the preempted lane; the victim resumes later and still completes with
    its full token budget — refcount-balanced throughout."""
    blockers = [engine.submit([i + 1] * 8, SamplingParams(max_new_tokens=40),
                              qos="batch") for i in range(2)]
    engine.step()
    before = engine.metrics.snapshot().get("preemptions", 0)
    urgent = engine.submit([9] * 4, SamplingParams(max_new_tokens=4),
                           qos="interactive")
    _drain(engine, blockers + [urgent])
    snap = engine.metrics.snapshot()
    assert snap["preemptions"] > before, "no cross-class preemption fired"
    assert snap["qos"]["batch"]["preempted"] >= 1
    assert snap["qos"]["interactive"].get("preempted", 0) == 0
    assert urgent.finish_reason in ("stop", "length")
    # Preempted batch work resumed and finished with its full budget.
    assert all(b.finish_reason in ("stop", "length") for b in blockers)
    assert all(len(b.output_tokens) == 40 or b.finish_reason == "stop"
               for b in blockers)
    _quiesce(engine)


def test_preemption_never_evicts_same_or_higher_class(engine):
    """A standard arrival must not preempt standard or interactive slots
    — preemption changes WHO degrades, never whether."""
    blockers = [engine.submit([i + 1] * 8, SamplingParams(max_new_tokens=16),
                              qos="interactive") for i in range(2)]
    engine.step()
    before = engine.metrics.snapshot().get("preemptions", 0)
    waiting = engine.submit([5] * 4, SamplingParams(max_new_tokens=2),
                            qos="standard")
    for _ in range(3):
        engine.step()
    assert engine.metrics.snapshot().get("preemptions", 0) == before
    _drain(engine, blockers + [waiting])
    _quiesce(engine)


def test_overload_sheds_only_batch_until_exhausted(engine):
    """ISSUE 6 satellite: a mixed interactive+batch backlog over the
    global quota sheds ONLY batch (429 at the door + scheduler-side shed)
    until batch is exhausted; per-class shed counters pin attribution."""
    engine.max_queue = 3
    blockers = [engine.submit([i + 1] * 8, SamplingParams(max_new_tokens=48),
                              qos="interactive") for i in range(2)]
    engine.step()                           # fill both slots
    try:
        shed0 = {c: engine.metrics.snapshot().get("qos", {})
                 .get(c, {}).get("shed", 0) for c in QOS_CLASSES}
        queued_batch = [engine.submit([6] * 4,
                                      SamplingParams(max_new_tokens=2),
                                      qos="batch") for _ in range(2)]
        queued_int = engine.submit([7] * 4, SamplingParams(max_new_tokens=2),
                                   qos="interactive")
        # Queue is now full (3). A batch arrival is the lowest class
        # present → 429 at the door, with Retry-After and its class.
        with pytest.raises(EngineOverloaded) as exc:
            engine.submit([8] * 4, SamplingParams(max_new_tokens=2),
                          qos="batch")
        assert exc.value.qos == "batch"
        assert exc.value.retry_after > 0
        # Interactive arrivals over-admit while lower classes wait: the
        # scheduler sheds queued batch to restore the bound. Repeat until
        # batch is exhausted from the queue.
        over_int = [engine.submit([9] * 4, SamplingParams(max_new_tokens=2),
                                  qos="interactive") for _ in range(2)]
        engine._drain_waiting()
        engine._enforce_queue_bound()
        assert all(b.done.is_set() and b.finish_reason == "shed"
                   for b in queued_batch), "queued batch was not shed first"
        assert not queued_int.done.is_set(), "interactive was shed"
        assert not any(r.done.is_set() for r in over_int)
        shed = engine.metrics.snapshot()["qos"]
        assert shed["batch"]["shed"] - shed0["batch"] == 3   # 1x429 + 2 queue
        assert shed["interactive"]["shed"] - shed0["interactive"] == 0
        # Batch exhausted: now the lowest class present is interactive —
        # a further interactive arrival 429s rather than shedding peers.
        with pytest.raises(EngineOverloaded) as exc:
            engine.submit([9] * 4, SamplingParams(max_new_tokens=2),
                          qos="interactive")
        assert exc.value.qos == "interactive"
        _drain(engine, blockers + [queued_int] + over_int)
    finally:
        engine.max_queue = 0
    _quiesce(engine)


def test_per_class_admission_quota(engine):
    """A class's own max_queue 429s that class even when the shared queue
    has room — and leaves other classes unaffected."""
    engine.qos_policies = {"batch": QoSClassPolicy(max_queue=1)}
    blockers = [engine.submit([i + 1] * 8, SamplingParams(max_new_tokens=24),
                              qos="standard") for i in range(2)]
    engine.step()
    try:
        q = engine.submit([5] * 4, SamplingParams(max_new_tokens=2),
                          qos="batch")
        with pytest.raises(EngineOverloaded) as exc:
            engine.submit([6] * 4, SamplingParams(max_new_tokens=2),
                          qos="batch")
        assert exc.value.qos == "batch"
        ok = engine.submit([7] * 4, SamplingParams(max_new_tokens=2),
                           qos="standard")     # other classes unaffected
        _drain(engine, blockers + [q, ok])
        assert ok.finish_reason in ("stop", "length")
    finally:
        engine.qos_policies = {}
    _quiesce(engine)


def test_per_class_queue_delay_budget(engine):
    """A tight batch queue-delay budget sheds stale queued batch while a
    budget-less interactive entry survives the same wait."""
    engine.qos_policies = {
        "batch": QoSClassPolicy(queue_delay_budget=0.02)}
    blockers = [engine.submit([i + 1] * 8, SamplingParams(max_new_tokens=32),
                              qos="interactive") for i in range(2)]
    engine.step()
    try:
        b = engine.submit([5] * 4, SamplingParams(max_new_tokens=2),
                          qos="batch")
        i = engine.submit([6] * 4, SamplingParams(max_new_tokens=2),
                          qos="interactive")
        time.sleep(0.05)
        engine.step()
        assert b.done.is_set() and b.finish_reason == "shed"
        assert not (i.done.is_set() and i.finish_reason == "shed")
        _drain(engine, blockers + [i])
    finally:
        engine.qos_policies = {}
    _quiesce(engine)


def test_preemption_storm_quiescent(engine):
    """Repeated interactive bursts preempting batch (the chaos-adjacent
    storm): every request resolves, refcounts balance, zero page leaks."""
    batch = [engine.submit([i + 1] * 8, SamplingParams(max_new_tokens=24),
                           qos="batch") for i in range(4)]
    engine.step()
    storm = []
    for wave in range(3):
        storm.extend(engine.submit([wave + 10] * 4,
                                   SamplingParams(max_new_tokens=3),
                                   qos="interactive") for _ in range(2))
        for _ in range(6):
            engine.step()
    _drain(engine, batch + storm)
    assert all(r.finish_reason in ("stop", "length") for r in batch + storm)
    assert engine.metrics.snapshot()["preemptions"] >= 1
    _quiesce(engine)


def test_qos_metrics_snapshot_and_histogram(engine):
    """Per-class snapshot carries completion counts and latency p95s; the
    per-class queue-delay histogram partitions the aggregate."""
    reqs = [engine.submit([c + 1] * 4, SamplingParams(max_new_tokens=2),
                          qos=cls)
            for c, cls in enumerate(("interactive", "batch"))]
    _drain(engine, reqs)
    snap = engine.metrics.snapshot()
    for cls in ("interactive", "batch"):
        assert snap["qos"][cls]["completed"] >= 1
        assert "ttft_p95_ms" in snap["qos"][cls]
    _, agg_counts, _, agg_n = engine.metrics.queue_delay_histogram()
    per_class_n = sum(
        engine.metrics.queue_delay_histogram(cls)[3]
        for cls in engine.metrics.qos_classes())
    assert per_class_n == agg_n
    assert agg_n == sum(agg_counts)
    _quiesce(engine)


# -- header propagation through the HTTP surface ------------------------------

@pytest.fixture(scope="module")
def served(cfg, params):
    from kubeflow_tpu.serve.server import ModelServer

    eng = LLMEngine(
        cfg,
        BatchingSpec(max_batch_size=2, max_seq_len=64, prefill_buckets=[16],
                     paged=True, page_size=8, chunked_prefill_tokens=8,
                     decode_steps=4),
        params=params)
    srv = ModelServer("qos-svc", eng, port=0)
    srv.start()
    yield srv
    srv.stop()


def _post(url, body, headers=None):
    data = json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def test_qos_header_reaches_engine_metrics(served):
    status, _ = _post(served.url + "/v1/completions",
                      {"prompt": "hi", "max_tokens": 2},
                      headers={"X-Kftpu-Qos": "interactive"})
    assert status == 200
    status, _ = _post(served.url + "/v1/completions",
                      {"prompt": "hi", "max_tokens": 2, "qos": "batch"})
    assert status == 200
    snap = served.engine.metrics.snapshot()
    assert snap["qos"]["interactive"]["completed"] >= 1   # via header
    assert snap["qos"]["batch"]["completed"] >= 1         # via body field
    text = served.metrics_text()
    assert 'kftpu_serving_qos_requests_total{model="qos-svc",' \
           'qos="interactive"}' in text
    assert "kftpu_serving_qos_ttft_p95_ms" in text
    assert "kftpu_serving_ttft_p95_ms" in text
    assert "kftpu_serving_qos_queue_delay_seconds_bucket" in text


def test_unknown_qos_header_is_400(served):
    status, body = _post(served.url + "/v1/completions",
                         {"prompt": "hi", "max_tokens": 2},
                         headers={"X-Kftpu-Qos": "platinum"})
    assert status == 400
    assert "unknown QoS class" in body["error"]


def test_router_forwards_qos_header(served):
    from kubeflow_tpu.serve.router import Router

    router = Router(queue_timeout=5.0)
    router.set_backends({"latest": [served.url]})
    router.start()
    try:
        before = served.engine.metrics.snapshot().get("qos", {}) \
            .get("batch", {}).get("completed", 0)
        status, _ = _post(router.url + "/v1/completions",
                          {"prompt": "hi", "max_tokens": 2},
                          headers={"X-Kftpu-Qos": "batch"})
        assert status == 200
        after = served.engine.metrics.snapshot()["qos"]["batch"]["completed"]
        assert after == before + 1, "qos header lost at the router hop"
    finally:
        router.stop()
