"""Runtime sanitizers (ISSUE 7): KFTPU_SANITIZE mode parsing, the
refcount owner-stamping allocator, and the lockorder watchdog — the
dynamic cross-checks of the S4xx/R5xx static rules.

The watchdog tests install/uninstall within the process; every test
restores the real threading factories on exit (the uninstall is in a
finally) so the rest of the suite runs unpatched."""

import threading

import pytest

from kubeflow_tpu.runtime import sanitize
from kubeflow_tpu.runtime.sanitize import (
    LockOrderError, install_lockorder_watchdog, sanitize_modes,
    uninstall_lockorder_watchdog,
)


class TestModeParsing:
    def test_unset_and_zero_are_off(self, monkeypatch):
        monkeypatch.delenv("KFTPU_SANITIZE", raising=False)
        assert sanitize_modes() == frozenset()
        monkeypatch.setenv("KFTPU_SANITIZE", "0")
        assert sanitize_modes() == frozenset()

    def test_legacy_one_means_transfer(self, monkeypatch):
        monkeypatch.setenv("KFTPU_SANITIZE", "1")
        assert sanitize_modes() == {"transfer"}

    def test_named_modes_and_lists(self, monkeypatch):
        monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
        assert sanitize_modes() == {"refcount"}
        monkeypatch.setenv("KFTPU_SANITIZE", "refcount,lockorder")
        assert sanitize_modes() == {"refcount", "lockorder"}
        monkeypatch.setenv("KFTPU_SANITIZE", "all")
        assert sanitize_modes() == {"transfer", "refcount", "lockorder"}

    def test_unknown_token_degrades_to_transfer(self, monkeypatch):
        # pre-ISSUE-7 setups used arbitrary truthy values for the
        # transfer guard; they must keep meaning what they meant
        monkeypatch.setenv("KFTPU_SANITIZE", "yes")
        assert sanitize_modes() == {"transfer"}

    def test_refcount_mode_does_not_engage_transfer_guard(self, monkeypatch):
        monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
        assert "transfer" not in sanitize_modes()


class TestRefcountStamping:
    @pytest.fixture()
    def pool(self, monkeypatch):
        monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
        from kubeflow_tpu.serve.paged import PageAllocator

        return PageAllocator(8, 4)

    def test_owner_attribution_and_balance(self, pool):
        assert pool.refcount_debug
        # deliberately unrecorded allocs: the leak report is the subject
        a = pool.alloc(2, owner="req-A")  # lint: disable=R501
        b = pool.alloc(1, owner="req-B")  # lint: disable=R501
        rep = pool.leak_report_by_owner()
        assert rep == {"req-A": 2, "req-B": 1}
        pool.free(a)
        assert pool.leak_report_by_owner() == {"req-B": 1}
        pool.free(b)
        assert pool.leak_report_by_owner() == {}
        pool.assert_quiescent()
        assert pool.stats["stamped_allocs"] == 3

    def test_incref_stacks_stamps(self, pool):
        pages = pool.alloc(1, owner="first")
        pool.incref(pages, owner="second")
        assert pool.leak_report_by_owner() == {"first": 1, "second": 1}
        pool.free(pages)     # LIFO: pops "second"
        assert pool.leak_report_by_owner() == {"first": 1}
        pool.free(pages)
        pool.assert_quiescent()

    def test_quiescence_failure_names_the_owner(self, pool):
        pool.alloc(1, owner="req-leaky")
        with pytest.raises(AssertionError, match="req-leaky"):
            pool.assert_quiescent()

    def test_site_stamp_when_no_owner(self, pool):
        pool.alloc(1)
        (label,) = pool.leak_report_by_owner()
        assert "test_sanitizers.py" in label

    def test_off_by_default(self, monkeypatch):
        monkeypatch.delenv("KFTPU_SANITIZE", raising=False)
        from kubeflow_tpu.serve.paged import PageAllocator

        pool = PageAllocator(4, 4)
        pool.free(pool.alloc(2, owner="x"))
        assert not pool.refcount_debug
        assert pool._stamps == {}
        assert pool.stats["stamped_allocs"] == 0
        pool.assert_quiescent()


class TestLockOrderWatchdog:
    def test_inversion_raises_and_releases(self):
        wd = install_lockorder_watchdog()
        try:
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
            with pytest.raises(LockOrderError, match="inversion"):
                with b:
                    with a:
                        pass
            # the failed acquisition must not leave 'a' locked
            assert a.acquire(timeout=1)
            a.release()
            rep = wd.report()
            assert any(rep.values())
        finally:
            uninstall_lockorder_watchdog()

    def test_consistent_order_is_silent(self):
        install_lockorder_watchdog()
        try:
            a = threading.Lock()
            b = threading.Lock()
            for _ in range(3):
                with a:
                    with b:
                        pass
        finally:
            uninstall_lockorder_watchdog()

    def test_same_site_instances_are_exempt(self):
        # ordered traversal over same-class instances (two Routers' _lock
        # from one creation line) is legitimate, not an inversion
        install_lockorder_watchdog()
        try:
            def mk():
                return threading.Lock()

            a, b = mk(), mk()
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
        finally:
            uninstall_lockorder_watchdog()

    def test_condition_event_queue_still_work(self):
        import queue

        install_lockorder_watchdog()
        try:
            q = queue.Queue()
            q.put(1)
            assert q.get(timeout=1) == 1
            ev = threading.Event()
            ev.set()
            assert ev.wait(0.5)
            lk = threading.Lock()
            cv = threading.Condition(lk)
            hits = []

            def waiter():
                with cv:
                    while not hits:
                        cv.wait(1.0)

            t = threading.Thread(target=waiter)
            t.start()
            with cv:
                hits.append(1)
                cv.notify_all()
            t.join(timeout=5)
            assert not t.is_alive()
        finally:
            uninstall_lockorder_watchdog()

    def test_cross_thread_edges_compose(self):
        # thread 1 records a->b; the MAIN thread closing b->a still fails:
        # the graph is process-wide, not per-thread
        install_lockorder_watchdog()
        try:
            a = threading.Lock()
            b = threading.Lock()

            def t1():
                with a:
                    with b:
                        pass

            t = threading.Thread(target=t1)
            t.start()
            t.join(timeout=5)
            with pytest.raises(LockOrderError):
                with b:
                    with a:
                        pass
        finally:
            uninstall_lockorder_watchdog()

    def test_install_is_idempotent_and_uninstall_restores(self):
        orig = threading.Lock
        wd1 = install_lockorder_watchdog()
        try:
            wd2 = install_lockorder_watchdog()
            assert wd1 is wd2
        finally:
            uninstall_lockorder_watchdog()
        assert threading.Lock is orig
        assert sanitize.lockorder_watchdog() is None


class TestEngineWiring:
    def test_transfer_flag_tracks_mode(self, monkeypatch):
        """engine.sanitize (the transfer guard) engages for transfer-ish
        values only — refcount/lockorder runs must not change the decode
        path's transfer semantics."""
        jax = pytest.importorskip("jax")  # noqa: F841
        from kubeflow_tpu.core.serving import BatchingSpec
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.serve.engine import LLMEngine

        cfg = preset("tiny")

        def mk():
            return LLMEngine(
                cfg, BatchingSpec(max_batch_size=1, max_seq_len=32,
                                  prefill_buckets=[16]), seed=0)

        monkeypatch.setenv("KFTPU_SANITIZE", "1")
        assert mk().sanitize is True
        monkeypatch.setenv("KFTPU_SANITIZE", "transfer,refcount")
        assert mk().sanitize is True
        monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
        assert mk().sanitize is False
        monkeypatch.delenv("KFTPU_SANITIZE")
        assert mk().sanitize is False
