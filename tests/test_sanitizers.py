"""Runtime sanitizers (ISSUEs 7/8): KFTPU_SANITIZE mode parsing, the
refcount owner-stamping allocator, the lockorder watchdog — the dynamic
cross-checks of the S4xx/R5xx static rules — and the recompile watchdog,
the dynamic half of the F6xx compilation-stability family: zero
steady-state recompiles on warmed dense/paged/spec engines and a warmed
train step, every warmup trace attributed to a call site.

The watchdog tests install/uninstall within the process; every test
restores the real threading factories / logging wiring on exit (the
uninstall is in a finally) so the rest of the suite runs unpatched."""

import logging
import threading

import pytest

from kubeflow_tpu.runtime import sanitize
from kubeflow_tpu.runtime.sanitize import (
    LockOrderError, RecompileError, install_lockorder_watchdog,
    install_recompile_watchdog, recompile_report, sanitize_modes,
    uninstall_lockorder_watchdog, uninstall_recompile_watchdog,
)


class TestModeParsing:
    def test_unset_and_zero_are_off(self, monkeypatch):
        monkeypatch.delenv("KFTPU_SANITIZE", raising=False)
        assert sanitize_modes() == frozenset()
        monkeypatch.setenv("KFTPU_SANITIZE", "0")
        assert sanitize_modes() == frozenset()

    def test_legacy_one_means_transfer(self, monkeypatch):
        monkeypatch.setenv("KFTPU_SANITIZE", "1")
        assert sanitize_modes() == {"transfer"}

    def test_named_modes_and_lists(self, monkeypatch):
        monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
        assert sanitize_modes() == {"refcount"}
        monkeypatch.setenv("KFTPU_SANITIZE", "refcount,lockorder")
        assert sanitize_modes() == {"refcount", "lockorder"}
        monkeypatch.setenv("KFTPU_SANITIZE", "all")
        assert sanitize_modes() == {"transfer", "refcount", "lockorder",
                                    "recompile", "contract", "threads"}

    def test_recompile_and_contract_are_named_modes(self, monkeypatch):
        # neither must degrade to the legacy transfer fallback
        monkeypatch.setenv("KFTPU_SANITIZE", "recompile")
        assert sanitize_modes() == {"recompile"}
        monkeypatch.setenv("KFTPU_SANITIZE", "contract")
        assert sanitize_modes() == {"contract"}

    def test_threads_is_a_named_mode(self, monkeypatch):
        monkeypatch.setenv("KFTPU_SANITIZE", "threads")
        assert sanitize_modes() == {"threads"}

    def test_unknown_token_degrades_to_transfer(self, monkeypatch):
        # pre-ISSUE-7 setups used arbitrary truthy values for the
        # transfer guard; they must keep meaning what they meant
        monkeypatch.setenv("KFTPU_SANITIZE", "yes")
        assert sanitize_modes() == {"transfer"}

    def test_refcount_mode_does_not_engage_transfer_guard(self, monkeypatch):
        monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
        assert "transfer" not in sanitize_modes()


class TestRefcountStamping:
    @pytest.fixture()
    def pool(self, monkeypatch):
        monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
        from kubeflow_tpu.serve.paged import PageAllocator

        return PageAllocator(8, 4)

    def test_owner_attribution_and_balance(self, pool):
        assert pool.refcount_debug
        # deliberately unrecorded allocs: the leak report is the subject
        a = pool.alloc(2, owner="req-A")  # lint: disable=R501
        b = pool.alloc(1, owner="req-B")  # lint: disable=R501
        rep = pool.leak_report_by_owner()
        assert rep == {"req-A": 2, "req-B": 1}
        pool.free(a)
        assert pool.leak_report_by_owner() == {"req-B": 1}
        pool.free(b)
        assert pool.leak_report_by_owner() == {}
        pool.assert_quiescent()
        assert pool.stats["stamped_allocs"] == 3

    def test_incref_stacks_stamps(self, pool):
        pages = pool.alloc(1, owner="first")
        pool.incref(pages, owner="second")
        assert pool.leak_report_by_owner() == {"first": 1, "second": 1}
        pool.free(pages)     # LIFO: pops "second"
        assert pool.leak_report_by_owner() == {"first": 1}
        pool.free(pages)
        pool.assert_quiescent()

    def test_quiescence_failure_names_the_owner(self, pool):
        pool.alloc(1, owner="req-leaky")
        with pytest.raises(AssertionError, match="req-leaky"):
            pool.assert_quiescent()

    def test_site_stamp_when_no_owner(self, pool):
        pool.alloc(1)
        (label,) = pool.leak_report_by_owner()
        assert "test_sanitizers.py" in label

    def test_off_by_default(self, monkeypatch):
        monkeypatch.delenv("KFTPU_SANITIZE", raising=False)
        from kubeflow_tpu.serve.paged import PageAllocator

        pool = PageAllocator(4, 4)
        pool.free(pool.alloc(2, owner="x"))
        assert not pool.refcount_debug
        assert pool._stamps == {}
        assert pool.stats["stamped_allocs"] == 0
        pool.assert_quiescent()


class TestLockOrderWatchdog:
    def test_inversion_raises_and_releases(self):
        wd = install_lockorder_watchdog()
        try:
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
            with pytest.raises(LockOrderError, match="inversion"):
                with b:
                    with a:
                        pass
            # the failed acquisition must not leave 'a' locked
            assert a.acquire(timeout=1)
            a.release()
            rep = wd.report()
            assert any(rep.values())
        finally:
            uninstall_lockorder_watchdog()

    def test_consistent_order_is_silent(self):
        install_lockorder_watchdog()
        try:
            a = threading.Lock()
            b = threading.Lock()
            for _ in range(3):
                with a:
                    with b:
                        pass
        finally:
            uninstall_lockorder_watchdog()

    def test_same_site_instances_are_exempt(self):
        # ordered traversal over same-class instances (two Routers' _lock
        # from one creation line) is legitimate, not an inversion
        install_lockorder_watchdog()
        try:
            def mk():
                return threading.Lock()

            a, b = mk(), mk()
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
        finally:
            uninstall_lockorder_watchdog()

    def test_condition_event_queue_still_work(self):
        import queue

        install_lockorder_watchdog()
        try:
            q = queue.Queue()
            q.put(1)
            assert q.get(timeout=1) == 1
            ev = threading.Event()
            ev.set()
            assert ev.wait(0.5)
            lk = threading.Lock()
            cv = threading.Condition(lk)
            hits = []

            def waiter():
                with cv:
                    while not hits:
                        cv.wait(1.0)

            t = threading.Thread(target=waiter)
            t.start()
            with cv:
                hits.append(1)
                cv.notify_all()
            t.join(timeout=5)
            assert not t.is_alive()
        finally:
            uninstall_lockorder_watchdog()

    def test_cross_thread_edges_compose(self):
        # thread 1 records a->b; the MAIN thread closing b->a still fails:
        # the graph is process-wide, not per-thread
        install_lockorder_watchdog()
        try:
            a = threading.Lock()
            b = threading.Lock()

            def t1():
                with a:
                    with b:
                        pass

            t = threading.Thread(target=t1)
            t.start()
            t.join(timeout=5)
            with pytest.raises(LockOrderError):
                with b:
                    with a:
                        pass
        finally:
            uninstall_lockorder_watchdog()

    def test_install_is_idempotent_and_uninstall_restores(self):
        orig = threading.Lock
        wd1 = install_lockorder_watchdog()
        try:
            wd2 = install_lockorder_watchdog()
            assert wd1 is wd2
        finally:
            uninstall_lockorder_watchdog()
        assert threading.Lock is orig
        assert sanitize.lockorder_watchdog() is None


@pytest.fixture()
def recompile_wd():
    wd = install_recompile_watchdog()
    wd.reset()
    try:
        yield wd
    finally:
        uninstall_recompile_watchdog()


class TestRecompileWatchdog:
    def test_counts_and_attributes_each_compile(self, recompile_wd):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp

        f = jax.jit(lambda x: x * 2)
        f(jnp.ones(3))
        f(jnp.ones(3))              # cache hit: not a compile
        recompile_wd.mark_warm()
        recompile_wd.assert_no_steady_recompiles()   # still clean
        f(jnp.ones(5))              # new shape: steady retrace
        rep = recompile_report()
        assert rep["warm"] is True
        assert any(e["fn"] == "<lambda>" for e in rep["warmup"])
        # every entry — warmup and steady — is attributed to THIS file
        for e in rep["warmup"] + rep["steady"]:
            assert "test_sanitizers.py" in e["site"], e
        assert rep["steady_count"] >= 1
        with pytest.raises(RecompileError) as exc:
            recompile_wd.assert_no_steady_recompiles()
        assert "test_sanitizers.py" in str(exc.value)

    def test_weak_type_is_its_own_cache_entry(self, recompile_wd):
        """The F602 defect, observed dynamically: a Python scalar and an
        explicitly-dtyped scalar of the same value are two compiles."""
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp

        f = jax.jit(lambda x: x + 1)
        f(jnp.float32(2.0))
        recompile_wd.mark_warm()
        # retrace-ok: the weak-typed retrace IS this test's subject
        f(2.0)
        assert recompile_wd.steady_count() >= 1

    def test_install_is_idempotent_and_uninstall_restores(self):
        lg = logging.getLogger("jax._src.interpreters.pxla")
        level, prop = lg.level, lg.propagate
        wd1 = install_recompile_watchdog()
        try:
            assert install_recompile_watchdog() is wd1
            assert lg.level == logging.DEBUG and lg.propagate is False
        finally:
            uninstall_recompile_watchdog()
        assert lg.level == level and lg.propagate == prop
        assert sanitize.recompile_watchdog() is None
        assert recompile_report() == {}          # off = empty payload

    def test_warnings_still_reach_parent_handlers(self, recompile_wd):
        """Propagation is cut to keep DEBUG compile records off the
        console, but WARNING+ records must still reach the jax logger's
        own handlers."""
        seen = []

        class Probe(logging.Handler):
            def emit(self, record):
                seen.append(record.getMessage())

        probe = Probe()
        parent = logging.getLogger("jax")
        parent.addHandler(probe)
        try:
            logging.getLogger("jax._src.interpreters.pxla").warning(
                "a real warning")
        finally:
            parent.removeHandler(probe)
        assert seen == ["a real warning"]


class TestSteadyStateZeroRecompiles:
    """The acceptance criterion: warmed engines and a warmed train step
    hold a FIXED trace set — identical steady-state traffic compiles
    nothing, and every warmup trace is attributed to a named site."""

    PROMPTS = [[3, 5, 7, 3, 5, 7, 3, 5], [2, 4, 6, 2, 4, 6, 2, 4]]

    def _drive(self, eng, wd):
        from kubeflow_tpu.serve.engine import SamplingParams

        for p in self.PROMPTS:
            eng.generate(p, SamplingParams(max_new_tokens=8))
        wd.mark_warm()
        for p in self.PROMPTS:
            eng.generate(p, SamplingParams(max_new_tokens=8))
        rep = recompile_report()
        assert rep["warmup"], "warmup must record attributed compiles"
        assert all(e["site"] != "<unknown>" for e in rep["warmup"])
        assert rep["steady_count"] == 0, rep["steady"]
        wd.assert_no_steady_recompiles()

    def test_dense_and_spec_engines(self, recompile_wd):
        jax = pytest.importorskip("jax")  # noqa: F841
        from kubeflow_tpu.core.serving import BatchingSpec, SpeculativeSpec
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.serve.engine import LLMEngine

        cfg = preset("tiny")
        self._drive(LLMEngine(cfg, BatchingSpec(
            max_batch_size=2, max_seq_len=64, prefill_buckets=[16])),
            recompile_wd)
        recompile_wd.reset()
        self._drive(LLMEngine(cfg, BatchingSpec(
            max_batch_size=2, max_seq_len=64, prefill_buckets=[16],
            speculative=SpeculativeSpec(mode="ngram", k=3))),
            recompile_wd)

    def test_paged_engine(self, recompile_wd):
        jax = pytest.importorskip("jax")  # noqa: F841
        from kubeflow_tpu.core.serving import BatchingSpec
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.serve.engine import LLMEngine

        cfg = preset("tiny")
        eng = LLMEngine(cfg, BatchingSpec(
            max_batch_size=2, max_seq_len=64, paged=True, page_size=16))
        self._drive(eng, recompile_wd)
        eng._allocator.assert_quiescent()

    @pytest.mark.slow  # tier-1 budget (ISSUE 14): slowest fast tests re-marked
    def test_warmed_train_step(self, recompile_wd):
        jax = pytest.importorskip("jax")
        import numpy as np

        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.runtime.mesh import build_mesh
        from kubeflow_tpu.train.optim import OptimizerConfig
        from kubeflow_tpu.train.step import setup_train

        cfg = preset("tiny", vocab_size=256, max_seq_len=32)
        task = setup_train(cfg, OptimizerConfig(warmup_steps=0),
                           build_mesh({"data": 8}))
        batch = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (8, 17), dtype=np.int32)
        put = lambda: jax.device_put(batch, task.batch_sharding)  # noqa: E731
        state, _ = task.step_fn(task.state, put())
        recompile_wd.mark_warm()
        state, _ = task.step_fn(state, put())
        assert recompile_wd.steady_count() == 0, recompile_report()["steady"]


class TestEngineWiring:
    def test_transfer_flag_tracks_mode(self, monkeypatch):
        """engine.sanitize (the transfer guard) engages for transfer-ish
        values only — refcount/lockorder runs must not change the decode
        path's transfer semantics."""
        jax = pytest.importorskip("jax")  # noqa: F841
        from kubeflow_tpu.core.serving import BatchingSpec
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.serve.engine import LLMEngine

        cfg = preset("tiny")

        def mk():
            return LLMEngine(
                cfg, BatchingSpec(max_batch_size=1, max_seq_len=32,
                                  prefill_buckets=[16]), seed=0)

        monkeypatch.setenv("KFTPU_SANITIZE", "1")
        assert mk().sanitize is True
        monkeypatch.setenv("KFTPU_SANITIZE", "transfer,refcount")
        assert mk().sanitize is True
        monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
        assert mk().sanitize is False
        monkeypatch.delenv("KFTPU_SANITIZE")
        assert mk().sanitize is False


# -- contract auditor (the dynamic half of the X7xx rules, ISSUE 10) -----------


class TestContractAuditor:
    def test_install_note_report_uninstall(self):
        from kubeflow_tpu.runtime.sanitize import (
            contract_report, install_contract_auditor,
            uninstall_contract_auditor,
        )

        wd = install_contract_auditor()
        try:
            assert install_contract_auditor() is wd   # idempotent
            wd.note_series("kftpu_b", "produced")
            wd.note_series("kftpu_a", "produced")
            wd.note_series("kftpu_a", "produced")     # set semantics
            wd.note_series("kftpu_a", "consumed")
            wd.note_header("X-Kftpu-Qos", "set")
            wd.note_header("X-Kftpu-Trace", "read")
            rep = contract_report()
            assert rep["series_produced"] == ["kftpu_a", "kftpu_b"]
            assert rep["series_consumed"] == ["kftpu_a"]
            assert rep["headers_set"] == ["X-Kftpu-Qos"]
            assert rep["headers_read"] == ["X-Kftpu-Trace"]
            wd.reset()
            assert contract_report() == {
                "series_produced": [], "series_consumed": [],
                "headers_set": [], "headers_read": []}
        finally:
            uninstall_contract_auditor()
        assert contract_report() == {}

    def test_diff_matches_exact_suffix_and_prefix(self):
        from kubeflow_tpu.runtime.sanitize import contract_diff

        static = {
            "series": {"produced": ["kftpu_delay_seconds", "kftpu_x"],
                       "consumed": ["kftpu_scraped"],
                       "produced_prefixes": ["kftpu_router_"]},
            "headers": {"set": ["X-Kftpu-Qos"], "read": ["X-Kftpu-Trace"]},
        }
        report = {
            "series_produced": [
                "kftpu_x",                        # exact
                "kftpu_delay_seconds_bucket",     # histogram suffix
                "kftpu_router_whatever",          # declared prefix
                "kftpu_rogue_total",              # UNDECLARED
            ],
            "series_consumed": ["kftpu_scraped"],
            "headers_set": ["x-kftpu-qos"],       # case-insensitive
            "headers_read": ["X-Kftpu-Rogue"],    # UNDECLARED
        }
        diff = contract_diff(report, static)
        assert diff["undeclared_series"] == ["kftpu_rogue_total"]
        assert diff["undeclared_headers"] == ["X-Kftpu-Rogue"]

    def test_diff_accepts_manifest_shaped_dicts(self):
        # --contracts-json emits {name: [sites]} maps; iteration over
        # them must mean "the declared names", not the site lists.
        from kubeflow_tpu.runtime.sanitize import contract_diff

        static = {"series": {"produced": {"kftpu_x": ["a.py:1"]},
                             "consumed": {}},
                  "headers": {"set": {"X-Kftpu-Qos": ["b.py:2"]},
                              "read": {}}}
        report = {"series_produced": ["kftpu_x"],
                  "headers_set": ["X-Kftpu-Qos"]}
        diff = contract_diff(report, static)
        assert diff == {"undeclared_series": [], "undeclared_headers": []}

    def test_maybe_install_contract_mode(self, monkeypatch):
        from kubeflow_tpu.runtime.sanitize import (
            contract_auditor, maybe_install, uninstall_contract_auditor,
        )

        uninstall_contract_auditor()
        monkeypatch.setenv("KFTPU_SANITIZE", "contract")
        try:
            maybe_install()
            assert contract_auditor() is not None
        finally:
            uninstall_contract_auditor()

    def test_registry_render_hook_is_noop_when_off(self):
        # The obs/registry bridge resolves through sys.modules and must
        # not record (or fail) when no auditor is installed.
        from kubeflow_tpu.obs.registry import (
            MetricsRegistry, contract_note_series,
        )
        from kubeflow_tpu.runtime.sanitize import (
            contract_report, install_contract_auditor,
            uninstall_contract_auditor,
        )

        uninstall_contract_auditor()
        contract_note_series("kftpu_whatever", "produced")   # no-op
        install_contract_auditor()
        try:
            reg = MetricsRegistry()
            reg.gauge("kftpu_hooked").set(1)
            reg.render()
            assert "kftpu_hooked" in contract_report()["series_produced"]
        finally:
            uninstall_contract_auditor()


# -- thread sanitizer (the dynamic half of the T8xx rules, ISSUE 20) -----------


class TestThreadSanitizer:
    @pytest.fixture()
    def san(self):
        san = sanitize.install_thread_sanitizer()
        try:
            yield san
        finally:
            sanitize.uninstall_thread_sanitizer()

    def test_stamp_site_and_owner_from_bound_target(self, san):
        class Comp:
            def _loop(self, ev):
                ev.wait(5.0)

        comp = Comp()
        ev = threading.Event()
        t = threading.Thread(target=comp._loop, args=(ev,))
        t.start()
        try:
            mine = [r for r in sanitize.thread_report()
                    if r["owner"] == "Comp"]
            assert mine, sanitize.thread_report()
            assert "test_sanitizers.py" in mine[0]["site"]
            assert mine[0]["daemon"] is False
        finally:
            ev.set()
            t.join(timeout=5.0)

    def test_owner_scope_labels_unbound_targets(self, san):
        ev = threading.Event()
        with sanitize.thread_owner("scrape-loop"):
            t = threading.Thread(target=ev.wait, args=(5.0,))
        t.start()
        try:
            rep = sanitize.thread_leak_report_by_owner()
            assert "scrape-loop" in rep, rep
            assert len(rep["scrape-loop"]) == 1
        finally:
            ev.set()
            t.join(timeout=5.0)

    def test_quiescence_raises_with_site_then_clears(self, san):
        class Comp:
            def _loop(self, ev):
                ev.wait(10.0)

        comp = Comp()
        ev = threading.Event()
        t = threading.Thread(target=comp._loop, args=(ev,))
        t.start()
        try:
            with pytest.raises(sanitize.ThreadLeakError) as exc:
                sanitize.assert_threads_quiescent(owner=comp, grace_s=0.2)
            assert "Comp" in str(exc.value)
            assert "test_sanitizers.py" in str(exc.value)
        finally:
            ev.set()
            t.join(timeout=5.0)
        # the same assert passes once the thread is joined
        sanitize.assert_threads_quiescent(owner=comp, grace_s=1.0)

    def test_owner_filter_ignores_other_components(self, san):
        class A:
            def _loop(self, ev):
                ev.wait(10.0)

        a, other = A(), A()
        ev = threading.Event()
        t = threading.Thread(target=a._loop, args=(ev,))
        t.start()
        try:
            # `other` owns nothing: its stop-side assert must not trip
            # on a's still-running thread
            sanitize.assert_threads_quiescent(owner=other, grace_s=0.2)
        finally:
            ev.set()
            t.join(timeout=5.0)

    def test_explicit_thread_list_audit(self, san):
        ev = threading.Event()
        t = threading.Thread(target=ev.wait, args=(10.0,))
        t.start()
        try:
            with pytest.raises(sanitize.ThreadLeakError):
                sanitize.assert_threads_quiescent(threads=(t,),
                                                  grace_s=0.2)
        finally:
            ev.set()
            t.join(timeout=5.0)
        sanitize.assert_threads_quiescent(threads=(t,), grace_s=1.0)

    def test_timer_subclass_still_constructs(self, san):
        # threading.Timer calls the module-global Thread.__init__ on a
        # non-subtype self; the patched class must tolerate it
        tm = threading.Timer(60.0, lambda: None)
        tm.cancel()

    def test_install_is_idempotent_and_uninstall_restores(self):
        orig = threading.Thread
        san1 = sanitize.install_thread_sanitizer()
        try:
            assert sanitize.install_thread_sanitizer() is san1
            assert threading.Thread is not orig
            assert threading.Thread.__name__ == "Thread"
        finally:
            sanitize.uninstall_thread_sanitizer()
        assert threading.Thread is orig
        assert sanitize.thread_sanitizer() is None
        assert sanitize.thread_report() == []
        sanitize.assert_threads_quiescent()          # no-op when off

    def test_maybe_install_threads_mode(self, monkeypatch):
        monkeypatch.setenv("KFTPU_SANITIZE", "threads")
        try:
            sanitize.maybe_install()
            assert sanitize.thread_sanitizer() is not None
        finally:
            sanitize.uninstall_thread_sanitizer()
