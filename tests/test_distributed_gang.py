"""Multi-process gang e2e: 2 workers rendezvous via jax.distributed and
compute a cross-process collective (SURVEY.md §7 risk-retirement #1; the
TPU-native analog of the reference's kind-based multi-pod e2e, §4.5)."""

import time

import pytest

from kubeflow_tpu.runtime.bootstrap import WorkerEnv, free_port
from kubeflow_tpu.runtime.procman import LocalProcessManager


def psum_entry(ctx):
    """Entrypoint run in each worker: global sum over the data axis."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = ctx.mesh
    sharding = NamedSharding(mesh, P(("dcn", "data", "fsdp")))
    local = np.full((2,), float(ctx.env.process_id + 1), np.float32)
    x = jax.make_array_from_process_local_data(sharding, local)
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
    got = float(np.asarray(total.addressable_shards[0].data))
    expect = 2.0 * sum(range(1, ctx.env.num_processes + 1))
    assert got == expect, f"psum mismatch: {got} != {expect}"
    # Write proof for the test to assert on.
    with open(f"{ctx.env.config['out_dir']}/ok-{ctx.env.process_id}", "w") as f:
        f.write(str(got))
    return 0


@pytest.mark.slow
def test_two_process_gang_collective(tmp_path):
    nproc = 2
    coord = f"127.0.0.1:{free_port()}"
    pm = LocalProcessManager(log_dir=str(tmp_path / "logs"))
    for pid in range(nproc):
        wenv = WorkerEnv(
            coordinator_address=coord, num_processes=nproc, process_id=pid,
            job="default/gang-e2e", replica_index=pid,
            entrypoint="tests.test_distributed_gang:psum_entry",
            config={"out_dir": str(tmp_path)},
            parallelism={"data": nproc},
            platform="cpu", virtual_devices=1,
            heartbeat_file=str(tmp_path / f"hb-{pid}"),
        )
        pm.launch(f"w{pid}", wenv, extra_env={"PYTHONPATH": "."})
    deadline = time.time() + 120
    while any(pm.poll(f"w{p}") is None for p in range(nproc)) and time.time() < deadline:
        time.sleep(0.3)
    codes = [pm.poll(f"w{p}") for p in range(nproc)]
    logs = ""
    for p in range(nproc):
        h = pm.get(f"w{p}")
        if h and h.log_path:
            logs += open(h.log_path).read()[-2000:]
    assert codes == [0, 0], f"exit codes {codes}\n{logs}"
    assert (tmp_path / "ok-0").read_text() == "6.0"
    assert (tmp_path / "ok-1").read_text() == "6.0"
