"""Unified metrics registry (obs/registry.py): primitives, escaping,
exposition grammar, the metric-name lint, and — the migration contract —
every pre-existing /metrics series name surviving the move onto the
registry (platform render_metrics + model-server metrics_text)."""

import math

import pytest

from kubeflow_tpu.obs.registry import (
    Counter, Gauge, Histogram, MetricsRegistry, escape_label_value,
    format_line, parse_exposition,
)


# -- primitives ----------------------------------------------------------------

def test_counter_gauge_histogram_render_and_parse():
    reg = MetricsRegistry()
    reg.counter("kftpu_reqs_total").inc(3, model="m")
    reg.gauge("kftpu_depth").set(7)
    h = reg.histogram("kftpu_delay_seconds", [0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(50.0)
    samples = dict(((n, tuple(sorted(lbl.items()))), v)
                   for n, lbl, v in parse_exposition(reg.render()))
    assert samples[("kftpu_reqs_total", (("model", "m"),))] == 3
    assert samples[("kftpu_depth", ())] == 7
    # cumulative buckets with the +Inf tail
    assert samples[("kftpu_delay_seconds_bucket", (("le", "0.1"),))] == 1
    assert samples[("kftpu_delay_seconds_bucket", (("le", "1.0"),))] == 2
    assert samples[("kftpu_delay_seconds_bucket", (("le", "+Inf"),))] == 3
    assert samples[("kftpu_delay_seconds_count", ())] == 3


def test_counter_refuses_negative_and_duplicate_type():
    reg = MetricsRegistry()
    c = reg.counter("kftpu_c_total")
    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(ValueError):
        reg.gauge("kftpu_c_total")   # same name, different type
    assert reg.counter("kftpu_c_total") is c   # same type: get-or-create


def test_register_refuses_duplicates():
    reg = MetricsRegistry()
    reg.register(Gauge("kftpu_x"))
    with pytest.raises(ValueError):
        reg.register(Counter("kftpu_x"))


def test_bad_names_rejected():
    with pytest.raises(ValueError):
        Gauge("kftpu bad name")
    with pytest.raises(ValueError):
        Histogram("kftpu_h", [1.0, 0.5])   # unsorted buckets
    g = Gauge("kftpu_ok")
    with pytest.raises(ValueError):
        g.set(1, **{"0bad": "v"})


# -- escaping (the satellite regression) ---------------------------------------

def test_label_escaping_quotes_backslashes_newlines():
    raw = 'he said "hi"\\and\nmoved on'
    line = format_line("kftpu_m", 1, {"name": raw})
    # The escaped line must parse under the strict grammar and round-trip
    # back to the original value.
    ((name, labels, value),) = parse_exposition(line)
    assert name == "kftpu_m" and value == 1
    assert labels["name"] == raw


def test_platform_line_uses_shared_escaper():
    # platform/metrics._line previously emitted invalid exposition text for
    # quotes/backslashes/newlines in object names.
    from kubeflow_tpu.platform.metrics import _line

    line = _line("kftpu_objects", 2, {"kind": 'Job"x\\y\nz'})
    ((_, labels, _),) = parse_exposition(line)
    assert labels["kind"] == 'Job"x\\y\nz'


def test_escape_is_order_correct():
    # Backslash must escape first, or \n in the input would double-escape.
    assert escape_label_value("\\n") == "\\\\n"
    assert escape_label_value("\n") == "\\n"


# -- grammar parser ------------------------------------------------------------

def test_parse_exposition_rejects_garbage():
    with pytest.raises(ValueError):
        parse_exposition('kftpu_m{unterminated="} 1')
    with pytest.raises(ValueError):
        parse_exposition("kftpu_m 1 2 3")
    with pytest.raises(ValueError):
        parse_exposition("# FROB kftpu_m gauge")
    assert parse_exposition("kftpu_m +Inf")[0][2] == math.inf


def test_parse_exposition_empty_payload():
    """An empty (or whitespace-only) scrape parses to zero samples —
    the contract extractor's consumers treat that as "no signal", never
    as an error."""
    assert parse_exposition("") == []
    assert parse_exposition("\n\n   \n") == []


def test_parse_exposition_histogram_suffix_family():
    """A labeled histogram renders the full ``_bucket``/``_sum``/
    ``_count`` family (the suffix grammar the X-rule contract matching
    strips back to the family name): cumulative buckets, a ``le`` label
    per bucket with the ``+Inf`` tail, and consistent count/sum lines."""
    reg = MetricsRegistry()
    h = reg.histogram("kftpu_ct_delay_seconds", [0.1, 1.0])
    h.set_cumulative([2, 3, 1], 7.5, 6, model="m", qos="batch")
    samples = parse_exposition(reg.render())
    names = {n for n, _, _ in samples}
    assert names == {"kftpu_ct_delay_seconds_bucket",
                     "kftpu_ct_delay_seconds_sum",
                     "kftpu_ct_delay_seconds_count"}
    buckets = {lbl["le"]: v for n, lbl, v in samples
               if n == "kftpu_ct_delay_seconds_bucket"}
    assert buckets == {"0.1": 2, "1.0": 5, "+Inf": 6}   # cumulative
    for n, lbl, v in samples:
        assert lbl["model"] == "m" and lbl["qos"] == "batch"
        if n.endswith("_count"):
            assert v == 6
        if n.endswith("_sum"):
            assert v == 7.5


def test_parse_exposition_escaped_label_values_round_trip():
    """Escaped quotes/backslashes/newlines inside label values must
    parse back to the original value — including on histogram suffix
    series, where a bad unescape would split the ``le`` label."""
    raw = 'tenant "a"\\eu\nwest'
    reg = MetricsRegistry()
    reg.counter("kftpu_ct_reqs_total").inc(1, tenant=raw)
    h = reg.histogram("kftpu_ct_lat_seconds", [0.5])
    h.observe(0.2, tenant=raw)
    samples = parse_exposition(reg.render())
    assert samples, "payload must parse"
    for name, labels, _ in samples:
        assert labels["tenant"] == raw
        if name == "kftpu_ct_lat_seconds_bucket":
            assert labels["le"] in ("0.5", "+Inf")


# -- lint ----------------------------------------------------------------------

def test_lint_flags_unprefixed_names():
    reg = MetricsRegistry()
    reg.gauge("kftpu_good")
    reg.gauge("bad_name")
    problems = reg.lint()
    assert any("bad_name" in p for p in problems)
    assert not any("kftpu_good" in p for p in problems)


# -- series-name migration contract --------------------------------------------

#: Every series family the seed's hand-rolled renderers exposed. The
#: registry migration must keep them all (supersets allowed).
SEED_PLATFORM_SERIES = {
    "kftpu_objects", "kftpu_job_step", "kftpu_job_tokens_per_sec_per_chip",
    "kftpu_job_step_time_ms", "kftpu_job_mfu", "kftpu_job_loss",
    "kftpu_workers", "kftpu_chips_total", "kftpu_chips_allocated",
    "kftpu_events_total",
}
SEED_SERVING_SERIES = {
    "kftpu_serving_in_flight", "kftpu_serving_requests_total",
    "kftpu_serving_tokens_total", "kftpu_serving_queue_depth",
    "kftpu_serving_requests_shed_total",
    "kftpu_serving_requests_cancelled_total",
    "kftpu_serving_requests_expired_total",
    "kftpu_serving_requests_per_sec", "kftpu_serving_tokens_per_sec",
    "kftpu_serving_queue_delay_seconds_bucket",
    "kftpu_serving_queue_delay_seconds_sum",
    "kftpu_serving_queue_delay_seconds_count",
    # Decode hot-loop health (ISSUE 4): per-round host gap + pipeline
    # depth, exposed per engine through the same registry path.
    "kftpu_engine_host_gap_seconds_bucket",
    "kftpu_engine_host_gap_seconds_sum",
    "kftpu_engine_host_gap_seconds_count",
    "kftpu_engine_dispatch_depth",
}


def test_platform_series_names_survive_migration():
    from kubeflow_tpu.core.events import EventRecorder
    from kubeflow_tpu.core.jobs import JAXJob, JAXJobSpec, ReplicaSpec, \
        TPUResourceSpec, Worker, WorkerSpec, WorkloadSpec
    from kubeflow_tpu.core.object import ObjectMeta
    from kubeflow_tpu.core.store import ObjectStore
    from kubeflow_tpu.platform.metrics import render_metrics
    from kubeflow_tpu.runtime.allocator import GangAllocator
    from kubeflow_tpu.runtime.topology import Cluster, SliceTopology

    store = ObjectStore()
    job = JAXJob(
        metadata=ObjectMeta(name="j", namespace="default"),
        spec=JAXJobSpec(replica_specs={"worker": ReplicaSpec(
            replicas=1,
            template=WorkloadSpec(entrypoint="noop", config={}),
            resources=TPUResourceSpec(tpu_chips=1))}))
    job.status.metrics.step = 5
    job.status.metrics.tokens_per_sec_per_chip = 10.0
    job.status.metrics.step_time_ms = 3.0
    job.status.metrics.mfu = 0.5
    job.status.metrics.loss = 2.0
    store.apply(job)
    store.apply(Worker(
        metadata=ObjectMeta(name="w", namespace="default"),
        spec=WorkerSpec(job="default/j", replica_index=0,
                        template=WorkloadSpec(entrypoint="noop", config={}))))
    recorder = EventRecorder()
    recorder.normal(job, "Created", "x")
    allocator = GangAllocator(Cluster(slices=[
        SliceTopology(name="s0", generation="v5e", dims=(2, 2))]))

    text = render_metrics(store, recorder, allocator)
    names = {n for n, _, _ in parse_exposition(text)}
    missing = SEED_PLATFORM_SERIES - names
    assert not missing, f"series lost in migration: {missing}"


def test_serving_series_names_survive_migration(tiny_engine_server):
    server = tiny_engine_server
    text = server.metrics_text()
    names = {n for n, _, _ in parse_exposition(text)}
    missing = SEED_SERVING_SERIES - names
    assert not missing, f"series lost in migration: {missing}"
    # and the whole scrape parses + is kftpu_-prefixed throughout
    for n in names:
        assert n.startswith("kftpu_"), n


@pytest.fixture(scope="module")
def tiny_engine_server():
    import jax

    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams
    from kubeflow_tpu.serve.server import ModelServer

    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    engine = LLMEngine(
        cfg, BatchingSpec(max_batch_size=2, max_seq_len=64,
                          prefill_buckets=[32]),
        params=params)
    # One completed request so rate/percentile gauges have data.
    req = engine.submit([1, 2, 3], SamplingParams(max_new_tokens=2))
    while not req.done.is_set():
        engine.step()
    server = ModelServer("tiny", engine, port=0)
    yield server
    server.httpd.server_close()


def test_capacity_accessor():
    from kubeflow_tpu.runtime.allocator import GangAllocator, GangRequest
    from kubeflow_tpu.runtime.topology import Cluster, SliceTopology

    alloc = GangAllocator(Cluster(slices=[
        SliceTopology(name="s0", generation="v5e", dims=(2, 2))]))
    assert alloc.capacity() == (4, 4)
    alloc.submit(GangRequest(name="g", num_workers=1, chips_per_worker=3))
    assert alloc.capacity() == (4, 1)
    alloc.release("g")
    assert alloc.capacity() == (4, 4)


def test_render_metrics_does_not_touch_private_cluster(monkeypatch):
    """platform metrics must use the public capacity() accessor, not
    allocator._cluster."""
    from kubeflow_tpu.core.events import EventRecorder
    from kubeflow_tpu.core.store import ObjectStore
    from kubeflow_tpu.platform.metrics import render_metrics

    class PublicOnlyAllocator:
        def capacity(self):
            return (8, 5)

    text = render_metrics(ObjectStore(), EventRecorder(),
                          PublicOnlyAllocator())
    samples = {n: v for n, _, v in parse_exposition(text)}
    assert samples["kftpu_chips_total"] == 8
    assert samples["kftpu_chips_allocated"] == 3
