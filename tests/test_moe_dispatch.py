"""Capacity-factor MoE dispatch vs the dense oracle (SURVEY.md §2.6 EP row:
the dispatch path is the default — only selected experts compute — while the
drop-free dense formulation remains the correctness oracle)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip(
    "jax.experimental.pallas",
    reason="Pallas unavailable: the MoE dispatch path's kernels need it")
from kubeflow_tpu.compat import HAS_SHARD_MAP, SHARD_MAP_NATIVE  # noqa: E402

if not HAS_SHARD_MAP:
    pytest.skip("this jax has no shard_map (native or experimental)",
                allow_module_level=True)

from kubeflow_tpu.models import layers as L
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import (
    decoder_loss, init_decoder_params)


def mk(impl, cf=8.0, **over):
    # capacity_factor=E (here up to 8) => C = k*T: nothing can drop, so
    # dispatch must match dense exactly (up to fp reduction order).
    return preset("tiny-moe", dtype="float32", moe_impl=impl,
                  capacity_factor=cf, **over)


@pytest.fixture(scope="module")
def x():
    return jax.random.normal(jax.random.PRNGKey(2), (2, 16, 64), jnp.float32)


@pytest.fixture(scope="module")
def moe_params(x):
    cfg = mk("dense")
    p, _ = L.init_moe(jax.random.PRNGKey(0), cfg)
    return p


def test_dispatch_matches_dense_with_ample_capacity(x, moe_params):
    out_d, aux_d = L.moe_block(moe_params, x, mk("dense"))
    out_s, aux_s = L.moe_block(moe_params, x, mk("dispatch"))
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_d),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(aux_s), float(aux_d), rtol=1e-6)


def test_dispatch_gradients_match_dense(x, moe_params):
    def loss(p, cfg):
        out, aux = L.moe_block(p, x, cfg)
        return jnp.sum(out ** 2) + 0.01 * aux

    g_d = jax.grad(loss)(moe_params, mk("dense"))
    g_s = jax.grad(loss)(moe_params, mk("dispatch"))
    for path in ("router", "gate", "up", "down"):
        np.testing.assert_allclose(np.asarray(g_s[path]),
                                   np.asarray(g_d[path]),
                                   rtol=5e-5, atol=1e-5, err_msg=path)


def test_dispatch_flop_shape_is_k_over_e():
    """The whole point: per-expert buffers total ~cf*k*T rows, NOT E*T."""
    cfg = mk("dispatch", cf=1.25)
    t = 2 * 16
    c = L.moe_capacity(cfg, t)
    assert c < t  # dense would be C == T per expert
    assert c >= cfg.experts_per_token * t // cfg.num_experts


def test_drop_policy_over_capacity():
    """All tokens routed to ONE expert with capacity_factor=1: only the
    first C (choice-major priority) survive; dropped (token, choice) pairs
    contribute nothing (no renormalization)."""
    cfg = mk("dispatch", cf=1.0)
    p, _ = L.init_moe(jax.random.PRNGKey(0), cfg)
    # Force the router: huge weight toward expert 0 for every token.
    p = dict(p)
    router = np.zeros((64, cfg.num_experts), np.float32)
    router[:, 0] = 100.0
    router[:, 1] = 50.0
    p["router"] = jnp.asarray(router)
    x = jnp.broadcast_to(
        jax.random.normal(jax.random.PRNGKey(3), (1, 1, 64)),
        (1, 32, 64)).astype(jnp.float32)  # identical tokens
    out, _ = L.moe_block(p, x, cfg)
    t = 32
    c = L.moe_capacity(cfg, t)  # cf=1: C = k*T/E rounded to 8s
    assert c < t, "test needs real drops"
    out = np.asarray(out)[0]
    # Identical tokens, so surviving rows (both choices kept) share one
    # value; tokens with dropped choices differ. First tokens keep their
    # first choice (choice-major priority): their outputs must be non-zero.
    assert np.abs(out[0]).sum() > 0
    # A fully-dropped token's MoE output is exactly zero.
    full = np.abs(out).sum(-1)
    assert (full[:c] > 0).all()          # first C kept their primary choice
    assert full[-1] == 0                 # tail token fully dropped


def test_decoder_loss_trains_with_dispatch():
    cfg = mk("dispatch", cf=1.25)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0,
                              cfg.vocab_size)
    loss, _ = decoder_loss(params, toks, cfg)
    assert np.isfinite(float(loss))
    grads = jax.grad(lambda p: decoder_loss(p, toks, cfg)[0])(params)
    gn = jax.tree.reduce(
        lambda a, b: a + float(jnp.sum(jnp.abs(b))), grads, 0.0)
    assert np.isfinite(gn) and gn > 0


@pytest.mark.skipif(
    not SHARD_MAP_NATIVE,
    reason="experimental shard_map fallback shifts the dispatch psum's "
           "reduction order beyond the exact-equivalence tolerance")
def test_dispatch_sharded_matches_unsharded():
    """dp×ep mesh: the expert dim of the dispatch buffers shards over the
    expert axis; sharded == unsharded."""
    from kubeflow_tpu.runtime.mesh import build_mesh
    from kubeflow_tpu.train.optim import OptimizerConfig
    from kubeflow_tpu.train.step import setup_train

    cfg = mk("dispatch", cf=8.0, n_layers=2)
    toks = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (4, cfg.max_seq_len + 1)).astype(np.int32)

    losses = {}
    for axes in ({"data": 1}, {"data": 2, "expert": 4}):
        mesh = build_mesh(axes, jax.devices()[:int(np.prod(
            list(axes.values())))])
        task = setup_train(cfg, OptimizerConfig(total_steps=2), mesh)
        batch = jax.device_put(toks, task.batch_sharding)
        _, metrics = task.step_fn(task.state, batch)
        losses[tuple(axes)] = float(metrics["loss"])
    vals = list(losses.values())
    np.testing.assert_allclose(vals[0], vals[1], rtol=2e-5)


def test_serving_engine_moe_phase_resolution():
    """A request's tokens must not depend on co-batched traffic. Decode
    co-batches slots, so it resolves to the drop-free dense formulation;
    prefill runs per-request, so the dispatch path is batch-independent by
    construction and stays (the measured winner — tests/test_serve_moe.py
    pins both paths token-exact against dense)."""
    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.serve.engine import LLMEngine

    cfg = preset("tiny-moe", moe_impl="dispatch")
    eng = LLMEngine(cfg, BatchingSpec(max_batch_size=2, max_seq_len=32,
                                      prefill_buckets=[16]))
    assert eng._cfg_decode.moe_impl == "dense"
    assert eng._cfg_prefill.moe_impl == "dispatch"
    assert eng.cfg.moe_impl == "dispatch"    # model cfg left untouched
