"""Model numerics tests: forward shapes, decode==full equivalence,
sharded-vs-unsharded equivalence (the test class the reference never needed —
SURVEY.md §4 rebuild translation (d))."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import (
    preset, init_decoder_params, decoder_forward, decoder_loss,
)
from kubeflow_tpu.models.decoder import decoder_param_specs, init_kv_caches
from kubeflow_tpu.parallel.sharding import (
    DEFAULT_RULES, logical_to_mesh_axes, shard_params,
)
from kubeflow_tpu.runtime.mesh import build_mesh


@pytest.mark.parametrize("name", ["tiny", "tiny-gemma", "tiny-moe"])
def test_forward_shapes_and_loss(name):
    cfg = preset(name)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, cfg.vocab_size)
    logits, caches, aux = decoder_forward(params, toks, cfg)
    assert logits.shape == (2, 17, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert caches is None
    loss, metrics = decoder_loss(params, toks, cfg)
    assert np.isfinite(float(loss))
    if cfg.is_moe:
        assert float(aux) > 0


def test_scan_vs_unrolled_equivalence():
    # float32 so fusion-order rounding doesn't mask real mismatches (bf16
    # differs ~1e-2 between fused-scan and eager-unrolled execution).
    cfg = preset("tiny", dtype="float32")
    cfg_unrolled = preset("tiny", scan_layers=False, dtype="float32")
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    # Unstack the scanned params into the unrolled layout.
    unrolled_layers = [
        jax.tree.map(lambda a: a[i], params["layers"]) for i in range(cfg.n_layers)
    ]
    params_u = {**params, "layers": unrolled_layers}
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, cfg.vocab_size)
    l1, _, _ = decoder_forward(params, toks, cfg)
    l2, _, _ = decoder_forward(params_u, toks, cfg_unrolled)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-5)


def test_decode_cache_matches_full_forward():
    cfg = preset("tiny")
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(2), (1, 9), 0, cfg.vocab_size)
    full, _, _ = decoder_forward(params, toks, cfg)
    cache = init_kv_caches(cfg, 1, 16)
    out, cache, _ = decoder_forward(params, toks[:, :6], cfg, kv_caches=cache)
    chunks = [out]
    for i in range(6, 9):
        pos = jnp.full((1, 1), i, jnp.int32)
        lg, cache, _ = decoder_forward(params, toks[:, i:i + 1], cfg,
                                       positions=pos, kv_caches=cache)
        chunks.append(lg)
    inc = jnp.concatenate(chunks, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(inc), atol=2e-2)
    assert int(cache["len"]) == 9


def test_remat_policies_same_loss():
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 256)
    losses = []
    for policy in ["none", "nothing_saveable", "full", "dots_no_batch",
                   "dots_flash"]:
        cfg = preset("tiny", remat_policy=policy)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        loss, _ = jax.jit(lambda p, t: decoder_loss(p, t, cfg))(params, toks)
        losses.append(float(loss))
    assert max(losses) - min(losses) < 1e-5


@pytest.mark.slow  # tier-1 budget: two pallas grad traces A/B'd, ~8s
def test_dots_flash_grads_match_unrematted():
    """The dots_flash policy (saved flash (o,lse) residuals) must not
    change gradients — only what the backward recomputes. Pallas impl so
    the saved names actually appear in the trace."""
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 256)
    grads = []
    for policy in ["none", "dots_flash"]:
        cfg = preset("tiny", remat_policy=policy, dtype="float32")
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        g = jax.grad(lambda p: decoder_loss(p, toks, cfg,
                                            attn_impl="pallas")[0])(params)
        grads.append(g)
    for a, b in zip(jax.tree.leaves(grads[0]), jax.tree.leaves(grads[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_param_count_formula_matches_actual():
    for name in ["tiny", "tiny-gemma", "tiny-moe"]:
        cfg = preset(name)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        actual = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
        assert actual == cfg.num_params(), (name, actual, cfg.num_params())


def test_spec_tree_matches_param_tree():
    for name in ["tiny", "tiny-moe"]:
        cfg = preset(name)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        specs = decoder_param_specs(cfg)
        from kubeflow_tpu.parallel.sharding import _is_spec_leaf

        pleaves, ptree = jax.tree.flatten(params)
        sleaves, stree = jax.tree.flatten(specs, is_leaf=_is_spec_leaf)
        assert len(pleaves) == len(sleaves)
        for p, s in zip(pleaves, sleaves):
            assert p.ndim == len(s), (p.shape, s)


# -- sharded vs unsharded equivalence (the core SPMD correctness test) --------

@pytest.mark.slow
@pytest.mark.parametrize("axes", [
    {"data": 8}, {"fsdp": 8}, {"fsdp": 4, "model": 2}, {"fsdp": 2, "model": 4},
    {"data": 2, "fsdp": 2, "model": 2},
])
def test_sharded_matches_unsharded(axes):
    cfg = preset("tiny")
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0, cfg.vocab_size)

    ref_loss, _ = jax.jit(lambda p, t: decoder_loss(p, t, cfg))(params, toks)

    mesh = build_mesh(axes)
    specs = decoder_param_specs(cfg)
    shardings = shard_params(params, specs, mesh)
    sharded_params = jax.tree.map(
        lambda a, sh: jax.device_put(a, sh), params,
        shardings)
    batch_sh = jax.NamedSharding(mesh, logical_to_mesh_axes(("batch", None)))
    sharded_toks = jax.device_put(toks, batch_sh)
    loss, _ = jax.jit(
        lambda p, t: decoder_loss(p, t, cfg, mesh=mesh))(sharded_params, sharded_toks)
    np.testing.assert_allclose(float(ref_loss), float(loss), rtol=2e-4)


@pytest.mark.slow
def test_moe_sharded_matches_unsharded_expert_parallel():
    cfg = preset("tiny-moe")
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 9), 0, cfg.vocab_size)
    ref_loss, _ = jax.jit(lambda p, t: decoder_loss(p, t, cfg))(params, toks)
    mesh = build_mesh({"fsdp": 2, "expert": 4})
    specs = decoder_param_specs(cfg)
    shardings = shard_params(params, specs, mesh)
    sharded_params = jax.tree.map(lambda a, sh: jax.device_put(a, sh), params, shardings)
    batch_sh = jax.NamedSharding(mesh, logical_to_mesh_axes(("batch", None)))
    loss, _ = jax.jit(lambda p, t: decoder_loss(p, t, cfg, mesh=mesh))(
        sharded_params, jax.device_put(toks, batch_sh))
    # bf16 all-to-all/psum reduction order differs under EP; ~1e-3 abs noise
    np.testing.assert_allclose(float(ref_loss), float(loss), rtol=5e-4)


@pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
def test_chunked_ce_matches_full():
    """loss_chunk_size must be numerics-identical (loss, accuracy, grads) to
    the full-logits path — it's a memory optimization, not an approximation."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import decoder_loss, init_decoder_params

    for name in ("tiny", "tiny-gemma"):       # gemma: softcap + tied head
        cfg = preset(name, dtype="float32")
        chunked = dataclasses.replace(cfg, loss_chunk_size=32)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 129), 0,
                                  cfg.vocab_size)
        l0, m0 = decoder_loss(params, toks, cfg)
        l1, m1 = decoder_loss(params, toks, chunked)
        assert abs(float(l0) - float(l1)) < 1e-5
        assert float(m0["accuracy"]) == float(m1["accuracy"])
        g0 = jax.grad(lambda p: decoder_loss(p, toks, cfg)[0])(params)
        g1 = jax.grad(lambda p: decoder_loss(p, toks, chunked)[0])(params)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            assert float(jnp.abs(a - b).max()) < 1e-5


def test_chunked_ce_odd_tail_falls_back():
    import dataclasses

    import jax

    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import decoder_loss, init_decoder_params

    cfg = preset("tiny", dtype="float32")
    chunked = dataclasses.replace(cfg, loss_chunk_size=50)  # 128 % 50 != 0
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 129), 0, 256)
    l0, _ = decoder_loss(params, toks, cfg)
    l1, _ = decoder_loss(params, toks, chunked)
    assert abs(float(l0) - float(l1)) < 1e-5
