"""SDK client surface (TrainingClient/KatibClient/kfp.Client analogs) +
Tensorboard controller — the remaining L6 parity items."""

import time

import pytest

from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.core.workspace_specs import (
    Tensorboard, TensorboardSpec,
)
from kubeflow_tpu.sdk import Client


@pytest.fixture()
def client(tmp_path):
    c = Client.local(base_dir=str(tmp_path), num_chips=4)
    yield c
    c.shutdown()


class TestTrainingClient:
    def test_create_wait_logs_delete(self, client):
        client.create_job("probe", entrypoint="objective_probe",
                          config={"x": 0.1, "y": 0.2, "steps": 2}, workers=2)
        job = client.wait_for_job_conditions("probe", timeout=60)
        assert job.status.has_condition("Succeeded")
        assert client.get_job_logs("probe") != ""
        client.delete_job("probe")
        assert client.get_job("probe") is None

    def test_failed_job_raises(self, client):
        client.create_job("boom", entrypoint="fail",
                          config={"exit_code": 3}, backoff_limit=0)
        with pytest.raises(RuntimeError, match="failed"):
            client.wait_for_job_conditions("boom", timeout=60)

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_train_high_level(self, client):
        job = client.train("mini", model="tiny",
                           model_overrides={"max_seq_len": 64},
                           steps=3, checkpoint=False,
                           optimizer={"warmup_steps": 0},
                           data={"global_batch": 4, "seq_len": 64},
                           wait=True, timeout=180)
        assert job.status.has_condition("Succeeded")
        assert job.status.metrics.loss is not None


class TestPipelineClient:
    def test_upload_and_run(self, client):
        from kubeflow_tpu.pipelines import dsl

        @dsl.component
        def double_it(x: int) -> int:
            return 2 * x

        @dsl.pipeline(name="sdk-pipe")
        def p(x: int = 21):
            double_it(x=x)

        client.upload_pipeline(p)
        run = client.create_run("sdk-pipe", run_name="r1", wait=True,
                                timeout=60)
        assert run.status.tasks["double_it"].outputs["output"] == 42


class TestTensorboard:
    def test_serves_logdir(self, client, tmp_path):
        logdir = tmp_path / "logs"
        logdir.mkdir()
        (logdir / "metrics.jsonl").write_text('{"step":1,"loss":1.0}\n')
        client.apply(Tensorboard(
            metadata=ObjectMeta(name="tb"),
            spec=TensorboardSpec(log_dir=str(logdir))))
        deadline = time.time() + 30
        tb = None
        while time.time() < deadline:
            tb = client.cp.store.try_get(Tensorboard, "tb")
            if tb is not None and tb.status.phase in ("Running", "Failed"):
                break
            time.sleep(0.2)
        assert tb is not None and tb.status.phase == "Running", \
            (tb.status.phase, tb.status.conditions)
        assert tb.status.url.startswith("http://127.0.0.1:")
        assert tb.status.pid is not None

    def test_missing_logdir_reported(self, client):
        client.apply(Tensorboard(
            metadata=ObjectMeta(name="tb2"),
            spec=TensorboardSpec(log_dir="/nonexistent/dir")))
        deadline = time.time() + 10
        while time.time() < deadline:
            tb = client.cp.store.try_get(Tensorboard, "tb2")
            if tb is not None and tb.status.get_condition("Running"):
                break
            time.sleep(0.2)
        cond = tb.status.get_condition("Running")
        assert cond is not None and cond.reason == "LogDirMissing"
