"""Worker entrypoint for the observation gRPC e2e: reports observations
DIRECTLY to the control plane's observation service (the db-manager path)
from a separate process, via the KFTPU_OBS_TARGET env the runtime
injects."""

import os


def report_obs(ctx) -> int:
    from kubeflow_tpu.tune.observation_service import RemoteObservationLog

    target = os.environ["KFTPU_OBS_TARGET"]
    log = RemoteObservationLog(target)
    try:
        log.report("default/grpc-exp", "grpc-trial", "loss",
                   [(0, 3.0), (1, 2.0), (2, 1.0)],
                   parameters={"lr": 0.5})
        log.finish_trial("grpc-trial", succeeded=True)
    finally:
        log.close()
    return 0
