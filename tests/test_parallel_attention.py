"""Numerics-equivalence tests for the data-plane parallelism the reference
never implements (SURVEY.md §2.6): Pallas flash attention vs the XLA oracle,
ring attention + Ulysses on a multi-device seq mesh, and the GPipe pipeline
vs sequential stages — sharded-vs-unsharded equivalence, the §4 'rebuild
translation' test family."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

pytest.importorskip(
    "jax.experimental.pallas",
    reason="Pallas unavailable: flash/ring kernels need it")
from kubeflow_tpu.compat import HAS_SHARD_MAP  # noqa: E402

if not HAS_SHARD_MAP:
    pytest.skip("this jax has no shard_map (native or experimental)",
                allow_module_level=True)

from kubeflow_tpu.ops.attention import multi_head_attention
from kubeflow_tpu.ops.flash_attention import flash_attention


def rel_close(a, b, rtol=2e-4, atol=1e-5):
    scale = float(jnp.abs(a).max()) + 1e-6
    err = float(jnp.abs(a - b).max())
    assert err <= atol + rtol * scale, f"err={err} scale={scale}"


def qkv(B=2, S=128, H=4, K=2, D=16, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return (jax.random.normal(ks[0], (B, S, H, D), dtype),
            jax.random.normal(ks[1], (B, S, K, D), dtype),
            jax.random.normal(ks[2], (B, S, K, D), dtype))


class TestFlashAttention:
    def test_matches_oracle_causal_gqa(self):
        q, k, v = qkv()
        ref = multi_head_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_kv=64)
        rel_close(ref, out)

    def test_non_causal(self):
        q, k, v = qkv(S=64)
        ref = multi_head_attention(q, k, v, causal=False)
        out = flash_attention(q, k, v, causal=False, block_q=32, block_kv=32)
        rel_close(ref, out)

    def test_softcap(self):
        q, k, v = qkv(S=64)
        ref = multi_head_attention(q, k, v, causal=True, logits_softcap=20.0)
        out = flash_attention(q, k, v, causal=True, logits_softcap=20.0,
                              block_q=32, block_kv=32)
        rel_close(ref, out)

    def test_q_offset_window(self):
        q, k, v = qkv(S=128)
        qs = q[:, :32]
        ref = multi_head_attention(qs, k, v, causal=True, q_offset=96)
        out = flash_attention(qs, k, v, causal=True, q_offset=96,
                              block_q=32, block_kv=32)
        rel_close(ref, out)

    def test_gradients_match_oracle(self):
        q, k, v = qkv(S=64)

        def loss(attn):
            def f(q, k, v):
                return jnp.sum(attn(q, k, v) ** 2)
            return f

        ref_fn = loss(lambda q, k, v: multi_head_attention(q, k, v, causal=True))
        fl_fn = loss(lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=32, block_kv=32))
        g_ref = jax.grad(ref_fn, argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(fl_fn, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_fl):
            rel_close(a, b, rtol=5e-4)

    @pytest.mark.parametrize("softcap,q_off", [(None, 0), (20.0, 0),
                                               (None, 96)])
    def test_pallas_bwd_matches_xla_bwd(self, softcap, q_off):
        """The blockwise Pallas backward kernels (dQ + dK/dV) must agree
        with the einsum/scan sweep across GQA, softcap, and offset-window
        configs — both against the saved-LSE recompute semantics."""
        q, k, v = qkv(S=128)
        if q_off:
            q = q[:, :32]

        def loss(bwd):
            def f(q, k, v):
                out = flash_attention(q, k, v, causal=True, q_offset=q_off,
                                      logits_softcap=softcap,
                                      block_q=32, block_kv=32, bwd_impl=bwd)
                return jnp.sum(out ** 2)
            return f

        g_xla = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
        g_pal = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_xla, g_pal):
            rel_close(a, b, rtol=5e-4)

    def test_attention_dispatch(self):
        q, k, v = qkv(S=64)
        out = multi_head_attention(q, k, v, causal=True, impl="pallas")
        ref = multi_head_attention(q, k, v, causal=True, impl="xla")
        rel_close(ref, out)

    def test_traced_offset_rejected(self):
        q, k, v = qkv(S=32)
        with pytest.raises((ValueError, jax.errors.TracerArrayConversionError)):
            jax.jit(lambda o: flash_attention(q, k, v, q_offset=o))(
                jnp.asarray(4))

    def test_bad_block_divisibility(self):
        q, k, v = qkv(S=100)
        with pytest.raises(ValueError, match="multiple of block size"):
            flash_attention(q, k, v, block_q=64, block_kv=64)

    def test_unaligned_seq_rejected_loudly(self):
        # 128 <= S < 1024 but not 128-aligned: S used to be accepted as a
        # single full-size block and fail deep inside Mosaic lowering;
        # _fit_block must reject it with the explicit error instead.
        from kubeflow_tpu.ops.flash_attention import _fit_block
        for s in (136, 160, 1000):
            with pytest.raises(ValueError, match="pass block_q/block_kv"):
                _fit_block(1024, s)
        # Aligned sizes keep working, including the sub-128 escape hatch.
        assert _fit_block(1024, 2048) == 1024
        assert _fit_block(1024, 384) == 384   # 128-aligned, divides itself
        assert _fit_block(1024, 64) == 64


@pytest.fixture(scope="module")
def seq_mesh():
    return Mesh(np.array(jax.devices()[:4]), ("seq",))


class TestRingAttention:
    def test_matches_full_attention(self, seq_mesh):
        from kubeflow_tpu.parallel.ring_attention import ring_attention_sharded

        q, k, v = qkv(S=128)
        ref = multi_head_attention(q, k, v, causal=True)
        out = ring_attention_sharded(q, k, v, seq_mesh)
        rel_close(ref, out)

    def test_non_causal_and_softcap(self, seq_mesh):
        from kubeflow_tpu.parallel.ring_attention import ring_attention_sharded

        q, k, v = qkv(S=64)
        ref = multi_head_attention(q, k, v, causal=False, logits_softcap=15.0)
        out = ring_attention_sharded(q, k, v, seq_mesh, causal=False,
                                     logits_softcap=15.0)
        rel_close(ref, out)

    def test_gradients(self, seq_mesh):
        from kubeflow_tpu.parallel.ring_attention import ring_attention_sharded

        q, k, v = qkv(S=64)

        def ref_loss(q, k, v):
            return jnp.sum(multi_head_attention(q, k, v, causal=True) ** 2)

        def ring_loss(q, k, v):
            return jnp.sum(ring_attention_sharded(q, k, v, seq_mesh) ** 2)

        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_ring):
            rel_close(a, b, rtol=5e-4)


class TestRingFlash:
    """The flash-kernel ring (VERDICT r3 #2): ring(impl="pallas") must equal
    the single-device oracle — forward AND gradients — at ≥2 shard counts,
    with GQA, softcap, and the non-causal path."""

    def _mesh(self, n):
        return Mesh(np.array(jax.devices()[:n]), ("seq",))

    @pytest.mark.parametrize("nshard", [2, 4])
    def test_forward_matches_oracle(self, nshard):
        from kubeflow_tpu.parallel.ring_attention import ring_attention_sharded

        q, k, v = qkv(S=128, H=4, K=2)           # GQA n_rep=2
        ref = multi_head_attention(q, k, v, causal=True)
        out = ring_attention_sharded(q, k, v, self._mesh(nshard),
                                     impl="pallas", interpret=True)
        rel_close(ref, out)

    @pytest.mark.parametrize("nshard", [2, 4])
    def test_gradients_match_oracle(self, nshard):
        from kubeflow_tpu.parallel.ring_attention import ring_attention_sharded

        q, k, v = qkv(S=128, H=4, K=2)
        mesh = self._mesh(nshard)

        def ref_loss(q, k, v):
            return jnp.sum(multi_head_attention(q, k, v, causal=True) ** 2)

        def ring_loss(q, k, v):
            return jnp.sum(ring_attention_sharded(
                q, k, v, mesh, impl="pallas", interpret=True) ** 2)

        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_ring):
            rel_close(a, b, rtol=5e-4)

    def test_non_causal_and_softcap(self, seq_mesh):
        from kubeflow_tpu.parallel.ring_attention import ring_attention_sharded

        q, k, v = qkv(S=64)
        ref = multi_head_attention(q, k, v, causal=False, logits_softcap=15.0)
        out = ring_attention_sharded(q, k, v, seq_mesh, causal=False,
                                     logits_softcap=15.0,
                                     impl="pallas", interpret=True)
        rel_close(ref, out)

    def test_matches_xla_ring(self, seq_mesh):
        # Kernel ring vs oracle ring on the same mesh — the seam the rest
        # of the suite leans on when impl="auto" resolves differently by
        # backend.
        from kubeflow_tpu.parallel.ring_attention import ring_attention_sharded

        q, k, v = qkv(S=128)
        a = ring_attention_sharded(q, k, v, seq_mesh, impl="xla")
        b = ring_attention_sharded(q, k, v, seq_mesh,
                                   impl="pallas", interpret=True)
        rel_close(a, b)


class TestUlysses:
    def test_matches_full_attention(self, seq_mesh):
        from kubeflow_tpu.parallel.ring_attention import \
            ulysses_attention_sharded

        # heads divisible by seq axis: H=8, K=4 over 4 devices
        q, k, v = qkv(S=128, H=8, K=4)
        ref = multi_head_attention(q, k, v, causal=True)
        out = ulysses_attention_sharded(q, k, v, seq_mesh)
        rel_close(ref, out)

    def test_indivisible_heads_rejected(self, seq_mesh):
        from kubeflow_tpu.parallel.ring_attention import \
            ulysses_attention_sharded

        q, k, v = qkv(S=64, H=4, K=2)
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention_sharded(q, k, v, seq_mesh)


class TestModelSeqParallel:
    """decoder_loss under a data×seq mesh with ring/ulysses attention must
    match the unsharded XLA forward — the SURVEY.md §4 sharded-vs-unsharded
    equivalence family at the model level."""

    @pytest.mark.parametrize("impl", ["ring", "ring_flash", "ulysses"])
    def test_decoder_loss_matches_xla(self, impl):
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params)
        from kubeflow_tpu.runtime.mesh import build_mesh

        cfg = preset("tiny", n_layers=2, hidden=64, n_heads=4, n_kv_heads=4,
                     head_dim=16, mlp_dim=128, vocab_size=256, max_seq_len=64,
                     dtype="float32")
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        # 65 tokens → 64 positions after the next-token shift (divisible by
        # the seq axis).
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 65), 0,
                                    cfg.vocab_size)
        ref, _ = decoder_loss(params, tokens, cfg, attn_impl="xla")
        mesh = build_mesh({"data": 2, "seq": 4})
        with jax.sharding.use_mesh(mesh) if hasattr(jax.sharding, "use_mesh") \
                else mesh:
            out, _ = jax.jit(
                lambda p, t: decoder_loss(p, t, cfg, attn_impl=impl,
                                          mesh=mesh))(params, tokens)
        assert abs(float(ref) - float(out)) < 5e-4 * max(1.0, abs(float(ref)))


class TestModelPipelineParallel:
    def test_decoder_loss_matches_unstaged(self):
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params)
        from kubeflow_tpu.runtime.mesh import build_mesh

        cfg = preset("tiny", n_layers=4, hidden=64, n_heads=4, n_kv_heads=4,
                     head_dim=16, mlp_dim=128, vocab_size=256, max_seq_len=64,
                     dtype="float32")
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0,
                                    cfg.vocab_size)
        ref, _ = decoder_loss(params, tokens, cfg, attn_impl="xla")
        mesh = build_mesh({"pipeline": 4, "data": 2})
        out, _ = jax.jit(
            lambda p, t: decoder_loss(p, t, cfg, mesh=mesh))(params, tokens)
        assert abs(float(ref) - float(out)) < 1e-4 * max(1.0, abs(float(ref)))

    @pytest.mark.slow  # tier-1 budget (ISSUE 17): slowest fast tests re-marked
    def test_train_step_on_pp_mesh(self):
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.runtime.mesh import build_mesh
        from kubeflow_tpu.train.data import DataConfig, make_data_source
        from kubeflow_tpu.train.optim import OptimizerConfig
        from kubeflow_tpu.train.step import setup_train

        cfg = preset("tiny", n_layers=4, max_seq_len=64)
        mesh = build_mesh({"pipeline": 4, "data": 2})
        task = setup_train(cfg, OptimizerConfig(total_steps=4, warmup_steps=0),
                           mesh)
        # Layer stack must actually be sharded over the pipeline axis.
        layer_sh = jax.tree.leaves(task.state_shardings["params"]["layers"])[0]
        assert "pipeline" in str(layer_sh.spec)
        data_cfg = DataConfig(vocab_size=cfg.vocab_size, seq_len=cfg.max_seq_len,
                              global_batch=8)
        batch = jax.device_put(make_data_source(data_cfg).batch_at(0),
                               task.batch_sharding)
        state, metrics = task.step_fn(task.state, batch)
        state, metrics2 = task.step_fn(state, batch)
        assert float(metrics2["loss"]) < float(metrics["loss"])  # it learns

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_moe_pp_ep_matches_unstaged(self, schedule):
        """PP×EP: expert weights stay expert-sharded inside the pipeline
        stage (local experts + psum combine). CE loss and grads must match
        the unsharded model; aux is microbatch-local by design, so compare
        with aux_loss_weight=0. capacity_factor is ample (no drops): MoE
        dispatch capacity is per dispatch-batch, so a microbatched pipeline
        legitimately drops DIFFERENT (token, choice) pairs than a full-batch
        run — with no drops anywhere the schedules must agree exactly
        (verified 8e-7; drop policy itself is covered in
        test_moe_dispatch.py)."""
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params)
        from kubeflow_tpu.runtime.mesh import build_mesh

        cfg = preset("tiny-moe", n_layers=4, dtype="float32",
                     pipeline_schedule=schedule, capacity_factor=8.0)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, 256)
        mesh = build_mesh({"pipeline": 2, "expert": 2, "data": 2})

        def ref_loss(p, t):
            return decoder_loss(p, t, cfg, aux_loss_weight=0.0)[0]

        def pp_loss(p, t):
            return decoder_loss(p, t, cfg, mesh=mesh, aux_loss_weight=0.0)[0]

        ref, g_ref = jax.value_and_grad(ref_loss)(params, tokens)
        out, g_pp = jax.jit(jax.value_and_grad(pp_loss))(params, tokens)
        assert abs(float(ref) - float(out)) < 5e-4 * max(1.0, abs(float(ref)))
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
            rel_close(a, b, rtol=2e-3)

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_moe_pp_ep_tp_matches_unstaged(self):
        """PP×TP×MoE (the round-3 NotImplementedError, lifted): expert
        weights shard over `expert` AND each expert's mlp dim over `model`
        inside the stage, attention head-sharded over `model` — one
        combined psum. Loss and grads must match the unsharded model
        (ample capacity: no drops, same caveat as the PP×EP test)."""
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params)
        from kubeflow_tpu.runtime.mesh import build_mesh

        cfg = preset("tiny-moe", n_layers=4, dtype="float32",
                     capacity_factor=8.0)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, 256)
        mesh = build_mesh({"pipeline": 2, "expert": 2, "model": 2})

        def ref_loss(p, t):
            return decoder_loss(p, t, cfg, aux_loss_weight=0.0)[0]

        def pp_loss(p, t):
            return decoder_loss(p, t, cfg, mesh=mesh, aux_loss_weight=0.0)[0]

        ref, g_ref = jax.value_and_grad(ref_loss)(params, tokens)
        out, g_pp = jax.jit(jax.value_and_grad(pp_loss))(params, tokens)
        assert abs(float(ref) - float(out)) < 5e-4 * max(1.0, abs(float(ref)))
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
            rel_close(a, b, rtol=2e-3)

    def test_moe_pp_aux_loss_flows(self):
        """The streamed aux accumulator must surface a positive
        load-balancing loss under PP."""
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params)
        from kubeflow_tpu.runtime.mesh import build_mesh

        cfg = preset("tiny-moe", n_layers=4, dtype="float32")
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, 256)
        mesh = build_mesh({"pipeline": 4, "expert": 2})
        _, metrics = jax.jit(
            lambda p, t: decoder_loss(p, t, cfg, mesh=mesh))(params, tokens)
        # Balanced routing floor: aux >= 1.0 by Cauchy-Schwarz; 0 would mean
        # the accumulator never streamed.
        assert float(metrics["aux_loss"]) >= 0.9

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_pp_sp_matches_unstaged(self, impl):
        """PP×SP: the streamed activation is seq-sharded and attention runs
        the collective form over the seq axis inside the stage."""
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params)
        from kubeflow_tpu.runtime.mesh import build_mesh

        cfg = preset("tiny", n_layers=4, n_kv_heads=2, max_seq_len=64,
                     dtype="float32")
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 65), 0, 256)
        mesh = build_mesh({"pipeline": 2, "seq": 2, "data": 2})

        def ref_loss(p, t):
            return decoder_loss(p, t, cfg)[0]

        def pp_loss(p, t):
            return decoder_loss(p, t, cfg, mesh=mesh, attn_impl=impl)[0]

        ref, g_ref = jax.value_and_grad(ref_loss)(params, tokens)
        out, g_pp = jax.jit(jax.value_and_grad(pp_loss))(params, tokens)
        assert abs(float(ref) - float(out)) < 5e-4 * max(1.0, abs(float(ref)))
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
            rel_close(a, b, rtol=2e-3)

    def test_pp_1f1b_decoder_matches(self):
        """Dense decoder under the 1F1B schedule (pipeline_schedule knob)."""
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params)
        from kubeflow_tpu.runtime.mesh import build_mesh

        cfg = preset("tiny", n_layers=4, max_seq_len=64, dtype="float32",
                     pipeline_schedule="1f1b")
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 33), 0, 256)
        ref, _ = decoder_loss(params, tokens, cfg)
        mesh = build_mesh({"pipeline": 4, "data": 2})
        out, _ = jax.jit(
            lambda p, t: decoder_loss(p, t, cfg, mesh=mesh))(params, tokens)
        assert abs(float(ref) - float(out)) < 5e-4 * max(1.0, abs(float(ref)))


class TestPipeline:
    @staticmethod
    def stage_fn(params, x):
        return jax.nn.gelu(x @ params["w"] + params["b"])

    def setup_method(self, method):
        from kubeflow_tpu.parallel.pipeline import stack_stage_params

        key = jax.random.PRNGKey(7)
        stages = []
        for _ in range(4):
            k1, k2, key = jax.random.split(key, 3)
            stages.append({"w": jax.random.normal(k1, (32, 32)) * 0.3,
                           "b": jax.random.normal(k2, (32,)) * 0.1})
        self.params = stack_stage_params(stages)
        self.x = jax.random.normal(key, (16, 32))
        self.mesh = Mesh(np.array(jax.devices()[:4]), ("pipeline",))

    def test_forward_matches_sequential(self):
        from kubeflow_tpu.parallel.pipeline import (
            pipeline_apply, sequential_apply)

        ref = sequential_apply(self.stage_fn, self.params, self.x)
        for m in (2, 4, 8):
            out = pipeline_apply(self.stage_fn, self.params, self.x,
                                 mesh=self.mesh, num_microbatches=m)
            rel_close(ref, out)

    def test_gradients_match_sequential(self):
        from kubeflow_tpu.parallel.pipeline import (
            pipeline_apply, sequential_apply)

        def ref_loss(p, x):
            return jnp.sum(sequential_apply(self.stage_fn, p, x) ** 2)

        def pp_loss(p, x):
            return jnp.sum(pipeline_apply(
                self.stage_fn, p, x, mesh=self.mesh, num_microbatches=4) ** 2)

        g_ref = jax.grad(ref_loss)(self.params, self.x)
        g_pp = jax.grad(pp_loss)(self.params, self.x)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
            rel_close(a, b, rtol=5e-4)

    def test_bad_microbatch_count(self):
        from kubeflow_tpu.parallel.pipeline import pipeline_apply

        with pytest.raises(ValueError, match="divisible"):
            pipeline_apply(self.stage_fn, self.params, self.x,
                           mesh=self.mesh, num_microbatches=3)

    @pytest.mark.parametrize("m", [4, 8, 16])
    def test_1f1b_forward_and_grads_match_sequential(self, m):
        """The hand-scheduled 1F1B backward must agree with autodiff through
        the sequential stack — including m > 2·stages, which GPipe's stash
        caps out at (the whole point of the schedule)."""
        from kubeflow_tpu.parallel.pipeline import (
            pipeline_apply, sequential_apply)

        def ref_loss(p, x):
            return jnp.sum(sequential_apply(self.stage_fn, p, x) ** 2)

        def pp_loss(p, x):
            return jnp.sum(pipeline_apply(
                self.stage_fn, p, x, mesh=self.mesh, num_microbatches=m,
                schedule="1f1b") ** 2)

        rel_close(sequential_apply(self.stage_fn, self.params, self.x),
                  pipeline_apply(self.stage_fn, self.params, self.x,
                                 mesh=self.mesh, num_microbatches=m,
                                 schedule="1f1b"))
        (ref_l, g_ref) = jax.value_and_grad(ref_loss)(self.params, self.x)
        (pp_l, g_pp) = jax.value_and_grad(pp_loss)(self.params, self.x)
        rel_close(ref_l, pp_l)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
            rel_close(a, b, rtol=5e-4)

    def test_1f1b_composes_with_data_parallel(self):
        """1F1B on a pipeline×data mesh: parameter grads must sum over data
        shards (regression: the hand-written backward once skipped that
        psum, dropping the other shard's contribution entirely)."""
        from kubeflow_tpu.parallel.pipeline import (
            pipeline_apply, sequential_apply)

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("data", "pipeline"))

        def ref_loss(p, x):
            return jnp.sum(sequential_apply(self.stage_fn, p, x) ** 2)

        def pp_loss(p, x):
            return jnp.sum(pipeline_apply(
                self.stage_fn, p, x, mesh=mesh, num_microbatches=4,
                schedule="1f1b") ** 2)

        g_ref = jax.grad(ref_loss)(self.params, self.x)
        g_pp = jax.grad(pp_loss)(self.params, self.x)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
            rel_close(a, b, rtol=5e-4)

    def test_1f1b_rejects_integer_stream(self):
        from kubeflow_tpu.parallel.pipeline import pipeline_apply

        with pytest.raises(TypeError, match="inexact"):
            pipeline_apply(
                lambda p, x: x, self.params,
                jnp.zeros((16, 32), jnp.int32),
                mesh=self.mesh, num_microbatches=4, schedule="1f1b")

    def test_composes_with_jit(self):
        from kubeflow_tpu.parallel.pipeline import (
            pipeline_apply, sequential_apply)

        jitted = jax.jit(lambda p, x: pipeline_apply(
            self.stage_fn, p, x, mesh=self.mesh, num_microbatches=4))
        rel_close(sequential_apply(self.stage_fn, self.params, self.x),
                  jitted(self.params, self.x))


class TestPipelineTensorParallel:
    """PP×TP: Megatron head/mlp splits inside the pipeline stage (manual
    psums in layers.py; 1F1B derives the gradient sync from the specs)."""

    def _cfg(self, schedule="gpipe"):
        from kubeflow_tpu.models.config import preset

        return preset("tiny", n_layers=4, n_heads=4, n_kv_heads=2,
                      max_seq_len=64, dtype="float32",
                      pipeline_schedule=schedule)

    @pytest.mark.parametrize("schedule", [
        pytest.param("gpipe", marks=pytest.mark.slow),  # tier-1 budget:
        # ~8s; 1f1b exercises the same pp x tp composition plus staging
        "1f1b",
    ])
    def test_pp_tp_matches_unstaged(self, schedule):
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params)
        from kubeflow_tpu.runtime.mesh import build_mesh

        cfg = self._cfg(schedule)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, 256)
        mesh = build_mesh({"pipeline": 2, "model": 2, "data": 2})

        ref, g_ref = jax.value_and_grad(
            lambda p: decoder_loss(p, tokens, cfg)[0])(params)
        out, g_pp = jax.jit(jax.value_and_grad(
            lambda p: decoder_loss(p, tokens, cfg, mesh=mesh)[0]))(params)
        assert abs(float(ref) - float(out)) < 5e-4 * max(1.0, abs(float(ref)))
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
            rel_close(a, b, rtol=2e-3)

    def test_indivisible_heads_rejected(self):
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params)
        from kubeflow_tpu.runtime.mesh import build_mesh

        cfg = preset("tiny", n_layers=4, n_heads=4, n_kv_heads=1,
                     max_seq_len=64)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, 256)
        mesh = build_mesh({"pipeline": 2, "model": 2, "data": 2})
        with pytest.raises(ValueError, match="divide"):
            decoder_loss(params, tokens, cfg, mesh=mesh)

    def test_pp_tp_moe_runs(self):
        """Round 3 guarded this composition with a NotImplementedError;
        round 4 composed it — a PP×TP MoE loss (no expert axis: TP slices
        each expert's mlp dim, experts replicated) must now just run.
        Full loss+grad equivalence incl. the expert axis lives in
        TestModelPipelineParallel::test_moe_pp_ep_tp_matches_unstaged."""
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params)
        from kubeflow_tpu.runtime.mesh import build_mesh

        cfg = preset("tiny-moe", n_layers=4, dtype="float32")
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0, 256)
        mesh = build_mesh({"pipeline": 2, "model": 2, "data": 2})
        ref, _ = decoder_loss(params, tokens, cfg)
        out, _ = jax.jit(
            lambda p, t: decoder_loss(p, t, cfg, mesh=mesh))(params, tokens)
        assert abs(float(ref) - float(out)) < 5e-3 * max(1.0, abs(float(ref)))


class TestShardedFlashTraining:
    @pytest.mark.slow  # tier-1 budget (ISSUE 17): slowest fast tests re-marked
    def test_pallas_train_step_matches_xla_on_mesh(self):
        """attn_impl='pallas' on a dp×fsdp×tp mesh: the flash kernel runs
        per-shard under shard_map (Mosaic can't be GSPMD-partitioned — the
        8B AOT validation caught this); loss and grads must match the XLA
        attention path on the same mesh."""
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params)
        from kubeflow_tpu.runtime.mesh import build_mesh

        cfg = preset("tiny", dtype="float32", max_seq_len=128)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 129), 0, 256)
        mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})

        outs = {}
        for impl in ("xla", "pallas"):
            # two traces total, one per impl — not compile-cache churn
            loss, grads = jax.jit(jax.value_and_grad(  # lint: disable=D105
                lambda p: decoder_loss(p, tokens, cfg, mesh=mesh,
                                       attn_impl=impl)[0]))(params)
            outs[impl] = (float(loss), grads)
        assert abs(outs["xla"][0] - outs["pallas"][0]) < 5e-5
        for a, b in zip(jax.tree.leaves(outs["xla"][1]),
                        jax.tree.leaves(outs["pallas"][1])):
            rel_close(a, b, rtol=2e-3)

    def test_nondivisible_heads_fall_back(self):
        """tp=8 over 4 q heads: flash_attention_sharded declines and the
        XLA path serves — the step still runs."""
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import (
            decoder_loss, init_decoder_params)
        from kubeflow_tpu.runtime.mesh import build_mesh

        cfg = preset("tiny", dtype="float32", max_seq_len=64)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 65), 0, 256)
        mesh = build_mesh({"model": 8})
        loss, _ = jax.jit(lambda p: decoder_loss(
            p, tokens, cfg, mesh=mesh, attn_impl="pallas"))(params)
        assert np.isfinite(float(loss))
