"""Decode hot-loop host-overhead elimination (ISSUE 4): device-resident
scheduler state + pipelined double-buffered dispatch.

Contracts pinned here:
- greedy outputs are TOKEN-IDENTICAL with pipelining on and off, across
  dense, paged, and speculative engines (the pipeline must be invisible to
  outputs — only latency moves);
- steady-state decode rounds perform ZERO full-array host→device uploads
  of scheduler state (counter-asserted: the device_state stats stay at
  their construction values while rounds accumulate, and per-slot syncs
  stay flat across decode-only rounds);
- the one-round staleness contract is bounded: a cancellation decided
  while a round is in flight masks that round's results — output streams
  never contain post-cancel tokens — and paged-KV refcounts balance;
- first-token sampling batches per admit round (one fetch for N
  admissions, chunked and grouped alike);
- EngineMetrics surfaces host_gap/dispatch_depth and the model server
  exposes them on /metrics.
"""

import time

import pytest
import jax

from kubeflow_tpu.core.serving import BatchingSpec, SpeculativeSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams


@pytest.fixture(scope="module")
def cfg():
    return preset("tiny", vocab_size=512)


@pytest.fixture(scope="module")
def params(cfg):
    return init_decoder_params(jax.random.PRNGKey(0), cfg)


PROMPTS = [[5, 17, 3, 99, 42], list(range(1, 50)), [7] * 20,
           [9, 8, 7, 6, 5, 4]]


def make_engine(cfg, params, *, pipelined, paged=False, spec=None,
                chunk=32, decode_steps=4, slots=4):
    return LLMEngine(cfg, BatchingSpec(
        max_batch_size=slots, max_seq_len=128, prefill_buckets=[16, 64],
        chunked_prefill_tokens=chunk, paged=paged, page_size=16,
        decode_steps=decode_steps, pipelined_decode=pipelined,
        speculative=spec or SpeculativeSpec()), params=params)


def run_all(eng, reqs, max_steps=1200):
    for _ in range(max_steps):
        eng.step()
        if all(r.done.is_set() for r in reqs):
            return
    raise AssertionError("requests did not finish")


def gen_all(eng, prompts, max_new=12):
    sp = SamplingParams(max_new_tokens=max_new, temperature=0.0)
    reqs = [eng.submit(list(p), sp) for p in prompts]
    run_all(eng, reqs)
    return [list(r.output_tokens) for r in reqs]


class TestTokenIdentity:
    """Pipelining on vs off must be invisible to greedy outputs on every
    engine flavor (the acceptance-criteria core)."""

    @pytest.fixture(scope="class")
    def want(self, cfg, params):
        return gen_all(make_engine(cfg, params, pipelined=False), PROMPTS)

    def test_dense(self, cfg, params, want):
        eng = make_engine(cfg, params, pipelined=True)
        assert gen_all(eng, PROMPTS) == want
        assert eng.decode_rounds > 0

    @pytest.mark.slow  # tier-1 budget (ISSUE 20): ~11s; test_spec_paged
    # keeps a fast pipelined-vs-off paged identity check in this class
    def test_paged(self, cfg, params, want):
        off = make_engine(cfg, params, pipelined=False, paged=True)
        on = make_engine(cfg, params, pipelined=True, paged=True)
        assert gen_all(off, PROMPTS) == want
        assert gen_all(on, PROMPTS) == want
        assert on.kv_pages_in_use() == 0

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_spec_ngram(self, cfg, params, want):
        spec = SpeculativeSpec(mode="ngram", k=4)
        off = make_engine(cfg, params, pipelined=False, spec=spec)
        on = make_engine(cfg, params, pipelined=True, spec=spec)
        assert gen_all(off, PROMPTS) == want
        assert gen_all(on, PROMPTS) == want

    def test_spec_paged(self, cfg, params, want):
        spec = SpeculativeSpec(mode="ngram", k=4)
        eng = make_engine(cfg, params, pipelined=True, paged=True,
                          spec=spec)
        assert gen_all(eng, PROMPTS) == want
        assert eng.kv_pages_in_use() == 0

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_staggered_admissions(self, cfg, params):
        """Requests joining while rounds are in flight (the one-round-late
        admission path) still decode exactly."""
        def staggered(eng):
            sp = SamplingParams(max_new_tokens=10, temperature=0.0)
            reqs = [eng.submit(list(PROMPTS[0]), sp),
                    eng.submit(list(PROMPTS[1]), sp)]
            for _ in range(2):
                eng.step()
            reqs += [eng.submit(list(PROMPTS[2]), sp),
                     eng.submit(list(PROMPTS[3]), sp)]
            run_all(eng, reqs)
            return [list(r.output_tokens) for r in reqs]

        out_off = staggered(make_engine(cfg, params, pipelined=False))
        out_on = staggered(make_engine(cfg, params, pipelined=True))
        assert out_on == out_off


class TestDeviceResidentState:
    """Tentpole (a): the scheduler state uploads ONCE, at construction;
    everything after is per-slot deltas — and decode-only rounds sync
    nothing at all."""

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_full_uploads_stay_at_construction(self, cfg, params):
        for paged in (False, True):
            eng = make_engine(cfg, params, pipelined=True, paged=paged)
            gen_all(eng, PROMPTS)
            rounds1 = eng.decode_rounds
            stats1 = dict(eng._dstate.stats)
            assert rounds1 > 0
            assert stats1["full_state_uploads"] == 1
            assert stats1["full_table_uploads"] == (1 if paged else 0)
            gen_all(eng, PROMPTS)
            stats2 = eng._dstate.stats
            assert eng.decode_rounds > rounds1
            assert stats2["full_state_uploads"] == 1
            assert stats2["full_table_uploads"] == (1 if paged else 0)

    def test_steady_state_rounds_sync_nothing(self, cfg, params):
        """Mid-generation decode rounds (no admissions, no reaps) must not
        scatter any slot state — the device carry is authoritative."""
        eng = make_engine(cfg, params, pipelined=True, decode_steps=2)
        req = eng.submit([3, 1, 4], SamplingParams(max_new_tokens=40))
        for _ in range(4):
            eng.step()          # admit + enter steady decode
        assert not req.done.is_set()
        syncs_before = eng._dstate.stats["slot_syncs"]
        rounds_before = eng.decode_rounds
        for _ in range(5):
            eng.step()
        assert not req.done.is_set()
        assert eng.decode_rounds > rounds_before
        assert eng._dstate.stats["slot_syncs"] == syncs_before
        run_all(eng, [req])

    def test_paged_growth_is_row_deltas(self, cfg, params):
        """Page-table growth mid-decode costs row scatters, never a full
        table upload."""
        eng = make_engine(cfg, params, pipelined=True, paged=True,
                          decode_steps=4)
        gen_all(eng, [[2, 3, 4]], max_new=60)   # grows across pages
        stats = eng._dstate.stats
        assert stats["full_table_uploads"] == 1
        assert stats["table_row_syncs"] > 0


class TestPipelinedCancellation:
    """The staleness contract's hard edge: results of a round dispatched
    before the cancel must never reach the stream."""

    def _drain_stream(self, req):
        toks = []
        while True:
            t = req.stream.get(timeout=5)
            if t is None:
                return toks
            toks.append(t)

    @pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
    def test_cancel_mid_flight_emits_nothing_after(self, cfg, params,
                                                   paged):
        eng = make_engine(cfg, params, pipelined=True, paged=paged,
                          decode_steps=4)
        req = eng.submit([4, 5, 6, 7], SamplingParams(max_new_tokens=100))
        for _ in range(3):
            eng.step()          # a round is now in flight past the cancel
        assert not req.done.is_set()
        assert eng._rounds, "pipelining should keep a round in flight"
        emitted_at_cancel = len(req.output_tokens)
        req.cancel()
        for _ in range(6):
            eng.step()
        assert req.done.is_set()
        assert req.finish_reason == "cancelled"
        assert len(req.output_tokens) == emitted_at_cancel, \
            "post-cancel tokens leaked into the output"
        streamed = self._drain_stream(req)
        assert streamed == req.output_tokens
        if paged:
            assert eng.kv_pages_in_use() == 0
            eng._allocator.assert_quiescent()

    def test_deadline_mid_flight_frees_pages(self, cfg, params):
        eng = make_engine(cfg, params, pipelined=True, paged=True,
                          decode_steps=4)
        req = eng.submit([9, 9, 9], SamplingParams(max_new_tokens=100),
                         deadline=time.monotonic() + 0.03)
        eng.step()
        time.sleep(0.05)
        for _ in range(8):
            eng.step()
        assert req.done.is_set() and req.finish_reason == "deadline"
        assert eng.kv_pages_in_use() == 0
        eng._allocator.assert_quiescent()

    def test_slot_reuse_after_mid_flight_cancel_is_clean(self, cfg, params):
        """A slot freed by a mid-flight cancel and immediately re-admitted
        must serve the newcomer untainted (its in-flight garbage KV is
        overwritten before ever being attended)."""
        want = gen_all(make_engine(cfg, params, pipelined=False),
                       [[11, 12, 13]], max_new=10)[0]
        eng = make_engine(cfg, params, pipelined=True, slots=1,
                          decode_steps=4)
        victim = eng.submit([4, 5, 6, 7], SamplingParams(max_new_tokens=80))
        for _ in range(3):
            eng.step()
        victim.cancel()
        fresh = eng.submit([11, 12, 13], SamplingParams(max_new_tokens=10))
        run_all(eng, [victim, fresh])
        assert victim.finish_reason == "cancelled"
        assert list(fresh.output_tokens) == want


class TestFirstTokenBatching:
    """Satellite: first-token fetches batch per admit round — one sampler
    dispatch + one device_get for every admission in the pass."""

    def test_chunked_completions_share_one_fetch(self, cfg, params):
        eng = make_engine(cfg, params, pipelined=True, chunk=16, slots=4)
        eng.max_concurrent_prefills = 3
        sp = SamplingParams(max_new_tokens=4, temperature=0.0)
        # Three same-length long prompts chunk in lockstep and complete in
        # the same admit pass.
        reqs = [eng.submit([i + 1] * 33, sp) for i in range(3)]
        before = eng.first_token_fetches
        while not all(r.first_token_time is not None for r in reqs):
            eng.step()
        assert eng.first_token_fetches == before + 1
        run_all(eng, reqs)

    def test_grouped_prefill_shares_one_fetch(self, cfg, params):
        eng = LLMEngine(cfg, BatchingSpec(
            max_batch_size=8, max_seq_len=64, prefill_buckets=[8],
            prefill_batch_max=4, decode_steps=4), params=params)
        sp = SamplingParams(max_new_tokens=4, temperature=0.0)
        reqs = [eng.submit([i + 1, i + 2, i + 3], sp) for i in range(4)]
        before = eng.first_token_fetches
        eng.step()
        assert all(r.first_token_time is not None for r in reqs)
        assert eng.first_token_fetches == before + 1
        run_all(eng, reqs)

    @pytest.mark.slow  # tier-1 budget (ISSUE 20): ~8s; the one-fetch
    # accounting stays fast via test_chunked_completions_share_one_fetch
    def test_batched_first_tokens_match_reference(self, cfg, params):
        """The batched sampler path must not perturb greedy outputs."""
        want = gen_all(make_engine(cfg, params, pipelined=False),
                       PROMPTS, max_new=6)
        eng = make_engine(cfg, params, pipelined=True, chunk=16)
        assert gen_all(eng, PROMPTS, max_new=6) == want


class TestTransferGuard:
    """Runtime half of the static device-hygiene rules (ISSUE 5): the
    engine's transfer contract is that every steady-state host<->device
    move is EXPLICIT (device_put at the sync sites, device_get at the
    designed fetch points). Proven by running mid-generation decode
    rounds under ``jax.transfer_guard("disallow")`` — an implicit
    transfer anywhere raises — on all three engine flavors, and by the
    ``KFTPU_SANITIZE=1`` mode that wires the same guard inside step()."""

    def _steady_state_under_guard(self, eng, warmup=6, guarded=5):
        sp = SamplingParams(max_new_tokens=60, temperature=0.0)
        req = eng.submit([3, 1, 4, 1, 5], sp)
        for _ in range(warmup):
            eng.step()          # admit + first token + enter steady decode
        assert not req.done.is_set()
        rounds_before = eng.decode_rounds
        with jax.transfer_guard("disallow"):
            for _ in range(guarded):
                eng.step()
        assert eng.decode_rounds > rounds_before
        run_all(eng, [req])
        return req

    def test_dense_steady_state(self, cfg, params):
        self._steady_state_under_guard(
            make_engine(cfg, params, pipelined=True))

    def test_paged_steady_state(self, cfg, params):
        eng = make_engine(cfg, params, pipelined=True, paged=True)
        self._steady_state_under_guard(eng)
        assert eng.kv_pages_in_use() == 0

    def test_spec_steady_state(self, cfg, params):
        spec = SpeculativeSpec(mode="ngram", k=4)
        self._steady_state_under_guard(
            make_engine(cfg, params, pipelined=True, spec=spec))

    @pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
    def test_sanitize_mode_token_identity(self, cfg, params, monkeypatch):
        """KFTPU_SANITIZE=1 engines guard every decode pass themselves and
        still produce reference greedy outputs on every flavor."""
        want = gen_all(make_engine(cfg, params, pipelined=False), PROMPTS)
        monkeypatch.setenv("KFTPU_SANITIZE", "1")
        for kw in ({}, {"paged": True},
                   {"spec": SpeculativeSpec(mode="ngram", k=4)}):
            eng = make_engine(cfg, params, pipelined=True, **kw)
            assert eng.sanitize
            assert gen_all(eng, PROMPTS) == want

    def test_sanitize_mode_off_by_default(self, cfg, params, monkeypatch):
        monkeypatch.delenv("KFTPU_SANITIZE", raising=False)
        assert not make_engine(cfg, params, pipelined=True).sanitize
        monkeypatch.setenv("KFTPU_SANITIZE", "0")
        assert not make_engine(cfg, params, pipelined=True).sanitize


class TestHotLoopMetrics:
    """Satellite: host_gap + dispatch_depth in EngineMetrics.snapshot()
    and on /metrics through the PR 3 registry."""

    def test_snapshot_has_host_gap_and_depth(self, cfg, params):
        for pipelined, want_depth in ((False, 0), (True, 1)):
            eng = make_engine(cfg, params, pipelined=pipelined)
            gen_all(eng, [[2] * 6], max_new=30)
            snap = eng.metrics.snapshot()
            assert snap["dispatch_depth"] == want_depth
            assert "host_gap_seconds" in snap
            assert snap["host_gap_p50_ms"] >= 0.0
            assert snap["host_gap_p99_ms"] >= snap["host_gap_p50_ms"]
            buckets, counts, total, n = eng.metrics.host_gap_histogram()
            assert n > 0 and sum(counts) == n
            assert total >= 0.0
            if pipelined:
                # Steady-state pipelined rounds have zero host gap by
                # construction — the distribution must reflect it.
                assert snap["host_gap_p50_ms"] == 0.0

    def test_metrics_endpoint_series(self, cfg, params):
        from kubeflow_tpu.obs.registry import parse_exposition
        from kubeflow_tpu.serve.server import ModelServer

        eng = make_engine(cfg, params, pipelined=True)
        gen_all(eng, [[2] * 6], max_new=20)
        srv = ModelServer("hotloop", eng, port=0)
        try:
            names = {n for n, _, _ in parse_exposition(srv.metrics_text())}
        finally:
            srv.httpd.server_close()
        for need in ("kftpu_engine_host_gap_seconds_bucket",
                     "kftpu_engine_host_gap_seconds_sum",
                     "kftpu_engine_host_gap_seconds_count",
                     "kftpu_engine_dispatch_depth"):
            assert need in names, f"missing {need}"

    def test_decode_span_host_gap_attribute(self, cfg, params):
        from kubeflow_tpu.obs.trace import get_tracer

        tracer = get_tracer()
        eng = make_engine(cfg, params, pipelined=True)
        sp = SamplingParams(max_new_tokens=40, temperature=0.0)
        with tracer.span("test.root") as root:
            req = eng.submit([3, 1, 4], sp, trace_parent=root)
            run_all(eng, [req])
        tr = tracer.trace(root.trace_id)
        gaps = []
        for s in tr["spans"]:
            if s["name"] != "engine.decode":
                continue
            for ev in s.get("events", []):
                if ev["name"] == "decode_round" and "host_gap_ms" in ev:
                    gaps.append(ev["host_gap_ms"])
        assert gaps, "no decode_round event carried host_gap_ms"
        assert all(isinstance(g, float) and g >= 0.0 for g in gaps)
