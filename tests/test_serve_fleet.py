"""Fleet-wide KV fabric (ISSUE 17): conversation failover through the
remote third tier, cross-host handoff retry/fallback, and the
mixed-version negotiation guard — driven over real HTTP model servers.

The chaos gate: SIGKILL an engine that holds a multi-turn conversation,
and the NEXT turn must land on a survivor, adopt the stored prefix from
the artifact store (prefix-hit counter > 0), and produce token-identical
output — while every injected handoff fault degrades to local recompute
with the request still resolving (failure costs a prefill, never the
request), and both pools balance their refcounts afterwards."""

import json
import socket
import time
import urllib.error
import urllib.request

import pytest
import jax

from kubeflow_tpu.core.headers import (
    DECODE_ALTS_HEADER, DECODE_BACKEND_HEADER, HANDOFF_DTYPE_HEADER,
    HANDOFF_WIRE_HEADER,
)
from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine
from kubeflow_tpu.serve.faults import ChaosProxy, kill_model_server
from kubeflow_tpu.serve.server import ModelServer


@pytest.fixture(scope="module")
def cfg():
    return preset("tiny", vocab_size=512)      # byte tokenizer fits


@pytest.fixture(scope="module")
def params(cfg):
    return init_decoder_params(jax.random.PRNGKey(0), cfg)


def spec(role="unified", *, remote_root=None, prefix=True):
    kw = {}
    if remote_root is not None:
        kw.update(host_kv_pages=64, kv_demote_after_s=0.05,
                  kv_remote_after_s=0.05, remote_kv_root=str(remote_root),
                  prefix_index="radix")
    return BatchingSpec(max_batch_size=2, max_seq_len=96,
                        prefill_buckets=[32], paged=True, page_size=16,
                        chunked_prefill_tokens=16, decode_steps=4,
                        enable_prefix_caching=prefix, role=role, **kw)


def mk_server(name, cfg, params, sp):
    srv = ModelServer(name, LLMEngine(cfg, sp, params=params), port=0)
    srv.start()
    return srv


def completion(url: str, prompt: str, *, headers=(), max_tokens: int = 8,
               timeout_s: float = 20.0) -> tuple[int, str]:
    body = json.dumps({"prompt": prompt, "max_tokens": max_tokens,
                       "timeout": timeout_s}).encode()
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(dict(headers))
    req = urllib.request.Request(url + "/v1/completions", data=body,
                                 headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s + 5) as r:
            obj = json.loads(r.read())
            return r.status, obj["choices"][0]["text"]
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(errors="replace")


def dead_url() -> str:
    """A URL nothing listens on: bound then immediately closed, so a
    connect fails fast with ECONNREFUSED (the dead-replica fault)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"http://127.0.0.1:{port}"


def audit_quiescent(*servers, deadline_s: float = 20.0) -> None:
    """Post-scenario refcount audit (the chaos-suite invariant): cancel
    anything stranded, drive the reaper, assert zero page leaks."""
    for srv in servers:
        eng = srv.engine
        for s in eng.slots:
            if s is not None:
                s.request.cancel()
        for lane in (eng._backlog, eng._preempted):
            for req in lane:
                req.cancel()
        for ch in list(eng._chunkings):
            ch.request.cancel()
        for hreq, _pages in list(eng._handoff_holds.values()):
            hreq.cancel()
        deadline = time.monotonic() + deadline_s
        while eng.kv_pages_in_use() > 0 or eng._handoff_holds:
            eng.step()
            assert time.monotonic() < deadline, \
                f"{srv.name}: KV pages leaked after scenario"
        eng._allocator.assert_quiescent()
        while eng._rounds:
            eng.step()


def stop_all(*servers):
    for s in servers:
        try:
            s.stop()
        except OSError:
            pass


@pytest.mark.slow  # tier-1 budget: three engines + store roundtrip, ~15s
def test_failover_sigkill_then_resume_on_survivor(cfg, params, tmp_path,
                                                  monkeypatch):
    """The chaos gate: turn 1 lands on replica A, the conversation goes
    idle and spills to the remote tier, A is SIGKILLed, and turn 2 on
    replica B (same store root, no live connection to A ever existed)
    adopts the stored prefix — token-identical with an untier-ed engine,
    prefix-hit counter > 0, refcounts exact under the sanitizer."""
    monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
    a = mk_server("fleet-a", cfg, params, spec(remote_root=tmp_path))
    b = mk_server("fleet-b", cfg, params, spec(remote_root=tmp_path))
    ref = mk_server("fleet-ref", cfg, params, spec(prefix=False))
    try:
        turn1 = "fleet failover: the conversation must survive the host"
        st, text1 = completion(a.url, turn1)
        assert st == 200
        st, want1 = completion(ref.url, turn1)
        assert st == 200 and text1 == want1
        # Idle: the background tier scan demotes the released
        # conversation to host RAM, then spills it into the store.
        deadline = time.monotonic() + 20.0
        while a.engine.kv_tier_stats().get("pages_demoted_remote", 0) < 3:
            time.sleep(0.02)
            assert time.monotonic() < deadline, \
                f"no remote spill happened: {a.engine.kv_tier_stats()}"
        # SIGKILL the conversation's home replica.
        kill_model_server(a)
        # Turn 2 on the SURVIVOR: prompt = turn 1 + its actual output +
        # new tokens. B has never seen this conversation — the only way
        # it can match the prefix is through the store.
        turn2 = turn1 + text1 + " and then"
        st, text2 = completion(b.url, turn2)
        assert st == 200
        st, want2 = completion(ref.url, turn2)
        assert st == 200 and text2 == want2
        tier = b.engine.kv_tier_stats()
        assert tier["remote_registry_hits"] > 0, tier
        assert tier["pages_promoted_remote"] >= 3, tier
        assert tier["prefix_hits"] >= 1, tier
        audit_quiescent(b, ref)
        for srv in (b, ref):
            assert srv.engine._allocator.leak_report_by_owner() == {}
    finally:
        stop_all(a, b, ref)


@pytest.mark.slow  # tier-1 budget: prefill+decode pair + chaos proxy, ~10s
def test_decode_ack_loss_mid_adoption_recomputes(cfg, params, monkeypatch):
    """Dropped handoff ack AFTER send (the decode side fully adopted the
    payload; the prefill side never heard): the prefill must take the
    terminal fallback — local recompute, same greedy text, request
    resolves — and BOTH pools balance, including the decode side's
    orphaned adoption."""
    monkeypatch.setenv("KFTPU_SANITIZE", "refcount")
    pre = mk_server("pre-a", cfg, params, spec("prefill"))
    dec = mk_server("dec-b", cfg, params, spec("decode"))
    proxy = ChaosProxy(dec.url)
    proxy.start()
    try:
        prompt = "handoff ack loss: the request must still resolve"
        hdr = [(DECODE_BACKEND_HEADER, proxy.url)]
        # Healthy handoff first: pins the expected text and proves the
        # disaggregated path is actually in play.
        st, want = completion(pre.url, prompt, headers=hdr)
        assert st == 200
        assert pre.engine.metrics.snapshot()["handoffs_exported"] >= 1
        assert dec.engine.metrics.snapshot()["handoffs_adopted"] >= 1
        # Arm the fault: the decode target processes the POST fully,
        # the ack never reaches the prefill side.
        proxy.drop_response()
        st, got = completion(pre.url, prompt, headers=hdr)
        assert st == 200 and got == want
        snap = pre.engine.metrics.snapshot()
        assert snap["handoffs_fallback"] >= 1, snap
        assert proxy.stats["responses_dropped"] >= 1
        proxy.undrop_response()
        audit_quiescent(pre, dec)
        for srv in (pre, dec):
            assert srv.engine._allocator.leak_report_by_owner() == {}
    finally:
        proxy.stop()
        stop_all(pre, dec)


@pytest.mark.slow  # tier-1 budget: two engine servers + dead-replica probe, ~7s
def test_handoff_retry_lands_on_alternate_replica(cfg, params):
    """Dead primary decode replica + router-stamped alternate: the
    bounded retry targets the DIFFERENT replica and the handoff
    completes there — counted in handoffs_retried, no fallback."""
    pre = mk_server("pre-a", cfg, params, spec("prefill"))
    dec = mk_server("dec-b", cfg, params, spec("decode"))
    try:
        st, text = completion(
            pre.url, "retry onto the alternate decode replica",
            headers=[(DECODE_BACKEND_HEADER, dead_url()),
                     (DECODE_ALTS_HEADER, dec.url)])
        assert st == 200 and text
        snap = pre.engine.metrics.snapshot()
        assert snap["handoffs_retried"] >= 1, snap
        assert snap["handoffs_fallback"] == 0, snap
        assert dec.engine.metrics.snapshot()["handoffs_adopted"] >= 1
        audit_quiescent(pre, dec)
    finally:
        stop_all(pre, dec)


def test_handoff_negotiation_rejects_409(cfg, params):
    """Mixed-version fleet guard: an unsupported wire version or a
    cache-dtype mismatch 409s at submit — BEFORE the payload bytes are
    interpreted — so the prefill side retries elsewhere or recomputes
    instead of the decode pool corrupting pages."""
    dec = mk_server("dec-b", cfg, params, spec("decode"))
    try:
        def post_handoff(headers):
            req = urllib.request.Request(
                dec.url + "/v1/handoff", data=b"",
                headers={"Content-Type": "application/octet-stream",
                         **headers})
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    return r.status, r.read().decode()
            except urllib.error.HTTPError as exc:
                return exc.code, exc.read().decode(errors="replace")

        st, body = post_handoff({HANDOFF_WIRE_HEADER: "99"})
        assert st == 409 and "wire version" in body
        st, body = post_handoff({HANDOFF_WIRE_HEADER: "2",
                                 HANDOFF_DTYPE_HEADER: "int8"})
        assert st == 409 and "dtype" in body
        audit_quiescent(dec)
    finally:
        stop_all(dec)
