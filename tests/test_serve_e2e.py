"""Serving e2e with a real model-server process: InferenceService submitted
to the live control plane → predictor worker spawns → readiness → requests
through the routed URL → crash recovery (SURVEY.md §3.2 end to end)."""

import json
import signal
import time
import urllib.request

import pytest

from kubeflow_tpu.core.jobs import Worker
from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.core.serving import (
    BatchingSpec, InferenceService, InferenceServiceSpec, ModelSpec,
    PredictorSpec,
)
from kubeflow_tpu.operator.control_plane import ControlPlane, ControlPlaneConfig
from kubeflow_tpu.runtime.topology import Cluster, SliceTopology


@pytest.fixture()
def cp(tmp_path):
    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="cpu",
                                              dims=(2, 2))]),
        platform="cpu"))
    plane.start()
    yield plane
    plane.stop()


def _post(url: str, body: dict, timeout=120) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.mark.slow
def test_isvc_serves_through_router_and_recovers(cp):
    isvc = cp.submit(InferenceService(
        metadata=ObjectMeta(name="llm"),
        spec=InferenceServiceSpec(predictor=PredictorSpec(
            model=ModelSpec(model_name="llm",
                            config={"preset": "tiny",
                                    "overrides": {"vocab_size": 512}}),
            batching=BatchingSpec(max_batch_size=2, max_seq_len=64,
                                  prefill_buckets=[32])))))
    ready = cp.wait_for(isvc, "Ready", timeout=180)
    url = ready.status.url

    out = _post(url + "/v1/completions", {"prompt": "hi", "max_tokens": 4})
    assert out["object"] == "text_completion"
    assert out["usage"]["completion_tokens"] >= 1

    out = _post(url + "/v1/models/llm:predict",
                {"instances": ["a"], "max_tokens": 2})
    assert len(out["predictions"]) == 1

    # Crash the replica; the controller must replace it and go Ready again.
    worker = cp.store.list(
        Worker, label_selector={"serving.tpu.kubeflow.dev/service": "llm"})[0]
    cp.runtime.procman.signal(
        f"default.{worker.metadata.name}", signal.SIGKILL)
    deadline = time.monotonic() + 180
    recovered = False
    while time.monotonic() < deadline:
        cur = cp.store.get(InferenceService, "llm")
        ws = cp.store.list(
            Worker, label_selector={"serving.tpu.kubeflow.dev/service": "llm"})
        if (cur.status.ready_replicas >= 1 and ws
                and ws[0].metadata.uid != worker.metadata.uid):
            recovered = True
            break
        time.sleep(0.5)
    assert recovered, "replica was not replaced after crash"
    out = _post(url + "/v1/completions", {"prompt": "yo", "max_tokens": 2})
    assert out["choices"][0]["finish_reason"] in ("length", "stop")


@pytest.mark.slow
def test_scale_to_zero_cold_start_e2e(cp):
    """The serverless path end to end ((U) kserve Knative mode): a
    min_replicas=0 service serves, idles to zero, then a request parks at
    the router, the controller cold-starts a replica, and the request is
    answered — no 503 anywhere."""
    isvc = cp.submit(InferenceService(
        metadata=ObjectMeta(name="szero"),
        spec=InferenceServiceSpec(predictor=PredictorSpec(
            model=ModelSpec(model_name="szero",
                            config={"preset": "tiny",
                                    "overrides": {"vocab_size": 512}}),
            min_replicas=0, max_replicas=1,
            batching=BatchingSpec(max_batch_size=2, max_seq_len=64,
                                  prefill_buckets=[32])))))
    ready = cp.wait_for(isvc, "Ready", timeout=180)
    url = ready.status.url
    out = _post(url + "/v1/completions", {"prompt": "hi", "max_tokens": 2})
    assert out["usage"]["completion_tokens"] >= 1

    # Idle past the cooldown → the controller drops the last replica.
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        cur = cp.store.get(InferenceService, "szero")
        ws = cp.store.list(Worker, label_selector={
            "serving.tpu.kubeflow.dev/service": "szero"})
        if cur.status.desired_replicas == 0 and not ws:
            break
        time.sleep(1.0)
    else:
        raise AssertionError("service never scaled to zero while idle")

    # A request against the zero-scaled URL: parks at the router, replica
    # cold-starts (spawn + model init + compile), request answers.
    out = _post(url + "/v1/completions", {"prompt": "again", "max_tokens": 2},
                timeout=240)
    assert out["usage"]["completion_tokens"] >= 1
    cur = cp.store.get(InferenceService, "szero")
    assert cur.status.ready_replicas >= 1


@pytest.mark.slow
def test_tensor_parallel_predictor_e2e(cp):
    """A tensor-parallel InferenceService: ONE replica process spanning 2
    (virtual) chips, engine GSPMD-sharded over the mesh ((U) kserve
    huggingfaceserver vLLM tensor_parallel_size; SURVEY.md §2.3#27)."""
    from kubeflow_tpu.core.jobs import ParallelismSpec

    isvc = cp.submit(InferenceService(
        metadata=ObjectMeta(name="tp"),
        spec=InferenceServiceSpec(predictor=PredictorSpec(
            model=ModelSpec(model_name="tp",
                            config={"preset": "tiny",
                                    "overrides": {"vocab_size": 512}}),
            parallelism=ParallelismSpec(model=2),
            batching=BatchingSpec(max_batch_size=2, max_seq_len=64,
                                  prefill_buckets=[32])))))
    ready = cp.wait_for(isvc, "Ready", timeout=240)
    # The replica worker is a 2-chip gang member, not two replicas.
    ws = cp.store.list(Worker, label_selector={
        "serving.tpu.kubeflow.dev/service": "tp"})
    assert len(ws) == 1
    assert ws[0].spec.resources.tpu_chips == 2
    assert ws[0].spec.parallelism.get("model") == 2
    out = _post(ready.status.url + "/v1/completions",
                {"prompt": "hi", "max_tokens": 4})
    assert out["usage"]["completion_tokens"] >= 1
