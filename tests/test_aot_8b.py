"""AOT shardability proof for the flagship 8B recipe (SURVEY.md §6;
VERDICT round-2 next #5): lower + compile — never execute — the real train
step and the TP-sharded serving decode against virtual TPU topologies via
libtpu's topology-only AOT path, and check per-chip memory against the HBM
budget. scripts/aot_validate_8b.py runs the full config table (results in
BASELINE.md); this test pins the mechanism + the v5p-16 train point and
the v5e-8 serving point.

Requires libtpu (present in this image); skips cleanly where the TPU AOT
plugin is unavailable.
"""

import pytest


def _topo(name):
    from jax.experimental import topologies

    try:
        return topologies.get_topology_desc(name, "tpu")
    except Exception as exc:  # noqa: BLE001
        # Skip ONLY where libtpu genuinely isn't installed. On an image
        # that ships it, a failing topology lookup means the flagship
        # shardability guarantee silently degraded to scripts-only — that
        # must be a loud failure, not a skip (round-3 verdict weak #5).
        import importlib.util

        if importlib.util.find_spec("libtpu") is not None:
            pytest.fail(
                f"libtpu is present but the AOT topology path broke: {exc}")
        pytest.skip(f"no libtpu: TPU AOT topology unavailable: {exc}")


@pytest.mark.slow
def test_train_step_8b_compiles_on_v5p16_within_hbm():
    import sys
    sys.path.insert(0, ".")
    from scripts.aot_validate_8b import train_step_analysis

    _topo("v5p:2x2x4")      # same skip/loud-fail semantics as the serve test
    out = train_step_analysis("v5p:2x2x4", {"fsdp": 8, "model": 2},
                              per_chip_batch=1)
    assert out["params_b"] > 7.5           # the real 8B, not a toy
    assert out["total_gb"] < 95.0, out     # v5p HBM budget
    # fp32 params + Adam state sharded 16 ways ≈ 96 GB/16 = 6 GB arguments.
    assert 3.0 < out["argument_gb"] < 12.0, out


@pytest.mark.slow
def test_serving_decode_8b_compiles_on_v5e8_within_hbm():
    import sys
    sys.path.insert(0, ".")
    from scripts.aot_validate_8b import serve_decode_analysis

    _topo("v5e:2x4x1")
    out = serve_decode_analysis("v5e:2x4x1", 8)
    # bf16 8B weights sharded 8 ways ≈ 2 GB/chip + KV cache: far under the
    # 16 GB a single v5e chip has — which full replication could never fit.
    assert out["total_gb"] < 16.0, out
    assert out["argument_gb"] > 1.5, out
