"""AOT shardability proof for the flagship 8B recipe (SURVEY.md §6;
VERDICT round-2 next #5): lower + compile — never execute — the real train
step and the TP-sharded serving decode against virtual TPU topologies via
libtpu's topology-only AOT path, and check per-chip memory against the HBM
budget. scripts/aot_validate_8b.py runs the full config table (results in
BASELINE.md); this test pins the mechanism + the v5p-16 train point and
the v5e-8 serving point.

Requires libtpu (present in this image); skips cleanly where the TPU AOT
plugin is unavailable.
"""

import pytest


def _topo(name):
    from jax.experimental import topologies

    try:
        return topologies.get_topology_desc(name, "tpu")
    except Exception as exc:  # noqa: BLE001
        # Skip ONLY where libtpu genuinely isn't installed. On an image
        # that ships it, a failing topology lookup means the flagship
        # shardability guarantee silently degraded to scripts-only — that
        # must be a loud failure, not a skip (round-3 verdict weak #5).
        import importlib.util

        if importlib.util.find_spec("libtpu") is not None:
            pytest.fail(
                f"libtpu is present but the AOT topology path broke: {exc}")
        pytest.skip(f"no libtpu: TPU AOT topology unavailable: {exc}")


@pytest.mark.slow
def test_train_step_8b_compiles_on_v5p16_within_hbm():
    import sys
    sys.path.insert(0, ".")
    from scripts.aot_validate_8b import train_step_analysis

    _topo("v5p:2x2x4")      # same skip/loud-fail semantics as the serve test
    out = train_step_analysis("v5p:2x2x4", {"fsdp": 8, "model": 2},
                              per_chip_batch=1)
    assert out["params_b"] > 7.5           # the real 8B, not a toy
    assert out["total_gb"] < 95.0, out     # v5p HBM budget
    # fp32 params + Adam state sharded 16 ways ≈ 96 GB/16 = 6 GB arguments.
    assert 3.0 < out["argument_gb"] < 12.0, out


@pytest.mark.slow
def test_serving_decode_8b_compiles_on_v5e8_within_hbm():
    import sys
    sys.path.insert(0, ".")
    from scripts.aot_validate_8b import serve_decode_analysis

    _topo("v5e:2x4x1")
    out = serve_decode_analysis("v5e:2x4x1", 8)
    # bf16 8B weights sharded 8 ways ≈ 2 GB/chip + KV cache: far under the
    # 16 GB a single v5e chip has — which full replication could never fit.
    assert out["total_gb"] < 16.0, out
    assert out["argument_gb"] > 1.5, out


# -- Mixtral-8x7B north star (BASELINE.json configs[2]; VERDICT r4 #2) ---------


@pytest.mark.slow
def test_train_step_mixtral_compiles_on_v5p64_within_hbm():
    """The real 46.7B MoE train step, expert×fsdp-sharded on a virtual
    v5p-64, per-chip memory within the 95 GB budget. Measured this session:
    30.6 GB/chip (fp32 params + Adam ≈ 560 GB sharded 64 ways + remat
    activations)."""
    import sys
    sys.path.insert(0, ".")
    from scripts.aot_validate_8b import train_step_analysis

    _topo("v5p:4x4x4")
    out = train_step_analysis("v5p:4x4x4", {"expert": 8, "fsdp": 8},
                              model="mixtral-8x7b", per_chip_batch=1)
    assert out["params_b"] > 45.0, out       # the real 8x7B, not a toy
    assert out["total_gb"] < 95.0, out
    # 560 GB of fp32 state over 64 chips ≈ 8.75 GB arguments per chip.
    assert 5.0 < out["argument_gb"] < 20.0, out


@pytest.mark.slow
def test_train_step_multislice_dcn_mechanism():
    """2-slice DCN multislice compiles end-to-end: the topology carries
    distinct slice_index per slice, build_mesh routes through the hybrid
    ICI×DCN assignment, and the dcn-axis collectives lower. Runs the tiny
    MoE config so the suite stays fast; the full 46.7B 2-slice point
    (49.9 GB/chip on v5p:2x4x4 ×2) lives in scripts/aot_validate_8b.py and
    BASELINE.md."""
    import sys
    sys.path.insert(0, ".")
    from scripts.aot_validate_8b import train_step_analysis

    _topo("v5p:2x2x1")
    # 2 slices x (2x2x1 = 4 chips/slice) = 8 devices: dcn 2 x ep 2 x fsdp 2.
    out = train_step_analysis("v5p:2x2x1", {"dcn": 2, "expert": 2,
                                            "fsdp": 2},
                              model="tiny-moe", per_chip_batch=1,
                              num_slices=2)
    assert out["total_gb"] < 95.0, out


@pytest.mark.slow
def test_serving_decode_mixtral_compiles_on_v5e8_within_hbm():
    """Mixtral-8x7B bf16 serving decode TP-sharded on v5e-8: ≈11.4 GB/chip
    of params (93 GB / 8) + KV — fits the 16 GB chip with room for the
    cache; single-chip serving could never hold it."""
    import sys
    sys.path.insert(0, ".")
    from scripts.aot_validate_8b import serve_decode_analysis

    _topo("v5e:2x4x1")
    out = serve_decode_analysis("v5e:2x4x1", 8, model="mixtral-8x7b")
    assert out["total_gb"] < 16.0, out
    assert out["argument_gb"] > 10.0, out    # the real 46.7B resident


# -- int8 density (VERDICT r4 #3: AOT-prove the quantization HBM win) ----------


@pytest.mark.slow
def test_serving_decode_8b_int8_fits_one_v5e_chip():
    """Weight-only int8 8B decode on ONE v5e chip: 12.7 GB of 16 — a
    deployment bf16 cannot reach (16 GB of params alone). The quantized
    param tree lowers through the same decode step (QuantizedTensor
    pytrees + per-field shardings)."""
    import sys
    sys.path.insert(0, ".")
    from scripts.aot_validate_8b import serve_decode_analysis

    _topo("v5e:2x4x1")      # libtpu-presence gate (shared skip semantics)
    out = serve_decode_analysis(
        "v5e:1x1x1", 1, model="llama3-8b", quantize="int8", slots=8,
        max_len=2048, topo_kwargs={"chips_per_host_bounds": [1, 1, 1]})
    assert out["total_gb"] < 16.0, out
    assert out["argument_gb"] < 11.0, out    # int8 params ≈ 8 GB + KV
